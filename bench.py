#!/usr/bin/env python3
"""Headline benchmark: echo goodput + RTT percentiles, 1KB-64MB sweep.

BASELINE.json's metric is rpc_press-style goodput AND p99 RTT across
1KB-64MB echo (the reference measures both: docs/cn/benchmark.md:104 for
the 2.3 GB/s pooled-connection headline, example/rdma_performance/client.cpp
for the per-size attachment echo sweep). This driver measures the same
two quantities on the TPU data plane:

- per size in {1KB .. 64MB}: RTT percentiles (p50/p99 over synchronous,
  device-blocking echo steps) and goodput (chained steps, one sync at the
  end, each iteration data-dependent on the last so nothing overlaps or
  folds away);
- the fused Pallas kernel (one HBM pass for copy+checksum) carries sizes
  it tiles; smaller payloads use the jitted XLA echo step;
- the C++ runtime's loopback numbers (bench_echo: 64-fiber sync echo via
  Server/Channel, the multi_threaded_echo_c++ analogue) ride along under
  "cpp" when the binary exists.

Prints ONE JSON line. Headline metric stays the 64MB echo goodput vs the
reference's 2.3 GB/s; the sweep rows are under "sweep".
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
import time

import jax
import jax.numpy as jnp

from brpc_tpu.models.echo import single_chip_echo_step

BASELINE_GBPS = 2.3
SIZES = [1 << 10, 1 << 16, 1 << 20, 1 << 24, 1 << 26]  # 1KB .. 64MB
FUSED_MIN_BYTES = 1 << 20  # fused kernel tiles 256KB blocks; use it from 1MB


def _steps():
    """size_bytes -> jitted echo step (payload: uint32[size/4])."""
    on_tpu = jax.devices()[0].platform == "tpu"
    fused = None
    if on_tpu:
        from brpc_tpu.ops.echo_kernel import echo_fused

        fused = jax.jit(echo_fused, donate_argnums=0)
    plain = jax.jit(single_chip_echo_step, donate_argnums=0)

    def pick(size: int):
        if fused is not None and size >= FUSED_MIN_BYTES:
            return fused
        return plain

    return pick


def _bench_size(step, size: int) -> dict:
    lanes = size // 4
    payload = jnp.arange(lanes, dtype=jnp.uint32)
    resp, csum = step(payload)  # compile + warm
    jax.block_until_ready((resp, csum))

    # RTT: synchronous steps, blocking per call — the per-call latency a
    # client of the device data plane observes.
    iters_lat = max(20, min(200, (16 << 20) // size))
    lats = []
    for _ in range(iters_lat):
        t0 = time.perf_counter()
        resp, csum = step(resp)
        jax.block_until_ready(csum)
        lats.append(time.perf_counter() - t0)
    lats.sort()

    # Goodput: chained (each iteration consumes the previous response), one
    # sync at the end.
    iters_tp = max(10, min(300, (256 << 20) // size))
    t0 = time.perf_counter()
    for _ in range(iters_tp):
        resp, csum = step(resp)
    jax.block_until_ready((resp, csum))
    dt = time.perf_counter() - t0

    def pct(p: float) -> float:
        return lats[min(len(lats) - 1, int(p * len(lats)))]

    return {
        "size": size,
        "goodput_gbps": round(size * iters_tp / dt / 1e9, 3),
        "p50_us": round(pct(0.50) * 1e6, 1),
        "p99_us": round(pct(0.99) * 1e6, 1),
    }


def _cpp_rows() -> list:
    """Loopback numbers from the C++ runtime (multi_threaded_echo analogue);
    skipped when the binary isn't built."""
    exe = os.path.join(os.path.dirname(os.path.abspath(__file__)), "build",
                       "bench_echo")
    if not os.path.exists(exe):
        return []
    rows = []
    for fibers, payload, conn in (
        (64, 1024, "single"),
        (8, 2 << 20, "single"),
        (8, 2 << 20, "pooled"),
    ):
        try:
            out = subprocess.run(
                [exe, str(fibers), str(payload), "2", conn],
                capture_output=True, text=True, timeout=60,
            )
            line = out.stdout.strip().splitlines()[-1]
            rows.append(json.loads(line))
        except Exception:  # noqa: BLE001 — bench must still print its line
            pass
    return rows


def _run_sweep() -> None:
    if os.environ.get("BENCH_FORCE_CPU"):
        jax.config.update("jax_platforms", "cpu")
    pick = _steps()
    sweep = [_bench_size(pick(size), size) for size in SIZES]
    head = sweep[-1]  # 64MB row
    print(
        json.dumps(
            {
                "metric": "echo_goodput_64MB",
                "value": head["goodput_gbps"],
                "unit": "GB/s",
                "vs_baseline": round(head["goodput_gbps"] / BASELINE_GBPS, 3),
                "platform": jax.devices()[0].platform,
                "sweep": sweep,
                "cpp": _cpp_rows(),
            }
        )
    )


def main() -> None:
    if os.environ.get("BENCH_CHILD"):
        _run_sweep()
        return
    # Watchdog: the axon TPU tunnel can wedge hard (uninterruptible hangs
    # inside backend init).  Run the sweep in a child with a deadline; if
    # the TPU leg never completes, fall back to a CPU run so the driver
    # always records a JSON line (marked by "platform").
    here = os.path.abspath(__file__)
    last_err = ""
    for attempt_env, deadline in (({}, 420), ({"BENCH_FORCE_CPU": "1"}, 300)):
        env = dict(os.environ)
        env["BENCH_CHILD"] = "1"
        env.update(attempt_env)
        # Own session so the whole group can be SIGKILLed; and do NOT
        # block on reaping — a child wedged in uninterruptible TPU-init
        # sleep may ignore even SIGKILL, and waiting on it would hang the
        # watchdog in exactly the scenario it guards against.
        with open("/tmp/bench_child.out", "w+") as out_f, open(
            "/tmp/bench_child.err", "w+"
        ) as err_f:
            child = subprocess.Popen(
                [sys.executable, here], env=env, stdout=out_f,
                stderr=err_f, start_new_session=True,
            )
            t0 = time.time()
            rc = None
            while time.time() - t0 < deadline:
                rc = child.poll()
                if rc is not None:
                    break
                time.sleep(1.0)
            if rc is None:
                import signal

                try:
                    os.killpg(child.pid, signal.SIGKILL)
                except OSError:
                    pass
                continue  # move on even if the corpse cannot be reaped
            out_f.seek(0)
            stdout = out_f.read()
            err_f.seek(0)
            last_err = err_f.read()[-2000:]
        lines = [ln for ln in stdout.splitlines() if ln.startswith("{")]
        if rc == 0 and lines:
            print(lines[-1])
            return
    raise RuntimeError(
        "bench failed on both TPU and CPU fallback; last stderr:\n" +
        last_err
    )


if __name__ == "__main__":
    main()
