#!/usr/bin/env python3
"""Headline benchmark: on-device echo goodput.

Mirrors the reference's headline (BASELINE.md): 2.3 GB/s max echo throughput
on its 2012-era test box (docs/cn/benchmark.md:104).  Here the echo data
plane is HBM-resident: one jitted step receives the 64MB payload, produces
the response copy, and checksums it — the single-chip form of the ICI echo
path.  Prints ONE JSON line.
"""

from __future__ import annotations

import json
import time

import jax
import jax.numpy as jnp

from brpc_tpu.models.echo import single_chip_echo_step

BASELINE_GBPS = 2.3
PAYLOAD_BYTES = 64 * 1024 * 1024
ITERS = 30


def _step_fn():
    """Prefer the fused Pallas kernel (one HBM pass) on TPU.  The off-TPU
    fallback (roll-based) does different work — the recorded metric is the
    TPU number."""
    if jax.devices()[0].platform == "tpu":
        from brpc_tpu.ops.echo_kernel import echo_fused

        return jax.jit(echo_fused, donate_argnums=0)
    return jax.jit(single_chip_echo_step, donate_argnums=0)


def main() -> None:
    payload = jnp.arange(PAYLOAD_BYTES // 4, dtype=jnp.uint32)
    step = _step_fn()
    # Warm up + compile.
    resp, csum = step(payload)
    jax.block_until_ready((resp, csum))

    # Chain each echo on the previous response so iterations cannot overlap
    # or be deduplicated — every step really moves the payload through HBM.
    t0 = time.perf_counter()
    for _ in range(ITERS):
        resp, csum = step(resp)
    jax.block_until_ready((resp, csum))
    dt = time.perf_counter() - t0

    gbps = PAYLOAD_BYTES * ITERS / dt / 1e9
    print(
        json.dumps(
            {
                "metric": "echo_goodput_64MB",
                "value": round(gbps, 3),
                "unit": "GB/s",
                "vs_baseline": round(gbps / BASELINE_GBPS, 3),
            }
        )
    )


if __name__ == "__main__":
    main()
