#!/usr/bin/env python3
"""Headline benchmark: echo goodput + RTT percentiles, 1KB-64MB sweep.

BASELINE.json's metric is rpc_press-style goodput AND p99 RTT across
1KB-64MB echo (the reference measures both: docs/cn/benchmark.md:104 for
the 2.3 GB/s pooled-connection headline, example/rdma_performance/client.cpp
for the per-size attachment echo sweep). This driver measures the same
two quantities on the TPU data plane:

- per size in {1KB .. 64MB}: RTT percentiles (p50/p99 over synchronous
  echo steps, each sample forced to MATERIALIZE its checksum on the host —
  `jax.block_until_ready` does not actually wait on the tunneled axon
  backend, so a host fetch is the only honest sync) and goodput measured
  as the MARGINAL cost between two chained runs of different lengths
  (every iteration data-dependent on the last; the constant tunnel-fetch
  cost cancels in the subtraction, leaving steady-state device goodput);
- the fused Pallas kernel (one HBM pass for copy+checksum) carries sizes
  it tiles; smaller payloads use the jitted XLA echo step;
- the C++ runtime's loopback numbers (bench_echo: 64-fiber sync echo via
  Server/Channel, the multi_threaded_echo_c++ analogue) ride along under
  "cpp" when the binary exists.

Robustness contract (the axon TPU tunnel can wedge uninterruptibly, even
to SIGKILL): the sweep child emits ONE JSON ROW PER SIZE incrementally;
the parent enforces a per-row deadline, keeps every completed row when a
size wedges, and re-runs only the MISSING sizes on a CPU fallback child.
Each row is tagged with the platform it actually ran on, so a partial
TPU leg yields partial TPU rows instead of a silently-CPU artifact.
Children share a persistent XLA compilation cache so re-runs skip the
20-40s first-compile cost.

Prints ONE JSON line. Headline metric stays the 64MB echo goodput vs the
reference's 2.3 GB/s; the sweep rows are under "sweep".

Env knobs: BENCH_FORCE_CPU=1 skips the TPU leg entirely; BENCH_CHILD=1
runs the row-emitting sweep in-process (sizes from BENCH_SIZES, csv of
bytes); BENCH_BUDGET=seconds caps the parent's total wall clock.
"""

from __future__ import annotations

import json
import os
import select
import signal
import subprocess
import sys
import time

BASELINE_GBPS = 2.3
SIZES = [1 << 10, 1 << 16, 1 << 20, 1 << 24, 1 << 26]  # 1KB .. 64MB
FUSED_MIN_BYTES = 1 << 20  # use the fused kernel from 1MB (it also needs
                           # the lane count to divide its block, checked
                           # per-size below)
CACHE_DIR = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                         ".jax_cache")


# ---------------------------------------------------------------- child ----

def _child_sweep(sizes: list[int]) -> None:
    """Runs in a subprocess: one JSON row per size, flushed immediately."""
    import jax
    import jax.numpy as jnp

    if os.environ.get("BENCH_FORCE_CPU"):
        jax.config.update("jax_platforms", "cpu")
    try:
        jax.config.update("jax_compilation_cache_dir", CACHE_DIR)
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 0)
    except Exception:  # noqa: BLE001 — cache is an optimization only
        pass

    from brpc_tpu.models.echo import single_chip_echo_step

    from brpc_tpu.ops.roofline import hbm_peak_gbps

    device = jax.devices()[0]
    platform = device.platform
    hbm_peak = hbm_peak_gbps(device.device_kind) if platform == "tpu" \
        else None
    fused = None
    fused_block = 1
    if platform == "tpu":
        from brpc_tpu.ops.echo_kernel import _BLOCK, echo_fused

        fused = jax.jit(echo_fused, donate_argnums=0)
        fused_block = _BLOCK
    plain = jax.jit(single_chip_echo_step, donate_argnums=0)

    def chained(step, resp, iters: int):
        """Time `iters` data-dependent echo steps, forcing the final
        checksum to the host (int() — the only sync that really waits on
        the tunneled backend).  Returns (seconds, live response) — the
        input buffer is donated away by the first step."""
        t0 = time.perf_counter()
        for _ in range(iters):
            resp, csum = step(resp)
        _ = int(csum)
        return time.perf_counter() - t0, resp

    for size in sizes:
        lanes = size // 4
        step = fused if (fused is not None and size >= FUSED_MIN_BYTES
                         and lanes % fused_block == 0) else plain
        payload = jnp.arange(lanes, dtype=jnp.uint32)
        resp, csum = step(payload)  # compile + warm
        first = int(csum)  # noqa: F841 — forces compile+execute+fetch
        t0 = time.perf_counter()
        resp, csum = step(resp)
        _ = int(csum)
        probe = time.perf_counter() - t0  # ≈ one tunnel fetch + one step

        # Goodput: marginal cost between a short and a long chained run.
        # Both runs pay the same constant tunnel-sync cost; the difference
        # is (n2 - n1) genuinely-executed, data-dependent iterations.
        # min-of-2 per length sheds jitter spikes; n2 is sized from the
        # short runs' own marginal estimate so a slow backend (CPU
        # fallback at 64MB is ~30ms/iter) stays inside the row deadline —
        # an inflated estimate only shrinks n2, which is the safe
        # direction.
        n1 = 16
        t_a, resp = chained(step, resp, n1)
        t_a2, resp = chained(step, resp, n1)
        t_a = min(t_a, t_a2)
        marg_est = max((t_a - probe) / n1, 1e-5)
        n2 = max(4 * n1, min(1024, int(8.0 / marg_est)))
        t_b, resp = chained(step, resp, n2)
        t_b2, resp = chained(step, resp, n2)
        t_b = min(t_b, t_b2)
        sync_fallback = t_b <= t_a
        if sync_fallback:  # jitter still swamped the delta: report the
            gbps = size * n2 / t_b / 1e9  # fetch-contaminated bound, tagged
        else:
            gbps = size * (n2 - n1) / (t_b - t_a) / 1e9

        # RTT percentiles of the DATA PLANE (r3 weak #3: per-call timings
        # here measure the ~70ms axon fetch, not the step).  Each sample is
        # a marginal-cost estimate — (chain of n1+m) − (chain of n1), both
        # paying the same constant fetch, divided by m — so the tunnel
        # cancels and the estimate is per-step device time.  m is sized so
        # the delta dominates fetch jitter; each sample still averages over
        # m steps, so tails narrower than the fetch jitter floor
        # (~jitter/m) are not observable — "latency_method" says so.
        per_iter = max(marg_est if not sync_fallback
                       else t_b / n2, 1e-7)
        m = max(8, min(1024, int(0.15 / per_iter)))
        lat_samples = []
        nlat = 10
        base = 2
        for _ in range(nlat):
            t_s, resp = chained(step, resp, base)
            t_l, resp = chained(step, resp, base + m)
            lat_samples.append(max((t_l - t_s) / m, 0.0))
        lat_samples.sort()
        fetch_ms = probe * 1e3  # one honest host fetch, for transparency

        def pct(p: float) -> float:
            return lat_samples[min(len(lat_samples) - 1,
                                   int(p * len(lat_samples)))]

        row = {
            "size": size,
            "goodput_gbps": round(gbps, 3),
            "p50_us": round(pct(0.50) * 1e6, 1),
            "p99_us": round(pct(0.99) * 1e6, 1),
            "latency_method": f"marginal_chain_m{m}",
            "fetch_ms": round(fetch_ms, 1),
            "platform": platform,
            "goodput_method": "device_chain",
        }
        # Edge sizes (weak #3): the r05 1KB/64MB numbers were Python
        # per-call overhead, not the runtime.  Drive these rows through
        # the batched RPC pipeline at depth >= 8 so goodput measures the
        # data plane again; the device-chain number stays alongside.
        # 16MB rides along (ISSUE 5): the mid-large band is where the
        # monolithic-frame path collapsed, so it gets an RPC-path number
        # (and a perf-smoke floor) of its own.
        if size == SIZES[0] or size >= (16 << 20):
            # Small payloads need a deep window to amortize per-call
            # runtime cost (native 1KB echo is ~90k calls/s; 8-deep
            # leaves the pipe mostly empty); big payloads need few.
            rpc = _rpc_batch_goodput(
                size, depth=8 if size >= (1 << 20) else 256)
            if rpc is not None:
                row["device_step_gbps"] = row["goodput_gbps"]
                row["goodput_gbps"] = rpc["goodput_gbps"]
                row["pipeline_depth"] = rpc["pipeline_depth"]
                row["bytes_moved_per_iter"] = rpc["bytes_moved_per_iter"]
                row["goodput_method"] = "rpc_call_batch"
                for k in ("stripe_rails", "stripe_chunk_bytes",
                          "timeline"):
                    if k in rpc:
                        row[k] = rpc[k]
                if rpc.get("vars"):
                    row["vars"] = rpc["vars"]
        if hbm_peak is not None and step is fused:
            # One read + one write pass per echo → HBM bytes = 2× goodput
            # bytes.  The roofline discipline of BASELINE.md applied to
            # the kernel (r3 weak #2).
            row["hbm_frac"] = round(2 * gbps / hbm_peak, 3)
        if sync_fallback:
            row["sync_fallback"] = True
        print(json.dumps(row), flush=True)


def _child_tpu_rpc() -> None:
    """device array → staging DMA → the FULL C++ RPC stack (Server/Channel
    over tcp/shm/ici rings, GIL released, payload by reference) → echoed
    bytes → device array, verified on device.  The rpc_* numbers measure
    the framework data plane at native speed (VERDICT r3 item 3: the old
    0.36 GB/s ceiling was per-call Python bounces, not the runtime)."""
    import ctypes

    import numpy as np

    import jax
    import jax.numpy as jnp

    if os.environ.get("BENCH_FORCE_CPU"):
        jax.config.update("jax_platforms", "cpu")
    try:
        jax.config.update("jax_compilation_cache_dir", CACHE_DIR)
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 0)
    except Exception:  # noqa: BLE001
        pass
    from brpc_tpu.rpc._lib import load_library

    lib = load_library()
    f = lib.trpc_bench_echo_rpc
    f.restype = ctypes.c_int
    f.argtypes = [ctypes.c_void_p, ctypes.c_size_t, ctypes.c_int,
                  ctypes.c_int, ctypes.c_char_p, ctypes.c_void_p,
                  ctypes.POINTER(ctypes.c_double), ctypes.c_char_p,
                  ctypes.c_size_t, ctypes.c_char_p, ctypes.c_size_t]

    size = 64 << 20
    platform = jax.devices()[0].platform
    dev = jnp.arange(size // 4, dtype=jnp.uint32)
    expected = int(jnp.sum(dev, dtype=jnp.uint64))  # forces materialize

    # Registered staging slab (VERDICT r4 #3): the device→host DMA lands
    # in ici-registered shm memory, so the ici leg ships it with
    # SENDER-OWNED descriptors — no ring DMA copy, one descriptor per
    # payload (the rdma block_pool takeover analogue; a PJRT pinned-host
    # backend would land the fetch here directly).
    lib.trpc_ici_staging_alloc.restype = ctypes.c_void_p
    lib.trpc_ici_staging_alloc.argtypes = [
        ctypes.c_size_t, ctypes.POINTER(ctypes.c_uint32)]
    lib.trpc_ici_zero_copy_counters.argtypes = [
        ctypes.POINTER(ctypes.c_uint64), ctypes.POINTER(ctypes.c_uint64)]
    ord_out = ctypes.c_uint32()
    slab = lib.trpc_ici_staging_alloc(size, ctypes.byref(ord_out))

    # The PJRT hop (np.asarray; this image exposes no device pointers —
    # tools/PJRT_PROBE.md), then the landing into registered memory.
    t0 = time.perf_counter()
    fetched = np.asarray(dev).view(np.uint8)
    dma_s = time.perf_counter() - t0
    if slab:
        staging = np.frombuffer(
            (ctypes.c_char * size).from_address(slab), dtype=np.uint8)
        t0 = time.perf_counter()
        np.copyto(staging, fetched)
        land_s = time.perf_counter() - t0
    else:  # staging alloc failed: fall back to numpy-owned memory
        staging = fetched
        land_s = 0.0

    iters = 12
    # Honest labeling (VERDICT r5 weak #4): this leg is a LOOPBACK
    # descriptor-path measurement — the ici number counts sender-owned
    # descriptors over in-process rings, not bytes across a chip
    # interconnect, and each iteration's goodput-counted payload is
    # `size` bytes.  The fields make that unmistakable in the artifact.
    # Path attribution (ISSUE 10): each ring leg is stamped rma|copy from
    # the rma_rx_msgs delta around it, plus the rail counts in force, so
    # a BENCH row can never silently change data path between rounds.
    def _var(name: str) -> int:
        out = ctypes.create_string_buffer(64)
        return (int(out.value) if lib.trpc_var_read(name.encode(), out, 64)
                == 0 and out.value else 0)

    def _flag(name: str) -> str:
        out = ctypes.create_string_buffer(64)
        return (out.value.decode() if
                lib.trpc_flag_get(name.encode(), out, 64) == 0 else "?")

    row = {"kind": "tpu_rpc_64MB", "platform": platform,
           "loopback": True,
           "bytes_moved_per_iter": size,
           "staging_dma_gbps": round(size / dma_s / 1e9, 3),
           "staging_land_gbps": round(size / land_s / 1e9, 3)
           if land_s > 0 else None,
           "rpc": {}, "rpc_path": {}, "rpc_16mb": {}, "rpc_16mb_path": {},
           "rma_rails": {"shm": _flag("trpc_shm_rails"),
                         "ici": _flag("trpc_ici_rails")}}
    best = 0.0
    resp = np.empty(size, dtype=np.uint8)
    zc0_w, zc0_b = ctypes.c_uint64(), ctypes.c_uint64()
    lib.trpc_ici_zero_copy_counters(ctypes.byref(zc0_w),
                                    ctypes.byref(zc0_b))

    def _zc_bytes() -> int:
        w, b = ctypes.c_uint64(), ctypes.c_uint64()
        lib.trpc_ici_zero_copy_counters(ctypes.byref(w), ctypes.byref(b))
        return b.value

    def _ring_leg(tr: str, leg_size: int, leg_iters: int, resp_ptr,
                  goodput_out: dict, path_out: dict) -> float:
        """One echo leg + its path stamp (rma | desc_zero_copy | copy)."""
        g = ctypes.c_double()
        used = ctypes.create_string_buffer(32)
        err = ctypes.create_string_buffer(256)
        rma0 = _var("rma_rx_msgs")
        zcb0 = _zc_bytes()
        rc = f(staging.ctypes.data, leg_size, leg_iters, 1, tr.encode(),
               resp_ptr, ctypes.byref(g), used, 32, err, 256)
        if rc != 0:
            goodput_out[tr] = f"failed: {err.value.decode()}"
            return 0.0
        name = used.value.decode()
        goodput_out[name] = round(g.value, 3)
        if _var("rma_rx_msgs") > rma0:
            path_out[name] = "rma"
        elif _zc_bytes() - zcb0 >= leg_size:
            path_out[name] = "desc_zero_copy"  # sender-owned descriptors
        else:
            path_out[name] = "copy"
        return g.value

    for tr in ("ici", "shm", "tcp"):
        best = max(best, _ring_leg(
            tr, size, iters, resp.ctypes.data if tr == "ici" else None,
            row["rpc"], row["rpc_path"]))
    # 16MB ring legs (same stack, mid-large band) with their own stamps.
    for tr in ("ici", "shm"):
        _ring_leg(tr, 16 << 20, iters * 4, None,
                  row["rpc_16mb"], row["rpc_16mb_path"])
    zc1_w, zc1_b = ctypes.c_uint64(), ctypes.c_uint64()
    lib.trpc_ici_zero_copy_counters(ctypes.byref(zc1_w),
                                    ctypes.byref(zc1_b))
    # The no-extra-host-copy assertion: the ici leg's payload bytes rode
    # sender-owned descriptors (ring DMA elided), not the bounce path.
    row["ici_zero_copy"] = {
        "wrs": zc1_w.value - zc0_w.value,
        "bytes": zc1_b.value - zc0_b.value,
        "payload_covered": bool(slab) and
        (zc1_b.value - zc0_b.value) >= size * iters,
    }

    # Close the loop: echoed bytes back onto the device, verified there.
    back = jax.device_put(resp.view(np.uint32))
    row["roundtrip_verified"] = (
        int(jnp.sum(back, dtype=jnp.uint64)) == expected)
    row["value"] = round(best, 3)
    print(json.dumps(row), flush=True)


def _observe_snapshot() -> dict | None:
    """Key observability vars for a BENCH row (ISSUE 4): every perf
    number ships with its own attribution — how often the wait-free
    inline write path hit, how big dispatch batches ran, how deep the
    pipeline actually was, and the per-method/client p99s.  Tolerant of
    ANY missing var (older libraries, partial registries): absent keys
    are simply omitted so BENCH artifacts stay comparable across
    rounds."""
    try:
        from brpc_tpu.rpc import observe
        v = observe.Vars.dump()
    except Exception:  # noqa: BLE001 — bench must still print its line
        return None
    out: dict = {}
    try:
        att = v.get("socket_inline_write_attempts", 0)
        hit = v.get("socket_inline_write_hits", 0)
        if att:
            out["inline_write_ratio"] = round(hit / att, 4)
    except Exception:  # noqa: BLE001
        pass
    for var, key, field in (
        ("messenger_dispatch_batch", "dispatch_batch_p50", "p50_us"),
        ("rpc_server_Echo.Echo", "server_echo_p99_us", "p99_us"),
        ("rpc_client_batch", "client_batch_p99_us", "p99_us"),
    ):
        try:
            out[key] = getattr(observe.Latency.read(var), field)
        except Exception:  # noqa: BLE001 — var not registered in this run
            pass
    for name in ("batch_depth", "batch_inflight"):
        if isinstance(v.get(name), (int, float)) and v[name] >= 0:
            out[name] = v[name]
    return out or None


def _rpc_batch_goodput(size: int, depth: int = 8,
                       target_s: float = 1.0) -> dict | None:
    """Loopback echo goodput of the PYTHON DATA PLANE at `depth`-deep
    pipelining: a WINDOWED submit/poll pipeline (batch API, one GIL
    crossing per drain, completions polled off-GIL) with buffer-protocol
    zero-copy requests and responses landing in recycled caller buffers;
    native echo server so the server side has no GIL in the path.  The
    window stays full in steady state — poll k, resubmit k — so there is
    no wait-for-all bubble between batches (the per-call-bounce artifact
    this leg exists to retire).  None on any failure (bench must still
    print its line)."""
    try:
        import numpy as np

        from brpc_tpu.rpc import Channel, Server

        srv = Server()
        srv.register_native_echo("Echo.Echo")
        srv.start(0)
        ch = pipe = None
        try:
            # Large payloads stream best over per-call pooled sockets
            # (the batch pipeline fans out one issue fiber per member);
            # small ones over the single multiplexed connection.
            conn = "pooled" if size >= (1 << 20) else "single"
            ch = Channel(f"127.0.0.1:{srv.port}", timeout_ms=60000,
                         connection_type=conn)
            payload = np.empty(size, dtype=np.uint8)
            payload.reshape(-1, 256)[:] = np.arange(256, dtype=np.uint8)
            pipe = ch.pipeline()
            free_bufs = [np.empty(size, dtype=np.uint8)
                         for _ in range(depth)]
            token2buf: dict[int, object] = {}

            def submit_k(k: int) -> None:
                bs = [free_bufs.pop() for _ in range(k)]
                toks = pipe.submit("Echo.Echo", [payload] * k,
                                   resp_bufs=bs)
                token2buf.update(zip(toks, bs))

            # Warm pass (untimed): fault in the landing buffers, grow the
            # block pool and connections to steady state — at 64MB the
            # first window alone moves 512MB through cold pages and would
            # dominate a short measurement.
            verified = False
            submit_k(depth)
            warm_left = depth
            while warm_left > 0:
                cs = pipe.poll(max_n=depth, timeout_ms=60000)
                if not cs:
                    return None  # wedged: bench must still print its line
                for c in cs:
                    if not c.ok:
                        return None
                    buf = token2buf.pop(c.token)
                    if not verified:
                        if not np.array_equal(buf, payload):
                            return None
                        verified = True
                    free_bufs.append(buf)
                    warm_left -= 1

            submit_k(depth)  # prime the measured window
            completed = 0
            t0 = time.perf_counter()
            inflight = depth
            submitting = True
            while inflight > 0:
                cs = pipe.poll(max_n=depth, timeout_ms=60000)
                if not cs:
                    return None  # wedged
                for c in cs:
                    if not c.ok:
                        return None  # a failed member voids the run
                    free_bufs.append(token2buf.pop(c.token))
                completed += len(cs)
                inflight -= len(cs)
                if submitting and (time.perf_counter() - t0 >= target_s
                                   or completed >= 200_000):
                    submitting = False  # drain the tail, stop refilling
                if submitting:
                    submit_k(len(cs))
                    inflight += len(cs)
            dt = time.perf_counter() - t0
            if completed == 0 or not verified:
                return None
            row = {
                "goodput_gbps": round(size * completed / dt / 1e9, 3),
                "pipeline_depth": depth,
                "bytes_moved_per_iter": size * depth,
                "conn": conn,
                # Built-in attribution (ISSUE 4): the observability-plane
                # snapshot taken right after the measured window, from
                # the process that ran it.
                "vars": _observe_snapshot(),
            }
            # Large-message striping attribution (ISSUE 5): which rail /
            # chunk geometry this row ran under, so goodput deltas across
            # rounds are attributable to config, not code alone.  Only
            # stamped when the payload actually striped.
            try:
                from brpc_tpu.rpc import get_flag

                # Flight-recorder attribution (ISSUE 9): rows stamp
                # whether trpc_timeline was recording during the
                # measured window, so BENCH comparability across rounds
                # is explicit (a timeline-on row is not the same series).
                row["timeline"] = get_flag("trpc_timeline") == "true"
                thr = int(get_flag("trpc_stripe_threshold"))
                if thr > 0 and size > thr:  # 0 = striping disabled
                    row["stripe_rails"] = int(get_flag("trpc_stripe_rails"))
                    row["stripe_chunk_bytes"] = int(
                        get_flag("trpc_stripe_chunk_bytes"))
            except Exception:  # noqa: BLE001 — bench must still print
                pass
            return row
        finally:
            if pipe is not None:
                pipe.close()
            if ch is not None:
                ch.close()
            srv.stop()
    except Exception:  # noqa: BLE001
        return None


def _child_qos_mixed() -> None:
    """Mixed-workload QoS row (ISSUE 6): the high-priority 1KB floor
    measured WHILE low-priority 64MB streams saturate the same server and
    an admission-limited background tenant floods it.  Load generators
    run in their OWN processes — in-process threads would measure this
    interpreter's GIL, not the server's isolation.  Extends the PR-5
    cut-budget HOL guard into a published number: the ratio column is the
    acceptance metric (loaded p99 within 2x unloaded)."""
    import statistics

    from brpc_tpu.rpc import Channel, Server, get_flag, set_flag

    lanes = 4
    lane_weights = "8,4,2,1"
    bg_spec = "bg:weight=1,limit=4;*:limit=10000"
    bulk_bytes = 64 << 20
    set_flag("trpc_qos_lanes", str(lanes))
    set_flag("trpc_qos_lane_weights", lane_weights)
    srv = Server()
    srv.register_native_echo("Echo.Echo")
    srv.set_qos(bg_spec)
    srv.start(0)
    addr = f"127.0.0.1:{srv.port}"
    fg = Channel(addr, timeout_ms=10000, qos_tenant="fg", qos_priority=0)

    def p99(lat: list) -> float:
        lat = sorted(lat)
        return lat[len(lat) * 99 // 100]

    def sample(seconds: float) -> list:
        lat = []
        end = time.perf_counter() + seconds
        while time.perf_counter() < end:
            t0 = time.perf_counter()
            fg.call("Echo.Echo", b"x" * 1024)
            lat.append((time.perf_counter() - t0) * 1e6)
        return lat

    for _ in range(100):  # warm: connections, pools, lazy init
        fg.call("Echo.Echo", b"x" * 1024)
    unloaded = sample(3.0)

    env = dict(os.environ)
    env["PYTHONPATH"] = os.path.dirname(os.path.abspath(__file__))
    load_secs = 14
    bulk_code = (
        "import time\nfrom brpc_tpu.rpc import Channel\n"
        f"ch = Channel({addr!r}, timeout_ms=60000, "
        "connection_type='pooled', qos_tenant='bulk', qos_priority=3)\n"
        f"buf = b'b' * {bulk_bytes}\n"
        f"end = time.time() + {load_secs}\n"
        "while time.time() < end:\n    ch.call('Echo.Echo', buf)\n")
    flood_code = (
        "import time\nfrom brpc_tpu.rpc import Channel\n"
        f"ch = Channel({addr!r}, timeout_ms=2000, qos_tenant='bg', "
        "qos_priority=2)\n"
        f"end = time.time() + {load_secs}\n"
        "while time.time() < end:\n"
        "    try: ch.call('Echo.Echo', b'y' * 1024)\n"
        "    except Exception: pass\n")
    procs = [subprocess.Popen([sys.executable, "-c", bulk_code], env=env)
             for _ in range(2)]
    procs += [subprocess.Popen([sys.executable, "-c", flood_code], env=env)
              for _ in range(2)]
    time.sleep(3)  # let the bulk streams reach steady state
    loaded = sample(8.0)
    for p in procs:
        p.wait()
    fg.close()
    srv.stop()
    row = {
        "workload": "qos_mixed_1kb_hi_under_64mb_lo",
        "p99_unloaded_us": round(p99(unloaded)),
        "p99_loaded_us": round(p99(loaded)),
        "median_unloaded_us": round(statistics.median(unloaded)),
        "median_loaded_us": round(statistics.median(loaded)),
        "ratio_p99": round(p99(loaded) / max(p99(unloaded), 1.0), 3),
        "samples_loaded": len(loaded),
        # Lane/tenant config stamped on the row: a future run with a
        # different config must not be read as the same series.  The
        # timeline stamp (ISSUE 9) keeps flight-recorder-on runs out of
        # the comparable series too.
        "timeline": get_flag("trpc_timeline") == "true",
        "qos_lanes": lanes,
        "lane_weights": lane_weights,
        "qos_spec": bg_spec,
        "bulk_bytes": bulk_bytes,
        "bulk_streams": 2,
        "bg_flooders": 2,
    }
    print(json.dumps(row))


def _child_kv_disagg() -> None:
    """Disaggregated prefill/decode KV row (ISSUE 11): KV-block goodput
    measured WHILE the token-RPC p99 is sampled against the same prefill
    server — the two metrics must hold *simultaneously* (the qos_mixed
    HOL guard generalized to the real serving workload).  The prefill
    server, the decode block puller, and this sampler are three separate
    PROCESSES (tools/kv_disagg.py driver), so the row measures the
    server's isolation, not one interpreter's GIL.  The row stamps the
    rails/lanes/rma-path config it ran under, like every BENCH series."""
    import subprocess as sp

    repo = os.path.dirname(os.path.abspath(__file__))
    tool = os.path.join(repo, "tools", "kv_disagg.py")
    shape = os.path.join(repo, "tests", "data", "golden_mixed.cap")
    env = dict(os.environ)
    env["PYTHONPATH"] = repo
    cmd = [sys.executable, tool, "--json", "--seconds", "6"]
    if os.path.exists(shape):
        # ISSUE 17: the prefix-cache phase rides the same run, with the
        # tenant mix shaped by the golden capture's recorded shares.
        cmd += ["--shape", shape]
    out = sp.run(cmd, env=env, capture_output=True, text=True, timeout=240)
    for ln in out.stdout.splitlines()[::-1]:
        if ln.startswith("{"):
            print(ln, flush=True)
            return
    raise RuntimeError(f"kv_disagg produced no row:\n{out.stderr[-2000:]}")


def _child_infer_serving() -> None:
    """Streamed-inference front door row (ISSUE 20): the four-phase
    tools/load_orchestrator.py --infer cycle — ramp 100k logical token
    streams over a handful of connections (the fd proof), drain every
    one to EOS (zero wedged), measure client-observed TTFT/TPOT through
    the prefix cache (cached prompt blocks skip recompute), then shed a
    2x-overloaded hog tenant typed-only while the victim tenant's TPOT
    p99 stays within 2x unloaded.  One driver run IS the row — the
    perf-smoke gate (BENCH_INFER_STREAMS scaled down) asserts the same
    measurement bench publishes."""
    import subprocess as sp

    repo = os.path.dirname(os.path.abspath(__file__))
    tool = os.path.join(repo, "tools", "load_orchestrator.py")
    env = dict(os.environ)
    env["PYTHONPATH"] = repo
    streams = os.environ.get("BENCH_INFER_STREAMS", "100000")
    out = sp.run([sys.executable, tool, "--infer", "--json",
                  "--infer-streams", streams, "--seconds", "6"],
                 env=env, capture_output=True, text=True, timeout=560)
    for ln in out.stdout.splitlines()[::-1]:
        if ln.startswith("{"):
            print(ln, flush=True)
            return
    raise RuntimeError(
        f"infer orchestrator produced no row:\n{out.stderr[-2000:]}")


def _child_pipeline_overlap() -> None:
    """Pipeline-parallel overlapped dataflow row (ISSUE 18): a 4-member
    fleet runs M microbatches of real jax CPU gradient compute whose
    reduce-scatter/all-gather rides UNDER the next microbatch's compute
    — transfers fire per-chunk as the producer stamps a readiness map
    (trpc_coll_overlap) instead of waiting for a whole-buffer barrier.
    Headline metric: overlap_efficiency = step_time / max(compute,
    comm) (1.0 = perfect overlap) plus the speedup over the sequential
    compute-then-communicate baseline of the SAME dataflow (acceptance
    ≥ 1.25x, byte-exact).  Driver is tools/pipeline_step.py so the row
    measures the multi-threaded fleet, not this interpreter's state."""
    import subprocess as sp

    repo = os.path.dirname(os.path.abspath(__file__))
    tool = os.path.join(repo, "tools", "pipeline_step.py")
    env = dict(os.environ)
    env["PYTHONPATH"] = repo
    env.setdefault("JAX_PLATFORMS", "cpu")
    cmd = [sys.executable, tool, "--json"]
    out = sp.run(cmd, env=env, capture_output=True, text=True, timeout=240)
    for ln in out.stdout.splitlines()[::-1]:
        if ln.startswith("{"):
            print(ln, flush=True)
            return
    raise RuntimeError(
        f"pipeline_step produced no row:\n{out.stderr[-2000:]}")


def _child_collective() -> None:
    """Collective-fabric row (ISSUE 13): a 4-member in-process fleet
    all-gathers 64MB shards over shm — every transfer a pull whose
    one-sided put lands DIRECT in the getter's registered buffer — and
    a reshard moves an overlapping source→target sharding pair through
    the planned minimal schedule.  Headline metrics: all-gather per-link
    GB/s ((n-1)·shard / wall per member link; acceptance ≥ 3.8, half the
    point-to-point one-sided 64MB put baseline) and reshard GB/s over
    the bytes the plan actually moves — stamped with the plan's
    moved/reused/naive bytes so the 2112.01075 minimality is in the
    artifact, plus the rpc_path/chunk/inflight config like every BENCH
    series."""
    import threading

    import numpy as np

    from brpc_tpu.rpc import (Server, collective, get_flag, observe, rma)

    n = 4
    shard = 64 << 20
    srvs = []
    for _ in range(n):
        s = Server()
        s.enable_collective()
        s.start(0)
        srvs.append(s)
    members = [f"127.0.0.1:{s.port}" for s in srvs]
    groups = [collective.Group(members, r, timeout_ms=60000)
              for r in range(n)]
    seq = [0]

    def run_all(fn):
        seq[0] += 1
        errs = [None] * n

        def go(r):
            try:
                fn(r, seq[0])
            except Exception as e:  # noqa: BLE001 — surfaced below
                errs[r] = e

        threads = [threading.Thread(target=go, args=(r,))
                   for r in range(n)]
        t0 = time.perf_counter()
        for t in threads:
            t.start()
        for t in threads:
            t.join(180)
        dt = time.perf_counter() - t0
        if any(errs):
            raise RuntimeError(f"collective bench member failed: {errs}")
        return dt

    # --- all_gather leg ---
    sends = [rma.RmaBuffer(shard) for _ in range(n)]
    recvs = [rma.RmaBuffer(n * shard) for _ in range(n)]
    for r in range(n):
        np.frombuffer(memoryview(sends[r].view),
                      dtype=np.uint8)[:] = (r + 1)

    def ag(r, s):
        groups[r].all_gather(sends[r], recvs[r], shard_bytes=shard,
                             run_seq=s)

    run_all(ag)  # warm: rings, windows, peer mappings
    rx0 = observe.Vars.dump().get("rma_rx_msgs", 0)
    iters = 5
    t0 = time.perf_counter()
    for _ in range(iters):
        run_all(ag)
    dt = (time.perf_counter() - t0) / iters
    rma_path = observe.Vars.dump().get("rma_rx_msgs", 0) > rx0
    verified = all(
        np.all(np.frombuffer(memoryview(recvs[r].view),
                             dtype=np.uint8)[src * shard:(src + 1) * shard]
               == src + 1)
        for r in range(n) for src in range(n))
    ag_row = {
        "members": n,
        "shard_bytes": shard,
        "ms_per_iter": round(dt * 1e3, 1),
        "per_link_gbps": round((n - 1) * shard / dt / 1e9, 3),
        "aggregate_gbps": round(n * (n - 1) * shard / dt / 1e9, 3),
        "rpc_path": "rma" if rma_path else "copy",
        "verified": verified,
    }
    for b in sends + recvs:
        b.free()

    # --- reshard leg: overlapping shardings, only boundary strips move ---
    total = n * shard
    q = total // n
    shift = 16 << 20
    src_ranges = [(r, r * q, q) for r in range(n)]
    dst_ranges = ([(0, 0, q + shift)] +
                  [(r, r * q + shift, q) for r in range(1, n - 1)] +
                  [(n - 1, (n - 1) * q + shift, q - shift)])
    plan = collective.plan_reshard_bytes(src_ranges, dst_ranges, total, n)
    sbufs = [rma.RmaBuffer(q) for _ in range(n)]
    dlens = [q + shift] + [q] * (n - 2) + [q - shift]
    rbufs = [rma.RmaBuffer(ln) for ln in dlens]

    def rs(r, s):
        groups[r].reshard(src_ranges, dst_ranges, total, sbufs[r],
                          rbufs[r], run_seq=s)

    run_all(rs)  # warm
    iters = 4
    t0 = time.perf_counter()
    for _ in range(iters):
        run_all(rs)
    dt = (time.perf_counter() - t0) / iters
    reshard_row = {
        "members": n,
        "total_bytes": total,
        "bytes_moved": plan["bytes_moved"],
        "bytes_reused": plan["bytes_reused"],
        "naive_bytes": plan["naive_bytes"],
        "minimal": plan["bytes_moved"] < plan["naive_bytes"],
        "ms_per_iter": round(dt * 1e3, 1),
        "moved_gbps": round(plan["bytes_moved"] / dt / 1e9, 3),
    }
    row = {
        "workload": "collective",
        "all_gather": ag_row,
        "reshard": reshard_row,
        "chunk_bytes": int(get_flag("trpc_coll_chunk_bytes")),
        "inflight": int(get_flag("trpc_coll_inflight")),
        "timeline": get_flag("trpc_timeline") == "true",
    }
    for g in groups:
        g.close()
    for b in sbufs + rbufs:
        b.free()
    for s in srvs:
        s.stop()
    print(json.dumps(row), flush=True)


def _child_slo_fleet() -> None:
    """Fleet-observability row (ISSUE 19): a 3-node in-process fleet —
    every node an SLO-armed echo server publishing its digest+SLO blob
    into a naming registry — serves the golden-capture tenant mix
    (tests/data/golden_mixed.cap: fg 1KB foreground + bulk large), and
    the row reports the MERGED per-tenant view (/fleet body) against a
    pooled-digest oracle built from the very blobs the nodes published
    (p99_oracle_ratio; acceptance <= 2.0, the octave bound), the 1KB
    QPS with the publisher ON vs OFF (publication must ride the
    Announcer's renew cadence, not the request path), and the time for
    an induced latency regression on ONE node to flip that tenant's
    burn-rate alert (breach_detect_ms; acceptance <= one fast window)."""
    from brpc_tpu.rpc import Channel, Server, get_flag, observe, set_flag
    from brpc_tpu.rpc.capture import load_capture
    from brpc_tpu.rpc.naming import NamingClient

    repo = os.path.dirname(os.path.abspath(__file__))
    fast_ms = 1500
    saved = {f: get_flag(f) for f in
             ("trpc_slo", "trpc_fleet_publish", "trpc_slo_fast_window_ms",
              "trpc_slo_slow_window_ms", "trpc_naming_lease_ms")}
    set_flag("trpc_slo_fast_window_ms", str(fast_ms))
    set_flag("trpc_slo_slow_window_ms", "8000")
    set_flag("trpc_naming_lease_ms", "400")
    observe.enable_slo(True)
    observe.enable_fleet_publish(False)

    spec = ("fg:p99_us=5000,avail=99.0;bulk:p99_us=200000,avail=99.0;"
            "*:p99_us=100000")
    registry = Server()
    registry.enable_naming_registry()
    registry.start(0)
    reg_addr = f"127.0.0.1:{registry.port}"
    srvs = []
    for _ in range(3):
        s = Server()
        s.register_native_echo("Echo.Echo")
        s.set_slo(spec)
        s.start(0)
        srvs.append(s)
    addrs = [f"127.0.0.1:{s.port}" for s in srvs]
    chans = {}

    def chan(node: int, tenant: str) -> Channel:
        key = (node, tenant)
        if key not in chans:
            chans[key] = Channel(addrs[node], timeout_ms=10000,
                                 qos_tenant=tenant)
        return chans[key]

    def qps_1kb(seconds: float = 1.2) -> float:
        # Untagged (scored under '*'): the probe volume must not drown
        # tenant fg's burn windows before the breach-detection leg.
        ch = chan(0, "")
        body = b"q" * 1024
        for _ in range(30):
            ch.call("Echo.Echo", body)
        n = 0
        t0 = time.perf_counter()
        while time.perf_counter() - t0 < seconds:
            ch.call("Echo.Echo", body)
            n += 1
        return n / (time.perf_counter() - t0)

    # Publisher OFF vs ON, interleaved best-of-2 each: publication rides
    # the Announcer's renew thread, so the request path must not notice.
    qps_off = qps_1kb()
    observe.enable_fleet_publish(True)
    for i, s in enumerate(srvs):
        s.announce(reg_addr, "fleet", zone=f"z{i}")
    time.sleep(0.6)  # a few renew rounds so publication is in flight
    qps_on = qps_1kb()
    observe.enable_fleet_publish(False)
    qps_off = max(qps_off, qps_1kb())
    observe.enable_fleet_publish(True)
    qps_on = max(qps_on, qps_1kb())

    # The golden-capture tenant mix, striped across the 3 nodes.
    _, records = load_capture(
        os.path.join(repo, "tests", "data", "golden_mixed.cap"))
    driven = {}
    for i, r in enumerate(records[:600]):
        tenant = r.tenant or "fg"
        size = min(max(int(r.request_bytes), 1), 64 << 10)
        chan(i % 3, tenant).call("Echo.Echo", b"m" * size)
        driven[tenant] = driven.get(tenant, 0) + 1

    # Wait until every node's published blob covers the traffic, then
    # build the pooled oracle FROM those blobs (the single-recorder
    # ground truth the octave bound is stated against).
    nc = NamingClient(reg_addr)
    deadline = time.time() + 30
    decoded = []
    while time.time() < deadline:
        _, recs = nc.stats("fleet")
        blobs = [r.payload for r in recs if r.payload]
        if len(blobs) == 3:
            decoded = [observe.fleet_blob_decode(b) for b in blobs]
            fg = [t for d in decoded for t in d["tenants"]
                  if t["tenant"] == "fg"]
            if sum(t["slow_total"] for t in fg) >= driven.get("fg", 0):
                break
        time.sleep(0.2)
    if len(decoded) != 3:
        raise RuntimeError("fleet blobs never covered the driven traffic")
    pooled = {}
    for d in decoded:
        for t in d["tenants"]:
            dg = t["digest"]
            if t["tenant"] in pooled:
                observe.digest_merge(pooled[t["tenant"]], dg)
            else:
                pooled[t["tenant"]] = dg

    view = observe.fleet_dump("fleet")
    tenants = []
    worst_ratio = 0.0
    for row in view["tenants"]:
        oracle = pooled.get(row["tenant"])
        if oracle is None or oracle.count == 0:
            continue
        oracle_p99 = observe.digest_percentile_us(oracle, 0.99)
        ratio = (max(row["p99_us"], oracle_p99)
                 / max(min(row["p99_us"], oracle_p99), 1))
        worst_ratio = max(worst_ratio, ratio)
        tenants.append({
            "tenant": row["tenant"], "nodes": row["nodes"],
            "rate": row["rate"], "p50_us": row["p50_us"],
            "p99_us": row["p99_us"], "oracle_p99_us": oracle_p99,
            "p99_oracle_ratio": round(ratio, 3),
            "error_rate": row["error_rate"],
            "budget_remaining": row["budget_remaining"],
            "burn_fast": row["burn_fast"], "burn_slow": row["burn_slow"],
        })

    # Induced regression on ONE node: time-to-alert for tenant fg.
    srvs[0].set_faults("svr_delay=1:25")
    ch = chan(0, "fg")
    t0 = time.perf_counter()
    breach_detect_ms = None
    while time.perf_counter() - t0 < fast_ms / 1000 * 4:
        ch.call("Echo.Echo", b"d" * 1024)
        fg_row = [t for t in srvs[0].slo_dump()["tenants"]
                  if t["tenant"] == "fg"]
        if fg_row and fg_row[0]["breached"]:
            breach_detect_ms = round((time.perf_counter() - t0) * 1e3, 1)
            break
    srvs[0].set_faults("")

    row = {
        "workload": "slo_fleet",
        "nodes": 3,
        "capture": "tests/data/golden_mixed.cap",
        "calls_driven": sum(driven.values()),
        "tenant_mix": driven,
        "tenants": tenants,
        "p99_oracle_ratio_worst": round(worst_ratio, 3),
        "qps_1kb_publish_off": round(qps_off, 1),
        "qps_1kb_publish_on": round(qps_on, 1),
        "publish_qps_ratio": round(qps_on / max(qps_off, 1e-9), 3),
        "breach_detect_ms": breach_detect_ms,
        "fast_window_ms": fast_ms,
    }
    for c in chans.values():
        c.close()
    for s in srvs:
        s.stop()
    registry.stop()
    for f, v in saved.items():
        set_flag(f, v)
    print(json.dumps(row), flush=True)


def _child_self_tune() -> None:
    """Self-tuning row (ISSUE 14 / ROADMAP item 4): each leg measures a
    workload hand-tuned (compiled defaults, tuner off), then re-runs it
    from DELIBERATELY-WRONG flags with the tuner ON and reports the
    recovery ratio (tuned/hand for throughput, hand/tuned for latency),
    the per-second recovery trajectory, the converged knob values, and
    the decision counts — the `tuner:` stamp that makes a tuning run a
    comparable BENCH series.  Wrong seeds, chosen for measured damage
    on this box: stripe chunk 64KB + 1 rail (~5x off on 64MB striped),
    messenger cut budget 64KB (the AIMD growth path on the 1KB and
    qos_mixed rows).  All knob movement goes through the validated
    reload path; defaults are restored between legs."""
    import numpy as np

    from brpc_tpu.rpc import (Channel, Server, get_flag, observe,
                              set_flag, tuner)

    TUNER_INTERVAL_MS = 50
    TUNER_EVAL_TICKS = 2

    defaults = {f["name"]: f["default"] for f in observe.flags()}
    # Every knob the controller can actuate: restored wholesale between
    # legs, so a side-effect move in one leg (e.g. the budget rule
    # firing on the striped leg's yields) can never contaminate the
    # next leg's hand-tuned baseline.
    tuner_knobs = [
        "trpc_stripe_chunk_bytes", "trpc_stripe_rails",
        "trpc_messenger_cut_budget", "trpc_rma_window_bytes",
        "trpc_qos_lane_weights",
    ]

    def restore(names):
        for n in names:
            set_flag(n, defaults[n])

    def tuner_on():
        set_flag("trpc_tuner_interval_ms", str(TUNER_INTERVAL_MS))
        set_flag("trpc_tuner_eval_ticks", str(TUNER_EVAL_TICKS))
        tuner.enable_tuner(True)

    def tuner_off():
        tuner.enable_tuner(False)
        restore(["trpc_tuner_interval_ms", "trpc_tuner_eval_ticks"])

    def pipeline_rate(size, depth, seconds):
        """Loopback echo through the batch pipeline; returns (per-second
        completion buckets, completions/s over the final 3 buckets)."""
        srv = Server()
        srv.register_native_echo("Echo.Echo")
        srv.start(0)
        conn = "pooled" if size >= (1 << 20) else "single"
        ch = Channel(f"127.0.0.1:{srv.port}", timeout_ms=60000,
                     connection_type=conn)
        payload = np.zeros(size, dtype=np.uint8)
        pipe = ch.pipeline()
        free = [np.empty(size, dtype=np.uint8) for _ in range(depth)]
        t2b: dict = {}

        def submit(k):
            bs = [free.pop() for _ in range(k)]
            toks = pipe.submit("Echo.Echo", [payload] * k, resp_bufs=bs)
            t2b.update(zip(toks, bs))

        try:
            submit(depth)
            t0 = time.perf_counter()
            done = last_done = 0
            last = t0
            buckets = []
            while time.perf_counter() - t0 < seconds:
                cs = pipe.poll(max_n=depth, timeout_ms=60000)
                if not cs:
                    raise RuntimeError("self_tune pipeline wedged")
                for c in cs:
                    if not c.ok:
                        raise RuntimeError(f"self_tune member failed: {c}")
                    free.append(t2b.pop(c.token))
                    done += 1
                submit(len(cs))
                now = time.perf_counter()
                if now - last >= 1.0:
                    buckets.append((done - last_done) / (now - last))
                    last, last_done = now, done
            while t2b:
                for c in pipe.poll(max_n=depth, timeout_ms=60000):
                    free.append(t2b.pop(c.token))
        finally:
            pipe.close()
            ch.close()
            srv.stop()
        tail = buckets[-3:] if len(buckets) >= 3 else buckets
        return buckets, sum(tail) / len(tail)

    legs = {}
    decisions_before = 0

    def leg_decisions():
        nonlocal decisions_before
        now = tuner.counters()["decisions"]
        n, decisions_before = now - decisions_before, now
        return n

    # ---- leg 1: 64MB striped goodput --------------------------------
    size = 64 << 20
    stripe_knobs = ["trpc_stripe_chunk_bytes", "trpc_stripe_rails"]
    _, hand_rate = pipeline_rate(size, depth=4, seconds=5)
    hand_gbps = hand_rate * size / 1e9
    set_flag("trpc_stripe_chunk_bytes", "65536")
    set_flag("trpc_stripe_rails", "1")
    tuner_on()
    traj, tuned_rate = pipeline_rate(size, depth=4, seconds=14)
    tuner_off()
    tuned_gbps = tuned_rate * size / 1e9
    legs["striped_64mb"] = {
        "metric": "goodput_gbps",
        "hand": round(hand_gbps, 3),
        "wrong_flags": {"trpc_stripe_chunk_bytes": 65536,
                        "trpc_stripe_rails": 1},
        "tuned": round(tuned_gbps, 3),
        "recovery": round(tuned_gbps / hand_gbps, 3),
        "trajectory_gbps": [round(b * size / 1e9, 2) for b in traj],
        "converged": {k: int(get_flag(k)) for k in stripe_knobs},
        "decisions": leg_decisions(),
    }
    restore(tuner_knobs)

    # ---- leg 2: 1KB pipelined QPS -----------------------------------
    _, hand_qps = pipeline_rate(1024, depth=256, seconds=5)
    set_flag("trpc_messenger_cut_budget", "65536")
    tuner_on()
    traj, tuned_qps = pipeline_rate(1024, depth=256, seconds=10)
    tuner_off()
    legs["one_kb"] = {
        "metric": "qps",
        "hand": round(hand_qps),
        "wrong_flags": {"trpc_messenger_cut_budget": 65536},
        "tuned": round(tuned_qps),
        "recovery": round(tuned_qps / hand_qps, 3),
        "trajectory_qps": [round(b) for b in traj],
        "converged": {"trpc_messenger_cut_budget":
                      int(get_flag("trpc_messenger_cut_budget"))},
        "decisions": leg_decisions(),
    }
    restore(tuner_knobs)

    # ---- leg 3: qos_mixed fg p99 under bulk saturation --------------
    set_flag("trpc_qos_lanes", "4")
    srv = Server()
    srv.register_native_echo("Echo.Echo")
    srv.set_qos("bg:weight=1,limit=4;*:limit=10000")
    srv.start(0)
    addr = f"127.0.0.1:{srv.port}"
    env = dict(os.environ)
    env["PYTHONPATH"] = os.path.dirname(os.path.abspath(__file__))
    load_secs = 26
    bulk_code = (
        "import time\nfrom brpc_tpu.rpc import Channel\n"
        f"ch = Channel({addr!r}, timeout_ms=60000, "
        "connection_type='pooled', qos_tenant='bulk', qos_priority=3)\n"
        f"buf = b'b' * {64 << 20}\n"
        f"end = time.time() + {load_secs}\n"
        "while time.time() < end:\n    ch.call('Echo.Echo', buf)\n")
    procs = [subprocess.Popen([sys.executable, "-c", bulk_code], env=env)
             for _ in range(2)]
    fg = Channel(addr, timeout_ms=20000, qos_tenant="fg", qos_priority=0)

    def p99(seconds):
        lat = []
        for _ in range(100):
            fg.call("Echo.Echo", b"x" * 1024)
        end = time.perf_counter() + seconds
        while time.perf_counter() < end:
            t0 = time.perf_counter()
            fg.call("Echo.Echo", b"x" * 1024)
            lat.append((time.perf_counter() - t0) * 1e6)
        lat.sort()
        return lat[len(lat) * 99 // 100], len(lat)

    try:
        time.sleep(2.5)  # bulk streams to steady state
        hand_p99, hand_n = p99(5.0)
        set_flag("trpc_messenger_cut_budget", "65536")
        tuner_on()
        time.sleep(3.0)  # convergence window under live load
        tuned_p99, tuned_n = p99(5.0)
        tuner_off()
    finally:
        fg.close()
        for p in procs:  # measurements done: don't idle out their timer
            p.terminate()
        for p in procs:
            p.wait()
        srv.stop()
    legs["qos_mixed"] = {
        "metric": "fg_p99_us",
        "hand": round(hand_p99),
        "wrong_flags": {"trpc_messenger_cut_budget": 65536},
        "tuned": round(tuned_p99),
        # Latency: recovery = hand/tuned (1.0 = fully recovered;
        # >1 = the tuned box beat the hand numbers).
        "recovery": round(hand_p99 / max(tuned_p99, 1.0), 3),
        "samples": {"hand": hand_n, "tuned": tuned_n},
        "converged": {"trpc_messenger_cut_budget":
                      int(get_flag("trpc_messenger_cut_budget"))},
        "decisions": leg_decisions(),
    }
    restore(tuner_knobs + ["trpc_qos_lanes"])

    row = {
        "workload": "self_tune",
        "tuner": {"interval_ms": TUNER_INTERVAL_MS,
                  "eval_ticks": TUNER_EVAL_TICKS,
                  "counters": tuner.counters()},
        "legs": legs,
        "timeline": get_flag("trpc_timeline") == "true",
    }
    print(json.dumps(row), flush=True)


def _child_rolling_restart() -> None:
    """Cluster control-plane row (ISSUE 12): drain + hot-restart one
    node of a 3-node naming-backed cluster under mixed 1KB + striped
    load and KV pulls (tools/load_orchestrator.py --rolling-restart,
    separate hub/node/successor/worker PROCESSES).  Stamps the
    client-visible error count (acceptance: 0), the drain-window p99
    against steady state (acceptance: <= 2x), and the stale-KV-admit
    count (acceptance: 0) — the zero-downtime restart headline."""
    import subprocess as sp

    tool = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                        "tools", "load_orchestrator.py")
    env = dict(os.environ)
    env["PYTHONPATH"] = os.path.dirname(os.path.abspath(__file__))
    env.setdefault("JAX_PLATFORMS", "cpu")
    out = sp.run([sys.executable, tool, "--rolling-restart", "--json",
                  "--seconds", "6", "--big-every", "50",
                  "--big-bytes", str(1 << 20)],
                 env=env, capture_output=True, text=True, timeout=240)
    for ln in out.stdout.splitlines()[::-1]:
        if ln.startswith("{"):
            print(ln, flush=True)
            return
    raise RuntimeError(
        f"rolling_restart produced no row:\n{out.stderr[-2000:]}")


def _child_replay() -> None:
    """Capture-and-replay regression row (ISSUE 16).  Records a mixed-
    tenant window on a QoS-laned server (fg 1KB echo — every 5th under a
    deadline scope — concurrent with a bulk tenant moving striped 16MB
    bodies from its own process), dumps the capture, then regresses two
    planes against it:

    exact leg — tools/traffic_replay.py re-offers the window open-loop
    at the recorded inter-arrival times with tenant/priority/deadline
    re-stamped; the capture tier stays armed through the replay, so the
    row compares the REPLAYED window's server-side per-tenant p99/rate
    against the RECORDED baseline apples-to-apples (acceptance: rate
    within 10%, p99 <= 2x, zero untyped errors).

    stat leg — statistical mode at 2x the fitted rate, composed with
    server-side chaos (svr_delay): shed-don't-degrade, i.e. every error
    is a typed shed (kELimit/kEOverloaded/kEDraining/kEDeadlineExpired),
    never an untyped failure."""
    import tempfile

    from brpc_tpu.rpc import Channel, Server, set_flag
    from brpc_tpu.rpc import capture as cap

    tool = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                        "tools", "traffic_replay.py")
    bulk_bytes = 16 << 20
    lanes = 4
    qos_spec = "fg:weight=8,limit=16;bulk:weight=1,limit=64;*:limit=10000"
    set_flag("trpc_qos_lanes", str(lanes))
    srv = Server()
    srv.register_native_echo("Echo.Echo")
    srv.set_qos(qos_spec)
    srv.start(0)
    addr = f"127.0.0.1:{srv.port}"

    env = dict(os.environ)
    env["PYTHONPATH"] = os.path.dirname(os.path.abspath(__file__))

    # ---- record the mixed-tenant window -------------------------------
    # Load generators run in their OWN processes, TWO fg senders + one
    # bulk, matching the replay side's two worker processes — the
    # recorded baseline and the replayed window then see the same client
    # concurrency, so the p99 comparison is apples-to-apples.
    fg = Channel(addr, timeout_ms=5000, qos_tenant="fg", qos_priority=0)
    small = b"x" * 1024
    for _ in range(50):  # warm: connections, pools, lazy init
        fg.call("Echo.Echo", small)
    fg.close()
    record_secs = 5.0
    # Bulk is recorded OPEN-LOOP (Batch, fixed 100ms cadence, bounded
    # in-flight) — the replayer is open-loop too, so a closed-loop
    # recording would hand it a baseline that never self-overlaps and
    # every replayed overlap would read as a regression.
    bulk_code = (
        "import time\nfrom brpc_tpu.rpc import Batch, Channel\n"
        f"ch = Channel({addr!r}, timeout_ms=60000, "
        "connection_type='pooled', qos_tenant='bulk', qos_priority=3)\n"
        "b = Batch(ch)\n"
        f"buf = b'b' * {bulk_bytes}\n"
        f"end = time.time() + {record_secs}\n"
        "next_t = time.time()\n"
        "pending = 0\n"
        "while time.time() < end:\n"
        "    if time.time() >= next_t and pending < 4:\n"
        "        b.submit('Echo.Echo', [buf], timeout_ms=60000)\n"
        "        pending += 1\n"
        "        next_t += 0.1\n"
        "    pending -= len(b.poll(max_n=8, timeout_ms=10))\n"
        "while pending > 0:\n"
        "    got = len(b.poll(max_n=8, timeout_ms=1000))\n"
        "    if not got:\n        break\n"
        "    pending -= got\n"
        "b.close()\nch.close()\n")
    fg_code = (
        "import time\n"
        "from brpc_tpu.rpc import Channel, deadline_scope\n"
        f"ch = Channel({addr!r}, timeout_ms=5000, qos_tenant='fg', "
        "qos_priority=0)\n"
        "buf = b'x' * 1024\n"
        f"end = time.time() + {record_secs}\n"
        "i = 0\n"
        "while time.time() < end:\n"
        "    try:\n"
        "        if i % 5 == 0:\n"
        "            with deadline_scope(500):\n"
        "                ch.call('Echo.Echo', buf)\n"
        "        else:\n"
        "            ch.call('Echo.Echo', buf)\n"
        "    except Exception:\n"
        "        pass\n"
        "    i += 1\n"
        "    time.sleep(0.002)\n")
    cap.enable_capture(True)
    cap.reset_capture()
    procs = [subprocess.Popen([sys.executable, "-c", bulk_code], env=env)]
    procs += [subprocess.Popen([sys.executable, "-c", fg_code], env=env)
              for _ in range(2)]
    for p in procs:
        p.wait(timeout=120)
    recorded = cap.summary()
    cap_path = tempfile.mktemp(prefix="bench_replay_", suffix=".cap")
    n_records = cap.dump(cap_path)

    def _tool_row(extra: list) -> dict:
        out = subprocess.run(
            [sys.executable, tool, "--addr", addr, "--capture", cap_path,
             "--workers", "2", "--default-timeout-ms", "30000", *extra],
            env=env, capture_output=True, text=True, timeout=240)
        for ln in out.stdout.splitlines()[::-1]:
            if ln.startswith("{"):
                return json.loads(ln)
        raise RuntimeError(f"replayer produced no row:\n{out.stderr[-2000:]}")

    # ---- exact leg: capture stays armed to measure the replayed window
    cap.reset_capture()
    exact = _tool_row([])
    replayed = cap.summary()

    tenants = {}
    worst_p99_ratio = 0.0
    worst_rate_dev = 0.0
    for t, rec_t in recorded["summary"].get("tenants", {}).items():
        rep_t = replayed["summary"].get("tenants", {}).get(t, {})
        ex_t = exact.get("tenants", {}).get(t, {})
        p99_ratio = (rep_t.get("p99_us", 0) /
                     max(rec_t.get("p99_us", 0), 1.0))
        rate_ratio = (rep_t.get("est_rate_rps", 0.0) /
                      max(rec_t.get("est_rate_rps", 0.0), 1e-9))
        worst_p99_ratio = max(worst_p99_ratio, p99_ratio)
        worst_rate_dev = max(worst_rate_dev, abs(1.0 - rate_ratio))
        tenants[t] = {
            "recorded_p99_us": rec_t.get("p99_us", 0),
            "replayed_p99_us": rep_t.get("p99_us", 0),
            "p99_ratio": round(p99_ratio, 3),
            "recorded_rate_rps": round(rec_t.get("est_rate_rps", 0.0), 1),
            "replayed_rate_rps": round(rep_t.get("est_rate_rps", 0.0), 1),
            "rate_ratio": round(rate_ratio, 3),
            "client_errors": ex_t.get("errors", {}),
        }

    # ---- stat leg: 2x fitted rate + server-side chaos -----------------
    srv.set_faults("svr_delay=1:20")
    cap.reset_capture()
    stat = _tool_row(["--mode", "stat", "--rate-scale", "2.0",
                      "--duration", "4"])
    srv.set_faults("")
    stat_sheds = sum(sum(t.get("errors", {}).values())
                     for t in stat.get("tenants", {}).values())
    stat_sent = sum(t.get("sent", 0) for t in stat.get("tenants", {}).values())

    cap.enable_capture(False)
    try:
        os.unlink(cap_path)
    except OSError:
        pass
    srv.stop()
    print(json.dumps({
        "workload": "capture_replay_mixed_tenant",
        "captured_records": n_records,
        "capture_window_us": recorded["summary"].get("window_us", 0),
        "burstiness_cv": recorded["summary"].get("burstiness_cv", 0.0),
        "tenants": tenants,
        "worst_p99_ratio": round(worst_p99_ratio, 3),
        "worst_rate_deviation": round(worst_rate_dev, 3),
        "exact_untyped_errors": exact.get("untyped_errors", -1),
        "exact_typed_only": exact.get("typed_errors_only", False),
        "stat_rate_scale": 2.0,
        "stat_chaos": "svr_delay=1:20",
        "stat_sent": stat_sent,
        "stat_sheds": stat_sheds,
        "stat_errors": {t: d.get("errors", {})
                        for t, d in stat.get("tenants", {}).items()},
        "stat_untyped_errors": stat.get("untyped_errors", -1),
        "stat_typed_only": stat.get("typed_errors_only", False),
        "qos_lanes": lanes,
        "qos_spec": qos_spec,
        "bulk_bytes": bulk_bytes,
    }))


def _child_zerocopy() -> None:
    """Loopback RPC echo, three Python-boundary strategies at 4MB: the
    per-call bytes-copy path, the per-call dlpack zero-copy path, and the
    headline — the 8-deep batched pipeline (one GIL crossing per batch,
    zero-copy both directions).  All three run against a NATIVE echo
    server so the numbers measure the client data plane, not the server's
    GIL (the r05 row measured a Python handler on the far side)."""
    import numpy as np

    from brpc_tpu.rpc import zerocopy
    from brpc_tpu.rpc.client import Channel
    from brpc_tpu.rpc.server import Server

    depth = int(os.environ.get("BENCH_PIPELINE_DEPTH", "8"))
    srv = Server()
    srv.register_native_echo("Echo.Echo")
    srv.start(0)
    ch = Channel(f"127.0.0.1:{srv.port}", timeout_ms=10000)
    size = 4 << 20
    payload = np.arange(size // 4, dtype=np.uint32)
    iters = 30

    ch.call("Echo.Echo", payload.tobytes())  # warm both directions
    zerocopy.call_zero_copy(ch, "Echo.Echo", payload)

    t0 = time.perf_counter()
    for _ in range(iters):
        ch.call("Echo.Echo", payload.tobytes())
    copied_dt = time.perf_counter() - t0

    t0 = time.perf_counter()
    for _ in range(iters):
        zerocopy.call_zero_copy(ch, "Echo.Echo", payload)
    zc_dt = time.perf_counter() - t0
    ch.close()

    batched = _rpc_batch_goodput(size, depth=depth, target_s=1.5)
    row = {
        "kind": "py_loopback_4MB",
        "server": "native_echo",
        "copied_gbps": round(size * iters / copied_dt / 1e9, 3),
        "percall_zerocopy_gbps": round(size * iters / zc_dt / 1e9, 3),
        # Headline: the pipelined zero-copy plane (ISSUE 3 acceptance:
        # >= 1.5 GB/s at 4MB x 8-deep vs 0.293 per-call in r05).
        "zerocopy_gbps": batched["goodput_gbps"] if batched else None,
        "pipeline_depth": depth,
        "bytes_moved_per_iter": size * depth,
        "vars": (batched or {}).get("vars") or _observe_snapshot(),
    }
    print(json.dumps(row), flush=True)
    srv.stop()


# --------------------------------------------------------------- parent ----

class _RowReader:
    """Runs a sweep child, harvesting JSON rows under per-row deadlines.

    The child gets its own session so the whole group can be SIGKILLed,
    and is never blockingly reaped — a child wedged in uninterruptible
    TPU-init sleep can ignore even SIGKILL, and waiting on it would hang
    the parent in exactly the scenario it guards against.
    """

    def __init__(self, sizes: list[int], force_cpu: bool):
        env = dict(os.environ)
        env["BENCH_CHILD"] = "1"
        env["BENCH_SIZES"] = ",".join(str(s) for s in sizes)
        env["JAX_COMPILATION_CACHE_DIR"] = CACHE_DIR
        if force_cpu:
            env["BENCH_FORCE_CPU"] = "1"
        self.err_f = open("/tmp/bench_child.err", "w+")
        self.child = subprocess.Popen(
            [sys.executable, os.path.abspath(__file__)], env=env,
            stdout=subprocess.PIPE, stderr=self.err_f,
            start_new_session=True,
        )
        self.buf = b""

    def next_row(self, deadline_s: float) -> dict | None:
        """One parsed row, or None on child exit/deadline (child killed)."""
        fd = self.child.stdout.fileno()
        t_end = time.time() + deadline_s
        while True:
            nl = self.buf.find(b"\n")
            if nl >= 0:
                line = self.buf[:nl].decode("utf-8", "replace").strip()
                self.buf = self.buf[nl + 1:]
                if line.startswith("{"):
                    try:
                        return json.loads(line)
                    except json.JSONDecodeError:
                        continue
                continue
            left = t_end - time.time()
            if left <= 0:
                self.kill()
                return None
            ready, _, _ = select.select([fd], [], [], min(left, 1.0))
            if not ready:
                continue
            chunk = os.read(fd, 65536)
            if not chunk:  # EOF: child finished (or died)
                return None
            self.buf += chunk

    def kill(self) -> None:
        try:
            os.killpg(self.child.pid, signal.SIGKILL)
        except OSError:
            pass

    def close(self) -> None:
        self.kill()
        try:
            self.child.stdout.close()
            self.err_f.close()
        except OSError:
            pass


def _cpp_rows() -> list:
    """Loopback numbers from the C++ runtime (multi_threaded_echo analogue);
    builds the binary on demand (works without cmake), else skips."""
    exe = os.path.join(os.path.dirname(os.path.abspath(__file__)), "build",
                       "bench_echo")
    try:
        from brpc_tpu.rpc._lib import ensure_bench_echo

        exe = str(ensure_bench_echo())
    except Exception:  # noqa: BLE001 — fall back to a prebuilt binary
        if not os.path.exists(exe):
            return []
    rows = []
    # Small-payload rows cover single AND multi-connection (pooled) so the
    # wait-free hot path (inline writes, batched dispatch, bulk wakeups)
    # is tracked per round; large rows guard against coalescing
    # regressions on the throughput path.
    for fibers, payload, conn in (
        (64, 1024, "single"),
        (64, 1024, "pooled"),
        (256, 1024, "pooled"),
        (8, 2 << 20, "single"),
        (8, 2 << 20, "pooled"),
        # Native anchor for the Python batch leg: same 4MB x 8-deep
        # geometry the zerocopy pipeline row runs, all-native — the gap
        # between the two IS the Python-boundary cost per round.
        (8, 4 << 20, "pooled"),
        # Mid-large band (ISSUE 5): the striped multi-rail path at native
        # sync-call geometry — the row the monolithic-frame collapse
        # (407 MB/s in r05) used to hide in.
        (8, 16 << 20, "pooled"),
    ):
        try:
            out = subprocess.run(
                [exe, str(fibers), str(payload), "2", conn],
                capture_output=True, text=True, timeout=60,
            )
            line = out.stdout.strip().splitlines()[-1]
            rows.append(json.loads(line))
        except Exception:  # noqa: BLE001 — bench must still print its line
            pass
    return rows


def _harvest(sizes: list[int], force_cpu: bool, budget_end: float,
             first_row_s: float, row_s: float) -> dict[int, dict]:
    """Collect rows for `sizes` from one child; partial results kept."""
    rows: dict[int, dict] = {}
    reader = _RowReader(sizes, force_cpu)
    try:
        deadline = first_row_s
        while len(rows) < len(sizes):
            deadline = min(deadline, budget_end - time.time())
            if deadline <= 0:
                break
            row = reader.next_row(deadline)
            if row is None:
                break
            if isinstance(row.get("size"), int):
                rows[row["size"]] = row
            deadline = row_s
    finally:
        reader.close()
    return rows


def _run_json_child(env_flags: dict[str, str], timeout: float) -> dict | None:
    """Runs this script as a child with `env_flags` set; returns its last
    JSON line (killable group — TPU children can wedge)."""
    if timeout < 10:
        return None
    try:
        env = dict(os.environ)
        env.update(env_flags)
        out = subprocess.run(
            [sys.executable, os.path.abspath(__file__)], env=env,
            capture_output=True, text=True, timeout=timeout,
            start_new_session=True)
        for ln in out.stdout.splitlines()[::-1]:
            if ln.startswith("{"):
                return json.loads(ln)
    except Exception:  # noqa: BLE001 — bench must still print its line
        pass
    return None


def main() -> None:
    if os.environ.get("BENCH_ZC"):
        _child_zerocopy()
        return
    if os.environ.get("BENCH_QOS"):
        _child_qos_mixed()
        return
    if os.environ.get("BENCH_KV"):
        _child_kv_disagg()
        return
    if os.environ.get("BENCH_INFER"):
        _child_infer_serving()
        return
    if os.environ.get("BENCH_RR"):
        _child_rolling_restart()
        return
    if os.environ.get("BENCH_REPLAY"):
        _child_replay()
        return
    if os.environ.get("BENCH_COLL"):
        _child_collective()
        return
    if os.environ.get("BENCH_OVERLAP"):
        _child_pipeline_overlap()
        return
    if os.environ.get("BENCH_SLO_FLEET"):
        _child_slo_fleet()
        return
    if os.environ.get("BENCH_SELF_TUNE"):
        _child_self_tune()
        return
    if os.environ.get("BENCH_TPU_RPC"):
        _child_tpu_rpc()
        return
    if os.environ.get("BENCH_CHILD"):
        sizes = [int(s) for s in
                 os.environ.get("BENCH_SIZES", "").split(",") if s] or SIZES
        _child_sweep(sizes)
        return

    # Default sized so the WORST case (every TPU attempt wedging through
    # its deadline) still finishes inside the driver's observed patience
    # (r04's run completed at ~700s; the retry loop spends budget-250 on
    # TPU attempts, then CPU fallback + rpc legs).
    budget = float(os.environ.get("BENCH_BUDGET", "800"))
    budget_end = time.time() + budget
    os.makedirs(CACHE_DIR, exist_ok=True)

    # TPU leg: RETRY through tunnel wedges (VERDICT r4 weak #1 — r04 gave
    # the TPU leg exactly one child; one wedged backend init erased the
    # whole round's hardware evidence).  Each attempt re-runs only the
    # still-missing sizes; a wedge-prone tunnel often comes good on the
    # second or third init.
    rows: dict[int, dict] = {}
    tpu_attempts = 0
    if not os.environ.get("BENCH_FORCE_CPU"):
        # Reserve tail budget: CPU fallback (~90s) + zerocopy (60s) +
        # the tpu_rpc leg (itself retried, below).
        tpu_end = budget_end - 250
        while tpu_attempts < 4:
            missing = [s for s in SIZES if s not in rows]
            remaining = tpu_end - time.time()
            if not missing or remaining < 60:
                break
            tpu_attempts += 1
            got = _harvest(missing, force_cpu=False, budget_end=tpu_end,
                           first_row_s=min(240, remaining), row_s=120)
            rows.update(got)
            if not got and tpu_attempts >= 2:
                break  # two inits in a row produced nothing: tunnel is down
    missing = [s for s in SIZES if s not in rows]
    if missing:
        cpu_rows = _harvest(missing, force_cpu=True, budget_end=budget_end,
                            first_row_s=90, row_s=60)
        rows.update(cpu_rows)

    sweep = [rows[s] for s in SIZES if s in rows]
    if not sweep:
        raise RuntimeError(
            "bench produced no rows on TPU or CPU; last child stderr:\n" +
            open("/tmp/bench_child.err").read()[-2000:])
    zerocopy = _run_json_child({"BENCH_ZC": "1"}, 60)
    qos_mixed = _run_json_child({"BENCH_QOS": "1"}, 90)
    kv_disagg = _run_json_child({"BENCH_KV": "1"}, 240)
    # prefix_cache row (ISSUE 17): the content-addressed cache metrics
    # measured in the SAME kv_disagg run (the goodput/p99 floors and the
    # recompute drop must hold simultaneously), lifted into their own
    # headline row.
    prefix_cache = None
    if kv_disagg and "prefix_recompute_drop" in kv_disagg:
        prefix_cache = {
            "workload": "prefix_cache_zipf_multitenant",
            "same_run_as": "kv_disagg",
            "kv_goodput_gbps": kv_disagg["kv_goodput_gbps"],
            "ratio_p99": kv_disagg["ratio_p99"],
        }
        prefix_cache.update({k: v for k, v in kv_disagg.items()
                             if k.startswith(("prefix_", "lb_hint_"))})
    rolling_restart = _run_json_child({"BENCH_RR": "1"}, 240)
    replay = _run_json_child({"BENCH_REPLAY": "1"}, 300)
    coll = _run_json_child({"BENCH_COLL": "1"}, 240)
    pipeline_overlap = _run_json_child({"BENCH_OVERLAP": "1"}, 240)
    slo_fleet = _run_json_child({"BENCH_SLO_FLEET": "1"}, 240)
    self_tune = _run_json_child({"BENCH_SELF_TUNE": "1"}, 240)
    infer_serving = _run_json_child({"BENCH_INFER": "1"}, 600)

    # tpu_rpc leg, same retry contract; a CPU-platform run is still a real
    # measurement of the native RPC stack, so fall back rather than emit
    # null (r04's artifact had tpu_rpc: null).
    tpu_rpc = None
    rpc_attempts = 0
    while tpu_rpc is None and rpc_attempts < 3:
        remaining = budget_end - 130 - time.time()
        if remaining < 30:
            break
        rpc_attempts += 1
        tpu_rpc = _run_json_child({"BENCH_TPU_RPC": "1"},
                                  min(240, remaining))
    if tpu_rpc is None:
        rpc_attempts += 1
        tpu_rpc = _run_json_child(
            {"BENCH_TPU_RPC": "1", "BENCH_FORCE_CPU": "1"},
            max(30.0, budget_end - time.time()))
    if tpu_rpc is not None:
        tpu_rpc["attempts"] = rpc_attempts

    head = sweep[-1]  # largest completed size (64MB when all rows landed)
    print(json.dumps({
        "metric": "echo_goodput_64MB",
        "value": head["goodput_gbps"],
        "unit": "GB/s",
        "vs_baseline": round(head["goodput_gbps"] / BASELINE_GBPS, 3),
        "platform": head["platform"],
        "tpu_attempts": tpu_attempts,
        "sweep": sweep,
        "tpu_rpc": tpu_rpc,
        "cpp": _cpp_rows(),
        "zerocopy": zerocopy,
        "qos_mixed": qos_mixed,
        "kv_disagg": kv_disagg,
        "prefix_cache": prefix_cache,
        "rolling_restart": rolling_restart,
        "replay": replay,
        "collective": coll,
        "pipeline_overlap": pipeline_overlap,
        "slo_fleet": slo_fleet,
        "self_tune": self_tune,
        "infer_serving": infer_serving,
    }))


if __name__ == "__main__":
    main()
