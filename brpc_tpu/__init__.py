"""brpc_tpu — a TPU-native RPC/data-movement framework.

Re-designs the capability set of apache/brpc (reference: /root/reference) for
TPU: the bulk data plane is compiled XLA collectives over the ICI mesh
(`brpc_tpu.transport`, `brpc_tpu.channels`), while the host runtime (fibers,
sockets, protocols, metrics) is native C++ under cpp/ bound via
`brpc_tpu.rpc`.  See ARCHITECTURE.md.
"""

from brpc_tpu.parallel.fabric import Fabric  # noqa: F401

__version__ = "0.1.0"
