from brpc_tpu.channels.combo import (  # noqa: F401
    DynamicPartitionChannel,
    ParallelChannel,
    PartitionChannel,
    SelectiveChannel,
)
from brpc_tpu.channels.balancer import (  # noqa: F401
    ConsistentHash,
    RandomBalancer,
    RoundRobin,
    WeightedRandom,
)
