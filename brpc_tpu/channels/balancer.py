"""Host-side load balancers over peer indices.

Parity with the reference's LB family (SURVEY.md §2.4, policy/*_load_balancer):
round-robin, (weighted) random, consistent hashing, and an EWMA
latency-feedback balancer standing in for locality-aware + p2c.  The balanced
"servers" are mesh peer indices consumed by
:class:`brpc_tpu.channels.combo.SelectiveChannel`; feedback comes from the
caller the way ``Controller::Call::OnComplete`` feeds brpc's LBs
(/root/reference/src/brpc/controller.cpp:804).
"""

from __future__ import annotations

import bisect
import hashlib
import itertools
import random
import threading

__all__ = ["RoundRobin", "RandomBalancer", "WeightedRandom", "ConsistentHash", "EwmaP2C"]


class RoundRobin:
    def __init__(self, n: int):
        self._it = itertools.cycle(range(n))
        self._lock = threading.Lock()

    def pick(self, key=None) -> int:
        with self._lock:
            return next(self._it)

    def feedback(self, peer: int, latency_s: float) -> None:
        pass


class RandomBalancer:
    def __init__(self, n: int, seed: int = 0):
        self.n = n
        self._rng = random.Random(seed)

    def pick(self, key=None) -> int:
        return self._rng.randrange(self.n)

    def feedback(self, peer: int, latency_s: float) -> None:
        pass


class WeightedRandom:
    def __init__(self, weights, seed: int = 0):
        self.weights = list(weights)
        self._rng = random.Random(seed)

    def pick(self, key=None) -> int:
        return self._rng.choices(range(len(self.weights)), self.weights)[0]

    def feedback(self, peer: int, latency_s: float) -> None:
        pass


class ConsistentHash:
    """Ketama-style ring: `replicas` virtual nodes per peer, md5 points."""

    def __init__(self, n: int, replicas: int = 50):
        points = []
        for peer in range(n):
            for r in range(replicas):
                h = hashlib.md5(f"{peer}:{r}".encode()).digest()
                points.append((int.from_bytes(h[:8], "little"), peer))
        points.sort()
        self._ring = [p[0] for p in points]
        self._peers = [p[1] for p in points]

    def pick(self, key) -> int:
        h = hashlib.md5(str(key).encode()).digest()
        x = int.from_bytes(h[:8], "little")
        i = bisect.bisect_left(self._ring, x) % len(self._ring)
        return self._peers[i]

    def feedback(self, peer: int, latency_s: float) -> None:
        pass


class EwmaP2C:
    """Power-of-two-choices with EWMA latency feedback (p2c_ewma parity)."""

    def __init__(self, n: int, alpha: float = 0.2, seed: int = 0):
        self.lat = [0.0] * n
        self.alpha = alpha
        self._rng = random.Random(seed)
        self._lock = threading.Lock()

    def pick(self, key=None) -> int:
        a, b = self._rng.sample(range(len(self.lat)), 2) if len(self.lat) > 1 else (0, 0)
        return a if self.lat[a] <= self.lat[b] else b

    def feedback(self, peer: int, latency_s: float) -> None:
        with self._lock:
            self.lat[peer] += self.alpha * (latency_s - self.lat[peer])
