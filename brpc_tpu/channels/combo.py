"""Combo channels — declarative scatter/gather lowered to XLA collectives.

Reference parity (SURVEY.md §2.4):

- ``ParallelChannel`` (/root/reference/src/brpc/parallel_channel.h:202) fans
  one request out to N sub-channels with a ``CallMapper`` (:102) and merges
  responses with a ``ResponseMerger`` (:141).  TPU-native: the fan-out is
  SPMD replication, each peer runs its handler shard, and the merger is a
  collective (all_gather / psum / pmax) — one compiled program instead of N
  sockets and a malloc'd sub-done block (parallel_channel.cpp:88-153).
- ``PartitionChannel`` (partition_channel.h:75) shards the request by a
  ``PartitionParser``; here partitioning IS the input PartitionSpec.
- ``SelectiveChannel`` (selective_channel.h:52) load-balances over
  heterogeneous sub-channels; here selection is a traced peer index and the
  reply is masked-psum'd back (no data-dependent branching outside lax).

Handlers are SPMD functions ``handler(peer_index, request_shard) ->
response_shard`` — the analogue of a service method body.
"""

from __future__ import annotations

from typing import Callable

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import PartitionSpec as P
from jax.tree_util import tree_map

from brpc_tpu.parallel.fabric import Fabric

__all__ = ["ParallelChannel", "PartitionChannel", "SelectiveChannel"]

Handler = Callable  # handler(peer_index, request) -> response


class _MergerLib:
    """Named response mergers (ResponseMerger parity)."""

    @staticmethod
    def get(name_or_fn, axis):
        if callable(name_or_fn):
            return lambda r: name_or_fn(r, axis)
        table = {
            "gather": lambda r: tree_map(
                lambda t: lax.all_gather(t, axis, tiled=False), r
            ),
            "concat": lambda r: tree_map(
                lambda t: lax.all_gather(t, axis, tiled=True), r
            ),
            "sum": lambda r: lax.psum(r, axis),
            "max": lambda r: lax.pmax(r, axis),
            "min": lambda r: lax.pmin(r, axis),
            "none": lambda r: r,  # keep responses sharded
        }
        return table[name_or_fn]


class _BoundCache:
    """bind() results memoized per handler so repeated call()s reuse the
    compiled program (jit caches by function identity; a fresh closure per
    call would recompile every time).

    Callers on a hot path must pass a STABLE callable — a lambda constructed
    inside the request loop misses this cache every time and re-traces.  The
    cache is bounded: oldest half is dropped past `kMax` entries so stale
    handlers (and the arrays they close over) can't accumulate forever.
    """

    kMax = 64

    def __init__(self):
        self._cache: dict = {}

    def get_or_build(self, handler, builder):
        fn = self._cache.get(handler)
        if fn is None:
            if len(self._cache) >= self.kMax:
                for key in list(self._cache)[: self.kMax // 2]:
                    del self._cache[key]
            fn = self._cache[handler] = builder()
        return fn


class ParallelChannel:
    """Fan a replicated request out to every peer on `axis`; merge replies.

    `out_spec` describes the merged response's global layout; it defaults to
    replicated for the named mergers and MUST be given for a callable merger
    that keeps its result sharded (e.g. a psum_scatter merger).
    """

    def __init__(
        self,
        fabric: Fabric,
        axis: str = "link",
        call_mapper: Callable | None = None,
        response_merger="gather",
        out_spec=None,
    ):
        self.fabric = fabric
        self.axis = axis
        self.call_mapper = call_mapper
        self.response_merger = response_merger
        if out_spec is None:
            out_spec = P(axis) if response_merger == "none" else P()
        self.out_spec = out_spec
        self._bound = _BoundCache()

    def bind(self, handler: Handler):
        """Compile `handler` behind this channel; returns request -> merged."""
        axis = self.axis
        merge = _MergerLib.get(self.response_merger, axis)
        mapper = self.call_mapper

        def build():
            def spmd(request):
                i = lax.axis_index(axis)
                sub = mapper(i, request) if mapper is not None else request
                return merge(handler(i, sub))

            fn = self.fabric.spmd(spmd, in_specs=P(), out_specs=self.out_spec)
            return jax.jit(fn)

        return self._bound.get_or_build(handler, build)

    def call(self, handler: Handler, request):
        return self.bind(handler)(request)


class PartitionChannel:
    """Shard the request along its leading dim across peers on `axis`."""

    def __init__(
        self,
        fabric: Fabric,
        axis: str = "link",
        response_merger="concat",
        out_spec=None,
    ):
        self.fabric = fabric
        self.axis = axis
        self.response_merger = response_merger
        if out_spec is None:
            out_spec = P(axis) if response_merger == "none" else P()
        self.out_spec = out_spec
        self._bound = _BoundCache()

    def bind(self, handler: Handler):
        axis = self.axis
        merge = _MergerLib.get(self.response_merger, axis)

        def build():
            def spmd(request):
                i = lax.axis_index(axis)
                return merge(handler(i, request))

            fn = self.fabric.spmd(spmd, in_specs=P(axis), out_specs=self.out_spec)
            return jax.jit(fn)

        return self._bound.get_or_build(handler, build)

    def call(self, handler: Handler, request):
        return self.bind(handler)(request)


class SelectiveChannel:
    """Route each request to ONE peer chosen at call time.

    The chosen index is a traced scalar, so one compiled program serves any
    routing decision — the host-side balancer (`brpc_tpu.channels.balancer`)
    plays the role of the LB inside selective_channel.cpp.  Handlers may
    return pytrees; every leaf is masked and psum'd back.
    """

    def __init__(self, fabric: Fabric, axis: str = "link"):
        self.fabric = fabric
        self.axis = axis
        self._bound = _BoundCache()

    def bind(self, handler: Handler):
        axis = self.axis

        def build():
            def spmd(request, chosen):
                i = lax.axis_index(axis)
                resp = handler(i, request)
                # where-select, not mask-multiply: a non-chosen peer emitting
                # inf/nan must not poison the psum (0 * inf = nan).
                picked = tree_map(
                    lambda t: jnp.where(i == chosen[0], t, jnp.zeros_like(t)),
                    resp,
                )
                return lax.psum(picked, axis)

            fn = self.fabric.spmd(spmd, in_specs=(P(), P()), out_specs=P())
            jitted = jax.jit(fn)
            return lambda request, chosen: jitted(
                request, jnp.asarray([chosen], dtype=jnp.int32)
            )

        return self._bound.get_or_build(handler, build)

    def call(self, handler: Handler, request, chosen: int):
        return self.bind(handler)(request, chosen)


class DynamicPartitionChannel:
    """Traffic split across COEXISTING partitioning schemes.

    Parity: the reference's DynamicPartitionChannel
    (/root/reference/src/brpc/partition_channel.h:136) — during a
    resharding migration both the old N-way and the new M-way partition
    groups serve, each receiving traffic proportional to its capacity, so
    the fleet migrates without a flag day.  TPU-native form: each scheme
    is a PartitionChannel over its own mesh axis/fabric; calls are routed
    host-side by capacity weights (default: the scheme's partition count),
    which can be re-weighted live as the migration progresses.
    """

    def __init__(self, schemes, weights=None, seed: int = 0):
        """schemes: list of PartitionChannel; weights: per-scheme capacity
        (defaults to each scheme's partition count)."""
        if not schemes:
            raise ValueError("need at least one partition scheme")
        self.schemes = list(schemes)
        if weights is None:
            weights = [s.fabric.axis_size(s.axis) for s in self.schemes]
        self.set_weights(weights)
        self._counts = [0] * len(self.schemes)
        self._seq = seed

    def set_weights(self, weights):
        """Live re-weighting (e.g. drain the old scheme to 0)."""
        if len(weights) != len(self.schemes) or any(w < 0 for w in weights):
            raise ValueError("one non-negative weight per scheme")
        if sum(weights) <= 0:
            raise ValueError("at least one scheme must have weight > 0")
        self.weights = list(weights)

    def _pick(self) -> int:
        # Deterministic low-discrepancy rotation (no RNG in the data path):
        # scheme i gets weight_i of every sum(weights) consecutive calls.
        total = sum(self.weights)
        tick = self._seq % total
        self._seq += 1
        for i, w in enumerate(self.weights):
            tick -= w
            if tick < 0:
                return i
        return len(self.weights) - 1

    def call(self, handler: Handler, request):
        """Routes one request to a scheme; returns (scheme_index, result).
        `request` must be shaped for ANY scheme (leading dim divisible by
        every scheme's partition count)."""
        i = self._pick()
        self._counts[i] += 1
        return i, self.schemes[i].call(handler, request)

    @property
    def counts(self):
        """Requests served per scheme (migration progress observability)."""
        return tuple(self._counts)
