from brpc_tpu.models.echo import (  # noqa: F401
    make_full_dataplane_step,
    make_nton_exchange,
    single_chip_echo_step,
)
