"""Benchmark workloads — the framework's "flagship models".

Parity targets (BASELINE.md configs):
- ``single_chip_echo_step``  → example/echo_c++ (single sync echo, one chip)
- ``make_nton_exchange``     → example/rdma_performance N-to-N 64MB exchange
  (/root/reference/example/rdma_performance/client.cpp:35-54)
- ``make_full_dataplane_step`` → the combined fan-out + partition + ring step
  the driver dry-runs over a multi-device mesh.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import PartitionSpec as P

from brpc_tpu.ops.checksum import sum32
from brpc_tpu.parallel.fabric import Fabric
from brpc_tpu.transport.ici import IciTransport, _ring_perm


def single_chip_echo_step(payload: jnp.ndarray):
    """One echo round trip on one chip: the server 'receives' the request
    buffer, verifies it, and materializes the response copy in HBM.

    Returns (response, checksum).  The copy is forced (payload + 0 would fold
    away; we roll by one lane so XLA must move the bytes) — the HBM write is
    the on-device analogue of the NIC's echo write-back.
    """
    resp = jnp.roll(payload, 1)
    return resp, sum32(resp)


def make_nton_exchange(fabric: Fabric, axis: str = "link"):
    """Every peer sends a distinct row to every other peer and checksums what
    it received — one compiled all-to-all riding the ICI mesh.

    Input layout per shard: (n, chunk) uint32, row j destined for peer j.
    Returns (received, checksum_per_peer).
    """
    t = IciTransport(fabric, axis)

    def spmd(local):
        recv = t.all_to_all(local)
        return recv, sum32(recv)[None]

    fn = fabric.spmd(spmd, in_specs=P(axis), out_specs=(P(axis), P(axis)))
    return jax.jit(fn)


def make_ring_exchange(fabric: Fabric, axis: str = "link"):
    """Explicit ppermute-ring N-to-N (the schedule variant): N-1 hops, each
    hop's arrival checksummed while the next hop is in flight."""
    t = IciTransport(fabric, axis)

    def spmd(local):
        buf, carry, _ = t.ring_exchange(local)
        return buf, carry[None]

    fn = fabric.spmd(spmd, in_specs=P(axis), out_specs=(P(axis), P(axis)))
    return jax.jit(fn)


def make_full_dataplane_step(fabric: Fabric, fan_axis: str = "dp", part_axis: str = "link"):
    """The composite step exercising every channel kind at once:

    - the request tensor is partitioned over `part_axis` (PartitionChannel),
    - replicated over `fan_axis` where each replica applies its own handler
      transform (ParallelChannel fan-out),
    - replicas' responses merge with psum over `fan_axis` (ResponseMerger),
    - partitions then run one ppermute ring hop over `part_axis` to their
      neighbor and back (streaming echo), and
    - a final fletcher-style checksum verifies the whole exchange.

    Returns a jitted fn: (payload[(rows, cols) f32]) -> (response, checksum).
    """
    n_part = fabric.axis_size(part_axis)
    perm = _ring_perm(n_part, 1)
    perm_back = _ring_perm(n_part, -1)

    def spmd(payload):
        rep = lax.axis_index(fan_axis).astype(payload.dtype)
        handled = payload * (rep + 1.0)  # per-replica handler
        merged = lax.psum(handled, fan_axis)  # ResponseMerger: sum
        sent = lax.ppermute(merged, part_axis, perm)  # stream out
        back = lax.ppermute(sent, part_axis, perm_back)  # echo back
        csum = lax.psum(jnp.sum(back), part_axis)
        return back, csum[None]

    fn = fabric.spmd(
        spmd,
        in_specs=P(part_axis, None),
        out_specs=(P(part_axis, None), P()),
    )
    return jax.jit(fn)
