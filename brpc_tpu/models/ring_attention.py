"""Ring attention — sequence-parallel attention with rotating KV blocks.

The long-context primitive SURVEY §5 asks for as a first-class citizen:
sequences too long for one chip shard along the sequence axis, each
device holds one Q/K/V block, and K/V blocks travel the ring (one
``ppermute`` hop per step) while every device folds each arriving block
into its local queries with the online-softmax (flash-attention)
accumulator.  Communication rides ICI exactly like the reference's RDMA
data plane rides ibverbs (/root/reference/src/brpc/rdma/
rdma_endpoint.cpp); "completion" is XLA dataflow, and the scan body only
serializes through the carry so hop k+1's DMA overlaps hop k's matmuls.

Numerics: the per-block update keeps running (max, sum, weighted output)
per query row; merging two blocks rescales both sides by
``exp(m_old - m_new)``.  This is the standard streaming-softmax identity,
so the result equals full attention up to float rounding (checked
against the single-block oracle in tests).

Causal masking is position-aware across the ring: block j's keys carry
global positions ``j*L .. (j+1)*L``, so hops from "future" blocks mask
to -inf entirely and the diagonal block applies the triangular mask.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import PartitionSpec as P

from brpc_tpu.parallel.fabric import Fabric

__all__ = ["ring_attention", "attention_reference"]

_NEG_INF = -1e30


def _block_scores(q, k, scale, causal, q_pos, k_pos):
    """Scaled scores of local queries against one KV block (+ causal mask)."""
    # q: [sq, d]  k: [sk, d]  → [sq, sk]; accumulate in f32 on the MXU.
    s = jnp.einsum("qd,kd->qk", q, k,
                   preferred_element_type=jnp.float32) * scale
    if causal:
        mask = q_pos[:, None] >= k_pos[None, :]
        s = jnp.where(mask, s, _NEG_INF)
    return s


def _fold_block(acc, s, v):
    """Online-softmax fold of one block's scores/values into (m, l, o)."""
    m, l, o = acc
    m_new = jnp.maximum(m, jnp.max(s, axis=-1))
    # exp() of fully-masked rows underflows to 0 — no NaN path.
    p = jnp.exp(s - m_new[:, None])
    correction = jnp.exp(m - m_new)
    l_new = l * correction + jnp.sum(p, axis=-1)
    o_new = o * correction[:, None] + jnp.einsum(
        "qk,kd->qd", p.astype(v.dtype), v,
        preferred_element_type=jnp.float32)
    return m_new, l_new, o_new


def ring_attention(fabric: Fabric, axis: str = "link",
                   causal: bool = False):
    """Builds the jitted SPMD ring-attention step over `fabric`.

    Returns ``fn(q, k, v) -> out`` where every array is
    ``[batch*heads, seq, head_dim]`` sharded along ``seq`` on `axis`
    (use ``fabric.sharding(None, axis, None)``); `out` matches `q`.
    """
    n = fabric.axis_size(axis)

    def spmd(q, k, v):
        my_id = lax.axis_index(axis)
        bh, sq, d = q.shape
        scale = 1.0 / (d ** 0.5)
        q_pos = my_id * sq + lax.iota(jnp.int32, sq)

        def fold(acc, kv, owner):
            k_blk, v_blk = kv
            k_pos = owner * sq + lax.iota(jnp.int32, sq)
            s = jax.vmap(lambda qq, kk: _block_scores(
                qq, kk, scale, causal, q_pos, k_pos))(q, k_blk)
            return jax.vmap(_fold_block)(acc, s, v_blk)

        acc0 = (
            jnp.full((bh, sq), _NEG_INF, jnp.float32),
            jnp.zeros((bh, sq), jnp.float32),
            jnp.zeros((bh, sq, d), jnp.float32),
        )
        # Hop 0: the local block, in place.
        acc = fold(acc0, (k, v), my_id)

        perm = [(i, (i + 1) % n) for i in range(n)]

        def body(state, hop):
            kv, acc = state
            # One ring hop: our current block moves right, the left
            # neighbor's lands here — a one-sided ICI put, double-buffered
            # by XLA; the scan carry is the only serialization.
            kv = lax.ppermute(kv, axis, perm)
            owner = lax.rem(my_id - hop + n, n)
            acc = fold(acc, kv, owner)
            return (kv, acc), None

        (kv, acc), _ = lax.scan(body, ((k, v), acc), jnp.arange(1, n))
        m, l, o = acc
        # Fully-masked rows (causal, leading queries see only themselves —
        # l is always ≥ 1 there; guard anyway for degenerate shapes).
        l = jnp.where(l == 0, 1.0, l)
        return (o / l[:, :, None]).astype(q.dtype)

    shard = P(None, axis, None)
    return jax.jit(fabric.spmd(spmd, in_specs=(shard,) * 3,
                               out_specs=shard))


def attention_reference(causal: bool = False):
    """Single-device oracle: plain full softmax attention."""

    @jax.jit
    def fn(q, k, v):
        d = q.shape[-1]
        s = jnp.einsum("bqd,bkd->bqk", q, k,
                       preferred_element_type=jnp.float32) / (d ** 0.5)
        if causal:
            sq, sk = s.shape[-2], s.shape[-1]
            mask = (lax.iota(jnp.int32, sq)[:, None] >=
                    lax.iota(jnp.int32, sk)[None, :])
            s = jnp.where(mask, s, _NEG_INF)
        p = jax.nn.softmax(s, axis=-1)
        return jnp.einsum("bqk,bkd->bqd", p.astype(v.dtype), v,
                          preferred_element_type=jnp.float32).astype(q.dtype)

    return fn
