from brpc_tpu.ops.checksum import fletcher32, sum32  # noqa: F401
