"""Payload checksums — the verification op of the echo benchmarks.

The reference checksums RPC payloads with hardware crc32c
(/root/reference/src/butil/crc32c.cc, policy/crc32c_checksum.cpp).  CRC's
bit-serial carry chain is hostile to the VPU, so the TPU-native integrity
check is a Fletcher-style two-lane sum — fully data-parallel, one pass over
HBM, fused by XLA into whatever op produced the payload.
"""

from __future__ import annotations

import jax.numpy as jnp

__all__ = ["sum32", "fletcher32"]


def sum32(x) -> jnp.ndarray:
    """Plain 32-bit wrapping sum of the payload (fast integrity check)."""
    return jnp.sum(x.astype(jnp.uint32).ravel(), dtype=jnp.uint32)


def fletcher32(x) -> jnp.ndarray:
    """Fletcher-like checksum: (sum, position-weighted sum) packed in uint32x2.

    Position weighting catches reorderings a plain sum misses — the property
    that matters for verifying ring-exchange hop schedules.
    """
    v = x.astype(jnp.uint32).ravel()
    idx = jnp.arange(v.shape[0], dtype=jnp.uint32) + jnp.uint32(1)
    return jnp.stack([jnp.sum(v, dtype=jnp.uint32), jnp.sum(v * idx, dtype=jnp.uint32)])
