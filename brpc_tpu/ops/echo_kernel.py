"""Fused echo kernel — the data-plane hot op as a single HBM pass.

The echo server's work per payload is "receive, verify, materialize the
response": as plain jnp this is a roll (copy) plus a reduction — two HBM
passes unless XLA fuses them.  The Pallas kernel guarantees the fusion: one
grid over the payload, each block copied through VMEM exactly once while the
checksum accumulates in SMEM.

Falls back to the jnp composition off-TPU (tests run it in interpret mode).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from brpc_tpu.ops.checksum import sum32

_ROWS = 16       # sublane-aligned block rows (uint32 min tile is 8x128);
                 # see tools/tune_echo.py for the measured sweep backing
                 # this default
_COLS = 8192     # lanes per row
_BLOCK = _ROWS * _COLS  # uint32 lanes per grid step (512KB)


def _kernel(x_ref, out_ref, acc_ref):
    from jax.experimental import pallas as pl  # noqa: PLC0415

    i = pl.program_id(0)

    @pl.when(i == 0)
    def _init():
        acc_ref[0, 0] = jnp.int32(0)

    block = x_ref[...]
    out_ref[...] = block
    # TPU lowers signed reductions only; int32 wrap == uint32 wrap.
    acc_ref[0, 0] += jnp.sum(block.astype(jnp.int32), dtype=jnp.int32)


def echo_fused(payload: jnp.ndarray, interpret: bool = False,
               rows: int = _ROWS, cols: int = _COLS):
    """payload: uint32[n] with n % (rows*cols) == 0.  Returns
    (copy, checksum).  rows/cols pick the per-grid-step tile (tuning:
    tools/tune_echo.py)."""
    from jax.experimental import pallas as pl  # noqa: PLC0415
    from jax.experimental.pallas import tpu as pltpu  # noqa: PLC0415

    n = payload.shape[0]
    block = rows * cols
    assert n % block == 0, f"payload lanes {n} not a multiple of {block}"
    x2d = payload.reshape(n // cols, cols)
    grid = (n // block,)
    copy, acc = pl.pallas_call(
        _kernel,
        grid=grid,
        in_specs=[pl.BlockSpec((rows, cols), lambda i: (i, 0))],
        out_specs=[
            pl.BlockSpec((rows, cols), lambda i: (i, 0)),
            pl.BlockSpec(memory_space=pltpu.SMEM),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((n // cols, cols), jnp.uint32),
            jax.ShapeDtypeStruct((1, 1), jnp.int32),
        ],
        interpret=interpret,
    )(x2d)
    return copy.reshape(n), acc[0, 0].astype(jnp.uint32)


def echo_reference(payload: jnp.ndarray):
    """The jnp composition the kernel fuses — used by the equivalence tests.

    NOT a performance fallback: XLA folds the +0 copy away, so off-TPU
    benchmarking uses models.echo.single_chip_echo_step (roll forces the
    copy); cross-backend goodput numbers are therefore not comparable.
    """
    return payload + jnp.uint32(0), sum32(payload)
