"""Pallas ring all-gather — the explicit ICI schedule as a kernel.

The XLA `lax.all_gather` already rides ICI; this kernel is the hand-rolled
equivalent (N-1 neighbor hops with double-buffered `make_async_remote_copy`
RDMA, per the TPU kernel playbook) for when the schedule itself must be
controlled — e.g. overlapping each arriving chunk with consumer compute, the
role brpc's RDMA endpoint plays for ibverbs
(/root/reference/src/brpc/rdma/rdma_endpoint.cpp).

Runs natively on a real multi-chip TPU backend, or anywhere under the
pallas TPU interpreter via ``interpret=True`` (how the CPU-mesh tests and
driver dryrun cover the shipping kernel). `ring_all_gather_reference` is
the XLA-collective oracle the kernel is checked against.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import PartitionSpec as P

from brpc_tpu.parallel.fabric import Fabric


def ring_all_gather_reference(fabric: Fabric, axis: str = "link"):
    """Collective-based reference: out[j] = shard j's row, on every peer."""

    def spmd(x):
        return lax.all_gather(x, axis, tiled=True)

    return jax.jit(fabric.spmd(spmd, in_specs=P(axis), out_specs=P()))


def _ring_kernel(axis, num_devices, chunk_rows, row_len, local_ref, out_ref,
                 comm_ref, send_sem, recv_sem, cap_sem):
    from jax.experimental import pallas as pl  # noqa: PLC0415
    from jax.experimental.pallas import tpu as pltpu  # noqa: PLC0415

    # The mesh is validated 1-D by the wrapper, so the axis index IS the
    # flat LOGICAL device id the remote copies address.
    my_id = lax.axis_index(axis)
    left = lax.rem(my_id - 1 + num_devices, num_devices)
    right = lax.rem(my_id + 1, num_devices)
    barrier = pltpu.get_barrier_semaphore()

    def hop_rdma(step):
        # Hop `step` sends from slot step%2 and lands in the peer's other
        # slot; descriptors are recreated per call — start/wait pair up via
        # the shared semaphores, not object identity.
        send_slot = lax.rem(step, 2)
        recv_slot = lax.rem(step + 1, 2)
        return pltpu.make_async_remote_copy(
            src_ref=comm_ref.at[send_slot],
            dst_ref=comm_ref.at[recv_slot],
            send_sem=send_sem.at[send_slot],
            recv_sem=recv_sem.at[recv_slot],
            device_id=right,
            device_id_type=pltpu.DeviceIdType.LOGICAL,
        )

    # Entry barrier: both neighbors are inside the kernel (scratch
    # allocated) before any hop-0 remote write may land.
    pltpu.semaphore_signal(barrier, inc=1, device_id=left)
    pltpu.semaphore_signal(barrier, inc=1, device_id=right)
    pltpu.semaphore_wait(barrier, 2)

    # Place the local chunk into its slot, seed the comm buffer, and put the
    # first hop's DMA in flight before any copy-out work.
    out_ref[pl.ds(my_id * chunk_rows, chunk_rows)] = local_ref[...]
    comm_ref[0] = local_ref[...]
    hop_rdma(0).start()

    def hop(step, _):
        recv_slot = lax.rem(step + 1, 2)
        parity = lax.rem(step, 2)
        src = lax.rem(my_id - step - 1 + 2 * num_devices, num_devices)
        cur = hop_rdma(step)
        cur.wait_recv()  # this hop's chunk has landed in comm[recv_slot]
        cur.wait_send()  # our send slot (parity) is drained — reusable

        # Double-buffered overlap: launch hop step+1 (forwarding the chunk
        # we just received) BEFORE copying this hop's chunk to the output,
        # so the next ICI transfer rides under the VMEM copy. Flow control
        # is point-to-point, not a counting barrier (a counting barrier
        # can't tell WHICH neighbor or WHICH round signaled, so a fast left
        # neighbor two signals ahead could unblock us while the right one
        # still holds the slot): after draining our own send of `parity` we
        # grant LEFT permission to overwrite comm[parity] next hop, and we
        # may only write into RIGHT's comm[parity] once right granted us
        # the same.
        @pl.when(step + 1 < num_devices - 1)
        def _start_next():
            pltpu.semaphore_signal(cap_sem.at[parity], inc=1, device_id=left)
            pltpu.semaphore_wait(cap_sem.at[parity], 1)
            hop_rdma(step + 1).start()

        out_ref[pl.ds(src * chunk_rows, chunk_rows)] = comm_ref[recv_slot]
        return 0

    lax.fori_loop(0, num_devices - 1, hop, 0)
    # Exit barrier: every signal we will ever receive has been consumed
    # (each grant pairs 1:1 with a wait), but neighbors may still have our
    # final DMA in flight — don't free scratch under them.
    pltpu.semaphore_signal(barrier, inc=1, device_id=left)
    pltpu.semaphore_signal(barrier, inc=1, device_id=right)
    pltpu.semaphore_wait(barrier, 2)


def ring_all_gather_pallas(fabric: Fabric, axis: str = "link",
                           interpret: bool = False):
    """Build the kernel-backed all-gather.

    Runs natively on a multi-chip TPU mesh; with ``interpret=True`` it runs
    under the pallas TPU interpreter (``pltpu.InterpretParams``), which
    emulates the remote DMAs and semaphores on any backend — that is how the
    CPU-mesh tests and the driver dryrun get correctness coverage of the
    exact kernel that ships to hardware.
    """
    from jax.experimental import pallas as pl  # noqa: PLC0415
    from jax.experimental.pallas import tpu as pltpu  # noqa: PLC0415

    n = fabric.axis_size(axis)
    mesh_platform = fabric.mesh.devices.flat[0].platform
    if n < 2 or (not interpret and mesh_platform != "tpu"):
        raise RuntimeError("pallas ring kernel needs a multi-chip TPU mesh; "
                           "use interpret=True or ring_all_gather_reference "
                           "elsewhere")
    if len(fabric.mesh.shape) != 1:
        # The kernel addresses remote DMAs by flat LOGICAL device id, which
        # only equals the axis index on a 1-D mesh.
        raise RuntimeError("pallas ring kernel needs a 1-D mesh over the "
                           "gathered axis; build a dedicated Fabric for it")

    def spmd(x):
        chunk_rows, row_len = x.shape
        kernel = functools.partial(_ring_kernel, axis, n, chunk_rows, row_len)
        # Chunks stay in VMEM (direct loads/stores are only legal there);
        # total VMEM footprint = (n + 3) * chunk — callers keep chunks small
        # and loop over larger payloads.
        return pl.pallas_call(
            kernel,
            out_shape=jax.ShapeDtypeStruct((n * chunk_rows, row_len), x.dtype),
            in_specs=[pl.BlockSpec(memory_space=pltpu.VMEM)],
            out_specs=pl.BlockSpec(memory_space=pltpu.VMEM),
            scratch_shapes=[
                pltpu.VMEM((2, chunk_rows, row_len), x.dtype),
                pltpu.SemaphoreType.DMA((2,)),
                pltpu.SemaphoreType.DMA((2,)),
                pltpu.SemaphoreType.REGULAR((2,)),
            ],
            compiler_params=pltpu.CompilerParams(collective_id=7),
            interpret=pltpu.InterpretParams() if interpret else False,
        )(x)

    return jax.jit(fabric.spmd(spmd, in_specs=P(axis), out_specs=P()))
