"""Pallas ring all-gather — the explicit ICI schedule as a kernel.

The XLA `lax.all_gather` already rides ICI; this kernel is the hand-rolled
equivalent (N-1 neighbor hops with double-buffered `make_async_remote_copy`
RDMA, per the TPU kernel playbook) for when the schedule itself must be
controlled — e.g. overlapping each arriving chunk with consumer compute, the
role brpc's RDMA endpoint plays for ibverbs
(/root/reference/src/brpc/rdma/rdma_endpoint.cpp).

Only constructible on a real multi-chip TPU backend; everywhere else use
`ring_all_gather_reference` (identical math via collectives), which the
equivalence test runs on the CPU mesh.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import PartitionSpec as P

from brpc_tpu.parallel.fabric import Fabric


def ring_all_gather_reference(fabric: Fabric, axis: str = "link"):
    """Collective-based reference: out[j] = shard j's row, on every peer."""

    def spmd(x):
        return lax.all_gather(x, axis, tiled=True)

    return jax.jit(fabric.spmd(spmd, in_specs=P(axis), out_specs=P()))


def _ring_kernel(num_devices, chunk_rows, row_len, local_ref, out_ref,
                 comm_ref, send_sem, recv_sem):
    from jax.experimental import pallas as pl  # noqa: PLC0415
    from jax.experimental.pallas import tpu as pltpu  # noqa: PLC0415

    my_id = lax.axis_index("link")
    left = lax.rem(my_id - 1 + num_devices, num_devices)
    right = lax.rem(my_id + 1, num_devices)
    barrier = pltpu.get_barrier_semaphore()

    def neighbor_barrier():
        # Both neighbors must pass this point before anyone's remote write
        # may land in our scratch (and vice versa).
        pltpu.semaphore_signal(barrier, inc=1, device_id=left)
        pltpu.semaphore_signal(barrier, inc=1, device_id=right)
        pltpu.semaphore_wait(barrier, 2)

    neighbor_barrier()  # peers are inside the kernel; scratch is ours

    # Place the local chunk into its slot and seed the comm buffer.
    out_ref[pl.ds(my_id * chunk_rows, chunk_rows)] = local_ref[...]
    comm_ref[0] = local_ref[...]

    def hop(step, _):
        send_slot = lax.rem(step, 2)
        recv_slot = lax.rem(step + 1, 2)
        src = lax.rem(my_id - step - 1 + 2 * num_devices, num_devices)
        rdma = pltpu.make_async_remote_copy(
            src_ref=comm_ref.at[send_slot],
            dst_ref=comm_ref.at[recv_slot],
            send_sem=send_sem.at[send_slot],
            recv_sem=recv_sem.at[recv_slot],
            device_id=(right,),
            device_id_type=pltpu.DeviceIdType.LOGICAL,
        )
        rdma.start()
        rdma.wait()
        out_ref[pl.ds(src * chunk_rows, chunk_rows)] = comm_ref[recv_slot]
        # Flow control: nobody starts hop step+1 (which reuses the other
        # slot parity) until both neighbors consumed this hop's chunk —
        # prevents a fast sender lapping a slow receiver's 2-slot buffer.
        neighbor_barrier()
        return 0

    lax.fori_loop(0, num_devices - 1, hop, 0)


def ring_all_gather_pallas(fabric: Fabric, axis: str = "link"):
    """Build the kernel-backed all-gather (TPU multi-chip only)."""
    from jax.experimental import pallas as pl  # noqa: PLC0415
    from jax.experimental.pallas import tpu as pltpu  # noqa: PLC0415

    n = fabric.axis_size(axis)
    if jax.devices()[0].platform != "tpu" or n < 2:
        raise RuntimeError("pallas ring kernel needs a multi-chip TPU mesh; "
                           "use ring_all_gather_reference elsewhere")

    def spmd(x):
        chunk_rows, row_len = x.shape
        kernel = functools.partial(_ring_kernel, n, chunk_rows, row_len)
        # Chunks stay in VMEM (direct loads/stores are only legal there);
        # total VMEM footprint = (n + 3) * chunk — callers keep chunks small
        # and loop over larger payloads.
        return pl.pallas_call(
            kernel,
            out_shape=jax.ShapeDtypeStruct((n * chunk_rows, row_len), x.dtype),
            in_specs=[pl.BlockSpec(memory_space=pltpu.VMEM)],
            out_specs=pl.BlockSpec(memory_space=pltpu.VMEM),
            scratch_shapes=[
                pltpu.VMEM((2, chunk_rows, row_len), x.dtype),
                pltpu.SemaphoreType.DMA((2,)),
                pltpu.SemaphoreType.DMA((2,)),
            ],
            compiler_params=pltpu.CompilerParams(collective_id=7),
        )(x)

    return jax.jit(fabric.spmd(spmd, in_specs=P(axis), out_specs=P()))
