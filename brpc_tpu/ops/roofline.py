"""Chip roofline constants — published HBM bandwidth per device kind.

The bench reports achieved-bandwidth fractions against these (BASELINE.md's
"≥80% of raw link" discipline applied to HBM: a kernel number without its
roofline fraction hides a 3-8x shortfall, VERDICT r3 weak #2).

Sources: public Cloud TPU system-architecture docs (cloud.google.com/tpu).
"""

HBM_PEAK_GBPS = {
    "TPU v4": 1228.0,
    "TPU v5 lite": 819.0,   # v5e
    "TPU v5": 2765.0,       # v5p
    "TPU v5p": 2765.0,
    "TPU v6 lite": 1640.0,  # v6e (Trillium)
    "TPU v6e": 1640.0,
}


def hbm_peak_gbps(device_kind: str) -> float | None:
    """Peak HBM bandwidth for a jax device_kind, or None if unknown."""
    if device_kind in HBM_PEAK_GBPS:
        return HBM_PEAK_GBPS[device_kind]
    for k, v in HBM_PEAK_GBPS.items():
        if device_kind.startswith(k):
            return v
    return None
