from brpc_tpu.parallel.fabric import Fabric, shard_map  # noqa: F401
