"""Fabric — the peer set of a TPU RPC domain.

The reference identifies peers with ``butil::EndPoint`` (ip:port,
/root/reference/src/butil/endpoint.h:253) resolved through naming services
(/root/reference/src/brpc/policy/*_naming_service.cpp) and pools connections in
a SocketMap.  On TPU the peer set is the XLA device mesh: every chip is
addressed by mesh coordinates, a "connection" is a (mesh, axis) pair whose
links are ICI neighbors, and "name resolution" is mesh construction.  There is
no per-connection state to pool — XLA compiles the routes.
"""

from __future__ import annotations

import math
from typing import Sequence

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

try:  # jax >= 0.6 promoted shard_map out of experimental
    from jax import shard_map as _shard_map_fn
except ImportError:  # pragma: no cover - older jax
    from jax.experimental.shard_map import shard_map as _shard_map_fn

shard_map = _shard_map_fn

__all__ = ["Fabric", "shard_map", "P"]


class Fabric:
    """A device mesh plus helpers to place data and wrap SPMD programs.

    Mirrors the role of brpc's ``NamingService``+``SocketMap`` pair
    (SURVEY.md §2.4): it answers "who are my peers and how do I address
    them", but the answer is mesh axes instead of EndPoint lists.
    """

    def __init__(self, mesh: Mesh):
        self.mesh = mesh

    # -- construction -----------------------------------------------------
    @classmethod
    def auto(
        cls,
        shape: Sequence[int] | None = None,
        axis_names: Sequence[str] = ("link",),
        devices=None,
    ) -> "Fabric":
        """Build a fabric over all (or the given) devices.

        With no shape, lays every device along the last axis — the common
        "one ring" topology used by the echo benchmarks.
        """
        devices = list(devices if devices is not None else jax.devices())
        if shape is None:
            shape = [1] * (len(axis_names) - 1) + [len(devices)]
        if math.prod(shape) != len(devices):
            raise ValueError(
                f"mesh shape {tuple(shape)} != device count {len(devices)}"
            )
        dev_array = np.asarray(devices).reshape(shape)
        return cls(Mesh(dev_array, tuple(axis_names)))

    # -- topology ---------------------------------------------------------
    @property
    def axis_names(self):
        return self.mesh.axis_names

    @property
    def size(self) -> int:
        return self.mesh.size

    def axis_size(self, axis: str) -> int:
        return self.mesh.shape[axis]

    # -- placement --------------------------------------------------------
    def sharding(self, *spec) -> NamedSharding:
        return NamedSharding(self.mesh, P(*spec))

    def replicated(self) -> NamedSharding:
        return NamedSharding(self.mesh, P())

    def put(self, x, *spec):
        return jax.device_put(x, self.sharding(*spec))

    # -- SPMD wrapping ----------------------------------------------------
    def spmd(self, fn, in_specs, out_specs, check_vma: bool = False):
        """shard_map over this fabric's mesh (the SPMD entry point)."""
        try:
            return shard_map(
                fn,
                mesh=self.mesh,
                in_specs=in_specs,
                out_specs=out_specs,
                check_vma=check_vma,
            )
        except TypeError:  # older jax spells the kwarg check_rep
            return shard_map(
                fn,
                mesh=self.mesh,
                in_specs=in_specs,
                out_specs=out_specs,
                check_rep=check_vma,
            )
