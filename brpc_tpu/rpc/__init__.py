from brpc_tpu.rpc import capture  # noqa: F401
from brpc_tpu.rpc import collective  # noqa: F401
from brpc_tpu.rpc import fault  # noqa: F401
from brpc_tpu.rpc import infer  # noqa: F401
from brpc_tpu.rpc import kv  # noqa: F401
from brpc_tpu.rpc import naming  # noqa: F401
from brpc_tpu.rpc import observe  # noqa: F401
from brpc_tpu.rpc import stream  # noqa: F401
from brpc_tpu.rpc import tuner  # noqa: F401
from brpc_tpu.rpc._lib import IOBuf, load_library, parse_endpoint  # noqa: F401
from brpc_tpu.rpc.batch import (  # noqa: F401
    Batch,
    Completion,
    ZeroCopyResponse,
)
from brpc_tpu.rpc.client import (  # noqa: F401
    Channel,
    ClusterChannel,
    DeadlineExpiredError,
    DrainingError,
    OverloadedError,
    RpcError,
    deadline_scope,
)
from brpc_tpu.rpc.flags import get_flag, set_flag  # noqa: F401
from brpc_tpu.rpc.infer import InferClient  # noqa: F401
from brpc_tpu.rpc.rma import RmaBuffer, kernel_supports  # noqa: F401
from brpc_tpu.rpc.server import Call, Server  # noqa: F401
from brpc_tpu.rpc.stream import (  # noqa: F401
    Stream,
    StreamChunkTooLargeError,
    StreamClosedError,
    StreamTimeoutError,
    open_stream,
)
