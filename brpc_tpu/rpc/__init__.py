from brpc_tpu.rpc._lib import IOBuf, load_library, parse_endpoint  # noqa: F401
