"""ctypes bindings to the native runtime (cpp/ → build/libtpurpc.so).

The C++ half is the host runtime (fibers, sockets, protocols — ARCHITECTURE.md);
these bindings are how the Python data plane hands payloads to it.  Builds the
library on demand with cmake if it isn't present.
"""

from __future__ import annotations

import ctypes
import os
import pathlib
import shutil
import subprocess
import threading
from concurrent.futures import ThreadPoolExecutor

_REPO = pathlib.Path(__file__).resolve().parent.parent.parent
_BUILD = _REPO / "build"
_LIB_PATH = _BUILD / "libtpurpc.so"
_lock = threading.Lock()
_lib = None

# Canonical mirror of the C++ runtime's error-code table (the
# `constexpr int kE* = NNNN;` constants in cpp/net/*.h).  The
# error-code-sync rule in tools/lint_trpc.py keeps the two in lockstep —
# a code added or renumbered on one side only fails tier-1 instead of
# silently mis-typing exceptions.  The typed-exception constructors in
# client.py / kv.py / naming.py / collective.py resolve codes through
# the runtime capi at call time; this table is the build-time contract.
ERROR_CODES = {
    "kELimit": 2004,
    "kEOverloaded": 2005,
    "kEDraining": 2006,
    "kEDeadlineExpired": 2007,
    "kEKvMiss": 2101,
    "kEKvStale": 2102,
    "kEKvExists": 2103,
    "kENamingStaleEpoch": 2111,
    "kENamingMiss": 2112,
    "kECollAbort": 2121,
    "kECollEpoch": 2122,
    "kECollMismatch": 2123,
}


def _newest_source_mtime() -> float:
    newest = 0.0
    for path in (_REPO / "cpp").rglob("*"):
        if path.suffix in (".cc", ".h", ".inc", ".S", ".txt"):
            newest = max(newest, path.stat().st_mtime)
    return newest


def _build_with_compiler() -> None:
    """cmake-less fallback: compile cpp/ straight with the system C++
    compiler (same flags as cpp/CMakeLists.txt) into build/obj/ and link
    libtpurpc.so.  Keeps the Python suite alive on minimal images that
    bake a toolchain but no cmake; the C++ unit BINARIES still need the
    cmake build (tests/test_cpp.py skips them instead)."""
    cxx = shutil.which("g++") or shutil.which("c++") or shutil.which("clang++")
    if cxx is None:
        raise FileNotFoundError(
            "neither cmake nor a C++ compiler available to build "
            "libtpurpc.so"
        )
    cpp = _REPO / "cpp"
    obj_dir = _BUILD / "obj"
    obj_dir.mkdir(parents=True, exist_ok=True)
    sources: list[pathlib.Path] = []
    for sub, pats in (
        ("base", ("*.cc",)),
        ("fiber", ("*.cc", "*.S")),
        ("stat", ("*.cc",)),
        ("net", ("*.cc",)),
        ("capi", ("*.cc",)),
    ):
        for pat in pats:
            sources.extend(sorted((cpp / sub).glob(pat)))
    flags = [
        "-std=c++20", "-fPIC", "-O2", "-g", "-Wall", "-Wextra",
        "-Wno-unused-parameter", "-fno-omit-frame-pointer", "-I", str(cpp),
    ]
    # A header edit invalidates every object (no dependency scanning here;
    # conservative and correct).
    newest_h = 0.0
    for pat in ("*.h", "*.inc"):
        for p in cpp.rglob(pat):
            newest_h = max(newest_h, p.stat().st_mtime)

    def run_tool(cmd: list[str]) -> None:
        # Surface the compiler diagnostics: a bare CalledProcessError with
        # swallowed stderr is undiagnosable from an import failure.
        try:
            subprocess.run(cmd, check=True, capture_output=True, text=True)
        except subprocess.CalledProcessError as e:
            raise RuntimeError(
                f"fallback build failed: {' '.join(cmd[:2])} ...\n"
                f"{e.stderr}"
            ) from e

    def compile_one(src: pathlib.Path) -> str:
        obj = obj_dir / (
            str(src.relative_to(cpp)).replace("/", "_") + ".o"
        )
        if (
            not obj.exists()
            or obj.stat().st_mtime < max(src.stat().st_mtime, newest_h)
        ):
            run_tool([cxx, *flags, "-c", str(src), "-o", str(obj)])
        return str(obj)

    with ThreadPoolExecutor(max_workers=os.cpu_count() or 4) as pool:
        objs = list(pool.map(compile_one, sources))
    run_tool(
        [cxx, "-shared", "-o", str(_LIB_PATH), *objs,
         "-lpthread", "-lrt", "-lz", "-ldl"]
    )


def ensure_built(all_targets: bool = False) -> None:
    """(Re)build the native library when missing or older than any cpp/
    source.  Shared by the bindings and the pytest fixture so there is one
    build recipe.  Without cmake, falls back to a direct compiler build of
    the library alone (all_targets callers must check for cmake/ctest
    themselves and skip)."""
    stale = (
        not _LIB_PATH.exists()
        or _LIB_PATH.stat().st_mtime < _newest_source_mtime()
    )
    if shutil.which("cmake") is None:
        if stale:
            _build_with_compiler()
        return
    if not stale and not all_targets:
        return
    subprocess.run(
        ["cmake", "-S", str(_REPO / "cpp"), "-B", str(_BUILD)],
        check=True,
        capture_output=True,
        text=True,
    )
    cmd = ["cmake", "--build", str(_BUILD), "-j", "2"]
    if not all_targets:
        cmd += ["--target", "tpurpc"]
    subprocess.run(cmd, check=True, capture_output=True, text=True)


_ensure_built = ensure_built


def ensure_bench_echo() -> pathlib.Path:
    """Build build/bench_echo (the C++ loopback echo benchmark) when
    missing or stale.  Links against libtpurpc.so so it works on
    cmake-less images too; bench.py and the perf smoke test share it."""
    ensure_built()
    exe = _BUILD / "bench_echo"
    src = _REPO / "cpp" / "tools" / "bench_echo.cc"
    if exe.exists() and exe.stat().st_mtime >= max(
        src.stat().st_mtime, _LIB_PATH.stat().st_mtime
    ):
        return exe
    cxx = shutil.which("g++") or shutil.which("c++") or shutil.which("clang++")
    if cxx is None:
        raise FileNotFoundError("no C++ compiler to build bench_echo")
    subprocess.run(
        [
            cxx, "-std=c++20", "-O2", "-g", "-fno-omit-frame-pointer",
            "-I", str(_REPO / "cpp"), str(src),
            "-L", str(_BUILD), f"-Wl,-rpath,{_BUILD}",
            "-ltpurpc", "-lpthread", "-o", str(exe),
        ],
        check=True,
        capture_output=True,
        text=True,
    )
    return exe


def load_library() -> ctypes.CDLL:
    global _lib
    with _lock:
        if _lib is None:
            _ensure_built()
            lib = ctypes.CDLL(str(_LIB_PATH))
            lib.trpc_iobuf_create.restype = ctypes.c_void_p
            lib.trpc_channel_create_ex.restype = ctypes.c_void_p
            lib.trpc_channel_create_ex.argtypes = [
                ctypes.c_char_p, ctypes.c_int64, ctypes.c_char_p,
                ctypes.c_int,
            ]
            lib.trpc_flag_set.argtypes = [ctypes.c_char_p, ctypes.c_char_p]
            lib.trpc_flag_get.argtypes = [
                ctypes.c_char_p, ctypes.c_char_p, ctypes.c_size_t,
            ]
            lib.trpc_iobuf_destroy.argtypes = [ctypes.c_void_p]
            lib.trpc_iobuf_append.argtypes = [
                ctypes.c_void_p,
                ctypes.c_char_p,
                ctypes.c_size_t,
            ]
            lib.trpc_iobuf_size.argtypes = [ctypes.c_void_p]
            lib.trpc_iobuf_size.restype = ctypes.c_size_t
            lib.trpc_iobuf_copy_to.argtypes = [
                ctypes.c_void_p,
                ctypes.c_void_p,
                ctypes.c_size_t,
                ctypes.c_size_t,
            ]
            lib.trpc_iobuf_copy_to.restype = ctypes.c_size_t
            lib.trpc_iobuf_cutn.argtypes = [
                ctypes.c_void_p,
                ctypes.c_void_p,
                ctypes.c_size_t,
            ]
            lib.trpc_iobuf_cutn.restype = ctypes.c_size_t
            lib.trpc_iobuf_pop_front.argtypes = [ctypes.c_void_p, ctypes.c_size_t]
            lib.trpc_iobuf_pop_front.restype = ctypes.c_size_t
            lib.trpc_iobuf_block_count.argtypes = [ctypes.c_void_p]
            lib.trpc_iobuf_block_count.restype = ctypes.c_size_t
            lib.trpc_iobuf_block_ptr.argtypes = [
                ctypes.c_void_p, ctypes.c_size_t,
            ]
            lib.trpc_iobuf_block_ptr.restype = ctypes.c_void_p
            lib.trpc_endpoint_parse.argtypes = [
                ctypes.c_char_p,
                ctypes.c_char_p,
                ctypes.c_size_t,
            ]
            lib.trpc_endpoint_parse.restype = ctypes.c_int
            # Device arena + zero-copy surface (capi/base_capi.cc).
            # Explicit marshalling for every pointer-crossing entry —
            # tools/lint_trpc.py's capi-gil rule gates this: a missing
            # restype silently truncates a 64-bit pointer/size_t.
            lib.trpc_arena_create.argtypes = [
                ctypes.c_uint32, ctypes.c_uint32, ctypes.c_int,
            ]
            lib.trpc_arena_create.restype = ctypes.c_void_p
            lib.trpc_arena_destroy.argtypes = [ctypes.c_void_p]
            lib.trpc_arena_destroy.restype = None
            lib.trpc_arena_alloc.argtypes = [
                ctypes.c_void_p, ctypes.POINTER(ctypes.c_void_p),
                ctypes.POINTER(ctypes.c_uint64),
            ]
            lib.trpc_arena_alloc.restype = ctypes.c_void_p
            lib.trpc_arena_release.argtypes = [
                ctypes.c_void_p, ctypes.c_void_p,
            ]
            lib.trpc_arena_release.restype = None
            lib.trpc_arena_block_size.argtypes = [ctypes.c_void_p]
            lib.trpc_arena_block_size.restype = ctypes.c_uint32
            lib.trpc_arena_blocks_in_use.argtypes = [ctypes.c_void_p]
            lib.trpc_arena_blocks_in_use.restype = ctypes.c_size_t
            lib.trpc_iobuf_append_block.argtypes = [
                ctypes.c_void_p, ctypes.c_void_p, ctypes.c_uint32,
            ]
            lib.trpc_iobuf_append_block.restype = ctypes.c_int
            lib.trpc_iobuf_append_user_data.argtypes = [
                ctypes.c_void_p, ctypes.c_void_p, ctypes.c_size_t,
                ctypes.c_void_p,  # deleter fn ptr (CFUNCTYPE or None)
                ctypes.c_void_p,
            ]
            lib.trpc_iobuf_append_user_data.restype = None
            lib.trpc_channel_call_buf.argtypes = [
                ctypes.c_void_p, ctypes.c_char_p, ctypes.c_void_p,
                ctypes.c_void_p, ctypes.c_int64,
                ctypes.c_char_p, ctypes.c_size_t,
            ]
            lib.trpc_channel_call_buf.restype = ctypes.c_int
            # One-sided RMA regions + kernel probe (capi/rpc_capi.cc;
            # net/rma.h, base/proc.h).
            lib.trpc_rma_alloc.argtypes = [
                ctypes.c_size_t, ctypes.POINTER(ctypes.c_uint64),
            ]
            lib.trpc_rma_alloc.restype = ctypes.c_void_p
            lib.trpc_rma_free.argtypes = [ctypes.c_void_p]
            lib.trpc_rma_free.restype = None
            lib.trpc_rma_reg.argtypes = [ctypes.c_void_p, ctypes.c_size_t]
            lib.trpc_rma_reg.restype = ctypes.c_uint64
            lib.trpc_rma_unreg.argtypes = [ctypes.c_uint64]
            lib.trpc_rma_unreg.restype = ctypes.c_int
            lib.trpc_rma_region_count.argtypes = []
            lib.trpc_rma_region_count.restype = ctypes.c_size_t
            lib.trpc_kernel_supports.argtypes = [ctypes.c_char_p]
            lib.trpc_kernel_supports.restype = ctypes.c_int
            # Paged KV-block registry (capi/kv_capi.cc; net/kvstore.h).
            lib.trpc_server_enable_kv_registry.argtypes = [ctypes.c_void_p]
            lib.trpc_server_enable_kv_registry.restype = ctypes.c_int
            lib.trpc_server_enable_kv_store.argtypes = [ctypes.c_void_p]
            lib.trpc_server_enable_kv_store.restype = ctypes.c_int
            lib.trpc_kv_publish.argtypes = [
                ctypes.c_void_p, ctypes.c_size_t, ctypes.c_uint64,
                ctypes.c_int64, ctypes.POINTER(ctypes.c_uint64),
                ctypes.POINTER(ctypes.c_uint64),
                ctypes.POINTER(ctypes.c_uint64),
            ]
            lib.trpc_kv_publish.restype = ctypes.c_int
            lib.trpc_kv_publish_ex.argtypes = [
                ctypes.c_void_p, ctypes.c_size_t, ctypes.c_uint64,
                ctypes.c_int64, ctypes.c_uint64,
                ctypes.POINTER(ctypes.c_uint64),
                ctypes.POINTER(ctypes.c_uint64),
                ctypes.POINTER(ctypes.c_uint64),
            ]
            lib.trpc_kv_publish_ex.restype = ctypes.c_int
            lib.trpc_kv_withdraw.argtypes = [ctypes.c_uint64]
            lib.trpc_kv_withdraw.restype = ctypes.c_int
            lib.trpc_kv_renew.argtypes = [ctypes.c_uint64, ctypes.c_int64]
            lib.trpc_kv_renew.restype = ctypes.c_int
            lib.trpc_kv_store_count.argtypes = []
            lib.trpc_kv_store_count.restype = ctypes.c_size_t
            lib.trpc_kv_store_bytes_used.argtypes = []
            lib.trpc_kv_store_bytes_used.restype = ctypes.c_uint64
            lib.trpc_kv_registry_count.argtypes = []
            lib.trpc_kv_registry_count.restype = ctypes.c_size_t
            lib.trpc_kv_codes.argtypes = [
                ctypes.POINTER(ctypes.c_int), ctypes.POINTER(ctypes.c_int),
                ctypes.POINTER(ctypes.c_int),
            ]
            lib.trpc_kv_codes.restype = None
            lib.trpc_kv_reset.argtypes = []
            lib.trpc_kv_reset.restype = None
            # Content-addressed prefix cache (capi/kv_capi.cc; ISSUE 17).
            lib.trpc_kv_content_hash.argtypes = [
                ctypes.c_void_p, ctypes.c_size_t,
                ctypes.POINTER(ctypes.c_uint64), ctypes.c_size_t,
                ctypes.POINTER(ctypes.c_uint64),
                ctypes.POINTER(ctypes.c_uint64),
            ]
            lib.trpc_kv_content_hash.restype = None
            lib.trpc_kv_prefix_chain.argtypes = [
                ctypes.POINTER(ctypes.c_uint64), ctypes.c_size_t,
                ctypes.c_int64, ctypes.POINTER(ctypes.c_uint64),
                ctypes.c_size_t,
            ]
            lib.trpc_kv_prefix_chain.restype = ctypes.c_size_t
            lib.trpc_kv_prefix_publish.argtypes = [
                ctypes.c_uint64, ctypes.c_uint64, ctypes.c_uint32,
                ctypes.c_void_p, ctypes.c_size_t,
                ctypes.POINTER(ctypes.c_uint64), ctypes.c_size_t,
                ctypes.c_int64, ctypes.c_uint64,
                ctypes.POINTER(ctypes.c_uint64),
                ctypes.POINTER(ctypes.c_uint64),
                ctypes.POINTER(ctypes.c_uint64),
                ctypes.POINTER(ctypes.c_uint64),
                ctypes.POINTER(ctypes.c_uint64),
            ]
            lib.trpc_kv_prefix_publish.restype = ctypes.c_int
            lib.trpc_kv_prefix_withdraw.argtypes = [
                ctypes.c_uint64, ctypes.c_uint64,
            ]
            lib.trpc_kv_prefix_withdraw.restype = ctypes.c_int
            lib.trpc_kv_prefix_store_count.argtypes = []
            lib.trpc_kv_prefix_store_count.restype = ctypes.c_size_t
            lib.trpc_kv_prefix_hot_bytes.argtypes = []
            lib.trpc_kv_prefix_hot_bytes.restype = ctypes.c_uint64
            lib.trpc_kv_prefix_cold_bytes.argtypes = []
            lib.trpc_kv_prefix_cold_bytes.restype = ctypes.c_uint64
            lib.trpc_kv_prefix_registry_count.argtypes = []
            lib.trpc_kv_prefix_registry_count.restype = ctypes.c_size_t
            lib.trpc_kv_prefix_registry_replicas.argtypes = []
            lib.trpc_kv_prefix_registry_replicas.restype = ctypes.c_size_t
            lib.trpc_kv_prefix_counters.argtypes = [
                ctypes.POINTER(ctypes.c_uint64),
                ctypes.POINTER(ctypes.c_uint64),
                ctypes.POINTER(ctypes.c_uint64),
                ctypes.POINTER(ctypes.c_uint64),
                ctypes.POINTER(ctypes.c_uint64),
            ]
            lib.trpc_kv_prefix_counters.restype = None
            # Cluster control plane (capi/naming_capi.cc; net/naming.h):
            # naming registry + graceful drain / hot-restart handoff.
            lib.trpc_server_enable_naming.argtypes = [ctypes.c_void_p]
            lib.trpc_server_enable_naming.restype = ctypes.c_int
            lib.trpc_server_announce.argtypes = [
                ctypes.c_void_p, ctypes.c_char_p, ctypes.c_char_p,
                ctypes.c_char_p, ctypes.c_int,
            ]
            lib.trpc_server_announce.restype = ctypes.c_int
            lib.trpc_server_drain.argtypes = [
                ctypes.c_void_p, ctypes.c_int64, ctypes.c_char_p,
            ]
            lib.trpc_server_drain.restype = ctypes.c_int
            lib.trpc_server_start_handoff.argtypes = [
                ctypes.c_void_p, ctypes.c_char_p, ctypes.c_int64,
            ]
            lib.trpc_server_start_handoff.restype = ctypes.c_int
            lib.trpc_server_draining.argtypes = [ctypes.c_void_p]
            lib.trpc_server_draining.restype = ctypes.c_int
            lib.trpc_draining_code.argtypes = []
            lib.trpc_draining_code.restype = ctypes.c_int
            lib.trpc_naming_codes.argtypes = [
                ctypes.POINTER(ctypes.c_int), ctypes.POINTER(ctypes.c_int),
            ]
            lib.trpc_naming_codes.restype = None
            lib.trpc_naming_member_count.argtypes = [ctypes.c_char_p]
            lib.trpc_naming_member_count.restype = ctypes.c_size_t
            lib.trpc_naming_reset.argtypes = []
            lib.trpc_naming_reset.restype = None
            lib.trpc_kv_withdraw_all.argtypes = []
            lib.trpc_kv_withdraw_all.restype = ctypes.c_size_t
            lib.trpc_rma_spans_in_use.argtypes = []
            lib.trpc_rma_spans_in_use.restype = ctypes.c_size_t
            # Collective transfer schedules (capi/coll_capi.cc;
            # net/collective.h): group put plans over the RMA fabric.
            lib.trpc_server_enable_collective.argtypes = [ctypes.c_void_p]
            lib.trpc_server_enable_collective.restype = ctypes.c_int
            lib.trpc_coll_group_create.argtypes = [
                ctypes.c_char_p, ctypes.c_uint32, ctypes.c_int64,
                ctypes.c_int,
            ]
            lib.trpc_coll_group_create.restype = ctypes.c_void_p
            lib.trpc_coll_group_create_naming.argtypes = [
                ctypes.c_char_p, ctypes.c_char_p, ctypes.c_int64,
                ctypes.c_int,
            ]
            lib.trpc_coll_group_create_naming.restype = ctypes.c_void_p
            lib.trpc_coll_group_destroy.argtypes = [ctypes.c_void_p]
            lib.trpc_coll_group_destroy.restype = None
            lib.trpc_coll_group_rank.argtypes = [ctypes.c_void_p]
            lib.trpc_coll_group_rank.restype = ctypes.c_uint32
            lib.trpc_coll_group_size.argtypes = [ctypes.c_void_p]
            lib.trpc_coll_group_size.restype = ctypes.c_uint32
            lib.trpc_coll_group_version.argtypes = [ctypes.c_void_p]
            lib.trpc_coll_group_version.restype = ctypes.c_uint64
            lib.trpc_coll_run.argtypes = [
                ctypes.c_void_p, ctypes.c_int, ctypes.c_void_p,
                ctypes.c_uint64, ctypes.c_void_p, ctypes.c_uint64,
                ctypes.c_uint64, ctypes.c_uint64,
            ]
            lib.trpc_coll_run.restype = ctypes.c_int
            # Overlap-aware path: trpc_coll_run + a readiness-map handle
            # over the caller's send buffer (ISSUE 18).
            lib.trpc_coll_run_ready.argtypes = [
                ctypes.c_void_p, ctypes.c_int, ctypes.c_void_p,
                ctypes.c_uint64, ctypes.c_void_p, ctypes.c_uint64,
                ctypes.c_uint64, ctypes.c_uint64, ctypes.c_uint64,
            ]
            lib.trpc_coll_run_ready.restype = ctypes.c_int
            lib.trpc_coll_ready_create.argtypes = [
                ctypes.c_void_p, ctypes.c_uint64, ctypes.c_uint64,
            ]
            lib.trpc_coll_ready_create.restype = ctypes.c_uint64
            lib.trpc_coll_ready_stamp.argtypes = [
                ctypes.c_uint64, ctypes.c_uint64, ctypes.c_uint64,
            ]
            lib.trpc_coll_ready_stamp.restype = ctypes.c_int
            lib.trpc_coll_ready_destroy.argtypes = [ctypes.c_uint64]
            lib.trpc_coll_ready_destroy.restype = None
            lib.trpc_coll_ready_maps.argtypes = []
            lib.trpc_coll_ready_maps.restype = ctypes.c_size_t
            lib.trpc_coll_reshard_run.argtypes = [
                ctypes.c_void_p, ctypes.c_char_p, ctypes.c_uint32,
                ctypes.c_uint32, ctypes.c_uint64, ctypes.c_void_p,
                ctypes.c_uint64, ctypes.c_void_p, ctypes.c_uint64,
                ctypes.c_uint64,
            ]
            lib.trpc_coll_reshard_run.restype = ctypes.c_int
            lib.trpc_coll_reshard_plan.argtypes = [
                ctypes.c_char_p, ctypes.c_uint32, ctypes.c_uint32,
                ctypes.c_uint64, ctypes.c_uint32,
                ctypes.POINTER(ctypes.c_uint64),
                ctypes.POINTER(ctypes.c_uint64),
                ctypes.POINTER(ctypes.c_uint64),
                ctypes.POINTER(ctypes.c_uint32),
            ]
            lib.trpc_coll_reshard_plan.restype = ctypes.c_int
            lib.trpc_coll_codes.argtypes = [
                ctypes.POINTER(ctypes.c_int), ctypes.POINTER(ctypes.c_int),
                ctypes.POINTER(ctypes.c_int),
            ]
            lib.trpc_coll_codes.restype = None
            lib.trpc_coll_sessions.argtypes = []
            lib.trpc_coll_sessions.restype = ctypes.c_size_t
            lib.trpc_rma_scavenge.argtypes = []
            lib.trpc_rma_scavenge.restype = ctypes.c_size_t
            # RPC surface (capi/rpc_capi.cc).
            lib.trpc_server_create.restype = ctypes.c_void_p
            lib.trpc_server_destroy.argtypes = [ctypes.c_void_p]
            lib.trpc_server_register.argtypes = [
                ctypes.c_void_p, ctypes.c_char_p, ctypes.c_void_p,
                ctypes.c_void_p,
            ]
            lib.trpc_server_register.restype = ctypes.c_int
            lib.trpc_call_respond.argtypes = [
                ctypes.c_void_p, ctypes.c_char_p, ctypes.c_size_t,
                ctypes.c_int, ctypes.c_char_p,
            ]
            lib.trpc_call_respond.restype = ctypes.c_int
            lib.trpc_server_start.argtypes = [ctypes.c_void_p, ctypes.c_int]
            lib.trpc_server_start.restype = ctypes.c_int
            lib.trpc_server_port.argtypes = [ctypes.c_void_p]
            lib.trpc_server_port.restype = ctypes.c_int
            lib.trpc_server_stop.argtypes = [ctypes.c_void_p]
            lib.trpc_channel_create.argtypes = [ctypes.c_char_p, ctypes.c_int64]
            lib.trpc_channel_create.restype = ctypes.c_void_p
            lib.trpc_channel_create_shm.argtypes = [
                ctypes.c_char_p, ctypes.c_int64,
            ]
            lib.trpc_channel_create_shm.restype = ctypes.c_void_p
            lib.trpc_channel_destroy.argtypes = [ctypes.c_void_p]
            lib.trpc_channel_transport.argtypes = [
                ctypes.c_void_p, ctypes.c_char_p, ctypes.c_size_t,
            ]
            lib.trpc_channel_call.argtypes = [
                ctypes.c_void_p, ctypes.c_char_p, ctypes.c_char_p,
                ctypes.c_size_t, ctypes.c_void_p, ctypes.c_int64,
                ctypes.c_char_p, ctypes.c_size_t,
            ]
            lib.trpc_channel_call.restype = ctypes.c_int
            lib.trpc_cluster_create.argtypes = [
                ctypes.c_char_p, ctypes.c_char_p, ctypes.c_int64, ctypes.c_int,
            ]
            lib.trpc_cluster_create.restype = ctypes.c_void_p
            lib.trpc_cluster_create_ex.argtypes = [
                ctypes.c_char_p, ctypes.c_char_p, ctypes.c_int64,
                ctypes.c_int, ctypes.c_int64, ctypes.c_char_p,
                ctypes.c_int64, ctypes.c_int64,
            ]
            lib.trpc_cluster_create_ex.restype = ctypes.c_void_p
            # Fault injection (cpp/net/fault.h).
            lib.trpc_fault_set.argtypes = [ctypes.c_char_p]
            lib.trpc_fault_set.restype = ctypes.c_int
            lib.trpc_fault_get.argtypes = [ctypes.c_char_p, ctypes.c_size_t]
            lib.trpc_fault_get.restype = ctypes.c_int
            lib.trpc_fault_log.argtypes = [ctypes.c_char_p, ctypes.c_size_t]
            lib.trpc_fault_log.restype = ctypes.c_size_t
            lib.trpc_fault_reset.argtypes = []
            lib.trpc_fault_injected.restype = ctypes.c_uint64
            lib.trpc_server_fault_set.argtypes = [
                ctypes.c_void_p, ctypes.c_char_p,
            ]
            lib.trpc_server_fault_set.restype = ctypes.c_int
            # QoS subsystem (capi/qos_capi.cc; cpp/net/qos.h).
            lib.trpc_server_set_qos.argtypes = [
                ctypes.c_void_p, ctypes.c_char_p,
            ]
            lib.trpc_server_set_qos.restype = ctypes.c_int
            lib.trpc_server_set_reuseport.argtypes = [
                ctypes.c_void_p, ctypes.c_int,
            ]
            lib.trpc_server_set_reuseport.restype = ctypes.c_int
            lib.trpc_server_accept_counts.argtypes = [
                ctypes.c_void_p, ctypes.POINTER(ctypes.c_uint64),
                ctypes.c_int,
            ]
            lib.trpc_server_accept_counts.restype = ctypes.c_int
            lib.trpc_channel_set_qos.argtypes = [
                ctypes.c_void_p, ctypes.c_char_p, ctypes.c_int,
            ]
            lib.trpc_cluster_set_qos.argtypes = [
                ctypes.c_void_p, ctypes.c_char_p, ctypes.c_int,
            ]
            lib.trpc_call_qos.argtypes = [
                ctypes.c_void_p, ctypes.c_char_p, ctypes.c_size_t,
            ]
            lib.trpc_call_qos.restype = ctypes.c_int
            lib.trpc_qos_overloaded_code.argtypes = []
            lib.trpc_qos_overloaded_code.restype = ctypes.c_int
            # Deadline & cancellation plane (capi/deadline_capi.cc;
            # cpp/net/deadline.h).
            lib.trpc_deadline_expired_code.argtypes = []
            lib.trpc_deadline_expired_code.restype = ctypes.c_int
            lib.trpc_call_remaining_us.argtypes = [ctypes.c_void_p]
            lib.trpc_call_remaining_us.restype = ctypes.c_int64
            lib.trpc_call_cancelled.argtypes = [ctypes.c_void_p]
            lib.trpc_call_cancelled.restype = ctypes.c_int
            lib.trpc_deadline_ambient_set.argtypes = [ctypes.c_int64]
            lib.trpc_deadline_ambient_set.restype = None
            lib.trpc_deadline_ambient_remaining.argtypes = []
            lib.trpc_deadline_ambient_remaining.restype = ctypes.c_int64
            lib.trpc_deadline_ambient_clear.argtypes = []
            lib.trpc_deadline_ambient_clear.restype = None
            lib.trpc_cancel_registered.argtypes = []
            lib.trpc_cancel_registered.restype = ctypes.c_size_t
            lib.trpc_deadline_ensure_registered.argtypes = []
            lib.trpc_deadline_ensure_registered.restype = None
            lib.trpc_qos_lane_depth.argtypes = [ctypes.c_int]
            lib.trpc_qos_lane_depth.restype = ctypes.c_int64
            # Batched async pipeline (capi/batch_capi.cc).
            lib.trpc_batch_create.argtypes = [ctypes.c_void_p, ctypes.c_int]
            lib.trpc_batch_create.restype = ctypes.c_void_p
            lib.trpc_batch_submit.argtypes = [
                ctypes.c_void_p, ctypes.c_char_p,
                ctypes.POINTER(ctypes.c_void_p),
                ctypes.POINTER(ctypes.c_size_t),
                ctypes.POINTER(ctypes.c_void_p),
                ctypes.POINTER(ctypes.c_size_t),
                ctypes.c_size_t, ctypes.c_int64,
                ctypes.c_void_p,  # deleter fn ptr (CFUNCTYPE or None)
                ctypes.POINTER(ctypes.c_void_p),
                ctypes.POINTER(ctypes.c_uint64),
            ]
            lib.trpc_batch_submit.restype = ctypes.c_size_t
            lib.trpc_batch_poll.argtypes = [
                ctypes.c_void_p, ctypes.c_void_p, ctypes.c_size_t,
                ctypes.c_int64,
            ]
            lib.trpc_batch_poll.restype = ctypes.c_size_t
            lib.trpc_batch_cancel.argtypes = [
                ctypes.c_void_p, ctypes.c_uint64,
            ]
            lib.trpc_batch_cancel.restype = ctypes.c_int
            lib.trpc_batch_outstanding.argtypes = [ctypes.c_void_p]
            lib.trpc_batch_outstanding.restype = ctypes.c_size_t
            lib.trpc_batch_inflight.argtypes = [ctypes.c_void_p]
            lib.trpc_batch_inflight.restype = ctypes.c_size_t
            lib.trpc_batch_quiesce.argtypes = [ctypes.c_void_p]
            lib.trpc_batch_destroy.argtypes = [ctypes.c_void_p]
            lib.trpc_server_register_echo.argtypes = [
                ctypes.c_void_p, ctypes.c_char_p,
            ]
            lib.trpc_server_register_echo.restype = ctypes.c_int
            # Observability plane (capi/observe_capi.cc).
            lib.trpc_vars_dump.argtypes = [
                ctypes.c_int, ctypes.c_char_p, ctypes.c_size_t,
            ]
            lib.trpc_vars_dump.restype = ctypes.c_size_t
            lib.trpc_var_read.argtypes = [
                ctypes.c_char_p, ctypes.c_char_p, ctypes.c_size_t,
            ]
            lib.trpc_var_read.restype = ctypes.c_int
            lib.trpc_latency_read.argtypes = [
                ctypes.c_char_p, ctypes.POINTER(ctypes.c_double),
            ]
            lib.trpc_latency_read.restype = ctypes.c_int
            lib.trpc_var_exists.argtypes = [ctypes.c_char_p]
            lib.trpc_var_exists.restype = ctypes.c_int
            lib.trpc_rpcz_dump.argtypes = [
                ctypes.c_size_t, ctypes.c_uint64, ctypes.c_int,
                ctypes.c_char_p, ctypes.c_size_t,
            ]
            lib.trpc_rpcz_dump.restype = ctypes.c_size_t
            # Timeline flight recorder (ISSUE 9).
            lib.trpc_timeline_dump.argtypes = [
                ctypes.c_int, ctypes.c_size_t, ctypes.c_char_p,
                ctypes.c_size_t,
            ]
            lib.trpc_timeline_dump.restype = ctypes.c_size_t
            lib.trpc_timeline_enabled.restype = ctypes.c_int
            lib.trpc_timeline_reset.restype = None
            # SLO engine + fleet observability (capi/slo_capi.cc;
            # stat/slo.h, net/naming.h fleet publication).
            lib.trpc_server_set_slo.argtypes = [
                ctypes.c_void_p, ctypes.c_char_p,
            ]
            lib.trpc_server_set_slo.restype = ctypes.c_int
            lib.trpc_slo_dump.argtypes = [
                ctypes.c_void_p, ctypes.c_char_p, ctypes.c_size_t,
            ]
            lib.trpc_slo_dump.restype = ctypes.c_size_t
            lib.trpc_fleet_blob.argtypes = [
                ctypes.c_void_p, ctypes.c_char_p, ctypes.c_size_t,
            ]
            lib.trpc_fleet_blob.restype = ctypes.c_size_t
            lib.trpc_fleet_dump.argtypes = [
                ctypes.c_char_p, ctypes.c_char_p, ctypes.c_size_t,
            ]
            lib.trpc_fleet_dump.restype = ctypes.c_size_t
            lib.trpc_slo_enabled.restype = ctypes.c_int
            lib.trpc_slo_breach_total.restype = ctypes.c_uint64
            # Self-tuning controller + flag introspection
            # (capi/tuner_capi.cc; stat/tuner.h).
            lib.trpc_flags_dump.argtypes = [
                ctypes.c_char_p, ctypes.c_size_t,
            ]
            lib.trpc_flags_dump.restype = ctypes.c_size_t
            lib.trpc_tuner_enabled.restype = ctypes.c_int
            lib.trpc_tuner_dump.argtypes = [
                ctypes.c_size_t, ctypes.c_char_p, ctypes.c_size_t,
            ]
            lib.trpc_tuner_dump.restype = ctypes.c_size_t
            lib.trpc_tuner_counters.argtypes = [
                ctypes.POINTER(ctypes.c_uint64),
                ctypes.POINTER(ctypes.c_uint64),
                ctypes.POINTER(ctypes.c_uint64),
                ctypes.POINTER(ctypes.c_uint64),
            ]
            lib.trpc_tuner_counters.restype = None
            lib.trpc_server_enable_tuner.argtypes = [ctypes.c_void_p]
            lib.trpc_server_enable_tuner.restype = ctypes.c_int
            lib.trpc_tuner_reset.argtypes = []
            lib.trpc_tuner_reset.restype = None
            # Traffic capture (capi/capture_capi.cc; stat/capture.h).
            lib.trpc_capture_enabled.restype = ctypes.c_int
            lib.trpc_capture_dump.argtypes = [
                ctypes.c_size_t, ctypes.c_char_p, ctypes.c_size_t,
            ]
            lib.trpc_capture_dump.restype = ctypes.c_size_t
            lib.trpc_capture_dump_file.argtypes = [ctypes.c_char_p]
            lib.trpc_capture_dump_file.restype = ctypes.c_longlong
            lib.trpc_capture_counters.argtypes = [
                ctypes.POINTER(ctypes.c_uint64),
                ctypes.POINTER(ctypes.c_uint64),
                ctypes.POINTER(ctypes.c_uint64),
                ctypes.POINTER(ctypes.c_uint64),
            ]
            lib.trpc_capture_counters.restype = None
            lib.trpc_capture_reset.argtypes = []
            lib.trpc_capture_reset.restype = None
            lib.trpc_trace_get.argtypes = [
                ctypes.POINTER(ctypes.c_uint64),
                ctypes.POINTER(ctypes.c_uint64),
            ]
            lib.trpc_trace_set.argtypes = [
                ctypes.c_uint64, ctypes.c_uint64,
            ]
            lib.trpc_trace_clear.argtypes = []
            lib.trpc_trace_new_id.restype = ctypes.c_uint64
            lib.trpc_span_start.argtypes = [ctypes.c_char_p, ctypes.c_int]
            lib.trpc_span_start.restype = ctypes.c_void_p
            lib.trpc_span_annotate.argtypes = [
                ctypes.c_void_p, ctypes.c_char_p,
            ]
            lib.trpc_span_ids.argtypes = [
                ctypes.c_void_p, ctypes.POINTER(ctypes.c_uint64),
                ctypes.POINTER(ctypes.c_uint64),
            ]
            lib.trpc_span_end.argtypes = [ctypes.c_void_p, ctypes.c_int]
            lib.trpc_latency_create.argtypes = [
                ctypes.c_char_p, ctypes.c_char_p,
            ]
            lib.trpc_latency_create.restype = ctypes.c_void_p
            lib.trpc_latency_record.argtypes = [
                ctypes.c_void_p, ctypes.c_int64,
            ]
            lib.trpc_latency_destroy.argtypes = [ctypes.c_void_p]
            lib.trpc_gauge_create.argtypes = [
                ctypes.c_char_p, ctypes.c_char_p,
            ]
            lib.trpc_gauge_create.restype = ctypes.c_void_p
            lib.trpc_gauge_set.argtypes = [ctypes.c_void_p, ctypes.c_int64]
            lib.trpc_gauge_add.argtypes = [ctypes.c_void_p, ctypes.c_int64]
            lib.trpc_gauge_add.restype = ctypes.c_int64
            lib.trpc_gauge_destroy.argtypes = [ctypes.c_void_p]
            lib.trpc_cluster_destroy.argtypes = [ctypes.c_void_p]
            lib.trpc_cluster_call.argtypes = [
                ctypes.c_void_p, ctypes.c_char_p, ctypes.c_char_p,
                ctypes.c_size_t, ctypes.c_void_p, ctypes.c_uint64,
                ctypes.c_char_p, ctypes.c_size_t,
            ]
            lib.trpc_cluster_call.restype = ctypes.c_int
            # Cache-aware routing (capi/rpc_capi.cc; net/lb_hint.h).
            lib.trpc_cluster_call_hinted.argtypes = [
                ctypes.c_void_p, ctypes.c_char_p, ctypes.c_char_p,
                ctypes.c_size_t, ctypes.c_void_p, ctypes.c_uint64,
                ctypes.c_char_p, ctypes.c_char_p, ctypes.c_size_t,
            ]
            lib.trpc_cluster_call_hinted.restype = ctypes.c_int
            lib.trpc_lb_hint_counters.argtypes = [
                ctypes.POINTER(ctypes.c_uint64),
                ctypes.POINTER(ctypes.c_uint64),
                ctypes.POINTER(ctypes.c_uint64),
            ]
            lib.trpc_lb_hint_counters.restype = None
            # Streaming plane (capi/stream_capi.cc; net/stream.h; ISSUE 20).
            lib.trpc_stream_open.argtypes = [
                ctypes.c_void_p, ctypes.c_char_p, ctypes.c_char_p,
                ctypes.c_size_t, ctypes.c_int64, ctypes.c_int64,
                ctypes.c_char_p, ctypes.c_int, ctypes.c_void_p,
                ctypes.POINTER(ctypes.c_int), ctypes.c_char_p,
                ctypes.c_size_t,
            ]
            lib.trpc_stream_open.restype = ctypes.c_void_p
            lib.trpc_call_stream_accept.argtypes = [
                ctypes.c_void_p, ctypes.c_int64,
            ]
            lib.trpc_call_stream_accept.restype = ctypes.c_void_p
            lib.trpc_stream_read.argtypes = [
                ctypes.c_void_p, ctypes.c_char_p, ctypes.c_size_t,
                ctypes.c_int64,
            ]
            lib.trpc_stream_read.restype = ctypes.c_long
            lib.trpc_stream_next_len.argtypes = [ctypes.c_void_p]
            lib.trpc_stream_next_len.restype = ctypes.c_long
            lib.trpc_stream_write.argtypes = [
                ctypes.c_void_p, ctypes.c_char_p, ctypes.c_size_t,
            ]
            lib.trpc_stream_write.restype = ctypes.c_int
            lib.trpc_stream_close.argtypes = [ctypes.c_void_p]
            lib.trpc_stream_close.restype = ctypes.c_int
            lib.trpc_stream_destroy.argtypes = [ctypes.c_void_p]
            lib.trpc_stream_destroy.restype = None
            lib.trpc_stream_id.argtypes = [ctypes.c_void_p]
            lib.trpc_stream_id.restype = ctypes.c_uint64
            lib.trpc_stream_pending.argtypes = [ctypes.c_void_p]
            lib.trpc_stream_pending.restype = ctypes.c_size_t
            # Streamed-inference front door (capi/infer_capi.cc;
            # net/infer.h; ISSUE 20).
            lib.trpc_server_enable_infer.argtypes = [
                ctypes.c_void_p, ctypes.c_int, ctypes.c_char_p,
                ctypes.c_char_p,
            ]
            lib.trpc_server_enable_infer.restype = ctypes.c_void_p
            lib.trpc_infer_stop.argtypes = [ctypes.c_void_p]
            lib.trpc_infer_stop.restype = None
            lib.trpc_infer_dump.argtypes = [
                ctypes.c_void_p, ctypes.c_char_p, ctypes.c_size_t,
            ]
            lib.trpc_infer_dump.restype = ctypes.c_size_t
            lib.trpc_infer_streams_live.argtypes = [ctypes.c_void_p]
            lib.trpc_infer_streams_live.restype = ctypes.c_longlong
            lib.trpc_infer_streams_peak.argtypes = [ctypes.c_void_p]
            lib.trpc_infer_streams_peak.restype = ctypes.c_longlong
            _lib = lib
    return _lib


class IOBuf:
    """Python view of trpc::IOBuf (zero-copy chained buffer)."""

    def __init__(self, data: bytes | None = None):
        self._lib = load_library()
        self._ptr = self._lib.trpc_iobuf_create()
        if data:
            self.append(data)

    def __del__(self):
        ptr, self._ptr = getattr(self, "_ptr", None), None
        if ptr:
            self._lib.trpc_iobuf_destroy(ptr)

    def __len__(self) -> int:
        return self._lib.trpc_iobuf_size(self._ptr)

    def append(self, data: bytes) -> None:
        self._lib.trpc_iobuf_append(self._ptr, data, len(data))

    def to_bytes(self) -> bytes:
        n = len(self)
        out = ctypes.create_string_buffer(n)
        got = self._lib.trpc_iobuf_copy_to(self._ptr, out, n, 0)
        return out.raw[:got]

    def cutn(self, n: int) -> "IOBuf":
        out = IOBuf()
        self._lib.trpc_iobuf_cutn(self._ptr, out._ptr, n)
        return out

    def pop_front(self, n: int) -> int:
        return self._lib.trpc_iobuf_pop_front(self._ptr, n)

    @property
    def block_count(self) -> int:
        return self._lib.trpc_iobuf_block_count(self._ptr)


def parse_endpoint(addr: str) -> str:
    """Normalize 'host:port[/device]' via the native EndPoint parser."""
    lib = load_library()
    out = ctypes.create_string_buffer(64)
    if lib.trpc_endpoint_parse(addr.encode(), out, 64) != 0:
        raise ValueError(f"bad endpoint: {addr!r}")
    return out.value.decode()
