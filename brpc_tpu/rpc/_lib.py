"""ctypes bindings to the native runtime (cpp/ → build/libtpurpc.so).

The C++ half is the host runtime (fibers, sockets, protocols — ARCHITECTURE.md);
these bindings are how the Python data plane hands payloads to it.  Builds the
library on demand with cmake if it isn't present.
"""

from __future__ import annotations

import ctypes
import pathlib
import subprocess
import threading

_REPO = pathlib.Path(__file__).resolve().parent.parent.parent
_BUILD = _REPO / "build"
_LIB_PATH = _BUILD / "libtpurpc.so"
_lock = threading.Lock()
_lib = None


def _newest_source_mtime() -> float:
    newest = 0.0
    for path in (_REPO / "cpp").rglob("*"):
        if path.suffix in (".cc", ".h", ".S", ".txt"):
            newest = max(newest, path.stat().st_mtime)
    return newest


def ensure_built(all_targets: bool = False) -> None:
    """(Re)build the native library when missing or older than any cpp/
    source.  Shared by the bindings and the pytest fixture so there is one
    build recipe."""
    stale = (
        not _LIB_PATH.exists()
        or _LIB_PATH.stat().st_mtime < _newest_source_mtime()
    )
    if not stale and not all_targets:
        return
    subprocess.run(
        ["cmake", "-S", str(_REPO / "cpp"), "-B", str(_BUILD)],
        check=True,
        capture_output=True,
        text=True,
    )
    cmd = ["cmake", "--build", str(_BUILD), "-j", "2"]
    if not all_targets:
        cmd += ["--target", "tpurpc"]
    subprocess.run(cmd, check=True, capture_output=True, text=True)


_ensure_built = ensure_built


def load_library() -> ctypes.CDLL:
    global _lib
    with _lock:
        if _lib is None:
            _ensure_built()
            lib = ctypes.CDLL(str(_LIB_PATH))
            lib.trpc_iobuf_create.restype = ctypes.c_void_p
            lib.trpc_channel_create_ex.restype = ctypes.c_void_p
            lib.trpc_channel_create_ex.argtypes = [
                ctypes.c_char_p, ctypes.c_int64, ctypes.c_char_p,
                ctypes.c_int,
            ]
            lib.trpc_flag_set.argtypes = [ctypes.c_char_p, ctypes.c_char_p]
            lib.trpc_flag_get.argtypes = [
                ctypes.c_char_p, ctypes.c_char_p, ctypes.c_size_t,
            ]
            lib.trpc_iobuf_destroy.argtypes = [ctypes.c_void_p]
            lib.trpc_iobuf_append.argtypes = [
                ctypes.c_void_p,
                ctypes.c_char_p,
                ctypes.c_size_t,
            ]
            lib.trpc_iobuf_size.argtypes = [ctypes.c_void_p]
            lib.trpc_iobuf_size.restype = ctypes.c_size_t
            lib.trpc_iobuf_copy_to.argtypes = [
                ctypes.c_void_p,
                ctypes.c_void_p,
                ctypes.c_size_t,
                ctypes.c_size_t,
            ]
            lib.trpc_iobuf_copy_to.restype = ctypes.c_size_t
            lib.trpc_iobuf_cutn.argtypes = [
                ctypes.c_void_p,
                ctypes.c_void_p,
                ctypes.c_size_t,
            ]
            lib.trpc_iobuf_cutn.restype = ctypes.c_size_t
            lib.trpc_iobuf_pop_front.argtypes = [ctypes.c_void_p, ctypes.c_size_t]
            lib.trpc_iobuf_pop_front.restype = ctypes.c_size_t
            lib.trpc_iobuf_block_count.argtypes = [ctypes.c_void_p]
            lib.trpc_iobuf_block_count.restype = ctypes.c_size_t
            lib.trpc_endpoint_parse.argtypes = [
                ctypes.c_char_p,
                ctypes.c_char_p,
                ctypes.c_size_t,
            ]
            lib.trpc_endpoint_parse.restype = ctypes.c_int
            # RPC surface (capi/rpc_capi.cc).
            lib.trpc_server_create.restype = ctypes.c_void_p
            lib.trpc_server_destroy.argtypes = [ctypes.c_void_p]
            lib.trpc_server_register.argtypes = [
                ctypes.c_void_p, ctypes.c_char_p, ctypes.c_void_p,
                ctypes.c_void_p,
            ]
            lib.trpc_server_register.restype = ctypes.c_int
            lib.trpc_call_respond.argtypes = [
                ctypes.c_void_p, ctypes.c_char_p, ctypes.c_size_t,
                ctypes.c_int, ctypes.c_char_p,
            ]
            lib.trpc_call_respond.restype = ctypes.c_int
            lib.trpc_server_start.argtypes = [ctypes.c_void_p, ctypes.c_int]
            lib.trpc_server_start.restype = ctypes.c_int
            lib.trpc_server_port.argtypes = [ctypes.c_void_p]
            lib.trpc_server_port.restype = ctypes.c_int
            lib.trpc_server_stop.argtypes = [ctypes.c_void_p]
            lib.trpc_channel_create.argtypes = [ctypes.c_char_p, ctypes.c_int64]
            lib.trpc_channel_create.restype = ctypes.c_void_p
            lib.trpc_channel_create_shm.argtypes = [
                ctypes.c_char_p, ctypes.c_int64,
            ]
            lib.trpc_channel_create_shm.restype = ctypes.c_void_p
            lib.trpc_channel_destroy.argtypes = [ctypes.c_void_p]
            lib.trpc_channel_transport.argtypes = [
                ctypes.c_void_p, ctypes.c_char_p, ctypes.c_size_t,
            ]
            lib.trpc_channel_call.argtypes = [
                ctypes.c_void_p, ctypes.c_char_p, ctypes.c_char_p,
                ctypes.c_size_t, ctypes.c_void_p, ctypes.c_int64,
                ctypes.c_char_p, ctypes.c_size_t,
            ]
            lib.trpc_channel_call.restype = ctypes.c_int
            lib.trpc_cluster_create.argtypes = [
                ctypes.c_char_p, ctypes.c_char_p, ctypes.c_int64, ctypes.c_int,
            ]
            lib.trpc_cluster_create.restype = ctypes.c_void_p
            lib.trpc_cluster_destroy.argtypes = [ctypes.c_void_p]
            lib.trpc_cluster_call.argtypes = [
                ctypes.c_void_p, ctypes.c_char_p, ctypes.c_char_p,
                ctypes.c_size_t, ctypes.c_void_p, ctypes.c_uint64,
                ctypes.c_char_p, ctypes.c_size_t,
            ]
            lib.trpc_cluster_call.restype = ctypes.c_int
            _lib = lib
    return _lib


class IOBuf:
    """Python view of trpc::IOBuf (zero-copy chained buffer)."""

    def __init__(self, data: bytes | None = None):
        self._lib = load_library()
        self._ptr = self._lib.trpc_iobuf_create()
        if data:
            self.append(data)

    def __del__(self):
        ptr, self._ptr = getattr(self, "_ptr", None), None
        if ptr:
            self._lib.trpc_iobuf_destroy(ptr)

    def __len__(self) -> int:
        return self._lib.trpc_iobuf_size(self._ptr)

    def append(self, data: bytes) -> None:
        self._lib.trpc_iobuf_append(self._ptr, data, len(data))

    def to_bytes(self) -> bytes:
        n = len(self)
        out = ctypes.create_string_buffer(n)
        got = self._lib.trpc_iobuf_copy_to(self._ptr, out, n, 0)
        return out.raw[:got]

    def cutn(self, n: int) -> "IOBuf":
        out = IOBuf()
        self._lib.trpc_iobuf_cutn(self._ptr, out._ptr, n)
        return out

    def pop_front(self, n: int) -> int:
        return self._lib.trpc_iobuf_pop_front(self._ptr, n)

    @property
    def block_count(self) -> int:
        return self._lib.trpc_iobuf_block_count(self._ptr)


def parse_endpoint(addr: str) -> str:
    """Normalize 'host:port[/device]' via the native EndPoint parser."""
    lib = load_library()
    out = ctypes.create_string_buffer(64)
    if lib.trpc_endpoint_parse(addr.encode(), out, 64) != 0:
        raise ValueError(f"bad endpoint: {addr!r}")
    return out.value.decode()
