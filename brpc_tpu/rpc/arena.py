"""Device staging arena — zero-copy payload path between JAX and the C++
runtime.

Parity: the fork's RDMA block_pool
(/root/reference/src/brpc/rdma/block_pool.cpp) registers memory once and
lets IOBufs carry it without copies.  TPU-native form: the C++ DeviceArena
(cpp/base/device_arena.h) owns registered staging slabs; Python wraps a
block as a writable numpy view, a device array lands in it with ONE
device→host DMA (`jax.device_get`-style — the transport hop itself, the
analogue of the NIC DMA), and the block then rides the RPC data path with
zero further host copies (`trpc_iobuf_append_block` hands the block to the
IOBuf by reference; writev sends straight from it).
"""

from __future__ import annotations

import ctypes

import numpy as np

from brpc_tpu.rpc._lib import load_library as load


class DeviceArena:
    """Registered staging-slab allocator (C++ DeviceArena)."""

    def __init__(self, block_size: int = 256 * 1024,
                 blocks_per_slab: int = 32, shm_backed: bool = False):
        self._lib = load()
        self._lib.trpc_arena_create.restype = ctypes.c_void_p
        self._lib.trpc_arena_alloc.restype = ctypes.c_void_p
        self._ptr = self._lib.trpc_arena_create(
            ctypes.c_uint32(block_size), ctypes.c_uint32(blocks_per_slab),
            ctypes.c_int(1 if shm_backed else 0))
        self.block_size = int(
            self._lib.trpc_arena_block_size(ctypes.c_void_p(self._ptr)))

    def alloc(self) -> "ArenaBlock":
        data = ctypes.c_void_p()
        meta = ctypes.c_uint64()
        block = self._lib.trpc_arena_alloc(
            ctypes.c_void_p(self._ptr), ctypes.byref(data),
            ctypes.byref(meta))
        if not block:
            raise MemoryError("device arena exhausted")
        return ArenaBlock(self, block, data.value, meta.value)

    @property
    def blocks_in_use(self) -> int:
        return int(self._lib.trpc_arena_blocks_in_use(
            ctypes.c_void_p(self._ptr)))

    def close(self) -> None:
        if self._ptr:
            self._lib.trpc_arena_destroy(ctypes.c_void_p(self._ptr))
            self._ptr = None

    def __del__(self):  # pragma: no cover - interpreter teardown
        try:
            self.close()
        except Exception:
            pass


class ArenaBlock:
    """One staging block; fill `view` then send (send consumes it)."""

    def __init__(self, arena: DeviceArena, handle, data_ptr: int,
                 meta: int):
        self.arena = arena
        self.handle = handle
        self.meta = meta  # (slab_id << 32 | offset) — the lkey analogue
        buf = (ctypes.c_char * arena.block_size).from_address(data_ptr)
        self.view = np.frombuffer(buf, dtype=np.uint8)  # writable, no copy

    def put(self, array) -> int:
        """Lands a (host or device) array's bytes in the staging block.
        Host-backed arrays enter via a dlpack VIEW (one memcpy into the
        slab, no intermediate); TPU-resident arrays take one device→host
        DMA then the memcpy.  Returns the byte length.  For the fully
        copy-free path, see rpc.zerocopy.append_jax — a slab only pays off
        when the block must live in registered/shm-backed memory."""
        from brpc_tpu.rpc.zerocopy import host_view

        flat, _owner = host_view(array)
        n = flat.size
        if n > self.view.size:
            raise ValueError(f"{n} bytes > block size {self.view.size}")
        np.copyto(self.view[:n], flat)
        return n

    def release(self) -> None:
        if self.handle:
            self.arena._lib.trpc_arena_release(
                ctypes.c_void_p(self.arena._ptr),
                ctypes.c_void_p(self.handle))
            self.handle = None


def call_with_block(channel, method: str, block: ArenaBlock,
                    length: int, timeout_ms: int = 0) -> bytes:
    """Sync RPC whose request payload is the arena block's [0, length)
    bytes, entering the IOBuf WITHOUT copying (block reference handoff).
    The block is consumed; returns the response bytes."""
    lib = block.arena._lib
    lib.trpc_iobuf_create.restype = ctypes.c_void_p
    req = lib.trpc_iobuf_create()
    resp = lib.trpc_iobuf_create()
    try:
        rc = lib.trpc_iobuf_append_block(ctypes.c_void_p(req),
                                         ctypes.c_void_p(block.handle),
                                         ctypes.c_uint32(length))
        block.handle = None  # consumed either way
        if rc != 0:
            raise ValueError(f"length {length} exceeds block capacity")
        err = ctypes.create_string_buffer(256)
        rc = lib.trpc_channel_call_buf(
            ctypes.c_void_p(channel._ptr), method.encode(),
            ctypes.c_void_p(req), ctypes.c_void_p(resp),
            ctypes.c_int64(timeout_ms), err, ctypes.c_size_t(len(err)))
        if rc != 0:
            from brpc_tpu.rpc.client import RpcError

            raise RpcError(rc, err.value.decode(errors="replace"))
        n = lib.trpc_iobuf_size(ctypes.c_void_p(resp))
        out = ctypes.create_string_buffer(n)
        lib.trpc_iobuf_copy_to(ctypes.c_void_p(resp), out,
                               ctypes.c_size_t(n), ctypes.c_size_t(0))
        return out.raw
    finally:
        lib.trpc_iobuf_destroy(ctypes.c_void_p(req))
        lib.trpc_iobuf_destroy(ctypes.c_void_p(resp))
