"""Pipelined zero-copy batch pipeline: submit N calls in one crossing,
poll completions with the GIL released.

Parity: fabric-lib's answer to "RPC Considered Harmful" (PAPERS.md) — deep
submission pipelines over registered buffers instead of one synchronous
round-trip per operation.  `Channel.call` is one blocked GIL round-trip
through `trpc_channel_call` per call; this module drives the batch C API
(cpp/capi/batch_capi.cc): `submit` hands the native runtime N requests by
reference (buffer protocol, no copy) and returns immediately; an issuing
fiber replays them as concurrent async calls; `poll` drains a lock-light
completion ring while the calling pthread sleeps OUTSIDE the GIL, so
Python handler servers, background threads and the submitting thread all
make progress during a deep poll.

Ownership rules (the zero-copy contract):

- Request buffers are pinned by this module until the native side drops
  its last IOBuf reference (a deleter callback, exactly like
  `zerocopy.append_jax`) — NOT merely until the completion is polled,
  because a timed-out call's bytes may still sit in a socket write queue.
- Response bytes either land in a caller-provided writable buffer (one
  native memcpy on the completion fiber, pool blocks recycled
  immediately) or ride out as a `ZeroCopyResponse` view over the pool
  blocks themselves; `release()` (or GC) recycles them.  No intermediate
  `bytes` object is created at the boundary on either path.
"""

from __future__ import annotations

import ctypes
import threading

import numpy as np

from brpc_tpu.rpc import zerocopy as _zc
from brpc_tpu.rpc._lib import load_library


def pinned_requests() -> int:
    """Number of buffers currently pinned by in-flight sends (shared
    registry with zerocopy.live_sends — one registry, one deleter)."""
    return _zc.live_sends()


class BatchCompletion(ctypes.Structure):
    """ABI mirror of `struct trpc_batch_completion` (batch_capi.cc)."""

    _fields_ = [
        ("token", ctypes.c_uint64),
        ("status", ctypes.c_int32),
        ("resp_copied", ctypes.c_uint32),
        ("resp_len", ctypes.c_uint64),
        ("resp_iobuf", ctypes.c_void_p),
        ("err", ctypes.c_char * 120),
    ]


class ZeroCopyResponse:
    """Response bytes viewed IN PLACE from the runtime's pool blocks.

    `view()` is a zero-copy memoryview when the response is physically
    contiguous (single block — the common case for pool-block responses);
    otherwise it materializes once.  `release()` (or GC) hands the blocks
    back to the pool; views must not outlive it."""

    def __init__(self, lib, iobuf_ptr: int, nbytes: int):
        self._lib = lib
        self._ptr = iobuf_ptr
        self.nbytes = nbytes

    def view(self) -> memoryview:
        lib = self._lib
        if not self._ptr:
            raise ValueError("response already released")
        if lib.trpc_iobuf_block_count(ctypes.c_void_p(self._ptr)) == 1:
            base = lib.trpc_iobuf_block_ptr(ctypes.c_void_p(self._ptr),
                                            ctypes.c_size_t(0))
            cbuf = (ctypes.c_char * self.nbytes).from_address(base)
            # The exported buffer pins this response (mv.obj -> cbuf ->
            # self), so dropping every other reference cannot recycle the
            # pool block under a live view; an EXPLICIT release() while
            # views exist is still the caller's contract to honor.
            cbuf._owner = self
            return memoryview(cbuf).cast("B")
        return memoryview(self.tobytes())

    def tobytes(self) -> bytes:
        if not self._ptr:
            raise ValueError("response already released")
        out = ctypes.create_string_buffer(self.nbytes)
        got = self._lib.trpc_iobuf_copy_to(
            ctypes.c_void_p(self._ptr), out, ctypes.c_size_t(self.nbytes),
            ctypes.c_size_t(0))
        return out.raw[:got]

    def release(self) -> None:
        ptr, self._ptr = self._ptr, None
        if ptr:
            self._lib.trpc_iobuf_destroy(ctypes.c_void_p(ptr))

    def __len__(self) -> int:
        return self.nbytes

    def __del__(self):
        try:
            self.release()
        except Exception:  # noqa: BLE001 — interpreter teardown
            pass


class Completion:
    """One finished call: `token`, `ok`, `status`/`error`, and the
    response — `data` is None when it landed in the caller's buffer
    (`resp_len` bytes written there), a `ZeroCopyResponse` otherwise."""

    __slots__ = ("token", "status", "error", "resp_len", "in_caller_buffer",
                 "data")

    def __init__(self, token, status, error, resp_len, in_caller_buffer,
                 data):
        self.token = token
        self.status = status
        self.error = error
        self.resp_len = resp_len
        self.in_caller_buffer = in_caller_buffer
        self.data = data

    @property
    def ok(self) -> bool:
        return self.status == 0

    def tobytes(self) -> bytes:
        """Materializes the response (b'' for empty / caller-buffer)."""
        if isinstance(self.data, ZeroCopyResponse):
            return self.data.tobytes()
        return b""

    def __repr__(self):
        state = "ok" if self.ok else f"err {self.status}: {self.error!r}"
        return f"<Completion token={self.token} {state} len={self.resp_len}>"


def _as_u8(buf) -> np.ndarray:
    """Flat uint8 view of any buffer-protocol object (no copy)."""
    return np.frombuffer(buf, dtype=np.uint8)


class Batch:
    """A submission pipeline over one Channel/ClusterChannel.

    submit() is one GIL crossing for N calls and returns their tokens
    without blocking on the network; poll() drains completions (GIL
    released while waiting).  Completions are correlation-matched by
    token, not ordered: issue order IS wire order on a single-connection
    channel, but responses complete as the server finishes them.

    The batch holds a reference to its channel; buffered completions
    remain drainable after `channel.close()` as long as nothing was in
    flight at close time."""

    def __init__(self, channel, is_cluster: bool | None = None):
        self._lib = load_library()
        if is_cluster is None:
            from brpc_tpu.rpc.client import ClusterChannel

            is_cluster = isinstance(channel, ClusterChannel)
        self._channel = channel  # keeps the native channel alive
        self._ptr = self._lib.trpc_batch_create(
            ctypes.c_void_p(channel._ptr), 1 if is_cluster else 0)
        if not self._ptr:
            raise ValueError("batch over a closed channel")
        self._resp_pins: dict[int, object] = {}
        # Serializes submit/cancel/introspection against close, and
        # counts pollers so close can wait for them to drain out of the
        # native poll before destroying the handle.
        self._lock = threading.Lock()
        self._cond = threading.Condition(self._lock)
        self._active_polls = 0

    def submit(self, method: str, requests, resp_bufs=None,
               timeout_ms: int = 0) -> list[int]:
        """Submits len(requests) calls in ONE crossing; returns tokens in
        request order.  Each request is any buffer-protocol object
        (bytes, numpy, memoryview); its bytes enter the wire path by
        reference and stay pinned until the runtime drops them.
        resp_bufs (optional, per-call, entries may be None) are WRITABLE
        buffers the responses land in natively — the zero-copy receive
        path; they must stay alive until their completion is polled."""
        if not self._ptr:
            raise ValueError("batch is closed")
        n = len(requests)
        if n == 0:
            return []
        # Validate and stage the response buffers BEFORE any request is
        # pinned: a raise past the pin loop would strand entries in
        # _pinned forever (the native deleter only fires for submitted
        # calls).
        rb = rc = None
        resp_views = []
        if resp_bufs is not None:
            if len(resp_bufs) != n:
                raise ValueError("resp_bufs length must match requests")
            rb = (ctypes.c_void_p * n)()
            rc = (ctypes.c_size_t * n)()
            for i, buf in enumerate(resp_bufs):
                if buf is None:
                    rb[i] = None
                    rc[i] = 0
                    continue
                v = np.frombuffer(buf, dtype=np.uint8)
                if not v.flags.writeable:
                    raise ValueError("resp_bufs entries must be writable")
                rb[i] = v.ctypes.data
                rc[i] = v.nbytes
                resp_views.append((v, buf))
        req_ptrs = (ctypes.c_void_p * n)()
        req_lens = (ctypes.c_size_t * n)()
        pin_ctxs = (ctypes.c_void_p * n)()
        tokens = (ctypes.c_uint64 * n)()
        pins = []
        try:
            for i, r in enumerate(requests):
                flat = _as_u8(r)
                if flat.nbytes == 0:
                    req_ptrs[i] = None
                    req_lens[i] = 0
                    pin_ctxs[i] = None
                    continue
                req_ptrs[i] = flat.ctypes.data
                req_lens[i] = flat.nbytes
                tok = _zc.pin(flat, r)
                pin_ctxs[i] = tok
                pins.append(tok)
        except Exception:
            for tok in pins:  # a bad request mid-loop must not leak pins
                _zc.unpin(tok)
            raise
        # self._lock is held across the native submit AND the pin
        # insertion: tokens are only known once submit returns, and a
        # concurrent poller that drained a completion in that window
        # would pop a pin that isn't registered yet (leaking it for the
        # batch's lifetime).  poll() pops under the same lock, so it
        # blocks those few microseconds until the pins are in place.
        with self._lock:
            if not self._ptr:
                for tok in pins:
                    _zc.unpin(tok)
                raise ValueError("batch is closed")
            got = self._lib.trpc_batch_submit(
                ctypes.c_void_p(self._ptr), method.encode(), req_ptrs,
                req_lens, rb, rc, ctypes.c_size_t(n),
                ctypes.c_int64(timeout_ms),
                ctypes.cast(_zc.release_cb, ctypes.c_void_p), pin_ctxs,
                tokens)
            if got != n:
                for tok in pins:  # nothing was issued; undo the pins
                    _zc.unpin(tok)
                raise RuntimeError("batch rejected the submit (closing?)")
            out = list(tokens)
            for t, (v, buf) in zip(
                    (t for i, t in enumerate(out)
                     if resp_bufs is not None and resp_bufs[i] is not None),
                    resp_views):
                self._resp_pins[t] = (v, buf)
        return out

    def poll(self, max_n: int = 64, timeout_ms: int = -1) -> list[Completion]:
        """Drains up to max_n completions, blocking OUTSIDE the GIL until
        at least one is ready or timeout_ms passes (0 = non-blocking,
        < 0 = wait forever).  Returns [] on timeout, and early (with
        whatever is buffered) once the batch is closing."""
        arr = (BatchCompletion * max_n)()
        with self._lock:
            if not self._ptr:
                raise ValueError("batch is closed")
            ptr = self._ptr
            self._active_polls += 1
        try:
            # The native handle stays valid for the whole call: close()
            # quiesces (which wakes parked pollers out of the wait) and
            # only destroys after _active_polls drains to zero.
            got = self._lib.trpc_batch_poll(
                ctypes.c_void_p(ptr), arr, ctypes.c_size_t(max_n),
                ctypes.c_int64(timeout_ms))
        finally:
            with self._lock:
                self._active_polls -= 1
                self._cond.notify_all()
        out = []
        if got:
            with self._lock:  # one locked pass, not one lock per record
                for i in range(got):
                    self._resp_pins.pop(arr[i].token, None)
        for i in range(got):
            c = arr[i]
            data = None
            if c.resp_iobuf:
                data = ZeroCopyResponse(self._lib, c.resp_iobuf, c.resp_len)
            out.append(Completion(
                token=c.token, status=c.status,
                error=c.err.decode(errors="replace") if c.status else "",
                resp_len=c.resp_len,
                in_caller_buffer=bool(c.resp_copied), data=data))
        return out

    def cancel(self, token: int) -> bool:
        """Best-effort cancel of one member: an in-flight call completes
        with ECANCELED via the runtime's StartCancel; a call that already
        completed (or was polled) is untouched.  True when the token was
        still live."""
        with self._lock:  # the native call is quick and must not race
            if not self._ptr:  # a concurrent destroy
                return False
            return self._lib.trpc_batch_cancel(
                ctypes.c_void_p(self._ptr), ctypes.c_uint64(token)) == 0

    @property
    def outstanding(self) -> int:
        """Calls submitted but not yet drained by poll()."""
        with self._lock:
            if not self._ptr:
                return 0
            return self._lib.trpc_batch_outstanding(
                ctypes.c_void_p(self._ptr))

    @property
    def inflight(self) -> int:
        """Calls still in flight (not yet completed into the ring).
        Zero means the batch no longer needs its channel: everything has
        settled, and only buffered completions remain to drain."""
        with self._lock:
            if not self._ptr:
                return 0
            return self._lib.trpc_batch_inflight(ctypes.c_void_p(self._ptr))

    def quiesce(self) -> None:
        """Rejects further submits, cancels in-flight members and waits
        for them to settle; buffered completions remain pollable.  After
        this the batch no longer touches its channel (Channel.close runs
        it on every live pipeline before destroying the native channel)."""
        with self._lock:  # held across the call so a concurrent close
            if self._ptr:  # cannot destroy the handle mid-quiesce
                self._lib.trpc_batch_quiesce(ctypes.c_void_p(self._ptr))

    def close(self) -> None:
        """Cancels in-flight members, waits for them (and any poller on
        another thread) to settle, frees unpolled completions."""
        with self._lock:
            ptr, self._ptr = self._ptr, None
        if ptr:
            # Quiesce wakes parked pollers; they observe the closed state
            # and drain out.  Destroy only once none is inside the native
            # poll — the handle dies with nobody touching it.
            self._lib.trpc_batch_quiesce(ctypes.c_void_p(ptr))
            with self._lock:
                while self._active_polls > 0:
                    self._cond.wait(timeout=1.0)
            self._lib.trpc_batch_destroy(ctypes.c_void_p(ptr))
        with self._lock:
            # Only after quiesce settled the in-flight members: a pin is
            # what keeps a caller-dropped landing buffer alive, and an
            # in-flight completion memcpys into it natively.
            self._resp_pins.clear()
        self._channel = None

    def __del__(self):
        try:
            self.close()
        except Exception:  # noqa: BLE001 — interpreter teardown
            pass


def call_batch(channel, method: str, requests, resp_bufs=None,
               timeout_ms: int = 0):
    """Synchronous batched call: submits all requests in one crossing,
    waits for every completion, returns results ALIGNED with `requests`.
    Per-call error isolation: a failed member yields an `RpcError`
    INSTANCE at its position (not raised), everything else completes
    normally.  Success entries are `bytes` (or None when the response
    landed in the matching resp_bufs entry).  Runs on its own private
    pipeline — a shared one could hand it completions belonging to other
    submitters."""
    from brpc_tpu.rpc.client import make_rpc_error

    b = Batch(channel)
    track = getattr(channel, "_track_pipeline", None)
    if track is not None:
        track(b)  # channel.close() on another thread settles us first
    try:
        tokens = b.submit(method, requests, resp_bufs=resp_bufs,
                          timeout_ms=timeout_ms)
        want = set(tokens)
        by_token: dict[int, object] = {}
        while want:
            for c in b.poll(max_n=len(want), timeout_ms=-1):
                want.discard(c.token)
                if not c.ok:
                    by_token[c.token] = make_rpc_error(
                        channel._lib, c.status, c.error)
                elif c.in_caller_buffer:
                    by_token[c.token] = None
                elif c.data is not None:
                    by_token[c.token] = c.data.tobytes()
                    c.data.release()
                else:
                    by_token[c.token] = b""
        return [by_token[t] for t in tokens]
    finally:
        b.close()
