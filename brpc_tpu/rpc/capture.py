"""Production traffic capture (the /capture page, in-process) and the
capture-file format shared with the replayers.

`cpp/stat/capture.cc` records sampled per-request METADATA — arrival
timestamps, method, tenant/priority (wire tail-group 5), deadline
budget (tail-group 7), trace/span ids, request/response sizes, status,
queue + handler latency — behind the default-off reloadable
`trpc_capture` flag, in a per-tenant stratified reservoir bounded by
`trpc_capture_max_records`.  Bodies stay with `Server::EnableDump`
(rpc_dump parity); this tier captures the *traffic shape* a replayer
needs: the arrival process, tenant mix and size distribution.

This module is the ctypes surface plus a pure-Python reader/writer for
the capture file (recordio "TREC" envelope; record 0 = "TRPCCAP1" magic
+ JSON header embedding the arrival-process summary and the recorded
per-tenant latency baseline; records 1..N = packed binary records):

- `enable_capture()` / `capture_enabled()` flip and read the flag;
- `summary()` returns the full /capture body (arrival-process summary:
  per-second rate series, burstiness CV, size histograms, per-tenant
  baseline, fan-out stats);
- `dump(path)` writes the capture file; `load_capture(path)` parses one
  (any process — no native library needed); `save_capture()` writes one
  from Python records (golden-capture tooling and tests);
- `counters()` exposes the seen/sampled/dropped/held accounting —
  `dropped > 0` means the capture is a uniform sample, not a complete
  record (the `capture_dropped_total` var says the same to Prometheus).

`tools/traffic_replay.py` consumes these files for exact (open-loop at
recorded inter-arrival times) and statistical (fitted arrival process)
replay; `cpp/tools/rpc_replay.cc` reads the same format natively.
"""

from __future__ import annotations

import ctypes
import json
import struct
from dataclasses import dataclass

from brpc_tpu.rpc._lib import load_library
from brpc_tpu.rpc.flags import set_flag
from brpc_tpu.rpc.observe import _dump_with_retry

# Capture-file record 0 prefix (cpp/stat/capture.h kFileMagic).
FILE_MAGIC = b"TRPCCAP1"
# recordio envelope magic (cpp/base/recordio.cc).
RECORDIO_MAGIC = b"TREC"
# Packed binary record prefix (cpp/stat/capture.cc serialize_record):
# version, arrival_mono_us, arrival_wall_us, trace_id, parent_span_id,
# request_bytes, response_bytes, status, queue_us, handler_us,
# deadline_budget_us, priority, method_len, tenant_len.
RECORD_STRUCT = struct.Struct("<BqqQQQQiIIIBBB")
RECORD_VERSION = 1


@dataclass
class CaptureRecord:
    """One captured request's metadata (mirror of capture::Sample)."""

    arrival_mono_us: int = 0
    arrival_wall_us: int = 0
    trace_id: int = 0
    parent_span_id: int = 0
    request_bytes: int = 0
    response_bytes: int = 0
    status: int = 0
    queue_us: int = 0
    handler_us: int = 0
    deadline_budget_us: int = 0
    priority: int = 0
    method: str = ""
    tenant: str = ""


def enable_capture(on: bool = True) -> None:
    """Flips traffic capture (the reloadable `trpc_capture` flag; off by
    default — flag-off cost is one relaxed load per request)."""
    set_flag("trpc_capture", "true" if on else "false")


def capture_enabled() -> bool:
    return load_library().trpc_capture_enabled() == 1


def reset_capture() -> None:
    """Clears the reservoir, the window counters and the sampling
    decision index (a fresh capture window; the lifetime
    capture_*_total vars keep counting)."""
    load_library().trpc_capture_reset()


def summary(records: int = 0) -> dict:
    """The raw /capture body for THIS process: {"enabled", "counters",
    "flags", "summary": {rate series, burstiness CV, size histograms,
    per-tenant baseline, fan-out}, "records" (newest `records`) when
    records > 0}."""
    lib = load_library()
    raw = _dump_with_retry(
        lambda buf, n: lib.trpc_capture_dump(records, buf, n))
    return json.loads(raw.decode())


def counters() -> dict:
    """Lifetime admission counters + records held: {"seen", "sampled",
    "dropped", "records"}.  Provably frozen at 0 while `trpc_capture`
    has never been on."""
    lib = load_library()
    seen = ctypes.c_uint64()
    sampled = ctypes.c_uint64()
    dropped = ctypes.c_uint64()
    records = ctypes.c_uint64()
    lib.trpc_capture_counters(ctypes.byref(seen), ctypes.byref(sampled),
                              ctypes.byref(dropped), ctypes.byref(records))
    return {
        "seen": seen.value,
        "sampled": sampled.value,
        "dropped": dropped.value,
        "records": records.value,
    }


def dump(path: str) -> int:
    """Writes this process's reservoir to a capture file.  Returns the
    number of records written; raises OSError on I/O failure."""
    n = load_library().trpc_capture_dump_file(path.encode())
    if n < 0:
        raise OSError(f"cannot write capture file: {path}")
    return int(n)


def pack_record(rec: CaptureRecord) -> bytes:
    """Serializes one record into the capture-file binary layout."""
    method = rec.method.encode()[:64]
    tenant = rec.tenant.encode()[:64]
    return RECORD_STRUCT.pack(
        RECORD_VERSION, rec.arrival_mono_us, rec.arrival_wall_us,
        rec.trace_id, rec.parent_span_id, rec.request_bytes,
        rec.response_bytes, rec.status, rec.queue_us, rec.handler_us,
        rec.deadline_budget_us, rec.priority, len(method),
        len(tenant)) + method + tenant


def unpack_record(payload: bytes) -> CaptureRecord:
    """Parses one capture-file record payload (raises ValueError on
    truncation or version mismatch)."""
    if len(payload) < RECORD_STRUCT.size:
        raise ValueError("truncated capture record")
    (version, arrival_mono, arrival_wall, trace_id, parent_span,
     req_bytes, resp_bytes, status, queue_us, handler_us, budget_us,
     priority, mlen, tlen) = RECORD_STRUCT.unpack_from(payload)
    if version != RECORD_VERSION:
        raise ValueError(f"unsupported capture record version {version}")
    base = RECORD_STRUCT.size
    if len(payload) < base + mlen + tlen:
        raise ValueError("truncated capture record strings")
    return CaptureRecord(
        arrival_mono_us=arrival_mono,
        arrival_wall_us=arrival_wall,
        trace_id=trace_id,
        parent_span_id=parent_span,
        request_bytes=req_bytes,
        response_bytes=resp_bytes,
        status=status,
        queue_us=queue_us,
        handler_us=handler_us,
        deadline_budget_us=budget_us,
        priority=priority,
        method=payload[base:base + mlen].decode(errors="replace"),
        tenant=payload[base + mlen:base + mlen + tlen].decode(
            errors="replace"),
    )


def _read_recordio(path: str):
    with open(path, "rb") as f:
        while True:
            head = f.read(8)
            if len(head) < 8:
                return
            if head[:4] != RECORDIO_MAGIC:
                raise ValueError(f"bad recordio magic in {path}")
            (length,) = struct.unpack("<I", head[4:])
            payload = f.read(length)
            if len(payload) < length:
                raise ValueError(f"truncated record in {path}")
            yield payload


def load_capture(path: str) -> tuple[dict, list[CaptureRecord]]:
    """Reads a capture file: (header dict, records in arrival order).
    Pure Python — works in any process, no native library needed."""
    header: dict = {}
    records: list[CaptureRecord] = []
    for i, payload in enumerate(_read_recordio(path)):
        if i == 0:
            if not payload.startswith(FILE_MAGIC):
                raise ValueError(
                    f"{path} is not a capture file (body dumps replay "
                    "via cpp/tools/rpc_replay)")
            header = json.loads(payload[len(FILE_MAGIC):].decode())
            continue
        records.append(unpack_record(payload))
    records.sort(key=lambda r: r.arrival_mono_us)
    return header, records


def save_capture(path: str, header: dict,
                 records: list[CaptureRecord]) -> None:
    """Writes a capture file from Python records (golden-capture tooling
    and tests; the native writer is capture::dump_file)."""

    def envelope(payload: bytes) -> bytes:
        return RECORDIO_MAGIC + struct.pack("<I", len(payload)) + payload

    with open(path, "wb") as f:
        f.write(envelope(FILE_MAGIC + json.dumps(header).encode()))
        for rec in sorted(records, key=lambda r: r.arrival_mono_us):
            f.write(envelope(pack_record(rec)))
