"""Python RPC clients: single-server Channel and ClusterChannel."""

from __future__ import annotations

import ctypes
import time

from brpc_tpu.rpc import batch as _batch
from brpc_tpu.rpc import observe as _observe
from brpc_tpu.rpc._lib import IOBuf, load_library


class RpcError(Exception):
    def __init__(self, code: int, text: str):
        super().__init__(f"rpc failed (code {code}): {text}")
        self.code = code
        self.text = text


class OverloadedError(RpcError):
    """The server shed this request via per-tenant admission control
    (cpp/net/qos.h kEOverloaded, code 2005): the node is ALIVE but over
    this tenant's bound.  Back off or route elsewhere — a ClusterChannel
    does that automatically (immediate failover to a different node +
    quarantine backoff on the shedding one)."""


class DrainingError(RpcError):
    """The server is draining gracefully (cpp/net/server.h Drain,
    kEDraining code 2006): healthy, just leaving the fleet.  A
    ClusterChannel fails over to a different node inside the same call
    WITHOUT quarantining the endpoint (its hot-restart successor revives
    there moments later); only a bare Channel surfaces this."""


class DeadlineExpiredError(RpcError):
    """The call's end-to-end budget ran out (cpp/net/deadline.h
    kEDeadlineExpired, code 2007): the request was shed before dispatch
    (server side, budget expired in flight or queued), or failed fast
    locally because the ambient budget was already exhausted.  NOT
    retriable — the budget is just as dead on every other node, and a
    ClusterChannel stops its attempt chain on it."""


def _overloaded_code(lib) -> int:
    return lib.trpc_qos_overloaded_code()


def make_rpc_error(lib, code: int, text: str) -> RpcError:
    """The typed error for a failed call's status code — OverloadedError
    for an admission-control shed, DrainingError for a graceful leave,
    DeadlineExpiredError for an exhausted end-to-end budget, RpcError
    otherwise.  Shared by the sync call paths and the batch plane so
    both surface the same type."""
    if code == _overloaded_code(lib):
        return OverloadedError(code, text)
    if code == lib.trpc_draining_code():
        return DrainingError(code, text)
    if code == lib.trpc_deadline_expired_code():
        return DeadlineExpiredError(code, text)
    return RpcError(code, text)


class deadline_scope:
    """Ambient end-to-end budget for the CURRENT THREAD's sync calls
    (cpp/net/deadline.h): inside the scope, every call stamps
    min(timeout, remaining budget) into meta tail-group 7, so a chain of
    proxied calls decrements one budget instead of each hop restarting
    its own.  Re-entrant scopes tighten only (an inner, longer budget is
    clamped to the outer one's remainder).

        with rpc.deadline_scope(50):       # 50ms end to end
            ch.call("A.Plan", req)          # stamps <= 50ms
            ch.call("A.Execute", req2)      # stamps what's left
    """

    def __init__(self, budget_ms: float):
        self._budget_us = int(budget_ms * 1000)
        self._lib = load_library()
        self._outer = -1

    def __enter__(self) -> "deadline_scope":
        self._outer = self._lib.trpc_deadline_ambient_remaining()
        self._t0 = time.monotonic()
        budget = self._budget_us
        if 0 <= self._outer < budget:
            budget = self._outer  # inner scopes only ever tighten
        self._lib.trpc_deadline_ambient_set(max(budget, 1))
        return self

    def __exit__(self, *exc) -> None:
        if self._outer >= 0:
            # Restore the OUTER budget minus the time this scope burned:
            # a nested scope must never hand time back.
            elapsed = int((time.monotonic() - self._t0) * 1e6)
            self._lib.trpc_deadline_ambient_set(
                max(self._outer - elapsed, 1))
        else:
            self._lib.trpc_deadline_ambient_clear()

    @property
    def remaining_us(self) -> int:
        """Remaining budget right now (0 = exhausted)."""
        rem = self._lib.trpc_deadline_ambient_remaining()
        return rem if rem >= 0 else 0


def _raise_rpc_error(lib, code: int, text: str):
    raise make_rpc_error(lib, code, text)


class _BatchMixin:
    """Pipelined data plane shared by Channel and ClusterChannel: one GIL
    crossing submits N calls, completions drain with the GIL released
    (brpc_tpu/rpc/batch.py over cpp/capi/batch_capi.cc)."""

    _default_batch = None
    _pipelines = None

    def pipeline(self) -> "_batch.Batch":
        """A dedicated submit/poll pipeline over this channel."""
        b = _batch.Batch(self)
        self._track_pipeline(b)
        return b

    def _track_pipeline(self, b) -> None:
        # Weakly tracked so close() can settle every live pipeline before
        # the native channel dies under their issuing fibers; a pipeline
        # the caller dropped closes itself via __del__ and falls out.
        import weakref

        if self._pipelines is None:
            self._pipelines = weakref.WeakSet()
        self._pipelines.add(b)

    def _batch_default(self) -> "_batch.Batch":
        if self._default_batch is None:
            self._default_batch = _batch.Batch(self)
            self._track_pipeline(self._default_batch)
        return self._default_batch

    def submit(self, method: str, requests, resp_bufs=None,
               timeout_ms: int = 0) -> list[int]:
        """Async pipelined issue: submits the requests (buffer-protocol
        zero-copy) on this channel's default pipeline and returns their
        tokens immediately; pair with poll()."""
        return self._batch_default().submit(
            method, requests, resp_bufs=resp_bufs, timeout_ms=timeout_ms)

    def poll(self, max_n: int = 64, timeout_ms: int = -1):
        """Drains completions from the default pipeline (GIL released
        while waiting); see batch.Batch.poll."""
        return self._batch_default().poll(max_n=max_n, timeout_ms=timeout_ms)

    def cancel(self, token: int) -> bool:
        """Cancels one in-flight submitted call by token."""
        return self._batch_default().cancel(token)

    def call_batch(self, method: str, requests, resp_bufs=None,
                   timeout_ms: int = 0) -> list:
        """Synchronous batched call over a fresh pipeline: all requests
        issue concurrently (one crossing in, one poll loop out), results
        return ALIGNED with requests, failed members as RpcError
        instances (error isolation — one failure never poisons the
        rest)."""
        return _batch.call_batch(self, method, requests,
                                 resp_bufs=resp_bufs, timeout_ms=timeout_ms)

    def _close_default_batch(self) -> None:
        b, self._default_batch = self._default_batch, None
        if b is not None:
            b.close()
        # Explicit pipelines with members in flight would be left calling
        # into a freed channel: quiesce each (cancel + settle).  Their
        # buffered completions stay drainable; only close() frees them.
        for p in list(self._pipelines or ()):
            p.quiesce()


def _call(lib, fn, ptr, method: str, request: bytes, extra,
          latency=None) -> bytes:
    resp = IOBuf()
    err = ctypes.create_string_buffer(256)
    t0 = time.perf_counter()
    rc = fn(ptr, method.encode(), request, len(request), resp._ptr, extra,
            err, 256)
    if latency is not None:
        # Client-side view of the same call the server's per-method
        # recorder times: includes queueing, wire, and (on errors) the
        # full timeout wait — the gap between the two IS the network.
        latency.record(int((time.perf_counter() - t0) * 1e6))
    if rc != 0:
        _raise_rpc_error(lib, rc, err.value.decode(errors="replace"))
    return resp.to_bytes()


class Channel(_BatchMixin):
    """Client stub for one server (parity: cpp/net/channel.h).

    use_shm routes same-host calls over shared-memory rings (TCP-handshaked;
    transparent TCP fallback)."""

    def __init__(self, addr: str, timeout_ms: int = 1000,
                 use_shm: bool = False, connection_type: str = "single",
                 qos_tenant: str = "", qos_priority: int = 0):
        self._lib = load_library()
        self._ptr = self._lib.trpc_channel_create_ex(
            addr.encode(), ctypes.c_int64(timeout_ms),
            connection_type.encode(), ctypes.c_int(1 if use_shm else 0))
        if not self._ptr:
            raise ValueError(
                f"bad address or options: {addr!r} / {connection_type!r}")
        if qos_tenant or qos_priority:
            self.set_qos(qos_tenant, qos_priority)
        # Client-side latency recorder in the shared var registry
        # (observe plane): shows in /vars + /brpc_metrics next to the
        # server's rpc_server_* series, readable in-process via
        # observe.Latency.read(ch.latency.name) or ch.latency.stats().
        # unique_var_name: a second channel to the same address gets
        # rpc_client_<addr>#2 instead of shadowing this recorder.
        self.latency = _observe.Latency(
            _observe.unique_var_name(f"rpc_client_{addr}"),
            f"client-side latency of sync calls on channel {addr}")

    def set_qos(self, tenant: str, priority: int = 0) -> None:
        """Default QoS tag for subsequent calls on this channel: `tenant`
        bills the server's per-tenant admission control (cpp/net/qos.h),
        `priority` picks the dispatch lane (0 = highest).  A shed request
        raises OverloadedError."""
        self._lib.trpc_channel_set_qos(
            self._ptr, tenant.encode(), int(priority))

    def call(self, method: str, request: bytes, timeout_ms: int = 0) -> bytes:
        return _call(self._lib, self._lib.trpc_channel_call, self._ptr,
                     method, request, timeout_ms, latency=self.latency)

    @property
    def transport(self) -> str:
        """Live transport name ("tcp", "shm_ring"); "" before first call."""
        out = ctypes.create_string_buffer(32)
        self._lib.trpc_channel_transport(self._ptr, out, 32)
        return out.value.decode()

    def close(self) -> None:
        # The default pipeline settles first: destroying the channel with
        # batch members in flight would pull the socket out from under
        # them.  Buffered completions on explicit pipelines stay
        # drainable after this returns.
        self._close_default_batch()
        ptr, self._ptr = self._ptr, None
        if ptr:
            self._lib.trpc_channel_destroy(ptr)
        self.latency.close()


class ClusterChannel(_BatchMixin):
    """Client over a named cluster with LB + retry + circuit breaking +
    hedging (parity: cpp/net/cluster.h).  naming_url: list://h:p,... or
    file://path; lb: rr | random | c_hash | wrr | p2c | la.

    backup_request_ms > 0 arms hedging: if the primary attempt hasn't
    answered within that budget a backup races it on another node and the
    first success wins.  health_check_method probes quarantined nodes every
    refresh tick and revives any that answer ('' disables probing);
    refresh_interval_ms is the re-resolve/probe cadence."""

    def __init__(self, naming_url: str, lb: str = "rr",
                 timeout_ms: int = 1000, max_retry: int = 2,
                 backup_request_ms: int = 0,
                 health_check_method: str | None = None,
                 health_check_timeout_ms: int = 0,
                 refresh_interval_ms: int = 0,
                 qos_tenant: str = "", qos_priority: int = 0):
        self._lib = load_library()
        self._ptr = self._lib.trpc_cluster_create_ex(
            naming_url.encode(), lb.encode(), timeout_ms, max_retry,
            backup_request_ms,
            None if health_check_method is None
            else health_check_method.encode(),
            health_check_timeout_ms, refresh_interval_ms,
        )
        if not self._ptr:
            raise ValueError(f"cluster init failed: {naming_url!r}")
        if qos_tenant or qos_priority:
            self.set_qos(qos_tenant, qos_priority)
        self.latency = _observe.Latency(
            _observe.unique_var_name(f"rpc_client_{naming_url}"),
            f"client-side latency of sync calls on cluster {naming_url} "
            "(includes retries and hedges)")

    def set_qos(self, tenant: str, priority: int = 0) -> None:
        """Default QoS tag for every member channel's subsequent calls
        (cpp/net/qos.h).  A node shedding this tenant (OverloadedError
        code) fails over to a different node inside the same call."""
        self._lib.trpc_cluster_set_qos(
            self._ptr, tenant.encode(), int(priority))

    def call(self, method: str, request: bytes, hash_key: int = 0,
             hint: str = "") -> bytes:
        """One cluster call.  `hint` ("host:port") names the preferred
        member — the node holding the longest cached KV prefix — and is
        honored by the c_hash_bl walk unless bounded load vetoes it
        (cpp/net/lb_hint.h).  Advisory only: an unknown or overloaded
        hint falls back to the plain ring walk."""
        if not hint:
            return _call(self._lib, self._lib.trpc_cluster_call, self._ptr,
                         method, request, hash_key, latency=self.latency)
        resp = IOBuf()
        err = ctypes.create_string_buffer(256)
        t0 = time.perf_counter()
        rc = self._lib.trpc_cluster_call_hinted(
            self._ptr, method.encode(), request, len(request), resp._ptr,
            hash_key, hint.encode(), err, 256)
        self.latency.record(int((time.perf_counter() - t0) * 1e6))
        if rc != 0:
            _raise_rpc_error(self._lib, rc, err.value.decode(errors="replace"))
        return resp.to_bytes()

    def close(self) -> None:
        self._close_default_batch()
        ptr, self._ptr = self._ptr, None
        if ptr:
            self._lib.trpc_cluster_destroy(ptr)
        self.latency.close()


def lb_hint_counters() -> tuple[int, int, int]:
    """(hit, veto, miss) cache-aware routing outcomes since process
    start: hit = hinted member selected, veto = bounded load overrode
    the hint (the ring walk took over), miss = hinted member absent or
    unhealthy (cpp/net/lb_hint.h)."""
    lib = load_library()
    hit = ctypes.c_uint64()
    veto = ctypes.c_uint64()
    miss = ctypes.c_uint64()
    lib.trpc_lb_hint_counters(ctypes.byref(hit), ctypes.byref(veto),
                              ctypes.byref(miss))
    return hit.value, veto.value, miss.value
