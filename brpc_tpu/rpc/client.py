"""Python RPC clients: single-server Channel and ClusterChannel."""

from __future__ import annotations

import ctypes

from brpc_tpu.rpc._lib import IOBuf, load_library


class RpcError(Exception):
    def __init__(self, code: int, text: str):
        super().__init__(f"rpc failed (code {code}): {text}")
        self.code = code
        self.text = text


def _call(lib, fn, ptr, method: str, request: bytes, extra) -> bytes:
    resp = IOBuf()
    err = ctypes.create_string_buffer(256)
    rc = fn(ptr, method.encode(), request, len(request), resp._ptr, extra,
            err, 256)
    if rc != 0:
        raise RpcError(rc, err.value.decode(errors="replace"))
    return resp.to_bytes()


class Channel:
    """Client stub for one server (parity: cpp/net/channel.h).

    use_shm routes same-host calls over shared-memory rings (TCP-handshaked;
    transparent TCP fallback)."""

    def __init__(self, addr: str, timeout_ms: int = 1000,
                 use_shm: bool = False, connection_type: str = "single"):
        self._lib = load_library()
        self._ptr = self._lib.trpc_channel_create_ex(
            addr.encode(), ctypes.c_int64(timeout_ms),
            connection_type.encode(), ctypes.c_int(1 if use_shm else 0))
        if not self._ptr:
            raise ValueError(
                f"bad address or options: {addr!r} / {connection_type!r}")

    def call(self, method: str, request: bytes, timeout_ms: int = 0) -> bytes:
        return _call(self._lib, self._lib.trpc_channel_call, self._ptr,
                     method, request, timeout_ms)

    @property
    def transport(self) -> str:
        """Live transport name ("tcp", "shm_ring"); "" before first call."""
        out = ctypes.create_string_buffer(32)
        self._lib.trpc_channel_transport(self._ptr, out, 32)
        return out.value.decode()

    def close(self) -> None:
        ptr, self._ptr = self._ptr, None
        if ptr:
            self._lib.trpc_channel_destroy(ptr)


class ClusterChannel:
    """Client over a named cluster with LB + retry + circuit breaking +
    hedging (parity: cpp/net/cluster.h).  naming_url: list://h:p,... or
    file://path; lb: rr | random | c_hash | wrr | p2c | la.

    backup_request_ms > 0 arms hedging: if the primary attempt hasn't
    answered within that budget a backup races it on another node and the
    first success wins.  health_check_method probes quarantined nodes every
    refresh tick and revives any that answer ('' disables probing);
    refresh_interval_ms is the re-resolve/probe cadence."""

    def __init__(self, naming_url: str, lb: str = "rr",
                 timeout_ms: int = 1000, max_retry: int = 2,
                 backup_request_ms: int = 0,
                 health_check_method: str | None = None,
                 health_check_timeout_ms: int = 0,
                 refresh_interval_ms: int = 0):
        self._lib = load_library()
        self._ptr = self._lib.trpc_cluster_create_ex(
            naming_url.encode(), lb.encode(), timeout_ms, max_retry,
            backup_request_ms,
            None if health_check_method is None
            else health_check_method.encode(),
            health_check_timeout_ms, refresh_interval_ms,
        )
        if not self._ptr:
            raise ValueError(f"cluster init failed: {naming_url!r}")

    def call(self, method: str, request: bytes, hash_key: int = 0) -> bytes:
        return _call(self._lib, self._lib.trpc_cluster_call, self._ptr,
                     method, request, hash_key)

    def close(self) -> None:
        ptr, self._ptr = self._ptr, None
        if ptr:
            self._lib.trpc_cluster_destroy(ptr)
