"""Collective transfer schedules over the RMA fabric — group put plans.

The Python surface of cpp/net/collective.h: all-gather, reduce-scatter,
all-to-all and generic array resharding expressed as *planned sets of
one-sided RMA puts* between group members.  Every member holds a
``Group`` over the same ordered member list (explicit, or snapshotted
from a ``naming://`` view so drained members are excluded and an epoch
change mid-schedule fails the step whole-or-nothing), and calls the same
sequence of collectives; transfers are cut into ``trpc_coll_chunk_bytes``
chunks issued ``trpc_coll_inflight`` deep so chunk k+1's put overlaps
chunk k's verification (T3, arXiv 2401.16677).  A dropped/corrupted
chunk fails the step for the WHOLE group (CollAbortError) — a failed
run's buffers are undefined, and no successful run ever contains torn
bytes.

Resharding follows the portable-collectives decomposition of arXiv
2112.01075: ``plan_reshard_bytes`` factors a source→target redistribution
into the minimal put set (bytes whose owner does not change are reused
in place, never re-fetched), and ``Group.reshard`` executes it.  The
service form (``Reshard.Plan`` / ``Reshard.Execute``, served by any
``Server`` with ``enable_collective()``) plans over the wire and — for
Execute — moves shards addressed as PR 11 KV blocks: each member's
source shard is block ``src_block_base + rank``, and the resharded
result re-publishes as ``dst_block_base + rank``.

Typical 4-member all-gather (each process)::

    srv = Server(); srv.enable_collective(); srv.start(port)
    g = collective.Group(members, my_rank)        # same list everywhere
    send = rma.RmaBuffer(S); recv = rma.RmaBuffer(4 * S)
    g.all_gather(send, recv, shard_bytes=S)
"""

from __future__ import annotations

import ctypes
import struct

from brpc_tpu.rpc._lib import load_library
from brpc_tpu.rpc.client import Channel, RpcError

# Wire forms — MUST mirror cpp/net/collective.h (coll-wire markers):
# CollPutWire (80 bytes), ReshardReqWire (64), ShardRangeWire (24),
# ReshardPlanWire (40), all fixed little-endian.
_PUT_WIRE = struct.Struct("<QQIIIIIIQQQQII")
assert _PUT_WIRE.size == 80
_RESHARD_WIRE = struct.Struct("<QQQQIIIIIIQ")
assert _RESHARD_WIRE.size == 64
_RANGE_WIRE = struct.Struct("<IIQQ")
assert _RANGE_WIRE.size == 24
_PLAN_WIRE = struct.Struct("<QQQIIQ")
assert _PLAN_WIRE.size == 40

PLAN_METHOD = "Reshard.Plan"
EXECUTE_METHOD = "Reshard.Execute"

ALL_GATHER = 1
REDUCE_SCATTER = 2
ALL_TO_ALL = 3


class CollError(RpcError):
    """Base of the collective error family (codes 2121..2123)."""


class CollAbortError(CollError):
    """The step failed for the whole group (a peer's chunk dropped, a
    member timed out, or a Coll.Abort arrived) — whole-or-nothing."""


class CollEpochError(CollError):
    """The group's naming view changed mid-schedule; recompile the
    group from the registry and re-run."""


class CollMismatchError(CollError):
    """Buffer sizes or shardings do not fit the compiled plan."""


def _codes() -> tuple[int, int, int]:
    lib = load_library()
    a = ctypes.c_int()
    e = ctypes.c_int()
    m = ctypes.c_int()
    lib.trpc_coll_codes(ctypes.byref(a), ctypes.byref(e), ctypes.byref(m))
    return a.value, e.value, m.value


def _coll_error(code: int, text: str) -> RpcError:
    a, e, m = _codes()
    cls = {a: CollAbortError, e: CollEpochError, m: CollMismatchError}.get(
        code)
    return (cls or CollError)(code, text)


def _buf_addr_len(buf) -> tuple[int, int]:
    """(address, nbytes) of an RmaBuffer, a writable buffer-protocol
    object, or `bytes` (send-side only — the caller keeps the object
    alive through the blocking run, so the address stays valid)."""
    if hasattr(buf, "address"):
        return buf.address, buf.nbytes
    mv = memoryview(buf)
    if mv.readonly:
        if isinstance(buf, bytes):
            addr = ctypes.cast(ctypes.c_char_p(buf), ctypes.c_void_p).value
            return addr, mv.nbytes
        raise TypeError(
            "read-only buffers other than bytes are not supported — pass "
            "an RmaBuffer (one-sided landings) or a writable buffer")
    view = (ctypes.c_char * 0).from_buffer(buf)
    return ctypes.addressof(view), mv.nbytes


def pack_ranges(ranges) -> bytes:
    """Packs [(rank, off, len), ...] as ShardRangeWire rows (the wire
    Reshard.Plan/Execute and the local planner both consume)."""
    return b"".join(_RANGE_WIRE.pack(rank, 0, off, ln)
                    for rank, off, ln in ranges)


def plan_reshard_bytes(src_ranges, dst_ranges, total: int,
                       nmembers: int) -> dict:
    """Plans src→dst locally (no RPC): {"bytes_moved", "bytes_reused",
    "naive_bytes", "steps"}.  bytes_moved < naive_bytes whenever the
    shardings overlap — the 2112.01075 minimality the bench row stamps.
    Ranges are (rank, global_off, len) tuples tiling [0, total)."""
    lib = load_library()
    rows = pack_ranges(list(src_ranges) + list(dst_ranges))
    moved = ctypes.c_uint64()
    reused = ctypes.c_uint64()
    naive = ctypes.c_uint64()
    steps = ctypes.c_uint32()
    rc = lib.trpc_coll_reshard_plan(
        rows, len(src_ranges), len(dst_ranges), total, nmembers,
        ctypes.byref(moved), ctypes.byref(reused), ctypes.byref(naive),
        ctypes.byref(steps))
    if rc != 0:
        raise ValueError("invalid shardings (must tile [0, total) with "
                         "ranks < nmembers)")
    return {"bytes_moved": moved.value, "bytes_reused": reused.value,
            "naive_bytes": naive.value, "steps": steps.value}


class ReadyMap:
    """Producer-stamped chunk-ready bitmap over a send buffer (the
    overlap-aware collective seam, ISSUE 18).  Create it over the SAME
    buffer a collective will read, ``stamp(off, len)`` ranges as the
    producer fills them (release-fenced after the writes), and pass the
    map as ``ready=`` to a Group collective: with ``trpc_coll_overlap``
    on, transfers fire the moment their compiled input chunks are
    stamped — microbatch i's communication overlapping microbatch i+1's
    compute; off, the executor waits once for the whole producer extent
    (byte-identical results either way).  The map does not own the
    buffer — keep the buffer alive while the map exists.

        ready = collective.ReadyMap(send, granularity=1 << 20)
        fill(send, 0, CHUNK); ready.stamp(0, CHUNK)   # ... keep filling
        g.reduce_scatter(send, recv, shard_bytes=S, ready=ready)
    """

    def __init__(self, buf, granularity: int = 0):
        lib = load_library()
        addr, nbytes = _buf_addr_len(buf)
        handle = lib.trpc_coll_ready_create(addr, nbytes, granularity)
        if handle == 0:
            raise ValueError(
                "ready map creation failed (empty buffer or bad "
                "granularity)")
        self._lib = lib
        self._handle = handle
        self.nbytes = nbytes

    @property
    def handle(self) -> int:
        """The opaque native handle (0 after close)."""
        return self._handle or 0

    def stamp(self, off: int, length: int) -> None:
        """Marks [off, off+length) ready.  `off` must be chunk-aligned
        and `length` a chunk multiple (or reach the buffer end); call it
        AFTER writing the bytes.  Monotonic — restamping is a no-op."""
        rc = self._lib.trpc_coll_ready_stamp(self._handle, off, length)
        if rc != 0:
            raise ValueError(
                f"bad stamp [{off}, {off + length}) — misaligned or "
                f"outside the {self.nbytes}-byte map")

    def close(self) -> None:
        handle, self._handle = self._handle, None
        if handle:
            self._lib.trpc_coll_ready_destroy(handle)

    def __enter__(self) -> "ReadyMap":
        return self

    def __exit__(self, *exc) -> None:
        self.close()

    def __del__(self):  # pragma: no cover - GC timing
        try:
            self.close()
        except Exception:  # noqa: BLE001 — interpreter teardown
            pass


def ready_maps_live() -> int:
    """Readiness maps currently registered in THIS process (0 when all
    closed — the quiescence probe for overlap tests)."""
    return int(load_library().trpc_coll_ready_maps())


class Group:
    """Channels to one member snapshot; every member must issue the same
    sequence of collectives.  Not safe for concurrent calls."""

    def __init__(self, members=None, my_rank: int = 0,
                 naming_url: str | None = None, self_addr: str = "",
                 timeout_ms: int = 30000, use_shm: bool = True):
        lib = load_library()
        if naming_url is not None:
            ptr = lib.trpc_coll_group_create_naming(
                naming_url.encode(), self_addr.encode(), timeout_ms,
                1 if use_shm else 0)
            if not ptr:
                raise RuntimeError(
                    f"group snapshot from {naming_url!r} failed (registry "
                    f"unreachable, or {self_addr!r} is not a member)")
        else:
            csv = ",".join(members)
            ptr = lib.trpc_coll_group_create(
                csv.encode(), my_rank, timeout_ms, 1 if use_shm else 0)
            if not ptr:
                raise RuntimeError(f"group init failed for {members!r}")
        self._lib = lib
        self._ptr = ptr

    @property
    def rank(self) -> int:
        return self._lib.trpc_coll_group_rank(self._ptr)

    @property
    def size(self) -> int:
        return self._lib.trpc_coll_group_size(self._ptr)

    @property
    def naming_version(self) -> int:
        """The snapshotted naming-view version (0 for explicit groups)."""
        return self._lib.trpc_coll_group_version(self._ptr)

    def _run(self, op: int, send, recv, shard_bytes: int,
             run_seq: int, ready=None) -> None:
        saddr, slen = _buf_addr_len(send)
        raddr, rlen = _buf_addr_len(recv)
        if ready is not None:
            handle = ready if isinstance(ready, int) else ready.handle
            rc = self._lib.trpc_coll_run_ready(
                self._ptr, op, saddr, slen, raddr, rlen, shard_bytes,
                run_seq, handle)
        else:
            rc = self._lib.trpc_coll_run(self._ptr, op, saddr, slen, raddr,
                                         rlen, shard_bytes, run_seq)
        if rc != 0:
            raise _coll_error(rc, f"collective op {op} failed (rc={rc})")

    def all_gather(self, send, recv, shard_bytes: int = 0,
                   run_seq: int = 0, ready=None) -> None:
        """Gathers every member's `send` shard into everyone's `recv`
        (rank-ordered).  shard_bytes defaults to len(send).  `ready`:
        an optional ReadyMap over `send` (overlap-aware path)."""
        self._run(ALL_GATHER, send, recv, shard_bytes, run_seq, ready)

    def reduce_scatter(self, send, recv, shard_bytes: int = 0,
                       run_seq: int = 0, ready=None) -> None:
        """Element-wise u32-sums the members' `send` arrays (n*shard
        each) and scatters chunk r to rank r's `recv`.  MUTATES `send`
        (it is the ring accumulator).  `ready`: an optional ReadyMap
        over `send` (overlap-aware path)."""
        self._run(REDUCE_SCATTER, send, recv, shard_bytes, run_seq, ready)

    def all_to_all(self, send, recv, shard_bytes: int = 0,
                   run_seq: int = 0, ready=None) -> None:
        """Transposes blocks: rank r's block d lands at rank d's block
        r.  shard_bytes defaults to len(send) / group size.  `ready`:
        an optional ReadyMap over `send` (overlap-aware path)."""
        self._run(ALL_TO_ALL, send, recv, shard_bytes, run_seq, ready)

    def reshard(self, src_ranges, dst_ranges, total: int, send, recv,
                run_seq: int = 0) -> None:
        """Moves this rank's source ranges (concatenated in `send`) into
        the target layout (`recv` receives this rank's target ranges) —
        only bytes whose owner changes ride the fabric."""
        rows = pack_ranges(list(src_ranges) + list(dst_ranges))
        saddr, slen = _buf_addr_len(send)
        raddr, rlen = _buf_addr_len(recv)
        rc = self._lib.trpc_coll_reshard_run(
            self._ptr, rows, len(src_ranges), len(dst_ranges), total,
            saddr, slen, raddr, rlen, run_seq)
        if rc != 0:
            raise _coll_error(rc, f"reshard failed (rc={rc})")

    def close(self) -> None:
        ptr, self._ptr = self._ptr, None
        if ptr:
            self._lib.trpc_coll_group_destroy(ptr)

    def __enter__(self) -> "Group":
        return self

    def __exit__(self, *exc) -> None:
        self.close()

    def __del__(self):  # pragma: no cover - GC timing
        try:
            self.close()
        except Exception:  # noqa: BLE001 — interpreter teardown
            pass


def sessions_live() -> int:
    """Receive sessions currently registered in THIS process (0 when no
    run is in flight — the cancel/abort quiescence probe)."""
    return int(load_library().trpc_coll_sessions())


def rma_scavenge() -> int:
    """One explicit RMA span-scavenger pass (net/rma.h rma_scavenge);
    returns window slots reclaimed.  The runtime also runs it lazily."""
    return int(load_library().trpc_rma_scavenge())


class ReshardClient:
    """RPC client for the resharding service on any collective-enabled
    server (Reshard.Plan is stateless; Reshard.Execute moves KV-block-
    addressed shards on the member fleet)."""

    def __init__(self, channel: Channel):
        self._ch = channel

    def plan(self, src_ranges, dst_ranges, total: int,
             nmembers: int) -> dict:
        """Plans over the wire; same dict shape as plan_reshard_bytes
        plus "transfers"."""
        req = _RESHARD_WIRE.pack(0, 0, 0, total, 0, nmembers,
                                 len(src_ranges), len(dst_ranges), 0, 0, 0)
        req += pack_ranges(list(src_ranges) + list(dst_ranges))
        try:
            resp = self._ch.call(PLAN_METHOD, req)
        except RpcError as e:
            raise _coll_error(e.code, e.text) from None
        moved, reused, naive, steps, transfers, _ = _PLAN_WIRE.unpack(resp)
        return {"bytes_moved": moved, "bytes_reused": reused,
                "naive_bytes": naive, "steps": steps,
                "transfers": transfers}

    @staticmethod
    def execute_request(run_id: int, members, my_rank: int, src_ranges,
                        dst_ranges, total: int, src_block_base: int,
                        dst_block_base: int, use_shm: bool = True,
                        timeout_ms: int = 30000) -> bytes:
        """The personalized Reshard.Execute request for `my_rank` — a
        coordinator builds one per member and fans them out (each member
        reshards kv block src_block_base+rank into dst_block_base+rank)."""
        req = _RESHARD_WIRE.pack(
            run_id, src_block_base, dst_block_base, total, my_rank,
            len(members), len(src_ranges), len(dst_ranges),
            1 if use_shm else 0, timeout_ms, 0)
        for m in members:
            req += m.encode()[:63].ljust(64, b"\0")
        req += pack_ranges(list(src_ranges) + list(dst_ranges))
        return req

    def execute(self, request: bytes, timeout_ms: int = 0) -> tuple[int, int]:
        """Sends one prepared execute_request; returns (dst_len,
        generation) of the member's re-published shard block."""
        try:
            resp = self._ch.call(EXECUTE_METHOD, request,
                                 timeout_ms=timeout_ms)
        except RpcError as e:
            raise _coll_error(e.code, e.text) from None
        return struct.unpack("<QQ", resp)
