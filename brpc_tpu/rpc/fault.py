"""Deterministic fault injection from Python (cpp/net/fault.h).

Drives the process-wide transport FaultActor: seeded, schedule-driven
packet drop / delay / corruption / truncation / partial writes /
connection resets, applied by the FaultTransport decorator wrapping every
socket's transport.  The same schedule string also works through the
"fault_schedule" flag and a live server's /faults HTTP endpoint — this
module is the pytest-facing form.

Schedule grammar (';'-separated key=value; see cpp/net/fault.h):
    seed=N peer=ip:port after=N max=N
    drop=P corrupt=P trunc=P partial=P reset=P refuse=P delay=P:MS

The svr_* fields (svr_delay=P:MS, svr_error=P:CODE, svr_reject=P) belong
to a SERVER's private actor — install them with `Server.set_faults`, not
here; this transport actor rejects them loudly rather than accepting a
schedule that could never fire.

Determinism: decision i is a pure function of (seed, i), so a given seed
replays the identical fault sequence; `reset()` restarts the sequence and
`log()` returns the injected faults for replay comparison.
"""

from __future__ import annotations

import ctypes

from brpc_tpu.rpc._lib import load_library


def set_schedule(spec: str) -> None:
    """Installs the transport fault schedule ('' disables).  Raises on a
    malformed spec — a typo must not silently mean 'no faults'."""
    if load_library().trpc_fault_set(spec.encode()) != 0:
        raise ValueError(f"bad fault schedule: {spec!r}")


def get_schedule() -> str:
    """The canonical active schedule ('' when off)."""
    lib = load_library()
    out = ctypes.create_string_buffer(4096)
    if lib.trpc_fault_get(out, 4096) != 0:
        return ""
    return out.value.decode()


def log(max_bytes: int = 1 << 16) -> list[str]:
    """Injected faults as '#index point kind' lines, oldest first."""
    lib = load_library()
    out = ctypes.create_string_buffer(max_bytes)
    lib.trpc_fault_log(out, max_bytes)
    text = out.value.decode()
    return [line for line in text.splitlines() if line]


def reset() -> None:
    """Restarts the deterministic sequence (counter + log; schedule
    kept)."""
    load_library().trpc_fault_reset()


def injected() -> int:
    """Faults injected since the last set/reset."""
    return load_library().trpc_fault_injected()
