"""Runtime flag access from Python (the /flags service's programmatic
form; parity: reloadable_flags.h + flags_service).  Flags defined by the
native runtime (e.g. rpcz_enabled, per-method max_concurrency_*) can be
read and flipped live."""

from __future__ import annotations

import ctypes

from brpc_tpu.rpc._lib import load_library


def set_flag(name: str, value: str) -> None:
    """Validated runtime flip; raises on unknown/bad/immutable flags."""
    rc = load_library().trpc_flag_set(name.encode(), str(value).encode())
    if rc != 0:
        reason = {-1: "unknown flag", -2: "rejected value",
                  -3: "immutable"}.get(rc, f"error {rc}")
        raise ValueError(f"set_flag({name!r}): {reason}")


def get_flag(name: str) -> str:
    lib = load_library()
    size = 256
    while True:
        out = ctypes.create_string_buffer(size)
        rc = lib.trpc_flag_get(name.encode(), out, ctypes.c_size_t(size))
        if rc == 0:
            return out.value.decode()
        if rc == -2 and size < 1 << 20:  # value larger than the buffer
            size *= 4
            continue
        raise KeyError(name)
