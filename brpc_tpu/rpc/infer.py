"""Client for the streamed-inference front door (parity: cpp/net/infer.h).

Submit a prompt (a list of token ids) and get back a live token stream:
the server's continuous-batching scheduler admits the request into the
running decode batch, prefills through the content-addressed prefix
cache (matched prompt blocks skip recompute), and pushes one TokenRecord
per decode step down a credit-windowed logical stream — thousands of
which multiplex per connection, so a 20k-fd box serves 100k+ concurrent
completions.

    client = InferClient(channel)
    completion = client.submit([1, 2, 3, 4], max_new_tokens=16)
    print(completion.cached_tokens)        # prompt tokens served by cache
    for tok in completion:                 # one token per decode step
        ...

Cancel by closing the completion (or just dropping the channel): the
server reaps the slot next step and aborts any in-flight prefix pulls
mid-RPC.  An overloaded tenant's submit raises OverloadedError (2005);
an expired deadline surfaces DeadlineExpiredError (2007).

Wire formats mirror cpp/net/infer.h exactly (infer-wire marker):
  InferSubmitWire   <IIII  magic, flags, max_new_tokens, n_prompt_tokens
                    then n x <Q token ids
  InferSubmitReply  <QII   request_id, cached_tokens, block_tokens
  TokenRecord       <QII   token, index, flags   (16 bytes per chunk)
"""

from __future__ import annotations

import struct
from typing import Iterator

from brpc_tpu.rpc import stream as _stream

INFER_MAGIC = 0x31464E49  # "INF1"
SUBMIT_NO_PUBLISH = 1

TOKEN_EOS = 1
TOKEN_CANCELLED = 2

_SUBMIT_HEADER = struct.Struct("<IIII")
_SUBMIT_REPLY = struct.Struct("<QII")
_TOKEN_RECORD = struct.Struct("<QII")

SUBMIT_METHOD = "Infer.Submit"


def pack_submit(prompt_tokens, max_new_tokens: int = 0,
                publish: bool = True) -> bytes:
    """The Infer.Submit request body for `prompt_tokens` (u64 ids)."""
    flags = 0 if publish else SUBMIT_NO_PUBLISH
    return _SUBMIT_HEADER.pack(
        INFER_MAGIC, flags, max_new_tokens, len(prompt_tokens)
    ) + struct.pack(f"<{len(prompt_tokens)}Q", *prompt_tokens)


class TokenRecord:
    """One decode step's output: (token, index, flags)."""

    __slots__ = ("token", "index", "flags")

    def __init__(self, token: int, index: int, flags: int):
        self.token = token
        self.index = index
        self.flags = flags

    @property
    def eos(self) -> bool:
        return bool(self.flags & TOKEN_EOS)

    @property
    def cancelled(self) -> bool:
        return bool(self.flags & TOKEN_CANCELLED)

    def __repr__(self):
        return (f"TokenRecord(token={self.token}, index={self.index}, "
                f"flags={self.flags})")


class CancelledError(Exception):
    """The server cancelled this completion mid-decode (deadline expiry
    or admission reaping) — the final record carried TOKEN_CANCELLED."""


class Completion:
    """A live completion: the submit reply plus the token stream.

    Iterate for token ids (stops cleanly at EOS, raises CancelledError
    on a server-side cancel); records() yields full TokenRecords.
    close() cancels server-side — the scheduler reaps the slot at the
    next step and re-admits a waiter in its place."""

    def __init__(self, stream: "_stream.Stream", request_id: int,
                 cached_tokens: int, block_tokens: int):
        self.stream = stream
        self.request_id = request_id
        # Prompt tokens served by the prefix cache (0 = fully recomputed).
        self.cached_tokens = cached_tokens
        self.block_tokens = block_tokens
        self.finished = False
        self.cancelled = False

    def records(self, timeout_ms: int = -1) -> Iterator[TokenRecord]:
        """Yields TokenRecords until the EOS or CANCELLED record
        (inclusive), or until the stream closes without one (connection
        death — surfaces as plain StopIteration after marking
        cancelled).  A chunk that is not exactly one TokenRecord is a
        protocol desync and raises: an oversized chunk (e.g. a widened
        record from a newer server) surfaces StreamChunkTooLargeError
        from the read, a short one raises ValueError — never silently
        skipped, which would desynchronize the token stream."""
        while not self.finished:
            try:
                chunk = self.stream.read(max_bytes=_TOKEN_RECORD.size,
                                         timeout_ms=timeout_ms)
            except _stream.StreamClosedError:
                self.finished = True
                self.cancelled = True
                return
            if len(chunk) != _TOKEN_RECORD.size:
                self.finished = True
                raise ValueError(
                    f"request {self.request_id}: malformed token record "
                    f"({len(chunk)} bytes, expected {_TOKEN_RECORD.size})")
            rec = TokenRecord(*_TOKEN_RECORD.unpack(chunk))
            if rec.eos or rec.cancelled:
                self.finished = True
                self.cancelled = rec.cancelled
            yield rec

    def __iter__(self) -> Iterator[int]:
        """Token ids in order; raises CancelledError on a server cancel."""
        for rec in self.records():
            if rec.cancelled:
                raise CancelledError(
                    f"request {self.request_id} cancelled at token "
                    f"{rec.index}")
            yield rec.token
        if self.cancelled:
            raise CancelledError(
                f"request {self.request_id} cancelled (stream closed)")

    def close(self) -> None:
        """Client-side cancel: closes the token stream; the scheduler
        observes the close and frees the slot at its next step."""
        self.finished = True
        self.stream.destroy()

    def __enter__(self) -> "Completion":
        return self

    def __exit__(self, *exc) -> None:
        self.close()


class InferClient:
    """Submits prompts to a server running Server.enable_infer()."""

    def __init__(self, channel, tenant: str = "", priority: int = 0):
        self._channel = channel
        self._tenant = tenant
        self._priority = priority

    def submit(self, prompt_tokens, max_new_tokens: int = 0,
               publish: bool = True, timeout_ms: int = 0,
               window_bytes: int = 0) -> Completion:
        """One completion request.  max_new_tokens = 0 takes the server's
        trpc_infer_max_new_tokens default; publish=False skips the
        post-prefill publish of this prompt's uncached blocks.  Raises
        OverloadedError when the tenant is shed (2005) and
        DeadlineExpiredError past budget (2007)."""
        req = pack_submit(prompt_tokens, max_new_tokens, publish)
        st, resp = _stream.open_stream(
            self._channel, SUBMIT_METHOD, req, timeout_ms=timeout_ms,
            window_bytes=window_bytes, tenant=self._tenant,
            priority=self._priority)
        if len(resp) < _SUBMIT_REPLY.size:
            st.destroy()
            raise ValueError(
                f"short Infer.Submit reply: {len(resp)} bytes")
        request_id, cached, block = _SUBMIT_REPLY.unpack(
            resp[:_SUBMIT_REPLY.size])
        return Completion(st, request_id, cached, block)
