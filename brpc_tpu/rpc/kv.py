"""Paged KV-block registry client — block-addressed KV-cache transfer.

The Python surface of cpp/net/kvstore.h (fabric-lib's abstraction,
arXiv 2510.27656): KV blocks are addressed by BLOCK ID through a
registry record {node, rkey, offset, len, generation}, never by
connection.  A prefill node `publish()`es blocks out of an `RmaBuffer`
(the store serves their bytes zero-copy from the registered pages) and
registers them; a decode node's `KvClient` looks blocks up (cached,
generation-checked), fetches them from the owning node, and can land
them ONE-SIDED in its own `RmaBuffer` via the PR 10 direct-landing path
(`fetch(..., resp_buf=...)`) — zero receiver-side copies over shm/ici,
transparent striped-copy degradation over TCP.

Cache-coherence contract: a cached lookup is used until a fetch proves
it stale — the owning node validates generation AND lease at serve time
and answers kv-stale (KvStaleError) on any mismatch, which invalidates
the cached record, re-resolves it through the registry once, and
retries.  A lease that expires while a fetch is in flight therefore
never admits stale bytes; a chunk fault fails the call whole (the
landing buffer is never partially complete).

Typical prefill side::

    srv = Server(); srv.enable_kv_store(); srv.enable_kv_registry()
    srv.start(0)
    pages = RmaBuffer(64 << 20)
    ...fill pages.view...
    meta = kv.publish(1001, pages, length=4 << 20,
                      node=f"127.0.0.1:{srv.port}")
    reg = kv.KvRegistryClient(Channel(f"127.0.0.1:{srv.port}"))
    reg.register(meta)

Typical decode side::

    cli = kv.KvClient(registry_addr, use_shm=True)
    land = RmaBuffer(4 << 20)
    n = cli.fetch(1001, resp_buf=land.view)   # one-sided landing
"""

from __future__ import annotations

import ctypes
import dataclasses
import struct

from brpc_tpu.rpc._lib import load_library
from brpc_tpu.rpc.client import Channel, RpcError

# Wire form shared by every Kv RPC — MUST mirror cpp/net/kvstore.h
# KvWire (kv-wire marker: fixed little-endian, 112 bytes).
_WIRE = struct.Struct("<QQQQQq64s")
assert _WIRE.size == 112

# Prefix-cache wire form — MUST mirror cpp/net/kvstore.h KvPrefixWire
# (kv-wire marker: fixed little-endian, 144 bytes): key hi/lo, hash
# hi/lo, generation, rkey, off, len, lease_ms, depth, flags, node.
_PREFIX_WIRE = struct.Struct("<QQQQQQQQqII64s")
assert _PREFIX_WIRE.size == 144

FETCH_METHOD = "Kv.Fetch"
REGISTER_METHOD = "KvReg.Register"
LOOKUP_METHOD = "KvReg.Lookup"
EVICT_METHOD = "KvReg.Evict"
RENEW_METHOD = "KvReg.Renew"
PREFIX_PUT_METHOD = "KvReg.PutPrefix"
PREFIX_MATCH_METHOD = "KvReg.Match"
PREFIX_FETCH_METHOD = "Kv.FetchPrefix"


class KvError(RpcError):
    """Base of the kv error family (codes 2101..2103)."""


class KvMissError(KvError):
    """Block unknown (never registered, or lease expired and pruned)."""


class KvStaleError(KvError):
    """The caller's record is outdated — generation bumped, lease
    lapsed, or block evicted.  Cached lookups must invalidate."""


class KvExistsError(KvError):
    """Double-register of a live block (ownership is exclusive while
    the lease holds)."""


def _codes() -> tuple[int, int, int]:
    lib = load_library()
    miss = ctypes.c_int()
    stale = ctypes.c_int()
    exists = ctypes.c_int()
    lib.trpc_kv_codes(ctypes.byref(miss), ctypes.byref(stale),
                      ctypes.byref(exists))
    return miss.value, stale.value, exists.value


def _kv_error(e: RpcError) -> RpcError:
    miss, stale, exists = _codes()
    cls = {miss: KvMissError, stale: KvStaleError,
           exists: KvExistsError}.get(e.code)
    return cls(e.code, e.text) if cls is not None else e


@dataclasses.dataclass
class KvBlockMeta:
    """One registry record: where block_id's bytes live right now."""

    block_id: int
    generation: int
    rkey: int
    off: int
    length: int
    node: str = ""
    lease_left_ms: int = 0

    def pack(self, lease_ms: int = 0) -> bytes:
        return _WIRE.pack(self.block_id, self.generation, self.rkey,
                          self.off, self.length, lease_ms,
                          self.node.encode()[:63])

    @classmethod
    def unpack(cls, data: bytes) -> "KvBlockMeta":
        bid, gen, rkey, off, length, lease, node = _WIRE.unpack_from(data)
        return cls(bid, gen, rkey, off, length,
                   node.split(b"\0", 1)[0].decode(errors="replace"), lease)


def _req(block_id: int, generation: int = 0, lease_ms: int = 0) -> bytes:
    return _WIRE.pack(block_id, generation, 0, 0, 0, lease_ms, b"")


def publish(block_id: int, buffer, offset: int = 0, length: int | None = None,
            lease_ms: int = 0, node: str = "",
            min_generation: int = 0) -> KvBlockMeta:
    """Publishes `length` bytes at `offset` of an RmaBuffer into this
    process's block store (native, zero-copy serving) and returns the
    registry-ready record.  lease_ms <= 0 uses the trpc_kv_lease_ms
    default.  Raises KvExistsError while the block is live.
    min_generation floors the minted generation — a hot-restart
    successor (fresh pid) passes the predecessor's last registry
    generation + 1 so its takeover re-publish outranks every cached
    record (drain flow, cpp/net/naming.h)."""
    base = buffer.address if hasattr(buffer, "address") else \
        ctypes.addressof((ctypes.c_char * 0).from_buffer(buffer))
    size = buffer.nbytes if hasattr(buffer, "nbytes") else len(buffer)
    if length is None:
        length = size - offset
    if offset < 0 or length <= 0 or offset + length > size:
        raise ValueError(f"bad block range: off={offset} len={length} "
                         f"of {size}")
    lib = load_library()
    gen = ctypes.c_uint64()
    rkey = ctypes.c_uint64()
    off = ctypes.c_uint64()
    rc = lib.trpc_kv_publish_ex(
        ctypes.c_void_p(base + offset), ctypes.c_size_t(length),
        ctypes.c_uint64(block_id), ctypes.c_int64(lease_ms),
        ctypes.c_uint64(min_generation),
        ctypes.byref(gen), ctypes.byref(rkey), ctypes.byref(off))
    if rc != 0:
        miss, stale, exists = _codes()
        if rc == exists:
            raise KvExistsError(rc, f"block {block_id} is live")
        raise MemoryError(
            f"kv publish failed (rc={rc}): the bytes must lie inside an "
            "RmaBuffer and fit trpc_kv_store_bytes")
    return KvBlockMeta(block_id, gen.value, rkey.value, off.value, length,
                       node)


def withdraw(block_id: int) -> None:
    """Evicts a local block (its generation tombstones, so stale fetches
    stay detectable).  Raises KvMissError if unknown."""
    rc = load_library().trpc_kv_withdraw(ctypes.c_uint64(block_id))
    if rc != 0:
        raise KvMissError(rc, f"block {block_id} not in the local store")


def renew(block_id: int, lease_ms: int = 0) -> None:
    """Extends a local block's lease."""
    rc = load_library().trpc_kv_renew(ctypes.c_uint64(block_id),
                                      ctypes.c_int64(lease_ms))
    if rc != 0:
        raise KvMissError(rc, f"block {block_id} not in the local store")


def store_count() -> int:
    return int(load_library().trpc_kv_store_count())


def store_bytes_used() -> int:
    return int(load_library().trpc_kv_store_bytes_used())


def registry_count() -> int:
    return int(load_library().trpc_kv_registry_count())


def reset() -> None:
    """Test support: drops every local block and registry record."""
    load_library().trpc_kv_reset()


# ---- content-addressed prefix cache (ISSUE 17) ---------------------------


@dataclasses.dataclass
class KvPrefixMeta:
    """One prefix-block replica record: chain key (where in the trie),
    content hash (what bytes), and where this replica lives."""

    key_hi: int
    key_lo: int
    hash_hi: int
    hash_lo: int
    generation: int
    rkey: int = 0
    off: int = 0
    length: int = 0
    depth: int = 0
    node: str = ""
    lease_left_ms: int = 0
    flags: int = 0  # bit 0: replica currently cold (tier telemetry)

    @property
    def key(self) -> tuple[int, int]:
        return self.key_hi, self.key_lo

    @property
    def hash(self) -> tuple[int, int]:
        return self.hash_hi, self.hash_lo

    def pack(self, lease_ms: int = 0) -> bytes:
        return _PREFIX_WIRE.pack(self.key_hi, self.key_lo, self.hash_hi,
                                 self.hash_lo, self.generation, self.rkey,
                                 self.off, self.length, lease_ms,
                                 self.depth, self.flags,
                                 self.node.encode()[:63])

    @classmethod
    def unpack(cls, data: bytes, offset: int = 0) -> "KvPrefixMeta":
        (khi, klo, hhi, hlo, gen, rkey, off, length, lease, depth, flags,
         node) = _PREFIX_WIRE.unpack_from(data, offset)
        return cls(khi, klo, hhi, hlo, gen, rkey, off, length, depth,
                   node.split(b"\0", 1)[0].decode(errors="replace"),
                   lease, flags)


def _token_array(tokens):
    toks = list(tokens)
    return (ctypes.c_uint64 * max(len(toks), 1))(*toks), len(toks)


def content_hash(data, tokens=()) -> tuple[int, int]:
    """128-bit content hash of (block bytes, token-id span) — identical
    inputs hash identically in every process (the fleet dedup key)."""
    lib = load_library()
    buf = bytes(data)
    tok_arr, ntok = _token_array(tokens)
    hi = ctypes.c_uint64()
    lo = ctypes.c_uint64()
    lib.trpc_kv_content_hash(
        ctypes.cast(ctypes.c_char_p(buf), ctypes.c_void_p),
        ctypes.c_size_t(len(buf)), tok_arr, ctypes.c_size_t(ntok),
        ctypes.byref(hi), ctypes.byref(lo))
    return hi.value, lo.value


def prefix_chain(tokens, block_tokens: int = 0) -> list[tuple[int, int]]:
    """Chain keys for a token-id sequence: key_i names the WHOLE prefix
    through block i, so longest-prefix match is a walk until first miss.
    Only FULL block_tokens-sized blocks produce keys (the partial tail is
    never cacheable).  block_tokens <= 0 uses trpc_kv_prefix_block_tokens
    — every node must agree on it for keys to dedup."""
    lib = load_library()
    tok_arr, ntok = _token_array(tokens)
    if ntok == 0:
        return []
    keys = (ctypes.c_uint64 * (2 * ntok))()
    wrote = lib.trpc_kv_prefix_chain(tok_arr, ctypes.c_size_t(ntok),
                                     ctypes.c_int64(block_tokens), keys,
                                     ctypes.c_size_t(ntok))
    return [(keys[2 * i], keys[2 * i + 1]) for i in range(int(wrote))]


def prefix_publish(key: tuple[int, int], depth: int, data, tokens,
                   lease_ms: int = 0, node: str = "",
                   min_generation: int = 0) -> tuple[KvPrefixMeta, bool]:
    """Publishes one prefix block into the local two-tier store under its
    content hash (bytes are COPIED into store-owned registered pages —
    any buffer works, no RmaBuffer needed).  Returns (meta, fresh):
    fresh=False is the cache-hit path — identical content was already
    live, the lease renewed, and NO bytes were admitted (the caller's
    bytes-not-recomputed accounting)."""
    lib = load_library()
    buf = bytes(data)
    if not buf:
        raise ValueError("empty prefix block")
    tok_arr, ntok = _token_array(tokens)
    hash_hi = ctypes.c_uint64()
    hash_lo = ctypes.c_uint64()
    gen = ctypes.c_uint64()
    rkey = ctypes.c_uint64()
    off = ctypes.c_uint64()
    rc = lib.trpc_kv_prefix_publish(
        ctypes.c_uint64(key[0]), ctypes.c_uint64(key[1]),
        ctypes.c_uint32(depth),
        ctypes.cast(ctypes.c_char_p(buf), ctypes.c_void_p),
        ctypes.c_size_t(len(buf)), tok_arr, ctypes.c_size_t(ntok),
        ctypes.c_int64(lease_ms), ctypes.c_uint64(min_generation),
        ctypes.byref(hash_hi), ctypes.byref(hash_lo), ctypes.byref(gen),
        ctypes.byref(rkey), ctypes.byref(off))
    _miss, _stale, exists = _codes()
    if rc != 0 and rc != exists:
        raise MemoryError(
            f"kv prefix publish failed (rc={rc}): the block must fit "
            "trpc_kv_store_bytes")
    meta = KvPrefixMeta(key[0], key[1], hash_hi.value, hash_lo.value,
                        gen.value, rkey.value, off.value, len(buf), depth,
                        node)
    return meta, rc == 0


def prefix_withdraw(hash_key: tuple[int, int]) -> None:
    """Evicts a local prefix block by content hash (tombstoned)."""
    rc = load_library().trpc_kv_prefix_withdraw(
        ctypes.c_uint64(hash_key[0]), ctypes.c_uint64(hash_key[1]))
    if rc != 0:
        raise KvMissError(rc, "prefix block not in the local store")


def prefix_store_count() -> int:
    return int(load_library().trpc_kv_prefix_store_count())


def prefix_hot_bytes() -> int:
    return int(load_library().trpc_kv_prefix_hot_bytes())


def prefix_cold_bytes() -> int:
    return int(load_library().trpc_kv_prefix_cold_bytes())


def prefix_registry_count() -> int:
    return int(load_library().trpc_kv_prefix_registry_count())


def prefix_registry_replicas() -> int:
    return int(load_library().trpc_kv_prefix_registry_replicas())


def prefix_counters() -> dict[str, int]:
    """Prefix-tier outcome counters since process start (promote,
    demote, hot_hits, cold_hits, dedup)."""
    lib = load_library()
    vals = [ctypes.c_uint64() for _ in range(5)]
    lib.trpc_kv_prefix_counters(*[ctypes.byref(v) for v in vals])
    return dict(zip(("promote", "demote", "hot_hits", "cold_hits",
                     "dedup"), (v.value for v in vals)))


class KvRegistryClient:
    """Thin RPC client for the registry methods over one channel."""

    def __init__(self, channel: Channel, owns_channel: bool = False):
        self._ch = channel
        self._owns = owns_channel

    def register(self, meta: KvBlockMeta, lease_ms: int = 0) -> int:
        """Records meta under a lease; returns the accepted generation.
        Raises KvExistsError while a live record holds the block."""
        try:
            resp = self._ch.call(REGISTER_METHOD, meta.pack(lease_ms))
        except RpcError as e:
            raise _kv_error(e) from None
        return struct.unpack("<Q", resp)[0]

    def lookup(self, block_id: int) -> KvBlockMeta:
        try:
            resp = self._ch.call(LOOKUP_METHOD, _req(block_id))
        except RpcError as e:
            raise _kv_error(e) from None
        return KvBlockMeta.unpack(resp)

    def evict(self, block_id: int) -> int:
        """Removes the record; returns the evicted generation."""
        try:
            resp = self._ch.call(EVICT_METHOD, _req(block_id))
        except RpcError as e:
            raise _kv_error(e) from None
        return struct.unpack("<Q", resp)[0]

    def renew(self, block_id: int, lease_ms: int = 0) -> int:
        """Extends a live record's lease; returns its generation."""
        try:
            resp = self._ch.call(RENEW_METHOD,
                                 _req(block_id, lease_ms=lease_ms))
        except RpcError as e:
            raise _kv_error(e) from None
        return struct.unpack("<Q", resp)[0]

    def put_prefix(self, meta: KvPrefixMeta,
                   lease_ms: int = 0) -> tuple[int, bool]:
        """Records one prefix-block replica; N publishers of the same
        chain key + content hash fold into ONE record with a replica
        set.  Returns (generation, fresh): fresh=False means the
        registry already held this exact replica and only renewed its
        lease (the idempotent re-offer every cache hit makes)."""
        try:
            resp = self._ch.call(PREFIX_PUT_METHOD, meta.pack(lease_ms))
        except RpcError as e:
            e = _kv_error(e)
            if isinstance(e, KvExistsError):
                return meta.generation, False
            raise e from None
        return struct.unpack("<Q", resp)[0], True

    def match(self, keys) -> list[KvPrefixMeta]:
        """Longest cached prefix: one replica record per live replica of
        every matched chain key, grouped in chain order (the walk stops
        at the first key with no live replica).  Empty list = nothing
        cached."""
        keys = list(keys)
        if not keys:
            return []
        req = struct.pack("<Q", len(keys)) + b"".join(
            struct.pack("<QQ", hi, lo) for hi, lo in keys)
        try:
            resp = self._ch.call(PREFIX_MATCH_METHOD, req)
        except RpcError as e:
            raise _kv_error(e) from None
        (count,) = struct.unpack_from("<Q", resp)
        return [KvPrefixMeta.unpack(resp, 8 + i * _PREFIX_WIRE.size)
                for i in range(count)]

    def close(self) -> None:
        if self._owns:
            self._ch.close()


class KvClient:
    """Decode-side client: registry lookups cached with generation-
    checked invalidation, per-node channel pool, one-sided landings.

    `fetch(block_id)` returns the bytes; `fetch(block_id, resp_buf=v)`
    lands them natively in `v` (an RmaBuffer view for the one-sided
    path) and returns the landed length.  A kv-stale answer invalidates
    the cached record, re-resolves, and retries once."""

    def __init__(self, registry_addr: str, use_shm: bool = True,
                 timeout_ms: int = 30000, qos_tenant: str = "",
                 qos_priority: int = 0, naming_addr: str | None = None,
                 naming_service: str = "kv"):
        self._use_shm = use_shm
        self._timeout_ms = timeout_ms
        self._qos = (qos_tenant, qos_priority)
        self._reg_ch = Channel(registry_addr, timeout_ms=timeout_ms,
                               qos_tenant=qos_tenant,
                               qos_priority=qos_priority)
        self.registry = KvRegistryClient(self._reg_ch)
        self._node_chs: dict[str, Channel] = {}
        self._cache: dict[int, KvBlockMeta] = {}
        # Optional cluster-membership view (cpp/net/naming.h registry at
        # naming_addr, service naming_service): when a fetch fails at the
        # TRANSPORT level and the cached node has left the fleet (drained
        # or died), the dead channel is dropped and the record re-resolves
        # through the registry instead of retrying a dead pid.
        self._naming = None
        self._naming_args = (naming_addr, naming_service)
        #: Lookup-cache telemetry (reads served without a registry RPC /
        #: registry round-trips / stale-triggered invalidations).
        self.cache_hits = 0
        self.cache_misses = 0
        self.invalidations = 0
        #: Fetches re-routed because the naming view said the cached
        #: node is gone (drain/crash re-resolution telemetry).
        self.node_reresolves = 0
        #: Pooled node channels dropped because their node left the
        #: naming view (the pool must not grow with membership churn).
        self.channels_evicted = 0

    #: Pool size at which creating a NEW node channel first prunes
    #: channels whose nodes left the naming view — bounds the pool to
    #: (live members + a little churn slack) instead of every node that
    #: ever served a block.
    _POOL_PRUNE_AT = 4

    def _prune_gone_channels(self) -> None:
        """Evicts pooled channels for nodes absent from the naming view
        (one resolve for the whole sweep; no view configured or registry
        unreachable = no verdict, keep everything)."""
        naming_addr, service = self._naming_args
        if naming_addr is None:
            return
        if self._naming is None:
            from brpc_tpu.rpc import naming as _naming

            self._naming = _naming.NamingClient(naming_addr,
                                                timeout_ms=self._timeout_ms)
        try:
            _version, members = self._naming.resolve(service)
        except RpcError:
            return
        live = {m.addr for m in members}
        for node in [n for n in self._node_chs if n not in live]:
            self._node_chs.pop(node).close()
            self.channels_evicted += 1

    def _node_channel(self, node: str) -> Channel:
        ch = self._node_chs.get(node)
        if ch is None:
            if len(self._node_chs) >= self._POOL_PRUNE_AT:
                # The pool is about to grow past the prune threshold:
                # drop channels for departed nodes first so membership
                # churn can't grow it unboundedly.
                self._prune_gone_channels()
            tenant, prio = self._qos
            # shm rings are single-connection by construction; TCP block
            # pulls spread over pooled sockets (stripe rails).
            ch = Channel(node, timeout_ms=self._timeout_ms,
                         use_shm=self._use_shm,
                         connection_type="single" if self._use_shm
                         else "pooled",
                         qos_tenant=tenant, qos_priority=prio)
            self._node_chs[node] = ch
        return ch

    def lookup(self, block_id: int, refresh: bool = False) -> KvBlockMeta:
        if not refresh:
            meta = self._cache.get(block_id)
            if meta is not None:
                self.cache_hits += 1
                return meta
        self.cache_misses += 1
        meta = self.registry.lookup(block_id)
        self._cache[block_id] = meta
        return meta

    def invalidate(self, block_id: int) -> None:
        if self._cache.pop(block_id, None) is not None:
            self.invalidations += 1

    def _node_gone(self, node: str) -> bool:
        """True when the naming view is configured AND `node` is not a
        member of it (the owner drained or died — its withdrawn/expired
        announcement is the authoritative 'do not retry this pid')."""
        naming_addr, service = self._naming_args
        if naming_addr is None:
            return False
        if self._naming is None:
            from brpc_tpu.rpc import naming as _naming

            self._naming = _naming.NamingClient(naming_addr,
                                                timeout_ms=self._timeout_ms)
        try:
            _version, members = self._naming.resolve(service)
        except RpcError:
            return False  # registry unreachable: no verdict, keep the node
        return all(m.addr != node for m in members)

    def fetch(self, block_id: int, resp_buf=None):
        """Bytes of block_id (or the landed length with resp_buf)."""
        last: RpcError | None = None
        # With a naming view a third attempt is budgeted: transport-dead
        # node -> drop channel + re-resolve -> fetch the re-published
        # block from its new owner.
        attempts = 3 if self._naming_args[0] is not None else 2
        for attempt in range(attempts):
            meta = self.lookup(block_id, refresh=attempt > 0)
            req = _req(block_id, generation=meta.generation)
            ch = self._node_channel(meta.node)
            try:
                if resp_buf is None:
                    return ch.call(FETCH_METHOD, req,
                                   timeout_ms=self._timeout_ms)
                return self._fetch_into(ch, req, resp_buf)
            except RpcError as e:
                e = _kv_error(e)
                if isinstance(e, (KvStaleError, KvMissError)):
                    last = e
                    self.invalidate(block_id)  # generation-checked
                    continue
                # Transport/chaos failure: the record MAY be fine — but
                # if the naming view says the owner left the fleet, the
                # dead channel must not be retried (it would only time
                # out again): drop it and re-resolve through the
                # registry, which the new owner re-publishes into.
                if attempt + 1 < attempts and self._node_gone(meta.node):
                    dead = self._node_chs.pop(meta.node, None)
                    if dead is not None:
                        dead.close()
                    self.invalidate(block_id)
                    self.node_reresolves += 1
                    last = e
                    continue
                raise
        raise last

    def _fetch_into(self, ch: Channel, req: bytes, resp_buf) -> int:
        """One fetch whose response lands natively in resp_buf (the
        one-sided direct path when resp_buf is RmaBuffer-backed and the
        node connection is shm/ici)."""
        pipe = ch.pipeline()
        try:
            pipe.submit(FETCH_METHOD, [req], resp_bufs=[resp_buf],
                        timeout_ms=self._timeout_ms)
            cs = pipe.poll(max_n=1, timeout_ms=self._timeout_ms)
            if not cs:
                raise RpcError(-1, "kv fetch timed out in poll")
            c = cs[0]
            if not c.ok:
                raise _kv_error(RpcError(c.status, c.error))
            if not c.in_caller_buffer and c.data is not None:
                # Copy-path degradation where the runtime returned a
                # view instead of landing in place (tiny responses).
                view = memoryview(resp_buf).cast("B")
                view[:c.resp_len] = c.data.view()[:c.resp_len]
                c.data.release()
            return c.resp_len
        finally:
            pipe.close()

    # ---- content-addressed prefix cache (ISSUE 17) ----

    def match_prefix(self, tokens,
                     block_tokens: int = 0) -> list[list[KvPrefixMeta]]:
        """Longest cached prefix for `tokens`: replica groups in chain
        order (groups[i] = every live replica of prefix block i).  An
        empty list means nothing is cached — full recompute."""
        keys = prefix_chain(tokens, block_tokens)
        if not keys:
            return []
        records = self.registry.match(keys)
        groups: list[list[KvPrefixMeta]] = []
        cur = None
        for r in records:
            if r.key != cur:
                groups.append([])
                cur = r.key
            groups[-1].append(r)
        return groups

    @staticmethod
    def prefix_hint(groups: list[list[KvPrefixMeta]]) -> str:
        """The routing hint for this prompt: the node holding the
        DEEPEST matched block ("host:port", "" when nothing matched).
        Pass it to ClusterChannel.call(..., hint=...) so decode/prefill
        traffic lands where the cache already is — unless bounded load
        vetoes."""
        return groups[-1][0].node if groups else ""

    def fetch_prefix(self, tokens, block_tokens: int = 0) -> list[bytes]:
        """Fetches every cached prefix block for `tokens` in chain
        order, failing over across replicas: a replica that answers
        stale/faulted serves nothing (whole-or-nothing per block) and
        the next replica is tried.  The returned list may be shorter
        than the match when every replica of a block fails — the
        cacheable prefix simply ends there (callers recompute the
        rest)."""
        blocks: list[bytes] = []
        for group in self.match_prefix(tokens, block_tokens):
            data = None
            for rep in group:
                ch = self._node_channel(rep.node)
                try:
                    data = ch.call(PREFIX_FETCH_METHOD, rep.pack(),
                                   timeout_ms=self._timeout_ms)
                    break
                except RpcError:
                    # Stale, chunk-faulted, or dead replica: the block
                    # is never admitted partially — try the next one.
                    continue
            if data is None:
                break
            blocks.append(data)
        return blocks

    def close(self) -> None:
        for ch in self._node_chs.values():
            ch.close()
        self._node_chs.clear()
        if self._naming is not None:
            self._naming.close()
            self._naming = None
        self._reg_ch.close()
