"""Cluster naming-service client — push-based membership from Python.

The Python surface of cpp/net/naming.h: any Server can host the registry
(``Server.enable_naming_registry()``); nodes announce ``{addr, zone,
weight, epoch}`` under the same lease semantics as the KV registry
(expired = gone, epoch-checked re-announce — an OLDER epoch is a zombie
and is rejected), and clients either poll ``resolve`` or park a
``watch`` long-poll that answers the moment membership changes.

A ``ClusterChannel("naming://registry_host:port/service", ...)`` wires
all of this in natively: the C++ watch fiber turns registry version
bumps into immediate refreshes, so adds/removals/weight changes apply
without reconnect storms, and a draining node's withdrawal re-balances
traffic before its listener handoff even starts.

Typical node side::

    srv = Server(); srv.register_native_echo()
    srv.start(0)
    srv.announce(f"127.0.0.1:{registry_port}", "echo", zone="z1")

Typical client side::

    ch = ClusterChannel(f"naming://127.0.0.1:{registry_port}/echo",
                        lb="zone_la")

This module is the thin RPC client for tests/tools that need the raw
registry view (the orchestrator's drain assertions, membership dumps).
"""

from __future__ import annotations

import ctypes
import dataclasses
import struct
import time

from brpc_tpu.rpc._lib import load_library
from brpc_tpu.rpc.client import Channel, RpcError

# Wire form shared by every Naming RPC — MUST mirror cpp/net/naming.h
# NamingWire (naming-wire marker: fixed little-endian, 176 bytes).
_WIRE = struct.Struct("<64s64s16siIQqQ")
assert _WIRE.size == 176

ANNOUNCE_METHOD = "Naming.Announce"
WITHDRAW_METHOD = "Naming.Withdraw"
RESOLVE_METHOD = "Naming.Resolve"
WATCH_METHOD = "Naming.Watch"
PUBLISH_METHOD = "Naming.Publish"
STATS_METHOD = "Naming.Stats"


class NamingError(RpcError):
    """Base of the naming error family (codes 2111..2112)."""


class NamingStaleEpochError(NamingError):
    """The announce/withdraw carried an epoch OLDER than the recorded
    member's — the caller is a zombie predecessor of a restarted node."""


class NamingMissError(NamingError):
    """Unknown service (never announced and nobody watching)."""


def _codes() -> tuple[int, int]:
    lib = load_library()
    stale = ctypes.c_int()
    miss = ctypes.c_int()
    lib.trpc_naming_codes(ctypes.byref(stale), ctypes.byref(miss))
    return stale.value, miss.value


def _naming_error(e: RpcError) -> RpcError:
    stale, miss = _codes()
    cls = {stale: NamingStaleEpochError, miss: NamingMissError}.get(e.code)
    return cls(e.code, e.text) if cls is not None else e


@dataclasses.dataclass
class Member:
    """One member of a named service, as the registry sees it."""

    addr: str
    zone: str = ""
    weight: int = 1
    epoch: int = 0
    lease_left_ms: int = 0


@dataclasses.dataclass
class StatsRecord:
    """One member's stats row (Naming.Stats): membership identity plus
    the opaque publication payload it last attached — for the fleet
    observability plane, a digest-wire 2 blob (observe.fleet_blob_decode
    reads it).  age_ms is how stale the payload is (-1 = never
    published)."""

    member: Member
    age_ms: int = -1
    payload: bytes = b""


def _pack(service: str, addr: str = "", zone: str = "", weight: int = 0,
          epoch: int = 0, lease_ms: int = 0, version: int = 0) -> bytes:
    return _WIRE.pack(service.encode()[:63], addr.encode()[:63],
                      zone.encode()[:15], weight, 0, epoch, lease_ms,
                      version)


def _unpack_view(data: bytes) -> tuple[int, list[Member]]:
    (_svc, _addr, _zone, count, _res, _epoch, _lease,
     version) = _WIRE.unpack_from(data)
    members = []
    for i in range(1, count + 1):
        (_s, addr, zone, weight, _r, epoch, lease,
         _v) = _WIRE.unpack_from(data, i * _WIRE.size)
        members.append(Member(
            addr.split(b"\0", 1)[0].decode(errors="replace"),
            zone.split(b"\0", 1)[0].decode(errors="replace"),
            weight, epoch, lease))
    return version, members


def mint_epoch() -> int:
    """A fresh announce epoch: realtime µs, strictly newer across
    restarts of the same endpoint (what the native Announcer mints)."""
    return time.time_ns() // 1000


class NamingClient:
    """Thin RPC client for the registry methods over one channel."""

    def __init__(self, registry_addr: str, timeout_ms: int = 2000):
        self._ch = Channel(registry_addr, timeout_ms=timeout_ms)
        self._timeout_ms = timeout_ms

    def announce(self, service: str, addr: str, zone: str = "",
                 weight: int = 1, epoch: int = 0, lease_ms: int = 0) -> int:
        """Announces (or renews: same epoch) a member.  Returns the epoch
        used (minted when 0).  Raises NamingStaleEpochError when a newer
        epoch holds the addr (this caller is the zombie)."""
        epoch = epoch or mint_epoch()
        try:
            self._ch.call(ANNOUNCE_METHOD,
                          _pack(service, addr, zone, weight, epoch,
                                lease_ms))
        except RpcError as e:
            raise _naming_error(e) from None
        return epoch

    def withdraw(self, service: str, addr: str, epoch: int) -> None:
        """Removes the member (idempotent — an already-gone member is the
        goal state).  Raises NamingStaleEpochError when a LIVE record
        holds a newer epoch."""
        try:
            self._ch.call(WITHDRAW_METHOD, _pack(service, addr, epoch=epoch))
        except RpcError as e:
            raise _naming_error(e) from None

    def resolve(self, service: str) -> tuple[int, list[Member]]:
        """(version, members) — the poll fallback."""
        try:
            resp = self._ch.call(RESOLVE_METHOD, _pack(service))
        except RpcError as e:
            raise _naming_error(e) from None
        return _unpack_view(resp)

    def watch(self, service: str, version: int = 0,
              park_ms: int = 1000) -> tuple[int, list[Member]]:
        """Long-poll: parks server-side until the membership version
        differs from `version` (or park_ms passes), then answers the
        full view — the push path.  Loop it: ``version, members =
        nc.watch(svc, version)``."""
        try:
            resp = self._ch.call(
                WATCH_METHOD,
                _pack(service, lease_ms=park_ms, version=version),
                timeout_ms=park_ms + self._timeout_ms)
        except RpcError as e:
            raise _naming_error(e) from None
        return _unpack_view(resp)

    def publish(self, service: str, addr: str, epoch: int,
                payload: bytes) -> None:
        """Attaches an opaque stats payload to a LIVE member record —
        the fleet observability publication path (the native Announcer
        does this every renew round under trpc_fleet_publish).  Same
        fencing as announce: the member must exist (lease un-expired,
        NamingMissError otherwise) and `epoch` must be no older than the
        recorded one (NamingStaleEpochError — a zombie predecessor can't
        overwrite its successor's stats).  Payloads die with the member
        and do NOT bump the membership version (watchers stay parked)."""
        try:
            self._ch.call(PUBLISH_METHOD,
                          _pack(service, addr, epoch=epoch) + payload)
        except RpcError as e:
            raise _naming_error(e) from None

    def stats(self, service: str) -> tuple[int, list[StatsRecord]]:
        """(version, records): every live member with its last published
        payload, sorted by addr — what /fleet and tools/fleet_top.py
        merge.  Raises NamingMissError for an unknown service."""
        try:
            resp = self._ch.call(STATS_METHOD, _pack(service))
        except RpcError as e:
            raise _naming_error(e) from None
        (_svc, _addr, _zone, count, _res, _epoch, _lease,
         version) = _WIRE.unpack_from(resp)
        records = []
        pos = _WIRE.size
        for _ in range(max(count, 0)):
            (_s, addr, zone, weight, _r, epoch, age_ms,
             _v) = _WIRE.unpack_from(resp, pos)
            pos += _WIRE.size
            (plen,) = struct.unpack_from("<Q", resp, pos)
            pos += 8
            payload = bytes(resp[pos:pos + plen])
            pos += plen
            records.append(StatsRecord(
                member=Member(
                    addr.split(b"\0", 1)[0].decode(errors="replace"),
                    zone.split(b"\0", 1)[0].decode(errors="replace"),
                    weight, epoch),
                age_ms=age_ms, payload=payload))
        return version, records

    def close(self) -> None:
        self._ch.close()


def local_member_count(service: str) -> int:
    """Members of `service` in THIS process's registry (test support)."""
    return int(load_library().trpc_naming_member_count(service.encode()))


def reset() -> None:
    """Test support: drops every service from the local registry."""
    load_library().trpc_naming_reset()
