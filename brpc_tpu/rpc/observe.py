"""In-process observability: vars, latency recorders, rpcz spans, traces.

Everything the builtin HTTP pages (/vars, /brpc_metrics, /rpcz) show is
readable here WITHOUT a server or an HTTP round-trip — a bare client
process has the same registry and span ring the serving processes do
(the ISSUE 4 tentpole: the reference jails bvar/rpcz behind builtin
pages; this module is the ctypes surface over `cpp/capi/observe_capi.cc`).

Three capability groups:

- **Read**: `Vars.dump()` / `Vars.read()` / `Vars.prometheus()` over the
  shared variable registry; `Latency.read(name)` for any registered
  recorder's window (count/qps/avg/p50/p90/p99/p999/max — e.g. a server
  method's `rpc_server_Echo.Echo` or a channel's `rpc_client_<addr>`);
  `spans()` / `rpcz_dump()` over the rpcz ring.
- **Register**: `Latency(name)` and `Gauge(name)` create NATIVE metrics
  owned by Python but living in the same registry, so client-side series
  appear in /vars and /brpc_metrics exactly like server methods do.
- **Trace**: `trace()` opens a span, installs it as the ambient trace
  context (fiber- or thread-local) so every RPC issued inside the block —
  sync calls, batch submits, nested hops across nodes — shares one
  trace_id; `annotate()` drops user timeline marks into the span.
  `get_trace()`/`set_trace()`/`clear_trace()` move the raw context across
  custom boundaries (queues, threads, processes).

Span collection for the AUTOMATIC per-RPC spans is gated by the
reloadable `rpcz_enabled` flag (`enable_rpcz()`); explicit `trace()`
spans always record.  When rpcz is off the plane costs nothing on the
hot path (guarded by test_perf_smoke).
"""

from __future__ import annotations

import ctypes
import json
import math
import struct
from dataclasses import dataclass, field

from brpc_tpu.rpc._lib import load_library
from brpc_tpu.rpc.flags import get_flag, set_flag


def _dump_with_retry(call, initial: int = 1 << 16) -> bytes:
    """Runs a size_t-returning dump C call, growing the buffer until the
    full rendering fits (the C side returns the FULL length)."""
    size = initial
    while True:
        out = ctypes.create_string_buffer(size)
        need = call(out, size)
        if need < size:
            return out.raw[:need]
        size = need + 1


# ---------------------------------------------------------------- vars ----


class Vars:
    """The shared variable registry (the /vars page, in-process)."""

    @staticmethod
    def dump() -> dict:
        """Every exposed variable: {name: float-or-str} (numeric values
        parse to numbers, structured ones — e.g. latency recorders' JSON
        summaries — stay strings)."""
        lib = load_library()
        raw = _dump_with_retry(
            lambda buf, n: lib.trpc_vars_dump(0, buf, n))
        return json.loads(raw.decode())

    @staticmethod
    def read(name: str):
        """One variable's value (float when numeric, parsed dict for
        latency-recorder summaries, str otherwise); KeyError if absent."""
        lib = load_library()
        size = 256
        while True:
            out = ctypes.create_string_buffer(size)
            rc = lib.trpc_var_read(name.encode(), out, size)
            if rc == 0:
                text = out.value.decode()
                break
            if rc == -2 and size < 1 << 24:
                size *= 4
                continue
            raise KeyError(name)
        try:
            return json.loads(text)
        except json.JSONDecodeError:
            return text

    @staticmethod
    def prometheus() -> str:
        """The full Prometheus text exposition (the /brpc_metrics body)."""
        lib = load_library()
        return _dump_with_retry(
            lambda buf, n: lib.trpc_vars_dump(1, buf, n)).decode()


# ---------------------------------------------------------------- flags ----


def flags() -> list[dict]:
    """Every runtime flag with its introspection record: {"name",
    "type", "value", "default", "reloadable"} plus "min"/"max" where
    the flag declared numeric bounds (base/flags.h set_int_range) — the
    same body /flags?format=json serves.  Tools (and the self-tuning
    controller) read actuation bounds from here instead of guessing, so
    out-of-range writes are impossible by construction."""
    lib = load_library()
    raw = _dump_with_retry(lambda buf, n: lib.trpc_flags_dump(buf, n))
    return json.loads(raw.decode())


# ------------------------------------------------------------- latency ----


def unique_var_name(base: str) -> str:
    """First unregistered name among base, base#2, base#3...  expose()
    silently REPLACES the previous owner of a name, so two live owners
    (e.g. two Channels to one address) must not share a slot: the second
    would shadow the first and closing it would erase the series.  Best
    effort — a concurrent registration can still race the probe."""
    lib = load_library()
    name = base
    k = 1
    while lib.trpc_var_exists(name.encode()):
        k += 1
        name = f"{base}#{k}"
    return name


@dataclass(frozen=True)
class LatencyStats:
    """One recorder's trailing window + cumulative count."""

    count: int
    qps: int
    avg_us: int
    p50_us: int
    p90_us: int
    p99_us: int
    p999_us: int
    max_us: int


class Latency:
    """A native latency recorder registered under `name` (per-second
    windows + octave-bucketed percentiles, the same machinery behind the
    server's per-method recorders).  `record(us)` feeds it; `stats()`
    reads it.  Use the classmethod `read(name)` to read a recorder
    registered by anyone (server methods, channels, other modules)."""

    def __init__(self, name: str, description: str = ""):
        self._lib = load_library()
        self.name = name
        self._ptr = self._lib.trpc_latency_create(
            name.encode(), description.encode())
        if not self._ptr:
            raise ValueError(f"bad recorder name: {name!r}")

    @classmethod
    def read(cls, name: str) -> LatencyStats:
        """Reads ANY registered latency recorder by name (KeyError when
        absent, TypeError when the var is not a latency recorder)."""
        lib = load_library()
        out = (ctypes.c_double * 8)()
        rc = lib.trpc_latency_read(name.encode(), out)
        if rc == -1:
            raise KeyError(name)
        if rc != 0:
            raise TypeError(f"{name!r} is not a latency recorder")
        return LatencyStats(*(int(v) for v in out))

    def record(self, latency_us: int) -> None:
        if self._ptr:
            self._lib.trpc_latency_record(
                ctypes.c_void_p(self._ptr), int(latency_us))

    def stats(self) -> LatencyStats:
        return self.read(self.name)

    def close(self) -> None:
        ptr, self._ptr = self._ptr, None
        if ptr:
            self._lib.trpc_latency_destroy(ctypes.c_void_p(ptr))

    def __del__(self):
        try:
            self.close()
        except Exception:  # noqa: BLE001 — interpreter teardown
            pass


class Gauge:
    """A native scalar gauge registered under `name` (pipeline depth,
    inflight counts, window sizes — levels, not event counts)."""

    def __init__(self, name: str, description: str = ""):
        self._lib = load_library()
        self.name = name
        self._ptr = self._lib.trpc_gauge_create(
            name.encode(), description.encode())
        if not self._ptr:
            raise ValueError(f"bad gauge name: {name!r}")

    def set(self, value: int) -> None:
        if self._ptr:
            self._lib.trpc_gauge_set(ctypes.c_void_p(self._ptr), int(value))

    def add(self, delta: int = 1) -> int:
        if not self._ptr:
            return 0
        return self._lib.trpc_gauge_add(
            ctypes.c_void_p(self._ptr), int(delta))

    def close(self) -> None:
        ptr, self._ptr = self._ptr, None
        if ptr:
            self._lib.trpc_gauge_destroy(ctypes.c_void_p(ptr))

    def __del__(self):
        try:
            self.close()
        except Exception:  # noqa: BLE001 — interpreter teardown
            pass


# ---------------------------------------------------------------- rpcz ----


@dataclass
class Span:
    """One finished rpcz span (ids are 16-hex-digit strings — 64-bit
    values that would truncate as floats)."""

    trace_id: str
    span_id: str
    parent_span_id: str
    side: str  # "client" | "server"
    method: str
    start_us: int
    end_us: int
    latency_us: int
    error_code: int
    request_bytes: int
    response_bytes: int
    annotations: list = field(default_factory=list)  # [(ts_us, text)]
    # Fiber the span ran on (16-hex digits; all zeros off-fiber) — the
    # exact join key onto timeline fiber_run/fiber_park events.
    fid: str = "0" * 16

    @classmethod
    def from_dict(cls, d: dict) -> "Span":
        return cls(
            trace_id=d["trace_id"], span_id=d["span_id"],
            parent_span_id=d["parent_span_id"], side=d["side"],
            method=d["method"], start_us=int(d["start_us"]),
            end_us=int(d["end_us"]), latency_us=int(d["latency_us"]),
            error_code=int(d["error_code"]),
            request_bytes=int(d["request_bytes"]),
            response_bytes=int(d["response_bytes"]),
            annotations=[(int(a["ts_us"]), a["text"])
                         for a in d.get("annotations", [])],
            fid=d.get("fid", "0" * 16),
        )


def _trace_id_int(trace_id) -> int:
    if trace_id is None:
        return 0
    if isinstance(trace_id, str):
        return int(trace_id, 16)
    return int(trace_id)


def rpcz_dump(limit: int = 200, trace_id=None) -> dict:
    """The raw structured rpcz dump for THIS process — the same shape
    `/rpcz?format=json` serves: {"pid", "now_mono_us", "now_wall_us",
    "spans": [...]} (the clock pair lets tools/trace_stitch.py place this
    node's spans on a wall-clock timeline next to other nodes')."""
    lib = load_library()
    tid = _trace_id_int(trace_id)
    raw = _dump_with_retry(
        lambda buf, n: lib.trpc_rpcz_dump(limit, tid, 0, buf, n))
    return json.loads(raw.decode())


def spans(limit: int = 200, trace_id=None) -> list[Span]:
    """Recent spans, newest first; `trace_id` (int or hex str) filters."""
    return [Span.from_dict(d)
            for d in rpcz_dump(limit, trace_id)["spans"]]


def enable_rpcz(on: bool = True) -> None:
    """Flips automatic per-RPC span collection (the `rpcz_enabled`
    reloadable flag; off by default — the hot path pays nothing)."""
    set_flag("rpcz_enabled", "true" if on else "false")


def rpcz_enabled() -> bool:
    return get_flag("rpcz_enabled") == "true"


# ------------------------------------------------------------- timeline ----


# Decoder side of the flight recorder's event-type table
# (cpp/stat/timeline.h kEventNames).  tools/lint_trpc.py's timeline-event
# rule keeps BOTH tables in lockstep via the `timeline-event N (name)`
# markers: ids must be unique, consecutive from 1, and identical on the
# C++ encoder and this decoder.  Ids are APPEND-ONLY — a recorded binary
# dump must stay decodable by a newer reader.
TIMELINE_EVENTS = {
    1: "fiber_create",    # timeline-event 1 (fiber_create)
    2: "fiber_ready",     # timeline-event 2 (fiber_ready)
    3: "fiber_run",       # timeline-event 3 (fiber_run)
    4: "fiber_park",      # timeline-event 4 (fiber_park)
    5: "fiber_wake",      # timeline-event 5 (fiber_wake)
    6: "fiber_steal",     # timeline-event 6 (fiber_steal)
    7: "fiber_migrate",   # timeline-event 7 (fiber_migrate)
    8: "fiber_done",      # timeline-event 8 (fiber_done)
    9: "sweep_start",     # timeline-event 9 (sweep_start)
    10: "sweep_end",      # timeline-event 10 (sweep_end)
    11: "inline_begin",   # timeline-event 11 (inline_begin)
    12: "inline_end",     # timeline-event 12 (inline_end)
    13: "bulk_wake",      # timeline-event 13 (bulk_wake)
    14: "write_flush",    # timeline-event 14 (write_flush)
    15: "writer_handoff",  # timeline-event 15 (writer_handoff)
    16: "write_coalesce",  # timeline-event 16 (write_coalesce)
    17: "stripe_cut",     # timeline-event 17 (stripe_cut)
    18: "stripe_send",    # timeline-event 18 (stripe_send)
    19: "stripe_land",    # timeline-event 19 (stripe_land)
    20: "stripe_done",    # timeline-event 20 (stripe_done)
    21: "qos_drain",      # timeline-event 21 (qos_drain)
    22: "kv_block",       # timeline-event 22 (kv_block)
    23: "coll_step",      # timeline-event 23 (coll_step)
    24: "tuner_decision",  # timeline-event 24 (tuner_decision)
    25: "deadline",       # timeline-event 25 (deadline)
    26: "capture",        # timeline-event 26 (capture)
    27: "coll_ready",     # timeline-event 27 (coll_ready)
    28: "slo_breach",     # timeline-event 28 (slo_breach)
    29: "token_step",     # timeline-event 29 (token_step)
}

# kCapture `b` op tags (cpp/stat/capture.cc: b = op << 56 | request
# bytes, or records written for "dump") — traffic-capture reservoir
# keep/drop decisions and file dumps.
TIMELINE_CAPTURE_OPS = {1: "keep", 2: "drop", 3: "dump"}

# kKvBlock `b` op tags (cpp/net/kvstore.h: b = op << 56 | payload len) —
# how a kv_block event reads: the store published / served / evicted a
# block, rejected a stale-generation fetch, or moved a prefix block
# between the hot (registered) and cold (heap) tiers.
TIMELINE_KV_OPS = {1: "publish", 2: "serve", 3: "evict", 4: "stale",
                   5: "promote", 6: "demote"}

# kCollStep `b` op tags (cpp/net/collective.h CollOp: b = op << 56 |
# step bytes; a = step index) — one event per completed collective
# schedule step on the member that completed it.
TIMELINE_COLL_OPS = {1: "all_gather", 2: "reduce_scatter",
                     3: "all_to_all", 4: "reshard"}

# kSloBreach `b` op tags (cpp/stat/slo.cc: b = op << 56 | fast-window
# burn rate in milli-units; a = FNV-1a hash of the tenant name) — one
# event per breach-state EDGE, never per evaluation.
TIMELINE_SLO_OPS = {1: "breach", 2: "clear"}

# kTokenStep `b` op tags (cpp/net/infer.h: b = op << 56 | low bits;
# a = request id) — one request's life through the continuous batch:
# admit (low bits = prefix-cache-matched tokens), prefill_done, one
# `token` per decode step (low bits = token index), eos / cancel (low
# bits = tokens emitted), shed (low bits = error code; a = 0).
TIMELINE_TOKEN_OPS = {1: "admit", 2: "prefill_done", 3: "token",
                      4: "eos", 5: "cancel", 6: "shed"}

# kStripeSend rail index meaning "the call's primary socket" (head
# frame / dead-rail fallback) — cpp/stat/timeline.h kStripePrimaryRail.
TIMELINE_STRIPE_PRIMARY_RAIL = 0xFFFF

# kStripeSend rail values with this bit set are one-sided RMA rails
# (net/rma.h): the chunk was WRITTEN into the peer's registered region
# by rail (value & 0x7FFF) — no ring/socket copy happened.  Mirrors
# cpp/stat/timeline.h kStripeRmaRailBit.
TIMELINE_STRIPE_RMA_BIT = 0x8000

_TL_MAGIC = b"TRPCTL01"
_TL_HEADER = struct.Struct("<qqI")       # now_mono_us, now_wall_us, nrings
_TL_RING = struct.Struct("<Q16sI")       # tid, name, nevents
_TL_EVENT = struct.Struct("<Iq5Q")       # type, ts, a, b, trace, span, fid


@dataclass(frozen=True)
class TimelineEvent:
    """One flight-recorder event (ids are 16-hex-digit strings, like
    rpcz spans — 64-bit values that would truncate as floats)."""

    ts_us: int
    type: int
    name: str
    a: int
    b: int
    trace_id: str
    span_id: str
    fid: str
    tid: int
    thread: str


def enable_timeline(on: bool = True) -> None:
    """Flips the flight recorder (the reloadable `trpc_timeline` flag;
    off by default — every hook costs one relaxed load while off)."""
    set_flag("trpc_timeline", "true" if on else "false")


def timeline_enabled() -> bool:
    return load_library().trpc_timeline_enabled() == 1


def reset_timeline() -> None:
    """Hides everything recorded so far (per-ring floors — safe against
    concurrent writers; lifetime counters keep counting)."""
    load_library().trpc_timeline_reset()


def timeline_dump(limit: int = 4096) -> dict:
    """The raw structured timeline dump for THIS process — the same
    shape `/timeline` serves: {"pid", "now_mono_us", "now_wall_us",
    "enabled", "threads": [{"tid", "name", "events": [...]}]} (the clock
    pair lets tools/trace_stitch.py --timeline place these events on the
    same wall-clock timeline as the node's rpcz spans)."""
    lib = load_library()
    raw = _dump_with_retry(
        lambda buf, n: lib.trpc_timeline_dump(0, limit, buf, n))
    return json.loads(raw.decode())


def timeline_binary(limit: int = 4096) -> bytes:
    """The packed binary dump (the /timeline?format=binary body)."""
    lib = load_library()
    return _dump_with_retry(
        lambda buf, n: lib.trpc_timeline_dump(1, limit, buf, n))


def parse_timeline_binary(raw: bytes) -> dict:
    """Decodes a binary timeline dump into the JSON dump's dict shape.
    The event-type ids resolve through TIMELINE_EVENTS — the table the
    lint rule pins against the C++ encoder."""
    if raw[:8] != _TL_MAGIC:
        raise ValueError(f"bad timeline magic: {raw[:8]!r}")
    off = 8
    now_mono, now_wall, nrings = _TL_HEADER.unpack_from(raw, off)
    off += _TL_HEADER.size
    threads = []
    for _ in range(nrings):
        tid, name, nevents = _TL_RING.unpack_from(raw, off)
        off += _TL_RING.size
        events = []
        for _ in range(nevents):
            etype, ts, a, b, trace, span, fid = _TL_EVENT.unpack_from(
                raw, off)
            off += _TL_EVENT.size
            events.append({
                "ts_us": ts, "type": etype,
                "name": TIMELINE_EVENTS.get(etype, "unknown"),
                # a/b as 16-hex strings, matching the JSON dump (they
                # often carry 64-bit handles a JSON double would round).
                "a": f"{a:016x}", "b": f"{b:016x}",
                "trace_id": f"{trace:016x}",
                "span_id": f"{span:016x}", "fid": f"{fid:016x}",
            })
        threads.append({"tid": tid,
                        "name": name.split(b"\0")[0].decode(),
                        "events": events})
    return {"now_mono_us": now_mono, "now_wall_us": now_wall,
            "threads": threads}


def timeline(limit: int = 4096) -> list[TimelineEvent]:
    """Flight-recorder events of THIS process, flattened across threads
    and sorted by timestamp (per-thread order is exact; cross-thread
    order is clock order)."""
    out = []
    for t in timeline_dump(limit)["threads"]:
        for e in t["events"]:
            out.append(TimelineEvent(
                ts_us=int(e["ts_us"]), type=int(e["type"]),
                name=e["name"], a=int(e["a"], 16), b=int(e["b"], 16),
                trace_id=e["trace_id"], span_id=e["span_id"],
                fid=e["fid"], tid=int(t["tid"]), thread=t["name"]))
    out.sort(key=lambda e: e.ts_us)
    return out


# ------------------------------------------------- digests + SLO fleet ----


# Decoder side of the mergeable latency digest and the fleet publication
# blob (cpp/stat/digest.h documents both layouts; tools/lint_trpc.py's
# digest-wire rule keeps encoder and decoder in lockstep via these
# markers).  Digests pool the recorder's octave-bucketed SAMPLES, so
# fleet percentiles come from a rank walk over merged data — never from
# averaging per-node p99s — with the recorder's own one-octave (2x)
# error bound.
_DG_MAGIC = b"TRPCDG01"  # digest-wire 1 (TRPCDG01)
_DG_OCTAVES = 32
# count, sum_us, max_us, total_count, window_secs, noct
_DG_HEAD = struct.Struct("<qqqqdI")
_DG_OCT = struct.Struct("<IqI")          # octave index, added, nsamples

_FL_MAGIC = b"TRPCFL01"  # digest-wire 2 (TRPCFL01)
_FL_HEAD = struct.Struct("<qI")          # wall_us, nentries
# p99_target_us, avail_target, fast_window_ms, slow_window_ms,
# fast_total, fast_bad, fast_err, slow_total, slow_bad, slow_err,
# burn_fast, burn_slow, breached
_FL_TENANT = struct.Struct("<qd" + "q" * 8 + "ddB")

# INT64_MAX in the p99_target_us slot means "latency-unbounded" (the
# tenant only declared an availability target).
SLO_NO_P99_TARGET = (1 << 63) - 1


@dataclass
class Digest:
    """One decoded latency digest: pooled octave counts + reservoir
    samples.  `oct` maps octave index -> (added, [samples_us...])."""

    count: int = 0
    sum_us: int = 0
    max_us: int = 0
    total_count: int = 0
    window_secs: float = 0.0
    oct: dict = field(default_factory=dict)

    @property
    def qps(self) -> float:
        w = self.window_secs if self.window_secs > 0 else 1.0
        return self.count / w

    @property
    def avg_us(self) -> float:
        return self.sum_us / self.count if self.count else 0.0


def digest_decode(raw: bytes, off: int = 0) -> tuple[Digest, int]:
    """Decodes one digest-wire 1 block starting at `off`; returns
    (digest, bytes_consumed).  Mirrors cpp/stat/digest.cc digest_decode
    byte for byte; raises ValueError on a malformed block."""
    if raw[off:off + 8] != _DG_MAGIC:
        raise ValueError(f"bad digest magic: {raw[off:off + 8]!r}")
    start = off
    off += 8
    count, sum_us, max_us, total_count, window_secs, noct = \
        _DG_HEAD.unpack_from(raw, off)
    off += _DG_HEAD.size
    if noct > _DG_OCTAVES:
        raise ValueError(f"digest noct {noct} > {_DG_OCTAVES}")
    d = Digest(count=count, sum_us=sum_us, max_us=max_us,
               total_count=total_count, window_secs=window_secs)
    for _ in range(noct):
        idx, added, nsamp = _DG_OCT.unpack_from(raw, off)
        off += _DG_OCT.size
        if idx >= _DG_OCTAVES or off + 4 * nsamp > len(raw):
            raise ValueError("malformed digest octave")
        samples = list(struct.unpack_from(f"<{nsamp}I", raw, off))
        off += 4 * nsamp
        d.oct[idx] = (added, samples)
    return d, off - start


def digest_merge(into: Digest, other: Digest) -> Digest:
    """Octave-wise pooling — counts sum, reservoirs concatenate (the
    merge digest_percentile_us rank-walks over)."""
    into.count += other.count
    into.sum_us += other.sum_us
    into.total_count += other.total_count
    into.max_us = max(into.max_us, other.max_us)
    into.window_secs = max(into.window_secs, other.window_secs)
    for idx, (added, samples) in other.oct.items():
        a, s = into.oct.get(idx, (0, []))
        into.oct[idx] = (a + added, s + samples)
    return into


def digest_percentile_us(d: Digest, p: float) -> int:
    """Rank walk over the pooled octaves — the same arithmetic as
    cpp/stat/digest.cc digest_percentile_us (and the recorder's own
    window percentiles), so a merged fleet digest and a pooled
    single-recorder oracle agree within one octave (2x)."""
    total = sum(added for added, _ in d.oct.values())
    if total == 0:
        return 0
    n = min(max(math.ceil(p * total), 1), total)
    for i in range(_DG_OCTAVES):
        added, samples = d.oct.get(i, (0, []))
        if added == 0:
            continue
        if n <= added:
            if not samples:
                return 1 << i  # count but no samples: octave floor
            merged = sorted(samples)
            sample_n = int(n * len(merged) / added)
            if sample_n >= len(merged):
                sample_n = len(merged) - 1
            elif sample_n > 0:
                sample_n -= 1
            return merged[sample_n]
        n -= added
    return d.max_us


def fleet_blob_decode(raw: bytes) -> dict:
    """Decodes one node's digest-wire 2 publication blob: {"wall_us",
    "tenants": [{tenant, p99_target_us (None when unbounded),
    avail_target, windows, counters, burns, breached, digest}]}.
    Mirrors cpp/stat/slo.cc fleet_blob_decode."""
    if raw[:8] != _FL_MAGIC:
        raise ValueError(f"bad fleet blob magic: {raw[:8]!r}")
    off = 8
    wall_us, nentries = _FL_HEAD.unpack_from(raw, off)
    off += _FL_HEAD.size
    if nentries > 4096:
        raise ValueError(f"fleet blob nentries {nentries} > 4096")
    tenants = []
    for _ in range(nentries):
        (name_len,) = struct.unpack_from("<H", raw, off)
        off += 2
        name = raw[off:off + name_len].decode()
        off += name_len
        (p99_target_us, avail_target, fast_window_ms, slow_window_ms,
         fast_total, fast_bad, fast_err, slow_total, slow_bad, slow_err,
         burn_fast, burn_slow, breached) = _FL_TENANT.unpack_from(raw, off)
        off += _FL_TENANT.size
        digest, used = digest_decode(raw, off)
        off += used
        tenants.append({
            "tenant": name,
            "p99_target_us": (None if p99_target_us == SLO_NO_P99_TARGET
                              else p99_target_us),
            "avail_target": avail_target,
            "fast_window_ms": fast_window_ms,
            "slow_window_ms": slow_window_ms,
            "fast_total": fast_total, "fast_bad": fast_bad,
            "fast_err": fast_err,
            "slow_total": slow_total, "slow_bad": slow_bad,
            "slow_err": slow_err,
            "burn_fast": burn_fast, "burn_slow": burn_slow,
            "breached": breached != 0,
            "digest": digest,
        })
    return {"wall_us": wall_us, "tenants": tenants}


def enable_slo(on: bool = True) -> None:
    """Flips the SLO engine (the reloadable `trpc_slo` flag; off by
    default — flag-off, the response path pays one relaxed load and
    every slo_* var stays frozen)."""
    set_flag("trpc_slo", "true" if on else "false")


def slo_enabled() -> bool:
    return load_library().trpc_slo_enabled() == 1


def enable_fleet_publish(on: bool = True) -> None:
    """Flips fleet publication (the reloadable `trpc_fleet_publish`
    flag): when on, each Announcer renew round piggybacks this node's
    digest+SLO blob onto its lease/epoch-fenced naming record."""
    set_flag("trpc_fleet_publish", "true" if on else "false")


def slo_breach_total() -> int:
    """Lifetime breach EDGES across all engines (slo_breach_total)."""
    return int(load_library().trpc_slo_breach_total())


def fleet_dump(service: str = "fleet") -> dict:
    """The fleet-wide merged per-tenant view over the LOCAL naming
    registry (the /fleet builtin body): digests merged octave-wise,
    window counters summed, burn rates recomputed from pooled counters."""
    lib = load_library()
    raw = _dump_with_retry(
        lambda buf, n: lib.trpc_fleet_dump(service.encode(), buf, n))
    return json.loads(raw.decode())


# --------------------------------------------------------------- traces ----


def get_trace() -> tuple[int, int]:
    """The ambient (trace_id, parent_span_id) of this thread/fiber —
    (0, 0) when none is installed."""
    lib = load_library()
    t = ctypes.c_uint64()
    s = ctypes.c_uint64()
    lib.trpc_trace_get(ctypes.byref(t), ctypes.byref(s))
    return t.value, s.value


def set_trace(trace_id: int, span_id: int = 0) -> None:
    """Installs an ambient trace context: RPCs issued by this thread (or
    fiber) become children of (trace_id, span_id).  Use to carry a trace
    across custom boundaries — threads, queues, processes."""
    load_library().trpc_trace_set(int(trace_id), int(span_id))


def clear_trace() -> None:
    load_library().trpc_trace_clear()


def new_trace_id() -> int:
    """A fresh nonzero 64-bit id for minting root traces by hand."""
    return load_library().trpc_trace_new_id()


class trace:
    """Context manager opening a named span that owns the block: every
    RPC issued inside — sync calls, batch submits, calls the far server
    makes in turn — shares its trace_id, and `annotate()` drops user
    marks onto its timeline.  The span records into the rpcz ring at
    exit regardless of `rpcz_enabled` (it was explicitly asked for);
    the AUTOMATIC child spans still need `enable_rpcz()`.

        with observe.trace("step-42") as t:
            t.annotate("inputs staged")
            ch.call("Model.Forward", blob)
        print(hex(t.trace_id), observe.spans(trace_id=t.trace_id))
    """

    def __init__(self, name: str = "trace"):
        self._lib = load_library()
        self._name = name
        self._h = None
        self.trace_id = 0
        self.span_id = 0

    def __enter__(self) -> "trace":
        self._h = self._lib.trpc_span_start(self._name.encode(), 0)
        t = ctypes.c_uint64()
        s = ctypes.c_uint64()
        self._lib.trpc_span_ids(ctypes.c_void_p(self._h),
                                ctypes.byref(t), ctypes.byref(s))
        self.trace_id = t.value
        self.span_id = s.value
        return self

    def annotate(self, text: str) -> None:
        if self._h:
            self._lib.trpc_span_annotate(
                ctypes.c_void_p(self._h), text.encode())

    def __exit__(self, exc_type, exc, tb) -> None:
        h, self._h = self._h, None
        if h:
            self._lib.trpc_span_end(
                ctypes.c_void_p(h), 0 if exc_type is None else 13)
