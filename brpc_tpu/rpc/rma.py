"""One-sided RMA regions — registered, remotely-writable response buffers.

Parity: brpc's RDMA one-sided verbs register caller memory so a peer can
WRITE results into it directly; fabric-lib (arXiv 2510.27656) builds its
KV-cache transfer engine on exactly that shape.  `RmaBuffer` is the
Python surface of cpp/net/rma.h's region registry: the buffer's bytes are
shm-backed and registered under an rkey, so a batch call that uses it as
`resp_buf` advertises the rkey on the request (meta tail-group 6) and —
over shm/ici connections — the SERVER writes the response payload
straight into this buffer with zero receiver-side copies, signalling
completion with a release-fenced chunk bitmap plus one tiny control
frame.  Over TCP (or when the one-sided plane is off) the same buffer
transparently degrades to the striped copy-path landing of PR 5.

Usage:

    buf = rma.RmaBuffer(64 << 20)
    batch = ch.call_batch([("Echo.Echo", req)], resp_bufs=[buf.view])
    ...
    buf.free()        # or use it as a context manager

The memory stays mapped until `free()` ran AND the runtime's references
drop: the region registry defers the unmap while any in-flight call is
still bound to the buffer (its landing registration), and zero-copy
views hold it past that.  Contract for FAILED calls: a call that timed
out or was cancelled while using this buffer may have a server-side put
still writing into the shared pages — do not REUSE the buffer for a new
call until that horizon passes (the runtime rejects a stale transfer's
completion via its correlation token, but a writer racing mid-flight is
inherent to shared memory); `free()` and allocating a fresh buffer is
the cheap, always-safe pattern.
"""

from __future__ import annotations

import ctypes

from brpc_tpu.rpc._lib import load_library


class RmaBuffer:
    """`size` shm-backed bytes registered for one-sided remote writes."""

    def __init__(self, size: int):
        if size <= 0:
            raise ValueError("RmaBuffer size must be positive")
        lib = load_library()
        rkey = ctypes.c_uint64()
        base = lib.trpc_rma_alloc(size, ctypes.byref(rkey))
        if not base:
            raise MemoryError(f"trpc_rma_alloc({size}) failed")
        self._lib = lib
        self._base = base
        self._size = size
        self._rkey = rkey.value
        # A ctypes array over the mapped bytes: buffer-protocol writable,
        # so it works anywhere a bytearray/numpy resp_buf does.
        self._view = (ctypes.c_char * size).from_address(base)

    @property
    def view(self):
        """Writable buffer-protocol view of the registered bytes."""
        if self._base is None:
            raise ValueError("RmaBuffer already freed")
        return self._view

    @property
    def rkey(self) -> int:
        return self._rkey

    @property
    def nbytes(self) -> int:
        return self._size

    @property
    def address(self) -> int:
        if self._base is None:
            raise ValueError("RmaBuffer already freed")
        return self._base

    def free(self) -> None:
        """Unregisters the region (idempotent).  The unmap is deferred
        while an in-flight call's landing registration or a zero-copy
        view still references the bytes; new calls can no longer use
        the buffer."""
        if self._base is not None:
            self._view = None
            self._lib.trpc_rma_free(self._base)
            self._base = None

    def __enter__(self) -> "RmaBuffer":
        return self

    def __exit__(self, *exc) -> None:
        self.free()

    def __len__(self) -> int:
        return self._size

    def __del__(self):  # pragma: no cover - GC timing
        try:
            self.free()
        except Exception:  # noqa: BLE001 — interpreter teardown
            pass


def kernel_supports(feature: str) -> int:
    """Runtime kernel-capability probe (base/proc.h): 1 supported, 0 not,
    -1 unknown.  ``kernel_supports("io_uring")`` is the ROADMAP item 2
    gate — kernels before 5.1 (this dev box: 4.4.0) answer ENOSYS."""
    return int(load_library().trpc_kernel_supports(feature.encode()))
