"""Python-facing RPC server over the native runtime.

Handlers run on fiber worker threads (ctypes re-acquires the GIL); they may
respond inline or keep the call handle and respond later (async), mirroring
the done-closure contract of the C++ `Server` (cpp/net/server.h).
"""

from __future__ import annotations

import ctypes
from typing import Callable

from brpc_tpu.rpc._lib import load_library

_HANDLER_CFUNC = ctypes.CFUNCTYPE(
    None, ctypes.c_void_p, ctypes.POINTER(ctypes.c_char), ctypes.c_size_t,
    ctypes.c_void_p
)


class Call:
    """One in-flight request; respond() completes it.

    Completion is idempotent — the native side accepts exactly one respond
    per call and ignores the rest, so an async handler racing an error path
    can never double-complete.
    """

    def __init__(self, lib, handle: int, tenant: str = "",
                 priority: int = 0):
        self._lib = lib
        self._handle = handle
        #: QoS tag of this request (cpp/net/qos.h): the tenant it bills
        #: and its dispatch-lane priority (0 = highest).  Empty/0 on
        #: untagged traffic.
        self.tenant = tenant
        self.priority = priority

    def respond(self, data: bytes = b"", error_code: int = 0,
                error_text: str = "") -> bool:
        """Returns True if this respond completed the call (False if it was
        already completed elsewhere)."""
        rc = self._lib.trpc_call_respond(
            self._handle, data, len(data), error_code, error_text.encode()
        )
        return rc == 0

    @property
    def remaining_us(self) -> int:
        """Remaining end-to-end budget of this request in µs
        (cpp/net/deadline.h): the caller's wire-propagated deadline minus
        elapsed time since arrival.  A very large value (INT64 max) when
        the caller set none, 0 when already past.  Only valid BEFORE
        respond() — the handle dies with the call."""
        return self._lib.trpc_call_remaining_us(self._handle)

    @property
    def cancelled(self) -> bool:
        """True when the caller cancelled this request (kCancel control
        frame) or its connection died — abandon work nobody will
        receive.  Only valid BEFORE respond()."""
        return bool(self._lib.trpc_call_cancelled(self._handle))

    def accept_stream(self, window_bytes: int = 0):
        """Accepts the stream the request OFFERED (stream.open_stream
        client-side) and returns an established stream.Stream.  MUST be
        called before respond() — acceptance rides the response wire.
        Returns None when the request offered no stream.  window_bytes
        = 0 keeps the flag default credit window."""
        from brpc_tpu.rpc import stream as _stream
        handle = self._lib.trpc_call_stream_accept(self._handle,
                                                   window_bytes)
        if not handle:
            return None
        return _stream.Stream(self._lib, handle)


class Server:
    def __init__(self):
        self._lib = load_library()
        self._ptr = self._lib.trpc_server_create()
        self._keepalive = []  # ctypes callbacks must outlive the server
        self._infer = None  # InferScheduler handle (enable_infer)

    def register(self, method: str, fn: Callable[[Call, bytes], None]) -> None:
        """fn(call, request_bytes) — call call.respond(...) when done."""
        lib = self._lib

        def thunk(handle, req_ptr, req_len, _ctx):
            # QoS tag fetched EAGERLY: the handle dies at respond(), and a
            # lazy property read after an async respond would be a
            # use-after-free.
            tbuf = ctypes.create_string_buffer(80)
            prio = lib.trpc_call_qos(handle, tbuf, 80)
            call = Call(lib, handle, tbuf.value.decode(errors="replace"),
                        prio)
            try:
                data = ctypes.string_at(req_ptr, req_len)
                fn(call, data)
            except BaseException as e:  # noqa: BLE001 - never leak the call
                try:
                    call.respond(error_code=13, error_text=repr(e))
                except BaseException:
                    pass  # respond is idempotent; worst case client times out

        cb = _HANDLER_CFUNC(thunk)
        self._keepalive.append(cb)
        if self._lib.trpc_server_register(self._ptr, method.encode(), cb, None) != 0:
            raise RuntimeError(f"register {method!r} failed (server running?)")

    def register_native_echo(self, method: str = "Echo.Echo") -> None:
        """Registers a NATIVE zero-copy echo handler for `method` — the
        request blocks are ref-shared into the response with no Python
        callback and no GIL.  The server-side anchor for data-plane
        benchmarks: a Python handler would measure the server's GIL, not
        the client pipeline."""
        if self._lib.trpc_server_register_echo(
                self._ptr, method.encode()) != 0:
            raise RuntimeError(
                f"register_native_echo {method!r} failed (server running?)")

    def enable_kv_store(self) -> None:
        """Attaches the NATIVE KV block-store fetch handler (Kv.Fetch,
        cpp/net/kvstore.h): blocks published from this process (kv.publish)
        are served zero-copy out of their registered pages with no Python
        callback and no GIL — the prefill side of the disaggregation
        workload.  Call before start."""
        if self._lib.trpc_server_enable_kv_store(self._ptr) != 0:
            raise RuntimeError("enable_kv_store failed (server running?)")

    def enable_kv_registry(self) -> None:
        """Attaches the NATIVE KV-block registry handlers
        (KvReg.Register/Lookup/Evict/Renew, cpp/net/kvstore.h): this
        server becomes a block directory mapping block_id -> {node, rkey,
        offset, len, generation} under lease-based ownership.  Call
        before start."""
        if self._lib.trpc_server_enable_kv_registry(self._ptr) != 0:
            raise RuntimeError("enable_kv_registry failed (server running?)")

    def enable_collective(self) -> None:
        """Attaches the NATIVE collective handlers (Coll.Put/Abort,
        Reshard.Plan/Execute, cpp/net/collective.h): this server can
        receive group put schedules — chunks land one-sided through the
        RMA plane and wake the local member's step countdown — and
        serve the resharding service (Plan is stateless; Execute moves
        KV-block-addressed shards).  Call before start."""
        if self._lib.trpc_server_enable_collective(self._ptr) != 0:
            raise RuntimeError("enable_collective failed (server running?)")

    def enable_tuner(self) -> None:
        """Attaches the self-tuning controller (cpp/stat/tuner.h):
        registers the trpc_tuner* flags/vars and flips `trpc_tuner` on
        through the validated reload path.  The controller is
        process-wide (it actuates process-wide flags); disable with
        rpc.tuner.enable_tuner(False).  Callable before or after
        start."""
        if self._lib.trpc_server_enable_tuner(self._ptr) != 0:
            raise RuntimeError("enable_tuner failed")

    def enable_infer(self, prefix_cache: bool = True,
                     kv_fetch_addr: str = "", node: str = "") -> None:
        """Attaches the streamed-inference front door (cpp/net/infer.h):
        registers Infer.Submit and starts the continuous-batching decode
        loop — requests join/leave the running batch every step, tokens
        push down per-request logical streams (infer.InferClient).
        prefix_cache wires the process kv_store()/kv_registry()
        singletons so matched prompt blocks skip recompute (composes
        with enable_kv_store/enable_kv_registry); kv_fetch_addr pulls
        matched blocks over Kv.FetchPrefix from that node instead
        (prefill/decode disaggregation).  Call before start; the
        scheduler stops automatically on close()."""
        sched = self._lib.trpc_server_enable_infer(
            self._ptr, 1 if prefix_cache else 0, kv_fetch_addr.encode(),
            node.encode())
        if not sched:
            raise RuntimeError("enable_infer failed (server running?)")
        self._infer = sched

    def infer_dump(self) -> dict:
        """The inference scheduler's live stats (the bench/orchestrator
        read): active/waiting/streams_live/streams_peak, admission and
        token counters, prefill cache bytes, and ttft/tpot percentile
        blocks.  Raises without enable_infer()."""
        if self._infer is None:
            raise RuntimeError("enable_infer() was not called")
        import json as _json
        size = 1 << 12
        while True:
            out = ctypes.create_string_buffer(size)
            need = self._lib.trpc_infer_dump(self._infer, out, size)
            if need < size:
                return _json.loads(out.raw[:need].decode())
            size = need + 1

    def infer_streams_live(self) -> int:
        if self._infer is None:
            return 0
        return int(self._lib.trpc_infer_streams_live(self._infer))

    def infer_streams_peak(self) -> int:
        if self._infer is None:
            return 0
        return int(self._lib.trpc_infer_streams_peak(self._infer))

    def enable_naming_registry(self) -> None:
        """Attaches the NATIVE naming-registry handlers
        (Naming.Announce/Withdraw/Resolve/Watch, cpp/net/naming.h): this
        server becomes a membership directory — nodes announce {addr,
        zone, weight, epoch} under leases, clients watch for push-based
        deltas.  Call before start."""
        if self._lib.trpc_server_enable_naming(self._ptr) != 0:
            raise RuntimeError("enable_naming_registry failed "
                               "(server running?)")

    def announce(self, registry_addr: str, service: str, zone: str = "",
                 weight: int = 1) -> None:
        """Announces this RUNNING server's address into `service` at the
        registry and keeps the lease renewed from a native fiber.  The
        announcement withdraws automatically on drain() (FIRST, so
        watchers re-balance before in-flight work finishes) and on
        close."""
        rc = self._lib.trpc_server_announce(
            self._ptr, registry_addr.encode(), service.encode(),
            zone.encode(), int(weight))
        if rc != 0:
            raise RuntimeError(
                f"announce to {registry_addr!r} failed (server not "
                "started, or registry unreachable)")

    def drain(self, deadline_ms: int = 0, handoff_path: str = "") -> bool:
        """Graceful drain (cpp/net/server.h Drain): new requests answer
        the draining status (DrainingError on a bare Channel; silent
        failover on a ClusterChannel), drain hooks withdraw this node's
        naming announcements and tombstone its KV blocks, and — with
        handoff_path — the SO_REUSEPORT listener set is served to a
        successor process (start_from_handoff) before our own fds close,
        so no connection is ever refused.  Then waits out in-flight
        requests and RMA window spans.  deadline_ms <= 0 uses the
        trpc_drain_deadline_ms flag.  Returns True when fully quiesced,
        False when the deadline cut the wait short."""
        return self._lib.trpc_server_drain(
            self._ptr, int(deadline_ms), handoff_path.encode()) == 0

    def start_from_handoff(self, handoff_path: str,
                           timeout_ms: int = 10000) -> int:
        """Hot-restart successor entry point: adopts the draining
        predecessor's listener fds from its handoff socket (retrying
        until the predecessor serves them) and starts THIS server on
        them — same port, shared accept queues, fresh process (and
        fresh RMA rkeys).  Register methods first, like start()."""
        if self._lib.trpc_server_start_handoff(
                self._ptr, handoff_path.encode(), int(timeout_ms)) != 0:
            raise RuntimeError(
                f"listener handoff from {handoff_path!r} failed")
        return self.port

    @property
    def draining(self) -> bool:
        return bool(self._lib.trpc_server_draining(self._ptr))

    def set_qos(self, spec: str) -> None:
        """Per-tenant QoS admission control (cpp/net/qos.h grammar):
        ';'-separated `tenant:weight=N,limit=<spec>` clauses, tenant '*'
        as the default.  Shed requests answer the overloaded status
        (OverloadedError client-side).  '' removes.  Call before start;
        raises on a malformed spec."""
        if self._lib.trpc_server_set_qos(self._ptr, spec.encode()) != 0:
            raise ValueError(f"bad qos spec (or server running): {spec!r}")

    def set_slo(self, spec: str) -> None:
        """Per-tenant SLO targets (cpp/stat/slo.h grammar): ';'-separated
        `tenant:p99_us=N,avail=PCT` clauses, tenant '*' as the default —
        e.g. "tenantA:p99_us=2000,avail=99.9;*:p99_us=10000".  Needs the
        reloadable `trpc_slo` flag on (observe.enable_slo) to record;
        exposes slo_tenant_* vars, the /slo builtin, and — with
        trpc_fleet_publish — this node's digest blob over naming://.
        '' removes.  Call before start; raises on a malformed spec."""
        if self._lib.trpc_server_set_slo(self._ptr, spec.encode()) != 0:
            raise ValueError(f"bad slo spec (or server running): {spec!r}")

    def slo_dump(self) -> dict:
        """This server's per-tenant SLO attainment/burn-rate view (the
        /slo builtin body): {"enabled", "tenants": [{tenant, targets,
        window counters, burn_fast/burn_slow, attainment, breached}]}."""
        import json as _json
        size = 1 << 14
        while True:
            out = ctypes.create_string_buffer(size)
            need = self._lib.trpc_slo_dump(self._ptr, out, size)
            if need < size:
                return _json.loads(out.raw[:need].decode())
            size = need + 1

    def fleet_blob(self) -> bytes:
        """This node's fleet publication blob (digest-wire 2 — the exact
        bytes the Announcer publishes; observe.fleet_blob_decode reads
        it).  b'' without an SLO engine."""
        size = 1 << 14
        while True:
            out = ctypes.create_string_buffer(size)
            need = self._lib.trpc_fleet_blob(self._ptr, out, size)
            if need < size:
                return out.raw[:need]
            size = need + 1

    def set_reuseport_shards(self, shards: int) -> None:
        """Shards the TCP acceptor across `shards` SO_REUSEPORT listeners
        (each on its own event-dispatcher slot — see the
        trpc_event_dispatchers flag).  Call before start."""
        if self._lib.trpc_server_set_reuseport(self._ptr, shards) != 0:
            raise ValueError(
                f"bad shard count (or server running): {shards}")

    def accept_counts(self) -> list:
        """Connections accepted per REUSEPORT shard (scale telemetry)."""
        out = (ctypes.c_uint64 * 16)()
        n = self._lib.trpc_server_accept_counts(self._ptr, out, 16)
        return [int(out[i]) for i in range(n)]

    def set_faults(self, spec: str) -> None:
        """Server-side fault injection (cpp/net/fault.h svr_* fields):
        svr_delay=P:MS delays dispatch, svr_error=P:CODE answers with an
        injected error, svr_reject=P closes fresh connections.  ''
        disables.  Callable at runtime; raises on a malformed spec."""
        if self._lib.trpc_server_fault_set(self._ptr, spec.encode()) != 0:
            raise ValueError(f"bad server fault schedule: {spec!r}")

    def start(self, port: int = 0) -> int:
        if self._lib.trpc_server_start(self._ptr, port) != 0:
            raise RuntimeError("server start failed")
        return self.port

    @property
    def port(self) -> int:
        return self._lib.trpc_server_port(self._ptr)

    def stop(self) -> None:
        self._lib.trpc_server_stop(self._ptr)

    def close(self) -> None:
        """Stops and frees the native server.  Only call once no requests
        are in flight (handlers hold references into the server)."""
        # The inference scheduler must stop BEFORE the server dies: its
        # loop fiber cancels/closes every live token stream on the way
        # out, and those streams reference server-side sockets.
        sched, self._infer = self._infer, None
        if sched is not None:
            self._lib.trpc_infer_stop(sched)
        ptr, self._ptr = self._ptr, None
        if ptr:
            self._lib.trpc_server_stop(ptr)
            self._lib.trpc_server_destroy(ptr)
