"""Ordered byte-chunk streams with credit flow control (parity:
cpp/net/stream.h over capi/stream_capi.cc).

A stream rides an ordinary RPC: the client OFFERS one with
``open_stream(channel, method, request)`` (StreamCreate before
CallMethod); the server handler ACCEPTS it via ``Call.accept_stream()``
before responding.  After the response both ends hold an established
Stream and exchange ordered chunks — writes park while the peer's credit
window is exhausted (the GIL is released, so other Python threads run),
reads block on a plain condition variable fed by the consume fiber.

Thousands of logical streams multiplex over ONE connection: a StreamId
is a runtime handle, not a socket, which is how the inference front door
(brpc_tpu/rpc/infer.py) holds 100k+ token streams under a 20k fd cap.
"""

from __future__ import annotations

import ctypes

from brpc_tpu.rpc._lib import IOBuf, load_library
from brpc_tpu.rpc.client import RpcError, make_rpc_error


class StreamClosedError(RpcError):
    """The peer closed (or the connection died) and every buffered chunk
    has been drained — raised by read()/read_exactly() instead of
    returning data.  Writes after this surface EPIPE via RpcError."""

    def __init__(self, stream_id: int):
        super().__init__(0, f"stream {stream_id} closed and drained")
        self.stream_id = stream_id


class StreamTimeoutError(RpcError):
    """read() hit its timeout with no chunk buffered and the stream
    still open.  The stream remains usable — retry the read."""

    def __init__(self, stream_id: int, timeout_ms: int):
        super().__init__(
            0, f"stream {stream_id} read timed out after {timeout_ms}ms")
        self.stream_id = stream_id


class StreamChunkTooLargeError(RpcError):
    """The next buffered chunk is larger than read()'s max_bytes.
    NOTHING was consumed or truncated — the chunk stays queued; retry
    with max_bytes >= .needed (silently dropping the tail would
    desynchronize framed readers without any error)."""

    def __init__(self, stream_id: int, needed: int, cap: int):
        super().__init__(
            0, f"stream {stream_id} next chunk is {needed} bytes but the "
               f"read buffer holds only {cap}")
        self.stream_id = stream_id
        self.needed = needed
        self.cap = cap


class Stream:
    """One end of an established stream.  Wraps the capi handle; close()
    is graceful (buffered chunks stay readable on the peer), __del__
    frees the native handle."""

    def __init__(self, lib, handle):
        self._lib = lib
        self._handle = handle

    @property
    def id(self) -> int:
        """The runtime StreamId (diagnostics; matches /streams dump)."""
        return int(self._lib.trpc_stream_id(self._handle))

    def read(self, max_bytes: int = 65536, timeout_ms: int = -1) -> bytes:
        """One ordered chunk (chunks never coalesce, split, or
        truncate).  timeout_ms < 0 waits forever.  Raises
        StreamClosedError once the stream is closed and drained,
        StreamTimeoutError on timeout, and StreamChunkTooLargeError
        when the next chunk exceeds max_bytes — the chunk stays queued,
        so retry with max_bytes >= the error's .needed."""
        if self._handle is None:
            raise StreamClosedError(0)
        buf = ctypes.create_string_buffer(max_bytes)
        n = self._lib.trpc_stream_read(self._handle, buf, max_bytes,
                                       timeout_ms)
        if n == -1:
            raise StreamClosedError(self.id)
        if n == -2:
            raise StreamTimeoutError(self.id, timeout_ms)
        if n == -3:
            needed = int(self._lib.trpc_stream_next_len(self._handle))
            raise StreamChunkTooLargeError(self.id, needed, max_bytes)
        return buf.raw[:n]

    def write(self, data: bytes) -> None:
        """Ordered write; parks while the peer's credit window is
        exhausted (GIL released).  Raises on a closed stream or dead
        connection (EPIPE/EINVAL as RpcError)."""
        if self._handle is None:
            raise StreamClosedError(0)
        rc = self._lib.trpc_stream_write(self._handle, data, len(data))
        if rc != 0:
            raise make_rpc_error(self._lib, rc,
                                 f"stream write failed (errno {rc})")

    def pending(self) -> int:
        """Chunks buffered locally, readable without blocking."""
        if self._handle is None:
            return 0
        return int(self._lib.trpc_stream_pending(self._handle))

    def close(self) -> None:
        """Graceful close of this end (idempotent).  The peer reads any
        in-flight chunks, then its reads raise StreamClosedError."""
        if self._handle is not None:
            self._lib.trpc_stream_close(self._handle)

    def destroy(self) -> None:
        """Close and free the native handle.  The stream's callbacks
        hold their own reference, so a consume batch mid-delivery
        finishes safely."""
        handle, self._handle = self._handle, None
        if handle is not None:
            self._lib.trpc_stream_destroy(handle)

    def __enter__(self) -> "Stream":
        return self

    def __exit__(self, *exc) -> None:
        self.destroy()

    def __del__(self):
        try:
            self.destroy()
        except Exception:
            pass


def open_stream(channel, method: str, request: bytes = b"",
                timeout_ms: int = 0, window_bytes: int = 0,
                tenant: str = "", priority: int = 0):
    """Offers a stream on `method`'s request over `channel` (a
    client.Channel) and returns ``(Stream, response_bytes)`` once the
    server accepts.  window_bytes = 0 keeps the flag default credit
    window (trpc_stream_window_bytes); tenant/priority override the
    channel's QoS for this call only.  Raises the typed RpcError when
    the call fails (the offered stream is torn down server-side)."""
    lib = load_library()
    resp = IOBuf()
    err_code = ctypes.c_int(0)
    err = ctypes.create_string_buffer(256)
    handle = lib.trpc_stream_open(
        channel._ptr, method.encode(), request, len(request), timeout_ms,
        window_bytes, tenant.encode(), int(priority), resp._ptr,
        ctypes.byref(err_code), err, 256)
    if not handle:
        raise make_rpc_error(lib, err_code.value,
                             err.value.decode(errors="replace"))
    return Stream(lib, handle), resp.to_bytes()
