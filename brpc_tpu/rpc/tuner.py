"""Self-tuning runtime controller (the /tuner page, in-process).

The native runtime carries ~30 validated reloadable flags and the var
surfaces to see exactly where time goes; `cpp/stat/tuner.cc` closes the
loop — a control loop samples the vars on a `trpc_tuner_interval_ms`
tick and drives per-knob feedback rules (hill-climb / AIMD with
hysteresis, cooldown and a revert-on-regression guard) through the
validated flag-reload path only.  This module is the ctypes surface:

- `enable_tuner()` / `tuner_enabled()` flip and read the reloadable
  `trpc_tuner` flag (default off; while off no thread runs, nothing is
  sampled, and the tuner vars stay frozen at 0);
- `status()` returns the full /tuner body: counters, the live rule
  table (knob, mode, effective bounds, freeze/cooldown state), the
  sampled input vars, and the structured decision journal;
- `decisions()` returns the journal as typed records — every knob
  change, revert and freeze, with the metric readings that drove it.

Every decision is also a `tuner_decision` timeline event (a = knob
hash, b = old<<32|new), so a tuning run shows up as its own track in
`tools/trace_stitch.py --timeline` Perfetto artifacts.
"""

from __future__ import annotations

import json
from dataclasses import dataclass

from brpc_tpu.rpc._lib import load_library
from brpc_tpu.rpc.flags import set_flag
from brpc_tpu.rpc.observe import _dump_with_retry


def enable_tuner(on: bool = True) -> None:
    """Flips the self-tuning controller (the reloadable `trpc_tuner`
    flag; off by default — flag-off cost is nothing: no thread, no
    sampling, no knob ever touched)."""
    set_flag("trpc_tuner", "true" if on else "false")


def tuner_enabled() -> bool:
    return load_library().trpc_tuner_enabled() == 1


def reset_tuner() -> None:
    """Test support: clears rules/state/journal/counters.  Call with the
    tuner OFF."""
    load_library().trpc_tuner_reset()


def status(limit: int = 128) -> dict:
    """The raw /tuner body for THIS process: {"enabled", "interval_ms",
    "ticks_total", "decisions_total", "reverts_total", "freezes_total",
    "rules": [...], "inputs": {...}, "decisions": [...]}."""
    lib = load_library()
    raw = _dump_with_retry(
        lambda buf, n: lib.trpc_tuner_dump(limit, buf, n))
    return json.loads(raw.decode())


@dataclass(frozen=True)
class TunerDecision:
    """One journal entry: a knob change the controller applied (or
    rolled back / froze), with the metric readings that drove it."""

    seq: int
    ts_mono_us: int
    ts_wall_us: int
    knob: str
    old: int
    new: int
    action: str  # "apply" | "revert" | "freeze"
    reason: str
    metric_before: float
    metric_after: float
    old_str: str = ""  # string knobs (qos lane weights)
    new_str: str = ""


def decisions(limit: int = 128) -> list[TunerDecision]:
    """The decision journal, oldest first (newest `limit` entries)."""
    out = []
    for d in status(limit)["decisions"]:
        out.append(TunerDecision(
            seq=int(d["seq"]), ts_mono_us=int(d["ts_mono_us"]),
            ts_wall_us=int(d["ts_wall_us"]), knob=d["knob"],
            old=int(d["old"]), new=int(d["new"]), action=d["action"],
            reason=d["reason"],
            metric_before=float(d["metric_before"]),
            metric_after=float(d["metric_after"]),
            old_str=d.get("old_str", ""), new_str=d.get("new_str", "")))
    return out


def counters() -> dict:
    """Lifetime counters in one crossing: {"ticks", "decisions",
    "reverts", "freezes"} — provably frozen at 0 while `trpc_tuner` has
    never been on."""
    import ctypes

    lib = load_library()
    t = ctypes.c_uint64()
    d = ctypes.c_uint64()
    r = ctypes.c_uint64()
    f = ctypes.c_uint64()
    lib.trpc_tuner_counters(ctypes.byref(t), ctypes.byref(d),
                            ctypes.byref(r), ctypes.byref(f))
    return {"ticks": t.value, "decisions": d.value, "reverts": r.value,
            "freezes": f.value}
