"""Zero-copy JAX→wire path: a device array's bytes enter the C++ IOBuf by
reference, with no host-side copies at all.

Parity: the fork's RDMA path hands NIC-registered memory to IOBufs without
copying (/root/reference/src/brpc/rdma/block_pool.cpp allocation takeover,
/root/reference/src/butil/iobuf.h:257 append_user_data_with_meta).  The
TPU-native form inverts the ownership: instead of making JAX allocate into
our slabs (PJRT offers no host-destination transfer), we export the JAX
buffer itself:

- Host-backed buffers (the CPU mesh; any host-visible backend): dlpack
  import yields a numpy VIEW of the very bytes JAX owns — `append_jax`
  hands that pointer to `IOBuf::append_user_data`, the wire writes straight
  from it, and a deleter keeps the array alive until the last IOBuf
  reference drops.  Zero copies, pointer-identity verifiable.
- TPU-resident buffers: dlpack import fails (device memory is not host
  addressable), so exactly ONE device→host DMA runs (`np.asarray` — the
  transport hop itself, the NIC-DMA analogue) and the RESULTING host buffer
  enters the IOBuf by reference.  One copy total, where the round-2 arena
  path took two (DMA into a temporary, memcpy into the slab).
"""

from __future__ import annotations

import ctypes
import threading

import numpy as np

from brpc_tpu.rpc._lib import load_library


_DELETER_T = ctypes.CFUNCTYPE(None, ctypes.c_void_p, ctypes.c_void_p)

# Arrays whose bytes are on the wire, keyed by token; the entry (and with
# it the last Python reference) drops when the C++ side runs the deleter.
_live: dict[int, tuple] = {}
_lock = threading.Lock()
_next_token = 1


@_DELETER_T
def _release(data, ctx):  # noqa: ARG001 - data unused, identity is ctx
    # Runs on whatever thread drops the last IOBuf reference (usually a
    # fiber worker after the wire write); ctypes re-acquires the GIL.
    with _lock:
        _live.pop(ctx, None)


def live_sends() -> int:
    """Number of buffers currently pinned by in-flight sends (tests).
    One registry serves every zero-copy producer (per-call `append_jax`
    AND the batch pipeline), so a pin leaked by either is visible here."""
    with _lock:
        return len(_live)


# The CFUNCTYPE deleter for ctypes callers outside this module (the batch
# pipeline): pass as the request deleter with a `pin(...)` token as ctx.
release_cb = _release


def pin(*objs) -> int:
    """Registers `objs` in the live-send registry and returns the token
    to hand the native side as deleter ctx (with `release_cb`); the
    entry — and the last Python reference to the pinned buffers — drops
    when the runtime runs the deleter."""
    global _next_token
    with _lock:
        token = _next_token
        _next_token += 1
        _live[token] = objs
    return token


def unpin(token: int) -> None:
    """Drops a pin that was never handed to the native side (failed
    submit paths); a pin the runtime owns is released by its deleter."""
    with _lock:
        _live.pop(token, None)


def host_view(array):
    """(flat_uint8_view, owner): host-visible bytes of a JAX/numpy array
    with the minimum number of copies — zero for host-backed buffers
    (dlpack import), exactly one device→host DMA otherwise."""
    try:
        host = np.from_dlpack(array)
    except (RuntimeError, TypeError, BufferError, AttributeError):
        host = np.asarray(array)
    return host.reshape(-1).view(np.uint8), host


def append_jax(iobuf_ptr: int, array, lib=None) -> int:
    """Appends `array`'s bytes to a trpc_iobuf by REFERENCE (no copy beyond
    the unavoidable device→host DMA for TPU-resident arrays).  The array is
    kept alive until the IOBuf drops it.  Returns the byte length."""
    global _next_token
    lib = lib or load_library()
    flat, owner = host_view(array)
    with _lock:
        token = _next_token
        _next_token += 1
        # Keep `flat` itself alive, not just its parents: reshape(-1) on a
        # NON-contiguous view returns a fresh buffer, and pinning only
        # (owner, array) would leave the IOBuf holding a dangling pointer.
        _live[token] = (flat, owner, array)
    lib.trpc_iobuf_append_user_data(
        ctypes.c_void_p(iobuf_ptr),
        ctypes.c_void_p(flat.ctypes.data),
        ctypes.c_size_t(flat.size),
        _release,
        ctypes.c_void_p(token))
    return flat.size


def call_zero_copy(channel, method: str, array, timeout_ms: int = 0) -> bytes:
    """Sync RPC whose request payload is `array`'s bytes entering the wire
    path without host copies.  Returns the response bytes."""
    lib = channel._lib
    lib.trpc_iobuf_create.restype = ctypes.c_void_p
    req = lib.trpc_iobuf_create()
    resp = lib.trpc_iobuf_create()
    try:
        append_jax(req, array, lib)
        err = ctypes.create_string_buffer(256)
        rc = lib.trpc_channel_call_buf(
            ctypes.c_void_p(channel._ptr), method.encode(),
            ctypes.c_void_p(req), ctypes.c_void_p(resp),
            ctypes.c_int64(timeout_ms), err, ctypes.c_size_t(len(err)))
        if rc != 0:
            from brpc_tpu.rpc.client import RpcError

            raise RpcError(rc, err.value.decode(errors="replace"))
        n = lib.trpc_iobuf_size(ctypes.c_void_p(resp))
        out = ctypes.create_string_buffer(n)
        lib.trpc_iobuf_copy_to(ctypes.c_void_p(resp), out,
                               ctypes.c_size_t(n), ctypes.c_size_t(0))
        return out.raw
    finally:
        lib.trpc_iobuf_destroy(ctypes.c_void_p(req))
        lib.trpc_iobuf_destroy(ctypes.c_void_p(resp))


def alloc_staging(nbytes: int, lib=None) -> np.ndarray:
    """Allocates a REGISTERED ICI staging slab and returns a uint8 numpy
    view over it (no copy).  Bytes living here cross ici ring connections
    as SENDER-OWNED descriptors — one descriptor per payload, no ring DMA
    copy, receiver wraps them in place (cpp/net/ici_transport.h; the rdma
    block_pool takeover analogue).  Land device fetches here
    (np.copyto(view, np.asarray(dev_array))) and pass view.ctypes.data to
    the native call APIs.  Free with free_staging() only after every RPC
    referencing the region has completed."""
    lib = lib or load_library()
    lib.trpc_ici_staging_alloc.restype = ctypes.c_void_p
    lib.trpc_ici_staging_alloc.argtypes = [
        ctypes.c_size_t, ctypes.POINTER(ctypes.c_uint32)]
    ordinal = ctypes.c_uint32()
    base = lib.trpc_ici_staging_alloc(nbytes, ctypes.byref(ordinal))
    if not base:
        raise MemoryError(f"ici staging alloc of {nbytes} bytes failed")
    view = np.frombuffer(
        (ctypes.c_char * nbytes).from_address(base), dtype=np.uint8)
    with _lock:
        _staging[int(base)] = True
    return view


def free_staging(view: np.ndarray, lib=None) -> None:
    """Unregisters and unlinks a slab from alloc_staging; the unmap is
    deferred past any in-flight wrapped references by the native
    refcount.  Pass the slab-base view (what alloc_staging returned, or
    any zero-offset view of it — resolution is by base address); no view
    or slice may be used afterwards."""
    lib = lib or load_library()
    base = int(view.ctypes.data)
    with _lock:
        known = _staging.pop(base, None)
    if known is not None:
        lib.trpc_ici_staging_free.argtypes = [ctypes.c_void_p]
        lib.trpc_ici_staging_free(ctypes.c_void_p(base))


def zero_copy_counters(lib=None) -> tuple[int, int]:
    """Process-wide (descriptors, bytes) sent via the sender-owned path —
    asserts that a staged payload really elided the ring copy."""
    lib = lib or load_library()
    lib.trpc_ici_zero_copy_counters.argtypes = [
        ctypes.POINTER(ctypes.c_uint64), ctypes.POINTER(ctypes.c_uint64)]
    wrs, nbytes = ctypes.c_uint64(), ctypes.c_uint64()
    lib.trpc_ici_zero_copy_counters(ctypes.byref(wrs), ctypes.byref(nbytes))
    return wrs.value, nbytes.value


_staging: dict[int, int] = {}


def block_ptr(iobuf_ptr: int, index: int = 0, lib=None) -> int:
    """Data pointer of an IOBuf block ref (pointer-identity tests)."""
    lib = lib or load_library()
    lib.trpc_iobuf_block_ptr.restype = ctypes.c_void_p
    return lib.trpc_iobuf_block_ptr(ctypes.c_void_p(iobuf_ptr),
                                    ctypes.c_size_t(index)) or 0
