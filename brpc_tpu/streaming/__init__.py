from brpc_tpu.streaming.stream import ring_stream, stream_echo  # noqa: F401
