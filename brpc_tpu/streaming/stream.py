"""Streaming — ordered chunk pipelines with windowed flow control.

Reference parity: brpc's streaming RPC (/root/reference/src/brpc/stream.cpp:
Create :78, AppendIfNotFull credit check :326, Consume :582) delivers ordered
byte chunks with a credit window so a fast writer can't overrun a slow
reader.  TPU-native, a stream between mesh peers is a ``lax.scan`` whose body
moves one chunk per step with ``ppermute``; ordering is the scan order and
"completion" is dataflow — XLA double-buffers the transfer of chunk k+1
against the consumer compute of chunk k, the overlap brpc's credit machinery
exists to enable.
"""

from __future__ import annotations

from typing import Callable

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import PartitionSpec as P

from brpc_tpu.parallel.fabric import Fabric
from brpc_tpu.transport.ici import _ring_perm

__all__ = ["ring_stream", "stream_echo"]


def ring_stream(
    fabric: Fabric,
    axis: str,
    on_chunk: Callable,
    *,
    in_spec=None,
    carry_spec=P(),
    out_spec=None,
    shift: int = 1,
):
    """Build a compiled stream over `axis`: each scan step ppermutes one chunk
    one hop and hands the arrival to ``on_chunk(carry, chunk) -> (carry,
    out)`` on the receiving peer.

    `chunks` must have leading dim = num_chunks; the default specs shard the
    second dim over `axis` (N concurrent streams riding N links — the
    pairwise topology streaming_echo_c++ exercises).  `carry_spec`/`out_spec`
    describe the *global* layout of the scan carry / stacked outputs.
    """
    n = fabric.axis_size(axis)
    perm = _ring_perm(n, shift)
    in_spec = P(None, axis) if in_spec is None else in_spec
    out_spec = P(None, axis) if out_spec is None else out_spec

    def spmd(chunks, carry0):
        def body(carry, chunk):
            arrived = lax.ppermute(chunk, axis, perm)
            return on_chunk(carry, arrived)

        return lax.scan(body, carry0, chunks)

    fn = fabric.spmd(
        spmd, in_specs=(in_spec, carry_spec), out_specs=(carry_spec, out_spec)
    )
    return jax.jit(fn)


def stream_echo(fabric: Fabric, axis: str, num_chunks: int):
    """Bidi stream echo (example/streaming_echo_c++ analogue): every chunk is
    streamed to the right neighbor, checksummed there, and per-chunk sums
    stacked; the carry keeps each receiver's running total (per-peer)."""

    def on_chunk(carry, chunk):
        s = jnp.sum(chunk.astype(jnp.uint32), dtype=jnp.uint32)
        # carry/out are (1,)-shaped per peer so the global view stacks along
        # the stream axis: carry -> (n,), outs -> (num_chunks, n).
        return carry + s[None], s[None]

    return ring_stream(
        fabric,
        axis,
        on_chunk,
        carry_spec=P(axis),
        out_spec=P(None, axis),
    )
