from brpc_tpu.transport.ici import IciTransport  # noqa: F401
