"""IciTransport — inter-chip data movement as compiled XLA collectives.

This is the TPU-native answer to the reference's pluggable ``Transport``
(/root/reference/src/brpc/transport.h:26-64) and its RDMA implementation
(/root/reference/src/brpc/rdma/rdma_endpoint.cpp): where RDMA hand-posts a
work request per message and polls a completion queue, ICI traffic is
*compiled into the program* — a one-sided put is ``lax.ppermute``, N-to-N
exchange is ``lax.all_to_all`` or a ppermute ring, and "completion" is XLA's
dataflow (the consuming op simply depends on the transfer).  The credit
windows of ``rdma_endpoint.h:292-328`` become scan carries
(`brpc_tpu.streaming`).

Every method here is jittable *inside* a shard_map region over the fabric's
mesh; the module-level helpers wrap them for host callers.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import PartitionSpec as P

from brpc_tpu.parallel.fabric import Fabric

__all__ = ["IciTransport"]


def _ring_perm(n: int, shift: int):
    """Source→dest pairs for a cyclic shift along an axis of size n."""
    return [(i, (i + shift) % n) for i in range(n)]


class IciTransport:
    """Point-to-point and collective movement along one mesh axis.

    The reference's RDMA endpoint exposes send (CutFromIOBufList) and posted
    receive buffers (rdma_endpoint.h:250-328); here both directions of a link
    are a single ``ppermute`` whose source and destination buffers XLA
    allocates in HBM — zero-copy by construction, the role the rdma
    ``block_pool`` (src/brpc/rdma/block_pool.cpp) plays for ibverbs.
    """

    def __init__(self, fabric: Fabric, axis: str = "link"):
        self.fabric = fabric
        self.axis = axis
        self.n = fabric.axis_size(axis)

    # -- inside-shard_map primitives -------------------------------------
    def put(self, x, shift: int = 1):
        """One-sided put to the neighbor `shift` hops down the ring."""
        return lax.ppermute(x, self.axis, _ring_perm(self.n, shift))

    def echo(self, x):
        """Round trip: put to right neighbor, neighbor returns it.

        The smallest "RPC" — parity with example/echo_c++ but over ICI.
        """
        return self.put(self.put(x, 1), -1)

    def all_gather(self, x, tiled: bool = False):
        return lax.all_gather(x, self.axis, tiled=tiled)

    def reduce_scatter(self, x, op: str = "sum"):
        assert op == "sum"
        return lax.psum_scatter(x, self.axis, tiled=True)

    def all_to_all(self, x):
        """N-to-N exchange: row i of x goes to peer i (rdma_performance
        analogue, /root/reference/example/rdma_performance/client.cpp)."""
        return lax.all_to_all(x, self.axis, split_axis=0, concat_axis=0, tiled=True)

    def ring_exchange(self, x, on_hop=None):
        """Explicit N-1 hop ring: hop 0 consumes the local chunk in place,
        then each of the N-1 scan steps moves the buffer one hop right and
        feeds the arrival to `on_hop(carry, chunk, hop)`.

        This is the schedule ring-attention / pipelined all-reduce use; XLA
        overlaps hop k+1's DMA with hop k's compute because the scan body
        only serializes through the carry.
        """
        if on_hop is None:
            on_hop = lambda c, chunk, hop: (c + jnp.sum(chunk), None)

        carry, out0 = on_hop(jnp.zeros((), x.dtype), x, 0)

        def body(state, hop):
            buf, carry = state
            buf = self.put(buf, 1)
            carry, out = on_hop(carry, buf, hop)
            return (buf, carry), out

        (buf, carry), outs = lax.scan(body, (x, carry), jnp.arange(1, self.n))
        if out0 is not None:
            outs = jnp.concatenate([out0[None], outs])
        return buf, carry, outs

    # -- host-callable wrappers ------------------------------------------
    def jit_echo(self):
        """Compiled echo over payload sharded along the transport axis."""
        spec = P(self.axis)
        fn = self.fabric.spmd(self.echo, in_specs=spec, out_specs=spec)
        return jax.jit(fn)

    def jit_all_to_all(self):
        spec = P(self.axis)
        fn = self.fabric.spmd(self.all_to_all, in_specs=spec, out_specs=spec)
        return jax.jit(fn)
