file(REMOVE_RECURSE
  "CMakeFiles/bench_echo.dir/tools/bench_echo.cc.o"
  "CMakeFiles/bench_echo.dir/tools/bench_echo.cc.o.d"
  "bench_echo"
  "bench_echo.pdb"
)

# Per-language clean rules from dependency scanning.
foreach(lang CXX)
  include(CMakeFiles/bench_echo.dir/cmake_clean_${lang}.cmake OPTIONAL)
endforeach()
