# Empty dependencies file for bench_echo.
# This may be replaced when dependencies are built.
