
# Consider dependencies only in project.
set(CMAKE_DEPENDS_IN_PROJECT_ONLY OFF)

# The set of languages for which implicit dependencies are needed:
set(CMAKE_DEPENDS_LANGUAGES
  )

# The set of dependency files which are needed:
set(CMAKE_DEPENDS_DEPENDENCY_FILES
  "/root/repo/cpp/tools/rpc_press.cc" "CMakeFiles/rpc_press.dir/tools/rpc_press.cc.o" "gcc" "CMakeFiles/rpc_press.dir/tools/rpc_press.cc.o.d"
  )

# Targets to which this target links.
set(CMAKE_TARGET_LINKED_INFO_FILES
  "/root/repo/build-asan/CMakeFiles/tpurpc.dir/DependInfo.cmake"
  )

# Fortran module output directory.
set(CMAKE_Fortran_TARGET_MODULE_DIR "")
