file(REMOVE_RECURSE
  "CMakeFiles/rpc_press.dir/tools/rpc_press.cc.o"
  "CMakeFiles/rpc_press.dir/tools/rpc_press.cc.o.d"
  "rpc_press"
  "rpc_press.pdb"
)

# Per-language clean rules from dependency scanning.
foreach(lang CXX)
  include(CMakeFiles/rpc_press.dir/cmake_clean_${lang}.cmake OPTIONAL)
endforeach()
