# Empty dependencies file for rpc_press.
# This may be replaced when dependencies are built.
