file(REMOVE_RECURSE
  "CMakeFiles/rpc_replay.dir/tools/rpc_replay.cc.o"
  "CMakeFiles/rpc_replay.dir/tools/rpc_replay.cc.o.d"
  "rpc_replay"
  "rpc_replay.pdb"
)

# Per-language clean rules from dependency scanning.
foreach(lang CXX)
  include(CMakeFiles/rpc_replay.dir/cmake_clean_${lang}.cmake OPTIONAL)
endforeach()
