# Empty dependencies file for rpc_replay.
# This may be replaced when dependencies are built.
