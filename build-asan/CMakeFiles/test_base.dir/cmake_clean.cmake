file(REMOVE_RECURSE
  "CMakeFiles/test_base.dir/tests/test_base.cc.o"
  "CMakeFiles/test_base.dir/tests/test_base.cc.o.d"
  "test_base"
  "test_base.pdb"
)

# Per-language clean rules from dependency scanning.
foreach(lang CXX)
  include(CMakeFiles/test_base.dir/cmake_clean_${lang}.cmake OPTIONAL)
endforeach()
