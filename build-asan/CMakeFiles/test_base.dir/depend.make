# Empty dependencies file for test_base.
# This may be replaced when dependencies are built.
