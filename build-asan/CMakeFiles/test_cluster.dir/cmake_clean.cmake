file(REMOVE_RECURSE
  "CMakeFiles/test_cluster.dir/tests/test_cluster.cc.o"
  "CMakeFiles/test_cluster.dir/tests/test_cluster.cc.o.d"
  "test_cluster"
  "test_cluster.pdb"
)

# Per-language clean rules from dependency scanning.
foreach(lang CXX)
  include(CMakeFiles/test_cluster.dir/cmake_clean_${lang}.cmake OPTIONAL)
endforeach()
