# Empty dependencies file for test_cluster.
# This may be replaced when dependencies are built.
