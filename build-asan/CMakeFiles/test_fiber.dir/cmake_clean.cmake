file(REMOVE_RECURSE
  "CMakeFiles/test_fiber.dir/tests/test_fiber.cc.o"
  "CMakeFiles/test_fiber.dir/tests/test_fiber.cc.o.d"
  "test_fiber"
  "test_fiber.pdb"
)

# Per-language clean rules from dependency scanning.
foreach(lang CXX)
  include(CMakeFiles/test_fiber.dir/cmake_clean_${lang}.cmake OPTIONAL)
endforeach()
