# Empty dependencies file for test_fiber.
# This may be replaced when dependencies are built.
