file(REMOVE_RECURSE
  "CMakeFiles/test_http.dir/tests/test_http.cc.o"
  "CMakeFiles/test_http.dir/tests/test_http.cc.o.d"
  "test_http"
  "test_http.pdb"
)

# Per-language clean rules from dependency scanning.
foreach(lang CXX)
  include(CMakeFiles/test_http.dir/cmake_clean_${lang}.cmake OPTIONAL)
endforeach()
