# Empty dependencies file for test_http.
# This may be replaced when dependencies are built.
