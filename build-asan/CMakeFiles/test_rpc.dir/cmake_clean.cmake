file(REMOVE_RECURSE
  "CMakeFiles/test_rpc.dir/tests/test_rpc.cc.o"
  "CMakeFiles/test_rpc.dir/tests/test_rpc.cc.o.d"
  "test_rpc"
  "test_rpc.pdb"
)

# Per-language clean rules from dependency scanning.
foreach(lang CXX)
  include(CMakeFiles/test_rpc.dir/cmake_clean_${lang}.cmake OPTIONAL)
endforeach()
