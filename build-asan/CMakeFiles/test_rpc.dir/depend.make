# Empty dependencies file for test_rpc.
# This may be replaced when dependencies are built.
