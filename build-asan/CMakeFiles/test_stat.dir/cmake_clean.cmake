file(REMOVE_RECURSE
  "CMakeFiles/test_stat.dir/tests/test_stat.cc.o"
  "CMakeFiles/test_stat.dir/tests/test_stat.cc.o.d"
  "test_stat"
  "test_stat.pdb"
)

# Per-language clean rules from dependency scanning.
foreach(lang CXX)
  include(CMakeFiles/test_stat.dir/cmake_clean_${lang}.cmake OPTIONAL)
endforeach()
