# Empty dependencies file for test_stat.
# This may be replaced when dependencies are built.
