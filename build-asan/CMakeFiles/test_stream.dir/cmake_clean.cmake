file(REMOVE_RECURSE
  "CMakeFiles/test_stream.dir/tests/test_stream.cc.o"
  "CMakeFiles/test_stream.dir/tests/test_stream.cc.o.d"
  "test_stream"
  "test_stream.pdb"
)

# Per-language clean rules from dependency scanning.
foreach(lang CXX)
  include(CMakeFiles/test_stream.dir/cmake_clean_${lang}.cmake OPTIONAL)
endforeach()
