# Empty dependencies file for test_stream.
# This may be replaced when dependencies are built.
