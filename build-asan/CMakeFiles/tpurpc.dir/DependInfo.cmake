
# Consider dependencies only in project.
set(CMAKE_DEPENDS_IN_PROJECT_ONLY OFF)

# The set of languages for which implicit dependencies are needed:
set(CMAKE_DEPENDS_LANGUAGES
  "ASM"
  )
# The set of files for implicit dependencies of each language:
set(CMAKE_DEPENDS_CHECK_ASM
  "/root/repo/cpp/fiber/context.S" "/root/repo/build-asan/CMakeFiles/tpurpc.dir/fiber/context.S.o"
  )
set(CMAKE_ASM_COMPILER_ID "GNU")

# Preprocessor definitions for this target.
set(CMAKE_TARGET_DEFINITIONS_ASM
  "tpurpc_EXPORTS"
  )

# The include file search paths:
set(CMAKE_ASM_TARGET_INCLUDE_PATH
  "/root/repo/cpp"
  )

# The set of dependency files which are needed:
set(CMAKE_DEPENDS_DEPENDENCY_FILES
  "/root/repo/cpp/base/arena.cc" "CMakeFiles/tpurpc.dir/base/arena.cc.o" "gcc" "CMakeFiles/tpurpc.dir/base/arena.cc.o.d"
  "/root/repo/cpp/base/endpoint.cc" "CMakeFiles/tpurpc.dir/base/endpoint.cc.o" "gcc" "CMakeFiles/tpurpc.dir/base/endpoint.cc.o.d"
  "/root/repo/cpp/base/iobuf.cc" "CMakeFiles/tpurpc.dir/base/iobuf.cc.o" "gcc" "CMakeFiles/tpurpc.dir/base/iobuf.cc.o.d"
  "/root/repo/cpp/base/logging.cc" "CMakeFiles/tpurpc.dir/base/logging.cc.o" "gcc" "CMakeFiles/tpurpc.dir/base/logging.cc.o.d"
  "/root/repo/cpp/base/recordio.cc" "CMakeFiles/tpurpc.dir/base/recordio.cc.o" "gcc" "CMakeFiles/tpurpc.dir/base/recordio.cc.o.d"
  "/root/repo/cpp/capi/base_capi.cc" "CMakeFiles/tpurpc.dir/capi/base_capi.cc.o" "gcc" "CMakeFiles/tpurpc.dir/capi/base_capi.cc.o.d"
  "/root/repo/cpp/capi/rpc_capi.cc" "CMakeFiles/tpurpc.dir/capi/rpc_capi.cc.o" "gcc" "CMakeFiles/tpurpc.dir/capi/rpc_capi.cc.o.d"
  "/root/repo/cpp/fiber/event.cc" "CMakeFiles/tpurpc.dir/fiber/event.cc.o" "gcc" "CMakeFiles/tpurpc.dir/fiber/event.cc.o.d"
  "/root/repo/cpp/fiber/fid.cc" "CMakeFiles/tpurpc.dir/fiber/fid.cc.o" "gcc" "CMakeFiles/tpurpc.dir/fiber/fid.cc.o.d"
  "/root/repo/cpp/fiber/fls.cc" "CMakeFiles/tpurpc.dir/fiber/fls.cc.o" "gcc" "CMakeFiles/tpurpc.dir/fiber/fls.cc.o.d"
  "/root/repo/cpp/fiber/scheduler.cc" "CMakeFiles/tpurpc.dir/fiber/scheduler.cc.o" "gcc" "CMakeFiles/tpurpc.dir/fiber/scheduler.cc.o.d"
  "/root/repo/cpp/fiber/stack.cc" "CMakeFiles/tpurpc.dir/fiber/stack.cc.o" "gcc" "CMakeFiles/tpurpc.dir/fiber/stack.cc.o.d"
  "/root/repo/cpp/fiber/timer.cc" "CMakeFiles/tpurpc.dir/fiber/timer.cc.o" "gcc" "CMakeFiles/tpurpc.dir/fiber/timer.cc.o.d"
  "/root/repo/cpp/net/builtin.cc" "CMakeFiles/tpurpc.dir/net/builtin.cc.o" "gcc" "CMakeFiles/tpurpc.dir/net/builtin.cc.o.d"
  "/root/repo/cpp/net/channel.cc" "CMakeFiles/tpurpc.dir/net/channel.cc.o" "gcc" "CMakeFiles/tpurpc.dir/net/channel.cc.o.d"
  "/root/repo/cpp/net/cluster.cc" "CMakeFiles/tpurpc.dir/net/cluster.cc.o" "gcc" "CMakeFiles/tpurpc.dir/net/cluster.cc.o.d"
  "/root/repo/cpp/net/dispatcher.cc" "CMakeFiles/tpurpc.dir/net/dispatcher.cc.o" "gcc" "CMakeFiles/tpurpc.dir/net/dispatcher.cc.o.d"
  "/root/repo/cpp/net/http_protocol.cc" "CMakeFiles/tpurpc.dir/net/http_protocol.cc.o" "gcc" "CMakeFiles/tpurpc.dir/net/http_protocol.cc.o.d"
  "/root/repo/cpp/net/messenger.cc" "CMakeFiles/tpurpc.dir/net/messenger.cc.o" "gcc" "CMakeFiles/tpurpc.dir/net/messenger.cc.o.d"
  "/root/repo/cpp/net/protocol.cc" "CMakeFiles/tpurpc.dir/net/protocol.cc.o" "gcc" "CMakeFiles/tpurpc.dir/net/protocol.cc.o.d"
  "/root/repo/cpp/net/server.cc" "CMakeFiles/tpurpc.dir/net/server.cc.o" "gcc" "CMakeFiles/tpurpc.dir/net/server.cc.o.d"
  "/root/repo/cpp/net/socket.cc" "CMakeFiles/tpurpc.dir/net/socket.cc.o" "gcc" "CMakeFiles/tpurpc.dir/net/socket.cc.o.d"
  "/root/repo/cpp/net/stream.cc" "CMakeFiles/tpurpc.dir/net/stream.cc.o" "gcc" "CMakeFiles/tpurpc.dir/net/stream.cc.o.d"
  "/root/repo/cpp/net/tcp_transport.cc" "CMakeFiles/tpurpc.dir/net/tcp_transport.cc.o" "gcc" "CMakeFiles/tpurpc.dir/net/tcp_transport.cc.o.d"
  "/root/repo/cpp/stat/latency_recorder.cc" "CMakeFiles/tpurpc.dir/stat/latency_recorder.cc.o" "gcc" "CMakeFiles/tpurpc.dir/stat/latency_recorder.cc.o.d"
  "/root/repo/cpp/stat/sampler.cc" "CMakeFiles/tpurpc.dir/stat/sampler.cc.o" "gcc" "CMakeFiles/tpurpc.dir/stat/sampler.cc.o.d"
  "/root/repo/cpp/stat/variable.cc" "CMakeFiles/tpurpc.dir/stat/variable.cc.o" "gcc" "CMakeFiles/tpurpc.dir/stat/variable.cc.o.d"
  )

# Targets to which this target links.
set(CMAKE_TARGET_LINKED_INFO_FILES
  )

# Fortran module output directory.
set(CMAKE_Fortran_TARGET_MODULE_DIR "")
