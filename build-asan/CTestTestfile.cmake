# CMake generated Testfile for 
# Source directory: /root/repo/cpp
# Build directory: /root/repo/build-asan
# 
# This file includes the relevant testing commands required for 
# testing this directory and lists subdirectories to be tested as well.
add_test(test_base "/root/repo/build-asan/test_base")
set_tests_properties(test_base PROPERTIES  _BACKTRACE_TRIPLES "/root/repo/cpp/CMakeLists.txt;41;add_test;/root/repo/cpp/CMakeLists.txt;0;")
add_test(test_cluster "/root/repo/build-asan/test_cluster")
set_tests_properties(test_cluster PROPERTIES  _BACKTRACE_TRIPLES "/root/repo/cpp/CMakeLists.txt;41;add_test;/root/repo/cpp/CMakeLists.txt;0;")
add_test(test_fiber "/root/repo/build-asan/test_fiber")
set_tests_properties(test_fiber PROPERTIES  _BACKTRACE_TRIPLES "/root/repo/cpp/CMakeLists.txt;41;add_test;/root/repo/cpp/CMakeLists.txt;0;")
add_test(test_http "/root/repo/build-asan/test_http")
set_tests_properties(test_http PROPERTIES  _BACKTRACE_TRIPLES "/root/repo/cpp/CMakeLists.txt;41;add_test;/root/repo/cpp/CMakeLists.txt;0;")
add_test(test_rpc "/root/repo/build-asan/test_rpc")
set_tests_properties(test_rpc PROPERTIES  _BACKTRACE_TRIPLES "/root/repo/cpp/CMakeLists.txt;41;add_test;/root/repo/cpp/CMakeLists.txt;0;")
add_test(test_stat "/root/repo/build-asan/test_stat")
set_tests_properties(test_stat PROPERTIES  _BACKTRACE_TRIPLES "/root/repo/cpp/CMakeLists.txt;41;add_test;/root/repo/cpp/CMakeLists.txt;0;")
add_test(test_stream "/root/repo/build-asan/test_stream")
set_tests_properties(test_stream PROPERTIES  _BACKTRACE_TRIPLES "/root/repo/cpp/CMakeLists.txt;41;add_test;/root/repo/cpp/CMakeLists.txt;0;")
