
# Consider dependencies only in project.
set(CMAKE_DEPENDS_IN_PROJECT_ONLY OFF)

# The set of languages for which implicit dependencies are needed:
set(CMAKE_DEPENDS_LANGUAGES
  )

# The set of dependency files which are needed:
set(CMAKE_DEPENDS_DEPENDENCY_FILES
  "/root/repo/cpp/base/arena.cc" "CMakeFiles/tpurpc.dir/base/arena.cc.o" "gcc" "CMakeFiles/tpurpc.dir/base/arena.cc.o.d"
  "/root/repo/cpp/base/endpoint.cc" "CMakeFiles/tpurpc.dir/base/endpoint.cc.o" "gcc" "CMakeFiles/tpurpc.dir/base/endpoint.cc.o.d"
  "/root/repo/cpp/base/iobuf.cc" "CMakeFiles/tpurpc.dir/base/iobuf.cc.o" "gcc" "CMakeFiles/tpurpc.dir/base/iobuf.cc.o.d"
  "/root/repo/cpp/base/logging.cc" "CMakeFiles/tpurpc.dir/base/logging.cc.o" "gcc" "CMakeFiles/tpurpc.dir/base/logging.cc.o.d"
  "/root/repo/cpp/capi/base_capi.cc" "CMakeFiles/tpurpc.dir/capi/base_capi.cc.o" "gcc" "CMakeFiles/tpurpc.dir/capi/base_capi.cc.o.d"
  )

# Targets to which this target links.
set(CMAKE_TARGET_LINKED_INFO_FILES
  )

# Fortran module output directory.
set(CMAKE_Fortran_TARGET_MODULE_DIR "")
