file(REMOVE_RECURSE
  "CMakeFiles/tpurpc.dir/base/arena.cc.o"
  "CMakeFiles/tpurpc.dir/base/arena.cc.o.d"
  "CMakeFiles/tpurpc.dir/base/endpoint.cc.o"
  "CMakeFiles/tpurpc.dir/base/endpoint.cc.o.d"
  "CMakeFiles/tpurpc.dir/base/iobuf.cc.o"
  "CMakeFiles/tpurpc.dir/base/iobuf.cc.o.d"
  "CMakeFiles/tpurpc.dir/base/logging.cc.o"
  "CMakeFiles/tpurpc.dir/base/logging.cc.o.d"
  "CMakeFiles/tpurpc.dir/capi/base_capi.cc.o"
  "CMakeFiles/tpurpc.dir/capi/base_capi.cc.o.d"
  "libtpurpc.pdb"
  "libtpurpc.so"
)

# Per-language clean rules from dependency scanning.
foreach(lang CXX)
  include(CMakeFiles/tpurpc.dir/cmake_clean_${lang}.cmake OPTIONAL)
endforeach()
