# Empty dependencies file for tpurpc.
# This may be replaced when dependencies are built.
