# CMake generated Testfile for 
# Source directory: /root/repo/cpp
# Build directory: /root/repo/build
# 
# This file includes the relevant testing commands required for 
# testing this directory and lists subdirectories to be tested as well.
add_test(test_base "/root/repo/build/test_base")
set_tests_properties(test_base PROPERTIES  _BACKTRACE_TRIPLES "/root/repo/cpp/CMakeLists.txt;33;add_test;/root/repo/cpp/CMakeLists.txt;0;")
