#include "base/arena.h"

#include <cstdlib>
#include <new>
#include <vector>

#include "base/tls_cache.h"

namespace trpc {

namespace {

struct BlockCacheTag {};

void drain_block(Block*& b) { free(b); }

std::vector<Block*>* tls_cache() {
  return TlsFreeCache<Block*, BlockCacheTag>::get(&drain_block);
}

constexpr size_t kMaxCachedBlocks = 64;

}  // namespace

void Block::release() {
  if (ref.fetch_sub(1, std::memory_order_acq_rel) == 1) {
    if (user_deleter != nullptr) {
      user_deleter(data, user_ctx);
      free(this);
    } else {
      arena->deallocate(this);
    }
  }
}

HostArena* HostArena::instance() {
  // Deliberately leaked: blocks may be released at/after static destruction.
  static HostArena* arena = new HostArena();
  return arena;
}

Block* HostArena::allocate(uint32_t min_cap) {
  std::vector<Block*>* cache = tls_cache();
  if (min_cap <= kDefaultBlockSize && cache != nullptr && !cache->empty()) {
    Block* b = cache->back();
    cache->pop_back();
    b->ref.store(1, std::memory_order_relaxed);
    b->size = 0;
    return b;
  }
  const uint32_t cap = min_cap <= kDefaultBlockSize
                           ? kDefaultBlockSize
                           : min_cap;
  void* mem = malloc(sizeof(Block) + cap);
  if (mem == nullptr) {
    throw std::bad_alloc();
  }
  Block* b = new (mem) Block();
  b->cap = cap;
  b->arena = this;
  b->data = reinterpret_cast<char*>(mem) + sizeof(Block);
  return b;
}

void HostArena::deallocate(Block* b) {
  std::vector<Block*>* cache = tls_cache();
  if (b->cap == kDefaultBlockSize && cache != nullptr &&
      cache->size() < kMaxCachedBlocks) {
    cache->push_back(b);
    return;
  }
  free(b);
}

void HostArena::flush_tls_cache() {
  std::vector<Block*>* cache = tls_cache();
  if (cache == nullptr) {
    return;
  }
  for (Block* b : *cache) {
    free(b);
  }
  cache->clear();
}

Block* make_user_block(void* data, uint32_t len,
                       void (*deleter)(void*, void*), void* ctx,
                       uint64_t meta) {
  Block* b = new (malloc(sizeof(Block))) Block();
  b->cap = len;
  b->size = len;
  b->data = static_cast<char*>(data);
  b->user_deleter = deleter;
  b->user_ctx = ctx;
  b->user_meta = meta;
  return b;
}

}  // namespace trpc
