#include "base/arena.h"

#include <cstdlib>
#include <new>
#include <vector>

namespace trpc {

namespace {

// Heap-owned TLS cache behind trivially-destructible thread_locals: blocks
// are released during static destruction (sockets in static servers), after
// this thread's non-trivial TLS has already died.
struct TlsBlockCache {
  std::vector<Block*> blocks;
};

struct TlsCacheGuard {
  TlsBlockCache** slot = nullptr;
  bool* dead = nullptr;
  ~TlsCacheGuard() {
    if (slot != nullptr && *slot != nullptr) {
      for (Block* b : (*slot)->blocks) {
        free(b);
      }
      delete *slot;
      *slot = nullptr;
    }
    if (dead != nullptr) {
      *dead = true;
    }
  }
};

TlsBlockCache* tls_cache() {
  static thread_local TlsBlockCache* cache = nullptr;  // trivial dtor
  static thread_local bool cache_dead = false;
  static thread_local TlsCacheGuard guard;
  if (cache_dead) {
    return nullptr;
  }
  if (cache == nullptr) {
    cache = new TlsBlockCache();
    guard.slot = &cache;
    guard.dead = &cache_dead;
  }
  return cache;
}

constexpr size_t kMaxCachedBlocks = 64;

}  // namespace

void Block::release() {
  if (ref.fetch_sub(1, std::memory_order_acq_rel) == 1) {
    if (user_deleter != nullptr) {
      user_deleter(data, user_ctx);
      free(this);
    } else {
      arena->deallocate(this);
    }
  }
}

HostArena* HostArena::instance() {
  // Deliberately leaked: blocks may be released at/after static destruction.
  static HostArena* arena = new HostArena();
  return arena;
}

Block* HostArena::allocate(uint32_t min_cap) {
  TlsBlockCache* cache = tls_cache();
  if (min_cap <= kDefaultBlockSize && cache != nullptr &&
      !cache->blocks.empty()) {
    Block* b = cache->blocks.back();
    cache->blocks.pop_back();
    b->ref.store(1, std::memory_order_relaxed);
    b->size = 0;
    return b;
  }
  const uint32_t cap = min_cap <= kDefaultBlockSize
                           ? kDefaultBlockSize
                           : min_cap;
  void* mem = malloc(sizeof(Block) + cap);
  if (mem == nullptr) {
    throw std::bad_alloc();
  }
  Block* b = new (mem) Block();
  b->cap = cap;
  b->arena = this;
  b->data = reinterpret_cast<char*>(mem) + sizeof(Block);
  return b;
}

void HostArena::deallocate(Block* b) {
  TlsBlockCache* cache = tls_cache();
  if (b->cap == kDefaultBlockSize && cache != nullptr &&
      cache->blocks.size() < kMaxCachedBlocks) {
    cache->blocks.push_back(b);
    return;
  }
  free(b);
}

void HostArena::flush_tls_cache() {
  TlsBlockCache* cache = tls_cache();
  if (cache == nullptr) {
    return;
  }
  for (Block* b : cache->blocks) {
    free(b);
  }
  cache->blocks.clear();
}

Block* make_user_block(void* data, uint32_t len,
                       void (*deleter)(void*, void*), void* ctx,
                       uint64_t meta) {
  Block* b = new (malloc(sizeof(Block))) Block();
  b->cap = len;
  b->size = len;
  b->data = static_cast<char*>(data);
  b->user_deleter = deleter;
  b->user_ctx = ctx;
  b->user_meta = meta;
  return b;
}

}  // namespace trpc
