#include "base/arena.h"

#include <cstdlib>
#include <new>
#include <vector>

namespace trpc {

namespace {

struct TlsBlockCache {
  std::vector<Block*> blocks;
  ~TlsBlockCache() {
    for (Block* b : blocks) {
      free(b);
    }
    blocks.clear();
  }
};

thread_local TlsBlockCache g_tls_cache;
constexpr size_t kMaxCachedBlocks = 64;

}  // namespace

void Block::release() {
  if (ref.fetch_sub(1, std::memory_order_acq_rel) == 1) {
    if (user_deleter != nullptr) {
      user_deleter(data, user_ctx);
      free(this);
    } else {
      arena->deallocate(this);
    }
  }
}

HostArena* HostArena::instance() {
  static HostArena arena;
  return &arena;
}

Block* HostArena::allocate(uint32_t min_cap) {
  if (min_cap <= kDefaultBlockSize && !g_tls_cache.blocks.empty()) {
    Block* b = g_tls_cache.blocks.back();
    g_tls_cache.blocks.pop_back();
    b->ref.store(1, std::memory_order_relaxed);
    b->size = 0;
    return b;
  }
  const uint32_t cap = min_cap <= kDefaultBlockSize
                           ? kDefaultBlockSize
                           : min_cap;
  void* mem = malloc(sizeof(Block) + cap);
  if (mem == nullptr) {
    throw std::bad_alloc();
  }
  Block* b = new (mem) Block();
  b->cap = cap;
  b->arena = this;
  b->data = reinterpret_cast<char*>(mem) + sizeof(Block);
  return b;
}

void HostArena::deallocate(Block* b) {
  if (b->cap == kDefaultBlockSize &&
      g_tls_cache.blocks.size() < kMaxCachedBlocks) {
    g_tls_cache.blocks.push_back(b);
    return;
  }
  free(b);
}

void HostArena::flush_tls_cache() {
  for (Block* b : g_tls_cache.blocks) {
    free(b);
  }
  g_tls_cache.blocks.clear();
}

Block* make_user_block(void* data, uint32_t len,
                       void (*deleter)(void*, void*), void* ctx,
                       uint64_t meta) {
  Block* b = new (malloc(sizeof(Block))) Block();
  b->cap = len;
  b->size = len;
  b->data = static_cast<char*>(data);
  b->user_deleter = deleter;
  b->user_ctx = ctx;
  b->user_meta = meta;
  return b;
}

}  // namespace trpc
