#include "base/arena.h"

#include <cstdlib>
#include <mutex>
#include <new>
#include <vector>

#include "base/flags.h"
#include "base/tls_cache.h"

namespace trpc {

namespace {

struct BlockCacheTag {};

void drain_block(Block*& b) { free(b); }

std::vector<Block*>* tls_cache() {
  return TlsFreeCache<Block*, BlockCacheTag>::get(&drain_block);
}

constexpr size_t kMaxCachedBlocks = 64;

// ---- big-block pool ------------------------------------------------------
// Size classes are powers of two from kBigBlockMin up to 1GB; allocate
// rounds up so a released block serves any later request of its class.
// One mutex is fine here: big blocks move at MB granularity (thousands of
// ops/s at line rate), never per small message.

constexpr int kBigClasses = 13;  // 256KB << 0 .. 256KB << 12 (1GB)

int big_class_of(uint32_t cap) {
  int cls = 0;
  uint64_t sz = HostArena::kBigBlockMin;
  while (sz < cap && cls < kBigClasses - 1) {
    sz <<= 1;
    ++cls;
  }
  return sz >= cap ? cls : -1;
}

uint32_t big_class_bytes(int cls) {
  return HostArena::kBigBlockMin << cls;
}

std::mutex& big_mu() {
  static std::mutex* mu = new std::mutex();
  return *mu;
}
// Deliberately leaked (like the mutex): detached poller/timer threads may
// release big blocks after static destruction, and a destructed vector
// under a still-valid mutex would be a shutdown use-after-free.
std::vector<Block*>* const g_big_pool = new std::vector<Block*>[kBigClasses];
size_t g_big_pool_bytes = 0;

Flag* big_pool_cap_flag() {
  static Flag* f = [] {
    Flag* flag = Flag::define_int64(
        "trpc_big_block_pool_bytes", 1ll << 30,
        "byte cap on pooled large IOBuf blocks (bulk reads + stripe "
        "landing buffers); blocks over the cap free to the heap");
    if (flag != nullptr) {
      flag->set_validator([](const std::string& v) {
        char* end = nullptr;
        const long long n = strtoll(v.c_str(), &end, 10);
        return end != v.c_str() && *end == '\0' && n >= 0;
      });
    }
    return flag;
  }();
  return f;
}

// Eager definition: the flag must be settable (and visible in /flags)
// before the first big-block release would lazily create it.
[[maybe_unused]] Flag* const g_big_pool_flag_eager = big_pool_cap_flag();

}  // namespace

void Block::release() {
  if (ref.fetch_sub(1, std::memory_order_acq_rel) == 1) {
    if (user_deleter != nullptr) {
      user_deleter(data, user_ctx);
      free(this);
    } else {
      arena->deallocate(this);
    }
  }
}

HostArena* HostArena::instance() {
  // Deliberately leaked: blocks may be released at/after static destruction.
  static HostArena* arena = new HostArena();
  return arena;
}

Block* HostArena::allocate(uint32_t min_cap) {
  std::vector<Block*>* cache = tls_cache();
  if (min_cap <= kDefaultBlockSize && cache != nullptr && !cache->empty()) {
    Block* b = cache->back();
    cache->pop_back();
    b->ref.store(1, std::memory_order_relaxed);
    b->size = 0;
    return b;
  }
  uint32_t cap = min_cap <= kDefaultBlockSize ? kDefaultBlockSize : min_cap;
  if (min_cap >= kBigBlockMin) {
    const int cls = big_class_of(min_cap);
    if (cls >= 0) {
      cap = big_class_bytes(cls);  // pow2 class so releases are reusable
      std::lock_guard<std::mutex> g(big_mu());
      if (!g_big_pool[cls].empty()) {
        Block* b = g_big_pool[cls].back();
        g_big_pool[cls].pop_back();
        g_big_pool_bytes -= b->cap;
        b->ref.store(1, std::memory_order_relaxed);
        b->size = 0;
        return b;
      }
    }
  }
  void* mem = malloc(sizeof(Block) + cap);
  if (mem == nullptr) {
    throw std::bad_alloc();
  }
  Block* b = new (mem) Block();
  b->cap = cap;
  b->arena = this;
  b->data = reinterpret_cast<char*>(mem) + sizeof(Block);
  return b;
}

void HostArena::deallocate(Block* b) {
  std::vector<Block*>* cache = tls_cache();
  if (b->cap == kDefaultBlockSize && cache != nullptr &&
      cache->size() < kMaxCachedBlocks) {
    cache->push_back(b);
    return;
  }
  if (b->cap >= kBigBlockMin) {
    const int cls = big_class_of(b->cap);
    if (cls >= 0 && big_class_bytes(cls) == b->cap) {
      const size_t cap_bytes = static_cast<size_t>(
          big_pool_cap_flag() != nullptr
              ? big_pool_cap_flag()->int64_value()
              : 0);
      std::lock_guard<std::mutex> g(big_mu());
      if (g_big_pool_bytes + b->cap <= cap_bytes) {
        g_big_pool[cls].push_back(b);
        g_big_pool_bytes += b->cap;
        return;
      }
    }
  }
  free(b);
}

size_t HostArena::big_pool_bytes() {
  std::lock_guard<std::mutex> g(big_mu());
  return g_big_pool_bytes;
}

void HostArena::flush_big_pool() {
  std::lock_guard<std::mutex> g(big_mu());
  for (int cls = 0; cls < kBigClasses; ++cls) {
    for (Block* b : g_big_pool[cls]) {
      free(b);
    }
    g_big_pool[cls].clear();
  }
  g_big_pool_bytes = 0;
}

void HostArena::flush_tls_cache() {
  std::vector<Block*>* cache = tls_cache();
  if (cache == nullptr) {
    return;
  }
  for (Block* b : *cache) {
    free(b);
  }
  cache->clear();
}

Block* make_user_block(void* data, uint32_t len,
                       void (*deleter)(void*, void*), void* ctx,
                       uint64_t meta) {
  Block* b = new (malloc(sizeof(Block))) Block();
  b->cap = len;
  b->size = len;
  b->data = static_cast<char*>(data);
  b->user_deleter = deleter;
  b->user_ctx = ctx;
  b->user_meta = meta;
  return b;
}

}  // namespace trpc
