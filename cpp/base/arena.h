// Block arenas — the pluggable allocator seam under IOBuf.
//
// Parity: butil::IOBuf's 8KB ref-counted blocks
// (/root/reference/src/butil/iobuf.cpp:47, iobuf.h:82) plus the fork's RDMA
// block_pool which swaps IOBuf allocation to DMA-registered memory
// (/root/reference/src/brpc/rdma/block_pool.cpp).  Designed day-1 for two
// arenas: the host heap arena below, and an HBM/DMA-registered arena with the
// same interface so device-visible buffers flow through the same IOBuf type
// (`user_meta` carries the device handle where RDMA carried lkeys).
#pragma once

#include <atomic>
#include <cstddef>
#include <cstdint>

namespace trpc {

class BlockArena;

// A ref-counted contiguous region.  `size` is the append cursor: bytes
// [0, size) are immutable once another reference can observe them; an IOBuf
// may extend [size, cap) only while it holds the sole reference.
struct Block {
  std::atomic<int32_t> ref{1};
  uint32_t cap = 0;
  uint32_t size = 0;
  BlockArena* arena = nullptr;
  char* data = nullptr;
  // Set for user-owned memory blocks (zero-copy append_user_data):
  void (*user_deleter)(void* data, void* ctx) = nullptr;
  void* user_ctx = nullptr;
  uint64_t user_meta = 0;  // device handle / lkey analogue

  void add_ref() { ref.fetch_add(1, std::memory_order_relaxed); }
  void release();  // frees via arena or user_deleter when count hits 0
};

class BlockArena {
 public:
  virtual ~BlockArena() = default;
  // Returns a block with ref == 1, size == 0, cap >= min_cap.
  virtual Block* allocate(uint32_t min_cap) = 0;
  virtual void deallocate(Block* b) = 0;
};

// Default heap arena: header+payload in one allocation, thread-local free
// cache of default-size blocks (parity: iobuf TLS block caching used at
// input_messenger.cpp:239), plus a global size-classed pool of LARGE
// blocks.  Large blocks exist for the bulk data path (multi-MB reads and
// stripe landing buffers — net/stripe.h): a fresh multi-MB malloc per
// message means fresh mmap'd pages, and first-touch page faults are what
// caps large-transfer goodput on paravirtualized kernels.  Pooled blocks
// keep their pages warm; the pool is byte-capped (reloadable flag
// trpc_big_block_pool_bytes) and classes are powers of two.
class HostArena : public BlockArena {
 public:
  static constexpr uint32_t kDefaultBlockSize = 8192;
  // Blocks at/above this capacity go through the big-block pool.
  static constexpr uint32_t kBigBlockMin = 256 * 1024;
  static HostArena* instance();

  Block* allocate(uint32_t min_cap) override;
  void deallocate(Block* b) override;

  // Drop this thread's cached blocks (called on thread exit / tests).
  static void flush_tls_cache();
  // Bytes currently parked in the big-block pool (tests/introspection).
  static size_t big_pool_bytes();
  // Free every pooled big block (tests reclaiming memory between cases).
  static void flush_big_pool();
};

// Wraps caller-owned memory in a Block without copying.
Block* make_user_block(void* data, uint32_t len,
                       void (*deleter)(void*, void*), void* ctx,
                       uint64_t meta);

}  // namespace trpc
