#include "base/compress.h"

#include <zlib.h>

#include "base/snappy.h"

#include <cstring>
#include <vector>

namespace trpc {

namespace {

// ---- zlib-backed compressors -------------------------------------------
// windowBits selects the wrapping: 15+16 = gzip, 15 = zlib (RFC 1950).

bool deflate_iobuf(const IOBuf& in, IOBuf* out, int window_bits) {
  z_stream zs;
  memset(&zs, 0, sizeof(zs));
  if (deflateInit2(&zs, Z_DEFAULT_COMPRESSION, Z_DEFLATED, window_bits, 8,
                   Z_DEFAULT_STRATEGY) != Z_OK) {
    return false;
  }
  // Feed block by block (zero copies beyond zlib's own window).
  std::vector<char> buf(64 * 1024);
  const size_t nblocks = in.block_count();
  for (size_t b = 0; b < nblocks; ++b) {
    const IOBuf::BlockRef& ref = in.ref_at(b);
    zs.next_in =
        reinterpret_cast<Bytef*>(const_cast<char*>(ref.block->data) +
                                 ref.offset);
    zs.avail_in = ref.length;
    const int flush = b + 1 == nblocks ? Z_FINISH : Z_NO_FLUSH;
    do {
      zs.next_out = reinterpret_cast<Bytef*>(buf.data());
      zs.avail_out = static_cast<uInt>(buf.size());
      const int rc = deflate(&zs, flush);
      if (rc == Z_STREAM_ERROR) {
        deflateEnd(&zs);
        return false;
      }
      out->append(buf.data(), buf.size() - zs.avail_out);
    } while (zs.avail_out == 0);
  }
  if (nblocks == 0) {  // empty input still needs the trailer
    zs.next_out = reinterpret_cast<Bytef*>(buf.data());
    zs.avail_out = static_cast<uInt>(buf.size());
    deflate(&zs, Z_FINISH);
    out->append(buf.data(), buf.size() - zs.avail_out);
  }
  deflateEnd(&zs);
  return true;
}

bool inflate_iobuf(const IOBuf& in, IOBuf* out, int window_bits,
                   uint64_t size_limit) {
  z_stream zs;
  memset(&zs, 0, sizeof(zs));
  if (inflateInit2(&zs, window_bits) != Z_OK) {
    return false;
  }
  std::vector<char> buf(64 * 1024);
  uint64_t total = 0;
  int rc = Z_OK;
  const size_t nblocks = in.block_count();
  for (size_t b = 0; b < nblocks && rc != Z_STREAM_END; ++b) {
    const IOBuf::BlockRef& ref = in.ref_at(b);
    zs.next_in =
        reinterpret_cast<Bytef*>(const_cast<char*>(ref.block->data) +
                                 ref.offset);
    zs.avail_in = ref.length;
    // Keep inflating while input remains OR the last call filled the
    // output chunk (pending window output with avail_in already 0 —
    // stopping there would truncate a valid stream).
    bool out_full = true;
    while ((zs.avail_in > 0 || out_full) && rc != Z_STREAM_END) {
      zs.next_out = reinterpret_cast<Bytef*>(buf.data());
      zs.avail_out = static_cast<uInt>(buf.size());
      rc = inflate(&zs, Z_NO_FLUSH);
      if (rc != Z_OK && rc != Z_STREAM_END) {
        inflateEnd(&zs);
        return false;  // corrupt stream
      }
      const size_t produced = buf.size() - zs.avail_out;
      out_full = zs.avail_out == 0;
      total += produced;
      if (total > size_limit) {  // zip-bomb guard
        inflateEnd(&zs);
        return false;
      }
      out->append(buf.data(), produced);
    }
  }
  inflateEnd(&zs);
  return rc == Z_STREAM_END;
}

bool gzip_compress(const IOBuf& in, IOBuf* out) {
  return deflate_iobuf(in, out, 15 + 16);
}
bool gzip_decompress(const IOBuf& in, IOBuf* out, uint64_t limit) {
  return inflate_iobuf(in, out, 15 + 16, limit);
}
bool zlib_compress(const IOBuf& in, IOBuf* out) {
  return deflate_iobuf(in, out, 15);
}
bool zlib_decompress(const IOBuf& in, IOBuf* out, uint64_t limit) {
  return inflate_iobuf(in, out, 15, limit);
}

// Snappy's matcher needs random access to the uncompressed bytes, so
// both directions flatten (the reference's snappy sink/source adapters
// do the same internally for chained buffers).
bool snappy_c(const IOBuf& in, IOBuf* out) {
  const std::string flat = in.to_string();
  std::string wire;
  snappy_compress(flat.data(), flat.size(), &wire);
  out->append(wire);
  return true;
}
bool snappy_d(const IOBuf& in, IOBuf* out, uint64_t limit) {
  const std::string flat = in.to_string();
  std::string plain;
  if (!snappy_decompress(flat.data(), flat.size(), &plain, limit)) {
    return false;
  }
  out->append(plain);
  return true;
}

const Compressor kGzipC = {"gzip", gzip_compress, gzip_decompress};
const Compressor kZlibC = {"zlib", zlib_compress, zlib_decompress};
const Compressor kSnappyC = {"snappy", snappy_c, snappy_d};

// ---- crc32c -------------------------------------------------------------

// Software table (Castagnoli polynomial 0x1EDC6F41, reflected 0x82F63B78).
const uint32_t* sw_table() {
  static uint32_t* table = [] {
    auto* t = new uint32_t[256];
    for (uint32_t i = 0; i < 256; ++i) {
      uint32_t c = i;
      for (int k = 0; k < 8; ++k) {
        c = (c & 1) ? 0x82F63B78u ^ (c >> 1) : c >> 1;
      }
      t[i] = c;
    }
    return t;
  }();
  return table;
}

uint32_t crc32c_sw(const uint8_t* p, size_t n, uint32_t crc) {
  const uint32_t* t = sw_table();
  for (size_t i = 0; i < n; ++i) {
    crc = t[(crc ^ p[i]) & 0xff] ^ (crc >> 8);
  }
  return crc;
}

#if defined(__x86_64__)
__attribute__((target("sse4.2"))) uint32_t crc32c_hw(const uint8_t* p,
                                                     size_t n, uint32_t crc) {
  uint64_t c = crc;
  while (n >= 8) {
    uint64_t v;
    memcpy(&v, p, 8);
    c = __builtin_ia32_crc32di(c, v);
    p += 8;
    n -= 8;
  }
  uint32_t c32 = static_cast<uint32_t>(c);
  while (n > 0) {
    c32 = __builtin_ia32_crc32qi(c32, *p);
    ++p;
    --n;
  }
  return c32;
}

bool have_sse42() {
  static const bool have = __builtin_cpu_supports("sse4.2");
  return have;
}
#endif

}  // namespace

const Compressor* find_compressor(CompressType type) {
  switch (type) {
    case CompressType::kGzip:
      return &kGzipC;
    case CompressType::kZlib:
      return &kZlibC;
    case CompressType::kSnappy:
      return &kSnappyC;
    case CompressType::kNone:
    default:
      return nullptr;
  }
}

uint32_t crc32c(const void* data, size_t n, uint32_t seed) {
  const uint8_t* p = static_cast<const uint8_t*>(data);
  uint32_t crc = seed ^ 0xffffffffu;
#if defined(__x86_64__)
  if (have_sse42()) {
    return crc32c_hw(p, n, crc) ^ 0xffffffffu;
  }
#endif
  return crc32c_sw(p, n, crc) ^ 0xffffffffu;
}

uint32_t crc32c(const IOBuf& buf, uint32_t seed) {
  // Running CRC across the block chain: fold each block's raw bytes in
  // without the init/final xor (applied once at the ends).
  uint32_t crc = seed ^ 0xffffffffu;
  for (size_t b = 0; b < buf.block_count(); ++b) {
    const IOBuf::BlockRef& ref = buf.ref_at(b);
    const uint8_t* p =
        reinterpret_cast<const uint8_t*>(ref.block->data) + ref.offset;
#if defined(__x86_64__)
    if (have_sse42()) {
      crc = crc32c_hw(p, ref.length, crc);
      continue;
    }
#endif
    crc = crc32c_sw(p, ref.length, crc);
  }
  return crc ^ 0xffffffffu;
}

}  // namespace trpc
