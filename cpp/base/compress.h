// Compression + checksum registries.
//
// Parity: the reference's extension registries for compress handlers
// (gzip/zlib/snappy, /root/reference/src/brpc/policy/gzip_compress.*,
// registered global.cpp:421-433) and checksum handlers (crc32c,
// policy/crc32c_checksum.*, global.cpp:435-441), negotiated per call via
// the request meta.  Redesigned condensed: a fixed id → vtable table
// (gzip + zlib via libz; snappy implemented from the format spec in
// base/snappy.* — its library isn't in this image), and
// hardware-accelerated crc32c (SSE4.2) with a software fallback.
#pragma once

#include <cstdint>
#include <string>

#include "base/iobuf.h"

namespace trpc {

// Wire ids (meta.compress_type).  0 = none.
enum class CompressType : uint8_t {
  kNone = 0,
  kGzip = 1,
  kZlib = 2,
  kSnappy = 3,
};

struct Compressor {
  const char* name;
  bool (*compress)(const IOBuf& in, IOBuf* out);
  bool (*decompress)(const IOBuf& in, IOBuf* out, uint64_t size_limit);
};

// nullptr for kNone or an unknown id.
const Compressor* find_compressor(CompressType type);

// crc32c (Castagnoli), HW-accelerated where SSE4.2 exists.
uint32_t crc32c(const void* data, size_t n, uint32_t seed = 0);
uint32_t crc32c(const IOBuf& buf, uint32_t seed = 0);

}  // namespace trpc
