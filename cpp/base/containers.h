// The remaining small containers from butil/containers/ that std::
// doesn't already cover (reference: mru_cache.h, case_ignored_flat_map.h,
// bounded_queue.h, mpsc_queue.h — /root/reference/src/butil/containers/).
// Re-designed minimal, offered as the user-facing container surface the
// reference's public headers provide — the runtime's own hot paths keep
// their specialized structures (Chase-Lev deque, ExecutionQueue).
#pragma once

#include <atomic>
#include <cctype>
#include <cstddef>
#include <list>
#include <string>
#include <unordered_map>
#include <utility>
#include <vector>

#include "base/flat_map.h"

namespace trpc {

// Recency-ordered bounded cache (reference mru_cache.h): Put/Get keep a
// usage list; inserting past capacity evicts the least-recently-used
// entry.  Not thread-safe (callers lock, as in the reference).
template <typename K, typename V>
class MruCache {
 public:
  explicit MruCache(size_t capacity) : cap_(capacity) {}

  // Inserts or overwrites; the entry becomes most-recent.
  void Put(const K& key, V value) {
    auto it = index_.find(key);
    if (it != index_.end()) {
      it->second->second = std::move(value);
      order_.splice(order_.begin(), order_, it->second);
      return;
    }
    order_.emplace_front(key, std::move(value));
    index_[key] = order_.begin();
    if (index_.size() > cap_) {
      index_.erase(order_.back().first);
      order_.pop_back();
    }
  }

  // nullptr when absent; a hit becomes most-recent.
  V* Get(const K& key) {
    auto it = index_.find(key);
    if (it == index_.end()) {
      return nullptr;
    }
    order_.splice(order_.begin(), order_, it->second);
    return &it->second->second;
  }

  // Peek without touching recency (diagnostics).
  const V* Peek(const K& key) const {
    auto it = index_.find(key);
    return it == index_.end() ? nullptr : &it->second->second;
  }

  bool Erase(const K& key) {
    auto it = index_.find(key);
    if (it == index_.end()) {
      return false;
    }
    order_.erase(it->second);
    index_.erase(it);
    return true;
  }

  size_t size() const { return index_.size(); }
  size_t capacity() const { return cap_; }

 private:
  size_t cap_;
  std::list<std::pair<K, V>> order_;  // front = most recent
  std::unordered_map<K, typename std::list<std::pair<K, V>>::iterator>
      index_;
};

// Case-insensitive string map (reference case_ignored_flat_map.h — the
// HTTP header table).  Keys are canonicalized to lowercase on the way
// in; lookups accept any casing.
template <typename V>
class CaseIgnoredFlatMap {
 public:
  static std::string lower(const std::string& s) {
    std::string out = s;
    for (char& c : out) {
      c = static_cast<char>(::tolower(static_cast<unsigned char>(c)));
    }
    return out;
  }

  V& operator[](const std::string& key) { return map_[lower(key)]; }
  V* seek(const std::string& key) { return map_.seek(lower(key)); }
  const V* seek(const std::string& key) const {
    return map_.seek(lower(key));
  }
  bool erase(const std::string& key) { return map_.erase(lower(key)); }
  size_t size() const { return map_.size(); }

  // Iteration sees the canonical (lowercased) keys.
  template <typename Fn>
  void for_each(Fn&& fn) const {
    map_.for_each(std::forward<Fn>(fn));
  }

 private:
  FlatMap<std::string, V> map_;
};

// Fixed-capacity ring (reference bounded_queue.h): no allocation after
// construction, no thread safety — for use under a caller's lock.
// (The scheduler's remote queue predates this and keeps its own ring.)
template <typename T>
class BoundedQueue {
 public:
  explicit BoundedQueue(size_t capacity)
      : items_(capacity + 1) {}  // one slot sacrificed to tell full/empty

  bool push(T v) {
    const size_t next = (tail_ + 1) % items_.size();
    if (next == head_) {
      return false;  // full
    }
    items_[tail_] = std::move(v);
    tail_ = next;
    return true;
  }

  bool pop(T* out) {
    if (head_ == tail_) {
      return false;  // empty
    }
    *out = std::move(items_[head_]);
    head_ = (head_ + 1) % items_.size();
    return true;
  }

  size_t size() const {
    return (tail_ + items_.size() - head_) % items_.size();
  }
  bool empty() const { return head_ == tail_; }
  bool full() const { return (tail_ + 1) % items_.size() == head_; }
  size_t capacity() const { return items_.size() - 1; }

 private:
  std::vector<T> items_;
  size_t head_ = 0;
  size_t tail_ = 0;
};

// Lock-free intrusive-node MPSC queue (reference mpsc_queue.h), the
// Vyukov exchange-link design: producers swing an atomic tail and link
// through it; the single consumer chases `next` pointers.  push is
// wait-free; pop may observe a momentarily unlinked node and report
// empty (the producer links it immediately after the exchange) — the
// consumer retries on its next wakeup, exactly like the ExecutionQueue
// revision loop this mirrors.
template <typename T>
class MpscQueue {
 public:
  MpscQueue() {
    Node* dummy = new Node;
    dummy->next.store(nullptr, std::memory_order_relaxed);
    head_ = dummy;
    tail_.store(dummy, std::memory_order_relaxed);
  }
  ~MpscQueue() {
    T ignored;
    while (pop(&ignored)) {
    }
    delete head_;  // the remaining dummy
  }

  void push(T v) {
    Node* n = new Node{std::move(v)};
    n->next.store(nullptr, std::memory_order_relaxed);
    Node* prev = tail_.exchange(n, std::memory_order_acq_rel);
    prev->next.store(n, std::memory_order_release);
  }

  // Single consumer only.  May report empty while a producer is between
  // its exchange and its link store; the value surfaces on the next
  // pop — callers that wake the consumer AFTER push (the normal
  // pattern) never observe a lost element.
  bool pop(T* out) {
    Node* next = head_->next.load(std::memory_order_acquire);
    if (next == nullptr) {
      return false;
    }
    *out = std::move(next->value);
    delete head_;
    head_ = next;  // consumed node becomes the new dummy
    return true;
  }

 private:
  struct Node {
    T value{};
    std::atomic<Node*> next{nullptr};
  };
  Node* head_;               // consumer-owned dummy
  std::atomic<Node*> tail_;  // producers exchange here
};

}  // namespace trpc
