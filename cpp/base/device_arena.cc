#include "base/device_arena.h"

#include <fcntl.h>
#include <stdlib.h>
#include <sys/mman.h>
#include <unistd.h>

#include <cstdio>
#include <cstring>

#include "base/logging.h"
#include "base/rand.h"

namespace trpc {

DeviceArena::DeviceArena(const Options& opts) : opts_(opts) {
  if (opts_.block_size < 4096) {
    opts_.block_size = 4096;
  }
  if (opts_.blocks_per_slab == 0) {
    opts_.blocks_per_slab = 1;
  }
}

DeviceArena::~DeviceArena() {
  std::lock_guard<std::mutex> g(mu_);
  for (Block* b : free_blocks_) {
    delete b;
  }
  for (Slab& s : slabs_) {
    if (opts_.unregister_slab != nullptr) {
      opts_.unregister_slab(s.base, s.len, opts_.reg_ctx, s.handle);
    }
    if (!s.shm_name.empty()) {
      munmap(s.base, s.len);
      shm_unlink(s.shm_name.c_str());
    } else {
      free(s.base);
    }
  }
}

int DeviceArena::grow_locked() {
  Slab slab;
  slab.len = static_cast<size_t>(opts_.block_size) * opts_.blocks_per_slab;
  if (opts_.shm_backed) {
    char name[64];
    snprintf(name, sizeof(name), "/trpc_arena_%d_%llx", getpid(),
             static_cast<unsigned long long>(fast_rand()));
    const int fd = shm_open(name, O_CREAT | O_EXCL | O_RDWR, 0600);
    if (fd < 0) {
      return -1;
    }
    if (ftruncate(fd, static_cast<off_t>(slab.len)) != 0) {
      close(fd);
      shm_unlink(name);
      return -1;
    }
    void* mem = mmap(nullptr, slab.len, PROT_READ | PROT_WRITE, MAP_SHARED,
                     fd, 0);
    close(fd);
    if (mem == MAP_FAILED) {
      shm_unlink(name);
      return -1;
    }
    slab.base = static_cast<char*>(mem);
    slab.shm_name = name;
  } else {
    void* mem = nullptr;
    if (posix_memalign(&mem, 4096, slab.len) != 0) {
      return -1;
    }
    slab.base = static_cast<char*>(mem);
  }
  if (opts_.register_slab != nullptr &&
      opts_.register_slab(slab.base, slab.len, opts_.reg_ctx,
                          &slab.handle) != 0) {
    if (!slab.shm_name.empty()) {
      munmap(slab.base, slab.len);
      shm_unlink(slab.shm_name.c_str());
    } else {
      free(slab.base);
    }
    return -1;
  }
  const uint32_t slab_id = static_cast<uint32_t>(slabs_.size());
  slabs_.push_back(slab);
  for (uint32_t i = 0; i < opts_.blocks_per_slab; ++i) {
    auto* b = new Block();
    b->cap = opts_.block_size;
    b->arena = this;
    b->data = slab.base + static_cast<size_t>(i) * opts_.block_size;
    // The "lkey" the transport ships instead of bytes.
    b->user_meta = (static_cast<uint64_t>(slab_id) << 32) |
                   (i * opts_.block_size);
    free_blocks_.push_back(b);
  }
  return 0;
}

Block* DeviceArena::allocate(uint32_t min_cap) {
  if (min_cap > opts_.block_size) {
    // Device blocks are fixed-granularity (registration is per-slab); a
    // larger request spans multiple blocks at the IOBuf layer instead.
    LOG(Warning) << "device arena block request " << min_cap << " > "
                 << opts_.block_size;
    return nullptr;
  }
  std::lock_guard<std::mutex> g(mu_);
  if (free_blocks_.empty() && grow_locked() != 0) {
    return nullptr;
  }
  Block* b = free_blocks_.back();
  free_blocks_.pop_back();
  b->ref.store(1, std::memory_order_relaxed);
  b->size = 0;
  ++in_use_;
  return b;
}

void DeviceArena::deallocate(Block* b) {
  std::lock_guard<std::mutex> g(mu_);
  b->size = 0;
  free_blocks_.push_back(b);
  --in_use_;
}

bool DeviceArena::locate(const void* data, void** slab_base,
                         uint64_t* handle, uint32_t* offset) const {
  const char* p = static_cast<const char*>(data);
  std::lock_guard<std::mutex> g(mu_);
  for (const Slab& s : slabs_) {
    if (p >= s.base && p < s.base + s.len) {
      *slab_base = s.base;
      *handle = s.handle;
      *offset = static_cast<uint32_t>(p - s.base);
      return true;
    }
  }
  return false;
}

size_t DeviceArena::slab_count() const {
  std::lock_guard<std::mutex> g(mu_);
  return slabs_.size();
}

size_t DeviceArena::blocks_in_use() const {
  std::lock_guard<std::mutex> g(mu_);
  return in_use_;
}

std::string DeviceArena::slab_shm_name(size_t i) const {
  std::lock_guard<std::mutex> g(mu_);
  return i < slabs_.size() ? slabs_[i].shm_name : "";
}

}  // namespace trpc
