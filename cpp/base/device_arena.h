// DeviceArena — slab-backed staging memory behind the IOBuf arena seam.
//
// Parity: the fork's RDMA block_pool (/root/reference/src/brpc/rdma/
// block_pool.cpp), which takes over IOBuf allocation with NIC-registered
// memory so payloads are DMA-able without copies; rdma_endpoint sends
// BlockRefs whose lkeys ride each block.  TPU-native redesign: the arena
// owns large aligned slabs that a device backend registers ONCE (the
// registration hook is where PJRT/ICI pinning goes — host staging memory
// the TPU DMAs from/to directly), blocks are carved from slabs on a lock-
// free-enough free list, and every block's `user_meta` carries
// (slab_id << 32 | offset) — the lkey analogue the transport ships instead
// of bytes.  Slabs can be POSIX-shm-backed so two processes on one host
// can exchange BlockRef descriptors over a ring and never copy payloads.
#pragma once

#include <cstdint>
#include <mutex>
#include <string>
#include <vector>

#include "base/arena.h"

namespace trpc {

class DeviceArena : public BlockArena {
 public:
  struct Options {
    uint32_t block_size = 256 * 1024;  // device DMA granularity
    uint32_t blocks_per_slab = 64;     // 16MB slabs by default
    bool shm_backed = false;           // name slabs in /dev/shm
    // Registration seam (block_pool::RegisterMemory parity): called once
    // per new slab; *handle becomes the high bits context a backend needs
    // (PJRT buffer id, ICI window id...).  Null = host-only staging.
    int (*register_slab)(void* base, size_t len, void* ctx,
                         uint64_t* handle) = nullptr;
    void (*unregister_slab)(void* base, size_t len, void* ctx,
                            uint64_t handle) = nullptr;
    void* reg_ctx = nullptr;
  };

  explicit DeviceArena(const Options& opts);
  ~DeviceArena() override;

  Block* allocate(uint32_t min_cap) override;
  void deallocate(Block* b) override;

  // (slab base, handle) for the slab containing `data`; false if foreign.
  bool locate(const void* data, void** slab_base, uint64_t* handle,
              uint32_t* offset) const;

  size_t slab_count() const;
  size_t blocks_in_use() const;
  uint32_t block_size() const { return opts_.block_size; }
  // Name of slab i's shm segment ("" when heap-backed).
  std::string slab_shm_name(size_t i) const;

 private:
  struct Slab {
    char* base = nullptr;
    size_t len = 0;
    uint64_t handle = 0;
    std::string shm_name;
  };
  int grow_locked();

  Options opts_;
  mutable std::mutex mu_;
  std::vector<Slab> slabs_;
  std::vector<Block*> free_blocks_;
  size_t in_use_ = 0;
};

}  // namespace trpc
