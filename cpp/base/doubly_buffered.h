// DoublyBufferedData — RCU-like read-mostly data.
//
// Parity: butil::DoublyBufferedData
// (/root/reference/src/butil/containers/doubly_buffered_data.h:574): readers
// take a per-thread mutex (never contended by other readers) and read the
// foreground copy; writers modify the background copy, flip the index, then
// briefly take every reader mutex to prove no reader still sees the old
// foreground, and modify it too.  This is what makes load-balancer
// SelectServer nearly contention-free (load_balancer.h:72).
#pragma once

#include <memory>
#include <mutex>
#include <vector>

namespace trpc {

template <typename T>
class DoublyBufferedData {
 public:
  class ScopedPtr {
   public:
    ScopedPtr() = default;
    ScopedPtr(const T* data, std::mutex* mu) : data_(data), mu_(mu) {}
    ScopedPtr(ScopedPtr&& o) noexcept : data_(o.data_), mu_(o.mu_) {
      o.mu_ = nullptr;
    }
    ~ScopedPtr() {
      if (mu_ != nullptr) {
        mu_->unlock();
      }
    }
    const T* get() const { return data_; }
    const T& operator*() const { return *data_; }
    const T* operator->() const { return data_; }

   private:
    const T* data_ = nullptr;
    std::mutex* mu_ = nullptr;
  };

  DoublyBufferedData() : index_(0) {
    static std::atomic<uint64_t> next_id{1};
    id_ = next_id.fetch_add(1, std::memory_order_relaxed);
  }

  // Read the foreground copy under this thread's own mutex.
  ScopedPtr Read() {
    ThreadMutex* tm = tls_mutex();
    tm->mu.lock();
    const T* fg = &data_[index_.load(std::memory_order_acquire)];
    return ScopedPtr(fg, &tm->mu);
  }

  // fn(T&) -> bool; applied to background, then (after flip + reader drain)
  // to the old foreground.  Returns false if the first application fails.
  template <typename Fn>
  bool Modify(Fn&& fn) {
    std::lock_guard<std::mutex> g(modify_mu_);
    const int bg = 1 - index_.load(std::memory_order_relaxed);
    if (!fn(data_[bg])) {
      return false;
    }
    index_.store(bg, std::memory_order_release);
    // Drain: once we've held each reader's mutex, no reader can still be
    // inside the old foreground.
    std::lock_guard<std::mutex> rg(registry_mu_);
    for (auto& tm : mutexes_) {
      std::lock_guard<std::mutex> r(tm->mu);
    }
    fn(data_[1 - bg]);
    return true;
  }

 private:
  struct ThreadMutex {
    std::mutex mu;
  };

  // TLS is keyed by a process-unique instance id (never by `this`, which
  // the allocator can reuse), and holds a shared_ptr so a mutex outlives a
  // destroyed instance until the thread exits — no use-after-free either way.
  ThreadMutex* tls_mutex() {
    static thread_local std::vector<
        std::pair<uint64_t, std::shared_ptr<ThreadMutex>>> tls;
    for (auto& p : tls) {
      if (p.first == id_) {
        return p.second.get();
      }
    }
    auto tm = std::make_shared<ThreadMutex>();
    {
      std::lock_guard<std::mutex> g(registry_mu_);
      mutexes_.push_back(tm);
    }
    tls.emplace_back(id_, tm);
    return tm.get();
  }

  T data_[2];
  std::atomic<int> index_;
  uint64_t id_ = 0;
  std::mutex modify_mu_;
  std::mutex registry_mu_;
  std::vector<std::shared_ptr<ThreadMutex>> mutexes_;
};

}  // namespace trpc
