#include "base/endpoint.h"

#include <arpa/inet.h>
#include <netdb.h>

#include <cstdio>
#include <cstring>

namespace trpc {

namespace {

// Parses "port" or "port/device" strictly (no trailing garbage).
int parse_port_dev(const char* s, int* port, int* dev) {
  char* end = nullptr;
  const long p = strtol(s, &end, 10);
  if (end == s || p < 0 || p > 65535) {
    return -1;
  }
  *port = static_cast<int>(p);
  *dev = -1;
  if (*end == '/') {
    const char* ds = end + 1;
    const long d = strtol(ds, &end, 10);
    if (end == ds || d < 0) {
      return -1;
    }
    *dev = static_cast<int>(d);
  }
  return *end == '\0' ? 0 : -1;
}

}  // namespace

int str2endpoint(const char* s, EndPoint* out) {
  if (strncmp(s, "unix:", 5) == 0 && s[5] != '\0') {
    // Paths beyond sun_path capacity would silently truncate at bind /
    // connect time; reject them here where the caller can see it.
    if (strlen(s + 5) >= sizeof(sockaddr_un{}.sun_path)) {
      return -1;
    }
    *out = EndPoint();
    out->unix_path = s + 5;
    return 0;
  }
  char host[128];
  const char* colon = strrchr(s, ':');
  if (colon == nullptr || colon == s ||
      static_cast<size_t>(colon - s) >= sizeof(host)) {
    return -1;
  }
  memcpy(host, s, colon - s);
  host[colon - s] = '\0';
  int port = 0;
  int dev = -1;
  if (parse_port_dev(colon + 1, &port, &dev) != 0) {
    return -1;
  }
  in_addr addr;
  if (inet_aton(host, &addr) == 0) {
    return -1;
  }
  out->ip = addr.s_addr;
  out->port = port;
  out->device_ordinal = dev;
  out->unix_path.clear();  // a reused EndPoint must not stay AF_UNIX
  return 0;
}

int hostname2endpoint(const char* s, EndPoint* out) {
  if (str2endpoint(s, out) == 0) {
    return 0;
  }
  const char* colon = strrchr(s, ':');
  if (colon == nullptr) {
    return -1;
  }
  int port = 0;
  int dev = -1;
  if (parse_port_dev(colon + 1, &port, &dev) != 0) {
    return -1;
  }
  std::string host(s, colon - s);
  addrinfo hints = {};
  hints.ai_family = AF_INET;
  hints.ai_socktype = SOCK_STREAM;
  addrinfo* res = nullptr;
  if (getaddrinfo(host.c_str(), nullptr, &hints, &res) != 0 ||
      res == nullptr) {
    return -1;
  }
  out->ip = reinterpret_cast<sockaddr_in*>(res->ai_addr)->sin_addr.s_addr;
  out->port = port;
  out->device_ordinal = dev;
  out->unix_path.clear();  // a reused EndPoint must not stay AF_UNIX
  freeaddrinfo(res);
  return 0;
}

std::string endpoint2str(const EndPoint& ep) {
  if (ep.is_unix()) {
    return "unix:" + ep.unix_path;
  }
  in_addr addr;
  addr.s_addr = ep.ip;
  char ip[INET_ADDRSTRLEN] = {};
  inet_ntop(AF_INET, &addr, ip, sizeof(ip));  // thread-safe, unlike inet_ntoa
  char buf[64];
  if (ep.device_ordinal >= 0) {
    snprintf(buf, sizeof(buf), "%s:%d/%d", ip, ep.port, ep.device_ordinal);
  } else {
    snprintf(buf, sizeof(buf), "%s:%d", ip, ep.port);
  }
  return buf;
}

sockaddr_in endpoint2sockaddr(const EndPoint& ep) {
  sockaddr_in sa = {};
  sa.sin_family = AF_INET;
  sa.sin_addr.s_addr = ep.ip;
  sa.sin_port = htons(static_cast<uint16_t>(ep.port));
  return sa;
}

sockaddr_un endpoint2sockaddr_un(const EndPoint& ep) {
  sockaddr_un sa = {};
  sa.sun_family = AF_UNIX;
  strncpy(sa.sun_path, ep.unix_path.c_str(), sizeof(sa.sun_path) - 1);
  return sa;
}

EndPoint sockaddr2endpoint(const sockaddr_in& sa) {
  EndPoint ep;
  ep.ip = sa.sin_addr.s_addr;
  ep.port = ntohs(sa.sin_port);
  return ep;
}

}  // namespace trpc
