#include "base/endpoint.h"

#include <arpa/inet.h>
#include <netdb.h>

#include <cstdio>
#include <cstring>

namespace trpc {

int str2endpoint(const char* s, EndPoint* out) {
  char host[128];
  int port = 0;
  int dev = -1;
  const char* colon = strrchr(s, ':');
  if (colon == nullptr || colon == s ||
      static_cast<size_t>(colon - s) >= sizeof(host)) {
    return -1;
  }
  memcpy(host, s, colon - s);
  host[colon - s] = '\0';
  if (sscanf(colon + 1, "%d/%d", &port, &dev) < 1) {
    return -1;
  }
  if (port < 0 || port > 65535) {
    return -1;
  }
  in_addr addr;
  if (inet_aton(host, &addr) == 0) {
    return -1;
  }
  out->ip = addr.s_addr;
  out->port = port;
  out->device_ordinal = dev;
  return 0;
}

int hostname2endpoint(const char* s, EndPoint* out) {
  if (str2endpoint(s, out) == 0) {
    return 0;
  }
  const char* colon = strrchr(s, ':');
  if (colon == nullptr) {
    return -1;
  }
  char* end = nullptr;
  const long port = strtol(colon + 1, &end, 10);
  if (end == colon + 1 || *end != '\0' || port < 0 || port > 65535) {
    return -1;
  }
  std::string host(s, colon - s);
  addrinfo hints = {};
  hints.ai_family = AF_INET;
  hints.ai_socktype = SOCK_STREAM;
  addrinfo* res = nullptr;
  if (getaddrinfo(host.c_str(), nullptr, &hints, &res) != 0 ||
      res == nullptr) {
    return -1;
  }
  out->ip = reinterpret_cast<sockaddr_in*>(res->ai_addr)->sin_addr.s_addr;
  out->port = static_cast<int>(port);
  out->device_ordinal = -1;
  freeaddrinfo(res);
  return 0;
}

std::string endpoint2str(const EndPoint& ep) {
  in_addr addr;
  addr.s_addr = ep.ip;
  char ip[INET_ADDRSTRLEN] = {};
  inet_ntop(AF_INET, &addr, ip, sizeof(ip));  // thread-safe, unlike inet_ntoa
  char buf[64];
  if (ep.device_ordinal >= 0) {
    snprintf(buf, sizeof(buf), "%s:%d/%d", ip, ep.port, ep.device_ordinal);
  } else {
    snprintf(buf, sizeof(buf), "%s:%d", ip, ep.port);
  }
  return buf;
}

sockaddr_in endpoint2sockaddr(const EndPoint& ep) {
  sockaddr_in sa = {};
  sa.sin_family = AF_INET;
  sa.sin_addr.s_addr = ep.ip;
  sa.sin_port = htons(static_cast<uint16_t>(ep.port));
  return sa;
}

EndPoint sockaddr2endpoint(const sockaddr_in& sa) {
  EndPoint ep;
  ep.ip = sa.sin_addr.s_addr;
  ep.port = ntohs(sa.sin_port);
  return ep;
}

}  // namespace trpc
