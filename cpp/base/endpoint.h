// EndPoint — peer address value type.
//
// Parity: butil::EndPoint (/root/reference/src/butil/endpoint.h:253)
// extended with an optional device ordinal so an ICI peer ("chip 3 behind
// host 10.0.0.2") is first-class, the way the fork's transports key sockets
// by (EndPoint, SocketMode).
#pragma once

#include <netinet/in.h>
#include <sys/un.h>

#include <cstdint>
#include <functional>
#include <string>

namespace trpc {

struct EndPoint {
  uint32_t ip = 0;          // network byte order
  int port = 0;
  int device_ordinal = -1;  // -1 = host endpoint; >=0 = TPU chip behind host
  // Non-empty = AF_UNIX address (ip/port unused) — reference endpoint.h
  // models unix sockets inside EndPoint the same way.
  std::string unix_path;

  bool is_unix() const { return !unix_path.empty(); }

  bool operator==(const EndPoint& o) const {
    return ip == o.ip && port == o.port &&
           device_ordinal == o.device_ordinal && unix_path == o.unix_path;
  }
  bool operator!=(const EndPoint& o) const { return !(*this == o); }
};

// "1.2.3.4:80", "1.2.3.4:80/3" (ICI device suffix), or "unix:/path";
// returns 0 on success.
int str2endpoint(const char* s, EndPoint* out);
// Resolves "host:port" via getaddrinfo when not dotted-quad; passes
// "unix:/path" through.
int hostname2endpoint(const char* s, EndPoint* out);
std::string endpoint2str(const EndPoint& ep);
sockaddr_in endpoint2sockaddr(const EndPoint& ep);
EndPoint sockaddr2endpoint(const sockaddr_in& sa);
// AF_UNIX form.  Paths are validated against sun_path capacity at
// parse time (str2endpoint), so no truncation can reach here.
sockaddr_un endpoint2sockaddr_un(const EndPoint& ep);

struct EndPointHash {
  size_t operator()(const EndPoint& ep) const {
    uint64_t v = (static_cast<uint64_t>(ep.ip) << 32) ^
                 (static_cast<uint64_t>(ep.port) << 8) ^
                 static_cast<uint64_t>(ep.device_ordinal + 1);
    if (ep.is_unix()) {
      v ^= std::hash<std::string>{}(ep.unix_path);
    }
    v ^= v >> 33;
    v *= 0xff51afd7ed558ccdull;
    v ^= v >> 33;
    return static_cast<size_t>(v);
  }
};

}  // namespace trpc
