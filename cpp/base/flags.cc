#include "base/flags.h"

#include <algorithm>
#include <cstdlib>
#include <map>

#include "base/json.h"

namespace trpc {

namespace {

// Leaked singletons (runtime registries outlive every static destructor —
// the repo-wide invariant).
std::mutex& registry_mu() {
  static std::mutex* mu = new std::mutex();
  return *mu;
}
std::map<std::string, Flag*>& registry() {
  static auto* m = new std::map<std::string, Flag*>();
  return *m;
}

bool parse_bool(const std::string& v, bool* out) {
  if (v == "true" || v == "1" || v == "on") {
    *out = true;
    return true;
  }
  if (v == "false" || v == "0" || v == "off") {
    *out = false;
    return true;
  }
  return false;
}

}  // namespace

Flag::Flag(std::string name, Type t, std::string dflt, std::string desc)
    : name_(std::move(name)),
      type_(t),
      default_str_(std::move(dflt)),
      desc_(std::move(desc)) {
  // Seed typed storage from the default (defaults are trusted input).
  switch (type_) {
    case Type::kBool: {
      bool b = false;
      parse_bool(default_str_, &b);
      num_.store(b ? 1 : 0, std::memory_order_release);
      break;
    }
    case Type::kInt64:
      num_.store(strtoll(default_str_.c_str(), nullptr, 10),
                 std::memory_order_release);
      break;
    case Type::kDouble:
      real_.store(strtod(default_str_.c_str(), nullptr),
                  std::memory_order_release);
      break;
    case Type::kString:
      str_ = default_str_;
      break;
  }
}

Flag* Flag::define(const std::string& name, Type t, const std::string& dflt,
                   const std::string& desc) {
  std::lock_guard<std::mutex> g(registry_mu());
  auto it = registry().find(name);
  if (it != registry().end()) {
    return it->second->type_ == t ? it->second : nullptr;
  }
  Flag* f = new Flag(name, t, dflt, desc);  // leaked with the registry
  registry()[name] = f;
  return f;
}

Flag* Flag::define_bool(const std::string& name, bool dflt,
                        const std::string& desc) {
  return define(name, Type::kBool, dflt ? "true" : "false", desc);
}
Flag* Flag::define_int64(const std::string& name, int64_t dflt,
                         const std::string& desc) {
  return define(name, Type::kInt64, std::to_string(dflt), desc);
}
Flag* Flag::define_double(const std::string& name, double dflt,
                          const std::string& desc) {
  return define(name, Type::kDouble, std::to_string(dflt), desc);
}
Flag* Flag::define_string(const std::string& name, const std::string& dflt,
                          const std::string& desc) {
  return define(name, Type::kString, dflt, desc);
}

Flag* Flag::find(const std::string& name) {
  std::lock_guard<std::mutex> g(registry_mu());
  auto it = registry().find(name);
  return it == registry().end() ? nullptr : it->second;
}

std::vector<Flag*> Flag::all() {
  std::lock_guard<std::mutex> g(registry_mu());
  std::vector<Flag*> out;
  out.reserve(registry().size());
  for (auto& [_, f] : registry()) {
    out.push_back(f);  // map iteration is already name-sorted
  }
  return out;
}

int Flag::set(const std::string& name, const std::string& value) {
  Flag* f = find(name);
  if (f == nullptr) {
    return -1;
  }
  return f->set_from_string(value);
}

int Flag::set_from_string(const std::string& value) {
  if (!reloadable_.load(std::memory_order_acquire)) {
    return -3;
  }
  std::function<bool(const std::string&)> validator;
  std::function<void(Flag*)> update_cb;
  {
    std::lock_guard<std::mutex> g(hook_mu_);
    validator = validator_;
    update_cb = update_cb_;
  }
  if (validator && !validator(value)) {
    return -2;
  }
  switch (type_) {
    case Type::kBool: {
      bool b = false;
      if (!parse_bool(value, &b)) {
        return -2;
      }
      num_.store(b ? 1 : 0, std::memory_order_release);
      break;
    }
    case Type::kInt64: {
      char* end = nullptr;
      const int64_t v = strtoll(value.c_str(), &end, 10);
      if (end == value.c_str() || *end != '\0') {
        return -2;
      }
      num_.store(v, std::memory_order_release);
      break;
    }
    case Type::kDouble: {
      char* end = nullptr;
      const double v = strtod(value.c_str(), &end);
      if (end == value.c_str() || *end != '\0') {
        return -2;
      }
      real_.store(v, std::memory_order_release);
      break;
    }
    case Type::kString: {
      std::lock_guard<std::mutex> g(str_mu_);
      str_ = value;
      break;
    }
  }
  if (update_cb) {
    update_cb(this);
  }
  return 0;
}

std::string Flag::string_value() const {
  std::lock_guard<std::mutex> g(str_mu_);
  return str_;
}

std::string Flag::value_string() const {
  switch (type_) {
    case Type::kBool:
      return bool_value() ? "true" : "false";
    case Type::kInt64:
      return std::to_string(int64_value());
    case Type::kDouble: {
      char buf[32];
      snprintf(buf, sizeof(buf), "%g", double_value());
      return buf;
    }
    case Type::kString:
      return string_value();
  }
  return "";
}

void Flag::set_validator(std::function<bool(const std::string&)> v) {
  std::lock_guard<std::mutex> g(hook_mu_);
  validator_ = std::move(v);
}

void Flag::on_update(std::function<void(Flag*)> cb) {
  std::lock_guard<std::mutex> g(hook_mu_);
  update_cb_ = std::move(cb);
}

void Flag::set_int_range(int64_t lo, int64_t hi) {
  set_validator([lo, hi](const std::string& v) {
    char* end = nullptr;
    const long long n = strtoll(v.c_str(), &end, 10);
    return end != v.c_str() && *end == '\0' && n >= lo && n <= hi;
  });
  set_bounds_hint(lo, hi);
}

void Flag::set_bounds_hint(int64_t lo, int64_t hi) {
  std::lock_guard<std::mutex> g(hook_mu_);
  has_bounds_ = true;
  bound_lo_ = lo;
  bound_hi_ = hi;
}

bool Flag::bounds(int64_t* lo, int64_t* hi) const {
  std::lock_guard<std::mutex> g(hook_mu_);
  if (!has_bounds_) {
    return false;
  }
  if (lo != nullptr) {
    *lo = bound_lo_;
  }
  if (hi != nullptr) {
    *hi = bound_hi_;
  }
  return true;
}

std::string Flag::dump_json() {
  static const char* kTypeNames[] = {"bool", "int64", "double", "string"};
  Json arr = Json::array();
  for (Flag* f : all()) {
    Json j = Json::object();
    j.set("name", Json::str(f->name()));
    j.set("type", Json::str(kTypeNames[static_cast<int>(f->type())]));
    j.set("value", Json::str(f->value_string()));
    j.set("default", Json::str(f->default_value()));
    j.set("reloadable", Json::boolean(f->reloadable()));
    int64_t lo = 0;
    int64_t hi = 0;
    if (f->bounds(&lo, &hi)) {
      j.set("min", Json::number(static_cast<double>(lo)));
      j.set("max", Json::number(static_cast<double>(hi)));
    }
    arr.push_back(std::move(j));
  }
  return arr.dump();
}

}  // namespace trpc
