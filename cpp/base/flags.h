// Runtime flag registry — reloadable configuration knobs.
//
// Parity: the reference's gflags + reloadable-flag pattern
// (/root/reference/src/butil/reloadable_flags.h: a validator registered per
// flag makes it safely mutable at runtime; /root/reference/src/brpc/builtin/
// flags_service.* exposes them over HTTP).  Redesigned condensed: one
// registry, typed atomic storage, optional validator + on-update hook so a
// flip can push into live components (e.g. a concurrency limiter bound).
//
// Usage:
//   static Flag* g_limit = Flag::define_int64(
//       "echo_max_concurrency", 128, "admission bound for Echo");
//   ... g_limit->int64_value() ...           // lock-free read
//   Flag::set("echo_max_concurrency", "64")  // validated runtime flip
#pragma once

#include <atomic>
#include <cstdint>
#include <functional>
#include <memory>
#include <mutex>
#include <string>
#include <vector>

namespace trpc {

class Flag {
 public:
  enum class Type { kBool, kInt64, kDouble, kString };

  // Define-or-get: defining the same name twice returns the first instance
  // (types must match; mismatch returns nullptr).  Thread-safe.
  static Flag* define_bool(const std::string& name, bool dflt,
                           const std::string& desc);
  static Flag* define_int64(const std::string& name, int64_t dflt,
                            const std::string& desc);
  static Flag* define_double(const std::string& name, double dflt,
                             const std::string& desc);
  static Flag* define_string(const std::string& name, const std::string& dflt,
                             const std::string& desc);

  // Registry.
  static Flag* find(const std::string& name);
  static std::vector<Flag*> all();  // sorted by name
  // Validated set; returns 0 on success, -1 unknown flag, -2 bad value /
  // rejected by validator, -3 not reloadable.
  static int set(const std::string& name, const std::string& value);

  // -- per-flag API ----------------------------------------------------
  const std::string& name() const { return name_; }
  const std::string& description() const { return desc_; }
  Type type() const { return type_; }
  bool reloadable() const { return reloadable_; }
  void set_reloadable(bool r) { reloadable_ = r; }
  const std::string& default_value() const { return default_str_; }

  bool bool_value() const {
    return num_.load(std::memory_order_acquire) != 0;
  }
  int64_t int64_value() const { return num_.load(std::memory_order_acquire); }
  double double_value() const { return real_.load(std::memory_order_acquire); }
  std::string string_value() const;
  std::string value_string() const;  // any type, rendered

  int set_from_string(const std::string& value);

  // Rejects a candidate value before it lands (reloadable_flags.h parity:
  // the validator IS what makes runtime mutation safe).
  void set_validator(std::function<bool(const std::string&)> v);
  // Runs after a successful set — push the new value into live components.
  void on_update(std::function<void(Flag*)> cb);

  // Declared numeric bounds, introspectable via dump_json (the /flags
  // ?format=json and trpc_flags_dump surfaces) and honored by actuators
  // like the stat/tuner controller, which clamp into [lo, hi] BEFORE
  // attempting a set — out-of-range actuation is impossible by
  // construction, not by hoping the validator catches it.
  // set_int_range installs BOTH a standard [lo, hi] range validator and
  // the bounds record; set_bounds_hint records bounds only (for flags
  // whose validator checks more than a range, e.g. power-of-two).
  void set_int_range(int64_t lo, int64_t hi);
  void set_bounds_hint(int64_t lo, int64_t hi);
  // False when no bounds were declared (out params untouched).
  bool bounds(int64_t* lo, int64_t* hi) const;

  // Introspection dump for tooling: a JSON array of {"name", "type",
  // "value", "default", "reloadable"} plus "min"/"max" where bounds
  // were declared.  The shape /flags?format=json serves and
  // observe.py flags() parses.
  static std::string dump_json();

 private:
  Flag(std::string name, Type t, std::string dflt, std::string desc);
  static Flag* define(const std::string& name, Type t,
                      const std::string& dflt, const std::string& desc);

  const std::string name_;
  const Type type_;
  const std::string default_str_;
  const std::string desc_;
  std::atomic<bool> reloadable_{true};
  std::atomic<int64_t> num_{0};     // bool / int64
  std::atomic<double> real_{0.0};   // double
  mutable std::mutex str_mu_;       // string payload
  std::string str_;
  mutable std::mutex hook_mu_;  // bounds() reads under it from const
  std::function<bool(const std::string&)> validator_;
  std::function<void(Flag*)> update_cb_;
  bool has_bounds_ = false;  // guarded by hook_mu_ (with lo/hi below)
  int64_t bound_lo_ = 0;
  int64_t bound_hi_ = 0;
};

}  // namespace trpc
