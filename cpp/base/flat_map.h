// FlatMap — open-addressing hash map for hot lookup tables.
//
// Parity: butil::FlatMap (/root/reference/src/butil/containers/flat_map.h),
// used for method tables and protocol dispatch.  Re-designed: linear probing
// with backward-shift deletion over a power-of-two slot array (the reference
// chains within buckets).
#pragma once

#include <cassert>
#include <cstddef>
#include <functional>
#include <utility>
#include <vector>

namespace trpc {

template <typename K, typename V, typename Hash = std::hash<K>>
class FlatMap {
 public:
  explicit FlatMap(size_t initial_cap = 16) { rehash(round_up(initial_cap)); }

  size_t size() const { return size_; }
  bool empty() const { return size_ == 0; }

  V* seek(const K& key) {
    const size_t mask = slots_.size() - 1;
    size_t i = Hash()(key) & mask;
    while (slots_[i].state == kFull) {
      if (slots_[i].kv.first == key) {
        return &slots_[i].kv.second;
      }
      i = (i + 1) & mask;
    }
    return nullptr;
  }
  const V* seek(const K& key) const {
    return const_cast<FlatMap*>(this)->seek(key);
  }

  V& operator[](const K& key) {
    if (size_ * 4 >= slots_.size() * 3) {
      rehash(slots_.size() * 2);
    }
    const size_t mask = slots_.size() - 1;
    size_t i = Hash()(key) & mask;
    while (slots_[i].state == kFull) {
      if (slots_[i].kv.first == key) {
        return slots_[i].kv.second;
      }
      i = (i + 1) & mask;
    }
    slots_[i].state = kFull;
    slots_[i].kv.first = key;
    slots_[i].kv.second = V();
    ++size_;
    return slots_[i].kv.second;
  }

  bool insert(const K& key, const V& value) {
    V& v = (*this)[key];
    v = value;
    return true;
  }

  // Backward-shift deletion keeps probe chains intact without tombstones.
  bool erase(const K& key) {
    const size_t mask = slots_.size() - 1;
    size_t i = Hash()(key) & mask;
    while (slots_[i].state == kFull) {
      if (slots_[i].kv.first == key) {
        size_t hole = i;
        size_t j = (i + 1) & mask;
        while (slots_[j].state == kFull) {
          const size_t home = Hash()(slots_[j].kv.first) & mask;
          // Can slot j legally move into the hole?
          const bool wraps = hole <= j ? (home <= hole || home > j)
                                       : (home <= hole && home > j);
          if (wraps) {
            slots_[hole].kv = std::move(slots_[j].kv);
            hole = j;
          }
          j = (j + 1) & mask;
        }
        slots_[hole].state = kEmpty;
        --size_;
        return true;
      }
      i = (i + 1) & mask;
    }
    return false;
  }

  template <typename Fn>
  void for_each(Fn&& fn) const {
    for (const Slot& s : slots_) {
      if (s.state == kFull) {
        fn(s.kv.first, s.kv.second);
      }
    }
  }

 private:
  enum State : uint8_t { kEmpty = 0, kFull = 1 };
  struct Slot {
    State state = kEmpty;
    std::pair<K, V> kv;
  };

  static size_t round_up(size_t n) {
    size_t p = 8;
    while (p < n) {
      p <<= 1;
    }
    return p;
  }

  void rehash(size_t new_cap) {
    std::vector<Slot> old = std::move(slots_);
    slots_.assign(new_cap, Slot());
    size_ = 0;
    for (Slot& s : old) {
      if (s.state == kFull) {
        (*this)[s.kv.first] = std::move(s.kv.second);
      }
    }
  }

  std::vector<Slot> slots_;
  size_t size_ = 0;
};

}  // namespace trpc
