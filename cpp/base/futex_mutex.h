// Futex mutex (Drepper, "Futexes Are Tricky" §6): 0 free, 1 held,
// 2 held-with-waiters.  For pthread-blocking critical sections that are
// shared between FIBERS and plain pthreads and must stay analyzable
// under TSan: gcc-10 libtsan loses the pthread_mutex interceptor
// pairing across __tsan_switch_to_fiber (a mutex locked from a fiber
// came back "already destroyed", yielding phantom double-lock/data-race
// reports on textbook lock-protected state — the old blanket
// TimerThread suppressions, ISSUE 7).  Plain atomics carry real
// acquire/release edges TSan models natively, with no interceptor to
// confuse.  Not a FiberMutex: blocking parks the calling PTHREAD, so
// keep critical sections short; use fiber/sync.h when the waiter should
// yield its worker instead.
#pragma once

#include <linux/futex.h>
#include <sys/syscall.h>
#include <unistd.h>

#include <atomic>
#include <ctime>

namespace trpc {

inline int futex_word_op(std::atomic<uint32_t>* addr, int op, uint32_t val,
                         const timespec* timeout) {
  return syscall(SYS_futex, reinterpret_cast<int*>(addr), op, val, timeout,
                 nullptr, 0);
}

// The kernel treats the futex word as an opaque 32-bit value; signed
// callers (ParkingLot's seq_) share the same wrapper.
inline int futex_word_op(std::atomic<int>* addr, int op, int val,
                         const timespec* timeout) {
  return syscall(SYS_futex, reinterpret_cast<int*>(addr), op, val, timeout,
                 nullptr, 0);
}

class FutexMutex {
 public:
  void lock() {
    uint32_t c = 0;
    if (word_.compare_exchange_strong(c, 1, std::memory_order_acquire,
                                      std::memory_order_relaxed)) {
      return;
    }
    do {
      if (c == 2 ||
          word_.compare_exchange_strong(c, 2, std::memory_order_acquire,
                                        std::memory_order_relaxed)) {
        futex_word_op(&word_, FUTEX_WAIT_PRIVATE, 2, nullptr);
      }
      c = 0;
    } while (!word_.compare_exchange_strong(c, 2, std::memory_order_acquire,
                                            std::memory_order_relaxed));
  }

  void unlock() {
    if (word_.exchange(0, std::memory_order_release) == 2) {
      futex_word_op(&word_, FUTEX_WAKE_PRIVATE, 1, nullptr);
    }
  }

 private:
  std::atomic<uint32_t> word_{0};
};

}  // namespace trpc
