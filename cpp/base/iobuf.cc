#include "base/iobuf.h"

#include "base/logging.h"

#include <errno.h>
#include <sys/socket.h>
#include <unistd.h>

#include <algorithm>

namespace trpc {

namespace {
constexpr int kMaxIov = 64;
// The write side carries whole coalesced KeepWrite batches (many small
// responses → many refs); a bigger budget keeps one drain = one writev.
constexpr int kMaxWriteIov = 256;
}

IOBuf::IOBuf(const IOBuf& other) : size_(other.size_), arena_(other.arena_) {
  refs_ = other.refs_;
  for (BlockRef& r : refs_) {
    r.block->add_ref();
  }
}

IOBuf& IOBuf::operator=(const IOBuf& other) {
  if (this != &other) {
    IOBuf tmp(other);
    *this = std::move(tmp);
  }
  return *this;
}

IOBuf::IOBuf(IOBuf&& other) noexcept
    : refs_(std::move(other.refs_)), size_(other.size_), arena_(other.arena_) {
  other.refs_.clear();
  other.size_ = 0;
}

IOBuf& IOBuf::operator=(IOBuf&& other) noexcept {
  if (this != &other) {
    clear();
    refs_ = std::move(other.refs_);
    size_ = other.size_;
    arena_ = other.arena_;
    other.refs_.clear();
    other.size_ = 0;
  }
  return *this;
}

void IOBuf::clear() {
  for (BlockRef& r : refs_) {
    r.block->release();
  }
  refs_.clear();
  size_ = 0;
}

void IOBuf::push_ref(Block* b, uint32_t offset, uint32_t length) {
  refs_.push_back(BlockRef{offset, length, b});
  size_ += length;
}

Block* IOBuf::extendable_tail(size_t want) const {
  if (refs_.empty()) {
    return nullptr;
  }
  const BlockRef& r = refs_.back();
  Block* b = r.block;
  // Extension is safe only while we hold the sole reference and our ref
  // covers the block's live tail.
  if (b->ref.load(std::memory_order_acquire) != 1 ||
      b->user_deleter != nullptr || r.offset + r.length != b->size ||
      b->size >= b->cap) {
    return nullptr;
  }
  return b;
}

void IOBuf::append(const void* data, size_t n) {
  const char* p = static_cast<const char*>(data);
  BlockArena* arena = arena_ ? arena_ : HostArena::instance();
  while (n > 0) {
    Block* b = extendable_tail(n);
    if (b != nullptr) {
      const size_t take = std::min<size_t>(n, b->cap - b->size);
      memcpy(b->data + b->size, p, take);
      b->size += take;
      refs_.back().length += take;
      size_ += take;
      p += take;
      n -= take;
      continue;
    }
    // Ask for ONE byte so every arena serves at its own granularity (a
    // device arena hands out full fixed-size blocks; large appends span
    // as many as needed) — EXCEPT bulk appends on the host arena, which
    // get large pooled blocks: a multi-MB body in 8KB slivers costs one
    // iovec per sliver at the writev below it, and per-iovec overhead is
    // what caps bulk goodput on paravirtualized kernels.  Genuine
    // exhaustion (slab growth failure) is a hard programming/resource
    // error at this copying entry point — the zero-copy path
    // (append_block/trpc_arena_alloc) reports it recoverably instead.
    const uint32_t want =
        (arena == HostArena::instance() && n >= HostArena::kBigBlockMin)
            ? static_cast<uint32_t>(std::min<size_t>(n, 8u << 20))
            : 1;
    Block* nb = arena->allocate(want);
    CHECK(nb != nullptr) << "arena exhausted appending " << n << " bytes";
    const size_t take = std::min<size_t>(n, nb->cap);
    memcpy(nb->data, p, take);
    nb->size = take;
    push_ref(nb, 0, take);  // ref==1 from allocate
    p += take;
    n -= take;
  }
}

void IOBuf::append(const IOBuf& other) {
  refs_.reserve(refs_.size() + other.refs_.size());
  for (const BlockRef& r : other.refs_) {
    r.block->add_ref();
    refs_.push_back(r);
  }
  size_ += other.size_;
}

void IOBuf::append(IOBuf&& other) {
  if (refs_.empty()) {
    *this = std::move(other);
    return;
  }
  refs_.reserve(refs_.size() + other.refs_.size());
  for (const BlockRef& r : other.refs_) {
    refs_.push_back(r);
  }
  size_ += other.size_;
  other.refs_.clear();
  other.size_ = 0;
}

void IOBuf::append_user_data(void* data, size_t n,
                             void (*deleter)(void*, void*), void* ctx,
                             uint64_t meta) {
  Block* b = make_user_block(data, n, deleter, ctx, meta);
  push_ref(b, 0, n);
}

char* IOBuf::reserve(size_t n) {
  BlockArena* arena = arena_ ? arena_ : HostArena::instance();
  Block* b = extendable_tail(n);
  if (b == nullptr || b->cap - b->size < n) {
    b = arena->allocate(n);
    CHECK(b != nullptr) << "arena cannot reserve " << n << " bytes";
    b->size = n;
    push_ref(b, 0, n);
    return b->data;
  }
  char* p = b->data + b->size;
  b->size += n;
  refs_.back().length += n;
  size_ += n;
  return p;
}

size_t IOBuf::copy_to(void* dst, size_t n, size_t pos) const {
  char* out = static_cast<char*>(dst);
  size_t copied = 0;
  size_t skip = pos;
  for (const BlockRef& r : refs_) {
    if (copied >= n) {
      break;
    }
    if (skip >= r.length) {
      skip -= r.length;
      continue;
    }
    const size_t avail = r.length - skip;
    const size_t take = std::min(n - copied, avail);
    memcpy(out + copied, r.block->data + r.offset + skip, take);
    copied += take;
    skip = 0;
  }
  return copied;
}

std::string IOBuf::to_string() const {
  std::string s;
  s.resize(size_);
  copy_to(s.data(), size_);
  return s;
}

size_t IOBuf::cutn(IOBuf* out, size_t n) {
  n = std::min(n, size_);
  size_t left = n;
  size_t i = 0;
  while (left > 0 && i < refs_.size()) {
    BlockRef& r = refs_[i];
    if (r.length <= left) {
      out->refs_.push_back(r);  // transfer our reference
      out->size_ += r.length;
      left -= r.length;
      ++i;
    } else {
      r.block->add_ref();
      out->refs_.push_back(BlockRef{r.offset, static_cast<uint32_t>(left),
                                    r.block});
      out->size_ += left;
      r.offset += left;
      r.length -= left;
      left = 0;
    }
  }
  refs_.erase(refs_.begin(), refs_.begin() + i);
  size_ -= n;
  return n;
}

size_t IOBuf::pop_front(size_t n) {
  n = std::min(n, size_);
  size_t left = n;
  size_t i = 0;
  while (left > 0) {
    BlockRef& r = refs_[i];
    if (r.length <= left) {
      left -= r.length;
      r.block->release();
      ++i;
    } else {
      r.offset += left;
      r.length -= left;
      left = 0;
    }
  }
  refs_.erase(refs_.begin(), refs_.begin() + i);
  size_ -= n;
  return n;
}

size_t IOBuf::pop_back(size_t n) {
  n = std::min(n, size_);
  size_t left = n;
  while (left > 0) {
    BlockRef& r = refs_.back();
    if (r.length <= left) {
      left -= r.length;
      r.block->release();
      refs_.pop_back();
    } else {
      r.length -= left;
      left = 0;
    }
  }
  size_ -= n;
  return n;
}

int IOBuf::fill_iovec(iovec* iov, int max_iov, size_t max_bytes) const {
  int n = 0;
  size_t total = 0;
  for (const BlockRef& r : refs_) {
    if (n >= max_iov || total >= max_bytes) {
      break;
    }
    const size_t take = std::min<size_t>(r.length, max_bytes - total);
    iov[n].iov_base = r.block->data + r.offset;
    iov[n].iov_len = take;
    total += take;
    ++n;
  }
  return n;
}

ssize_t IOBuf::append_from_fd(int fd, size_t max_bytes, size_t block_hint) {
  BlockArena* arena = arena_ ? arena_ : HostArena::instance();
  const uint32_t fresh_cap = block_hint > HostArena::kDefaultBlockSize
                                 ? static_cast<uint32_t>(std::min<size_t>(
                                       block_hint, 64ull << 20))
                                 : HostArena::kDefaultBlockSize;
  // Read into up to kMaxIov fresh blocks with readv.
  iovec iov[kMaxIov];
  Block* blocks[kMaxIov];
  int n = 0;
  size_t planned = 0;
  while (n < kMaxIov && planned < max_bytes) {
    Block* b = extendable_tail(1);
    if (n == 0 && b != nullptr) {
      iov[n].iov_base = b->data + b->size;
      iov[n].iov_len = std::min<size_t>(b->cap - b->size, max_bytes);
      blocks[n] = nullptr;  // marks "extend tail"
      planned += iov[n].iov_len;
      ++n;
      continue;
    }
    Block* nb = arena->allocate(fresh_cap);
    iov[n].iov_base = nb->data;
    iov[n].iov_len = std::min<size_t>(nb->cap, max_bytes - planned);
    blocks[n] = nb;
    planned += iov[n].iov_len;
    ++n;
  }
  ssize_t rc = readv(fd, iov, n);
  if (rc <= 0) {
    for (int i = 0; i < n; ++i) {
      if (blocks[i] != nullptr) {
        blocks[i]->release();
      }
    }
    return rc;
  }
  size_t remain = static_cast<size_t>(rc);
  for (int i = 0; i < n; ++i) {
    const size_t got = std::min<size_t>(remain, iov[i].iov_len);
    if (blocks[i] == nullptr) {  // extended tail block
      Block* b = refs_.back().block;
      b->size += got;
      refs_.back().length += got;
      size_ += got;
    } else if (got > 0) {
      blocks[i]->size = got;
      push_ref(blocks[i], 0, got);
    } else {
      blocks[i]->release();
    }
    remain -= got;
  }
  return rc;
}

ssize_t IOBuf::cut_into_fd(int fd, size_t max_bytes) {
  iovec iov[kMaxWriteIov];
  const int n = fill_iovec(iov, kMaxWriteIov, max_bytes);
  if (n == 0) {
    return 0;
  }
  // MSG_NOSIGNAL: a peer racing its close ahead of this write must surface
  // as EPIPE, not a process-killing SIGPIPE — no global handler is owned
  // here.  Non-socket fds (pipes) fall back to writev.
  msghdr msg{};
  msg.msg_iov = iov;
  msg.msg_iovlen = static_cast<size_t>(n);
  ssize_t rc = ::sendmsg(fd, &msg, MSG_NOSIGNAL);
  if (rc < 0 && errno == ENOTSOCK) {
    rc = writev(fd, iov, n);
  }
  if (rc > 0) {
    pop_front(static_cast<size_t>(rc));
  }
  return rc;
}

bool IOBuf::equals(const void* data, size_t n) const {
  if (n != size_) {
    return false;
  }
  const char* p = static_cast<const char*>(data);
  size_t pos = 0;
  for (const BlockRef& r : refs_) {
    if (memcmp(p + pos, r.block->data + r.offset, r.length) != 0) {
      return false;
    }
    pos += r.length;
  }
  return true;
}

}  // namespace trpc
