// IOBuf — zero-copy chained buffer, THE data plane type.
//
// Parity: butil::IOBuf (/root/reference/src/butil/iobuf.h:68): ref-counted
// block chain, cheap copy/cut/append by reference, scatter-gather to fds,
// user-owned memory with deleter+meta for device registration.  Re-designed:
// refs live in a std::vector (no small/big union), a block is extendable
// only while singly-referenced (no shared TLS tail cursor), and the arena is
// pluggable per-append for the HBM path.
#pragma once

#include <sys/uio.h>

#include <cstring>
#include <string>
#include <vector>

#include "base/arena.h"

namespace trpc {

class IOBuf {
 public:
  struct BlockRef {
    uint32_t offset = 0;
    uint32_t length = 0;
    Block* block = nullptr;
  };

  IOBuf() = default;
  explicit IOBuf(BlockArena* arena) : arena_(arena) {}
  IOBuf(const IOBuf& other);
  IOBuf& operator=(const IOBuf& other);
  IOBuf(IOBuf&& other) noexcept;
  IOBuf& operator=(IOBuf&& other) noexcept;
  ~IOBuf() { clear(); }

  size_t size() const { return size_; }
  bool empty() const { return size_ == 0; }
  size_t block_count() const { return refs_.size(); }
  void clear();
  // Heap bytes pinned by the refs vector itself (the blocks are released
  // by clear(); this capacity is what a pooled empty IOBuf still holds).
  size_t ref_capacity_bytes() const {
    return refs_.capacity() * sizeof(BlockRef);
  }
  // clear() + drop the refs vector's heap storage (pooled-object cap).
  void shrink_storage() {
    clear();
    std::vector<BlockRef>().swap(refs_);
  }

  // -- writing ---------------------------------------------------------
  void append(const void* data, size_t n);
  void append(const std::string& s) { append(s.data(), s.size()); }
  void append(const char* s) { append(s, strlen(s)); }
  // Share the other buffer's blocks (zero copy).
  void append(const IOBuf& other);
  // Move other's refs to our tail (zero copy, clears other).
  void append(IOBuf&& other);
  // Wrap caller-owned memory without copying; deleter runs when the last
  // reference drops (parity: iobuf.h:257 append_user_data_with_meta; `meta`
  // carries the device/DMA handle).
  void append_user_data(void* data, size_t n, void (*deleter)(void*, void*),
                        void* ctx = nullptr, uint64_t meta = 0);
  // Appends an arena Block, CONSUMING the caller's reference (the block
  // returns to its arena when the last IOBuf ref drops).  The zero-copy
  // entry for device-arena payloads (block_pool parity).
  void append_block(Block* b, uint32_t offset, uint32_t length) {
    push_ref(b, offset, length);
  }

  // Reserve n contiguous writable bytes at the tail; returns pointer.
  // Caller must fill them before any other operation.
  char* reserve(size_t n);

  // -- reading / cutting ----------------------------------------------
  // Copy up to n bytes starting at pos into dst; returns bytes copied.
  size_t copy_to(void* dst, size_t n, size_t pos = 0) const;
  std::string to_string() const;
  // Move the first n bytes into *out (zero copy); returns bytes moved.
  size_t cutn(IOBuf* out, size_t n);
  // Drop the first n bytes; returns bytes dropped.
  size_t pop_front(size_t n);
  // Drop the last n bytes; returns bytes dropped.
  size_t pop_back(size_t n);
  // First byte (buf must be non-empty).
  char front() const { return refs_.front().block->data[refs_.front().offset]; }

  // -- scatter-gather --------------------------------------------------
  // Fill up to max_iov iovecs covering at most max_bytes; returns count.
  int fill_iovec(iovec* iov, int max_iov,
                 size_t max_bytes = SIZE_MAX) const;
  // Append by taking ownership semantics from readv-style writes:
  // append up to n bytes read from fd; returns bytes read or -1.
  // block_hint > 0 sizes the fresh blocks (bulk path: a few multi-MB
  // blocks instead of thousands of 8KB ones — fewer iovecs per syscall,
  // contiguous landing for the stripe layer); 0 = default block size.
  ssize_t append_from_fd(int fd, size_t max_bytes, size_t block_hint = 0);
  // Write to fd with writev, popping written bytes; returns written or -1.
  ssize_t cut_into_fd(int fd, size_t max_bytes = SIZE_MAX);

  // Raw ref access (transports iterate blocks for DMA posting).
  const BlockRef& ref_at(size_t i) const { return refs_[i]; }

  bool equals(const void* data, size_t n) const;

 private:
  void push_ref(Block* b, uint32_t offset, uint32_t length);  // takes 1 ref
  Block* extendable_tail(size_t want) const;

  std::vector<BlockRef> refs_;
  size_t size_ = 0;
  BlockArena* arena_ = nullptr;  // nullptr → HostArena::instance()
};

}  // namespace trpc
