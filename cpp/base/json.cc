#include "base/json.h"

#include <cmath>
#include <cstdio>
#include <cstdlib>
#include <cstring>

namespace trpc {

Json Json::boolean(bool b) {
  Json j;
  j.type_ = Type::kBool;
  j.bool_ = b;
  return j;
}
Json Json::number(double d) {
  Json j;
  j.type_ = Type::kNumber;
  j.num_ = d;
  return j;
}
Json Json::str(std::string s) {
  Json j;
  j.type_ = Type::kString;
  j.str_ = std::move(s);
  return j;
}
Json Json::array() {
  Json j;
  j.type_ = Type::kArray;
  return j;
}
Json Json::object() {
  Json j;
  j.type_ = Type::kObject;
  return j;
}

void Json::push_back(Json v) {
  type_ = Type::kArray;
  arr_.push_back(std::move(v));
}

void Json::set(const std::string& key, Json v) {
  type_ = Type::kObject;
  obj_[key] = std::move(v);
}

const Json* Json::find(const std::string& key) const {
  auto it = obj_.find(key);
  return it == obj_.end() ? nullptr : &it->second;
}

namespace {

void escape_into(const std::string& s, std::string* out) {
  out->push_back('"');
  for (unsigned char c : s) {
    switch (c) {
      case '"': *out += "\\\""; break;
      case '\\': *out += "\\\\"; break;
      case '\n': *out += "\\n"; break;
      case '\r': *out += "\\r"; break;
      case '\t': *out += "\\t"; break;
      default:
        if (c < 0x20) {
          char buf[8];
          snprintf(buf, sizeof(buf), "\\u%04x", c);
          *out += buf;
        } else {
          out->push_back(static_cast<char>(c));
        }
    }
  }
  out->push_back('"');
}

}  // namespace

std::string Json::dump() const {
  std::string out;
  switch (type_) {
    case Type::kNull:
      out = "null";
      break;
    case Type::kBool:
      out = bool_ ? "true" : "false";
      break;
    case Type::kNumber: {
      if (!std::isfinite(num_)) {
        out = "null";  // JSON has no inf/nan (and casting them is UB)
        break;
      }
      char buf[32];
      if (std::fabs(num_) < 1e15 && num_ == static_cast<int64_t>(num_)) {
        snprintf(buf, sizeof(buf), "%lld",
                 static_cast<long long>(num_));
      } else {
        snprintf(buf, sizeof(buf), "%.17g", num_);
      }
      out = buf;
      break;
    }
    case Type::kString:
      escape_into(str_, &out);
      break;
    case Type::kArray: {
      out = "[";
      for (size_t i = 0; i < arr_.size(); ++i) {
        out += (i != 0 ? "," : "") + arr_[i].dump();
      }
      out += "]";
      break;
    }
    case Type::kObject: {
      out = "{";
      bool first = true;
      for (const auto& [k, v] : obj_) {
        if (!first) {
          out += ",";
        }
        first = false;
        escape_into(k, &out);
        out += ":" + v.dump();
      }
      out += "}";
      break;
    }
  }
  return out;
}

namespace {

struct Parser {
  const char* p;
  const char* end;
  int depth = 0;

  void ws() {
    while (p < end &&
           (*p == ' ' || *p == '\t' || *p == '\n' || *p == '\r')) {
      ++p;
    }
  }

  bool value(Json* out) {
    if (++depth > 64) {
      return false;  // depth bomb
    }
    ws();
    if (p >= end) {
      return false;
    }
    bool ok = false;
    switch (*p) {
      case '{': ok = object(out); break;
      case '[': ok = array(out); break;
      case '"': {
        std::string s;
        ok = string_lit(&s);
        if (ok) {
          *out = Json::str(std::move(s));
        }
        break;
      }
      case 't':
        ok = literal("true");
        if (ok) {
          *out = Json::boolean(true);
        }
        break;
      case 'f':
        ok = literal("false");
        if (ok) {
          *out = Json::boolean(false);
        }
        break;
      case 'n':
        ok = literal("null");
        if (ok) {
          *out = Json::null();
        }
        break;
      default: ok = number_lit(out); break;
    }
    --depth;
    return ok;
  }

  bool literal(const char* lit) {
    const size_t n = strlen(lit);
    if (static_cast<size_t>(end - p) < n || memcmp(p, lit, n) != 0) {
      return false;
    }
    p += n;
    return true;
  }

  bool number_lit(Json* out) {
    // RFC 8259 grammar gate before strtod (which would also accept
    // nan/inf/hex floats/leading '+').
    const char* q = p;
    if (q < end && *q == '-') {
      ++q;
    }
    if (q >= end || *q < '0' || *q > '9') {
      return false;
    }
    char* num_end = nullptr;
    const double v = strtod(p, &num_end);
    if (num_end == p || num_end > end || !std::isfinite(v)) {
      return false;
    }
    p = num_end;
    *out = Json::number(v);
    return true;
  }

  bool hex4(unsigned* out) {
    unsigned v = 0;
    for (int i = 0; i < 4; ++i) {
      if (p >= end) {
        return false;
      }
      const char c = *p++;
      v <<= 4;
      if (c >= '0' && c <= '9') {
        v |= c - '0';
      } else if (c >= 'a' && c <= 'f') {
        v |= c - 'a' + 10;
      } else if (c >= 'A' && c <= 'F') {
        v |= c - 'A' + 10;
      } else {
        return false;
      }
    }
    *out = v;
    return true;
  }

  bool string_lit(std::string* out) {
    if (p >= end || *p != '"') {
      return false;
    }
    ++p;
    while (p < end && *p != '"') {
      if (*p == '\\') {
        ++p;
        if (p >= end) {
          return false;
        }
        switch (*p) {
          case '"': out->push_back('"'); break;
          case '\\': out->push_back('\\'); break;
          case '/': out->push_back('/'); break;
          case 'b': out->push_back('\b'); break;
          case 'f': out->push_back('\f'); break;
          case 'n': out->push_back('\n'); break;
          case 'r': out->push_back('\r'); break;
          case 't': out->push_back('\t'); break;
          case 'u': {
            ++p;
            unsigned cp = 0;
            if (!hex4(&cp)) {
              return false;
            }
            // Basic-plane UTF-8 encode (surrogates passed through as-is).
            if (cp < 0x80) {
              out->push_back(static_cast<char>(cp));
            } else if (cp < 0x800) {
              out->push_back(static_cast<char>(0xC0 | (cp >> 6)));
              out->push_back(static_cast<char>(0x80 | (cp & 0x3F)));
            } else {
              out->push_back(static_cast<char>(0xE0 | (cp >> 12)));
              out->push_back(static_cast<char>(0x80 | ((cp >> 6) & 0x3F)));
              out->push_back(static_cast<char>(0x80 | (cp & 0x3F)));
            }
            continue;  // p already advanced past the 4 hex digits
          }
          default: return false;
        }
        ++p;
      } else {
        out->push_back(*p++);
      }
    }
    if (p >= end) {
      return false;  // unterminated
    }
    ++p;  // closing quote
    return true;
  }

  bool array(Json* out) {
    ++p;  // '['
    *out = Json::array();
    ws();
    if (p < end && *p == ']') {
      ++p;
      return true;
    }
    while (true) {
      Json v;
      if (!value(&v)) {
        return false;
      }
      out->push_back(std::move(v));
      ws();
      if (p < end && *p == ',') {
        ++p;
        continue;
      }
      if (p < end && *p == ']') {
        ++p;
        return true;
      }
      return false;
    }
  }

  bool object(Json* out) {
    ++p;  // '{'
    *out = Json::object();
    ws();
    if (p < end && *p == '}') {
      ++p;
      return true;
    }
    while (true) {
      ws();
      std::string key;
      if (!string_lit(&key)) {
        return false;
      }
      ws();
      if (p >= end || *p != ':') {
        return false;
      }
      ++p;
      Json v;
      if (!value(&v)) {
        return false;
      }
      out->set(key, std::move(v));
      ws();
      if (p < end && *p == ',') {
        ++p;
        continue;
      }
      if (p < end && *p == '}') {
        ++p;
        return true;
      }
      return false;
    }
  }
};

}  // namespace

bool Json::parse(const std::string& text, Json* out) {
  Parser ps{text.data(), text.data() + text.size()};
  if (!ps.value(out)) {
    return false;
  }
  ps.ws();
  return ps.p == ps.end;  // no trailing garbage
}

}  // namespace trpc
