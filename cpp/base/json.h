// Minimal JSON value + parser/writer.
//
// Parity role: the reference's json2pb bridge (/root/reference/src/
// json2pb/, 2,068 LoC pb⇄json transcoding).  This runtime is
// deliberately protobuf-free (the framed meta is a hand-rolled TLV), so
// the bridge's form here is a standalone JSON codec: builtin services
// render structured output (?format=json), tools parse JSON inputs, and
// Python/C++ handlers exchange structured payloads without a schema
// compiler.  Strict parser: rejects trailing garbage, caps depth.
#pragma once

#include <cstdint>
#include <map>
#include <memory>
#include <string>
#include <vector>

namespace trpc {

class Json {
 public:
  enum class Type { kNull, kBool, kNumber, kString, kArray, kObject };

  Json() = default;
  static Json null() { return Json(); }
  static Json boolean(bool b);
  static Json number(double d);
  static Json str(std::string s);
  static Json array();
  static Json object();

  Type type() const { return type_; }
  bool is_null() const { return type_ == Type::kNull; }
  bool as_bool() const { return bool_; }
  double as_number() const { return num_; }
  const std::string& as_string() const { return str_; }

  // Arrays.
  void push_back(Json v);
  size_t size() const { return arr_.size(); }
  const Json& operator[](size_t i) const { return arr_[i]; }

  // Objects.
  void set(const std::string& key, Json v);
  const Json* find(const std::string& key) const;  // nullptr when absent
  const std::map<std::string, Json>& items() const { return obj_; }

  // Serialization (compact; strings escaped per RFC 8259).
  std::string dump() const;

  // Strict parse of the WHOLE input; false on any error.
  static bool parse(const std::string& text, Json* out);

 private:
  Type type_ = Type::kNull;
  bool bool_ = false;
  double num_ = 0;
  std::string str_;
  std::vector<Json> arr_;
  std::map<std::string, Json> obj_;
};

}  // namespace trpc
