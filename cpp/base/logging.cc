#include "base/logging.h"

#include <unistd.h>

#include <cstdio>
#include <cstring>
#include <ctime>

namespace trpc {

std::atomic<int>& log_min_level() {
  static std::atomic<int> level{static_cast<int>(LogLevel::kInfo)};
  return level;
}

LogMessage::LogMessage(LogLevel level, const char* file, int line)
    : level_(level) {
  static const char* names = "DIWEF";
  const char* base = strrchr(file, '/');
  timespec ts;
  clock_gettime(CLOCK_REALTIME, &ts);
  tm t;
  localtime_r(&ts.tv_sec, &t);
  char prefix[96];
  snprintf(prefix, sizeof(prefix), "%c%02d%02d %02d:%02d:%02d.%06ld %s:%d] ",
           names[static_cast<int>(level)], t.tm_mon + 1, t.tm_mday, t.tm_hour,
           t.tm_min, t.tm_sec, ts.tv_nsec / 1000, base ? base + 1 : file,
           line);
  stream_ << prefix;
}

LogMessage::~LogMessage() {
  stream_ << '\n';
  const std::string s = stream_.str();
  ssize_t rc = write(STDERR_FILENO, s.data(), s.size());
  (void)rc;
  if (level_ == LogLevel::kFatal) {
    abort();
  }
}

}  // namespace trpc
