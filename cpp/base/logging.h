// Stream-style logging (parity: butil/logging.h LOG() macros,
// /root/reference/src/butil/logging.h — re-designed minimal, not a port).
#pragma once

#include <atomic>
#include <cstdlib>
#include <sstream>

namespace trpc {

enum class LogLevel : int { kDebug = 0, kInfo, kWarning, kError, kFatal };

// Runtime-adjustable minimum level (default Info).
std::atomic<int>& log_min_level();

class LogMessage {
 public:
  LogMessage(LogLevel level, const char* file, int line);
  ~LogMessage();  // flushes; aborts on kFatal
  std::ostream& stream() { return stream_; }

 private:
  LogLevel level_;
  std::ostringstream stream_;
};

class LogVoidify {
 public:
  void operator&(std::ostream&) {}
};

}  // namespace trpc

#define TRPC_LOG_IS_ON(level) \
  (static_cast<int>(::trpc::LogLevel::level) >= ::trpc::log_min_level().load(std::memory_order_relaxed))

#define LOG(level)                                                   \
  !TRPC_LOG_IS_ON(k##level)                                          \
      ? (void)0                                                      \
      : ::trpc::LogVoidify() &                                       \
            ::trpc::LogMessage(::trpc::LogLevel::k##level, __FILE__, \
                               __LINE__)                             \
                .stream()

#define LOG_IF(level, cond) \
  (!(cond)) ? (void)0 : LOG(level)

#define CHECK(cond)                                                       \
  (cond) ? (void)0                                                        \
         : ::trpc::LogVoidify() &                                         \
               ::trpc::LogMessage(::trpc::LogLevel::kFatal, __FILE__,     \
                                  __LINE__)                               \
                   .stream()                                              \
               << "Check failed: " #cond " "
