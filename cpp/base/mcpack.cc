#include "base/mcpack.h"

#include <cstring>

namespace trpc {

namespace {

constexpr uint8_t kShortMask = 0x80;
constexpr uint8_t kFixedMask = 0x0F;
constexpr uint8_t kNonDeletedMask = 0x70;

void put_u32(std::string* out, uint32_t v) {
  char b[4];
  memcpy(b, &v, 4);  // mcpack is little-endian-native, like the reference
  out->append(b, 4);
}

uint32_t get_u32(const char* p) {
  uint32_t v;
  memcpy(&v, p, 4);
  return v;
}

size_t fixed_value_size(McpackType t) {
  return static_cast<uint8_t>(t) & kFixedMask;
}

bool is_container(McpackType t) {
  return t == McpackType::kObject || t == McpackType::kArray ||
         t == McpackType::kIsoArray;
}

// Serializes a fixed-type scalar's raw value bytes.
void append_scalar(const McpackValue& v, std::string* out) {
  char b[8] = {0};
  switch (v.type) {
    case McpackType::kInt8: {
      const int8_t x = static_cast<int8_t>(v.i64);
      memcpy(b, &x, 1);
      out->append(b, 1);
      return;
    }
    case McpackType::kInt16: {
      const int16_t x = static_cast<int16_t>(v.i64);
      memcpy(b, &x, 2);
      out->append(b, 2);
      return;
    }
    case McpackType::kInt32: {
      const int32_t x = static_cast<int32_t>(v.i64);
      memcpy(b, &x, 4);
      out->append(b, 4);
      return;
    }
    case McpackType::kInt64:
      memcpy(b, &v.i64, 8);
      out->append(b, 8);
      return;
    case McpackType::kUint8: {
      const uint8_t x = static_cast<uint8_t>(v.u64);
      memcpy(b, &x, 1);
      out->append(b, 1);
      return;
    }
    case McpackType::kUint16: {
      const uint16_t x = static_cast<uint16_t>(v.u64);
      memcpy(b, &x, 2);
      out->append(b, 2);
      return;
    }
    case McpackType::kUint32: {
      const uint32_t x = static_cast<uint32_t>(v.u64);
      memcpy(b, &x, 4);
      out->append(b, 4);
      return;
    }
    case McpackType::kUint64:
      memcpy(b, &v.u64, 8);
      out->append(b, 8);
      return;
    case McpackType::kBool:
      b[0] = v.i64 != 0 ? 1 : 0;
      out->append(b, 1);
      return;
    case McpackType::kFloat: {
      const float x = static_cast<float>(v.f64);
      memcpy(b, &x, 4);
      out->append(b, 4);
      return;
    }
    case McpackType::kDouble:
      memcpy(b, &v.f64, 8);
      out->append(b, 8);
      return;
    case McpackType::kNull:
      b[0] = 0;
      out->append(b, 1);
      return;
    default:
      return;
  }
}

bool parse_scalar(McpackType t, const char* p, size_t n, McpackValue* out) {
  if (n != fixed_value_size(t)) {
    return false;
  }
  out->type = t;
  switch (t) {
    case McpackType::kInt8: {
      int8_t x;
      memcpy(&x, p, 1);
      out->i64 = x;
      return true;
    }
    case McpackType::kInt16: {
      int16_t x;
      memcpy(&x, p, 2);
      out->i64 = x;
      return true;
    }
    case McpackType::kInt32: {
      int32_t x;
      memcpy(&x, p, 4);
      out->i64 = x;
      return true;
    }
    case McpackType::kInt64:
      memcpy(&out->i64, p, 8);
      return true;
    case McpackType::kUint8: {
      uint8_t x;
      memcpy(&x, p, 1);
      out->u64 = x;
      return true;
    }
    case McpackType::kUint16: {
      uint16_t x;
      memcpy(&x, p, 2);
      out->u64 = x;
      return true;
    }
    case McpackType::kUint32: {
      uint32_t x;
      memcpy(&x, p, 4);
      out->u64 = x;
      return true;
    }
    case McpackType::kUint64:
      memcpy(&out->u64, p, 8);
      return true;
    case McpackType::kBool:
      out->i64 = p[0] != 0;
      return true;
    case McpackType::kFloat: {
      float x;
      memcpy(&x, p, 4);
      out->f64 = x;
      return true;
    }
    case McpackType::kDouble:
      memcpy(&out->f64, p, 8);
      return true;
    case McpackType::kNull:
      return true;
    default:
      return false;
  }
}

// Parses ONE item at data[0..len); recursion bounded by depth.
// *deleted: the item is a tombstone ((type & 0x70) == 0) — counted in its
// container's item_count but not a live field.
bool parse_item(const char* data, size_t len, std::string* name,
                McpackValue* out, size_t* consumed, bool* deleted,
                int depth) {
  if (depth > 32 || len < 2) {
    return false;
  }
  const uint8_t first = static_cast<uint8_t>(data[0]);
  uint8_t raw_type;
  size_t name_size, value_size, head_size;
  if (first & kFixedMask) {  // fixed head: 2 bytes, size in the nibble
    raw_type = first;
    name_size = static_cast<uint8_t>(data[1]);
    value_size = first & kFixedMask;
    head_size = 2;
  } else if (first & kShortMask) {  // short head: 3 bytes
    if (len < 3) {
      return false;
    }
    raw_type = first & static_cast<uint8_t>(~kShortMask);
    name_size = static_cast<uint8_t>(data[1]);
    value_size = static_cast<uint8_t>(data[2]);
    head_size = 3;
  } else {  // long head: 6 bytes
    if (len < 6) {
      return false;
    }
    raw_type = first;
    name_size = static_cast<uint8_t>(data[1]);
    value_size = get_u32(data + 2);
    head_size = 6;
  }
  const size_t full = head_size + name_size + value_size;
  if (full > len) {
    return false;
  }
  // The reference treats names as C-strings INCLUDING the trailing NUL;
  // a name whose last byte is not NUL is malformed, and stripping it
  // anyway would silently eat the name's last real byte (ADVICE r5).
  if (name_size > 0 && data[head_size + name_size - 1] != '\0') {
    return false;
  }
  if (name != nullptr) {
    if (name_size > 0) {
      name->assign(data + head_size, name_size - 1);  // strip the NUL
    } else {
      name->clear();
    }
  }
  *consumed = full;
  *deleted = !(raw_type & kNonDeletedMask);
  if (*deleted) {
    out->type = McpackType::kNull;
    return true;
  }
  const char* v = data + head_size + name_size;
  const auto t = static_cast<McpackType>(raw_type);
  switch (t) {
    case McpackType::kObject:
    case McpackType::kArray: {
      if (value_size < 4) {
        return false;
      }
      out->type = t;
      const uint32_t count = get_u32(v);
      const char* p = v + 4;
      size_t left = value_size - 4;
      for (uint32_t i = 0; i < count; ++i) {
        std::string child_name;
        McpackValue child;
        size_t used = 0;
        bool child_deleted = false;
        if (!parse_item(p, left, &child_name, &child, &used, &child_deleted,
                        depth + 1)) {
          return false;
        }
        p += used;
        left -= used;
        if (child_deleted) {
          continue;  // tombstone: counted on the wire, absent in the tree
        }
        if (t == McpackType::kObject) {
          out->fields.emplace_back(std::move(child_name), std::move(child));
        } else {
          out->items.push_back(std::move(child));
        }
      }
      return true;
    }
    case McpackType::kIsoArray: {
      if (value_size < 1) {
        return false;
      }
      const auto elem = static_cast<McpackType>(v[0]);
      const size_t esz = fixed_value_size(elem);
      if (esz == 0 || (value_size - 1) % esz != 0) {
        return false;
      }
      out->type = t;
      out->iso_type = elem;
      const char* p = v + 1;
      for (size_t i = 0; i < (value_size - 1) / esz; ++i) {
        McpackValue e;
        if (!parse_scalar(elem, p + i * esz, esz, &e)) {
          return false;
        }
        out->items.push_back(std::move(e));
      }
      return true;
    }
    case McpackType::kString:
      if (value_size == 0 || v[value_size - 1] != '\0') {
        return false;  // strings carry a trailing NUL on the wire
      }
      out->type = t;
      out->str.assign(v, value_size - 1);
      return true;
    case McpackType::kBinary:
      out->type = t;
      out->str.assign(v, value_size);
      return true;
    default:
      return parse_scalar(t, v, value_size, out);
  }
}

}  // namespace

McpackValue McpackValue::Str(std::string s) {
  McpackValue v = with(McpackType::kString);
  v.str = std::move(s);
  return v;
}

McpackValue McpackValue::Binary(std::string bytes) {
  McpackValue v = with(McpackType::kBinary);
  v.str = std::move(bytes);
  return v;
}

McpackValue McpackValue::I32(int32_t x) {
  McpackValue v = with(McpackType::kInt32);
  v.i64 = x;
  return v;
}

McpackValue McpackValue::I64(int64_t x) {
  McpackValue v = with(McpackType::kInt64);
  v.i64 = x;
  return v;
}

McpackValue McpackValue::U64(uint64_t x) {
  McpackValue v = with(McpackType::kUint64);
  v.u64 = x;
  return v;
}

McpackValue McpackValue::Bool(bool x) {
  McpackValue v = with(McpackType::kBool);
  v.i64 = x ? 1 : 0;
  return v;
}

McpackValue McpackValue::Double(double x) {
  McpackValue v = with(McpackType::kDouble);
  v.f64 = x;
  return v;
}

McpackValue McpackValue::IsoArray(McpackType elem) {
  McpackValue v = with(McpackType::kIsoArray);
  v.iso_type = elem;
  return v;
}

const McpackValue* McpackValue::field(const std::string& name) const {
  for (const auto& [k, v] : fields) {
    if (k == name) {
      return &v;
    }
  }
  return nullptr;
}

bool McpackValue::serialize_item(const std::string& name,
                                 std::string* out) const {
  if (name.size() > 254) {
    // The wire's name_size is one byte (name + NUL ≤ 255); emitting a
    // truncated length would corrupt the whole image (reference
    // serializer.cpp:195 rejects the same way).
    return false;
  }
  const uint8_t raw = static_cast<uint8_t>(type);
  const size_t name_size = name.empty() ? 0 : name.size() + 1;
  auto append_name = [&] {
    if (!name.empty()) {
      out->append(name);
      out->push_back('\0');
    }
  };
  if (raw & kFixedMask) {  // fixed head
    out->push_back(static_cast<char>(raw));
    out->push_back(static_cast<char>(name_size));
    append_name();
    append_scalar(*this, out);
    return true;
  }
  // Build the value bytes first (containers need their size up front).
  std::string value;
  switch (type) {
    case McpackType::kObject:
      put_u32(&value, static_cast<uint32_t>(fields.size()));
      for (const auto& [k, v] : fields) {
        if (!v.serialize_item(k, &value)) {
          return false;
        }
      }
      break;
    case McpackType::kArray:
      put_u32(&value, static_cast<uint32_t>(items.size()));
      for (const McpackValue& v : items) {
        if (!v.serialize_item("", &value)) {
          return false;
        }
      }
      break;
    case McpackType::kIsoArray:
      value.push_back(static_cast<char>(iso_type));
      for (const McpackValue& v : items) {
        append_scalar(v, &value);
      }
      break;
    case McpackType::kString:
      value.assign(str);
      value.push_back('\0');
      break;
    case McpackType::kBinary:
      value.assign(str);
      break;
    default:
      break;
  }
  if (value.size() <= 255 &&
      (type == McpackType::kString || type == McpackType::kBinary)) {
    // Short head for small strings/raws (parser.cpp:43 FieldShortHead).
    out->push_back(static_cast<char>(raw | kShortMask));
    out->push_back(static_cast<char>(name_size));
    out->push_back(static_cast<char>(value.size()));
  } else {
    out->push_back(static_cast<char>(raw));
    out->push_back(static_cast<char>(name_size));
    put_u32(out, static_cast<uint32_t>(value.size()));
  }
  append_name();
  out->append(value);
  return true;
}

std::string McpackValue::serialize() const {
  std::string out;
  if (!serialize_item("", &out)) {
    return "";  // some field name exceeds the wire's 254-byte limit
  }
  return out;
}

bool McpackValue::parse(const char* data, size_t len, McpackValue* out,
                        size_t* consumed) {
  size_t used = 0;
  bool deleted = false;
  if (!parse_item(data, len, nullptr, out, &used, &deleted, 0)) {
    return false;
  }
  if (consumed != nullptr) {
    *consumed = used;
  }
  return true;
}

}  // namespace trpc
