// mcpack_v2 — Baidu's tagged binary serialization, the payload format of
// nshead-framed legacy services.
//
// Parity: /root/reference/src/mcpack2pb (field_type.h:30 type tags;
// parser.cpp:30-80 the three head forms; serializer.cpp object/array
// bodies).  The reference compiles .proto files into mcpack
// parse/serialize functions; ours is a VALUE-MODEL codec (like this
// repo's json.h / thrift.h / mongo BSON): parse to a tree, build a tree,
// serialize — which is what a polyglot RPC framework needs to interop
// with mcpack peers without a codegen step.
//
// Wire format (mcpack_v2):
//   item      := head name? value
//   head      := fixed (2B: type, name_size)          low nibble != 0
//              | short (3B: type|0x80, name_size, value_size u8)
//              | long  (6B: type, name_size, value_size u32)
//   name      := name_size bytes INCLUDING a trailing NUL (0 = unnamed)
//   OBJECT 0x10 / ARRAY 0x20 value := u32 item_count, then items
//   ISOARRAY 0x30 value := u8 item_type, then packed primitive values
//   STRING 0x50 value includes a trailing NUL; BINARY 0x60 is raw
//   fixed types encode their size in the low nibble (INT32 0x14, ...)
//   deleted items have (type & 0x70) == 0 and are skipped
#pragma once

#include <cstdint>
#include <map>
#include <memory>
#include <string>
#include <vector>

namespace trpc {

enum class McpackType : uint8_t {
  kObject = 0x10,
  kArray = 0x20,
  kIsoArray = 0x30,
  kString = 0x50,
  kBinary = 0x60,
  kInt8 = 0x11,
  kInt16 = 0x12,
  kInt32 = 0x14,
  kInt64 = 0x18,
  kUint8 = 0x21,
  kUint16 = 0x22,
  kUint32 = 0x24,
  kUint64 = 0x28,
  kBool = 0x31,
  kFloat = 0x44,
  kDouble = 0x48,
  kNull = 0x61,
};

struct McpackValue {
  McpackType type = McpackType::kNull;
  // Scalars.
  int64_t i64 = 0;     // all signed ints + bool
  uint64_t u64 = 0;    // all unsigned ints
  double f64 = 0.0;    // float + double
  std::string str;     // string (no NUL) / binary bytes
  // Containers: object fields keep insertion order (names in `keys`).
  std::vector<std::pair<std::string, McpackValue>> fields;  // object
  std::vector<McpackValue> items;                           // array
  McpackType iso_type = McpackType::kNull;  // isoarray element type

  // -- builders ---------------------------------------------------------
  static McpackValue Object() { return with(McpackType::kObject); }
  static McpackValue Array() { return with(McpackType::kArray); }
  static McpackValue Str(std::string s);
  static McpackValue Binary(std::string bytes);
  static McpackValue I32(int32_t v);
  static McpackValue I64(int64_t v);
  static McpackValue U64(uint64_t v);
  static McpackValue Bool(bool v);
  static McpackValue Double(double v);
  static McpackValue Null() { return {}; }
  // Homogeneous packed array of a FIXED type (kInt32 etc.).
  static McpackValue IsoArray(McpackType elem);

  void add_field(const std::string& name, McpackValue v) {
    fields.emplace_back(name, std::move(v));
  }
  void add_item(McpackValue v) { items.push_back(std::move(v)); }
  const McpackValue* field(const std::string& name) const;

  // -- codec ------------------------------------------------------------
  // Serializes this value as an UNNAMED root item (the nshead body form).
  // Returns "" when a field name exceeds the wire's 254-byte limit.
  std::string serialize() const;
  // Parses one root item; false on malformed/truncated input.
  // *consumed (optional) reports the item's full wire size.
  static bool parse(const char* data, size_t len, McpackValue* out,
                    size_t* consumed = nullptr);

 private:
  static McpackValue with(McpackType t) {
    McpackValue v;
    v.type = t;
    return v;
  }
  bool serialize_item(const std::string& name, std::string* out) const;
};

}  // namespace trpc
