#include "base/pbwire.h"

#include <cstring>

namespace trpc {

// ---- primitives ----------------------------------------------------------

void pb_put_varint(std::string* out, uint64_t v) {
  while (v >= 0x80) {
    out->push_back(static_cast<char>((v & 0x7f) | 0x80));
    v >>= 7;
  }
  out->push_back(static_cast<char>(v));
}

void pb_put_tag(std::string* out, uint32_t field, uint32_t wire_type) {
  pb_put_varint(out, (static_cast<uint64_t>(field) << 3) | wire_type);
}

uint64_t pb_zigzag(int64_t v) {
  return (static_cast<uint64_t>(v) << 1) ^
         static_cast<uint64_t>(v >> 63);
}

int64_t pb_unzigzag(uint64_t v) {
  return static_cast<int64_t>((v >> 1) ^ (~(v & 1) + 1));
}

bool pb_get_varint(std::string_view in, size_t* pos, uint64_t* out) {
  uint64_t v = 0;
  int shift = 0;
  size_t p = *pos;
  while (p < in.size() && shift < 70) {
    uint8_t b = static_cast<uint8_t>(in[p++]);
    if (shift == 63 && (b & 0x7e) != 0) return false;  // overflows u64
    v |= static_cast<uint64_t>(b & 0x7f) << shift;
    if (!(b & 0x80)) {
      *pos = p;
      *out = v;
      return true;
    }
    shift += 7;
  }
  return false;  // truncated or > 10 bytes
}

static void put_fixed32(std::string* out, uint32_t v) {
  char b[4];
  std::memcpy(b, &v, 4);  // wire is little-endian == host on x86_64
  out->append(b, 4);
}

static void put_fixed64(std::string* out, uint64_t v) {
  char b[8];
  std::memcpy(b, &v, 8);
  out->append(b, 8);
}

// ---- PbMessage build side ------------------------------------------------

void PbMessage::add_varint(uint32_t field, uint64_t v) {
  PbField f;
  f.num = field;
  f.wire = PbField::kVarint;
  f.varint = v;
  fields_.push_back(std::move(f));
}

void PbMessage::add_sint(uint32_t field, int64_t v) {
  add_varint(field, pb_zigzag(v));
}

void PbMessage::add_fixed32(uint32_t field, uint32_t v) {
  PbField f;
  f.num = field;
  f.wire = PbField::kFixed32;
  f.varint = v;
  fields_.push_back(std::move(f));
}

void PbMessage::add_fixed64(uint32_t field, uint64_t v) {
  PbField f;
  f.num = field;
  f.wire = PbField::kFixed64;
  f.varint = v;
  fields_.push_back(std::move(f));
}

void PbMessage::add_double(uint32_t field, double v) {
  uint64_t bits;
  std::memcpy(&bits, &v, 8);
  add_fixed64(field, bits);
}

void PbMessage::add_float(uint32_t field, float v) {
  uint32_t bits;
  std::memcpy(&bits, &v, 4);
  add_fixed32(field, bits);
}

void PbMessage::add_bytes(uint32_t field, std::string_view v) {
  PbField f;
  f.num = field;
  f.wire = PbField::kBytes;
  f.bytes.assign(v.data(), v.size());
  fields_.push_back(std::move(f));
}

void PbMessage::add_message(uint32_t field, const PbMessage& m) {
  add_bytes(field, m.serialize());
}

// ---- PbMessage read side -------------------------------------------------

static const PbField* first(const std::vector<PbField>& fields,
                            uint32_t num) {
  for (const PbField& f : fields) {
    if (f.num == num) return &f;
  }
  return nullptr;
}

bool PbMessage::has(uint32_t field) const {
  return first(fields_, field) != nullptr;
}

uint64_t PbMessage::get_varint(uint32_t field, uint64_t def) const {
  const PbField* f = first(fields_, field);
  return (f && f->wire != PbField::kBytes) ? f->varint : def;
}

int64_t PbMessage::get_sint(uint32_t field, int64_t def) const {
  const PbField* f = first(fields_, field);
  return (f && f->wire != PbField::kBytes) ? pb_unzigzag(f->varint) : def;
}

uint64_t PbMessage::get_fixed(uint32_t field, uint64_t def) const {
  return get_varint(field, def);
}

double PbMessage::get_double(uint32_t field, double def) const {
  const PbField* f = first(fields_, field);
  if (!f || f->wire != PbField::kFixed64) return def;
  double d;
  uint64_t bits = f->varint;
  std::memcpy(&d, &bits, 8);
  return d;
}

std::string_view PbMessage::get_bytes(uint32_t field,
                                      std::string_view def) const {
  const PbField* f = first(fields_, field);
  return (f && f->wire == PbField::kBytes) ? std::string_view(f->bytes)
                                           : def;
}

bool PbMessage::get_message(uint32_t field, PbMessage* out) const {
  const PbField* f = first(fields_, field);
  if (!f || f->wire != PbField::kBytes) return false;
  return out->parse(f->bytes);
}

std::vector<const PbField*> PbMessage::all(uint32_t field) const {
  std::vector<const PbField*> out;
  for (const PbField& f : fields_) {
    if (f.num == field) out.push_back(&f);
  }
  return out;
}

void PbMessage::serialize(std::string* out) const {
  for (const PbField& f : fields_) {
    pb_put_tag(out, f.num, f.wire);
    switch (f.wire) {
      case PbField::kVarint:
        pb_put_varint(out, f.varint);
        break;
      case PbField::kFixed64:
        put_fixed64(out, f.varint);
        break;
      case PbField::kFixed32:
        put_fixed32(out, static_cast<uint32_t>(f.varint));
        break;
      case PbField::kBytes:
        pb_put_varint(out, f.bytes.size());
        out->append(f.bytes);
        break;
    }
  }
}

std::string PbMessage::serialize() const {
  std::string out;
  serialize(&out);
  return out;
}

bool PbMessage::parse(std::string_view in) {
  fields_.clear();
  size_t pos = 0;
  while (pos < in.size()) {
    uint64_t key;
    if (!pb_get_varint(in, &pos, &key)) return false;
    uint32_t num = static_cast<uint32_t>(key >> 3);
    uint32_t wt = static_cast<uint32_t>(key & 7);
    if (num == 0) return false;  // field 0 is reserved/invalid
    PbField f;
    f.num = num;
    switch (wt) {
      case 0: {
        f.wire = PbField::kVarint;
        if (!pb_get_varint(in, &pos, &f.varint)) return false;
        break;
      }
      case 1: {
        f.wire = PbField::kFixed64;
        if (pos + 8 > in.size()) return false;
        uint64_t v;
        std::memcpy(&v, in.data() + pos, 8);
        f.varint = v;
        pos += 8;
        break;
      }
      case 2: {
        f.wire = PbField::kBytes;
        uint64_t len;
        if (!pb_get_varint(in, &pos, &len)) return false;
        if (len > in.size() - pos) return false;
        f.bytes.assign(in.data() + pos, len);
        pos += len;
        break;
      }
      case 5: {
        f.wire = PbField::kFixed32;
        if (pos + 4 > in.size()) return false;
        uint32_t v;
        std::memcpy(&v, in.data() + pos, 4);
        f.varint = v;
        pos += 4;
        break;
      }
      default:
        return false;  // groups (3/4) and invalid types rejected
    }
    fields_.push_back(std::move(f));
  }
  return true;
}

// ---- schema --------------------------------------------------------------

const PbSchema::Field* PbSchema::by_num(uint32_t num) const {
  for (const Field& f : fields) {
    if (f.num == num) return &f;
  }
  return nullptr;
}

const PbSchema::Field* PbSchema::by_name(std::string_view name) const {
  for (const Field& f : fields) {
    if (name == f.name) return &f;
  }
  return nullptr;
}

// ---- JSON transcoding ----------------------------------------------------

static const char kHex[] = "0123456789abcdef";

static std::string to_hex(std::string_view in) {
  std::string out;
  out.reserve(in.size() * 2);
  for (unsigned char c : in) {
    out.push_back(kHex[c >> 4]);
    out.push_back(kHex[c & 15]);
  }
  return out;
}

static bool from_hex(std::string_view in, std::string* out) {
  if (in.size() % 2) return false;
  out->clear();
  out->reserve(in.size() / 2);
  for (size_t i = 0; i < in.size(); i += 2) {
    auto nib = [](char c) -> int {
      if (c >= '0' && c <= '9') return c - '0';
      if (c >= 'a' && c <= 'f') return c - 'a' + 10;
      if (c >= 'A' && c <= 'F') return c - 'A' + 10;
      return -1;
    };
    int hi = nib(in[i]), lo = nib(in[i + 1]);
    if (hi < 0 || lo < 0) return false;
    out->push_back(static_cast<char>((hi << 4) | lo));
  }
  return true;
}

static Json field_to_json(const PbField& f, const PbSchema::Field& sf) {
  switch (sf.kind) {
    case PbSchema::kInt64:
      return Json::number(
          static_cast<double>(static_cast<int64_t>(f.varint)));
    case PbSchema::kUint64:
    case PbSchema::kFixed32:
    case PbSchema::kFixed64:
      return Json::number(static_cast<double>(f.varint));
    case PbSchema::kSint64:
      return Json::number(static_cast<double>(pb_unzigzag(f.varint)));
    case PbSchema::kBool:
      return Json::boolean(f.varint != 0);
    case PbSchema::kString:
      return Json::str(f.bytes);
    case PbSchema::kBytesHex:
      return Json::str(to_hex(f.bytes));
    case PbSchema::kDouble: {
      double d;
      uint64_t bits = f.varint;
      std::memcpy(&d, &bits, 8);
      return Json::number(d);
    }
    case PbSchema::kFloat: {
      float fl;
      uint32_t bits = static_cast<uint32_t>(f.varint);
      std::memcpy(&fl, &bits, 4);
      return Json::number(fl);
    }
    case PbSchema::kMessage: {
      PbMessage nested;
      if (sf.nested && nested.parse(f.bytes)) {
        return pb_to_json(nested, *sf.nested);
      }
      return Json::str(to_hex(f.bytes));
    }
  }
  return Json::null();
}

Json pb_to_json(const PbMessage& msg, const PbSchema& schema) {
  Json out = Json::object();
  // Repeated fields accumulate in a staging map (appending through the
  // object would copy the growing array per occurrence — quadratic).
  std::map<std::string, Json> arrays;
  for (const PbField& f : msg.fields()) {
    const PbSchema::Field* sf = schema.by_num(f.num);
    if (!sf) {  // unknown field: keep under its number, best effort
      std::string key = std::to_string(f.num);
      if (f.wire == PbField::kBytes) {
        out.set(key, Json::str(to_hex(f.bytes)));
      } else {
        out.set(key, Json::number(static_cast<double>(f.varint)));
      }
      continue;
    }
    Json v = field_to_json(f, *sf);
    if (sf->repeated) {
      Json& slot = arrays.try_emplace(sf->name, Json::array()).first->second;
      slot.push_back(std::move(v));
    } else {
      out.set(sf->name, std::move(v));
    }
  }
  for (auto& [name, arr] : arrays) {
    out.set(name, std::move(arr));
  }
  return out;
}

static bool json_value_to_field(const Json& v, const PbSchema::Field& sf,
                                PbMessage* out) {
  switch (sf.kind) {
    case PbSchema::kInt64:
      if (v.type() != Json::Type::kNumber) return false;
      out->add_varint(sf.num,
                      static_cast<uint64_t>(
                          static_cast<int64_t>(v.as_number())));
      return true;
    case PbSchema::kUint64:
      if (v.type() != Json::Type::kNumber) return false;
      out->add_varint(sf.num, static_cast<uint64_t>(v.as_number()));
      return true;
    case PbSchema::kSint64:
      if (v.type() != Json::Type::kNumber) return false;
      out->add_sint(sf.num, static_cast<int64_t>(v.as_number()));
      return true;
    case PbSchema::kBool:
      if (v.type() != Json::Type::kBool) return false;
      out->add_bool(sf.num, v.as_bool());
      return true;
    case PbSchema::kString:
      if (v.type() != Json::Type::kString) return false;
      out->add_bytes(sf.num, v.as_string());
      return true;
    case PbSchema::kBytesHex: {
      if (v.type() != Json::Type::kString) return false;
      std::string raw;
      if (!from_hex(v.as_string(), &raw)) return false;
      out->add_bytes(sf.num, raw);
      return true;
    }
    case PbSchema::kDouble:
      if (v.type() != Json::Type::kNumber) return false;
      out->add_double(sf.num, v.as_number());
      return true;
    case PbSchema::kFloat:
      if (v.type() != Json::Type::kNumber) return false;
      out->add_float(sf.num, static_cast<float>(v.as_number()));
      return true;
    case PbSchema::kFixed32:
      if (v.type() != Json::Type::kNumber) return false;
      out->add_fixed32(sf.num, static_cast<uint32_t>(v.as_number()));
      return true;
    case PbSchema::kFixed64:
      if (v.type() != Json::Type::kNumber) return false;
      out->add_fixed64(sf.num, static_cast<uint64_t>(v.as_number()));
      return true;
    case PbSchema::kMessage: {
      if (v.type() != Json::Type::kObject || !sf.nested) return false;
      PbMessage nested;
      if (!json_to_pb(v, *sf.nested, &nested)) return false;
      out->add_message(sf.num, nested);
      return true;
    }
  }
  return false;
}

bool json_to_pb(const Json& j, const PbSchema& schema, PbMessage* out) {
  if (j.type() != Json::Type::kObject) return false;
  for (const auto& [key, val] : j.items()) {
    const PbSchema::Field* sf = schema.by_name(key);
    if (!sf) continue;  // unknown keys ignored (json2pb behavior)
    if (sf->repeated && val.type() == Json::Type::kArray) {
      for (size_t i = 0; i < val.size(); ++i) {
        if (!json_value_to_field(val[i], *sf, out)) return false;
      }
    } else if (!json_value_to_field(val, *sf, out)) {
      return false;
    }
  }
  return true;
}

static bool mostly_printable(std::string_view s) {
  if (s.empty()) return true;
  size_t printable = 0;
  for (unsigned char c : s) {
    if (c == '\t' || c == '\n' || (c >= 0x20 && c < 0x7f)) ++printable;
  }
  return printable * 10 >= s.size() * 9;  // >= 90%
}

Json pb_to_json_schemaless(const PbMessage& msg, int max_depth) {
  Json out = Json::object();
  // Stage per-number value lists first (linear), then emit scalars for
  // single occurrences and arrays for repeats.
  std::map<std::string, std::vector<Json>> staged;
  for (const PbField& f : msg.fields()) {
    std::string key = std::to_string(f.num);
    Json v;
    if (f.wire == PbField::kBytes) {
      PbMessage nested;
      // Heuristic order matters: short printable buffers often ALSO parse
      // as messages ("hi" = field 13 varint 105), so printable wins, then
      // the nested-message attempt, then hex.
      if (mostly_printable(f.bytes)) {
        v = Json::str(f.bytes);
      } else if (max_depth > 0 && !f.bytes.empty() &&
                 nested.parse(f.bytes)) {
        v = pb_to_json_schemaless(nested, max_depth - 1);
      } else {
        v = Json::str(to_hex(f.bytes));
      }
    } else {
      v = Json::number(static_cast<double>(f.varint));
    }
    staged[key].push_back(std::move(v));
  }
  for (auto& [key, vals] : staged) {
    if (vals.size() == 1) {
      out.set(key, std::move(vals[0]));
    } else {
      Json arr = Json::array();
      for (Json& v : vals) {
        arr.push_back(std::move(v));
      }
      out.set(key, std::move(arr));
    }
  }
  return out;
}


// ---- runtime .proto parsing (rpc_press_impl parity) ----------------------

namespace {

// Tokenizer: identifiers/numbers, punctuation chars, skips whitespace,
// // and /* */ comments.
struct ProtoLexer {
  std::string_view s;
  size_t i = 0;

  void skip_ws() {
    while (i < s.size()) {
      if (isspace(static_cast<unsigned char>(s[i]))) {
        ++i;
      } else if (s.compare(i, 2, "//") == 0) {
        while (i < s.size() && s[i] != '\n') {
          ++i;
        }
      } else if (s.compare(i, 2, "/*") == 0) {
        const size_t end = s.find("*/", i + 2);
        i = end == std::string_view::npos ? s.size() : end + 2;
      } else {
        break;
      }
    }
  }

  std::string next() {
    skip_ws();
    if (i >= s.size()) {
      return "";
    }
    const char c = s[i];
    if (isalnum(static_cast<unsigned char>(c)) || c == '_' || c == '.') {
      const size_t start = i;
      while (i < s.size() &&
             (isalnum(static_cast<unsigned char>(s[i])) || s[i] == '_' ||
              s[i] == '.')) {
        ++i;
      }
      return std::string(s.substr(start, i - start));
    }
    if (c == '"') {  // string literal (option values)
      const size_t start = i++;
      while (i < s.size() && s[i] != '"') {
        ++i;
      }
      ++i;
      return std::string(s.substr(start, i - start));
    }
    ++i;
    return std::string(1, c);
  }

};

bool scalar_kind(const std::string& type, PbSchema::Kind* kind) {
  if (type == "int32" || type == "int64") {
    *kind = PbSchema::kInt64;
  } else if (type == "uint32" || type == "uint64") {
    *kind = PbSchema::kUint64;
  } else if (type == "sint32" || type == "sint64") {
    *kind = PbSchema::kSint64;
  } else if (type == "bool") {
    *kind = PbSchema::kBool;
  } else if (type == "string") {
    *kind = PbSchema::kString;
  } else if (type == "bytes") {
    *kind = PbSchema::kBytesHex;
  } else if (type == "double") {
    *kind = PbSchema::kDouble;
  } else if (type == "float") {
    *kind = PbSchema::kFloat;
  } else if (type == "fixed32") {
    *kind = PbSchema::kFixed32;
  } else if (type == "fixed64") {
    *kind = PbSchema::kFixed64;
  } else {
    return false;
  }
  return true;
}

struct PendingField {
  std::string message;  // owning message
  std::string type;     // unresolved message-type name
  size_t index;         // field slot in that schema
};

// Parses one message block (after "message Name {"); nested message
// definitions recurse and register under their bare name.
bool parse_message_block(ProtoLexer* lex, const std::string& name,
                         std::map<std::string, PbSchema>* out,
                         std::vector<PendingField>* pending,
                         std::string* err) {
  if (out->count(name) != 0) {
    // Bare-name registry: silently merging two same-named messages
    // (e.g. nested `Entry` in two siblings) would interleave their
    // fields; reject instead.
    *err = "duplicate message name " + name +
           " (the runtime subset registers bare names)";
    return false;
  }
  PbSchema& schema = (*out)[name];  // node address stable from here on
  while (true) {
    std::string tok = lex->next();
    if (tok.empty()) {
      *err = "unterminated message " + name;
      return false;
    }
    if (tok == "}") {
      return true;
    }
    if (tok == ";") {
      continue;
    }
    if (tok == "message") {  // nested definition
      const std::string inner = lex->next();
      if (lex->next() != "{") {
        *err = "expected { after nested message " + inner;
        return false;
      }
      if (!parse_message_block(lex, inner, out, pending, err)) {
        return false;
      }
      continue;
    }
    if (tok == "option" || tok == "reserved") {
      while (!tok.empty() && tok != ";") {
        tok = lex->next();
      }
      continue;
    }
    // Field: [repeated|optional|required] <type> <name> = <num> [...] ;
    bool repeated = false;
    if (tok == "repeated") {
      repeated = true;
      tok = lex->next();
    } else if (tok == "optional" || tok == "required") {
      tok = lex->next();
    }
    const std::string type = tok;
    const std::string fname = lex->next();
    if (lex->next() != "=") {
      *err = "expected = after field " + fname + " in " + name;
      return false;
    }
    const std::string numtok = lex->next();
    char* endp = nullptr;
    const long num = strtol(numtok.c_str(), &endp, 10);
    if (endp == numtok.c_str() || num <= 0) {
      *err = "bad field number for " + fname + " in " + name;
      return false;
    }
    // Swallow options/semicolon.
    for (std::string t = lex->next(); !t.empty() && t != ";";
         t = lex->next()) {
    }
    if (type == "sfixed32" || type == "sfixed64" || type == "group" ||
        type == "map" || type == "enum" || type == "oneof") {
      *err = "unsupported field type " + type + " (field " + fname +
             " in " + name + ")";
      return false;
    }
    schema.name_pool.push_back(fname);
    PbSchema::Field f;
    f.num = static_cast<uint32_t>(num);
    f.name = schema.name_pool.back().c_str();
    f.repeated = repeated;
    if (!scalar_kind(type, &f.kind)) {
      f.kind = PbSchema::kMessage;  // message type: resolve after parsing
      pending->push_back(PendingField{name, type, schema.fields.size()});
    }
    schema.fields.push_back(f);
  }
}

}  // namespace

bool parse_proto_file(const std::string& text,
                      std::map<std::string, PbSchema>* out,
                      std::string* err) {
  ProtoLexer lex{text};
  std::vector<PendingField> pending;
  while (true) {
    std::string tok = lex.next();
    if (tok.empty()) {
      break;
    }
    if (tok == "syntax" || tok == "package" || tok == "option" ||
        tok == "import") {
      while (!tok.empty() && tok != ";") {
        tok = lex.next();
      }
      continue;
    }
    if (tok == "message") {
      const std::string name = lex.next();
      if (lex.next() != "{") {
        *err = "expected { after message " + name;
        return false;
      }
      if (!parse_message_block(&lex, name, out, &pending, err)) {
        return false;
      }
      continue;
    }
    if (tok == ";") {
      continue;
    }
    *err = "unsupported construct: " + tok;
    return false;
  }
  // Resolve message-typed fields (bare name, or the last dotted segment).
  for (const PendingField& pf : pending) {
    std::string type = pf.type;
    const size_t dot = type.rfind('.');
    if (dot != std::string::npos) {
      type = type.substr(dot + 1);
    }
    auto it = out->find(type);
    if (it == out->end()) {
      *err = "unknown message type " + pf.type + " (field in " +
             pf.message + ")";
      return false;
    }
    (*out)[pf.message].fields[pf.index].nested = &it->second;
  }
  return true;
}

}  // namespace trpc
