// Protobuf wire-format codec — schema-free message model + JSON transcoding.
//
// Parity role: the reference's interop protocols carry protobuf metas on the
// wire (policy/hulu_pbrpc_meta.proto, sofa_pbrpc_meta.proto,
// public_pbrpc_meta.proto, baidu_rpc_meta.proto) and its json2pb module
// (/root/reference/src/json2pb/, 2,068 LoC) transcodes pb⇄json through
// generated descriptors.  This runtime is deliberately protobuf-free, so the
// equivalent seam is a hand-rolled wire codec: PbMessage models an encoded
// message as an ordered field list (numbers + wire types, no descriptor),
// letting protocols build and read byte-compatible metas, and PbSchema is a
// lightweight runtime descriptor that names fields for proper JSON
// transcoding both directions (the json2pb replacement — no codegen).
//
// Wire format implemented per the public protobuf encoding spec:
// varint / zigzag sint / fixed32 / fixed64 / length-delimited, tags
// (field_number << 3) | wire_type.  Groups (deprecated wire types 3/4) are
// rejected.
#pragma once

#include <cstdint>
#include <deque>
#include <map>
#include <string>
#include <string_view>
#include <vector>

#include "base/json.h"

namespace trpc {

// ---- primitive encoding (exposed for protocol packers + tests) -----------

void pb_put_varint(std::string* out, uint64_t v);
void pb_put_tag(std::string* out, uint32_t field, uint32_t wire_type);
uint64_t pb_zigzag(int64_t v);    // sint encoding
int64_t pb_unzigzag(uint64_t v);

// Reads one varint at (*pos); false on truncation/overlong (>10 bytes).
bool pb_get_varint(std::string_view in, size_t* pos, uint64_t* out);

// ---- message model -------------------------------------------------------

// One decoded/buildable field.  `wire` distinguishes how the value is
// encoded; accessors on PbMessage interpret it.
struct PbField {
  enum Wire : uint8_t {
    kVarint = 0,
    kFixed64 = 1,
    kBytes = 2,   // length-delimited: strings, bytes, nested messages
    kFixed32 = 5,
  };
  uint32_t num = 0;
  Wire wire = kVarint;
  uint64_t varint = 0;   // kVarint / kFixed32 / kFixed64 payload
  std::string bytes;     // kBytes payload
};

// An encoded-message view: fields in wire order, repeated numbers kept.
// Build-side helpers append; read-side helpers return the FIRST match
// (proto2 semantics for scalars are "last wins" on merge, but metas here
// never repeat scalar fields — all() exposes every occurrence for the
// cases that do repeat).
class PbMessage {
 public:
  // Build side.
  void add_varint(uint32_t field, uint64_t v);
  void add_sint(uint32_t field, int64_t v);       // zigzag
  void add_bool(uint32_t field, bool v) { add_varint(field, v ? 1 : 0); }
  void add_fixed32(uint32_t field, uint32_t v);
  void add_fixed64(uint32_t field, uint64_t v);
  void add_double(uint32_t field, double v);
  void add_float(uint32_t field, float v);
  void add_bytes(uint32_t field, std::string_view v);
  void add_message(uint32_t field, const PbMessage& m);

  // Read side (first occurrence; `def` when absent).
  bool has(uint32_t field) const;
  uint64_t get_varint(uint32_t field, uint64_t def = 0) const;
  int64_t get_sint(uint32_t field, int64_t def = 0) const;
  bool get_bool(uint32_t field, bool def = false) const {
    return get_varint(field, def ? 1 : 0) != 0;
  }
  uint64_t get_fixed(uint32_t field, uint64_t def = 0) const;
  double get_double(uint32_t field, double def = 0) const;
  std::string_view get_bytes(uint32_t field,
                             std::string_view def = {}) const;
  // Parses the first occurrence of `field` as a nested message.
  bool get_message(uint32_t field, PbMessage* out) const;
  std::vector<const PbField*> all(uint32_t field) const;

  const std::vector<PbField>& fields() const { return fields_; }

  void serialize(std::string* out) const;
  std::string serialize() const;
  // Strict parse of the whole buffer; false on malformed input.  Depth
  // does not apply here (nested messages stay as bytes until
  // get_message), so arbitrarily deep inputs cost nothing until walked.
  bool parse(std::string_view in);

 private:
  std::vector<PbField> fields_;
};

// ---- JSON transcoding (the json2pb seam) ---------------------------------

// A lightweight runtime descriptor: names + kinds per field number, for
// schema'd transcoding.  Nested message fields point at another schema.
struct PbSchema {
  enum Kind : uint8_t {
    kInt64,     // varint, signed two's-complement (int32/int64)
    kUint64,    // varint, unsigned
    kSint64,    // varint, zigzag
    kBool,
    kString,
    kBytesHex,  // bytes rendered as lowercase hex in JSON
    kDouble,    // fixed64
    kFloat,     // fixed32
    kFixed32,
    kFixed64,
    kMessage,
  };
  struct Field {
    uint32_t num;
    const char* name;
    Kind kind;
    const PbSchema* nested = nullptr;  // kMessage only
    bool repeated = false;
  };
  std::vector<Field> fields;

  const Field* by_num(uint32_t num) const;
  const Field* by_name(std::string_view name) const;

  // Backing store for names owned by RUNTIME-parsed schemas
  // (parse_proto_file); compile-time schemas use literals and leave it
  // empty.  Field::name points into it, so such schemas must not be
  // copied after construction (the registry map's node stability is the
  // contract).
  std::deque<std::string> name_pool;
};

// Parses a .proto definition at RUNTIME (tools/rpc_press_impl parity —
// the reference compiles .proto files on the fly via libprotobuf's
// importer; ours parses the subset the wire codec speaks): proto2/proto3
// `message` blocks with scalar/string/bytes fields, nested or sibling
// message types, `repeated`, `=N` tags; `syntax`/`package`/`option`/
// comments skipped.  Returns schemas keyed by message name — map node
// addresses are stable, which is what nested Field::nested pointers rely
// on.  False + *err on anything outside the subset.
bool parse_proto_file(const std::string& text,
                      std::map<std::string, PbSchema>* out,
                      std::string* err);

// Schema'd transcodes.  Unknown fields (not in the schema) are emitted
// under their number as a string key with a best-effort value, so nothing
// is silently dropped.
Json pb_to_json(const PbMessage& msg, const PbSchema& schema);
// Builds a message from JSON per the schema; false if a value's JSON type
// cannot encode as its field's kind.  Keys not in the schema are ignored.
bool json_to_pb(const Json& j, const PbSchema& schema, PbMessage* out);

// Schema-less transcode: field numbers become keys; length-delimited
// payloads that parse cleanly as messages recurse, printable ones become
// strings, the rest hex.  The /protobufs-style debugging view.
Json pb_to_json_schemaless(const PbMessage& msg, int max_depth = 8);

}  // namespace trpc
