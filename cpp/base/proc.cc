#include "base/proc.h"

#include <dirent.h>
#include <stdio.h>
#include <stdlib.h>
#include <string.h>

namespace trpc {

long proc_status_kb(const char* key) {
  FILE* f = fopen("/proc/self/status", "r");
  if (f == nullptr) {
    return -1;
  }
  char line[256];
  long val = -1;
  const size_t klen = strlen(key);
  while (fgets(line, sizeof(line), f) != nullptr) {
    if (strncmp(line, key, klen) == 0) {
      val = atol(line + klen);
      break;
    }
  }
  fclose(f);
  return val;
}

long proc_fd_count() {
  DIR* d = opendir("/proc/self/fd");
  if (d == nullptr) {
    return -1;
  }
  long n = 0;
  while (readdir(d) != nullptr) {
    ++n;
  }
  closedir(d);
  return n - 2;  // . and ..
}

}  // namespace trpc
