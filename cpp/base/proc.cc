#include "base/proc.h"

#include <dirent.h>
#include <stdio.h>
#include <stdlib.h>
#include <string.h>

#include <cmath>

namespace trpc {

long proc_status_kb(const char* key) {
  FILE* f = fopen("/proc/self/status", "r");
  if (f == nullptr) {
    return -1;
  }
  char line[256];
  long val = -1;
  const size_t klen = strlen(key);
  while (fgets(line, sizeof(line), f) != nullptr) {
    if (strncmp(line, key, klen) == 0) {
      val = atol(line + klen);
      break;
    }
  }
  fclose(f);
  return val;
}

long proc_fd_count() {
  DIR* d = opendir("/proc/self/fd");
  if (d == nullptr) {
    return -1;
  }
  long n = 0;
  while (readdir(d) != nullptr) {
    ++n;
  }
  closedir(d);
  return n - 2;  // . and ..
}

bool parse_plain_number(const char* s, double* out) {
  if (s == nullptr || *s == '\0') {
    return false;
  }
  // RFC 8259 number grammar head: '-'? digit...  (rejects nan/inf/hex/'+'
  // which strtod would happily accept).
  const char* p = s;
  if (*p == '-') {
    ++p;
  }
  if (*p < '0' || *p > '9') {
    return false;
  }
  char* end = nullptr;
  const double v = strtod(s, &end);
  if (end == s || *end != '\0' || !std::isfinite(v)) {
    return false;
  }
  *out = v;
  return true;
}

}  // namespace trpc
