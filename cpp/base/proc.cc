#include "base/proc.h"

#include <dirent.h>
#include <errno.h>
#include <stdio.h>
#include <stdlib.h>
#include <string.h>
#include <sys/syscall.h>
#include <unistd.h>

#include <cmath>

namespace trpc {

long proc_status_kb(const char* key) {
  FILE* f = fopen("/proc/self/status", "r");
  if (f == nullptr) {
    return -1;
  }
  char line[256];
  long val = -1;
  const size_t klen = strlen(key);
  while (fgets(line, sizeof(line), f) != nullptr) {
    if (strncmp(line, key, klen) == 0) {
      val = atol(line + klen);
      break;
    }
  }
  fclose(f);
  return val;
}

long proc_fd_count() {
  DIR* d = opendir("/proc/self/fd");
  if (d == nullptr) {
    return -1;
  }
  long n = 0;
  while (readdir(d) != nullptr) {
    ++n;
  }
  closedir(d);
  return n - 2;  // . and ..
}

bool parse_plain_number(const char* s, double* out) {
  if (s == nullptr || *s == '\0') {
    return false;
  }
  // RFC 8259 number grammar head: '-'? digit...  (rejects nan/inf/hex/'+'
  // which strtod would happily accept).
  const char* p = s;
  if (*p == '-') {
    ++p;
  }
  if (*p < '0' || *p > '9') {
    return false;
  }
  char* end = nullptr;
  const double v = strtod(s, &end);
  if (end == s || *end != '\0' || !std::isfinite(v)) {
    return false;
  }
  *out = v;
  return true;
}

#ifndef __NR_io_uring_setup
// x86_64 and aarch64 share the unified syscall number; an older libc's
// headers may predate it even where the kernel could support it.
#define __NR_io_uring_setup 425
#endif

int kernel_supports(const char* feature) {
  if (feature == nullptr) {
    return -1;
  }
  if (strcmp(feature, "io_uring") == 0) {
    // Probed once: deliberately-invalid arguments, so a supporting
    // kernel answers EINVAL/EFAULT while a pre-5.1 kernel (this dev
    // box: 4.4.0) answers ENOSYS.  EPERM (a seccomp profile blocking
    // the syscall — Docker's default since 2023) counts as UNSUPPORTED:
    // the question this gate answers is "can this process actually use
    // io_uring here", not "does the kernel have the code".  Never
    // creates a ring.
    static const int supported = [] {
      errno = 0;
      const long rc = syscall(__NR_io_uring_setup, 0, nullptr);
      if (rc >= 0) {  // unreachable with these args, but be safe
        return 1;
      }
      return (errno == ENOSYS || errno == EPERM) ? 0 : 1;
    }();
    return supported;
  }
  return -1;
}

}  // namespace trpc
