// /proc/self introspection shared by /memory, /threads and the default
// process variables (bvar/default_variables.cpp parity).
#pragma once

namespace trpc {

// Value of a "Key:  <n> kB"-style line in /proc/self/status; -1 if absent.
long proc_status_kb(const char* key);

// True when `s` is one plain finite decimal number (the shared "render a
// metric value as a JSON/Prometheus number or fall back to a string"
// classification); fills *out.
bool parse_plain_number(const char* s, double* out);
// Open fd count for this process (-1 on failure).
long proc_fd_count();

// Runtime kernel-capability probe: 1 = the running kernel supports the
// feature, 0 = it does not, -1 = unknown feature name.  Known features:
//   "io_uring"  io_uring_setup reachable (kernel >= 5.1; ENOSYS on this
//               repo's 4.4.0 dev box — the gate that killed the ROADMAP
//               item 2 io_uring backend as a buildable tentpole here).
// Surfaced in /vars as kernel_io_uring_supported and through the
// trpc_kernel_supports C ABI so future issues can check before picking
// kernel-gated work.
int kernel_supports(const char* feature);

}  // namespace trpc
