// /proc/self introspection shared by /memory, /threads and the default
// process variables (bvar/default_variables.cpp parity).
#pragma once

namespace trpc {

// Value of a "Key:  <n> kB"-style line in /proc/self/status; -1 if absent.
long proc_status_kb(const char* key);
// Open fd count for this process (-1 on failure).
long proc_fd_count();

}  // namespace trpc
