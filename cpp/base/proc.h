// /proc/self introspection shared by /memory, /threads and the default
// process variables (bvar/default_variables.cpp parity).
#pragma once

namespace trpc {

// Value of a "Key:  <n> kB"-style line in /proc/self/status; -1 if absent.
long proc_status_kb(const char* key);

// True when `s` is one plain finite decimal number (the shared "render a
// metric value as a JSON/Prometheus number or fall back to a string"
// classification); fills *out.
bool parse_plain_number(const char* s, double* out);
// Open fd count for this process (-1 on failure).
long proc_fd_count();

}  // namespace trpc
