// Per-thread xorshift RNG (parity: butil/fast_rand.h,
// /root/reference/src/butil/fast_rand.cpp — used for steal victims and LB).
#pragma once

#include <cstdint>
#include <ctime>

namespace trpc {

inline uint64_t fast_rand() {
  static thread_local uint64_t s0 = 0, s1 = 0;
  if (s0 == 0 && s1 == 0) {
    timespec ts;
    clock_gettime(CLOCK_MONOTONIC, &ts);
    s0 = static_cast<uint64_t>(ts.tv_nsec) * 2654435761u + 1;
    s1 = reinterpret_cast<uintptr_t>(&s0) ^ 0x9e3779b97f4a7c15ull;
  }
  // xorshift128+
  uint64_t x = s0;
  const uint64_t y = s1;
  s0 = y;
  x ^= x << 23;
  s1 = x ^ y ^ (x >> 17) ^ (y >> 26);
  return s1 + y;
}

inline uint64_t fast_rand_less_than(uint64_t bound) {
  return bound ? fast_rand() % bound : 0;
}

}  // namespace trpc
