#include "base/recordio.h"

#include <cstring>
#include <vector>

namespace trpc {

namespace {
constexpr char kMagic[4] = {'T', 'R', 'E', 'C'};
constexpr size_t kMaxRecord = 256 * 1024 * 1024;
}  // namespace

RecordWriter::RecordWriter(const std::string& path)
    : file_(fopen(path.c_str(), "ab")) {}

RecordWriter::~RecordWriter() {
  if (file_ != nullptr) {
    fclose(file_);
  }
}

bool RecordWriter::write(const IOBuf& record) {
  if (file_ == nullptr || record.size() > kMaxRecord) {
    return false;  // reject what the reader would reject (or worse, desync)
  }
  const uint32_t len = static_cast<uint32_t>(record.size());
  if (fwrite(kMagic, 1, 4, file_) != 4 ||
      fwrite(&len, 1, 4, file_) != 4) {
    return false;
  }
  const std::string flat = record.to_string();
  return fwrite(flat.data(), 1, flat.size(), file_) == flat.size();
}

void RecordWriter::flush() {
  if (file_ != nullptr) {
    fflush(file_);
  }
}

RecordReader::RecordReader(const std::string& path)
    : file_(fopen(path.c_str(), "rb")) {}

RecordReader::~RecordReader() {
  if (file_ != nullptr) {
    fclose(file_);
  }
}

bool RecordReader::read(IOBuf* record) {
  if (file_ == nullptr) {
    return false;
  }
  char magic[4];
  uint32_t len = 0;
  if (fread(magic, 1, 4, file_) != 4 || memcmp(magic, kMagic, 4) != 0 ||
      fread(&len, 1, 4, file_) != 4 || len > kMaxRecord) {
    return false;
  }
  std::vector<char> buf(len);
  if (fread(buf.data(), 1, len, file_) != len) {
    return false;
  }
  record->append(buf.data(), len);
  return true;
}

}  // namespace trpc
