// recordio — length-prefixed record files.
//
// Parity: butil recordio (/root/reference/src/butil/recordio.h), the format
// under rpc_dump / rpc_replay.  Wire: "TREC" magic | u32 payload len |
// payload, repeated.
#pragma once

#include <cstdio>
#include <string>

#include "base/iobuf.h"

namespace trpc {

class RecordWriter {
 public:
  // Appends to path; returns nullptr-equivalent invalid writer on failure.
  explicit RecordWriter(const std::string& path);
  ~RecordWriter();
  bool valid() const { return file_ != nullptr; }
  bool write(const IOBuf& record);
  void flush();

 private:
  FILE* file_ = nullptr;
};

class RecordReader {
 public:
  explicit RecordReader(const std::string& path);
  ~RecordReader();
  bool valid() const { return file_ != nullptr; }
  // False at EOF or on corruption.
  bool read(IOBuf* record);

 private:
  FILE* file_ = nullptr;
};

}  // namespace trpc
