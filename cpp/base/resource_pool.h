// Index-addressed object pool — foundation of versioned handles.
//
// Parity: butil::ResourcePool (/root/reference/src/butil/resource_pool.h):
// 32-bit ids addressing slab-allocated objects, recycled without destruction
// so id-version fields in the object survive reuse (the ABA armor behind
// fiber ids and SocketId).  Re-designed: lazily allocated fixed segments +
// thread-local free lists with a mutexed global overflow, instead of the
// reference's block-group machinery.
#pragma once

#include <atomic>
#include <cstdint>
#include <mutex>
#include <string>
#include <vector>

namespace trpc {

template <typename T>
class ResourcePool {
 public:
  static constexpr uint32_t kItemsPerSegBits = 8;
  static constexpr uint32_t kItemsPerSeg = 1u << kItemsPerSegBits;
  static constexpr uint32_t kMaxSegs = 1u << 16;  // ~16.7M items

  static ResourcePool* instance() {
    // Deliberately leaked: pooled objects (sockets, fibers) are touched by
    // detached threads during and after static destruction.
    static ResourcePool* pool = new ResourcePool();
    return pool;
  }

  ResourcePool(const ResourcePool&) = delete;
  ResourcePool& operator=(const ResourcePool&) = delete;

  // Returns the index of a (possibly recycled) default-constructed object.
  // Recycled objects are NOT re-constructed: callers reset state and bump
  // their embedded version.
  uint32_t acquire(T** out) {
    TlsCache* tls = tls_cache();
    if (tls != nullptr) {
      if (tls->free.empty()) {
        refill(tls);
      }
      if (!tls->free.empty()) {
        const uint32_t idx = tls->free.back();
        tls->free.pop_back();
        *out = at(idx);
        return idx;
      }
    } else {
      // TLS cache already destructed (static-destruction path): go global.
      std::lock_guard<std::mutex> g(global_mu_);
      if (!global_free_.empty()) {
        const uint32_t idx = global_free_.back();
        global_free_.pop_back();
        *out = at(idx);
        return idx;
      }
    }
    const uint32_t idx = hwm_.fetch_add(1, std::memory_order_relaxed);
    const uint32_t seg = idx >> kItemsPerSegBits;
    if (seg >= kMaxSegs) {  // pool exhausted: fail loudly, not OOB
      *out = nullptr;
      return UINT32_MAX;
    }
    T* items = segs_[seg].load(std::memory_order_acquire);
    if (items == nullptr) {
      T* fresh = new T[kItemsPerSeg];
      if (!segs_[seg].compare_exchange_strong(items, fresh,
                                              std::memory_order_acq_rel)) {
        delete[] fresh;  // another thread won
      } else {
        items = fresh;
      }
      if (items == nullptr) {
        items = segs_[seg].load(std::memory_order_acquire);
      }
    }
    *out = &items[idx & (kItemsPerSeg - 1)];
    return idx;
  }

  void release(uint32_t idx) {
    TlsCache* tls = tls_cache();
    if (tls == nullptr) {  // static-destruction path
      std::lock_guard<std::mutex> g(global_mu_);
      global_free_.push_back(idx);
      return;
    }
    tls->free.push_back(idx);
    if (tls->free.size() >= kTlsHighWater) {
      std::lock_guard<std::mutex> g(global_mu_);
      global_free_.insert(global_free_.end(),
                          tls->free.begin() + kTlsLowWater, tls->free.end());
      tls->free.resize(kTlsLowWater);
    }
  }

  // Allocation high-water mark: every ever-created slot is < hwm().
  // Enumeration (diagnostics: /fibers) walks [0, hwm) and filters by the
  // object's own liveness (version parity).
  uint32_t hwm() const { return hwm_.load(std::memory_order_acquire); }

  T* at(uint32_t idx) {
    const uint32_t seg = idx >> kItemsPerSegBits;
    if (seg >= kMaxSegs) {
      return nullptr;
    }
    T* items = segs_[seg].load(std::memory_order_acquire);
    return items ? &items[idx & (kItemsPerSeg - 1)] : nullptr;
  }

 private:
  ResourcePool() = default;  // singleton per T: TLS free lists assume it

  static constexpr size_t kTlsHighWater = 128;
  static constexpr size_t kTlsLowWater = 32;

  struct TlsCache {
    ResourcePool* owner = nullptr;
    std::vector<uint32_t> free;
  };

  // TLS destruction order vs static destruction is undefined, and pooled
  // objects (sockets in static Servers) ARE released during static
  // destruction.  The cache is heap-owned behind trivially-destructible
  // thread_locals; after the guard runs, callers fall back to the global
  // list instead of touching a dead vector.
  struct TlsGuard {
    TlsCache** slot = nullptr;
    bool* dead = nullptr;
    ~TlsGuard() {
      if (slot != nullptr && *slot != nullptr) {
        TlsCache* c = *slot;
        if (c->owner != nullptr && !c->free.empty()) {
          std::lock_guard<std::mutex> g(c->owner->global_mu_);
          c->owner->global_free_.insert(c->owner->global_free_.end(),
                                        c->free.begin(), c->free.end());
        }
        delete c;
        *slot = nullptr;
      }
      if (dead != nullptr) {
        *dead = true;
      }
    }
  };

  TlsCache* tls_cache() {
    static thread_local TlsCache* cache = nullptr;   // trivial dtor
    static thread_local bool cache_dead = false;     // trivial dtor
    static thread_local TlsGuard guard;
    if (cache_dead) {
      return nullptr;
    }
    if (cache == nullptr) {
      cache = new TlsCache();
      cache->owner = this;
      guard.slot = &cache;
      guard.dead = &cache_dead;
    }
    return cache;
  }

  void refill(TlsCache* tls) {
    std::lock_guard<std::mutex> g(global_mu_);
    const size_t take = std::min<size_t>(kTlsLowWater, global_free_.size());
    tls->free.insert(tls->free.end(), global_free_.end() - take,
                     global_free_.end());
    global_free_.resize(global_free_.size() - take);
  }

  std::atomic<T*> segs_[kMaxSegs] = {};
  std::atomic<uint32_t> hwm_{0};
  std::mutex global_mu_;
  std::vector<uint32_t> global_free_;
};

// Shared skeleton of the diagnostic table dumps (/fibers /sockets /ids):
// walk [0, hwm), let `row` decide liveness and format, cap at max_rows,
// footer with the full live count.  row(slot, item, line_or_null)
// returns true for live items and fills *line only when non-null (the
// cap already hit: keep counting, stop formatting).
template <typename T, typename RowFn>
std::string dump_pool_table(const char* header, size_t max_rows,
                            RowFn&& row) {
  std::string out = header;
  ResourcePool<T>* pool = ResourcePool<T>::instance();
  const uint32_t hwm = pool->hwm();
  size_t live = 0, shown = 0;
  for (uint32_t slot = 0; slot < hwm; ++slot) {
    T* item = pool->at(slot);
    if (item == nullptr) {
      continue;
    }
    std::string line;
    if (!row(slot, item, shown < max_rows ? &line : nullptr)) {
      continue;
    }
    ++live;
    if (shown < max_rows) {
      out += line;
      ++shown;
    }
  }
  out += std::to_string(live) + " live";
  if (live > shown) {
    out += " (rows truncated at " + std::to_string(shown) + ")";
  }
  out += "\n";
  return out;
}

}  // namespace trpc
