// Index-addressed object pool — foundation of versioned handles.
//
// Parity: butil::ResourcePool (/root/reference/src/butil/resource_pool.h):
// 32-bit ids addressing slab-allocated objects, recycled without destruction
// so id-version fields in the object survive reuse (the ABA armor behind
// fiber ids and SocketId).  Re-designed: lazily allocated fixed segments +
// thread-local free lists with a mutexed global overflow, instead of the
// reference's block-group machinery.
#pragma once

#include <atomic>
#include <cstdint>
#include <mutex>
#include <vector>

namespace trpc {

template <typename T>
class ResourcePool {
 public:
  static constexpr uint32_t kItemsPerSegBits = 8;
  static constexpr uint32_t kItemsPerSeg = 1u << kItemsPerSegBits;
  static constexpr uint32_t kMaxSegs = 1u << 16;  // ~16.7M items

  static ResourcePool* instance() {
    static ResourcePool pool;
    return &pool;
  }

  ResourcePool(const ResourcePool&) = delete;
  ResourcePool& operator=(const ResourcePool&) = delete;

  // Returns the index of a (possibly recycled) default-constructed object.
  // Recycled objects are NOT re-constructed: callers reset state and bump
  // their embedded version.
  uint32_t acquire(T** out) {
    TlsCache& tls = tls_cache();
    if (tls.free.empty()) {
      refill(&tls);
    }
    if (!tls.free.empty()) {
      const uint32_t idx = tls.free.back();
      tls.free.pop_back();
      *out = at(idx);
      return idx;
    }
    const uint32_t idx = hwm_.fetch_add(1, std::memory_order_relaxed);
    const uint32_t seg = idx >> kItemsPerSegBits;
    if (seg >= kMaxSegs) {  // pool exhausted: fail loudly, not OOB
      *out = nullptr;
      return UINT32_MAX;
    }
    T* items = segs_[seg].load(std::memory_order_acquire);
    if (items == nullptr) {
      T* fresh = new T[kItemsPerSeg];
      if (!segs_[seg].compare_exchange_strong(items, fresh,
                                              std::memory_order_acq_rel)) {
        delete[] fresh;  // another thread won
      } else {
        items = fresh;
      }
      if (items == nullptr) {
        items = segs_[seg].load(std::memory_order_acquire);
      }
    }
    *out = &items[idx & (kItemsPerSeg - 1)];
    return idx;
  }

  void release(uint32_t idx) {
    TlsCache& tls = tls_cache();
    tls.free.push_back(idx);
    if (tls.free.size() >= kTlsHighWater) {
      std::lock_guard<std::mutex> g(global_mu_);
      global_free_.insert(global_free_.end(),
                          tls.free.begin() + kTlsLowWater, tls.free.end());
      tls.free.resize(kTlsLowWater);
    }
  }

  T* at(uint32_t idx) {
    const uint32_t seg = idx >> kItemsPerSegBits;
    if (seg >= kMaxSegs) {
      return nullptr;
    }
    T* items = segs_[seg].load(std::memory_order_acquire);
    return items ? &items[idx & (kItemsPerSeg - 1)] : nullptr;
  }

 private:
  ResourcePool() = default;  // singleton per T: TLS free lists assume it

  static constexpr size_t kTlsHighWater = 128;
  static constexpr size_t kTlsLowWater = 32;

  struct TlsCache {
    ResourcePool* owner = nullptr;
    std::vector<uint32_t> free;
    ~TlsCache() {
      if (owner != nullptr && !free.empty()) {
        std::lock_guard<std::mutex> g(owner->global_mu_);
        owner->global_free_.insert(owner->global_free_.end(), free.begin(),
                                   free.end());
      }
    }
  };

  TlsCache& tls_cache() {
    static thread_local TlsCache tls;
    tls.owner = this;
    return tls;
  }

  void refill(TlsCache* tls) {
    std::lock_guard<std::mutex> g(global_mu_);
    const size_t take = std::min<size_t>(kTlsLowWater, global_free_.size());
    tls->free.insert(tls->free.end(), global_free_.end() - take,
                     global_free_.end());
    global_free_.resize(global_free_.size() - take);
  }

  std::atomic<T*> segs_[kMaxSegs] = {};
  std::atomic<uint32_t> hwm_{0};
  std::mutex global_mu_;
  std::vector<uint32_t> global_free_;
};

}  // namespace trpc
