#include "base/sha1.h"

#include <cstring>

namespace trpc {

namespace {

inline uint32_t rol(uint32_t v, int n) {
  return (v << n) | (v >> (32 - n));
}

void process_block(const uint8_t* p, uint32_t h[5]) {
  uint32_t w[80];
  for (int i = 0; i < 16; ++i) {
    w[i] = (static_cast<uint32_t>(p[i * 4]) << 24) |
           (static_cast<uint32_t>(p[i * 4 + 1]) << 16) |
           (static_cast<uint32_t>(p[i * 4 + 2]) << 8) | p[i * 4 + 3];
  }
  for (int i = 16; i < 80; ++i) {
    w[i] = rol(w[i - 3] ^ w[i - 8] ^ w[i - 14] ^ w[i - 16], 1);
  }
  uint32_t a = h[0], b = h[1], c = h[2], d = h[3], e = h[4];
  for (int i = 0; i < 80; ++i) {
    uint32_t f, k;
    if (i < 20) {
      f = (b & c) | (~b & d);
      k = 0x5a827999;
    } else if (i < 40) {
      f = b ^ c ^ d;
      k = 0x6ed9eba1;
    } else if (i < 60) {
      f = (b & c) | (b & d) | (c & d);
      k = 0x8f1bbcdc;
    } else {
      f = b ^ c ^ d;
      k = 0xca62c1d6;
    }
    const uint32_t t = rol(a, 5) + f + e + k + w[i];
    e = d;
    d = c;
    c = rol(b, 30);
    b = a;
    a = t;
  }
  h[0] += a;
  h[1] += b;
  h[2] += c;
  h[3] += d;
  h[4] += e;
}

}  // namespace

void sha1(const void* data, size_t len, uint8_t digest[20]) {
  uint32_t h[5] = {0x67452301, 0xefcdab89, 0x98badcfe, 0x10325476,
                   0xc3d2e1f0};
  const uint8_t* p = static_cast<const uint8_t*>(data);
  size_t remaining = len;
  while (remaining >= 64) {
    process_block(p, h);
    p += 64;
    remaining -= 64;
  }
  // Final block(s): message || 0x80 || zeros || 64-bit bit length.
  uint8_t tail[128] = {};
  std::memcpy(tail, p, remaining);
  tail[remaining] = 0x80;
  const size_t tail_len = remaining + 1 + 8 <= 64 ? 64 : 128;
  const uint64_t bits = static_cast<uint64_t>(len) * 8;
  for (int i = 0; i < 8; ++i) {
    tail[tail_len - 1 - i] = static_cast<uint8_t>(bits >> (8 * i));
  }
  process_block(tail, h);
  if (tail_len == 128) {
    process_block(tail + 64, h);
  }
  for (int i = 0; i < 5; ++i) {
    digest[i * 4] = static_cast<uint8_t>(h[i] >> 24);
    digest[i * 4 + 1] = static_cast<uint8_t>(h[i] >> 16);
    digest[i * 4 + 2] = static_cast<uint8_t>(h[i] >> 8);
    digest[i * 4 + 3] = static_cast<uint8_t>(h[i]);
  }
}

}  // namespace trpc
