// SHA-1 (RFC 3174) — needed by the mysql_native_password scramble
// (net/mysql.h).  Parity slot: the reference links OpenSSL for this
// (policy/mysql/mysql_authenticator.cpp); this runtime keeps the base
// layer dependency-free and hand-rolls the 160-bit digest.
//
// Not for new cryptographic designs — present strictly for protocol
// compatibility (mysql auth predates modern hashes).
#pragma once

#include <cstddef>
#include <cstdint>
#include <string>

namespace trpc {

// digest must point at 20 writable bytes.
void sha1(const void* data, size_t len, uint8_t digest[20]);

inline std::string sha1(const std::string& in) {
  std::string out(20, '\0');
  sha1(in.data(), in.size(), reinterpret_cast<uint8_t*>(out.data()));
  return out;
}

}  // namespace trpc
