#include "base/sha256.h"

#include <cstring>

namespace trpc {

namespace {

// FIPS 180-4 constants: first 32 bits of the fractional parts of the
// cube roots of the first 64 primes.
const uint32_t K[64] = {
    0x428a2f98, 0x71374491, 0xb5c0fbcf, 0xe9b5dba5, 0x3956c25b,
    0x59f111f1, 0x923f82a4, 0xab1c5ed5, 0xd807aa98, 0x12835b01,
    0x243185be, 0x550c7dc3, 0x72be5d74, 0x80deb1fe, 0x9bdc06a7,
    0xc19bf174, 0xe49b69c1, 0xefbe4786, 0x0fc19dc6, 0x240ca1cc,
    0x2de92c6f, 0x4a7484aa, 0x5cb0a9dc, 0x76f988da, 0x983e5152,
    0xa831c66d, 0xb00327c8, 0xbf597fc7, 0xc6e00bf3, 0xd5a79147,
    0x06ca6351, 0x14292967, 0x27b70a85, 0x2e1b2138, 0x4d2c6dfc,
    0x53380d13, 0x650a7354, 0x766a0abb, 0x81c2c92e, 0x92722c85,
    0xa2bfe8a1, 0xa81a664b, 0xc24b8b70, 0xc76c51a3, 0xd192e819,
    0xd6990624, 0xf40e3585, 0x106aa070, 0x19a4c116, 0x1e376c08,
    0x2748774c, 0x34b0bcb5, 0x391c0cb3, 0x4ed8aa4a, 0x5b9cca4f,
    0x682e6ff3, 0x748f82ee, 0x78a5636f, 0x84c87814, 0x8cc70208,
    0x90befffa, 0xa4506ceb, 0xbef9a3f7, 0xc67178f2};

uint32_t rotr(uint32_t x, int n) { return (x >> n) | (x << (32 - n)); }

struct Sha256Ctx {
  uint32_t h[8] = {0x6a09e667, 0xbb67ae85, 0x3c6ef372, 0xa54ff53a,
                   0x510e527f, 0x9b05688c, 0x1f83d9ab, 0x5be0cd19};
  uint8_t block[64];
  size_t block_len = 0;
  uint64_t total = 0;

  void process(const uint8_t* p) {
    uint32_t w[64];
    for (int i = 0; i < 16; ++i) {
      w[i] = (static_cast<uint32_t>(p[4 * i]) << 24) |
             (static_cast<uint32_t>(p[4 * i + 1]) << 16) |
             (static_cast<uint32_t>(p[4 * i + 2]) << 8) | p[4 * i + 3];
    }
    for (int i = 16; i < 64; ++i) {
      const uint32_t s0 =
          rotr(w[i - 15], 7) ^ rotr(w[i - 15], 18) ^ (w[i - 15] >> 3);
      const uint32_t s1 =
          rotr(w[i - 2], 17) ^ rotr(w[i - 2], 19) ^ (w[i - 2] >> 10);
      w[i] = w[i - 16] + s0 + w[i - 7] + s1;
    }
    uint32_t a = h[0], b = h[1], c = h[2], d = h[3], e = h[4], f = h[5],
             g = h[6], hh = h[7];
    for (int i = 0; i < 64; ++i) {
      const uint32_t S1 = rotr(e, 6) ^ rotr(e, 11) ^ rotr(e, 25);
      const uint32_t ch = (e & f) ^ (~e & g);
      const uint32_t t1 = hh + S1 + ch + K[i] + w[i];
      const uint32_t S0 = rotr(a, 2) ^ rotr(a, 13) ^ rotr(a, 22);
      const uint32_t maj = (a & b) ^ (a & c) ^ (b & c);
      const uint32_t t2 = S0 + maj;
      hh = g;
      g = f;
      f = e;
      e = d + t1;
      d = c;
      c = b;
      b = a;
      a = t1 + t2;
    }
    h[0] += a;
    h[1] += b;
    h[2] += c;
    h[3] += d;
    h[4] += e;
    h[5] += f;
    h[6] += g;
    h[7] += hh;
  }

  void update(const uint8_t* p, size_t n) {
    total += n;
    while (n > 0) {
      if (block_len == 0 && n >= 64) {
        process(p);
        p += 64;
        n -= 64;
        continue;
      }
      const size_t take = n < 64 - block_len ? n : 64 - block_len;
      memcpy(block + block_len, p, take);
      block_len += take;
      p += take;
      n -= take;
      if (block_len == 64) {
        process(block);
        block_len = 0;
      }
    }
  }

  void final(uint8_t out[32]) {
    const uint64_t bits = total * 8;
    const uint8_t one = 0x80;
    update(&one, 1);
    const uint8_t zero = 0;
    while (block_len != 56) {
      update(&zero, 1);
    }
    uint8_t len_be[8];
    for (int i = 0; i < 8; ++i) {
      len_be[i] = static_cast<uint8_t>(bits >> (56 - 8 * i));
    }
    update(len_be, 8);
    for (int i = 0; i < 8; ++i) {
      out[4 * i] = static_cast<uint8_t>(h[i] >> 24);
      out[4 * i + 1] = static_cast<uint8_t>(h[i] >> 16);
      out[4 * i + 2] = static_cast<uint8_t>(h[i] >> 8);
      out[4 * i + 3] = static_cast<uint8_t>(h[i]);
    }
  }
};

}  // namespace

void sha256(const void* data, size_t n, uint8_t out[kSha256Size]) {
  Sha256Ctx ctx;
  ctx.update(static_cast<const uint8_t*>(data), n);
  ctx.final(out);
}

void hmac_sha256(const void* key, size_t key_len, const void* data,
                 size_t n, uint8_t out[kSha256Size]) {
  uint8_t k[64] = {0};
  if (key_len > 64) {
    sha256(key, key_len, k);
  } else {
    memcpy(k, key, key_len);
  }
  uint8_t ipad[64], opad[64];
  for (int i = 0; i < 64; ++i) {
    ipad[i] = k[i] ^ 0x36;
    opad[i] = k[i] ^ 0x5c;
  }
  uint8_t inner[kSha256Size];
  Sha256Ctx ictx;
  ictx.update(ipad, 64);
  ictx.update(static_cast<const uint8_t*>(data), n);
  ictx.final(inner);
  Sha256Ctx octx;
  octx.update(opad, 64);
  octx.update(inner, kSha256Size);
  octx.final(out);
}

}  // namespace trpc
