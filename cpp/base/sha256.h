// SHA-256 (FIPS 180-4) + HMAC-SHA256 (RFC 2104), self-contained — the
// image ships no OpenSSL headers, and the RTMP digest handshake plus
// future signature needs want a hash that doesn't dlopen anything.
// Verified against NIST/RFC 4231 vectors in tests.
#pragma once

#include <cstddef>
#include <cstdint>

namespace trpc {

constexpr size_t kSha256Size = 32;

void sha256(const void* data, size_t n, uint8_t out[kSha256Size]);

void hmac_sha256(const void* key, size_t key_len, const void* data,
                 size_t n, uint8_t out[kSha256Size]);

}  // namespace trpc
