#include "base/snappy.h"

#include <cstring>

namespace trpc {

namespace {

constexpr size_t kFragment = 65536;  // matcher window; offsets fit 16 bits
constexpr int kHashBits = 14;

void put_varint32(std::string* out, uint32_t v) {
  while (v >= 0x80) {
    out->push_back(static_cast<char>(v | 0x80));
    v >>= 7;
  }
  out->push_back(static_cast<char>(v));
}

bool get_varint32(const char* in, size_t n, size_t* pos, uint32_t* out) {
  uint32_t v = 0;
  for (int shift = 0; shift < 35; shift += 7) {
    if (*pos >= n) {
      return false;
    }
    const uint8_t b = static_cast<uint8_t>(in[(*pos)++]);
    v |= static_cast<uint32_t>(b & 0x7f) << shift;
    if ((b & 0x80) == 0) {
      *out = v;
      return true;
    }
  }
  return false;  // >5 bytes: not a varint32
}

uint32_t load32(const char* p) {
  uint32_t v;
  memcpy(&v, p, 4);
  return v;
}

uint32_t hash32(uint32_t v) {
  return (v * 0x1e35a7bdu) >> (32 - kHashBits);
}

void emit_literal(std::string* out, const char* p, size_t len) {
  if (len == 0) {
    return;
  }
  const size_t l = len - 1;
  if (l < 60) {
    out->push_back(static_cast<char>(l << 2));
  } else {
    int extra = l < (1u << 8) ? 1 : l < (1u << 16) ? 2
                : l < (1u << 24) ? 3 : 4;
    out->push_back(static_cast<char>((59 + extra) << 2));
    for (int i = 0; i < extra; ++i) {
      out->push_back(static_cast<char>(l >> (8 * i)));
    }
  }
  out->append(p, len);
}

// Copy with 16-bit offset (tag 2); len must be in [1, 64].
void emit_copy_chunk(std::string* out, size_t offset, size_t len) {
  out->push_back(static_cast<char>(((len - 1) << 2) | 2));
  out->push_back(static_cast<char>(offset));
  out->push_back(static_cast<char>(offset >> 8));
}

void emit_copy(std::string* out, size_t offset, size_t len) {
  // Chunks of ≤64 with the final one ≥4 (decoder accepts any, but the
  // canonical encoder never emits a sub-4 copy).
  while (len >= 68) {
    emit_copy_chunk(out, offset, 64);
    len -= 64;
  }
  if (len > 64) {
    emit_copy_chunk(out, offset, 60);
    len -= 60;
  }
  emit_copy_chunk(out, offset, len);
}

void compress_fragment(const char* frag, size_t n, std::string* out) {
  static thread_local uint16_t table[1 << kHashBits];
  memset(table, 0, sizeof(table));
  // Slot 0 doubles as "empty"; position 0 as a candidate is then only
  // believed when its 4 bytes really match (self-match at ip=0 is
  // rejected by the offset!=0 check).
  size_t ip = 0, next_emit = 0;
  while (ip + 4 <= n) {
    const uint32_t v = load32(frag + ip);
    const uint32_t h = hash32(v);
    const size_t cand = table[h];
    table[h] = static_cast<uint16_t>(ip);
    if (cand < ip && load32(frag + cand) == v) {
      emit_literal(out, frag + next_emit, ip - next_emit);
      size_t len = 4;
      while (ip + len < n && frag[cand + len] == frag[ip + len]) {
        ++len;
      }
      emit_copy(out, ip - cand, len);
      ip += len;
      next_emit = ip;
      continue;
    }
    ++ip;
  }
  emit_literal(out, frag + next_emit, n - next_emit);
}

}  // namespace

void snappy_compress(const char* in, size_t n, std::string* out) {
  put_varint32(out, static_cast<uint32_t>(n));
  for (size_t off = 0; off < n; off += kFragment) {
    compress_fragment(in + off,
                      n - off < kFragment ? n - off : kFragment, out);
  }
}

bool snappy_decompress(const char* in, size_t n, std::string* out,
                       uint64_t size_limit) {
  size_t p = 0;
  uint32_t total = 0;
  if (!get_varint32(in, n, &p, &total) || total > size_limit) {
    return false;
  }
  const size_t base = out->size();
  out->reserve(base + total);
  while (p < n) {
    const uint8_t tag = static_cast<uint8_t>(in[p++]);
    size_t len = 0, offset = 0;
    switch (tag & 3) {
      case 0: {  // literal
        size_t l = tag >> 2;
        if (l >= 60) {
          const int extra = static_cast<int>(l) - 59;
          if (n - p < static_cast<size_t>(extra)) {
            return false;
          }
          l = 0;
          for (int i = 0; i < extra; ++i) {
            l |= static_cast<size_t>(static_cast<uint8_t>(in[p++]))
                 << (8 * i);
          }
        }
        len = l + 1;
        if (n - p < len || out->size() - base + len > total) {
          return false;
        }
        out->append(in + p, len);
        p += len;
        continue;
      }
      case 1:
        if (p >= n) {
          return false;
        }
        len = 4 + ((tag >> 2) & 7);
        offset = (static_cast<size_t>(tag >> 5) << 8) |
                 static_cast<uint8_t>(in[p++]);
        break;
      case 2:
        if (n - p < 2) {
          return false;
        }
        len = (tag >> 2) + 1;
        offset = static_cast<uint8_t>(in[p]) |
                 (static_cast<size_t>(static_cast<uint8_t>(in[p + 1]))
                  << 8);
        p += 2;
        break;
      default:  // case 3
        if (n - p < 4) {
          return false;
        }
        len = (tag >> 2) + 1;
        offset = 0;
        for (int i = 0; i < 4; ++i) {
          offset |= static_cast<size_t>(static_cast<uint8_t>(in[p + i]))
                    << (8 * i);
        }
        p += 4;
        break;
    }
    const size_t produced = out->size() - base;
    if (offset == 0 || offset > produced || produced + len > total) {
      return false;
    }
    // Byte-wise: copies may overlap their own output (run-length form).
    size_t src = out->size() - offset;
    for (size_t i = 0; i < len; ++i) {
      out->push_back((*out)[src + i]);
    }
  }
  return out->size() - base == total;
}

}  // namespace trpc
