// Snappy block-format codec, implemented from the public format
// description (no external library — this image has none).
//
// Parity: the reference registers a snappy compress handler
// (/root/reference/src/brpc/policy/snappy_compress.*, vendoring
// butil/third_party/snappy).  Format recap: a varint32 uncompressed
// length, then tagged elements — tag&3: 0 literal (len-1 in the high 6
// bits, 60..63 = that many extra LE length bytes), 1 copy len 4..11 /
// 11-bit offset, 2 copy len 1..64 / 16-bit offset, 3 copy len 1..64 /
// 32-bit offset.  The encoder works in 64KB fragments with a 4-byte
// hash matcher, so emitted offsets always fit tag 2.
#pragma once

#include <cstdint>
#include <string>

namespace trpc {

// Compresses all of `in`; output appends to *out.  Always succeeds.
void snappy_compress(const char* in, size_t n, std::string* out);

// Decompresses; false on malformed input or when the decoded size would
// exceed `size_limit` (zip-bomb guard).  *out is appended to.
bool snappy_decompress(const char* in, size_t n, std::string* out,
                       uint64_t size_limit);

}  // namespace trpc
