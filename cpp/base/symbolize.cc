#include "base/symbolize.h"

#include <dlfcn.h>
#include <stdio.h>
#include <string.h>

#include <cstdint>

namespace trpc {

std::string symbolize_addr(void* addr) {
  Dl_info info;
  if (dladdr(addr, &info) != 0) {
    if (info.dli_sname != nullptr) {
      return info.dli_sname;  // exported symbol
    }
    if (info.dli_fname != nullptr) {
      // Static functions have no dynamic symbol: report module+offset so
      // external tooling (addr2line, pprof with the binary) can resolve.
      const char* base = strrchr(info.dli_fname, '/');
      char buf[256];
      snprintf(buf, sizeof(buf), "%s+0x%zx",
               base != nullptr ? base + 1 : info.dli_fname,
               reinterpret_cast<uintptr_t>(addr) -
                   reinterpret_cast<uintptr_t>(info.dli_fbase));
      return buf;
    }
  }
  char buf[32];
  snprintf(buf, sizeof(buf), "%p", addr);
  return buf;
}

}  // namespace trpc
