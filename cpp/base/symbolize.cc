#include "base/symbolize.h"

#include <cxxabi.h>
#include <dlfcn.h>
#include <elf.h>
#include <fcntl.h>
#include <stdio.h>
#include <string.h>
#include <sys/mman.h>
#include <sys/stat.h>
#include <unistd.h>

#include <algorithm>
#include <cstdint>
#include <map>
#include <mutex>
#include <vector>

namespace trpc {

namespace {

// One module's function symbols, sorted by offset.  Built lazily by
// reading the ELF .symtab (falls back to .dynsym) — dladdr alone only
// sees the dynamic table, so static functions would print as hex
// (the reference vendors Chromium's symbolize for the same reason).
struct ModuleSyms {
  bool is_dyn = false;  // ET_DYN: st_value is a load-base offset
  std::vector<std::pair<uint64_t, std::string>> funcs;  // sorted
};

ModuleSyms load_module_syms(const char* path) {
  ModuleSyms out;
  const int fd = open(path, O_RDONLY | O_CLOEXEC);
  if (fd < 0) {
    return out;
  }
  struct stat st;
  if (fstat(fd, &st) != 0 || st.st_size < static_cast<off_t>(sizeof(Elf64_Ehdr))) {
    close(fd);
    return out;
  }
  void* map = mmap(nullptr, st.st_size, PROT_READ, MAP_PRIVATE, fd, 0);
  close(fd);
  if (map == MAP_FAILED) {
    return out;
  }
  const uint8_t* base = static_cast<const uint8_t*>(map);
  const auto* eh = reinterpret_cast<const Elf64_Ehdr*>(base);
  const auto bounded = [&](uint64_t off, uint64_t n) {
    return off <= static_cast<uint64_t>(st.st_size) &&
           n <= static_cast<uint64_t>(st.st_size) - off;
  };
  if (memcmp(eh->e_ident, ELFMAG, SELFMAG) != 0 ||
      eh->e_ident[EI_CLASS] != ELFCLASS64 ||
      !bounded(eh->e_shoff,
               static_cast<uint64_t>(eh->e_shnum) * sizeof(Elf64_Shdr))) {
    munmap(map, st.st_size);
    return out;
  }
  out.is_dyn = eh->e_type == ET_DYN;
  const auto* sh = reinterpret_cast<const Elf64_Shdr*>(base + eh->e_shoff);
  // Prefer the full .symtab; .dynsym is the dladdr-visible subset.
  for (const uint32_t want : {SHT_SYMTAB, SHT_DYNSYM}) {
    for (int i = 0; i < eh->e_shnum; ++i) {
      if (sh[i].sh_type != want || sh[i].sh_link >= eh->e_shnum ||
          sh[i].sh_entsize != sizeof(Elf64_Sym) ||
          !bounded(sh[i].sh_offset, sh[i].sh_size) ||
          !bounded(sh[sh[i].sh_link].sh_offset,
                   sh[sh[i].sh_link].sh_size)) {
        continue;
      }
      const auto* syms =
          reinterpret_cast<const Elf64_Sym*>(base + sh[i].sh_offset);
      const size_t n = sh[i].sh_size / sizeof(Elf64_Sym);
      const char* strtab = reinterpret_cast<const char*>(
          base + sh[sh[i].sh_link].sh_offset);
      const size_t str_size = sh[sh[i].sh_link].sh_size;
      out.funcs.reserve(n);
      for (size_t s = 0; s < n; ++s) {
        if (ELF64_ST_TYPE(syms[s].st_info) != STT_FUNC ||
            syms[s].st_value == 0 || syms[s].st_name >= str_size) {
          continue;
        }
        const char* name = strtab + syms[s].st_name;
        // Bound the NUL scan by the strtab section: a truncated module
        // whose strtab ends at EOF without a terminator must not read
        // past the mapping.
        const void* nul =
            memchr(name, 0, str_size - syms[s].st_name);
        if (nul == nullptr || *name == '\0') {
          continue;
        }
        out.funcs.emplace_back(
            syms[s].st_value,
            std::string(name, static_cast<const char*>(nul)));
      }
      break;
    }
    if (!out.funcs.empty()) {
      break;
    }
  }
  munmap(map, st.st_size);
  std::sort(out.funcs.begin(), out.funcs.end(),
            [](const auto& a, const auto& b) { return a.first < b.first; });
  return out;
}

std::mutex g_syms_mu;
std::map<std::string, ModuleSyms>& syms_cache() {
  static auto* m = new std::map<std::string, ModuleSyms>();  // leaked
  return *m;
}

// Largest function symbol at or below `off`, or nullptr.
const std::string* lookup(const ModuleSyms& mod, uint64_t off) {
  auto it = std::upper_bound(
      mod.funcs.begin(), mod.funcs.end(), off,
      [](uint64_t v, const auto& p) { return v < p.first; });
  if (it == mod.funcs.begin()) {
    return nullptr;
  }
  --it;
  // A hit more than 1MB past the symbol start is a gap, not a function.
  if (off - it->first > (1u << 20)) {
    return nullptr;
  }
  return &it->second;
}

std::string demangled(const char* name) {
  int status = 0;
  char* d = abi::__cxa_demangle(name, nullptr, nullptr, &status);
  if (status == 0 && d != nullptr) {
    std::string out = d;
    free(d);
    return out;
  }
  free(d);
  return name;
}

}  // namespace

std::string symbolize_addr(void* addr) {
  Dl_info info;
  if (dladdr(addr, &info) != 0) {
    if (info.dli_sname != nullptr) {
      return demangled(info.dli_sname);  // exported symbol: cheap path
    }
    if (info.dli_fname != nullptr) {
      // Static functions have no dynamic symbol — consult the module's
      // full .symtab (built once per module, cached).
      const ModuleSyms* mod;
      {
        std::lock_guard<std::mutex> g(g_syms_mu);
        auto [it, fresh] = syms_cache().try_emplace(info.dli_fname);
        if (fresh) {
          it->second = load_module_syms(info.dli_fname);
        }
        mod = &it->second;
      }
      const uint64_t off =
          mod->is_dyn
              ? reinterpret_cast<uintptr_t>(addr) -
                    reinterpret_cast<uintptr_t>(info.dli_fbase)
              : reinterpret_cast<uintptr_t>(addr);
      if (const std::string* name = lookup(*mod, off)) {
        return demangled(name->c_str());
      }
      const char* base = strrchr(info.dli_fname, '/');
      char buf[256];
      snprintf(buf, sizeof(buf), "%s+0x%zx",
               base != nullptr ? base + 1 : info.dli_fname,
               reinterpret_cast<uintptr_t>(addr) -
                   reinterpret_cast<uintptr_t>(info.dli_fbase));
      return buf;
    }
  }
  char buf[32];
  snprintf(buf, sizeof(buf), "%p", addr);
  return buf;
}

}  // namespace trpc
