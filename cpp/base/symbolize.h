// Address symbolization shared by the profilers and the fiber tracer:
// dynamic symbol name when exported, else "module+0xoffset" (resolvable
// by addr2line / pprof against the binary), else the raw pointer.
#pragma once

#include <string>

namespace trpc {

std::string symbolize_addr(void* addr);

}  // namespace trpc
