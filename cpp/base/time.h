// Time helpers (parity: butil/time.h cpuwide_time_ns/gettimeofday_us,
// /root/reference/src/butil/time.h — CLOCK_MONOTONIC based here; rdtsc
// calibration is not worth its drift complexity on modern kernels).
#pragma once

#include <cstdint>
#include <ctime>

namespace trpc {

inline int64_t monotonic_time_ns() {
  timespec ts;
  clock_gettime(CLOCK_MONOTONIC, &ts);
  return ts.tv_sec * 1000000000LL + ts.tv_nsec;
}

inline int64_t monotonic_time_us() { return monotonic_time_ns() / 1000; }
inline int64_t monotonic_time_ms() { return monotonic_time_ns() / 1000000; }

inline int64_t realtime_us() {
  timespec ts;
  clock_gettime(CLOCK_REALTIME, &ts);
  return ts.tv_sec * 1000000LL + ts.tv_nsec / 1000;
}

}  // namespace trpc
