// Trivially-destructible thread-local free-cache with a teardown guard.
//
// The pattern (previously hand-rolled in fiber/stack.cc, base/arena.cc and
// net/socket.cc): a heap-owned cache behind a TRIVIALLY-destructible
// `thread_local` pointer, so entries released during static destruction
// (sockets owned by static servers, fibers finishing after main) can still
// reach it after this thread's non-trivial TLS has died; a separate guard
// object drains the cache at thread exit and flips a dead flag so late
// callers see nullptr instead of a resurrected cache.
#pragma once

#include <vector>

namespace trpc {

// One cache per (Entry, Tag) pair per thread.  `drain` is invoked on each
// remaining entry at thread teardown; it must be safe to run during TLS
// destruction (no non-trivial TLS of its own).  The first call on a
// thread captures `drain`; later calls may pass the same function.
template <typename Entry, typename Tag>
struct TlsFreeCache {
  using DrainFn = void (*)(Entry&);

  // The thread's cache vector, or nullptr after teardown began.
  static std::vector<Entry>* get(DrainFn drain) {
    static thread_local State* state = nullptr;  // trivial dtor
    static thread_local bool dead = false;
    static thread_local Guard guard;
    if (dead) {
      return nullptr;
    }
    if (state == nullptr) {
      state = new State();
      guard.slot = &state;
      guard.dead = &dead;
      guard.drain = drain;
    }
    return &state->items;
  }

 private:
  struct State {
    std::vector<Entry> items;
  };
  struct Guard {
    State** slot = nullptr;
    bool* dead = nullptr;
    DrainFn drain = nullptr;
    ~Guard() {
      if (slot != nullptr && *slot != nullptr) {
        for (Entry& e : (*slot)->items) {
          drain(e);
        }
        delete *slot;
        *slot = nullptr;
      }
      if (dead != nullptr) {
        *dead = true;
      }
    }
  };
};

}  // namespace trpc
