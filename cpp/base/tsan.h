// ThreadSanitizer annotation shim — the ONE place the runtime talks to
// TSan (ISSUE 7: suppressions → fixes).
//
// Two families:
//
//  * Fiber identity (__tsan_create/destroy/switch_to_fiber): without
//    them TSan sees one pthread's shadow stack teleporting between
//    fiber stacks and reports phantom races.  Used by the scheduler's
//    context switches (fiber/scheduler.cc).
//
//  * Explicit happens-before edges (__tsan_acquire/__tsan_release):
//    for handoffs whose ordering is real but flows through a channel
//    TSan cannot model — a futex syscall pair (ParkingLot park/wake,
//    the timer shard sleep), a kernel-mediated epoll edge (socket
//    connect → first readable), or a fiber-sync mutex whose ownership
//    transfers across __tsan_switch_to_fiber.  TRPC_TSAN_RELEASE(addr)
//    on the publishing side + TRPC_TSAN_ACQUIRE(addr) on the observing
//    side draw the edge on `addr` exactly where the kernel guarantees
//    it; both compile to nothing outside -fsanitize=thread builds.
//
// Policy: prefer restructuring onto plain atomics (TSan models
// acquire/release natively — see the timer-shard futex mutex) over
// annotations, and annotations over cpp/tsan.supp lines.  Every
// remaining suppression must cite the unmodeled edge it papers over.
#pragma once

#include <cstddef>

// gcc spells it __SANITIZE_THREAD__; clang only __has_feature.
#if defined(__SANITIZE_THREAD__)
#define TRPC_TSAN 1
#elif defined(__has_feature)
#if __has_feature(thread_sanitizer)
#define TRPC_TSAN 1
#endif
#endif
#ifndef TRPC_TSAN
#define TRPC_TSAN 0
#endif

#if TRPC_TSAN
extern "C" {
void* __tsan_get_current_fiber();
void* __tsan_create_fiber(unsigned flags);
void __tsan_destroy_fiber(void* fiber);
void __tsan_switch_to_fiber(void* fiber, unsigned flags);
void __tsan_acquire(void* addr);
void __tsan_release(void* addr);
}
#define TRPC_TSAN_ACQUIRE(addr) __tsan_acquire((void*)(addr))
#define TRPC_TSAN_RELEASE(addr) __tsan_release((void*)(addr))
#else
static inline void* __tsan_get_current_fiber() { return nullptr; }
static inline void* __tsan_create_fiber(unsigned) { return nullptr; }
static inline void __tsan_destroy_fiber(void*) {}
static inline void __tsan_switch_to_fiber(void*, unsigned) {}
#define TRPC_TSAN_ACQUIRE(addr) ((void)0)
#define TRPC_TSAN_RELEASE(addr) ((void)0)
#endif
