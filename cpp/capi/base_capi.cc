// Flat C ABI over the runtime for Python ctypes (the image has no pybind11;
// parity role: the reference's C++ API surface consumed by its examples).
#include <cstring>

#include "base/endpoint.h"
#include "base/iobuf.h"

using trpc::EndPoint;
using trpc::IOBuf;

extern "C" {

void* trpc_iobuf_create() { return new IOBuf(); }

void trpc_iobuf_destroy(void* buf) { delete static_cast<IOBuf*>(buf); }

void trpc_iobuf_append(void* buf, const void* data, size_t n) {
  static_cast<IOBuf*>(buf)->append(data, n);
}

size_t trpc_iobuf_size(void* buf) { return static_cast<IOBuf*>(buf)->size(); }

size_t trpc_iobuf_copy_to(void* buf, void* dst, size_t n, size_t pos) {
  return static_cast<IOBuf*>(buf)->copy_to(dst, n, pos);
}

size_t trpc_iobuf_cutn(void* from, void* to, size_t n) {
  return static_cast<IOBuf*>(from)->cutn(static_cast<IOBuf*>(to), n);
}

size_t trpc_iobuf_pop_front(void* buf, size_t n) {
  return static_cast<IOBuf*>(buf)->pop_front(n);
}

size_t trpc_iobuf_block_count(void* buf) {
  return static_cast<IOBuf*>(buf)->block_count();
}

// Returns 0 on success; writes normalized form into out.
int trpc_endpoint_parse(const char* s, char* out, size_t out_len) {
  EndPoint ep;
  if (trpc::hostname2endpoint(s, &ep) != 0) {
    return -1;
  }
  const std::string str = trpc::endpoint2str(ep);
  if (str.size() + 1 > out_len) {
    return -1;
  }
  memcpy(out, str.c_str(), str.size() + 1);
  return 0;
}

}  // extern "C"
