// Flat C ABI over the runtime for Python ctypes (the image has no pybind11;
// parity role: the reference's C++ API surface consumed by its examples).
#include <cstring>

#include "base/device_arena.h"
#include "base/endpoint.h"
#include "base/iobuf.h"

using trpc::Block;
using trpc::DeviceArena;
using trpc::EndPoint;
using trpc::IOBuf;

extern "C" {

// ---- device arena (block_pool parity; see base/device_arena.h) ----------

void* trpc_arena_create(uint32_t block_size, uint32_t blocks_per_slab,
                        int shm_backed) {
  DeviceArena::Options opts;
  opts.block_size = block_size;
  opts.blocks_per_slab = blocks_per_slab;
  opts.shm_backed = shm_backed != 0;
  return new DeviceArena(opts);
}

void trpc_arena_destroy(void* arena) {
  delete static_cast<DeviceArena*>(arena);
}

// Allocates one block; *data_out is the caller-writable staging memory
// (wrap it in numpy / hand it to a device DMA), *meta_out the slab/offset
// handle a device transport would ship instead of bytes.  The block is
// consumed by trpc_iobuf_append_block or returned via trpc_arena_release.
void* trpc_arena_alloc(void* arena, void** data_out, uint64_t* meta_out) {
  Block* b = static_cast<DeviceArena*>(arena)->allocate(0);
  if (b == nullptr) {
    return nullptr;
  }
  *data_out = b->data;
  *meta_out = b->user_meta;
  return b;
}

void trpc_arena_release(void* /*arena*/, void* block) {
  static_cast<Block*>(block)->release();
}

uint32_t trpc_arena_block_size(void* arena) {
  return static_cast<DeviceArena*>(arena)->block_size();
}

size_t trpc_arena_blocks_in_use(void* arena) {
  return static_cast<DeviceArena*>(arena)->blocks_in_use();
}

// Zero-copy append: the block's [0, len) bytes enter the IOBuf without
// copying; the caller's reference is consumed.  Returns 0, or -1 when len
// exceeds the block capacity (a ctypes caller is a trust boundary: an
// oversized length would put neighboring slab bytes on the wire).
int trpc_iobuf_append_block(void* buf, void* block, uint32_t len) {
  Block* b = static_cast<Block*>(block);
  if (len > b->cap) {
    b->release();  // still consumes, so the block cannot leak
    return -1;
  }
  b->size = len;
  static_cast<IOBuf*>(buf)->append_block(b, 0, len);
  return 0;
}

// True when byte `pos` of the IOBuf physically lives inside `arena`
// (introspection for zero-copy tests).
int trpc_iobuf_in_arena(void* buf, void* arena, size_t pos) {
  auto* iobuf = static_cast<IOBuf*>(buf);
  size_t off = 0;
  for (size_t i = 0; i < iobuf->block_count(); ++i) {
    const IOBuf::BlockRef& ref = iobuf->ref_at(i);
    if (pos < off + ref.length) {
      void* base;
      uint64_t handle;
      uint32_t boff;
      return static_cast<DeviceArena*>(arena)->locate(
                 ref.block->data + ref.offset + (pos - off), &base, &handle,
                 &boff)
                 ? 1
                 : 0;
    }
    off += ref.length;
  }
  return 0;
}

// Wrap caller-owned memory (e.g. a dlpack-exported JAX host buffer)
// without copying: the bytes enter the IOBuf by reference and
// deleter(data, ctx) runs when the LAST IOBuf reference drops — which may
// be on a fiber worker after the wire write completes, so a Python ctypes
// deleter must be re-entrant-safe (ctypes acquires the GIL itself).
void trpc_iobuf_append_user_data(void* buf, void* data, size_t n,
                                 void (*deleter)(void*, void*), void* ctx) {
  static_cast<IOBuf*>(buf)->append_user_data(data, n, deleter, ctx);
}

// Data pointer of block ref i (pointer-identity introspection for the
// zero-copy tests: proves the caller's buffer itself is on the wire).
void* trpc_iobuf_block_ptr(void* buf, size_t i) {
  auto* b = static_cast<IOBuf*>(buf);
  if (i >= b->block_count()) {
    return nullptr;
  }
  const IOBuf::BlockRef& r = b->ref_at(i);
  return r.block->data + r.offset;
}

void* trpc_iobuf_create() { return new IOBuf(); }

void trpc_iobuf_destroy(void* buf) { delete static_cast<IOBuf*>(buf); }

void trpc_iobuf_append(void* buf, const void* data, size_t n) {
  static_cast<IOBuf*>(buf)->append(data, n);
}

size_t trpc_iobuf_size(void* buf) { return static_cast<IOBuf*>(buf)->size(); }

size_t trpc_iobuf_copy_to(void* buf, void* dst, size_t n, size_t pos) {
  return static_cast<IOBuf*>(buf)->copy_to(dst, n, pos);
}

size_t trpc_iobuf_cutn(void* from, void* to, size_t n) {
  return static_cast<IOBuf*>(from)->cutn(static_cast<IOBuf*>(to), n);
}

size_t trpc_iobuf_pop_front(void* buf, size_t n) {
  return static_cast<IOBuf*>(buf)->pop_front(n);
}

size_t trpc_iobuf_block_count(void* buf) {
  return static_cast<IOBuf*>(buf)->block_count();
}

// Returns 0 on success; writes normalized form into out.
int trpc_endpoint_parse(const char* s, char* out, size_t out_len) {
  EndPoint ep;
  if (trpc::hostname2endpoint(s, &ep) != 0) {
    return -1;
  }
  const std::string str = trpc::endpoint2str(ep);
  if (str.size() + 1 > out_len) {
    return -1;
  }
  memcpy(out, str.c_str(), str.size() + 1);
  return 0;
}

}  // extern "C"
