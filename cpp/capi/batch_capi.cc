// Batched asynchronous call pipeline — the Python data plane's hot path.
//
// One ctypes crossing submits N calls (trpc_batch_submit); an issuing
// fiber replays them IN ORDER as async CallMethods over the existing
// Channel/ClusterChannel (the trpc_bench_echo_rpc fiber-loop shape, so
// the native stack pipelines exactly as the bench proves it can); each
// completion lands in a lock-light MPSC ring that trpc_batch_poll drains
// with the GIL released — one GIL round-trip per batch instead of one
// blocked round-trip per call (the r05 0.2-0.3 GB/s Python-plane ceiling).
//
// Ownership protocol (mirrors the rdma submission-queue discipline from
// "RPC Considered Harmful"'s fabric-lib answer):
//  - request bytes enter the wire path BY REFERENCE (caller deleter runs
//    when the last IOBuf reference drops — which may be after a timeout
//    completion, so the caller must free on the deleter, not on poll);
//  - responses land in the caller's buffer (one native memcpy off-GIL on
//    the completion fiber, pool blocks recycled immediately) or ride out
//    as an IOBuf handle the caller owns (view in place, destroy to
//    recycle) — no Python bytes objects at the boundary either way;
//  - a BatchCall is freed at the LAST of {issuer done, completion polled},
//    so cancel/poll/destroy racing an inline completion can never
//    use-after-free (refcount of 2, registry lookups serialized on mu_).
#include <atomic>
#include <cerrno>
#include <cstring>
#include <memory>
#include <mutex>
#include <string>
#include <unordered_map>
#include <vector>

#include "base/iobuf.h"
#include "base/time.h"
#include "fiber/event.h"
#include "fiber/fiber.h"
#include "net/channel.h"
#include "net/cluster.h"
#include "net/controller.h"
#include "net/span.h"
#include "stat/latency_recorder.h"
#include "stat/reducer.h"
#include "stat/variable.h"

using namespace trpc;

extern "C" {
// Fixed-layout completion record (mirrored by ctypes.Structure in
// brpc_tpu/rpc/batch.py — field order/sizes are ABI).
struct trpc_batch_completion {
  uint64_t token;
  int32_t status;        // 0 ok, else errno-style code
  uint32_t resp_copied;  // 1 when the response landed in the caller buffer
  uint64_t resp_len;     // full response length in bytes
  void* resp_iobuf;      // non-null: caller owns, free via trpc_iobuf_destroy
  char err[120];
};
}  // extern "C"

namespace {

struct Batch;

// Pipeline-wide observability (ISSUE 4): the pair of /vars series the
// perf PRs read first — how deep is the window NOW (batch_inflight) and
// how deep has it ever been (batch_depth) — plus the client-side latency
// recorder every batch member reports into (the mirror of the server's
// per-method recorder; the gap between the two is queueing + wire).
std::atomic<int64_t> g_batch_inflight{0};

struct BatchPipelineVars {
  PassiveStatus<long> inflight{[] {
    return static_cast<long>(
        g_batch_inflight.load(std::memory_order_relaxed));
  }};
  Maxer depth;
  LatencyRecorder latency;
  BatchPipelineVars() {
    inflight.expose("batch_inflight",
                    "batch-pipeline calls currently in flight, summed "
                    "over all live batches");
    depth.expose("batch_depth",
                 "high-water pipeline depth (max concurrent in-flight "
                 "batch calls) since process start");
    latency.expose("rpc_client_batch",
                   "client-side latency of batch-pipeline calls");
  }
};

BatchPipelineVars& batch_vars() {
  // Leaked with the registry: completion fibers outlive static dtors.
  static auto* v = new BatchPipelineVars();
  return *v;
}

// One trpc_batch_submit's span: the parent every member's client span
// links under, carrying the submitter's ambient trace (so a Python
// trace() around submit+poll owns the whole batch).  Submitted into the
// ring when the LAST member completes — the span covers the window from
// submit to final completion.
struct SubmitGroup {
  Span* span = nullptr;
  std::atomic<int64_t> remaining{0};
  // First member failure: the batch span must not read error_code 0
  // when its members failed (a trace filtered for errors would skip
  // exactly the failing batches).
  std::atomic<int32_t> first_error{0};
  std::atomic<int64_t> failures{0};
};

struct BatchCall {
  Batch* batch = nullptr;
  uint64_t token = 0;
  std::string method;
  IOBuf request;
  IOBuf response;
  Controller cntl;
  void* resp_buf = nullptr;  // caller-provided landing buffer (optional)
  size_t resp_cap = 0;
  int64_t timeout_ms = 0;
  SubmitGroup* group = nullptr;  // non-null iff rpcz was on at submit
  // Stamped just before CallMethod — the batch's own clock for the
  // rpc_client_batch recorder.  (Channel stamps cntl.call().start_us,
  // but ClusterChannel never does; relying on it dropped every cluster
  // member from the recorder.)
  int64_t issue_us = 0;
  std::atomic<bool> canceled{false};
  // Published by the issuer after CallMethod returns, so a cancel can
  // reach the in-flight fid (0 = not yet issued / cluster-internal).
  std::atomic<fid_t> issued_cid{0};
  // Completion record, written exactly once on the completion path.
  int32_t status = 0;
  bool resp_copied = false;
  size_t resp_len = 0;
  std::string err;
  BatchCall* done_next = nullptr;  // MPSC completion-ring link
  // Two owners: the issuing fiber and the completion->ring->poll chain.
  std::atomic<int> refs{2};
};

void unref(BatchCall* c) {
  if (c->refs.fetch_sub(1, std::memory_order_acq_rel) == 1) {
    delete c;
  }
}

struct Batch {
  void* channel = nullptr;
  bool is_cluster = false;
  std::atomic<bool> closing{false};
  std::atomic<uint64_t> next_token{1};
  std::atomic<int64_t> outstanding{0};  // submitted, not yet in the ring
  std::atomic<int> issuers{0};          // live issuing fibers
  std::atomic<BatchCall*> done_head{nullptr};  // MPSC LIFO of completions
  Event ev;  // value bumps on every completion / issuer exit
  std::mutex mu_;  // token registry (per batch-op, never per byte)
  std::unordered_map<uint64_t, BatchCall*> calls;
  std::mutex poll_mu_;       // serializes consumers
  BatchCall* drained = nullptr;  // consumer-local FIFO (reversed chain)
};

// Completion path — runs on whatever fiber finishes the call (dispatch
// fiber inline for responses, timeout fiber, canceller).  Bounded
// framework work only: status capture, the native landing memcpy, one
// atomic push, one wake.
void on_call_done(BatchCall* c) {
  Batch* b = c->batch;
  // Client-side latency into the shared recorder (issue_us 0 means the
  // call failed before issue — nothing to time).
  if (c->issue_us != 0) {
    batch_vars().latency << monotonic_time_us() - c->issue_us;
  }
  g_batch_inflight.fetch_sub(1, std::memory_order_relaxed);
  SubmitGroup* g = c->group;
  if (g != nullptr) {
    if (c->cntl.Failed()) {
      int32_t expect = 0;
      const int32_t code =
          c->cntl.error_code() != 0 ? c->cntl.error_code() : -1;
      g->first_error.compare_exchange_strong(expect, code,
                                             std::memory_order_relaxed);
      g->failures.fetch_add(1, std::memory_order_relaxed);
    }
    if (g->remaining.fetch_sub(1, std::memory_order_acq_rel) == 1) {
      // Last member: the batch span's window closes here, carrying the
      // first member failure (if any) as its error code.
      const int64_t failed = g->failures.load(std::memory_order_relaxed);
      if (failed > 0) {
        span_annotate(g->span,
                      std::to_string(failed) + " member(s) failed");
      }
      submit_span(g->span, g->first_error.load(std::memory_order_relaxed));
      delete g;
    }
  }
  if (c->cntl.Failed()) {
    c->status = c->cntl.error_code() != 0 ? c->cntl.error_code() : -1;
    c->err = c->cntl.error_text();
  } else if (c->resp_buf != nullptr) {
    const size_t n = c->response.size();
    c->resp_len = n;
    if (n > c->resp_cap) {
      c->status = EMSGSIZE;
      c->err = "response larger than caller buffer";
    } else {
      // Striped responses may ALREADY be in the caller's buffer (the
      // stripe layer landed chunks there in place); copying a buffer
      // onto itself would be both wasted bandwidth and UB.
      const bool in_place =
          c->response.block_count() == 1 &&
          c->response.ref_at(0).block->data + c->response.ref_at(0).offset ==
              c->resp_buf;
      if (!in_place) {
        c->response.copy_to(c->resp_buf, n);
      }
      c->resp_copied = true;
      c->response.clear();  // recycle pool blocks now, not at poll
    }
  } else {
    c->resp_len = c->response.size();
  }
  BatchCall* head = b->done_head.load(std::memory_order_relaxed);
  do {
    c->done_next = head;
  } while (!b->done_head.compare_exchange_weak(
      head, c, std::memory_order_release, std::memory_order_relaxed));
  // Wake FIRST, decrement LAST: trpc_batch_destroy frees the Batch as
  // soon as it observes outstanding==0 && issuers==0, so the decrement
  // must be this thread's final access to *b — signalling after it
  // would race the delete.  A waiter that saw the wake before the
  // decrement re-checks on its (bounded) wait timeout.
  b->ev.value.fetch_add(1, std::memory_order_release);
  b->ev.wake_all();
  b->outstanding.fetch_sub(1, std::memory_order_release);
}

// Issues ONE call asynchronously (the per-call body shared by both issue
// strategies).  Consumes the issuer reference.
void issue_call(Batch* b, BatchCall* c) {
  if (b->closing.load(std::memory_order_acquire) ||
      c->canceled.load(std::memory_order_acquire)) {
    c->cntl.SetFailed(ECANCELED, "canceled before issue");
    on_call_done(c);
    unref(c);
    return;
  }
  if (c->timeout_ms > 0) {
    c->cntl.set_timeout_ms(c->timeout_ms);
  }
  // Trace linkage: the member's client span (created inside CallMethod
  // when rpcz is on) must parent under the batch's submit span, and the
  // issuing context here is a fiber (or, pool-exhausted, the caller's
  // pthread) with its OWN ambient slot — install the batch span around
  // the issue and restore after (the pool-exhausted inline path would
  // otherwise leak it into the caller's thread-local context).
  uint64_t prev_trace = 0;
  uint64_t prev_span = 0;
  if (c->group != nullptr) {
    get_ambient_trace(&prev_trace, &prev_span);
    set_ambient_trace(c->group->span->trace_id, c->group->span->span_id);
  }
  const bool restore_ambient = c->group != nullptr;
  c->issue_us = monotonic_time_us();
  if (!b->is_cluster && c->resp_buf != nullptr) {
    // Stripe-aware landing (net/stripe.h): a striped response's chunks
    // memcpy straight into the caller's buffer instead of bouncing
    // through an arena block — the completion below detects the in-place
    // view and skips its copy.
    c->cntl.call().land_buf = c->resp_buf;
    c->cntl.call().land_cap = c->resp_cap;
  }
  BatchCall* cc = c;
  Closure done = [cc] { on_call_done(cc); };
  if (b->is_cluster) {
    static_cast<ClusterChannel*>(b->channel)
        ->CallMethod(c->method, c->request, &c->response, &c->cntl,
                     std::move(done));
  } else {
    static_cast<Channel*>(b->channel)
        ->CallMethod(c->method, c->request, &c->response, &c->cntl,
                     std::move(done));
  }
  if (restore_ambient) {
    // c->group may already be freed (inline completion of the last
    // member) — restore from the saved ids, never through the group.
    set_ambient_trace(prev_trace, prev_span);
  }
  // Single-channel async calls return with the fid live; publish it so
  // cancel can reach the in-flight call.  (Cluster members issue on
  // their own fiber — cancel covers them pre-issue only.)
  //
  // seq_cst on BOTH store/load pairs here and in trpc_batch_cancel: this
  // is a store-then-load-on-the-other's-atomic handshake (Dekker), and
  // with release/acquire both sides can legally miss — cancel would
  // report success while the call runs to its timeout (the same class
  // of race PR 2's writer handoff fixed with seq_cst).
  c->issued_cid.store(c->cntl.call_id(), std::memory_order_seq_cst);
  if (c->canceled.load(std::memory_order_seq_cst)) {
    // Cancel raced the issue: the flag alone missed the fid, so cancel
    // it here.  Stale fids (call already completed) are no-ops.
    StartCancel(c->issued_cid.load(std::memory_order_seq_cst));
  }
  unref(c);
}

void issuer_exit(Batch* b) {
  // Same ordering contract as on_call_done: the decrement is the final
  // access to *b, because destroy may free the Batch the moment it
  // reads issuers == 0.
  b->ev.value.fetch_add(1, std::memory_order_release);
  b->ev.wake_all();
  b->issuers.fetch_sub(1, std::memory_order_release);
}

struct IssueJob {
  Batch* b = nullptr;
  std::vector<BatchCall*> calls;
};

// FIFO strategy (single-connection channels): replays the submitted
// calls IN ORDER on one fiber, so issue order IS wire order (one writer,
// FIFO write queue).  Completions are correlation-matched, not ordered.
void issuer_main(void* p) {
  std::unique_ptr<IssueJob> job(static_cast<IssueJob*>(p));
  Batch* b = job->b;
  for (BatchCall* c : job->calls) {
    issue_call(b, c);
  }
  issuer_exit(b);
}

// Fan-out strategy (pooled/short/cluster channels): one issue fiber per
// call, bulk-published with ONE ParkingLot signal (fiber_start_batch),
// so the inline request writes overlap across their per-call sockets
// instead of serializing 8x4MB on one issuing fiber.  Wire order across
// distinct connections is meaningless, so nothing is lost.
void issue_one_main(void* p) {
  auto* c = static_cast<BatchCall*>(p);
  Batch* b = c->batch;
  issue_call(b, c);
  issuer_exit(b);
}

// Pops the next completion in FIFO order (consumer-local reversal of the
// LIFO ring).  poll_mu_ held by the caller.
BatchCall* pop_completion(Batch* b) {
  if (b->drained == nullptr) {
    BatchCall* chain =
        b->done_head.exchange(nullptr, std::memory_order_acquire);
    while (chain != nullptr) {  // reverse LIFO -> FIFO
      BatchCall* next = chain->done_next;
      chain->done_next = b->drained;
      b->drained = chain;
      chain = next;
    }
  }
  BatchCall* c = b->drained;
  if (c != nullptr) {
    b->drained = c->done_next;
  }
  return c;
}

void fill_completion(BatchCall* c, trpc_batch_completion* out) {
  out->token = c->token;
  out->status = c->status;
  out->resp_copied = c->resp_copied ? 1 : 0;
  out->resp_len = c->resp_len;
  out->resp_iobuf = nullptr;
  if (!c->resp_copied && c->response.size() > 0) {
    out->resp_iobuf = new IOBuf(std::move(c->response));
  }
  out->err[0] = '\0';
  if (!c->err.empty()) {
    strncpy(out->err, c->err.c_str(), sizeof(out->err) - 1);
    out->err[sizeof(out->err) - 1] = '\0';
  }
}

}  // namespace

extern "C" {

// channel: a trpc_channel_* handle (is_cluster == 0) or a trpc_cluster_*
// handle (is_cluster != 0).  The channel must outlive the batch's
// in-flight calls; polling buffered completions needs no channel, so
// destroying the channel AFTER the last call completed and BEFORE the
// last poll is safe.
void* trpc_batch_create(void* channel, int is_cluster) {
  if (channel == nullptr) {
    return nullptr;
  }
  batch_vars();  // register batch_inflight/batch_depth before traffic
  auto* b = new Batch();
  b->channel = channel;
  b->is_cluster = is_cluster != 0;
  return b;
}

// Submits n calls in ONE crossing.  reqs[i]/req_lens[i] are the request
// payloads; with req_deleter set, the bytes enter the wire path by
// reference and req_deleter(reqs[i], req_deleter_ctxs[i]) runs when the
// last IOBuf reference drops (buffer-protocol zero-copy); with a null
// deleter the bytes are copied here.  resp_bufs/resp_caps (either array
// nullable, entries nullable) are caller-owned landing buffers: the
// response is memcpy'd there natively on the completion fiber and the
// pool blocks recycle immediately.  timeout_ms <= 0 uses the channel
// default.  Writes per-call tokens to tokens_out; returns the number of
// calls accepted (0 after close).
size_t trpc_batch_submit(void* batch, const char* method,
                         const void* const* reqs, const size_t* req_lens,
                         void* const* resp_bufs, const size_t* resp_caps,
                         size_t n, int64_t timeout_ms,
                         void (*req_deleter)(void*, void*),
                         void* const* req_deleter_ctxs,
                         uint64_t* tokens_out) {
  auto* b = static_cast<Batch*>(batch);
  if (b == nullptr || n == 0 || method == nullptr ||
      b->closing.load(std::memory_order_acquire)) {
    return 0;
  }
  // rpcz: one parent span per submit.  start_span resolves the parent
  // from THIS thread's ambient context — ctypes callers run submit on
  // their own pthread, where a Python trace()/trpc_trace_set installed
  // it — so the whole batch hangs under the user's trace.
  SubmitGroup* group = nullptr;
  if (rpcz_enabled()) {
    group = new SubmitGroup();
    group->span =
        start_span(/*server_side=*/false, std::string("batch:") + method);
    span_annotate(group->span, "submit n=" + std::to_string(n));
    group->remaining.store(static_cast<int64_t>(n),
                           std::memory_order_relaxed);
  }
  const int64_t now_inflight =
      g_batch_inflight.fetch_add(static_cast<int64_t>(n),
                                 std::memory_order_relaxed) +
      static_cast<int64_t>(n);
  batch_vars().depth << now_inflight;
  auto job = std::make_unique<IssueJob>();
  job->b = b;
  job->calls.reserve(n);
  for (size_t i = 0; i < n; ++i) {
    auto* c = new BatchCall();
    c->batch = b;
    c->group = group;
    c->token = b->next_token.fetch_add(1, std::memory_order_relaxed);
    c->method = method;
    if (reqs != nullptr && reqs[i] != nullptr && req_lens[i] > 0) {
      if (req_deleter != nullptr) {
        c->request.append_user_data(
            const_cast<void*>(reqs[i]), req_lens[i], req_deleter,
            req_deleter_ctxs != nullptr ? req_deleter_ctxs[i] : nullptr);
      } else {
        c->request.append(reqs[i], req_lens[i]);
      }
    }
    if (resp_bufs != nullptr && resp_bufs[i] != nullptr) {
      c->resp_buf = resp_bufs[i];
      c->resp_cap = resp_caps != nullptr ? resp_caps[i] : 0;
    }
    c->timeout_ms = timeout_ms;
    // The completion closure is bounded framework work (memcpy + atomic
    // push + wake): safe to run inline on a dispatch fiber, no per-call
    // completion-fiber spawn.
    c->cntl.set_done_inline_safe(true);
    if (tokens_out != nullptr) {
      tokens_out[i] = c->token;
    }
    job->calls.push_back(c);
  }
  {
    std::lock_guard<std::mutex> g(b->mu_);
    for (BatchCall* c : job->calls) {
      b->calls.emplace(c->token, c);
    }
  }
  b->outstanding.fetch_add(static_cast<int64_t>(n),
                           std::memory_order_release);
  // Single-connection channels get ONE issuing fiber (issue order = wire
  // order); everything with per-call connections fans out one fiber per
  // call so their inline request writes run concurrently.
  const bool fifo =
      !b->is_cluster &&
      static_cast<Channel*>(b->channel)->conn_type_raw() == 0;
  if (fifo || n == 1) {
    b->issuers.fetch_add(1, std::memory_order_release);
    IssueJob* raw = job.release();
    if (fiber_start(nullptr, issuer_main, raw, 0) != 0) {
      issuer_main(raw);  // pool exhausted: issue on the caller (GIL
                         // already released by ctypes), never drop
    }
  } else {
    b->issuers.fetch_add(static_cast<int>(n), std::memory_order_release);
    const size_t started = fiber_start_batch(
        issue_one_main,
        reinterpret_cast<void* const*>(job->calls.data()), n, 0);
    for (size_t i = started; i < n; ++i) {
      issue_one_main(job->calls[i]);  // pool exhausted: issue inline
    }
  }
  return n;
}

// Drains up to max completion records, blocking the calling PTHREAD (not
// a fiber — ctypes has already released the GIL) until at least one is
// available or timeout_ms elapses (0 = non-blocking, < 0 = wait
// forever).  Completions already buffered in the ring remain drainable
// after the channel is closed.  The consumer mutex covers only the
// DRAIN, never the wait — a parked infinite poller must not block a
// concurrent non-blocking poll (or destroy) behind it.  A quiesced
// batch wakes parked pollers and they drain out with whatever is left.
// Returns the number of records written.
size_t trpc_batch_poll(void* batch, trpc_batch_completion* out, size_t max,
                       int64_t timeout_ms) {
  auto* b = static_cast<Batch*>(batch);
  if (b == nullptr || out == nullptr || max == 0) {
    return 0;
  }
  const int64_t deadline_us =
      timeout_ms < 0 ? -1 : monotonic_time_us() + timeout_ms * 1000;
  size_t n = 0;
  for (;;) {
    const uint32_t seq = b->ev.value.load(std::memory_order_acquire);
    {
      std::lock_guard<std::mutex> consumer(b->poll_mu_);
      while (n < max) {
        BatchCall* c = pop_completion(b);
        if (c == nullptr) {
          break;
        }
        fill_completion(c, &out[n]);
        ++n;
        std::lock_guard<std::mutex> g(b->mu_);
        b->calls.erase(c->token);
        unref(c);
      }
    }
    if (n > 0 || timeout_ms == 0) {
      return n;
    }
    if (deadline_us >= 0 && monotonic_time_us() >= deadline_us) {
      return n;
    }
    if (b->closing.load(std::memory_order_acquire)) {
      return n;  // quiesced and the ring is dry: drain out, don't re-park
    }
    b->ev.wait(seq, deadline_us);
  }
}

// Cancels one in-flight member (the existing StartCancel path: it
// completes with ECANCELED exactly once; a cancel racing the response is
// a stale-fid no-op and the call completes normally).  Cluster members
// cancel pre-issue only (their attempts run on internal controllers).
// Returns 0 when the token was live, -1 when unknown/already polled.
int trpc_batch_cancel(void* batch, uint64_t token) {
  auto* b = static_cast<Batch*>(batch);
  if (b == nullptr) {
    return -1;
  }
  fid_t cid = 0;
  {
    std::lock_guard<std::mutex> g(b->mu_);
    auto it = b->calls.find(token);
    if (it == b->calls.end()) {
      return -1;
    }
    // seq_cst pair with issue_call's publish/check (Dekker handshake —
    // see the comment there): at least one side must see the other.
    it->second->canceled.store(true, std::memory_order_seq_cst);
    cid = it->second->issued_cid.load(std::memory_order_seq_cst);
  }
  StartCancel(cid);  // outside mu_: the error path may complete inline
  return 0;
}

// Calls submitted but not yet drained by poll (in flight + ring).
size_t trpc_batch_outstanding(void* batch) {
  auto* b = static_cast<Batch*>(batch);
  if (b == nullptr) {
    return 0;
  }
  std::lock_guard<std::mutex> g(b->mu_);
  return b->calls.size();
}

// Calls still IN FLIGHT (not yet completed into the ring).  Zero means
// every submitted call has settled — the channel is no longer needed by
// this batch and closing it is safe; buffered completions remain
// drainable.
size_t trpc_batch_inflight(void* batch) {
  auto* b = static_cast<Batch*>(batch);
  if (b == nullptr) {
    return 0;
  }
  const int64_t n = b->outstanding.load(std::memory_order_acquire);
  return n > 0 ? static_cast<size_t>(n) : 0;
}

// Quiesces the batch WITHOUT freeing it: rejects further submits,
// cancels everything in flight, waits for issuers and completions to
// settle, then wakes any parked poller so it can observe the closed
// state and drain out.  After this returns the batch no longer touches
// its channel — buffered completions remain pollable, so the channel
// may be destroyed while results are still being harvested.
void trpc_batch_quiesce(void* batch) {
  auto* b = static_cast<Batch*>(batch);
  if (b == nullptr) {
    return;
  }
  b->closing.store(true, std::memory_order_seq_cst);
  {
    std::lock_guard<std::mutex> g(b->mu_);
    for (auto& kv : b->calls) {
      // Same seq_cst handshake as trpc_batch_cancel.
      kv.second->canceled.store(true, std::memory_order_seq_cst);
      StartCancel(kv.second->issued_cid.load(std::memory_order_seq_cst));
    }
  }
  for (;;) {
    const uint32_t seq = b->ev.value.load(std::memory_order_acquire);
    if (b->outstanding.load(std::memory_order_acquire) == 0 &&
        b->issuers.load(std::memory_order_acquire) == 0) {
      break;
    }
    b->ev.wait(seq, monotonic_time_us() + 50 * 1000);
  }
  // Kick parked pollers: they re-check closing and return instead of
  // re-parking on a batch that will produce nothing further.
  b->ev.value.fetch_add(1, std::memory_order_release);
  b->ev.wake_all();
}

// Quiesce, then free unpolled completions (their response pool blocks
// recycle) and destroy the batch.  Safe with calls in flight; callers
// must ensure no poller is INSIDE trpc_batch_poll when this runs (the
// Python wrapper quiesces first, waits for its pollers to drain out,
// then destroys).
void trpc_batch_destroy(void* batch) {
  auto* b = static_cast<Batch*>(batch);
  if (b == nullptr) {
    return;
  }
  trpc_batch_quiesce(b);
  {
    std::lock_guard<std::mutex> consumer(b->poll_mu_);
    for (BatchCall* c = pop_completion(b); c != nullptr;
         c = pop_completion(b)) {
      std::lock_guard<std::mutex> g(b->mu_);
      b->calls.erase(c->token);
      unref(c);
    }
  }
  delete b;
}

}  // extern "C"
