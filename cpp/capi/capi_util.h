// Shared helpers for the C ABI surface (capi/*.cc).
#pragma once

#include <cstring>
#include <string>

namespace trpc {
namespace capi {

// The buffer protocol every dump-style capi call follows: the return
// value is the FULL byte length of the rendered text (excluding the
// NUL); the buffer receives min(full, out_len-1) bytes plus a NUL.  A
// caller seeing ret >= out_len re-calls with a bigger buffer — no
// truncated body is ever parsed by accident.  One definition, so the
// contract cannot drift between capi files.
inline size_t copy_out(const std::string& s, char* out, size_t out_len) {
  if (out != nullptr && out_len > 0) {
    const size_t n = s.size() < out_len - 1 ? s.size() : out_len - 1;
    memcpy(out, s.data(), n);
    out[n] = '\0';
  }
  return s.size();
}

}  // namespace capi
}  // namespace trpc
