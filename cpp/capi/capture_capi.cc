// C ABI for the traffic-capture plane (stat/capture.h) — Python ctypes
// binding surface, brpc_tpu/rpc/capture.py.
//
// Buffer protocol: capi/capi_util.h copy_out — dump calls return the
// FULL byte length; a caller seeing ret >= out_len re-calls bigger.
#include <cstdint>
#include <string>

#include "capi/capi_util.h"
#include "stat/capture.h"

using namespace trpc;
using trpc::capi::copy_out;

extern "C" {

// 1 while the trpc_capture flag is on (requests are being recorded).
int trpc_capture_enabled() {
  capture::ensure_registered();
  return capture::enabled() ? 1 : 0;
}

// The /capture body, in-process: {"enabled", counters, flags, "summary"
// (arrival-process + per-tenant baseline), "records" (newest
// `max_records`) when max_records > 0}.  Served even while capture is
// off — the reservoir may hold an earlier enabled window.
size_t trpc_capture_dump(size_t max_records, char* out, size_t out_len) {
  if (max_records > (1u << 16)) {
    max_records = 1u << 16;
  }
  return copy_out(capture::dump_json(max_records), out, out_len);
}

// Writes the reservoir to a recordio capture file (header record +
// binary records).  Returns records written, or -1 on I/O error.
long long trpc_capture_dump_file(const char* path) {
  if (path == nullptr) {
    return -1;
  }
  return capture::dump_file(path);
}

// Lifetime admission counters (the capture_* vars, one crossing) plus
// the records currently held.
void trpc_capture_counters(uint64_t* seen, uint64_t* sampled,
                           uint64_t* dropped, uint64_t* records) {
  if (seen != nullptr) {
    *seen = capture::seen_total();
  }
  if (sampled != nullptr) {
    *sampled = capture::sampled_total();
  }
  if (dropped != nullptr) {
    *dropped = capture::dropped_total();
  }
  if (records != nullptr) {
    *records = capture::records_held();
  }
}

// Test/windowing support: clears the reservoir, window counters and the
// sampling decision index (lifetime capture_*_total vars keep counting).
void trpc_capture_reset() { capture::reset(); }

}  // extern "C"
