// C ABI for collective transfer schedules (net/collective.h) — the
// Python surface brpc_tpu/rpc/collective.py binds.  The data plane stays
// native: puts ride the one-sided RMA fabric with no Python in the path;
// these entry points compile groups/plans and block (GIL released by
// ctypes) while a schedule runs.
#include <string.h>

#include <string>
#include <vector>

#include "net/channel.h"
#include "net/collective.h"
#include "net/rma.h"
#include "net/server.h"

using namespace trpc;

namespace {

// Unpacks ShardRangeWire rows (collective.py packs the same wire it
// sends to Reshard.Plan — one marshalling, two consumers).
void unpack_sharding(const void* rows, uint32_t count, uint64_t total,
                     uint32_t skip, Sharding* out) {
  out->total = total;
  const auto* w = static_cast<const ShardRangeWire*>(rows) + skip;
  for (uint32_t i = 0; i < count; ++i) {
    ShardRange r;
    r.rank = w[i].rank;
    r.off = w[i].off;
    r.len = w[i].len;
    out->ranges.push_back(r);
  }
}

}  // namespace

extern "C" {

// Attaches the native handlers (Coll.Put/Abort, Reshard.Plan/Execute)
// to a not-yet-started server.  Returns 0, or -1.
int trpc_server_enable_collective(void* srv) {
  return coll_attach(static_cast<Server*>(srv));
}

// Compiles a group from a comma-separated ordered member list (every
// member passes the SAME list; members[my_rank] is this process).
// Returns an opaque handle, or NULL.
void* trpc_coll_group_create(const char* members_csv, uint32_t my_rank,
                             int64_t timeout_ms, int use_shm) {
  if (members_csv == nullptr) {
    return nullptr;
  }
  std::vector<std::string> members;
  const char* p = members_csv;
  while (*p != '\0') {
    const char* comma = strchr(p, ',');
    members.emplace_back(p, comma != nullptr ? comma - p : strlen(p));
    if (comma == nullptr) {
      break;
    }
    p = comma + 1;
  }
  auto* g = new GroupChannel();
  GroupChannel::Options opts;
  opts.timeout_ms = timeout_ms > 0 ? timeout_ms : 30000;
  opts.use_shm = use_shm != 0;
  if (g->Init(members, my_rank, &opts) != 0) {
    delete g;
    return nullptr;
  }
  return g;
}

// Snapshots a naming:// view ("naming://host:port/service") into a
// group; self_addr must be an announced member.  Returns NULL when the
// resolve fails or self is not a member.
void* trpc_coll_group_create_naming(const char* naming_url,
                                    const char* self_addr,
                                    int64_t timeout_ms, int use_shm) {
  if (naming_url == nullptr || self_addr == nullptr) {
    return nullptr;
  }
  auto* g = new GroupChannel();
  GroupChannel::Options opts;
  opts.timeout_ms = timeout_ms > 0 ? timeout_ms : 30000;
  opts.use_shm = use_shm != 0;
  if (g->InitNaming(naming_url, self_addr, &opts) != 0) {
    delete g;
    return nullptr;
  }
  return g;
}

void trpc_coll_group_destroy(void* g) {
  delete static_cast<GroupChannel*>(g);
}

uint32_t trpc_coll_group_rank(void* g) {
  return static_cast<GroupChannel*>(g)->my_rank();
}

uint32_t trpc_coll_group_size(void* g) {
  return static_cast<GroupChannel*>(g)->nmembers();
}

uint64_t trpc_coll_group_version(void* g) {
  return static_cast<GroupChannel*>(g)->naming_version();
}

// Runs one collective (op: 1 all_gather, 2 reduce_scatter, 3 all_to_all
// — CollOp values).  shard_bytes is the per-member shard (all_gather:
// send size; reduce_scatter: recv size; all_to_all: send_len/n when 0).
// reduce_scatter MUTATES sendbuf (ring accumulator).  Blocks until the
// schedule completes; every member must call with the same run_seq
// sequence (0 = the group's internal counter).  Returns 0, a coll error
// code (2121..2123), or a transport errno.
int trpc_coll_run(void* g, int op, const void* sendbuf, uint64_t send_len,
                  void* recvbuf, uint64_t recv_len, uint64_t shard_bytes,
                  uint64_t run_seq) {
  auto* group = static_cast<GroupChannel*>(g);
  TransferSchedule plan;
  switch (op) {
    case 1:
      plan = plan_all_gather(group->nmembers(),
                             shard_bytes != 0 ? shard_bytes : send_len);
      break;
    case 2:
      plan = plan_reduce_scatter(
          group->nmembers(),
          shard_bytes != 0 ? shard_bytes : recv_len);
      break;
    case 3:
      if (shard_bytes == 0) {
        // A remainder would silently drop the tail (the shard floors).
        if (group->nmembers() == 0 ||
            send_len % group->nmembers() != 0) {
          return kECollMismatch;
        }
        shard_bytes = send_len / group->nmembers();
      }
      plan = plan_all_to_all(group->nmembers(), shard_bytes);
      break;
    default:
      return kECollMismatch;
  }
  return group->run(plan, sendbuf, send_len, recvbuf, recv_len, run_seq);
}

// trpc_coll_run with a readiness map attached (overlap-aware path):
// `ready` is a trpc_coll_ready_create handle over THIS member's
// sendbuf.  Transfers whose compiled input ranges are stamped fire
// immediately when trpc_coll_overlap is on; off, the executor waits
// once for the full producer extent — byte-identical either way.
// ready = 0 degrades to trpc_coll_run exactly.
int trpc_coll_run_ready(void* g, int op, const void* sendbuf,
                        uint64_t send_len, void* recvbuf,
                        uint64_t recv_len, uint64_t shard_bytes,
                        uint64_t run_seq, uint64_t ready) {
  auto* group = static_cast<GroupChannel*>(g);
  TransferSchedule plan;
  switch (op) {
    case 1:
      plan = plan_all_gather(group->nmembers(),
                             shard_bytes != 0 ? shard_bytes : send_len);
      break;
    case 2:
      plan = plan_reduce_scatter(
          group->nmembers(),
          shard_bytes != 0 ? shard_bytes : recv_len);
      break;
    case 3:
      if (shard_bytes == 0) {
        if (group->nmembers() == 0 ||
            send_len % group->nmembers() != 0) {
          return kECollMismatch;
        }
        shard_bytes = send_len / group->nmembers();
      }
      plan = plan_all_to_all(group->nmembers(), shard_bytes);
      break;
    default:
      return kECollMismatch;
  }
  return group->run(plan, sendbuf, send_len, recvbuf, recv_len, run_seq,
                    ready);
}

// Registers a readiness map over [base, base+len) at `granularity`
// bytes per chunk (0 = trpc_coll_ready_granularity_bytes).  The
// producer stamps ranges as it fills them; collective runs with the
// handle attached gate their transfers on the stamps.  Returns a
// non-zero handle, or 0 on invalid arguments.
uint64_t trpc_coll_ready_create(const void* base, uint64_t len,
                                uint64_t granularity) {
  coll_ensure_registered();
  if (granularity == 0) {
    granularity = coll_ready_default_granularity();
  }
  return rma_ready_create(base, len, granularity);
}

// Marks [off, off+len) ready (release-fenced after the producer's
// writes; off chunk-aligned, len a chunk multiple or reaching the
// buffer end).  Returns 0, or -1 on bad handle / misaligned span.
int trpc_coll_ready_stamp(uint64_t handle, uint64_t off, uint64_t len) {
  return rma_ready_stamp(handle, off, len);
}

// Unregisters a readiness map; parked waiters wake and fail cleanly.
void trpc_coll_ready_destroy(uint64_t handle) {
  rma_ready_destroy(handle);
}

// Live readiness maps in this process (0 = quiesced; tests).
size_t trpc_coll_ready_maps() { return rma_ready_maps(); }

// Runs a reshard over the group.  `ranges` is (nsrc + ndst) packed
// ShardRangeWire rows (source rows first — the same wire collective.py
// sends to Reshard.Plan).  sendbuf holds this rank's source ranges
// concatenated; recvbuf receives its target ranges.  Returns like
// trpc_coll_run.
int trpc_coll_reshard_run(void* g, const void* ranges, uint32_t nsrc,
                          uint32_t ndst, uint64_t total,
                          const void* sendbuf, uint64_t send_len,
                          void* recvbuf, uint64_t recv_len,
                          uint64_t run_seq) {
  auto* group = static_cast<GroupChannel*>(g);
  Sharding src, dst;
  unpack_sharding(ranges, nsrc, total, 0, &src);
  unpack_sharding(ranges, ndst, total, nsrc, &dst);
  if (!sharding_valid(src, group->nmembers()) ||
      !sharding_valid(dst, group->nmembers())) {
    return kECollMismatch;
  }
  return group->run(plan_reshard(src, dst, group->nmembers()), sendbuf,
                    send_len, recvbuf, recv_len, run_seq);
}

// Plans a reshard WITHOUT executing (local, no RPC): fills the bytes the
// schedule would move / reuse and the naive full-exchange baseline —
// the minimality stamp bench rows and tests assert.  Returns 0, or -1
// on invalid shardings.
int trpc_coll_reshard_plan(const void* ranges, uint32_t nsrc,
                           uint32_t ndst, uint64_t total,
                           uint32_t nmembers, uint64_t* moved,
                           uint64_t* reused, uint64_t* naive_out,
                           uint32_t* steps_out) {
  Sharding src, dst;
  unpack_sharding(ranges, nsrc, total, 0, &src);
  unpack_sharding(ranges, ndst, total, nsrc, &dst);
  if (!sharding_valid(src, nmembers) || !sharding_valid(dst, nmembers)) {
    return -1;
  }
  const TransferSchedule plan = plan_reshard(src, dst, nmembers);
  if (moved != nullptr) {
    *moved = plan.bytes_moved();
  }
  if (reused != nullptr) {
    *reused = plan.bytes_reused();
  }
  if (naive_out != nullptr) {
    *naive_out = reshard_naive_bytes(src, nmembers);
  }
  if (steps_out != nullptr) {
    *steps_out = static_cast<uint32_t>(plan.steps.size());
  }
  return 0;
}

// The coll error-code family (net/collective.h), read once by
// collective.py so the Python exception mapping can never drift.
void trpc_coll_codes(int* abort_code, int* epoch, int* mismatch) {
  if (abort_code != nullptr) {
    *abort_code = kECollAbort;
  }
  if (epoch != nullptr) {
    *epoch = kECollEpoch;
  }
  if (mismatch != nullptr) {
    *mismatch = kECollMismatch;
  }
}

// Receive sessions currently registered (0 = quiesced; tests).
size_t trpc_coll_sessions() { return coll_sessions_live(); }

// One explicit scavenger pass over this process's receive windows
// (net/rma.h rma_scavenge); returns slots reclaimed.  The runtime also
// runs it lazily (resolve tick + drain poll) — this is for tests/tools.
size_t trpc_rma_scavenge() { return rma_scavenge(); }

}  // extern "C"
