// C ABI for the deadline & cancellation plane (net/deadline.h) — the
// Python surface's view of propagated budgets and cascading cancel.
//
// Handlers read the serving request's remaining budget / cancel state
// through their call handle; client pthreads install an ambient budget
// around their sync calls exactly like the ambient trace context
// (trpc_trace_set), so a Python proxy re-stamps budget-minus-elapsed on
// every downstream call without passing anything explicitly.
#include <cstdint>

#include "base/time.h"
#include "net/controller.h"
#include "net/deadline.h"

using namespace trpc;

namespace trpc {
// capi/rpc_capi.cc: the controller of an in-flight PendingCall handle.
Controller* trpc_internal_pending_controller(void* call_handle);
}  // namespace trpc

extern "C" {

// The kEDeadlineExpired status (2007) — Python maps it to the typed
// DeadlineExpiredError (the lint error-code-sync rule pins the table).
int trpc_deadline_expired_code() { return kEDeadlineExpired; }

// Remaining budget of an in-flight call handle in µs: INT64_MAX when the
// caller set no deadline, 0 when already past.  Valid only before the
// handle's trpc_call_respond (like trpc_call_qos).
int64_t trpc_call_remaining_us(void* call_handle) {
  return trpc_internal_pending_controller(call_handle)->remaining_us();
}

// 1 when the call's cancel scope fired (client kCancel / dead
// connection), else 0.  Same handle-validity contract as above.
int trpc_call_cancelled(void* call_handle) {
  Controller* cntl = trpc_internal_pending_controller(call_handle);
  return cntl->IsCanceled() ? 1 : 0;
}

// Ambient budget for the CALLING pthread: remaining_us from now.  Sync
// calls issued on this thread fold it into their stamped budget
// (min(timeout, ambient)); 0/negative clears.
void trpc_deadline_ambient_set(int64_t remaining_us) {
  set_ambient_deadline(
      remaining_us > 0 ? monotonic_time_us() + remaining_us : 0);
}

// Remaining ambient budget in µs (-1 = none set).
int64_t trpc_deadline_ambient_remaining() {
  const int64_t abs_us = ambient_deadline();
  if (abs_us == 0) {
    return -1;
  }
  const int64_t rem = abs_us - monotonic_time_us();
  return rem > 0 ? rem : 0;
}

void trpc_deadline_ambient_clear() { set_ambient_deadline(0); }

// Live cancel-scope registrations (tests: drains to 0 when idle).
size_t trpc_cancel_registered() { return cancel_registered(); }

// Registers the deadline flags/vars eagerly (so /flags?setvalue and the
// observe plane see them before first traffic).
void trpc_deadline_ensure_registered() { deadline_ensure_registered(); }

}  // extern "C"
