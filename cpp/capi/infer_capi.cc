// C ABI for the streamed-inference front door (net/infer.h) — the Python
// surface of brpc_tpu/rpc/infer.py.  Submission itself needs no capi:
// clients pack the InferSubmitWire request and offer a stream via
// trpc_stream_open; this file covers server-side attach/stop and the
// stats dump the orchestrator and bench read.
#include <string>

#include "capi/capi_util.h"
#include "fiber/fiber.h"
#include "net/infer.h"
#include "net/kvstore.h"
#include "net/server.h"

using namespace trpc;

extern "C" {

// Attaches the continuous-batching scheduler to `srv` (registers
// Infer.Submit, starts the decode loop).  use_prefix_cache != 0 wires the
// PROCESS-wide kv_store()/kv_registry() singletons (composes with
// trpc_server_enable_kv_store/_registry and cross-node prefill);
// kv_fetch_addr non-empty pulls matched blocks over Kv.FetchPrefix from
// that node instead of the local store.  Returns the scheduler handle,
// NULL on failure.  Stop with trpc_infer_stop BEFORE destroying the
// server.
void* trpc_server_enable_infer(void* srv, int use_prefix_cache,
                               const char* kv_fetch_addr,
                               const char* node) {
  InferOptions opts;
  if (use_prefix_cache != 0) {
    opts.store = &kv_store();
    opts.registry = &kv_registry();
  }
  if (kv_fetch_addr != nullptr) {
    opts.kv_fetch_addr = kv_fetch_addr;
  }
  if (node != nullptr && node[0] != '\0') {
    opts.node = node;
  }
  return infer_attach(static_cast<Server*>(srv), opts);
}

// Stops the loop (cancelling every queued/active request) and frees the
// scheduler.  Joins fibers: pinned like the other sync paths.
void trpc_infer_stop(void* sched) {
  ScopedPthreadWait pin;
  infer_stop(static_cast<InferScheduler*>(sched));
}

// Scheduler stats JSON (copy_out contract: returns the full length;
// re-call with a bigger buffer when ret >= out_len).
size_t trpc_infer_dump(void* sched, char* out, size_t out_len) {
  return capi::copy_out(infer_dump_json(static_cast<InferScheduler*>(sched)),
                        out, out_len);
}

// Fast-path gauges for the scale orchestrator (≥100k-streams proof).
long long trpc_infer_streams_live(void* sched) {
  return infer_streams_live(static_cast<InferScheduler*>(sched));
}

long long trpc_infer_streams_peak(void* sched) {
  return infer_streams_peak(static_cast<InferScheduler*>(sched));
}

}  // extern "C"
