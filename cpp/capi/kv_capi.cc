// C ABI for the paged KV-block registry (net/kvstore.h) — the Python
// surface brpc_tpu/rpc/kv.py binds.  The data plane stays native: the
// store serves block bytes zero-copy out of registered regions with no
// Python in the path; these entry points only publish/withdraw blocks
// and attach the native handlers to a server.
#include <string.h>

#include "base/iobuf.h"
#include "net/kvstore.h"
#include "net/server.h"

using namespace trpc;

extern "C" {

// Attaches the registry handlers (KvReg.Register/Lookup/Evict/Renew) to
// a not-yet-started server.  Returns 0, or -1 (server already running —
// the registrations were refused).
int trpc_server_enable_kv_registry(void* srv) {
  return kv_attach_registry(static_cast<Server*>(srv));
}

// Attaches the block-store fetch handler (Kv.Fetch).  Returns 0, or -1
// (server already running — the registration was refused).
int trpc_server_enable_kv_store(void* srv) {
  return kv_attach_store(static_cast<Server*>(srv));
}

// Publishes [data, data+len) — which must lie inside an rma_alloc'd
// region (RmaBuffer bytes) — as block_id under a lease (lease_ms <= 0:
// the trpc_kv_lease_ms default).  Fills the minted generation and the
// region coordinates for the registry record.  Returns 0, kEKvExists
// (2103) while the block is live, or -1 (not registered memory / over
// budget).
int trpc_kv_publish_ex(const void* data, size_t len, uint64_t block_id,
                       int64_t lease_ms, uint64_t min_generation,
                       uint64_t* gen_out, uint64_t* rkey_out,
                       uint64_t* off_out);

int trpc_kv_publish(const void* data, size_t len, uint64_t block_id,
                    int64_t lease_ms, uint64_t* gen_out, uint64_t* rkey_out,
                    uint64_t* off_out) {
  return trpc_kv_publish_ex(data, len, block_id, lease_ms, 0, gen_out,
                            rkey_out, off_out);
}

// Takeover variant (net/naming.h drain + hot restart): min_generation
// floors the minted generation so a successor pid's re-publish outranks
// the dead predecessor's registry record and cached lookups.
int trpc_kv_publish_ex(const void* data, size_t len, uint64_t block_id,
                       int64_t lease_ms, uint64_t min_generation,
                       uint64_t* gen_out, uint64_t* rkey_out,
                       uint64_t* off_out) {
  KvBlockMeta m;
  const int rc = kv_store().publish(block_id, data, len, lease_ms, &m,
                                    min_generation);
  if (rc != 0) {
    return rc;
  }
  if (gen_out != nullptr) {
    *gen_out = m.generation;
  }
  if (rkey_out != nullptr) {
    *rkey_out = m.rkey;
  }
  if (off_out != nullptr) {
    *off_out = m.off;
  }
  return 0;
}

// Evicts a local block (generation tombstoned).  0 or kEKvMiss (2101).
int trpc_kv_withdraw(uint64_t block_id) {
  return kv_store().withdraw(block_id);
}

// Extends a local block's lease.  0 or kEKvMiss.
int trpc_kv_renew(uint64_t block_id, int64_t lease_ms) {
  return kv_store().renew(block_id, lease_ms);
}

size_t trpc_kv_store_count() { return kv_store().count(); }

uint64_t trpc_kv_store_bytes_used() { return kv_store().bytes_used(); }

size_t trpc_kv_registry_count() { return kv_registry().count(); }

// The kv error-code family (net/kvstore.h), read once by kv.py so the
// Python exception mapping can never drift from the C++ constants.
void trpc_kv_codes(int* miss, int* stale, int* exists) {
  if (miss != nullptr) {
    *miss = kEKvMiss;
  }
  if (stale != nullptr) {
    *stale = kEKvStale;
  }
  if (exists != nullptr) {
    *exists = kEKvExists;
  }
}

// Test support: drops every local block, tombstone, and registry record.
void trpc_kv_reset() {
  kv_store().clear();
  kv_registry().clear();
}

}  // extern "C"
