// C ABI for the paged KV-block registry (net/kvstore.h) — the Python
// surface brpc_tpu/rpc/kv.py binds.  The data plane stays native: the
// store serves block bytes zero-copy out of registered regions with no
// Python in the path; these entry points only publish/withdraw blocks
// and attach the native handlers to a server.
#include <string.h>

#include "base/iobuf.h"
#include "net/kvstore.h"
#include "net/server.h"

using namespace trpc;

extern "C" {

// Attaches the registry handlers (KvReg.Register/Lookup/Evict/Renew) to
// a not-yet-started server.  Returns 0, or -1 (server already running —
// the registrations were refused).
int trpc_server_enable_kv_registry(void* srv) {
  return kv_attach_registry(static_cast<Server*>(srv));
}

// Attaches the block-store fetch handler (Kv.Fetch).  Returns 0, or -1
// (server already running — the registration was refused).
int trpc_server_enable_kv_store(void* srv) {
  return kv_attach_store(static_cast<Server*>(srv));
}

// Publishes [data, data+len) — which must lie inside an rma_alloc'd
// region (RmaBuffer bytes) — as block_id under a lease (lease_ms <= 0:
// the trpc_kv_lease_ms default).  Fills the minted generation and the
// region coordinates for the registry record.  Returns 0, kEKvExists
// (2103) while the block is live, or -1 (not registered memory / over
// budget).
int trpc_kv_publish_ex(const void* data, size_t len, uint64_t block_id,
                       int64_t lease_ms, uint64_t min_generation,
                       uint64_t* gen_out, uint64_t* rkey_out,
                       uint64_t* off_out);

int trpc_kv_publish(const void* data, size_t len, uint64_t block_id,
                    int64_t lease_ms, uint64_t* gen_out, uint64_t* rkey_out,
                    uint64_t* off_out) {
  return trpc_kv_publish_ex(data, len, block_id, lease_ms, 0, gen_out,
                            rkey_out, off_out);
}

// Takeover variant (net/naming.h drain + hot restart): min_generation
// floors the minted generation so a successor pid's re-publish outranks
// the dead predecessor's registry record and cached lookups.
int trpc_kv_publish_ex(const void* data, size_t len, uint64_t block_id,
                       int64_t lease_ms, uint64_t min_generation,
                       uint64_t* gen_out, uint64_t* rkey_out,
                       uint64_t* off_out) {
  KvBlockMeta m;
  const int rc = kv_store().publish(block_id, data, len, lease_ms, &m,
                                    min_generation);
  if (rc != 0) {
    return rc;
  }
  if (gen_out != nullptr) {
    *gen_out = m.generation;
  }
  if (rkey_out != nullptr) {
    *rkey_out = m.rkey;
  }
  if (off_out != nullptr) {
    *off_out = m.off;
  }
  return 0;
}

// Evicts a local block (generation tombstoned).  0 or kEKvMiss (2101).
int trpc_kv_withdraw(uint64_t block_id) {
  return kv_store().withdraw(block_id);
}

// Extends a local block's lease.  0 or kEKvMiss.
int trpc_kv_renew(uint64_t block_id, int64_t lease_ms) {
  return kv_store().renew(block_id, lease_ms);
}

size_t trpc_kv_store_count() { return kv_store().count(); }

uint64_t trpc_kv_store_bytes_used() { return kv_store().bytes_used(); }

size_t trpc_kv_registry_count() { return kv_registry().count(); }

// The kv error-code family (net/kvstore.h), read once by kv.py so the
// Python exception mapping can never drift from the C++ constants.
void trpc_kv_codes(int* miss, int* stale, int* exists) {
  if (miss != nullptr) {
    *miss = kEKvMiss;
  }
  if (stale != nullptr) {
    *stale = kEKvStale;
  }
  if (exists != nullptr) {
    *exists = kEKvExists;
  }
}

// ---- content-addressed prefix cache (ISSUE 17) ---------------------------

// 128-bit content hash of (block bytes, token-id span) — deterministic
// across processes: the fleet-wide dedup key.
void trpc_kv_content_hash(const void* data, size_t len,
                          const uint64_t* tokens, size_t ntokens,
                          uint64_t* hi, uint64_t* lo) {
  Key128 k;
  kv_content_hash(data, len, tokens, ntokens, &k);
  if (hi != nullptr) {
    *hi = k.hi;
  }
  if (lo != nullptr) {
    *lo = k.lo;
  }
}

// Chain keys for a token-id sequence, written as interleaved (hi, lo)
// u64 pairs (Key128's exact layout).  block_tokens <= 0 uses
// trpc_kv_prefix_block_tokens.  Returns the number of FULL blocks.
size_t trpc_kv_prefix_chain(const uint64_t* tokens, size_t ntokens,
                            int64_t block_tokens, uint64_t* keys_out,
                            size_t max_keys) {
  static_assert(sizeof(Key128) == 16, "interleaved (hi, lo) pairs");
  return kv_prefix_chain(tokens, ntokens, block_tokens,
                         reinterpret_cast<Key128*>(keys_out), max_keys);
}

// Publishes one prefix block into the two-tier store (bytes are COPIED
// into store-owned registered pages — any caller memory works).  Fills
// the content hash, minted generation and hot-tier coordinates.
// Returns 0 (fresh bytes admitted), kEKvExists (2103: identical content
// already live — the cache-hit path, lease renewed, outputs filled), or
// -1 (over budget / bad args).
int trpc_kv_prefix_publish(uint64_t key_hi, uint64_t key_lo, uint32_t depth,
                           const void* data, size_t len,
                           const uint64_t* tokens, size_t ntokens,
                           int64_t lease_ms, uint64_t min_generation,
                           uint64_t* hash_hi, uint64_t* hash_lo,
                           uint64_t* gen_out, uint64_t* rkey_out,
                           uint64_t* off_out) {
  Key128 key;
  key.hi = key_hi;
  key.lo = key_lo;
  KvPrefixMeta m;
  const int rc = kv_store().publish_prefix(key, depth, data, len, tokens,
                                           ntokens, lease_ms, &m,
                                           min_generation);
  if (rc != 0 && rc != kEKvExists) {
    return rc;
  }
  if (hash_hi != nullptr) {
    *hash_hi = m.hash.hi;
  }
  if (hash_lo != nullptr) {
    *hash_lo = m.hash.lo;
  }
  if (gen_out != nullptr) {
    *gen_out = m.generation;
  }
  if (rkey_out != nullptr) {
    *rkey_out = m.rkey;
  }
  if (off_out != nullptr) {
    *off_out = m.off;
  }
  return rc;
}

// Evicts a local prefix block by content hash (generation tombstoned).
int trpc_kv_prefix_withdraw(uint64_t hash_hi, uint64_t hash_lo) {
  Key128 h;
  h.hi = hash_hi;
  h.lo = hash_lo;
  return kv_store().withdraw_prefix(h);
}

size_t trpc_kv_prefix_store_count() { return kv_store().prefix_count(); }

uint64_t trpc_kv_prefix_hot_bytes() { return kv_store().prefix_hot_bytes(); }

uint64_t trpc_kv_prefix_cold_bytes() {
  return kv_store().prefix_cold_bytes();
}

size_t trpc_kv_prefix_registry_count() {
  return kv_registry().prefix_count();
}

size_t trpc_kv_prefix_registry_replicas() {
  return kv_registry().prefix_replicas();
}

// Prefix-tier outcome counters since process start.
void trpc_kv_prefix_counters(uint64_t* promote, uint64_t* demote,
                             uint64_t* hot_hits, uint64_t* cold_hits,
                             uint64_t* dedup) {
  KvPrefixCounters& c = kv_prefix_counters();
  if (promote != nullptr) {
    *promote = KvPrefixCounters::read(c.promote);
  }
  if (demote != nullptr) {
    *demote = KvPrefixCounters::read(c.demote);
  }
  if (hot_hits != nullptr) {
    *hot_hits = KvPrefixCounters::read(c.hot_hits);
  }
  if (cold_hits != nullptr) {
    *cold_hits = KvPrefixCounters::read(c.cold_hits);
  }
  if (dedup != nullptr) {
    *dedup = KvPrefixCounters::read(c.dedup);
  }
}

// Test support: drops every local block, tombstone, and registry record
// (both the id-addressed and content-addressed tiers) and zeroes the
// prefix outcome counters.
void trpc_kv_reset() {
  kv_store().clear();
  kv_registry().clear();
  KvPrefixCounters& c = kv_prefix_counters();
  c.promote.store(0, std::memory_order_relaxed);
  c.demote.store(0, std::memory_order_relaxed);
  c.hot_hits.store(0, std::memory_order_relaxed);
  c.cold_hits.store(0, std::memory_order_relaxed);
  c.dedup.store(0, std::memory_order_relaxed);
}

}  // extern "C"
