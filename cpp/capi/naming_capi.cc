// C ABI for the cluster control plane (net/naming.h + Server drain/hot
// restart) — Python ctypes binding surface (brpc_tpu/rpc/server.py,
// brpc_tpu/rpc/naming.py).
#include <cstring>

#include "fiber/event.h"
#include "net/kvstore.h"
#include "net/naming.h"
#include "net/rma.h"
#include "net/server.h"

using namespace trpc;

extern "C" {

// Attaches the native naming-registry handlers
// (Naming.Announce/Withdraw/Resolve/Watch) to a not-yet-started server.
int trpc_server_enable_naming(void* srv) {
  return naming_attach(static_cast<Server*>(srv));
}

// Announces "127.0.0.1:<port>" of a RUNNING server into `service` at the
// registry (zone/weight ride the membership record), wiring withdrawal
// into the server's drain hooks.  Returns 0, or -1.
int trpc_server_announce(void* srv, const char* registry_addr,
                         const char* service, const char* zone,
                         int weight) {
  // The first announce is a sync RPC: same pthread-pinning contract as
  // every other sync capi entry (ctypes must return on the thread it
  // entered on).
  ScopedPthreadWait pin;
  return server_announce(static_cast<Server*>(srv),
                         registry_addr != nullptr ? registry_addr : "",
                         service != nullptr ? service : "default",
                         zone != nullptr ? zone : "", weight);
}

// Graceful drain (Server::Drain): answers kEDraining, runs drain hooks
// (naming withdrawal + KV tombstoning), optionally serves the listener
// handoff at `handoff_path` (null/"" = plain drain), then waits out
// in-flight requests and RMA window spans.  Returns 0 when quiesced,
// ETIMEDOUT when the deadline cut it short, -1 if not running.
int trpc_server_drain(void* srv, int64_t deadline_ms,
                      const char* handoff_path) {
  // Drain parks the calling pthread (ctypes released the GIL) — same
  // contract as the sync call paths.
  ScopedPthreadWait pin;
  return static_cast<Server*>(srv)->Drain(
      deadline_ms, handoff_path != nullptr ? handoff_path : "");
}

// Hot-restart successor: adopts the predecessor's SO_REUSEPORT listener
// set from its handoff socket and starts serving (register methods
// first, like trpc_server_start).  Returns 0 on ok.
int trpc_server_start_handoff(void* srv, const char* handoff_path,
                              int64_t timeout_ms) {
  ScopedPthreadWait pin;
  return static_cast<Server*>(srv)->StartFromHandoff(
      handoff_path != nullptr ? handoff_path : "", timeout_ms);
}

int trpc_server_draining(void* srv) {
  return static_cast<Server*>(srv)->draining() ? 1 : 0;
}

// The kEDraining status code (graceful-leave failover), so bindings
// never hardcode 2006.
int trpc_draining_code() { return kEDraining; }

// The naming error family (kENamingStaleEpoch / kENamingMiss).
void trpc_naming_codes(int* stale_epoch, int* miss) {
  if (stale_epoch != nullptr) {
    *stale_epoch = kENamingStaleEpoch;
  }
  if (miss != nullptr) {
    *miss = kENamingMiss;
  }
}

// Registry introspection + test support.
size_t trpc_naming_member_count(const char* service) {
  return naming_registry().member_count(
      service != nullptr ? service : "default");
}

void trpc_naming_reset() { naming_registry().clear(); }

// Drain support for embedders driving the KV plane from Python: every
// local block withdrawn + tombstoned (decode caches fail kv-stale and
// re-resolve).  Returns the number withdrawn.
size_t trpc_kv_withdraw_all() { return kv_store().withdraw_all(); }

// RMA window spans currently held by peers (the drain quiesce probe).
size_t trpc_rma_spans_in_use() { return rma_spans_in_use(); }

}  // extern "C"
