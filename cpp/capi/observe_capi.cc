// C ABI for the observability plane (Python ctypes binding surface —
// brpc_tpu/rpc/observe.py).
//
// Everything the builtin HTTP pages show is readable IN-PROCESS here:
// the var registry (JSON + Prometheus), per-recorder latency quantiles,
// the rpcz span ring as structured JSON, and the ambient trace context
// (read/install/clear around fiber-side calls).  Python can also
// REGISTER metrics — latency recorders and gauges — into the same
// registry, so client-side series appear in /vars and /brpc_metrics
// exactly like server methods do.
//
// Buffer protocol for the dump calls: the return value is the FULL
// byte length of the rendered text (excluding the NUL); the buffer
// receives min(full, out_len-1) bytes plus a NUL.  A caller seeing
// ret >= out_len re-calls with a bigger buffer — no truncated JSON is
// ever parsed by accident.
#include <cstdint>
#include <cstring>
#include <string>

#include "base/json.h"
#include "base/proc.h"
#include "capi/capi_util.h"
#include "net/span.h"
#include "stat/latency_recorder.h"
#include "stat/timeline.h"
#include "stat/variable.h"

using namespace trpc;
using trpc::capi::copy_out;

namespace {

// An explicit span handle: the span itself plus the ambient context it
// displaced, restored at end so nested trace()/span scopes unwind
// correctly on one thread/fiber.
struct CapiSpan {
  Span* span = nullptr;
  uint64_t prev_trace = 0;
  uint64_t prev_span = 0;
};

}  // namespace

extern "C" {

// ---- var registry -------------------------------------------------------

// format 0: JSON object {name: number-or-string} (the /vars?format=json
// shape); format 1: Prometheus text exposition (the /brpc_metrics body).
size_t trpc_vars_dump(int format, char* out, size_t out_len) {
  if (format == 1) {
    return copy_out(Variable::dump_prometheus(), out, out_len);
  }
  Json j = Json::object();
  for (auto& [name, value] : Variable::dump_exposed()) {
    double num = 0;
    if (parse_plain_number(value.c_str(), &num)) {
      j.set(name, Json::number(num));
    } else {
      j.set(name, Json::str(value));
    }
  }
  return copy_out(j.dump(), out, out_len);
}

// One variable's value_str.  Returns 0 on success, -1 unknown var, -2
// when the value does not fit (nothing useful written; retry bigger).
int trpc_var_read(const char* name, char* out, size_t out_len) {
  if (name == nullptr) {
    return -1;
  }
  std::string v;
  if (!Variable::read_exposed(name, &v)) {
    return -1;
  }
  if (out == nullptr || out_len == 0 || v.size() + 1 > out_len) {
    return -2;
  }
  memcpy(out, v.c_str(), v.size() + 1);
  return 0;
}

// Reads a registered LatencyRecorder's window in one crossing.
// out[8] = {count, qps, avg_us, p50_us, p90_us, p99_us, p999_us, max_us}.
// Returns 0 ok, -1 unknown var, -2 the var is not a latency recorder.
int trpc_latency_read(const char* name, double* out) {
  if (name == nullptr || out == nullptr) {
    return -1;
  }
  int rc = -2;
  // with_exposed pins the recorder alive (registry lock); read_stats
  // takes the window lock once for all four quantiles so that global
  // critical section stays short.
  const bool found = Variable::with_exposed(name, [&](Variable* v) {
    auto* lat = dynamic_cast<LatencyRecorder*>(v);
    if (lat == nullptr) {
      return;
    }
    lat->read_stats(out);
    rc = 0;
  });
  return found ? rc : -1;
}

// 1 when a variable is registered under `name`, else 0 — a pure
// registry probe (no value rendering; unique_var_name polls this).
int trpc_var_exists(const char* name) {
  return name != nullptr && Variable::read_exposed(name, nullptr) ? 1 : 0;
}

// ---- rpcz ---------------------------------------------------------------

// Recent spans as structured JSON (net/span.h rpcz_dump_json — the same
// shape /rpcz?format=json serves): newest first, at most `limit`,
// filtered to `trace_id` when nonzero.  `format` is reserved (0 = JSON).
size_t trpc_rpcz_dump(size_t limit, uint64_t trace_id, int format,
                      char* out, size_t out_len) {
  (void)format;
  if (limit == 0 || limit > (1 << 16)) {
    // Same cap as /rpcz?format=json: the span copy runs under the
    // submit-side ring mutex.
    limit = limit == 0 ? 200 : (1 << 16);
  }
  return copy_out(rpcz_dump_json(limit, trace_id), out, out_len);
}

// ---- timeline flight recorder -------------------------------------------

// The /timeline body, in-process (brpc_tpu/rpc/observe.py timeline()).
// format 0: JSON (see timeline::dump_json for the shape); format 1: the
// packed binary form (timeline::dump_binary — observe.py's struct
// parser).  Same buffer-retry contract as the other dumps: returns the
// FULL byte length; a caller seeing ret >= out_len re-calls bigger.
// Note the binary body may contain NULs — callers must slice by the
// returned length, never strlen.
size_t trpc_timeline_dump(int format, size_t per_thread_limit, char* out,
                          size_t out_len) {
  if (per_thread_limit == 0 || per_thread_limit > (1 << 16)) {
    per_thread_limit = per_thread_limit == 0 ? 4096 : (1 << 16);
  }
  return copy_out(format == 1 ? timeline::dump_binary(per_thread_limit)
                              : timeline::dump_json(per_thread_limit),
                  out, out_len);
}

// 1 while the trpc_timeline flag is on (events are being recorded).
int trpc_timeline_enabled() {
  timeline::ensure_registered();
  return timeline::enabled() ? 1 : 0;
}

// Test support: hides everything recorded so far (per-ring floors; no
// deallocation, safe against concurrent writers).
void trpc_timeline_reset() { timeline::reset(); }

// ---- ambient trace context ----------------------------------------------

// The context client spans inherit as their parent.  Works on fibers
// (handler-side) AND plain pthreads (Python callers) — span.cc falls
// back to thread-local storage off-fiber.
void trpc_trace_get(uint64_t* trace_id, uint64_t* span_id) {
  uint64_t t = 0;
  uint64_t s = 0;
  get_ambient_trace(&t, &s);
  if (trace_id != nullptr) {
    *trace_id = t;
  }
  if (span_id != nullptr) {
    *span_id = s;
  }
}

void trpc_trace_set(uint64_t trace_id, uint64_t span_id) {
  set_ambient_trace(trace_id, span_id);
}

void trpc_trace_clear() { set_ambient_trace(0, 0); }

// A fresh nonzero 64-bit id (for minting root trace ids in Python).
uint64_t trpc_trace_new_id() { return new_span_id(); }

// ---- explicit spans (the trace() context manager's substrate) -----------

// Starts a span named `name` and installs it as the ambient context
// (children inherit); parent resolution = current ambient, else a fresh
// trace rooted here.  Explicit spans always record — the caller asked
// for them — unlike the automatic per-RPC spans gated on rpcz_enabled.
void* trpc_span_start(const char* name, int server_side) {
  auto* h = new CapiSpan();
  get_ambient_trace(&h->prev_trace, &h->prev_span);
  h->span = start_span(server_side != 0,
                       name != nullptr ? name : "span");
  set_ambient_span(h->span);
  return h;
}

void trpc_span_annotate(void* handle, const char* text) {
  auto* h = static_cast<CapiSpan*>(handle);
  if (h != nullptr && h->span != nullptr && text != nullptr) {
    span_annotate(h->span, text);
  }
}

void trpc_span_ids(void* handle, uint64_t* trace_id, uint64_t* span_id) {
  auto* h = static_cast<CapiSpan*>(handle);
  if (h == nullptr || h->span == nullptr) {
    return;
  }
  if (trace_id != nullptr) {
    *trace_id = h->span->trace_id;
  }
  if (span_id != nullptr) {
    *span_id = h->span->span_id;
  }
}

// Ends the span: restores the ambient context it displaced, submits it
// into the rpcz ring, frees the handle.
void trpc_span_end(void* handle, int error_code) {
  auto* h = static_cast<CapiSpan*>(handle);
  if (h == nullptr) {
    return;
  }
  set_ambient_trace(h->prev_trace, h->prev_span);
  submit_span(h->span, error_code);
  delete h;
}

// ---- Python-registered metrics ------------------------------------------

// A latency recorder owned by the caller, exposed under `name` in the
// shared registry (shows in /vars, /brpc_metrics, trpc_latency_read).
void* trpc_latency_create(const char* name, const char* desc) {
  if (name == nullptr || name[0] == '\0') {
    return nullptr;
  }
  auto* lat = new LatencyRecorder();
  lat->expose(name, desc != nullptr ? desc : "");
  return lat;
}

void trpc_latency_record(void* handle, int64_t latency_us) {
  if (handle != nullptr) {
    *static_cast<LatencyRecorder*>(handle) << latency_us;
  }
}

void trpc_latency_destroy(void* handle) {
  delete static_cast<LatencyRecorder*>(handle);
}

// A push-based scalar gauge (pipeline depth, inflight, window size).
void* trpc_gauge_create(const char* name, const char* desc) {
  if (name == nullptr || name[0] == '\0') {
    return nullptr;
  }
  auto* g = new IntGauge();
  g->expose(name, desc != nullptr ? desc : "");
  return g;
}

void trpc_gauge_set(void* handle, int64_t value) {
  if (handle != nullptr) {
    static_cast<IntGauge*>(handle)->set(value);
  }
}

int64_t trpc_gauge_add(void* handle, int64_t delta) {
  return handle != nullptr ? static_cast<IntGauge*>(handle)->add(delta)
                           : 0;
}

void trpc_gauge_destroy(void* handle) {
  delete static_cast<IntGauge*>(handle);
}

}  // extern "C"
