// C ABI for the QoS subsystem (net/qos.h): per-tenant admission specs,
// channel-default tags, acceptor sharding, and the server-side view of a
// request's tag — the Python surface of the million-user front door.
#include <cstring>
#include <string>

#include "net/channel.h"
#include "net/cluster.h"
#include "net/concurrency_limiter.h"
#include "net/controller.h"
#include "net/qos.h"
#include "net/server.h"

using namespace trpc;

// Defined in rpc_capi.cc (the PendingCall layout owner).
namespace trpc {
Controller* trpc_internal_pending_controller(void* call_handle);
}

extern "C" {

// Per-tenant QoS spec (Server::SetQos; net/qos.h grammar).  "" removes.
// Returns 0, -1 on a malformed spec or a running server.
int trpc_server_set_qos(void* srv, const char* spec) {
  return static_cast<Server*>(srv)->SetQos(spec != nullptr ? spec : "");
}

// SO_REUSEPORT acceptor shards (Server::set_reuseport_shards).  Call
// before start.  Returns 0, -1 on a bad count or a running server.
int trpc_server_set_reuseport(void* srv, int shards) {
  return static_cast<Server*>(srv)->set_reuseport_shards(shards);
}

// Per-shard accepted-connection counters; returns the number written
// (≤ cap) — accept-distribution telemetry for the scale harness.
int trpc_server_accept_counts(void* srv, uint64_t* out, int cap) {
  const auto counts = static_cast<Server*>(srv)->accept_counts();
  int n = 0;
  for (; n < static_cast<int>(counts.size()) && n < cap; ++n) {
    out[n] = counts[n];
  }
  return n;
}

// Default QoS tag for every subsequent call on this channel (tenant may
// be ""/null = untagged; priority 0 = highest lane).
void trpc_channel_set_qos(void* ch, const char* tenant, int priority) {
  static_cast<Channel*>(ch)->set_default_qos(
      tenant != nullptr ? tenant : "",
      static_cast<uint8_t>(priority < 0 ? 0 : priority));
}

// Same for a cluster channel: stored for future member channels and
// pushed into the live ones.
void trpc_cluster_set_qos(void* ch, const char* tenant, int priority) {
  static_cast<ClusterChannel*>(ch)->set_default_qos(
      tenant != nullptr ? tenant : "",
      static_cast<uint8_t>(priority < 0 ? 0 : priority));
}

// The QoS tag of an in-flight server call (read inside the handler
// callback, BEFORE trpc_call_respond frees the handle).  Returns the
// priority; copies the tenant (truncated if needed) into tenant_out.
int trpc_call_qos(void* call_handle, char* tenant_out, size_t tenant_len) {
  Controller* cntl = trpc::trpc_internal_pending_controller(call_handle);
  if (tenant_out != nullptr && tenant_len > 0) {
    const std::string& t = cntl->qos_tenant();
    const size_t n = t.size() < tenant_len - 1 ? t.size() : tenant_len - 1;
    memcpy(tenant_out, t.data(), n);
    tenant_out[n] = '\0';
  }
  return cntl->qos_priority();
}

// The kEOverloaded status code (admission-control shed), so bindings
// never hardcode it.
int trpc_qos_overloaded_code() { return kEOverloaded; }

// Live depth of one QoS lane (test/telemetry convenience; the same value
// rides /vars as qos_lane_depth_<i>).
int64_t trpc_qos_lane_depth(int lane) { return qos_lane_depth(lane); }

}  // extern "C"
