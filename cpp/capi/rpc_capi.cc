// C ABI for the RPC runtime (Python ctypes binding surface).
//
// Handlers registered from Python are invoked on fiber stacks; ctypes
// callbacks re-acquire the GIL themselves.  Responses are completed via
// trpc_call_respond (sync or later — async handlers just stash the call
// handle).
#include <atomic>
#include <cstring>
#include <string>
#include <vector>

#include "base/iobuf.h"
#include "base/time.h"
#include "fiber/event.h"
#include "fiber/fiber.h"
#include "base/flags.h"
#include "net/span.h"
#include "net/channel.h"
#include "net/cluster.h"
#include "net/deadline.h"
#include "net/lb_hint.h"
#include "net/naming.h"
#include "net/controller.h"
#include "net/fault.h"
#include "base/proc.h"
#include "net/ici_transport.h"
#include "net/infer.h"
#include "net/kvstore.h"
#include "net/rma.h"
#include "stat/slo.h"
#include "net/server.h"

using namespace trpc;

namespace {

struct PendingCall {
  Controller* cntl;
  IOBuf* response;
  Closure done;
  std::atomic<bool> responded{false};
};

using HandlerCb = void (*)(void* call_handle, const char* req, size_t req_len,
                           void* user_ctx);

}  // namespace

namespace trpc {
// Internal accessor for sibling capi TUs (qos_capi.cc): the controller of
// an in-flight PendingCall handle.  Valid only while the handle is —
// i.e. before its trpc_call_respond.
Controller* trpc_internal_pending_controller(void* call_handle) {
  return static_cast<PendingCall*>(call_handle)->cntl;
}
}  // namespace trpc

extern "C" {

// ---- server -------------------------------------------------------------

void* trpc_server_create() { return new Server(); }

void trpc_server_destroy(void* srv) {
  // ~Server may run an owned Announcer's withdraw RPC (net/naming.h)
  // and fiber joins: pin like the sync call paths so a ctypes caller
  // returns on the pthread it entered on.
  ScopedPthreadWait pin;
  delete static_cast<Server*>(srv);
}

int trpc_server_register(void* srv, const char* method, HandlerCb cb,
                         void* user_ctx) {
  return static_cast<Server*>(srv)->RegisterMethod(
      method, [cb, user_ctx](Controller* cntl, const IOBuf& req,
                             IOBuf* resp, Closure done) {
        auto* pending = new PendingCall();
        pending->cntl = cntl;
        pending->response = resp;
        pending->done = std::move(done);
        const std::string flat = req.to_string();
        cb(pending, flat.data(), flat.size(), user_ctx);
      });
}

// Completes a call (callable from the handler callback or any thread
// later).  Idempotent: a second respond on the same handle is ignored, so
// an async-handler/error-path race cannot double-complete.  err_text may be
// null.  Returns 0 if this call completed the RPC, -1 if already done.
int trpc_call_respond(void* call_handle, const char* data, size_t len,
                      int err_code, const char* err_text) {
  auto* pending = static_cast<PendingCall*>(call_handle);
  bool expect = false;
  if (!pending->responded.compare_exchange_strong(
          expect, true, std::memory_order_acq_rel)) {
    return -1;
  }
  if (err_code != 0) {
    pending->cntl->SetFailed(err_code, err_text != nullptr ? err_text : "");
  } else if (data != nullptr && len > 0) {
    pending->response->append(data, len);
  }
  pending->done();
  delete pending;
  return 0;
}

// Registers a NATIVE zero-copy echo handler (response shares the request
// blocks by reference; no Python callback, no GIL).  The server-side
// anchor for the Python data-plane benchmarks and the batch-API perf
// floor: against a Python handler they would measure the server's GIL,
// not the client pipeline.
int trpc_server_register_echo(void* srv, const char* method) {
  return static_cast<Server*>(srv)->RegisterMethod(
      method, [](Controller*, const IOBuf& req, IOBuf* resp, Closure done) {
        resp->append(req);  // zero-copy ref share
        done();
      });
}

int trpc_server_start(void* srv, int port) {
  return static_cast<Server*>(srv)->Start(port);
}

int trpc_server_port(void* srv) { return static_cast<Server*>(srv)->port(); }

void trpc_server_stop(void* srv) { static_cast<Server*>(srv)->Stop(); }

// ---- single-server channel ---------------------------------------------

namespace {
void* create_channel(const char* addr, int64_t timeout_ms, bool use_shm,
                     const char* conn_type = nullptr) {
  auto* ch = new Channel();
  Channel::Options opts;
  opts.timeout_ms = timeout_ms;
  opts.use_shm = use_shm;
  if (conn_type != nullptr && conn_type[0] != '\0') {
    opts.connection_type = conn_type;
  }
  if (ch->Init(addr, &opts) != 0) {
    delete ch;
    return nullptr;
  }
  return ch;
}

// Flags register lazily from function-local statics (rpcz_enabled on its
// first check, per-method bounds at registration); a fresh process using
// ONLY the flag API would otherwise see "unknown flag".  Touch the static
// runtime flags here.
void ensure_runtime_flags() {
  rpcz_enabled();
  rpcz_ring_capacity();  // registers trpc_rpcz_ring_size
  fault_register_flag();
  cluster_ensure_registered();     // trpc_cluster_* knobs
  Server::drain_ensure_registered();  // trpc_drain_deadline_ms
  naming_ensure_registered();      // trpc_naming_* + trpc_fleet_publish
  deadline_ensure_registered();    // trpc_deadline_wire + retry budget
  slo::ensure_registered();        // trpc_slo + burn windows/alert
  kv_ensure_registered();          // trpc_kv_* incl. prefix block span
  infer_ensure_registered();       // trpc_infer_* serving knobs
}
}  // namespace

void* trpc_channel_create(const char* addr, int64_t timeout_ms) {
  return create_channel(addr, timeout_ms, false);
}

// Same-host shared-memory variant (falls back to TCP if the handshake
// fails; see net/shm_transport.h).
void* trpc_channel_create_shm(const char* addr, int64_t timeout_ms) {
  return create_channel(addr, timeout_ms, true);
}

// Full-option creation: conn_type "single"/"pooled"/"short"
// (socket_map.h matrix).  Returns nullptr on bad address/options.
void* trpc_channel_create_ex(const char* addr, int64_t timeout_ms,
                             const char* conn_type, int use_shm) {
  return create_channel(addr, timeout_ms, use_shm != 0, conn_type);
}

// Runtime flag access (base/flags.h; the /flags service's programmatic
// form).  Returns 0 on success (set) / found (get).
int trpc_flag_set(const char* name, const char* value) {
  ensure_runtime_flags();
  return Flag::set(name, value);
}

// Returns 0 on success, -1 unknown flag, -2 when the value does not fit
// (nothing written in that case; also guards degenerate buffers).
int trpc_flag_get(const char* name, char* out, size_t out_len) {
  ensure_runtime_flags();
  Flag* f = Flag::find(name);
  if (f == nullptr) {
    return -1;
  }
  const std::string v = f->value_string();
  if (out == nullptr || out_len == 0 || v.size() + 1 > out_len) {
    return -2;
  }
  memcpy(out, v.c_str(), v.size() + 1);
  return 0;
}

// Copies the live transport name ("tcp", "shm_ring", "" if unconnected).
void trpc_channel_transport(void* ch, char* out, size_t out_len) {
  const std::string name = static_cast<Channel*>(ch)->transport_name();
  strncpy(out, name.c_str(), out_len - 1);
  out[out_len - 1] = '\0';
}

void trpc_channel_destroy(void* ch) { delete static_cast<Channel*>(ch); }

// Synchronous call.  Returns 0 on success and fills *resp (a trpc_iobuf
// handle created by the caller); on failure returns the error code and
// copies the error text into err_buf.
namespace {
int call_channel_sync(void* ch, const char* method, const IOBuf& request,
                      void* resp_iobuf, int64_t timeout_ms, char* err_buf,
                      size_t err_buf_len) {
  // GIL safety: a ctypes caller must return on the pthread it entered on,
  // so any park inside the sync call blocks the thread, never migrates.
  ScopedPthreadWait pin;
  Controller cntl;
  if (timeout_ms > 0) {
    cntl.set_timeout_ms(timeout_ms);
  }
  static_cast<Channel*>(ch)->CallMethod(
      method, request, static_cast<IOBuf*>(resp_iobuf), &cntl);
  if (cntl.Failed()) {
    if (err_buf != nullptr && err_buf_len > 0) {
      strncpy(err_buf, cntl.error_text().c_str(), err_buf_len - 1);
      err_buf[err_buf_len - 1] = '\0';
    }
    return cntl.error_code() != 0 ? cntl.error_code() : -1;
  }
  return 0;
}
}  // namespace

int trpc_channel_call(void* ch, const char* method, const char* req,
                      size_t req_len, void* resp_iobuf, int64_t timeout_ms,
                      char* err_buf, size_t err_buf_len) {
  IOBuf request;
  request.append(req, req_len);
  return call_channel_sync(ch, method, request, resp_iobuf, timeout_ms,
                           err_buf, err_buf_len);
}

// IOBuf-request variant: the request IOBuf handle is used as-is (no
// flattening/copy — arena blocks ride straight to the wire).  The handle
// remains caller-owned; its payload is shared, not consumed.
int trpc_channel_call_buf(void* ch, const char* method, void* req_iobuf,
                          void* resp_iobuf, int64_t timeout_ms,
                          char* err_buf, size_t err_buf_len) {
  return call_channel_sync(ch, method, *static_cast<IOBuf*>(req_iobuf),
                           resp_iobuf, timeout_ms, err_buf, err_buf_len);
}

// ---- fault injection (net/fault.h) --------------------------------------

// Installs the process-wide transport fault schedule through the
// fault_schedule flag (so /flags and /faults observe the same value).
// Empty spec disables.  Returns 0, nonzero on a malformed spec.
int trpc_fault_set(const char* spec) {
  ensure_runtime_flags();
  return Flag::set("fault_schedule", spec != nullptr ? spec : "");
}

// Copies the canonical active schedule ("" when off).  Returns 0, or -2
// when the buffer is too small.
int trpc_fault_get(char* out, size_t out_len) {
  const std::string s = FaultActor::global().spec();
  if (out == nullptr || out_len == 0 || s.size() + 1 > out_len) {
    return -2;
  }
  memcpy(out, s.c_str(), s.size() + 1);
  return 0;
}

// Copies the injected-fault log ("#index point kind" lines, oldest
// first; truncated from the front if the buffer is too small).  Returns
// the number of bytes written (excluding the NUL).
size_t trpc_fault_log(char* out, size_t out_len) {
  if (out == nullptr || out_len == 0) {
    return 0;
  }
  std::string s = FaultActor::global().log_text();
  if (s.size() + 1 > out_len) {
    // Truncate from the front on a LINE boundary so the first returned
    // entry is never a garbled fragment.
    size_t start = s.size() + 1 - out_len;
    const size_t nl = s.find('\n', start);
    start = nl == std::string::npos ? s.size() : nl + 1;
    s = s.substr(start);
  }
  memcpy(out, s.c_str(), s.size() + 1);
  return s.size();
}

// Restarts the deterministic sequence (counter + log; schedule kept) —
// the seam the seed-replay assertion uses.
void trpc_fault_reset() { FaultActor::global().reset_counters(); }

uint64_t trpc_fault_injected() { return FaultActor::global().injected(); }

// Per-server dispatch/accept fault schedule (svr_* fields).  Returns 0,
// -1 on a malformed spec.
int trpc_server_fault_set(void* srv, const char* spec) {
  return static_cast<Server*>(srv)->SetFaults(spec != nullptr ? spec : "");
}

// ---- cluster channel ----------------------------------------------------

void* trpc_cluster_create_ex(const char* naming_url, const char* lb,
                             int64_t timeout_ms, int max_retry,
                             int64_t backup_request_ms,
                             const char* health_method,
                             int64_t health_timeout_ms,
                             int64_t refresh_interval_ms);

void* trpc_cluster_create(const char* naming_url, const char* lb,
                          int64_t timeout_ms, int max_retry) {
  return trpc_cluster_create_ex(naming_url, lb, timeout_ms, max_retry, 0,
                                nullptr, 0, 0);
}

// Full-option cluster creation: hedging (backup_request_ms > 0 races a
// second attempt after that budget), health-check probe method/timeout
// (empty method disables probing) and the re-resolve/probe cadence.
// Zero/negative numeric options mean "keep the default"; health_method
// nullptr keeps the default, "" disables.
void* trpc_cluster_create_ex(const char* naming_url, const char* lb,
                             int64_t timeout_ms, int max_retry,
                             int64_t backup_request_ms,
                             const char* health_method,
                             int64_t health_timeout_ms,
                             int64_t refresh_interval_ms) {
  auto* ch = new ClusterChannel();
  ClusterChannel::Options opts;
  opts.timeout_ms = timeout_ms;
  opts.max_retry = max_retry;
  if (backup_request_ms > 0) {
    opts.backup_request_ms = backup_request_ms;
  }
  if (health_method != nullptr) {
    opts.health_check_method = health_method;
  }
  if (health_timeout_ms > 0) {
    opts.health_check_timeout_ms = health_timeout_ms;
  }
  if (refresh_interval_ms > 0) {
    opts.refresh_interval_ms = refresh_interval_ms;
  }
  if (ch->Init(naming_url, lb, &opts) != 0) {
    delete ch;
    return nullptr;
  }
  return ch;
}

void trpc_cluster_destroy(void* ch) {
  delete static_cast<ClusterChannel*>(ch);
}

int trpc_cluster_call(void* ch, const char* method, const char* req,
                      size_t req_len, void* resp_iobuf, uint64_t hash_key,
                      char* err_buf, size_t err_buf_len) {
  ScopedPthreadWait pin;  // see trpc_channel_call
  Controller cntl;
  IOBuf request;
  request.append(req, req_len);
  static_cast<ClusterChannel*>(ch)->CallMethod(
      method, request, static_cast<IOBuf*>(resp_iobuf), &cntl, nullptr,
      hash_key);
  if (cntl.Failed()) {
    if (err_buf != nullptr && err_buf_len > 0) {
      strncpy(err_buf, cntl.error_text().c_str(), err_buf_len - 1);
      err_buf[err_buf_len - 1] = '\0';
    }
    return cntl.error_code() != 0 ? cntl.error_code() : -1;
  }
  return 0;
}

// Cache-aware variant (net/lb_hint.h): hint_addr ("host:port") names
// the member holding the longest cached prefix; the c_hash_bl walk
// honors it on attempt 0 unless bounded load vetoes.  An empty or
// unparseable hint degrades to trpc_cluster_call semantics — routing
// hints are advisory, never load-bearing for correctness.
int trpc_cluster_call_hinted(void* ch, const char* method, const char* req,
                             size_t req_len, void* resp_iobuf,
                             uint64_t hash_key, const char* hint_addr,
                             char* err_buf, size_t err_buf_len) {
  EndPoint hint;
  const bool have_hint = hint_addr != nullptr && hint_addr[0] != '\0' &&
                         hostname2endpoint(hint_addr, &hint) == 0;
  ScopedPthreadWait pin;  // see trpc_channel_call
  Controller cntl;
  IOBuf request;
  request.append(req, req_len);
  {
    // Scope the ambient hint to exactly this call: a leaked hint would
    // silently re-route the thread's next unrelated call.
    LbHintScope scope(have_hint ? hint : EndPoint());
    if (!have_hint) {
      lb_hint_clear();
    }
    static_cast<ClusterChannel*>(ch)->CallMethod(
        method, request, static_cast<IOBuf*>(resp_iobuf), &cntl, nullptr,
        hash_key);
  }
  if (cntl.Failed()) {
    if (err_buf != nullptr && err_buf_len > 0) {
      strncpy(err_buf, cntl.error_text().c_str(), err_buf_len - 1);
      err_buf[err_buf_len - 1] = '\0';
    }
    return cntl.error_code() != 0 ? cntl.error_code() : -1;
  }
  return 0;
}

// Hint routing outcomes since process start (hit = hinted member
// selected, veto = bounded load overrode the hint, miss = hinted member
// absent or unhealthy).
void trpc_lb_hint_counters(uint64_t* hit, uint64_t* veto, uint64_t* miss) {
  LbHintCounters& c = lb_hint_counters();
  if (hit != nullptr) {
    *hit = LbHintCounters::read(c.hit);
  }
  if (veto != nullptr) {
    *veto = LbHintCounters::read(c.veto);
  }
  if (miss != nullptr) {
    *miss = LbHintCounters::read(c.miss);
  }
}

}  // extern "C"

// ---- full-stack native benchmark ----------------------------------------

namespace {

struct NativeBenchWorker {
  Channel* ch = nullptr;
  const void* data = nullptr;
  size_t len = 0;
  int calls = 0;
  std::atomic<long>* failures = nullptr;
};

void noop_deleter(void*, void*) {}

void native_bench_fiber(void* p) {
  auto* w = static_cast<NativeBenchWorker*>(p);
  for (int i = 0; i < w->calls; ++i) {
    Controller cntl;
    cntl.set_timeout_ms(60000);
    // Payload enters the wire path BY REFERENCE from the pre-registered
    // staging buffer — zero client-side copies (append_user_data_with_meta
    // parity; the buffer outlives the synchronous loop by contract).
    IOBuf req, resp;
    req.append_user_data(const_cast<void*>(w->data), w->len, &noop_deleter);
    w->ch->CallMethod("Echo.Echo", req, &resp, &cntl);
    if (cntl.Failed() || resp.size() != w->len) {
      w->failures->fetch_add(1);
    }
  }
}

}  // namespace

extern "C" {

// Runs the ENTIRE echo loop inside the runtime — the calling pthread only
// parks, and ctypes released the GIL on entry, so Python is out of the
// measured path (the r3 0.36 GB/s ceiling was the per-call Python bounce).
// An in-process Server with a ref-sharing native echo handler serves
// `concurrency` fibers, each issuing synchronous calls whose payload is
// `len` bytes referenced (not copied) from `data`.  transport: "tcp",
// "shm" or "ici" (ici = the DMA-ring endpoint, net/ici_transport.h).
// Returns 0 and fills *out_gbps (payload bytes × calls / elapsed, the
// rpc_press goodput convention) and transport_used; nonzero on failure
// (first response mismatch, channel init failure, any call failure).
// resp_out (nullable, len bytes): receives one post-loop echo response so
// the caller can close the device→wire→device loop on REAL echoed bytes.
int trpc_bench_echo_rpc(const void* data, size_t len, int iters,
                        int concurrency, const char* transport,
                        void* resp_out, double* out_gbps,
                        char* transport_used, size_t tu_len, char* err,
                        size_t err_len) {
  auto fail = [&](const char* msg) {
    if (err != nullptr && err_len > 0) {
      strncpy(err, msg, err_len - 1);
      err[err_len - 1] = '\0';
    }
    return -1;
  };
  if (data == nullptr || len == 0 || iters <= 0 || concurrency <= 0) {
    return fail("bad arguments");
  }
  const std::string tr = transport != nullptr ? transport : "tcp";
  // Bench geometry is a process-global proposal for NEW client conns:
  // restore the embedder's configured value on every exit path so later
  // ICI connections don't silently inherit bench geometry.
  struct GeometryGuard {
    uint32_t bs = 0, sl = 0, mb = 0;
    bool armed = false;
    ~GeometryGuard() {
      if (armed) {
        ici_set_ring_geometry(bs, sl, mb);
      }
    }
  } geom_guard;
  if (tr == "ici") {
    ici_get_ring_geometry(&geom_guard.bs, &geom_guard.sl, &geom_guard.mb);
    // Wide window + 256KB DMA blocks so a 64MB payload is ~256 WRs and
    // the pool comfortably holds request+response in flight.
    geom_guard.armed = ici_set_ring_geometry(256 * 1024, 32, 1024);
  }
  Server server;
  server.RegisterMethod("Echo.Echo", [](Controller*, const IOBuf& req,
                                        IOBuf* resp, Closure done) {
    resp->append(req);  // zero-copy ref share
    done();
  });
  if (server.Start(0) != 0) {
    return fail("server start failed");
  }
  Channel ch;
  Channel::Options copts;
  copts.timeout_ms = 60000;
  copts.use_shm = tr == "shm";
  copts.use_ici = tr == "ici";
  char addr[64];
  snprintf(addr, sizeof(addr), "127.0.0.1:%d", server.port());
  if (ch.Init(addr, &copts) != 0) {
    server.Stop();
    return fail("channel init failed");
  }
  {
    // Warm + verify: one full round trip, content-checked.
    Controller cntl;
    cntl.set_timeout_ms(60000);
    IOBuf req, resp;
    req.append_user_data(const_cast<void*>(data), len, &noop_deleter);
    ch.CallMethod("Echo.Echo", req, &resp, &cntl);
    if (cntl.Failed()) {
      server.Stop();
      return fail(cntl.error_text().c_str());
    }
    std::string back = resp.to_string();
    if (back.size() != len || memcmp(back.data(), data, len) != 0) {
      server.Stop();
      return fail("echo verification mismatch");
    }
  }
  if (transport_used != nullptr && tu_len > 0) {
    const std::string name = ch.transport_name();
    strncpy(transport_used, name.c_str(), tu_len - 1);
    transport_used[tu_len - 1] = '\0';
  }
  std::atomic<long> failures{0};
  std::vector<NativeBenchWorker> workers(concurrency);
  std::vector<fiber_t> fids(concurrency);
  const int per = iters / concurrency > 0 ? iters / concurrency : 1;
  const int64_t t0 = monotonic_time_us();
  for (int i = 0; i < concurrency; ++i) {
    workers[i] = NativeBenchWorker{&ch, data, len, per, &failures};
    fiber_start(&fids[i], &native_bench_fiber, &workers[i], 0);
  }
  for (int i = 0; i < concurrency; ++i) {
    fiber_join(fids[i]);
  }
  const int64_t dt = monotonic_time_us() - t0;
  if (failures.load() > 0) {
    server.Stop();
    return fail("calls failed during the measured loop");
  }
  if (resp_out != nullptr) {
    Controller cntl;
    cntl.set_timeout_ms(60000);
    IOBuf req, resp;
    req.append_user_data(const_cast<void*>(data), len, &noop_deleter);
    ch.CallMethod("Echo.Echo", req, &resp, &cntl);
    if (cntl.Failed() || resp.copy_to(resp_out, len) != len) {
      server.Stop();
      return fail("post-loop response fetch failed");
    }
  }
  server.Stop();
  if (out_gbps != nullptr) {
    *out_gbps = static_cast<double>(len) * (per * concurrency) /
                (dt * 1e-6) / 1e9;
  }
  return 0;
}

// Sender-owned zero-copy staging (net/ici_transport.h): registered,
// shm-published payload memory the ICI ring ships WITHOUT its DMA copy —
// one descriptor per payload, receiver wraps the bytes in place.  Python
// views the slab via np.frombuffer and lands device fetches in it; see
// bench.py's tpu_rpc leg.
void* trpc_ici_staging_alloc(size_t len, uint32_t* ordinal_out) {
  return ici_staging_alloc(len, ordinal_out);
}

void trpc_ici_staging_free(void* base) { ici_staging_free(base); }

void trpc_ici_zero_copy_counters(uint64_t* wrs, uint64_t* bytes) {
  ici_zero_copy_counters(wrs, bytes);
}

// One-sided RMA regions (net/rma.h).  trpc_rma_alloc returns `len`
// usable shm-backed bytes registered under *rkey_out; a batch resp_buf
// pointing at them becomes a genuine remote-write target (the request
// advertises the rkey, the server puts the response straight in).
// Python views the buffer via (ctypes.c_char * len).from_address.
void* trpc_rma_alloc(size_t len, uint64_t* rkey_out) {
  return rma_alloc(len, rkey_out);
}

void trpc_rma_free(void* data) { rma_free(data); }

// Local-only pin of arbitrary caller memory (0 on failure).
uint64_t trpc_rma_reg(const void* buf, size_t len) {
  return rma_reg(buf, len);
}

int trpc_rma_unreg(uint64_t rkey) { return rma_unreg(rkey); }

// Live regions (tests).
size_t trpc_rma_region_count() { return rma_region_count(); }

// Runtime kernel-capability probe (base/proc.h): 1 supported, 0 not,
// -1 unknown feature.  "io_uring" records the ROADMAP item 2 gate —
// this box's 4.4.0 kernel answers ENOSYS.
int trpc_kernel_supports(const char* feature) {
  return kernel_supports(feature);
}

// Full-option channel creation including the transport: "tcp", "shm",
// "ici".  conn_type as trpc_channel_create_ex.
void* trpc_channel_create_transport(const char* addr, int64_t timeout_ms,
                                    const char* conn_type,
                                    const char* transport) {
  auto* ch = new Channel();
  Channel::Options opts;
  opts.timeout_ms = timeout_ms;
  const std::string tr = transport != nullptr ? transport : "tcp";
  opts.use_shm = tr == "shm";
  opts.use_ici = tr == "ici";
  if (conn_type != nullptr && conn_type[0] != '\0') {
    opts.connection_type = conn_type;
  }
  if (ch->Init(addr, &opts) != 0) {
    delete ch;
    return nullptr;
  }
  return ch;
}

}  // extern "C"
