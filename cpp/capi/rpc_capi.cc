// C ABI for the RPC runtime (Python ctypes binding surface).
//
// Handlers registered from Python are invoked on fiber stacks; ctypes
// callbacks re-acquire the GIL themselves.  Responses are completed via
// trpc_call_respond (sync or later — async handlers just stash the call
// handle).
#include <atomic>
#include <cstring>
#include <string>

#include "base/iobuf.h"
#include "fiber/event.h"
#include "base/flags.h"
#include "net/span.h"
#include "net/channel.h"
#include "net/cluster.h"
#include "net/controller.h"
#include "net/server.h"

using namespace trpc;

namespace {

struct PendingCall {
  Controller* cntl;
  IOBuf* response;
  Closure done;
  std::atomic<bool> responded{false};
};

using HandlerCb = void (*)(void* call_handle, const char* req, size_t req_len,
                           void* user_ctx);

}  // namespace

extern "C" {

// ---- server -------------------------------------------------------------

void* trpc_server_create() { return new Server(); }

void trpc_server_destroy(void* srv) { delete static_cast<Server*>(srv); }

int trpc_server_register(void* srv, const char* method, HandlerCb cb,
                         void* user_ctx) {
  return static_cast<Server*>(srv)->RegisterMethod(
      method, [cb, user_ctx](Controller* cntl, const IOBuf& req,
                             IOBuf* resp, Closure done) {
        auto* pending = new PendingCall();
        pending->cntl = cntl;
        pending->response = resp;
        pending->done = std::move(done);
        const std::string flat = req.to_string();
        cb(pending, flat.data(), flat.size(), user_ctx);
      });
}

// Completes a call (callable from the handler callback or any thread
// later).  Idempotent: a second respond on the same handle is ignored, so
// an async-handler/error-path race cannot double-complete.  err_text may be
// null.  Returns 0 if this call completed the RPC, -1 if already done.
int trpc_call_respond(void* call_handle, const char* data, size_t len,
                      int err_code, const char* err_text) {
  auto* pending = static_cast<PendingCall*>(call_handle);
  bool expect = false;
  if (!pending->responded.compare_exchange_strong(
          expect, true, std::memory_order_acq_rel)) {
    return -1;
  }
  if (err_code != 0) {
    pending->cntl->SetFailed(err_code, err_text != nullptr ? err_text : "");
  } else if (data != nullptr && len > 0) {
    pending->response->append(data, len);
  }
  pending->done();
  delete pending;
  return 0;
}

int trpc_server_start(void* srv, int port) {
  return static_cast<Server*>(srv)->Start(port);
}

int trpc_server_port(void* srv) { return static_cast<Server*>(srv)->port(); }

void trpc_server_stop(void* srv) { static_cast<Server*>(srv)->Stop(); }

// ---- single-server channel ---------------------------------------------

namespace {
void* create_channel(const char* addr, int64_t timeout_ms, bool use_shm,
                     const char* conn_type = nullptr) {
  auto* ch = new Channel();
  Channel::Options opts;
  opts.timeout_ms = timeout_ms;
  opts.use_shm = use_shm;
  if (conn_type != nullptr && conn_type[0] != '\0') {
    opts.connection_type = conn_type;
  }
  if (ch->Init(addr, &opts) != 0) {
    delete ch;
    return nullptr;
  }
  return ch;
}

// Flags register lazily from function-local statics (rpcz_enabled on its
// first check, per-method bounds at registration); a fresh process using
// ONLY the flag API would otherwise see "unknown flag".  Touch the static
// runtime flags here.
void ensure_runtime_flags() { rpcz_enabled(); }
}  // namespace

void* trpc_channel_create(const char* addr, int64_t timeout_ms) {
  return create_channel(addr, timeout_ms, false);
}

// Same-host shared-memory variant (falls back to TCP if the handshake
// fails; see net/shm_transport.h).
void* trpc_channel_create_shm(const char* addr, int64_t timeout_ms) {
  return create_channel(addr, timeout_ms, true);
}

// Full-option creation: conn_type "single"/"pooled"/"short"
// (socket_map.h matrix).  Returns nullptr on bad address/options.
void* trpc_channel_create_ex(const char* addr, int64_t timeout_ms,
                             const char* conn_type, int use_shm) {
  return create_channel(addr, timeout_ms, use_shm != 0, conn_type);
}

// Runtime flag access (base/flags.h; the /flags service's programmatic
// form).  Returns 0 on success (set) / found (get).
int trpc_flag_set(const char* name, const char* value) {
  ensure_runtime_flags();
  return Flag::set(name, value);
}

// Returns 0 on success, -1 unknown flag, -2 when the value does not fit
// (nothing written in that case; also guards degenerate buffers).
int trpc_flag_get(const char* name, char* out, size_t out_len) {
  ensure_runtime_flags();
  Flag* f = Flag::find(name);
  if (f == nullptr) {
    return -1;
  }
  const std::string v = f->value_string();
  if (out == nullptr || out_len == 0 || v.size() + 1 > out_len) {
    return -2;
  }
  memcpy(out, v.c_str(), v.size() + 1);
  return 0;
}

// Copies the live transport name ("tcp", "shm_ring", "" if unconnected).
void trpc_channel_transport(void* ch, char* out, size_t out_len) {
  const std::string name = static_cast<Channel*>(ch)->transport_name();
  strncpy(out, name.c_str(), out_len - 1);
  out[out_len - 1] = '\0';
}

void trpc_channel_destroy(void* ch) { delete static_cast<Channel*>(ch); }

// Synchronous call.  Returns 0 on success and fills *resp (a trpc_iobuf
// handle created by the caller); on failure returns the error code and
// copies the error text into err_buf.
namespace {
int call_channel_sync(void* ch, const char* method, const IOBuf& request,
                      void* resp_iobuf, int64_t timeout_ms, char* err_buf,
                      size_t err_buf_len) {
  // GIL safety: a ctypes caller must return on the pthread it entered on,
  // so any park inside the sync call blocks the thread, never migrates.
  ScopedPthreadWait pin;
  Controller cntl;
  if (timeout_ms > 0) {
    cntl.set_timeout_ms(timeout_ms);
  }
  static_cast<Channel*>(ch)->CallMethod(
      method, request, static_cast<IOBuf*>(resp_iobuf), &cntl);
  if (cntl.Failed()) {
    if (err_buf != nullptr && err_buf_len > 0) {
      strncpy(err_buf, cntl.error_text().c_str(), err_buf_len - 1);
      err_buf[err_buf_len - 1] = '\0';
    }
    return cntl.error_code() != 0 ? cntl.error_code() : -1;
  }
  return 0;
}
}  // namespace

int trpc_channel_call(void* ch, const char* method, const char* req,
                      size_t req_len, void* resp_iobuf, int64_t timeout_ms,
                      char* err_buf, size_t err_buf_len) {
  IOBuf request;
  request.append(req, req_len);
  return call_channel_sync(ch, method, request, resp_iobuf, timeout_ms,
                           err_buf, err_buf_len);
}

// IOBuf-request variant: the request IOBuf handle is used as-is (no
// flattening/copy — arena blocks ride straight to the wire).  The handle
// remains caller-owned; its payload is shared, not consumed.
int trpc_channel_call_buf(void* ch, const char* method, void* req_iobuf,
                          void* resp_iobuf, int64_t timeout_ms,
                          char* err_buf, size_t err_buf_len) {
  return call_channel_sync(ch, method, *static_cast<IOBuf*>(req_iobuf),
                           resp_iobuf, timeout_ms, err_buf, err_buf_len);
}

// ---- cluster channel ----------------------------------------------------

void* trpc_cluster_create(const char* naming_url, const char* lb,
                          int64_t timeout_ms, int max_retry) {
  auto* ch = new ClusterChannel();
  ClusterChannel::Options opts;
  opts.timeout_ms = timeout_ms;
  opts.max_retry = max_retry;
  if (ch->Init(naming_url, lb, &opts) != 0) {
    delete ch;
    return nullptr;
  }
  return ch;
}

void trpc_cluster_destroy(void* ch) {
  delete static_cast<ClusterChannel*>(ch);
}

int trpc_cluster_call(void* ch, const char* method, const char* req,
                      size_t req_len, void* resp_iobuf, uint64_t hash_key,
                      char* err_buf, size_t err_buf_len) {
  ScopedPthreadWait pin;  // see trpc_channel_call
  Controller cntl;
  IOBuf request;
  request.append(req, req_len);
  static_cast<ClusterChannel*>(ch)->CallMethod(
      method, request, static_cast<IOBuf*>(resp_iobuf), &cntl, nullptr,
      hash_key);
  if (cntl.Failed()) {
    if (err_buf != nullptr && err_buf_len > 0) {
      strncpy(err_buf, cntl.error_text().c_str(), err_buf_len - 1);
      err_buf[err_buf_len - 1] = '\0';
    }
    return cntl.error_code() != 0 ? cntl.error_code() : -1;
  }
  return 0;
}

}  // extern "C"
