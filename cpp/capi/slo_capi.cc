// C ABI for the SLO / fleet observability plane (stat/slo.h,
// stat/digest.h, net/naming.h fleet publication) — the Python surface of
// /slo, /fleet and the digest-wire blobs fleet_top.py merges.
#include <cstring>
#include <string>

#include "base/time.h"
#include "capi/capi_util.h"
#include "net/naming.h"
#include "net/server.h"
#include "stat/digest.h"
#include "stat/slo.h"

using namespace trpc;

extern "C" {

// Per-tenant SLO spec (Server::SetSlo; stat/slo.h grammar, e.g.
// "tenantA:p99_us=2000,avail=99.9;*:p99_us=10000").  "" removes.
// Returns 0, -1 on a malformed spec or a running server.
int trpc_server_set_slo(void* srv, const char* spec) {
  return static_cast<Server*>(srv)->SetSlo(spec != nullptr ? spec : "");
}

// /slo JSON for this server's engine (copy_out contract: returns the
// full length; re-call with a bigger buffer when ret >= out_len).
size_t trpc_slo_dump(void* srv, char* out, size_t out_len) {
  auto slo = static_cast<Server*>(srv)->slo_engine();
  const std::string body =
      slo != nullptr ? slo->dump_json()
                     : std::string("{\"enabled\":") +
                           (slo::enabled() ? "true" : "false") +
                           ",\"tenants\":[]}";
  return capi::copy_out(body, out, out_len);
}

// This node's fleet publication blob (digest-wire 2, binary — the exact
// bytes the Announcer publishes).  Empty ("" → returns 0) without an
// engine.  copy_out contract; the blob is binary, so callers slice
// out[:ret] instead of reading to the NUL.
size_t trpc_fleet_blob(void* srv, char* out, size_t out_len) {
  auto slo = static_cast<Server*>(srv)->slo_engine();
  if (slo == nullptr) {
    return capi::copy_out(std::string(), out, out_len);
  }
  return capi::copy_out(slo->encode_blob(realtime_us()), out, out_len);
}

// Fleet-wide merged JSON over the LOCAL naming registry (the /fleet
// builtin's body; copy_out contract).
size_t trpc_fleet_dump(const char* service, char* out, size_t out_len) {
  return capi::copy_out(
      fleet_dump_json(service != nullptr ? service : "fleet"), out,
      out_len);
}

// One relaxed load of the trpc_slo switch (flag-off invisibility tests).
int trpc_slo_enabled() { return slo::enabled() ? 1 : 0; }

// Lifetime breach edges across all engines (slo_breach_total).
uint64_t trpc_slo_breach_total() { return slo::breach_total(); }

}  // extern "C"
