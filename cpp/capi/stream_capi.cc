// C ABI for the streaming plane (net/stream.h) — ordered byte-chunk
// streams with credit flow control, surfaced to Python as
// brpc_tpu/rpc/stream.py.
//
// A handle wraps a queue-backed CStream: the C++ on_message callback
// (consume fiber) enqueues chunks and notifies; trpc_stream_read blocks
// the calling pthread on a plain condition variable (ctypes releases the
// GIL), so Python readers never touch fiber primitives.  The handle is a
// heap shared_ptr holder — the stream's callbacks keep their own
// reference, so a destroy racing a late consume batch can never free the
// queue under the consumer.
#include <chrono>
#include <condition_variable>
#include <cstring>
#include <deque>
#include <memory>
#include <mutex>
#include <string>

#include "base/iobuf.h"
#include "capi/capi_util.h"
#include "fiber/fiber.h"
#include "net/channel.h"
#include "net/controller.h"
#include "net/stream.h"

using namespace trpc;

namespace trpc {
Controller* trpc_internal_pending_controller(void* call_handle);
}

namespace {

struct CStream {
  StreamId sid = 0;
  std::mutex mu;
  std::condition_variable cv;
  std::deque<std::string> chunks;
  bool closed = false;
};

using CStreamPtr = std::shared_ptr<CStream>;

// The handle Python holds: a heap shared_ptr (callbacks hold siblings).
CStreamPtr& of(void* h) { return *static_cast<CStreamPtr*>(h); }

StreamOptions options_for(const CStreamPtr& cs, int64_t window_bytes) {
  StreamOptions opts;
  if (window_bytes > 0) {
    opts.window_bytes = window_bytes;
  }
  opts.on_message = [cs](StreamId, IOBuf&& chunk) {
    std::string bytes = chunk.to_string();
    {
      std::lock_guard<std::mutex> g(cs->mu);
      cs->chunks.push_back(std::move(bytes));
    }
    cs->cv.notify_all();
  };
  opts.on_closed = [cs](StreamId) {
    {
      std::lock_guard<std::mutex> g(cs->mu);
      cs->closed = true;
    }
    cs->cv.notify_all();
  };
  return opts;
}

}  // namespace

extern "C" {

// Client side: offer a stream on `method`'s request and return the
// established stream handle.  The RPC runs synchronously; *resp_iobuf
// (a trpc_iobuf handle) receives the response body.  On failure returns
// NULL with *err_code / err_buf filled (the offered stream is destroyed
// by the failed-call path).  tenant/priority override the channel's QoS
// default when tenant is non-empty.
void* trpc_stream_open(void* ch, const char* method, const char* req,
                       size_t req_len, int64_t timeout_ms,
                       int64_t window_bytes, const char* tenant,
                       int priority, void* resp_iobuf, int* err_code,
                       char* err_buf, size_t err_buf_len) {
  ScopedPthreadWait pin;  // sync CallMethod parks; see trpc_channel_call
  auto cs = std::make_shared<CStream>();
  Controller cntl;
  if (timeout_ms > 0) {
    cntl.set_timeout_ms(timeout_ms);
  }
  if (tenant != nullptr && tenant[0] != '\0') {
    cntl.set_qos(tenant, static_cast<uint8_t>(priority));
  }
  StreamId sid = 0;
  if (StreamCreate(&sid, &cntl, options_for(cs, window_bytes)) != 0) {
    if (err_code != nullptr) {
      *err_code = ENOMEM;
    }
    return nullptr;
  }
  cs->sid = sid;
  IOBuf request;
  if (req != nullptr && req_len > 0) {
    request.append(req, req_len);
  }
  static_cast<Channel*>(ch)->CallMethod(
      method, request, static_cast<IOBuf*>(resp_iobuf), &cntl);
  if (cntl.Failed()) {
    if (err_code != nullptr) {
      *err_code = cntl.error_code() != 0 ? cntl.error_code() : -1;
    }
    if (err_buf != nullptr && err_buf_len > 0) {
      strncpy(err_buf, cntl.error_text().c_str(), err_buf_len - 1);
      err_buf[err_buf_len - 1] = '\0';
    }
    // The failed-call path already closed the offered stream; the
    // callbacks' shared_ptr unwinds with the stream options.
    return nullptr;
  }
  if (err_code != nullptr) {
    *err_code = 0;
  }
  return new CStreamPtr(std::move(cs));
}

// Server side: accept the stream offered by the request behind an
// in-flight call handle (brpc_tpu server thunk).  Must be called BEFORE
// trpc_call_respond.  NULL when the request offered no stream.
void* trpc_call_stream_accept(void* call_handle, int64_t window_bytes) {
  Controller* cntl = trpc_internal_pending_controller(call_handle);
  auto cs = std::make_shared<CStream>();
  StreamId sid = 0;
  if (StreamAccept(&sid, cntl, options_for(cs, window_bytes)) != 0) {
    return nullptr;
  }
  cs->sid = sid;
  return new CStreamPtr(std::move(cs));
}

// Blocking read of ONE chunk: returns the chunk's length (always <=
// `cap` — the chunk is copied whole or not at all), -1 when the stream
// is closed and drained, -2 on timeout (timeout_ms < 0 waits forever),
// -3 when the next chunk is LARGER than `cap`.  A -3 chunk stays queued
// and nothing is consumed: query trpc_stream_next_len and retry with a
// buffer that fits — silent truncation would desynchronize framed
// readers (e.g. fixed-size TokenRecord streams) without any error.
long trpc_stream_read(void* h, char* buf, size_t cap, int64_t timeout_ms) {
  const CStreamPtr& cs = of(h);
  std::unique_lock<std::mutex> g(cs->mu);
  const bool wait_forever = timeout_ms < 0;
  auto ready = [&cs] { return !cs->chunks.empty() || cs->closed; };
  if (wait_forever) {
    cs->cv.wait(g, ready);
  } else if (!cs->cv.wait_for(g, std::chrono::milliseconds(timeout_ms),
                              ready)) {
    return -2;
  }
  if (cs->chunks.empty()) {
    return -1;  // closed and drained
  }
  if (cs->chunks.front().size() > cap) {
    return -3;  // caller's buffer too small; chunk left queued
  }
  std::string chunk = std::move(cs->chunks.front());
  cs->chunks.pop_front();
  g.unlock();
  if (buf != nullptr && !chunk.empty()) {
    memcpy(buf, chunk.data(), chunk.size());
  }
  return static_cast<long>(chunk.size());
}

// Length of the next buffered chunk (bytes), -1 when none is buffered.
// Pairs with a -3 read: resize and retry without losing the chunk.
long trpc_stream_next_len(void* h) {
  const CStreamPtr& cs = of(h);
  std::lock_guard<std::mutex> g(cs->mu);
  return cs->chunks.empty() ? -1
                            : static_cast<long>(cs->chunks.front().size());
}

// Ordered write; parks while the peer's credit window is exhausted.
// Returns 0, EPIPE (closed / connection dead), EINVAL (gone).
int trpc_stream_write(void* h, const char* data, size_t len) {
  ScopedPthreadWait pin;  // StreamWrite parks on the credit window
  const CStreamPtr& cs = of(h);
  IOBuf chunk;
  if (data != nullptr && len > 0) {
    chunk.append(data, len);
  }
  return StreamWrite(cs->sid, std::move(chunk));
}

// Graceful close of the local end.  Buffered chunks stay readable; reads
// return -1 once drained.  Idempotent.
int trpc_stream_close(void* h) {
  const CStreamPtr& cs = of(h);
  {
    std::lock_guard<std::mutex> g(cs->mu);
    if (cs->closed && !StreamExists(cs->sid)) {
      return 0;
    }
  }
  return StreamClose(cs->sid);
}

// Close (if still open) and free the handle.  The stream's callbacks
// hold their own reference, so a consume batch mid-delivery finishes
// against live memory.
void trpc_stream_destroy(void* h) {
  if (h == nullptr) {
    return;
  }
  trpc_stream_close(h);
  delete static_cast<CStreamPtr*>(h);
}

unsigned long long trpc_stream_id(void* h) {
  return static_cast<unsigned long long>(of(h)->sid);
}

// Chunks currently buffered client-side (observability / tests).
size_t trpc_stream_pending(void* h) {
  const CStreamPtr& cs = of(h);
  std::lock_guard<std::mutex> g(cs->mu);
  return cs->chunks.size();
}

}  // extern "C"
