// C ABI for the self-tuning controller (stat/tuner.h) and the flag
// introspection surface it rides on (Python ctypes binding surface —
// brpc_tpu/rpc/tuner.py and observe.py flags()).
//
// Buffer protocol: capi/capi_util.h copy_out — dump calls return the
// FULL byte length; a caller seeing ret >= out_len re-calls bigger.
#include <cstdint>
#include <string>

#include "base/flags.h"
#include "capi/capi_util.h"
#include "net/server.h"
#include "stat/tuner.h"

using namespace trpc;
using trpc::capi::copy_out;

extern "C" {

// ---- flag introspection --------------------------------------------------

// Every runtime flag as a JSON array of {"name", "type", "value",
// "default", "reloadable"} plus "min"/"max" where bounds were declared
// (base/flags.h set_int_range / set_bounds_hint) — the same body
// /flags?format=json serves.  Tools read bounds from here instead of
// guessing, so out-of-range actuation is impossible by construction.
size_t trpc_flags_dump(char* out, size_t out_len) {
  return copy_out(Flag::dump_json(), out, out_len);
}

// ---- tuner ---------------------------------------------------------------

// 1 while the trpc_tuner flag is on (the control loop is ticking).
int trpc_tuner_enabled() {
  tuner::ensure_registered();
  return tuner::enabled() ? 1 : 0;
}

// The /tuner body, in-process: {"enabled", counters, "rules", "inputs",
// "decisions" (newest `limit`, oldest first)}.  Served even while the
// tuner is off — the journal may hold decisions from an earlier
// enabled window.
size_t trpc_tuner_dump(size_t limit, char* out, size_t out_len) {
  if (limit == 0 || limit > 512) {
    limit = limit == 0 ? 128 : 512;  // journal ring cap
  }
  return copy_out(tuner::dump_json(limit), out, out_len);
}

// Lifetime counters (the tuner_* vars, one crossing).
void trpc_tuner_counters(uint64_t* ticks, uint64_t* decisions,
                         uint64_t* reverts, uint64_t* freezes) {
  if (ticks != nullptr) {
    *ticks = tuner::ticks_total();
  }
  if (decisions != nullptr) {
    *decisions = tuner::decisions_total();
  }
  if (reverts != nullptr) {
    *reverts = tuner::reverts_total();
  }
  if (freezes != nullptr) {
    *freezes = tuner::freezes_total();
  }
}

// Attach point: registers the tuner flags/vars and flips trpc_tuner on
// for this process (Server::EnableTuner — the embedder's one-liner).
// Returns 0 on success.
int trpc_server_enable_tuner(void* srv) {
  if (srv == nullptr) {
    return -1;
  }
  return static_cast<Server*>(srv)->EnableTuner() ? 0 : -1;
}

// Test support: clears rules/state/journal/counters (flag must be off).
void trpc_tuner_reset() { tuner::reset_for_test(); }

}  // extern "C"
