// auth — connection-level authentication: the client's credential rides
// the connection's FIRST frame; the server verifies once and gates every
// later request (parity: example/echo_c++ + Authenticator;
// the HTTP/h2/redis paths carry the same credential differently — see
// net/auth.h).
//
// Run: ./build/example_auth
#include <cstdio>
#include <string>

#include "net/auth.h"
#include "net/channel.h"
#include "net/server.h"

using namespace trpc;

namespace {

// A toy shared-secret authenticator; real deployments would wrap
// mTLS identities or signed tokens in the same two hooks.
class TokenAuth : public Authenticator {
 public:
  explicit TokenAuth(std::string token) : token_(std::move(token)) {}
  int generate_credential(std::string* out) const override {
    *out = token_;
    return 0;
  }
  int verify_credential(const std::string& cred,
                        const EndPoint& peer) const override {
    (void)peer;  // real policies may also pin peer addresses
    return cred == token_ ? 0 : -1;
  }

 private:
  std::string token_;
};

}  // namespace

int main() {
  TokenAuth good("open-sesame");
  TokenAuth bad("wrong-token");

  Server server;
  server.set_authenticator(&good);
  server.RegisterMethod("Vault.Read", [](Controller*, const IOBuf&,
                                         IOBuf* resp, Closure done) {
    resp->append("secret-contents");
    done();
  });
  if (server.Start(0) != 0) {
    return 1;
  }
  const std::string addr = "127.0.0.1:" + std::to_string(server.port());

  {  // Correct credential: calls flow.
    Channel ch;
    Channel::Options opts;
    opts.auth = &good;
    ch.Init(addr, &opts);
    Controller cntl;
    IOBuf req, resp;
    ch.CallMethod("Vault.Read", req, &resp, &cntl);
    printf("authorized client : %s\n",
           cntl.Failed() ? cntl.error_text().c_str()
                         : resp.to_string().c_str());
    if (cntl.Failed()) {
      return 1;
    }
  }
  {  // Wrong credential: the server rejects the connection.
    Channel ch;
    Channel::Options opts;
    opts.auth = &bad;
    opts.timeout_ms = 500;
    ch.Init(addr, &opts);
    Controller cntl;
    IOBuf req, resp;
    ch.CallMethod("Vault.Read", req, &resp, &cntl);
    printf("wrong credential  : %s\n",
           cntl.Failed() ? "rejected (as it must be)" : "UNEXPECTED OK");
    if (!cntl.Failed()) {
      return 1;
    }
  }
  {  // No credential at all: EACCES before the handler runs.
    Channel ch;
    Channel::Options opts;
    opts.timeout_ms = 500;
    ch.Init(addr, &opts);
    Controller cntl;
    IOBuf req, resp;
    ch.CallMethod("Vault.Read", req, &resp, &cntl);
    printf("anonymous client  : %s\n",
           cntl.Failed() ? "rejected (as it must be)" : "UNEXPECTED OK");
    if (!cntl.Failed()) {
      return 1;
    }
  }
  printf("ok\n");
  return 0;
}
