// auto_concurrency_limiter — adaptive per-method admission control
// (parity: example/auto_concurrency_limiter; policy/
// auto_concurrency_limiter.cpp).  Three limiter kinds are registered per
// method via Server::SetMethodMaxConcurrency:
//   "<N>"          constant bound
//   "auto"         AIMD on latency vs the no-load EMA
//   "timeout:<ms>" queueing estimate (inflight x avg latency) vs budget
// Overload answers kELimit (2004) instantly instead of queueing to death.
//
// Run: ./build/example_auto_concurrency_limiter
#include <atomic>
#include <cstdio>
#include <vector>

#include "fiber/fiber.h"
#include "fiber/sync.h"
#include "net/channel.h"
#include "net/concurrency_limiter.h"
#include "net/server.h"

using namespace trpc;

namespace {

Channel* g_ch = nullptr;
std::atomic<int> g_ok{0}, g_limited{0};
CountdownEvent* g_done = nullptr;

void caller(void*) {
  Controller cntl;
  cntl.set_timeout_ms(5000);
  IOBuf req, resp;
  req.append("work");
  g_ch->CallMethod("Svc.Slow", req, &resp, &cntl);
  if (!cntl.Failed()) {
    g_ok.fetch_add(1);
  } else if (cntl.error_code() == kELimit) {
    g_limited.fetch_add(1);
  }
  g_done->signal();
}

}  // namespace

int main() {
  Server server;
  server.RegisterMethod("Svc.Slow", [](Controller*, const IOBuf& req,
                                       IOBuf* resp, Closure done) {
    fiber_sleep_us(50 * 1000);  // 50ms of "work"
    resp->append(req);
    done();
  });
  // The adaptive limiter: the limit grows while latency holds near the
  // no-load EMA and backs off multiplicatively once queueing inflates it.
  if (server.SetMethodMaxConcurrency("Svc.Slow", "auto") != 0) {
    return 1;
  }
  if (server.Start(0) != 0) {
    return 1;
  }
  Channel ch;
  if (ch.Init("127.0.0.1:" + std::to_string(server.port())) != 0) {
    return 1;
  }
  g_ch = &ch;

  // A burst far beyond capacity (the AIMD limit starts at 64): some calls
  // run, the pile-up is shed with kELimit instantly (no timeout agony).
  const int kBurst = 150;
  CountdownEvent done(kBurst);
  g_done = &done;
  std::vector<fiber_t> fids(kBurst);
  for (auto& f : fids) {
    fiber_start(&f, &caller, nullptr);
  }
  done.wait(-1);
  printf("burst of %d: %d served, %d shed with ELIMIT\n", kBurst,
         g_ok.load(), g_limited.load());
  if (g_ok.load() + g_limited.load() != kBurst || g_ok.load() == 0) {
    return 1;
  }
  printf("ok\n");
  return 0;
}
