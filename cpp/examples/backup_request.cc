// backup_request — hedging: when the first attempt is slow, a backup
// races it on another node and the first success wins (parity:
// example/backup_request_c++; ClusterChannel::Options::backup_request_ms).
//
// Run: ./build/example_backup_request
#include <cstdio>

#include "fiber/fiber.h"
#include "net/cluster.h"
#include "net/server.h"

using namespace trpc;

int main() {
  // One pathologically slow node, one fast node.
  Server slow, fast;
  slow.RegisterMethod("B.Get", [](Controller*, const IOBuf&, IOBuf* resp,
                                  Closure done) {
    fiber_sleep_us(300 * 1000);  // 300ms: way past the hedge budget
    resp->append("slow");
    done();
  });
  fast.RegisterMethod("B.Get", [](Controller*, const IOBuf&, IOBuf* resp,
                                  Closure done) {
    resp->append("fast");
    done();
  });
  if (slow.Start(0) != 0 || fast.Start(0) != 0) {
    return 1;
  }

  ClusterChannel cluster;
  ClusterChannel::Options opts;
  opts.timeout_ms = 2000;
  // If an attempt hasn't answered within 30ms, hedge to another node.
  opts.backup_request_ms = 30;
  const std::string url = "list://127.0.0.1:" + std::to_string(slow.port()) +
                          ",127.0.0.1:" + std::to_string(fast.port());
  if (cluster.Init(url, "rr", &opts) != 0) {
    return 1;
  }

  int hedged_wins = 0;
  for (int i = 0; i < 8; ++i) {
    Controller cntl;
    IOBuf req, resp;
    req.append("x");
    cluster.CallMethod("B.Get", req, &resp, &cntl);
    if (cntl.Failed()) {
      fprintf(stderr, "call failed: %s\n", cntl.error_text().c_str());
      return 1;
    }
    // Every call answers fast: whichever attempt hit the slow node was
    // outraced by its backup.
    if (cntl.latency_us() < 200 * 1000) {
      ++hedged_wins;
    }
    printf("call %d → %s in %lld us\n", i, resp.to_string().c_str(),
           static_cast<long long>(cntl.latency_us()));
  }
  printf("%d/8 calls beat the slow node via hedging\n", hedged_wins);
  return hedged_wins == 8 ? 0 : 1;
}
