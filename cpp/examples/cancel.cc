// cancel — cancelling an in-flight RPC (parity: example/cancel_c++).
//
// A call's CallId (Controller::call_id()) can be stashed and cancelled
// from any thread or fiber, before or after the call completes: the
// versioned fid makes a late cancel a harmless no-op, and an effective
// one completes the call exactly once with ECANCELED (waking sync
// joiners, running the async done, cancelling the timeout timer).
//
// Build: cmake --build build --target example_cancel
// Run:   ./build/example_cancel
#include <errno.h>

#include <cstdio>

#include "fiber/fiber.h"
#include "fiber/sync.h"
#include "net/channel.h"
#include "net/server.h"

using namespace trpc;

int main() {
  Server server;
  // A deliberately slow handler: parks its fiber for 2s before replying.
  server.RegisterMethod("Sleep.Sleep", [](Controller*, const IOBuf& req,
                                          IOBuf* resp, Closure done) {
    fiber_sleep_us(2 * 1000 * 1000);
    resp->append(req);
    done();
  });
  server.RegisterMethod("Echo.Echo", [](Controller*, const IOBuf& req,
                                        IOBuf* resp, Closure done) {
    resp->append(req);
    done();
  });
  if (server.Start(0) != 0) {
    fprintf(stderr, "start failed\n");
    return 1;
  }
  Channel channel;
  if (channel.Init("127.0.0.1:" + std::to_string(server.port())) != 0) {
    return 1;
  }

  // 1. Async call cancelled mid-flight: done runs promptly with ECANCELED
  // instead of waiting out the 2s handler (or the 10s timeout).
  {
    Controller cntl;
    cntl.set_timeout_ms(10 * 1000);
    IOBuf request, response;
    request.append("will be cancelled");
    CountdownEvent finished(1);
    channel.CallMethod("Sleep.Sleep", request, &response, &cntl,
                       [&finished] { finished.signal(); });
    const fid_t id = cntl.call_id();  // stashable, thread-safe handle
    printf("issued call %llx; cancelling...\n",
           static_cast<unsigned long long>(id));
    StartCancel(id);  // equivalently: cntl.StartCancel()
    finished.wait(-1);
    printf("async call completed: %s (code %d, %lld us)\n",
           cntl.error_text().c_str(), cntl.error_code(),
           static_cast<long long>(cntl.latency_us()));
    if (cntl.error_code() != ECANCELED) {
      return 1;
    }
  }

  // 2. Cancel AFTER completion is a no-op: the fid version moved on.
  {
    Controller cntl;
    cntl.set_timeout_ms(10 * 1000);
    IOBuf request, response;
    request.append("fast");
    channel.CallMethod("Echo.Echo", request, &response, &cntl);
    const fid_t stale = cntl.call_id();
    StartCancel(stale);  // harmless
    printf("stale cancel ignored; response intact: %s\n",
           response.to_string().c_str());
    if (cntl.Failed()) {
      return 1;
    }
  }
  printf("ok\n");
  return 0;
}
