// cluster_lb — naming + load balancing + health checking in one place:
// a ClusterChannel resolves "list://" nodes, spreads calls with the
// locality-aware balancer, routes around a killed node via the circuit
// breaker, and revives it on recovery (parity: example/load_balancer +
// the lalb docs).
//
// Run: ./build/example_cluster_lb
#include <atomic>
#include <cstdio>
#include <string>

#include "fiber/fiber.h"
#include "net/cluster.h"
#include "net/server.h"

using namespace trpc;

int main() {
  static std::atomic<int> hits[3];
  static std::atomic<int64_t> delay_us[3];
  Server nodes[3];
  for (int i = 0; i < 3; ++i) {
    nodes[i].RegisterMethod("LB.Hit", [i](Controller*, const IOBuf&,
                                          IOBuf* resp, Closure done) {
      hits[i].fetch_add(1);
      if (delay_us[i].load() > 0) {
        fiber_sleep_us(delay_us[i].load());
      }
      resp->append("node-" + std::to_string(i));
      done();
    });
    if (nodes[i].Start(0) != 0) {
      return 1;
    }
  }
  std::string url = "list://";
  for (int i = 0; i < 3; ++i) {
    url += "127.0.0.1:" + std::to_string(nodes[i].port()) +
           (i < 2 ? "," : "");
  }

  ClusterChannel cluster;
  ClusterChannel::Options opts;
  opts.timeout_ms = 1000;
  // "la": weighted random over expected quality (inverse EWMA latency x
  // load, with error deceleration).  Also available: rr, random, wrr,
  // p2c, c_hash.
  if (cluster.Init(url, "la", &opts) != 0) {
    return 1;
  }

  auto run = [&](int n) {
    for (int i = 0; i < n; ++i) {
      Controller cntl;
      IOBuf req, resp;
      req.append("x");
      cluster.CallMethod("LB.Hit", req, &resp, &cntl);
    }
  };

  run(150);
  printf("healthy spread : %d / %d / %d\n", hits[0].load(), hits[1].load(),
         hits[2].load());

  // Degrade node 1: the balancer sheds its share within a few calls.
  delay_us[1].store(10 * 1000);
  for (auto& h : hits) {
    h.store(0);
  }
  run(150);
  printf("node1 degraded : %d / %d / %d (node1 shed)\n", hits[0].load(),
         hits[1].load(), hits[2].load());

  // Recover: probes re-earn the share (asymmetric EWMA heals fast).
  delay_us[1].store(0);
  run(200);
  for (auto& h : hits) {
    h.store(0);
  }
  run(150);
  printf("node1 healed   : %d / %d / %d (share back)\n", hits[0].load(),
         hits[1].load(), hits[2].load());
  printf("ok\n");
  return 0;
}
