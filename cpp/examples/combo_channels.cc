// combo_channels — the four declarative composition channels in one
// walkthrough: ParallelChannel (scatter/gather), SelectiveChannel
// (failover), PartitionChannel (shard one request), and
// DynamicPartitionChannel (coexisting partition schemes with live
// capacity feedback).  Parity: example/parallel_echo_c++,
// selective_echo_c++, partition_echo_c++, dynamic_partition_echo_c++.
//
// Run: ./build/example_combo_channels
#include <cstdio>
#include <memory>
#include <vector>

#include "net/combo.h"
#include "net/server.h"

using namespace trpc;

namespace {

std::shared_ptr<SubChannel> sub_for(int port) {
  auto ch = std::make_shared<Channel>();
  ch->Init("127.0.0.1:" + std::to_string(port));
  return make_sub_channel(ch);
}

std::vector<IOBuf> even_split(const IOBuf& req, size_t n) {
  std::vector<IOBuf> parts(n);
  IOBuf rest = req;
  const size_t per = req.size() / n;
  for (size_t i = 0; i + 1 < n; ++i) {
    rest.cutn(&parts[i], per);
  }
  parts[n - 1] = std::move(rest);
  return parts;
}

}  // namespace

int main() {
  // Three backend shards, each tagging responses with its index.
  Server nodes[3];
  for (int i = 0; i < 3; ++i) {
    nodes[i].RegisterMethod("Svc.Work", [i](Controller*, const IOBuf& req,
                                            IOBuf* resp, Closure done) {
      resp->append("[" + std::to_string(i) + ":" + req.to_string() + "]");
      done();
    });
    if (nodes[i].Start(0) != 0) {
      return 1;
    }
  }

  // ParallelChannel: broadcast, wait for all, merge (fail_limit lets a
  // bounded number of subs fail without failing the call).
  {
    ParallelChannel pch;
    for (auto& n : nodes) {
      pch.add_sub_channel(sub_for(n.port()));
    }
    Controller cntl;
    IOBuf req, resp;
    req.append("fanout");
    pch.CallMethod("Svc.Work", req, &resp, &cntl);
    printf("parallel : %s\n", resp.to_string().c_str());
  }

  // SelectiveChannel: one sub per call, failing over to the next.
  {
    SelectiveChannel sch;
    for (auto& n : nodes) {
      sch.add_sub_channel(sub_for(n.port()));
    }
    Controller cntl;
    IOBuf req, resp;
    req.append("pick-one");
    sch.CallMethod("Svc.Work", req, &resp, &cntl, /*max_failover=*/1);
    printf("selective: %s\n", resp.to_string().c_str());
  }

  // PartitionChannel: ONE logical request sharded across all subs.
  {
    PartitionChannel pch;
    for (auto& n : nodes) {
      pch.add_partition(sub_for(n.port()));
    }
    Controller cntl;
    IOBuf req, resp;
    req.append("abcdefghi");  // 9 bytes → 3 per partition
    pch.CallMethod("Svc.Work", req, &resp, &cntl, even_split);
    printf("partition: %s\n", resp.to_string().c_str());
  }

  // DynamicPartitionChannel: a 1-way and a 3-way scheme coexist (as
  // during resharding); calls pick a scheme by capacity, corrected live
  // by observed latency/errors.
  {
    DynamicPartitionChannel dyn;
    dyn.add_scheme({sub_for(nodes[0].port())});
    dyn.add_scheme({sub_for(nodes[0].port()), sub_for(nodes[1].port()),
                    sub_for(nodes[2].port())});
    for (int i = 0; i < 8; ++i) {
      Controller cntl;
      IOBuf req, resp;
      req.append("dynamic-req");
      dyn.CallMethod("Svc.Work", req, &resp, &cntl, even_split);
      if (cntl.Failed()) {
        return 1;
      }
    }
    printf("dynpart  : weights now 1-way=%lld 3-way=%lld\n",
           static_cast<long long>(dyn.scheme_weight(0)),
           static_cast<long long>(dyn.scheme_weight(1)));
  }
  printf("ok\n");
  return 0;
}
