// compression — per-call compression negotiation: the same method
// called with gzip, zlib, and snappy request bodies, responses come
// back compressed symmetrically (parity: example/echo_c++ --gzip).
//
// Build: cmake --build build --target example_compression
#include <cstdio>

#include "base/compress.h"
#include "net/channel.h"
#include "net/server.h"

using namespace trpc;

int main() {
  Server server;
  server.RegisterMethod("Echo.Echo", [](Controller*, const IOBuf& req,
                                        IOBuf* resp, Closure done) {
    resp->append(req);  // handlers see PLAINTEXT either way
    done();
  });
  if (server.Start(0) != 0) {
    return 1;
  }
  Channel ch;
  if (ch.Init("127.0.0.1:" + std::to_string(server.port())) != 0) {
    return 1;
  }
  // Compressible payload: ~1MB of repetitive text.
  std::string body;
  for (int i = 0; i < 20000; ++i) {
    body += "all work and no play makes a dull payload ";
  }
  struct {
    CompressType type;
    const char* name;
  } algos[] = {{CompressType::kGzip, "gzip"},
               {CompressType::kZlib, "zlib"},
               {CompressType::kSnappy, "snappy"}};
  for (const auto& algo : algos) {
    // Wire-size preview via the registry (what the meta negotiates).
    IOBuf plain, squeezed;
    plain.append(body);
    find_compressor(algo.type)->compress(plain, &squeezed);
    Controller cntl;
    cntl.set_timeout_ms(5000);
    cntl.set_request_compress_type(static_cast<uint8_t>(algo.type));
    cntl.set_enable_checksum(true);  // crc32c over the wire bytes too
    IOBuf req, resp;
    req.append(body);
    ch.CallMethod("Echo.Echo", req, &resp, &cntl);
    if (cntl.Failed() || resp.to_string() != body) {
      fprintf(stderr, "%s roundtrip failed\n", algo.name);
      return 1;
    }
    printf("%-6s  %zu -> %zu bytes (%.1f%%), roundtrip ok\n", algo.name,
           body.size(), squeezed.size(),
           100.0 * squeezed.size() / body.size());
  }
  server.Stop();
  server.Join();
  printf("ok\n");
  return 0;
}
