// coroutine_echo — user code as C++20 co_await chains over the fiber
// runtime (parity: example/coroutine; fiber/coroutine.h).
//
// Build: cmake --build build --target example_coroutine_echo
// Run:   ./build/example_coroutine_echo
#include <cstdio>

#include "fiber/coroutine.h"
#include "net/channel.h"
#include "net/server.h"

using namespace trpc;

namespace {

CoTask<std::string> pipeline(Channel* ch, std::string seed) {
  // Three sequential RPCs, written linearly; each co_await parks the
  // coroutine (not a worker) until the response lands.
  for (int i = 0; i < 3; ++i) {
    Controller cntl;
    IOBuf req, rsp;
    req.append(seed + "+" + std::to_string(i));
    co_await co_call(ch, "Echo.Echo", req, &rsp, &cntl);
    if (cntl.Failed()) {
      co_return std::string("FAILED: ") + cntl.error_text();
    }
    seed = rsp.to_string();
  }
  // Offload a CPU-ish step to a fresh fiber mid-coroutine.
  const size_t n = co_await co_run([&seed] { return seed.size(); });
  co_return seed + " (len " + std::to_string(n) + ")";
}

}  // namespace

int main() {
  Server server;
  server.RegisterMethod("Echo.Echo", [](Controller*, const IOBuf& req,
                                        IOBuf* rsp, Closure done) {
    rsp->append(req);
    done();
  });
  if (server.Start(0) != 0) {
    fprintf(stderr, "start failed\n");
    return 1;
  }
  Channel ch;
  if (ch.Init("127.0.0.1:" + std::to_string(server.port())) != 0) {
    return 1;
  }
  CoTask<std::string> task = pipeline(&ch, "seed");
  const std::string out = task.join();
  printf("coroutine result: %s\n", out.c_str());
  server.Stop();
  server.Join();
  return out == "seed+0+1+2 (len 10)" ? 0 : 1;
}
