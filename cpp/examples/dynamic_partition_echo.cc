// dynamic_partition_echo — coexisting partition schemes of one logical
// service (parity: example/dynamic_partition_echo_c++): a 2-way and a
// 4-way deployment serve simultaneously (a resharding migration);
// DynamicPartitionChannel shards each call across ONE scheme, weighted
// by capacity and live quality feedback.
//
// Run: ./build/example_dynamic_partition_echo
#include <cstdio>
#include <memory>
#include <string>
#include <vector>

#include "net/combo.h"
#include "net/server.h"

using namespace trpc;

namespace {

std::vector<IOBuf> even_split(const IOBuf& req, size_t n) {
  std::vector<IOBuf> parts(n);
  IOBuf rest = req;
  const size_t per = req.size() / n;
  for (size_t i = 0; i + 1 < n; ++i) {
    rest.cutn(&parts[i], per);
  }
  parts[n - 1] = std::move(rest);
  return parts;
}

}  // namespace

int main() {
  // Six shard servers: ports 0-1 form the 2-way scheme, 2-5 the 4-way.
  Server nodes[6];
  for (int i = 0; i < 6; ++i) {
    nodes[i].RegisterMethod("Svc.Shard", [](Controller*, const IOBuf& req,
                                            IOBuf* resp, Closure done) {
      resp->append(req);  // each shard echoes its slice
      done();
    });
    if (nodes[i].Start(0) != 0) {
      return 1;
    }
  }
  auto sub = [&](int i) {
    auto ch = std::make_shared<Channel>();
    ch->Init("127.0.0.1:" + std::to_string(nodes[i].port()));
    return make_sub_channel(ch);
  };

  DynamicPartitionChannel dpc;
  dpc.add_scheme({sub(0), sub(1)});                  // 2-way
  dpc.add_scheme({sub(2), sub(3), sub(4), sub(5)});  // 4-way
  printf("schemes: %zu (weights %lld vs %lld — capacity prior)\n",
         dpc.scheme_count(), static_cast<long long>(dpc.scheme_weight(0)),
         static_cast<long long>(dpc.scheme_weight(1)));

  const std::string payload = "0123456789abcdef0123456789abcdef";
  for (int i = 0; i < 32; ++i) {
    Controller cntl;
    cntl.set_timeout_ms(1000);
    IOBuf req, resp;
    req.append(payload);
    dpc.CallMethod("Svc.Shard", req, &resp, &cntl, &even_split);
    if (cntl.Failed() || resp.to_string() != payload) {
      fprintf(stderr, "fanout %d failed: %s\n", i,
              cntl.error_text().c_str());
      return 1;
    }
  }
  // Both schemes earned traffic; weights reflect observed quality now.
  printf("32 sharded calls ok; live weights %lld vs %lld\n",
         static_cast<long long>(dpc.scheme_weight(0)),
         static_cast<long long>(dpc.scheme_weight(1)));
  printf("ok\n");
  return 0;
}
