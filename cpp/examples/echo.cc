// echo — the smallest complete program: one server, one channel, a sync
// call and an async call (parity: example/echo_c++).
//
// Build: cmake --build build --target example_echo
// Run:   ./build/example_echo
#include <cstdio>

#include "fiber/sync.h"
#include "net/channel.h"
#include "net/server.h"

using namespace trpc;

int main() {
  // A handler receives (cntl, request, response, done) and MUST call
  // done() exactly once; it may do so later, from any fiber (async).
  Server server;
  server.RegisterMethod("Echo.Echo", [](Controller*, const IOBuf& req,
                                        IOBuf* resp, Closure done) {
    resp->append(req);
    done();
  });
  if (server.Start(0) != 0) {  // port 0: pick a free port
    fprintf(stderr, "start failed\n");
    return 1;
  }
  printf("server listening on 127.0.0.1:%d\n", server.port());

  Channel channel;
  if (channel.Init("127.0.0.1:" + std::to_string(server.port())) != 0) {
    return 1;
  }

  // Synchronous call: CallMethod parks the calling fiber (or pthread)
  // until the response lands or the timeout fires.
  {
    Controller cntl;
    cntl.set_timeout_ms(1000);
    IOBuf request, response;
    request.append("hello tpu-rpc");
    channel.CallMethod("Echo.Echo", request, &response, &cntl);
    if (cntl.Failed()) {
      fprintf(stderr, "sync call failed: %s\n", cntl.error_text().c_str());
      return 1;
    }
    printf("sync echo: %s (%lld us)\n", response.to_string().c_str(),
           static_cast<long long>(cntl.latency_us()));
  }

  // Asynchronous call: pass a done closure; CallMethod returns at once.
  {
    auto cntl = std::make_shared<Controller>();
    auto response = std::make_shared<IOBuf>();
    auto finished = std::make_shared<CountdownEvent>(1);
    cntl->set_timeout_ms(1000);
    IOBuf request;
    request.append("async hello");
    channel.CallMethod("Echo.Echo", request, response.get(), cntl.get(),
                       [cntl, response, finished] {
                         printf("async echo: %s\n",
                                response->to_string().c_str());
                         finished->signal();
                       });
    finished->wait(-1);
  }
  printf("ok\n");
  return 0;
}
