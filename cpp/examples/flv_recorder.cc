// flv_recorder — record a live RTMP publish to an FLV file via the
// media observer, then demux the file back (parity: the reference's
// FLV writer riding rtmp.cpp).  Uses the digest (complex) handshake.
//
// Build: cmake --build build --target example_flv_recorder
#include <chrono>
#include <cstdio>
#include <thread>

#include "fiber/sync.h"
#include "net/flv.h"
#include "net/rtmp.h"
#include "net/server.h"

using namespace trpc;

int main() {
  RtmpService svc;
  std::string file;
  FiberMutex mu;
  flv_write_header(/*audio=*/true, /*video=*/true, &file);
  svc.set_media_observer([&](const std::string& name,
                             const RtmpMessage& m) {
    if (name == "studio") {
      LockGuard<FiberMutex> g(mu);
      flv_write_message(m, &file);
    }
  });
  Server server;
  server.set_rtmp_service(&svc);
  if (server.Start(0) != 0) {
    return 1;
  }

  RtmpClient pub;
  RtmpClient::Options opts;
  opts.use_digest = true;  // complex handshake, like OBS/ffmpeg
  if (pub.Init("127.0.0.1:" + std::to_string(server.port()), &opts) != 0) {
    return 1;
  }
  uint32_t msid = 0;
  if (pub.create_stream(&msid) != 0 || pub.publish(msid, "studio") != 0) {
    fprintf(stderr, "publish failed\n");
    return 1;
  }
  // A keyframe, audio, and a big frame spanning many chunks.
  pub.send_media(msid, RtmpMsgType::kVideo, 0, "KEYFRAME");
  pub.send_media(msid, RtmpMsgType::kAudio, 20, "AAC0");
  pub.send_media(msid, RtmpMsgType::kVideo, 40, std::string(50000, 'P'));

  // The relay runs on read fibers; wait for all three tags to land.
  for (int spin = 0; spin < 1000; ++spin) {
    {
      LockGuard<FiberMutex> g(mu);
      if (file.size() > 50000) {
        break;
      }
    }
    std::this_thread::sleep_for(std::chrono::milliseconds(2));
  }

  LockGuard<FiberMutex> g(mu);
  printf("recorded %zu bytes of FLV\n", file.size());
  size_t pos = 0;
  bool a = false, v = false;
  if (flv_read_header(file, &pos, &a, &v) != 1) {
    return 1;
  }
  FlvTag tag;
  int tags = 0;
  while (flv_read_tag(file, &pos, &tag) == 1) {
    printf("  tag type=%2d ts=%4u size=%zu\n", tag.type, tag.timestamp,
           tag.data.size());
    ++tags;
  }
  server.Stop();
  server.Join();
  printf(tags == 3 ? "ok\n" : "FAIL\n");
  return tags == 3 ? 0 : 1;
}
