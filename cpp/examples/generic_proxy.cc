// generic_proxy — a method-agnostic RPC proxy built on the catch-all
// handler: every request, whatever its method, is forwarded verbatim to
// a backend and the response relayed (parity:
// example/baidu_proxy_and_generic_call + BaiduMasterService).
//
// Build: cmake --build build --target example_generic_proxy
#include <cstdio>
#include <memory>

#include "net/channel.h"
#include "net/server.h"

using namespace trpc;

int main() {
  // Backend with two real methods.
  Server backend;
  backend.RegisterMethod("Echo.Echo", [](Controller*, const IOBuf& req,
                                         IOBuf* resp, Closure done) {
    resp->append(req);
    done();
  });
  backend.RegisterMethod("Math.Square",
                         [](Controller*, const IOBuf& req, IOBuf* resp,
                            Closure done) {
                           const long v = atol(req.to_string().c_str());
                           resp->append(std::to_string(v * v));
                           done();
                         });
  if (backend.Start(0) != 0) {
    return 1;
  }

  // The proxy registers NO methods — only the generic handler, which
  // sees the method name via cntl->method() and the raw body.
  Server proxy;
  auto upstream = std::make_shared<Channel>();
  if (upstream->Init("127.0.0.1:" + std::to_string(backend.port())) != 0) {
    return 1;
  }
  proxy.set_generic_handler([upstream](Controller* cntl, const IOBuf& req,
                                       IOBuf* resp, Closure done) {
    Controller fwd;
    fwd.set_timeout_ms(2000);
    upstream->CallMethod(cntl->method(), req, resp, &fwd);
    if (fwd.Failed()) {
      cntl->SetFailed(fwd.error_code(), "via proxy: " + fwd.error_text());
    }
    done();
  });
  if (proxy.Start(0) != 0) {
    return 1;
  }
  printf("proxy %d -> backend %d\n", proxy.port(), backend.port());

  Channel ch;
  if (ch.Init("127.0.0.1:" + std::to_string(proxy.port())) != 0) {
    return 1;
  }
  for (const auto& [method, body] :
       {std::pair<std::string, std::string>{"Echo.Echo", "hello"},
        {"Math.Square", "12"},
        {"No.Such", "x"}}) {
    Controller cntl;
    IOBuf req, resp;
    req.append(body);
    ch.CallMethod(method, req, &resp, &cntl);
    if (cntl.Failed()) {
      printf("%-12s -> error %d (%s)\n", method.c_str(),
             cntl.error_code(), cntl.error_text().c_str());
    } else {
      printf("%-12s -> %s\n", method.c_str(), resp.to_string().c_str());
    }
  }
  proxy.Stop();
  proxy.Join();
  backend.Stop();
  backend.Join();
  printf("ok\n");
  return 0;
}
