// h2_grpc — the same server speaks tstd, HTTP/1.1, h2 and gRPC on ONE
// port; this example drives it with our own h2 and gRPC clients,
// including a progressive (streaming-read) response consumer (parity:
// example/grpc_c++ + http_c++).
//
// Run: ./build/example_h2_grpc
#include <cstdio>
#include <string>

#include "net/channel.h"
#include "net/progressive.h"
#include "net/server.h"

using namespace trpc;

namespace {

// Collects a progressive response piece by piece (net/progressive.h).
class PartCounter : public ProgressiveReader {
 public:
  bool on_part(const IOBuf& piece) override {
    ++parts_;
    bytes_ += piece.size();
    return true;  // false would cancel the stream
  }
  void on_done(int error_code, const std::string&) override {
    printf("progressive read done: %d parts, %zu bytes, rc=%d\n", parts_,
           bytes_, error_code);
  }
  int parts() const { return parts_; }
  size_t bytes() const { return bytes_; }

 private:
  int parts_ = 0;
  size_t bytes_ = 0;
};

}  // namespace

int main() {
  Server server;
  server.RegisterMethod("Greeter.Hello", [](Controller*, const IOBuf& req,
                                            IOBuf* resp, Closure done) {
    resp->append("hello, " + req.to_string());
    done();
  });
  server.RegisterMethod("Blob.Get", [](Controller*, const IOBuf&,
                                       IOBuf* resp, Closure done) {
    resp->append(std::string(1 << 20, 'B'));  // 1MB: many DATA frames
    done();
  });
  if (server.Start(0) != 0) {
    return 1;
  }
  const std::string addr = "127.0.0.1:" + std::to_string(server.port());

  // Plain h2: response body = payload, HTTP status surfaces errors.
  {
    Channel h2;
    Channel::Options opts;
    opts.protocol = "h2";
    h2.Init(addr, &opts);
    Controller cntl;
    IOBuf req, resp;
    req.append("h2-world");
    h2.CallMethod("Greeter.Hello", req, &resp, &cntl);
    printf("h2   : %s\n", cntl.Failed() ? cntl.error_text().c_str()
                                        : resp.to_string().c_str());
    if (cntl.Failed()) {
      return 1;
    }
  }
  // gRPC: length-prefixed framing, grpc-status in trailers; unknown
  // methods come back as UNIMPLEMENTED, not a transport error.
  {
    Channel grpc;
    Channel::Options opts;
    opts.protocol = "grpc";
    grpc.Init(addr, &opts);
    Controller cntl;
    IOBuf req, resp;
    req.append("grpc-world");
    grpc.CallMethod("Greeter.Hello", req, &resp, &cntl);
    printf("grpc : %s\n", cntl.Failed() ? cntl.error_text().c_str()
                                        : resp.to_string().c_str());
    if (cntl.Failed()) {
      return 1;
    }
  }
  // Progressive read over h2: 1MB arrives as ~64 flow-controlled DATA
  // frames, each handed to the reader instead of accumulating.
  {
    Channel h2;
    Channel::Options opts;
    opts.protocol = "h2";
    opts.timeout_ms = 5000;
    h2.Init(addr, &opts);
    PartCounter reader;
    Controller cntl;
    cntl.ReadProgressively(&reader);
    IOBuf req, resp;
    h2.CallMethod("Blob.Get", req, &resp, &cntl);
    if (cntl.Failed() || reader.bytes() != (1u << 20) ||
        reader.parts() < 2) {
      fprintf(stderr, "progressive read failed\n");
      return 1;
    }
  }
  printf("ok\n");
  return 0;
}
