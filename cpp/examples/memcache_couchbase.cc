// memcache_couchbase — the binary memcache protocol served in-process
// (beyond the reference, which is client-only) and a vbucket-routing
// couchbase client over two ownership-enforcing nodes (parity:
// example/memcache_c++ + the couchbase fork extension).
//
// Build: cmake --build build --target example_memcache_couchbase
#include <cstdio>

#include "net/couchbase.h"
#include "net/memcache.h"
#include "net/server.h"

using namespace trpc;

int main() {
  // Plain memcache: one server, one pipelined client.
  Server cache;
  cache.set_memcache_service(new MemcacheService());
  if (cache.Start(0) != 0) {
    return 1;
  }
  const std::string addr = "127.0.0.1:" + std::to_string(cache.port());
  MemcacheClient mc;
  if (mc.Init(addr) != 0) {
    return 1;
  }
  mc.Set("greeting", "hello", /*flags=*/7);
  McResult got = mc.Get("greeting");
  printf("memcache GET greeting -> '%s' (flags %u)\n", got.value.c_str(),
         got.flags);
  // CAS: a stale token must lose.
  McResult fresh = mc.Set("greeting", "updated", 0, 0, got.cas);
  McResult stale = mc.Set("greeting", "clobber", 0, 0, got.cas);
  printf("CAS fresh=%s stale=%s\n", fresh.ok() ? "ok" : "lost",
         stale.status == McStatus::kExists ? "rejected (EXISTS)" : "?!");
  // Counters with wraparound semantics handled server-side.
  mc.Increment("hits", 1, /*initial=*/100);
  printf("hits -> %llu\n",
         static_cast<unsigned long long>(mc.Increment("hits", 5).numeric));

  // Couchbase: two nodes enforcing even/odd vbucket ownership; the
  // client's map routes, NOT_MY_VBUCKET probing self-heals stale maps.
  Server nodes[2];
  std::string naddr[2];
  for (int i = 0; i < 2; ++i) {
    auto* svc = new MemcacheService();
    svc->set_vbucket_filter(
        [i](uint16_t vb) { return (vb % 2) == static_cast<uint16_t>(i); });
    nodes[i].set_memcache_service(svc);
    if (nodes[i].Start(0) != 0) {
      return 1;
    }
    naddr[i] = "127.0.0.1:" + std::to_string(nodes[i].port());
  }
  CouchbaseClient cb;
  CouchbaseClient::Options copts;
  copts.n_vbuckets = 64;
  if (cb.Init({naddr[0], naddr[1]}, &copts) != 0) {
    return 1;
  }
  for (int i = 0; i < 8; ++i) {
    const std::string key = "doc-" + std::to_string(i);
    if (!cb.Set(key, "body-" + std::to_string(i)).ok()) {
      return 1;
    }
  }
  int ok = 0;
  for (int i = 0; i < 8; ++i) {
    const std::string key = "doc-" + std::to_string(i);
    McResult r = cb.Get(key);
    printf("couchbase GET %s (vb %u) -> %s\n", key.c_str(),
           couchbase_vbucket_of(key, 64), r.value.c_str());
    ok += r.ok();
  }
  cache.Stop();
  cache.Join();
  for (auto& n : nodes) {
    n.Stop();
    n.Join();
  }
  printf(ok == 8 ? "ok\n" : "FAIL\n");
  return ok == 8 ? 0 : 1;
}
