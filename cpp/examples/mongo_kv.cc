// mongo_kv — a mongo-speaking server (OP_MSG + BSON, stock drivers can
// connect) exposing insert/find over an in-memory store, driven by the
// MongoClient (parity: policy/mongo_protocol.cpp server adaptor).
//
// Build: cmake --build build --target example_mongo_kv
#include <cstdio>
#include <map>

#include "net/mongo.h"
#include "net/server.h"

using namespace trpc;

int main() {
  static std::map<std::string, std::string> store;
  auto* svc = new MongoService();
  svc->AddCommandHandler("insert", [](const BsonDoc& req) {
    // {insert: <collection>, documents: [{_id, value}, ...]}
    const BsonValue* docs = bson_find(req, "documents");
    int n = 0;
    if (docs != nullptr && docs->doc != nullptr) {
      for (const auto& [idx, d] : *docs->doc) {
        if (d.doc == nullptr) continue;
        const BsonValue* id = bson_find(*d.doc, "_id");
        const BsonValue* val = bson_find(*d.doc, "value");
        if (id != nullptr && val != nullptr) {
          store[id->str] = val->str;
          ++n;
        }
      }
    }
    BsonDoc reply = MongoService::ok_reply();
    reply.emplace_back("n", BsonValue::Int32(n));
    return reply;
  });
  svc->AddCommandHandler("find", [](const BsonDoc& req) {
    // {find: <collection>, filter: {_id: key}}
    BsonDoc reply = MongoService::ok_reply();
    const BsonValue* filter = bson_find(req, "filter");
    std::vector<BsonValue> batch;
    if (filter != nullptr && filter->doc != nullptr) {
      const BsonValue* id = bson_find(*filter->doc, "_id");
      auto it = id != nullptr ? store.find(id->str) : store.end();
      if (it != store.end()) {
        batch.push_back(BsonValue::Document(
            {{"_id", BsonValue::Str(it->first)},
             {"value", BsonValue::Str(it->second)}}));
      }
    }
    reply.emplace_back(
        "cursor", BsonValue::Document(
                      {{"id", BsonValue::Int64(0)},
                       {"firstBatch", BsonValue::Array(std::move(batch))}}));
    return reply;
  });

  Server server;
  server.set_mongo_service(svc);
  if (server.Start(0) != 0) {
    return 1;
  }
  printf("mongo-speaking server on 127.0.0.1:%d\n", server.port());

  MongoClient cli;
  if (cli.Init("127.0.0.1:" + std::to_string(server.port())) != 0) {
    return 1;
  }
  // The driver handshake a real client would send works too.
  MongoClient::Result hello = cli.run_command({{"hello", BsonValue::Int32(1)}});
  printf("hello -> ok=%d\n", hello.ok);

  MongoClient::Result ins = cli.run_command(
      {{"insert", BsonValue::Str("kv")},
       {"documents",
        BsonValue::Array({BsonValue::Document(
            {{"_id", BsonValue::Str("alpha")},
             {"value", BsonValue::Str("the-first-letter")}})})}});
  const BsonValue* n = ins.ok ? bson_find(ins.reply, "n") : nullptr;
  printf("insert -> n=%lld\n",
         n != nullptr ? static_cast<long long>(n->i) : -1);

  MongoClient::Result found = cli.run_command(
      {{"find", BsonValue::Str("kv")},
       {"filter", BsonValue::Document({{"_id", BsonValue::Str("alpha")}})}});
  const BsonValue* cursor =
      found.ok ? bson_find(found.reply, "cursor") : nullptr;
  const BsonValue* batch =
      cursor != nullptr && cursor->doc != nullptr
          ? bson_find(*cursor->doc, "firstBatch")
          : nullptr;
  if (batch == nullptr || batch->doc == nullptr || batch->doc->empty()) {
    fprintf(stderr, "find returned nothing\n");
    return 1;
  }
  const BsonValue& doc0 = (*batch->doc)[0].second;
  const BsonValue* value =
      doc0.doc != nullptr ? bson_find(*doc0.doc, "value") : nullptr;
  printf("find alpha -> %s\n",
         value != nullptr ? value->str.c_str() : "?");

  server.Stop();
  server.Join();
  printf("ok\n");
  return 0;
}
