// multi_threaded_echo — N fibers hammer one server through a shared
// channel and report qps + latency percentiles (parity:
// example/multi_threaded_echo_c++, the reference's benchmark staple).
//
// Run: ./build/example_multi_threaded_echo [fibers=32] [seconds=2]
#include <algorithm>
#include <atomic>
#include <cstdio>
#include <cstdlib>
#include <vector>

#include "base/time.h"
#include "fiber/fiber.h"
#include "net/channel.h"
#include "net/server.h"

using namespace trpc;

namespace {

struct WorkerArgs {
  Channel* channel;
  int64_t stop_us;
  std::atomic<long>* ok;
  std::atomic<long>* failed;
  std::vector<int64_t>* latencies;  // per-worker, merged at the end
};

void worker(void* arg) {
  auto* a = static_cast<WorkerArgs*>(arg);
  IOBuf request;
  request.append(std::string(1024, 'e'));
  while (monotonic_time_us() < a->stop_us) {
    Controller cntl;
    cntl.set_timeout_ms(1000);
    IOBuf response;
    const int64_t t0 = monotonic_time_us();
    a->channel->CallMethod("Echo.Echo", request, &response, &cntl);
    if (cntl.Failed()) {
      a->failed->fetch_add(1);
    } else {
      a->ok->fetch_add(1);
      a->latencies->push_back(monotonic_time_us() - t0);
    }
  }
}

}  // namespace

int main(int argc, char** argv) {
  const int fibers = argc > 1 ? atoi(argv[1]) : 32;
  const int seconds = argc > 2 ? atoi(argv[2]) : 2;

  Server server;
  server.RegisterMethod("Echo.Echo", [](Controller*, const IOBuf& req,
                                        IOBuf* resp, Closure done) {
    resp->append(req);
    done();
  });
  if (server.Start(0) != 0) {
    return 1;
  }
  Channel channel;
  channel.Init("127.0.0.1:" + std::to_string(server.port()));

  std::atomic<long> ok{0};
  std::atomic<long> failed{0};
  std::vector<std::vector<int64_t>> lats(fibers);
  std::vector<WorkerArgs> args(fibers);
  std::vector<fiber_t> ids(fibers);
  const int64_t t0 = monotonic_time_us();
  const int64_t stop = t0 + seconds * 1000000LL;
  for (int i = 0; i < fibers; ++i) {
    args[i] = {&channel, stop, &ok, &failed, &lats[i]};
    fiber_start(&ids[i], worker, &args[i]);
  }
  for (fiber_t f : ids) {
    fiber_join(f);
  }
  const double secs = (monotonic_time_us() - t0) / 1e6;

  std::vector<int64_t> all;
  for (auto& v : lats) {
    all.insert(all.end(), v.begin(), v.end());
  }
  std::sort(all.begin(), all.end());
  auto pct = [&](double p) {
    return all.empty()
               ? 0ll
               : static_cast<long long>(all[std::min(
                     all.size() - 1, static_cast<size_t>(p * all.size()))]);
  };
  printf("fibers=%d qps=%.0f p50=%lldus p99=%lldus failures=%ld\n", fibers,
         ok.load() / secs, pct(0.5), pct(0.99), failed.load());
  return failed.load() == 0 ? 0 : 1;
}
