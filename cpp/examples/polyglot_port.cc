// polyglot_port — ONE server port speaking six protocols at once: tstd
// RPC, thrift, memcache, redis, mongo, and hulu pbrpc, each probed off
// the first bytes of its connection (parity: brpc's "many protocols on
// one port" headline; InputMessenger protocol multiplexing).
//
// Build: cmake --build build --target example_polyglot_port
// Run:   ./build/example_polyglot_port
#include <cstdio>

#include "net/channel.h"
#include "net/legacy_pbrpc.h"
#include "net/memcache.h"
#include "net/mongo.h"
#include "net/redis.h"
#include "net/server.h"
#include "net/thrift.h"

using namespace trpc;

int main() {
  Server server;
  // The SAME handler serves tstd ("Echo.Echo") and the legacy pbrpc
  // family ("EchoService.Echo" names arrive from hulu/sofa).
  Server::Handler echo = [](Controller*, const IOBuf& req, IOBuf* rsp,
                            Closure done) {
    rsp->append(req);
    done();
  };
  server.RegisterMethod("Echo.Echo", echo);
  server.RegisterMethod("EchoService.Echo", echo);

  ThriftService thrift;
  thrift.AddMethodHandler("Echo", [](const ThriftValue& args,
                                     std::string*) {
    ThriftValue result = ThriftValue::Struct();
    const ThriftValue* s = args.field(1);
    result.add_field(0,
                     ThriftValue::Str(s != nullptr ? s->str : ""));
    return result;
  });
  server.set_thrift_service(&thrift);

  MemcacheService memcache;
  server.set_memcache_service(&memcache);

  RedisService redis;
  redis.AddCommandHandler("hello", [](const std::vector<std::string>&) {
    return RedisReply::Status("polyglot");
  });
  server.set_redis_service(&redis);

  MongoService mongo;
  server.set_mongo_service(&mongo);

  if (server.Start(0) != 0) {
    fprintf(stderr, "start failed\n");
    return 1;
  }
  const std::string addr = "127.0.0.1:" + std::to_string(server.port());
  printf("one port, six protocols: %s\n", addr.c_str());

  // 1. tstd RPC.
  Channel ch;
  Controller cntl;
  IOBuf req, rsp;
  req.append("over-tstd");
  if (ch.Init(addr) != 0) return 1;
  ch.CallMethod("Echo.Echo", req, &rsp, &cntl);
  printf("tstd     : %s\n", cntl.Failed() ? "FAILED"
                                          : rsp.to_string().c_str());
  if (cntl.Failed()) return 1;

  // 2. thrift framed.
  ThriftClient tc;
  if (tc.Init(addr) != 0) return 1;
  ThriftValue targs = ThriftValue::Struct();
  targs.add_field(1, ThriftValue::Str("over-thrift"));
  ThriftClient::Result tr = tc.call("Echo", targs);
  printf("thrift   : %s\n",
         tr.ok ? tr.result.field(0)->str.c_str() : "FAILED");
  if (!tr.ok) return 1;

  // 3. memcache binary.
  MemcacheClient mc;
  if (mc.Init(addr) != 0) return 1;
  mc.Set("k", "over-memcache");
  McResult got = mc.Get("k");
  printf("memcache : %s\n", got.ok() ? got.value.c_str() : "FAILED");
  if (!got.ok()) return 1;

  // 4. redis (RESP).
  RedisClient rc;
  if (rc.Init(addr) != 0) return 1;
  RedisReply rr = rc.execute({"HELLO"});
  printf("redis    : %s\n",
         rr.type == RedisReply::kStatus ? rr.str.c_str() : "FAILED");
  if (rr.type != RedisReply::kStatus) return 1;

  // 5. mongo OP_MSG.
  MongoClient mg;
  if (mg.Init(addr) != 0) return 1;
  BsonDoc ping;
  ping.emplace_back("ping", BsonValue::Int32(1));
  MongoClient::Result mr = mg.run_command(ping);
  printf("mongo    : %s\n", mr.ok ? "ok" : "FAILED");
  if (!mr.ok) return 1;

  // 6. hulu pbrpc.
  LegacyRpcClient lc;
  if (lc.Init(addr, LegacyProto::kHulu) != 0) return 1;
  IOBuf hreq;
  hreq.append("over-hulu");
  LegacyRpcClient::Result hr = lc.call("EchoService", "Echo", 0, hreq);
  printf("hulu     : %s\n",
         hr.ok ? hr.response.to_string().c_str() : "FAILED");
  if (!hr.ok) return 1;

  server.Stop();
  server.Join();
  printf("all six protocols answered on one port\n");
  return 0;
}
