// progressive_download — a handler answers headers immediately and
// streams a large body over time (ProgressiveAttachment, parity:
// progressive_attachment.h:32); any HTTP client (curl) consumes the
// chunks as they arrive.  The demo fetches its own stream with a raw
// socket and shows chunks landing before the handler finished.
//
// Run: ./build/example_progressive_download
#include <arpa/inet.h>
#include <netinet/in.h>
#include <sys/socket.h>
#include <unistd.h>

#include <atomic>
#include <cstdio>
#include <string>

#include "fiber/fiber.h"
#include "net/progressive.h"
#include "net/server.h"

using namespace trpc;

namespace {
std::atomic<int> g_written_chunks{0};
}

int main() {
  Server server;
  server.RegisterMethod("File.Stream", [](Controller* cntl, const IOBuf&,
                                          IOBuf*, Closure done) {
    // done() flushes "Transfer-Encoding: chunked" headers NOW; the body
    // follows from this fiber at its own pace, bounded memory.
    auto pa = cntl->CreateProgressiveAttachment();
    done();
    for (int i = 0; i < 16; ++i) {
      IOBuf piece;
      piece.append(std::string(128 * 1024, static_cast<char>('a' + i)));
      if (pa->Write(piece) != 0) {
        return;  // client went away
      }
      g_written_chunks.fetch_add(1);
      fiber_sleep_us(10 * 1000);
    }
    pa->close();  // terminating chunk; connection stays keep-alive
  });
  if (server.Start(0) != 0) {
    return 1;
  }
  printf("try: curl -s http://127.0.0.1:%d/File.Stream | wc -c\n",
         server.port());

  // Raw-socket consumer standing in for curl.
  const int fd = socket(AF_INET, SOCK_STREAM, 0);
  sockaddr_in sa = {};
  sa.sin_family = AF_INET;
  sa.sin_addr.s_addr = htonl(INADDR_LOOPBACK);
  sa.sin_port = htons(static_cast<uint16_t>(server.port()));
  if (connect(fd, reinterpret_cast<sockaddr*>(&sa), sizeof(sa)) != 0) {
    return 1;
  }
  const std::string rq = "GET /File.Stream HTTP/1.1\r\nHost: x\r\n\r\n";
  if (write(fd, rq.data(), rq.size()) != static_cast<ssize_t>(rq.size())) {
    return 1;
  }
  std::string in;
  char buf[65536];
  bool saw_early_bytes = false;
  while (in.find("\r\n0\r\n\r\n") == std::string::npos) {
    const ssize_t n = read(fd, buf, sizeof(buf));
    if (n <= 0) {
      return 1;
    }
    in.append(buf, n);
    if (!saw_early_bytes && in.size() > 64 * 1024) {
      // Bytes are arriving while the handler is still mid-stream: this
      // is a STREAM, not a buffered response.
      printf("first %zu KB arrived with only %d/16 chunks written\n",
             in.size() / 1024, g_written_chunks.load());
      saw_early_bytes = true;
    }
  }
  close(fd);
  printf("full body received (%zu KB on the wire)\n", in.size() / 1024);
  return saw_early_bytes ? 0 : 1;
}
