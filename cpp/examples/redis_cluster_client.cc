// redis_cluster_client — a two-node "cluster" of redis-speaking servers
// and a slot-routing client following MOVED redirects (parity:
// example/redis_c++ + the redis_cluster client machinery).
//
// Build: cmake --build build --target example_redis_cluster_client
#include <cstdio>
#include <map>

#include "net/redis.h"
#include "net/redis_cluster.h"
#include "net/server.h"

using namespace trpc;

namespace {

// One node: owns a slot range, stores keys, MOVEDs everything else.
struct Node {
  Server srv;
  std::map<std::string, std::string> store;
  int beg, end;
  std::string addr;
};
Node nodes[2];

void start(Node* n, int beg, int end, const std::string& other_ref) {
  n->beg = beg;
  n->end = end;
  auto* rs = new RedisService();
  rs->AddCommandHandler("cluster", [](const std::vector<std::string>& a) {
    auto range = [](const Node& node) {
      const size_t c = node.addr.rfind(':');
      return RedisReply::Array(
          {RedisReply::Integer(node.beg), RedisReply::Integer(node.end),
           RedisReply::Array(
               {RedisReply::Bulk(node.addr.substr(0, c)),
                RedisReply::Integer(atoi(node.addr.c_str() + c + 1))})});
    };
    return RedisReply::Array({range(nodes[0]), range(nodes[1])});
  });
  auto owned = [n](const std::string& key) {
    const int s = redis_key_slot(key);
    return s >= n->beg && s <= n->end;
  };
  rs->AddCommandHandler("set", [n, owned](const std::vector<std::string>& a) {
    if (a.size() != 3) return RedisReply::Error("ERR args");
    if (!owned(a[1])) {
      Node* other = (n == &nodes[0]) ? &nodes[1] : &nodes[0];
      return RedisReply::Error(
          "MOVED " + std::to_string(redis_key_slot(a[1])) + " " +
          other->addr);
    }
    n->store[a[1]] = a[2];
    return RedisReply::Status("OK");
  });
  rs->AddCommandHandler("get", [n, owned](const std::vector<std::string>& a) {
    if (a.size() != 2) return RedisReply::Error("ERR args");
    if (!owned(a[1])) {
      Node* other = (n == &nodes[0]) ? &nodes[1] : &nodes[0];
      return RedisReply::Error(
          "MOVED " + std::to_string(redis_key_slot(a[1])) + " " +
          other->addr);
    }
    auto it = n->store.find(a[1]);
    return it == n->store.end() ? RedisReply::Nil()
                                : RedisReply::Bulk(it->second);
  });
  n->srv.set_redis_service(rs);
  if (n->srv.Start(0) != 0) {
    exit(1);
  }
  n->addr = "127.0.0.1:" + std::to_string(n->srv.port());
  (void)other_ref;
}

}  // namespace

int main() {
  start(&nodes[0], 0, 8191, "");
  start(&nodes[1], 8192, 16383, "");
  printf("cluster: %s (slots 0-8191), %s (slots 8192-16383)\n",
         nodes[0].addr.c_str(), nodes[1].addr.c_str());

  RedisClusterClient cc;
  if (cc.Init({nodes[0].addr}) != 0) {
    return 1;
  }
  // "foo" hashes to slot 12182 (node 1), "bar" to 5061 (node 0): one
  // client, two nodes, routing is invisible to the caller.
  for (const char* key : {"foo", "bar", "user:{42}:name"}) {
    RedisReply r = cc.execute({"SET", key, std::string("value-of-") + key});
    printf("SET %-15s slot %5d -> %s\n", key, redis_key_slot(key),
           r.str.c_str());
  }
  for (const char* key : {"foo", "bar", "user:{42}:name"}) {
    RedisReply r = cc.execute({"GET", key});
    printf("GET %-15s -> %s\n", key, r.str.c_str());
    if (r.str != std::string("value-of-") + key) {
      return 1;
    }
  }
  printf("node0 holds %zu keys, node1 holds %zu keys\n",
         nodes[0].store.size(), nodes[1].store.size());
  nodes[0].srv.Stop();
  nodes[1].srv.Stop();
  nodes[0].srv.Join();
  nodes[1].srv.Join();
  printf("ok\n");
  return 0;
}
