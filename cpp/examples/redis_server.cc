// redis_server — build a redis-speaking service on the RPC server's
// port (RedisService, parity: example/redis_c++ + redis.h:194), then
// drive it with the pipelining RedisClient.  Stock redis clients
// (redis-cli) can talk to it too — the port still serves tstd/HTTP/h2
// alongside.
//
// Run: ./build/example_redis_server
#include <cstdio>
#include <map>
#include <string>
#include <vector>

#include "net/redis.h"
#include "net/server.h"

using namespace trpc;

int main() {
  // A tiny keyspace behind GET/SET/DEL/INCR handlers.  Handlers run
  // inline in the connection's read fiber, strictly in arrival order —
  // exactly redis-server's execution model, so no locking is needed for
  // per-connection ordering (use your own locks for cross-connection
  // shared state; a plain map + the ordering suffices for this demo).
  static std::map<std::string, std::string> store;
  RedisService service;
  service.AddCommandHandler("set", [](const std::vector<std::string>& a) {
    if (a.size() != 3) {
      return RedisReply::Error("ERR wrong number of arguments for 'set'");
    }
    store[a[1]] = a[2];
    return RedisReply::Status("OK");
  });
  service.AddCommandHandler("get", [](const std::vector<std::string>& a) {
    if (a.size() != 2) {
      return RedisReply::Error("ERR wrong number of arguments for 'get'");
    }
    auto it = store.find(a[1]);
    return it == store.end() ? RedisReply::Nil()
                             : RedisReply::Bulk(it->second);
  });
  service.AddCommandHandler("incr", [](const std::vector<std::string>& a) {
    std::string& v = store[a[1]];
    const long long n = v.empty() ? 1 : atoll(v.c_str()) + 1;
    v = std::to_string(n);
    return RedisReply::Integer(n);
  });

  Server server;
  server.set_redis_service(&service);
  if (server.Start(0) != 0) {
    return 1;
  }
  printf("redis-speaking server on 127.0.0.1:%d (try redis-cli -p %d)\n",
         server.port(), server.port());

  RedisClient client;
  if (client.Init("127.0.0.1:" + std::to_string(server.port())) != 0) {
    return 1;
  }
  // Single round trips.
  printf("SET k v    → %s\n", client.execute({"SET", "k", "v"}).str.c_str());
  printf("GET k      → %s\n", client.execute({"GET", "k"}).str.c_str());
  printf("PING       → %s\n", client.execute({"PING"}).str.c_str());

  // Pipelining: 100 commands in ONE write, replies correlated FIFO
  // (socket pipelined_count parity) — the latency of one round trip
  // amortized over the whole batch.
  std::vector<std::vector<std::string>> batch;
  for (int i = 0; i < 100; ++i) {
    batch.push_back({"INCR", "counter"});
  }
  std::vector<RedisReply> replies = client.pipeline(batch);
  printf("pipelined 100 INCRs → counter = %lld\n",
         static_cast<long long>(replies.back().integer));
  return replies.back().integer == 100 ? 0 : 1;
}
