// rpcz_echo — per-RPC tracing spans and the /rpcz builtin (parity:
// example/rpcz_echo_c++ + builtin/rpcz_service).  Spans record each
// call's timeline; client spans started INSIDE a handler parent to the
// ambient server span, so a proxy hop shows as one trace.
//
// Run: ./build/example_rpcz_echo
#include <unistd.h>

#include <cstdio>
#include <string>

#include "base/flags.h"
#include "net/channel.h"
#include "net/http_client.h"
#include "net/server.h"
#include "net/span.h"

using namespace trpc;

int main() {
  // rpcz is a reloadable flag (default off, like -enable_rpcz); a live
  // process can flip it via /flags?setvalue too.
  (void)rpcz_enabled();  // touch the lazily-registered flag
  if (Flag::set("rpcz_enabled", "true") != 0) {
    fprintf(stderr, "rpcz flag flip failed\n");
    return 1;
  }

  Server backend;
  backend.RegisterMethod("Echo.Echo", [](Controller*, const IOBuf& req,
                                         IOBuf* resp, Closure done) {
    resp->append(req);
    done();
  });
  if (backend.Start(0) != 0) {
    return 1;
  }
  Server frontend;  // proxies to backend: two spans, one trace
  Channel to_backend;
  if (to_backend.Init("127.0.0.1:" + std::to_string(backend.port())) != 0) {
    return 1;
  }
  frontend.RegisterMethod(
      "Front.Hop", [&to_backend](Controller* cntl, const IOBuf& req,
                                 IOBuf* resp, Closure done) {
        // This client call inherits the handler's ambient trace: the
        // backend span links as a child of the frontend span.
        Controller inner;
        inner.set_timeout_ms(1000);
        to_backend.CallMethod("Echo.Echo", req, resp, &inner);
        if (inner.Failed()) {
          cntl->SetFailed(inner.error_code(), inner.error_text());
        }
        done();
      });
  if (frontend.Start(0) != 0) {
    return 1;
  }

  Channel ch;
  if (ch.Init("127.0.0.1:" + std::to_string(frontend.port())) != 0) {
    return 1;
  }
  for (int i = 0; i < 4; ++i) {
    Controller cntl;
    cntl.set_timeout_ms(1000);
    IOBuf req, resp;
    req.append("traced-" + std::to_string(i));
    ch.CallMethod("Front.Hop", req, &resp, &cntl);
    if (cntl.Failed()) {
      fprintf(stderr, "call failed: %s\n", cntl.error_text().c_str());
      return 1;
    }
  }

  // Browse the spans like an operator would: GET /rpcz.  Handlers submit
  // their span AFTER the response leaves, so poll briefly.
  HttpClient hc;
  if (hc.Init("127.0.0.1:" + std::to_string(frontend.port())) != 0) {
    return 1;
  }
  HttpResult r;
  for (int attempt = 0; attempt < 100; ++attempt) {
    r = hc.Get("/rpcz");
    if (r.ok && r.body.find("Front.Hop") != std::string::npos) {
      break;
    }
    usleep(10 * 1000);
  }
  if (!r.ok || r.status != 200 ||
      r.body.find("Front.Hop") == std::string::npos) {
    fprintf(stderr, "/rpcz missing spans (status %d)\n", r.status);
    return 1;
  }
  printf("/rpcz shows %zu bytes of spans, Front.Hop present\n",
         r.body.size());
  printf("ok\n");
  return 0;
}
