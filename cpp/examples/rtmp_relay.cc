// rtmp_relay — a live media relay in ~60 lines: one publisher pushes
// audio/video messages, two players receive them fanned out by the
// server's per-stream hub (parity: example rtmp usage of the
// reference's media substrate).
//
// Build: cmake --build build --target example_rtmp_relay
// Run:   ./build/example_rtmp_relay
#include <atomic>
#include <chrono>
#include <cstdio>
#include <thread>

#include "net/rtmp.h"
#include "net/server.h"

using namespace trpc;

int main() {
  RtmpService svc;
  Server server;
  server.set_rtmp_service(&svc);
  if (server.Start(0) != 0) {
    fprintf(stderr, "start failed\n");
    return 1;
  }
  const std::string addr = "127.0.0.1:" + std::to_string(server.port());
  printf("rtmp relay on %s (app=live, stream=cam)\n", addr.c_str());

  std::atomic<int> frames[2] = {{0}, {0}};
  RtmpClient players[2];
  for (int i = 0; i < 2; ++i) {
    if (players[i].Init(addr) != 0) return 1;
    uint32_t msid = 0;
    if (players[i].create_stream(&msid) != 0) return 1;
    if (players[i].play(msid, "cam",
                        [&frames, i](const RtmpMessage& m) {
                          if (m.type == 9) {
                            frames[i].fetch_add(1);
                          }
                        }) != 0) {
      return 1;
    }
  }

  RtmpClient pub;
  if (pub.Init(addr) != 0) return 1;
  uint32_t msid = 0;
  if (pub.create_stream(&msid) != 0) return 1;
  if (pub.publish(msid, "cam") != 0) return 1;
  for (int f = 0; f < 10; ++f) {
    if (pub.send_media(msid, RtmpMsgType::kVideo,
                       static_cast<uint32_t>(f * 33),
                       std::string(32768, static_cast<char>('0' + f))) !=
        0) {
      return 1;
    }
  }

  for (int spin = 0;
       spin < 1000 && (frames[0].load() < 10 || frames[1].load() < 10);
       ++spin) {
    std::this_thread::sleep_for(std::chrono::milliseconds(2));
  }
  printf("player0=%d player1=%d video frames relayed\n", frames[0].load(),
         frames[1].load());
  server.Stop();
  server.Join();
  return frames[0].load() == 10 && frames[1].load() == 10 ? 0 : 1;
}
