#!/bin/sh
# Smoke-runs every example binary; any nonzero exit fails the run, and
# finding NO binaries fails too (a stale build tree must not pass
# vacuously — the example targets come from a cmake GLOB that needs a
# reconfigure after adding files).
# Usage: examples/run_all.sh <build-dir>
set -e
BUILD="${1:-build}"
status=0
count=0
for exe in "$BUILD"/example_*; do
  [ -x "$exe" ] || continue
  count=$((count + 1))
  name=$(basename "$exe")
  if out=$("$exe" 2>&1); then
    echo "PASS $name"
  else
    echo "FAIL $name"
    echo "$out" | tail -20
    status=1
  fi
done
if [ "$count" -eq 0 ]; then
  echo "FAIL no example binaries found in $BUILD (stale configure?)"
  status=1
fi
exit $status
