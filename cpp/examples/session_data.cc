// session_data — pooled per-request scratch objects: an "expensive"
// context is created twice (reserve) and reused by every request
// instead of constructed per call (parity:
// example/session_data_and_thread_local + simple_data_pool).
//
// Build: cmake --build build --target example_session_data
#include <atomic>
#include <cstdio>

#include "net/channel.h"
#include "net/data_pool.h"
#include "net/server.h"

using namespace trpc;

namespace {

std::atomic<int> g_constructed{0};

struct ExpensiveContext {
  int uses = 0;
  char arena[4096];  // stand-in for a parser/model state
};

struct ContextFactory : DataFactory {
  void* CreateData() override {
    g_constructed.fetch_add(1);
    return new ExpensiveContext();
  }
  void DestroyData(void* d) override {
    delete static_cast<ExpensiveContext*>(d);
  }
};

}  // namespace

int main() {
  static ContextFactory factory;
  Server server;
  server.set_session_local_data_factory(&factory, /*reserve=*/2);
  server.RegisterMethod("Work.Do", [](Controller* cntl, const IOBuf&,
                                      IOBuf* resp, Closure done) {
    auto* ctx =
        static_cast<ExpensiveContext*>(cntl->session_local_data());
    // The object persists across requests: uses accumulates.
    resp->append("context-use #" + std::to_string(++ctx->uses));
    done();
  });
  if (server.Start(0) != 0) {
    return 1;
  }
  Channel ch;
  if (ch.Init("127.0.0.1:" + std::to_string(server.port())) != 0) {
    return 1;
  }
  for (int i = 0; i < 10; ++i) {
    Controller cntl;
    IOBuf req, resp;
    ch.CallMethod("Work.Do", req, &resp, &cntl);
    if (cntl.Failed()) {
      return 1;
    }
    if (i == 0 || i == 9) {
      printf("request %d -> %s\n", i, resp.to_string().c_str());
    }
  }
  printf("10 requests, %d contexts ever constructed, %zu pooled free\n",
         g_constructed.load(), server.session_data_pool()->free_count());
  server.Stop();
  server.Join();
  printf(g_constructed.load() == 2 ? "ok\n" : "FAIL\n");
  return g_constructed.load() == 2 ? 0 : 1;
}
