// streaming — establish a stream over a normal RPC, upload ordered
// chunks under credit-window flow control, then close (parity:
// example/streaming_echo_c++; the tstd long-payload path).
//
// Run: ./build/example_streaming
#include <atomic>
#include <cstdio>

#include "fiber/sync.h"
#include "net/channel.h"
#include "net/server.h"
#include "net/stream.h"

using namespace trpc;

namespace {
std::atomic<int64_t> g_received_bytes{0};
std::atomic<int> g_received_chunks{0};
CountdownEvent g_closed(1);
}  // namespace

int main() {
  Server server;
  // The stream is OFFERED by the client inside an ordinary call; the
  // handler ACCEPTS it and installs message/close callbacks.
  server.RegisterMethod("Upload.Open", [](Controller* cntl, const IOBuf&,
                                          IOBuf* resp, Closure done) {
    StreamOptions opts;
    opts.on_message = [](StreamId, IOBuf&& chunk) {
      g_received_bytes.fetch_add(chunk.size());
      g_received_chunks.fetch_add(1);
    };
    opts.on_closed = [](StreamId sid) {
      g_closed.signal();
      StreamClose(sid);  // close our half too
    };
    StreamId sid = 0;
    if (StreamAccept(&sid, cntl, opts) != 0) {
      cntl->SetFailed(EINVAL, "no stream offered");
    } else {
      resp->append("accepted");
    }
    done();
  });
  if (server.Start(0) != 0) {
    return 1;
  }
  Channel channel;
  channel.Init("127.0.0.1:" + std::to_string(server.port()));

  // Client side: create the stream against the controller, then make the
  // call that carries the offer.
  Controller cntl;
  cntl.set_timeout_ms(2000);
  StreamId stream = 0;
  StreamOptions client_opts;  // upload-only: no on_message needed
  if (StreamCreate(&stream, &cntl, client_opts) != 0) {
    return 1;
  }
  IOBuf request, response;
  channel.CallMethod("Upload.Open", request, &response, &cntl);
  if (cntl.Failed()) {
    fprintf(stderr, "open failed: %s\n", cntl.error_text().c_str());
    return 1;
  }

  // Write 64 x 64KB; StreamWrite blocks (parks the fiber) when the
  // receiver's credit window is exhausted — built-in backpressure.
  for (int i = 0; i < 64; ++i) {
    IOBuf chunk;
    chunk.append(std::string(64 * 1024, static_cast<char>('a' + i % 26)));
    if (StreamWrite(stream, std::move(chunk)) != 0) {
      fprintf(stderr, "stream write failed\n");
      return 1;
    }
  }
  StreamClose(stream);
  g_closed.wait(-1);
  printf("uploaded %d chunks, %lld bytes; server saw them in order\n",
         g_received_chunks.load(),
         static_cast<long long>(g_received_bytes.load()));
  return g_received_bytes.load() == 64ll * 64 * 1024 ? 0 : 1;
}
