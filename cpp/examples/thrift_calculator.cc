// thrift_calculator — a thrift-speaking service without any codegen:
// handlers work on ThriftValue trees directly (parity:
// example/thrift_extension_c++, which needs .thrift codegen).
//
// Build: cmake --build build --target example_thrift_calculator
#include <cstdio>

#include "net/server.h"
#include "net/thrift.h"

using namespace trpc;

int main() {
  auto* svc = new ThriftService();
  // add(1: i32 a, 2: i32 b) -> i32
  svc->AddMethodHandler(
      "add", [](const ThriftValue& args, std::string* app_error) {
        const ThriftValue* a = args.field(1);
        const ThriftValue* b = args.field(2);
        ThriftValue result = ThriftValue::Struct();
        if (a == nullptr || b == nullptr) {
          *app_error = "add needs fields 1 and 2";
          return result;
        }
        result.add_field(0, ThriftValue::I32(
                                static_cast<int32_t>(a->i + b->i)));
        return result;
      });
  // divide(1: i32 a, 2: i32 b) -> i32, throws on b == 0 (declared
  // exception convention: result field 1).
  svc->AddMethodHandler(
      "divide", [](const ThriftValue& args, std::string* app_error) {
        ThriftValue result = ThriftValue::Struct();
        const ThriftValue* a = args.field(1);
        const ThriftValue* b = args.field(2);
        if (a == nullptr || b == nullptr || b->i == 0) {
          ThriftValue ex = ThriftValue::Struct();
          ex.add_field(1, ThriftValue::Str("division by zero"));
          result.add_field(1, std::move(ex));
          return result;
        }
        (void)app_error;
        result.add_field(0, ThriftValue::I32(
                                static_cast<int32_t>(a->i / b->i)));
        return result;
      });

  Server server;
  server.set_thrift_service(svc);
  if (server.Start(0) != 0) {
    return 1;
  }
  printf("thrift calculator on 127.0.0.1:%d\n", server.port());

  ThriftClient cli;
  if (cli.Init("127.0.0.1:" + std::to_string(server.port())) != 0) {
    return 1;
  }
  ThriftValue args = ThriftValue::Struct();
  args.add_field(1, ThriftValue::I32(40));
  args.add_field(2, ThriftValue::I32(2));
  ThriftClient::Result r = cli.call("add", args);
  if (!r.ok || r.result.field(0) == nullptr) {
    fprintf(stderr, "add failed: %s\n", r.error.c_str());
    return 1;
  }
  printf("add(40, 2) = %lld\n",
         static_cast<long long>(r.result.field(0)->i));

  args = ThriftValue::Struct();
  args.add_field(1, ThriftValue::I32(1));
  args.add_field(2, ThriftValue::I32(0));
  r = cli.call("divide", args);
  const ThriftValue* ex = r.ok ? r.result.field(1) : nullptr;
  printf("divide(1, 0) -> %s\n",
         ex != nullptr && ex->field(1) != nullptr
             ? ex->field(1)->str.c_str()
             : "?!");
  // Unknown methods answer TApplicationException, surfaced in error.
  r = cli.call("nope", ThriftValue::Struct());
  printf("nope() -> ok=%d (%s)\n", r.ok, r.error.c_str());

  server.Stop();
  server.Join();
  printf("ok\n");
  return 0;
}
