// tls_echo — encrypted RPC: the server sniffs each connection's first
// byte, so TLS and plaintext clients share one port (parity:
// ServerOptions::mutable_ssl_options + the reference's sniffing
// acceptor).  Generates a throwaway self-signed cert with the openssl
// CLI.
//
// Run: ./build/example_tls_echo
#include <cstdio>
#include <cstdlib>
#include <string>

#include "net/channel.h"
#include "net/server.h"
#include "net/tls.h"

using namespace trpc;

int main() {
  if (!tls_available()) {
    printf("libssl not available on this host; skipping\n");
    return 0;
  }
  // Private scratch dir: a fixed /tmp name would race concurrent runs
  // (half-written keys → flaky handshakes) and invite symlink planting.
  char dir[] = "/tmp/trpc_tls_XXXXXX";
  if (mkdtemp(dir) == nullptr) {
    return 1;
  }
  const std::string cert = std::string(dir) + "/cert.pem";
  const std::string key = std::string(dir) + "/key.pem";
  const std::string gen =
      "openssl req -x509 -newkey rsa:2048 -nodes -keyout " + key +
      " -out " + cert + " -days 1 -subj /CN=localhost >/dev/null 2>&1";
  if (system(gen.c_str()) != 0) {
    // Missing openssl CLI is an environment gap, not a runtime failure:
    // skip like the missing-libssl case above.
    printf("openssl CLI unavailable; skipping\n");
    return 0;
  }

  Server server;
  server.RegisterMethod("Echo.Echo", [](Controller*, const IOBuf& req,
                                        IOBuf* resp, Closure done) {
    resp->append(req);
    done();
  });
  if (server.EnableTls(cert, key) != 0 || server.Start(0) != 0) {
    return 1;
  }
  const std::string addr = "127.0.0.1:" + std::to_string(server.port());

  {  // Encrypted client.
    Channel ch;
    Channel::Options opts;
    opts.use_tls = true;
    ch.Init(addr, &opts);
    Controller cntl;
    IOBuf req, resp;
    req.append("over-tls");
    ch.CallMethod("Echo.Echo", req, &resp, &cntl);
    if (cntl.Failed()) {
      fprintf(stderr, "tls call failed: %s\n", cntl.error_text().c_str());
      return 1;
    }
    printf("tls echo       : %s (transport=%s)\n",
           resp.to_string().c_str(), ch.transport_name().c_str());
  }
  {  // A PLAINTEXT client on the very same port still works (sniffed).
    Channel ch;
    ch.Init(addr);
    Controller cntl;
    IOBuf req, resp;
    req.append("plaintext");
    ch.CallMethod("Echo.Echo", req, &resp, &cntl);
    if (cntl.Failed()) {
      return 1;
    }
    printf("plaintext echo : %s (same port)\n", resp.to_string().c_str());
  }
  printf("ok\n");
  return 0;
}
