#include "fiber/analysis.h"

#include <mutex>
#include <unordered_map>
#include <unordered_set>
#include <vector>

#include "base/flags.h"
#include "base/symbolize.h"
#include "fiber/fiber.h"
#include "stat/reducer.h"

namespace trpc {
namespace analysis {

std::atomic<bool> g_enabled{false};
std::atomic<bool> g_graph_used{false};

namespace {

// ---- flag ---------------------------------------------------------------

Flag* analysis_flag() {
  static Flag* f = [] {
    Flag* flag = Flag::define_bool(
        "trpc_analysis", false,
        "runtime invariant checkers: fiber-aware lock-order recording and "
        "blocking-call-on-dispatch detection (default off; reports via "
        "analysis_* vars and /analysis)");
    if (flag != nullptr) {
      flag->set_validator([](const std::string& v) {
        return v == "true" || v == "false" || v == "1" || v == "0" ||
               v == "on" || v == "off";
      });
      flag->on_update([](Flag* self) {
        g_enabled.store(self->bool_value(), std::memory_order_release);
      });
    }
    return flag;
  }();
  return f;
}

}  // namespace

void ensure_registered();

namespace {

// Eager registration so /flags?setvalue can flip the flag and /vars can
// scrape the (zero) counters before the first /analysis request.
[[maybe_unused]] const bool g_eager = [] {
  ensure_registered();
  return true;
}();

// ---- vars ---------------------------------------------------------------

struct AnalysisVars {
  Adder cycles;
  Adder violations;
  AnalysisVars() {
    cycles.expose("analysis_lock_cycles",
                  "lock-order inversions (acquisition-graph cycles) found "
                  "by the trpc_analysis lock recorder");
    violations.expose("analysis_blocking_violations",
                      "blocking calls observed inside a dispatch scope "
                      "(messenger inline window / QoS drainer role) by "
                      "the trpc_analysis checker");
  }
};

AnalysisVars& avars() {
  // Deliberately leaked: hooks may fire during static destruction.
  static AnalysisVars* v = new AnalysisVars();
  return *v;
}

// ---- per-context state (fiber-local, pthread fallback) ------------------

constexpr int kMaxHeld = 16;

struct Ctx {
  void* held[kMaxHeld];
  void* sites[kMaxHeld];
  int n_held = 0;
  int dispatch_depth = 0;
  int bounded_depth = 0;  // inside a ScopedBoundedWait (lock slow path)
  const char* dispatch_what = nullptr;
};

void ctx_dtor(void* p) { delete static_cast<Ctx*>(p); }

fls_key_t ctx_key() {
  static fls_key_t key = [] {
    fls_key_t k;
    fls_key_create(&k, ctx_dtor);
    return k;
  }();
  return key;
}

Ctx* ctx() {
  if (in_fiber()) {
    void* v = fls_get(ctx_key());
    if (v == nullptr) {
      v = new Ctx();
      fls_set(ctx_key(), v);
    }
    return static_cast<Ctx*>(v);
  }
  static thread_local Ctx c;
  return &c;
}

// ---- acquisition graph --------------------------------------------------

constexpr size_t kMaxNodes = 4096;    // runaway-growth backstop
constexpr size_t kMaxReports = 32;    // report ring depth

struct Graph {
  std::mutex mu;
  // lock instance → set of lock instances acquired while holding it.
  std::unordered_map<void*, std::unordered_set<void*>> edges;
  // acquisition site per lock (latest wins; for reports only).
  std::unordered_map<void*, void*> site_of;
  // edges already reported as cycle-closing (one report per held→lock
  // pair), keyed like `edges` so destroy can purge them — a stale entry
  // would silently swallow a real inversion between NEW locks recycled
  // onto the same addresses.
  std::unordered_map<void*, std::unordered_set<void*>> reported;
  std::vector<std::string> cycle_reports;
  std::vector<std::string> blocking_reports;
  uint64_t cycles = 0;
  uint64_t violations = 0;
  // kMaxNodes hit: edge recording stopped, "0 inversions" no longer
  // means "checked clean" — surfaced in report() so an operator can
  // tell saturation from a clean bill.
  bool saturated = false;
};

Graph& graph() {
  // Deliberately leaked: fibers may release locks during static
  // destruction.
  static Graph* g = new Graph();
  return *g;
}

// Iterative DFS under graph().mu: is `to` reachable from `from`?
// Explicit worklist, NOT recursion — this runs on fiber stacks (1MB)
// and the graph cap is 4096 nodes.  Path reconstructed via parent map
// for the report (reverse order: from → … → to pushed back-to-front).
bool reachable(const Graph& g, void* from, void* to,
               std::vector<void*>* path, std::unordered_set<void*>* seen) {
  std::unordered_map<void*, void*> parent;
  std::vector<void*> work{from};
  seen->insert(from);
  while (!work.empty()) {
    void* cur = work.back();
    work.pop_back();
    if (cur == to) {
      for (void* p = cur; ; p = parent[p]) {
        path->push_back(p);
        if (p == from) {
          break;
        }
      }
      return true;
    }
    auto it = g.edges.find(cur);
    if (it == g.edges.end()) {
      continue;
    }
    for (void* next : it->second) {
      if (seen->insert(next).second) {
        parent[next] = cur;
        work.push_back(next);
      }
    }
  }
  return false;
}

std::string site_str(const Graph& g, void* lock) {
  auto it = g.site_of.find(lock);
  std::string s = "lock@";
  char buf[32];
  snprintf(buf, sizeof(buf), "%p", lock);
  s += buf;
  if (it != g.site_of.end()) {
    s += " acquired at " + symbolize_addr(it->second);
  }
  return s;
}

}  // namespace

void ensure_registered() {
  analysis_flag();
  avars();  // scrapeable at 0, not only after the first finding
}

void on_lock_acquired(void* lock, void* site) {
  Ctx* c = ctx();
  if (c->n_held > 0) {
    Graph& g = graph();
    std::lock_guard<std::mutex> lk(g.mu);
    if (g.edges.size() >= kMaxNodes) {
      g.saturated = true;
    } else {
      // Armed under g.mu, only when the graph actually gains state —
      // destructors need the purge path exactly while nodes exist.
      g_graph_used.store(true, std::memory_order_relaxed);
      g.site_of[lock] = site;
      for (int i = 0; i < c->n_held; ++i) {
        void* held = c->held[i];
        if (held == lock) {
          continue;  // recursive re-acquire reports elsewhere
        }
        if (!g.edges[held].insert(lock).second) {
          continue;  // known edge, already cycle-checked
        }
        // New edge held→lock: a path lock→…→held makes it a cycle.
        std::vector<void*> path;
        std::unordered_set<void*> seen;
        if (reachable(g, lock, held, &path, &seen) &&
            g.reported[held].insert(lock).second) {
          ++g.cycles;
          avars().cycles << 1;
          std::string r = "lock-order inversion: holding " +
                          site_str(g, held) + " while acquiring " +
                          site_str(g, lock) + "; reverse path:";
          for (auto pit = path.rbegin(); pit != path.rend(); ++pit) {
            r += "\n    " + site_str(g, *pit);
          }
          if (g.cycle_reports.size() < kMaxReports) {
            g.cycle_reports.push_back(std::move(r));
          }
        }
      }
    }
  }
  if (c->n_held < kMaxHeld) {
    c->held[c->n_held] = lock;
    c->sites[c->n_held] = site;
    ++c->n_held;
  }
}

void on_lock_released(void* lock) {
  Ctx* c = ctx();
  for (int i = c->n_held - 1; i >= 0; --i) {  // newest first (stack-ish)
    if (c->held[i] == lock) {
      for (int j = i; j < c->n_held - 1; ++j) {
        c->held[j] = c->held[j + 1];
        c->sites[j] = c->sites[j + 1];
      }
      --c->n_held;
      return;
    }
  }
}

void on_lock_destroyed(void* lock) {
  Graph& g = graph();
  std::lock_guard<std::mutex> lk(g.mu);
  if (g.edges.empty() && g.site_of.empty()) {
    return;
  }
  g.edges.erase(lock);
  for (auto& [from, outs] : g.edges) {
    outs.erase(lock);
  }
  g.site_of.erase(lock);
  g.reported.erase(lock);
  for (auto& [from, outs] : g.reported) {
    outs.erase(lock);
  }
  if (g.edges.empty() && g.site_of.empty()) {
    // Graph drained: restore the destructor fast path (one relaxed load,
    // no global mutex) — otherwise a single flag toggle would serialize
    // every FiberMutex teardown for the rest of the process.
    g_graph_used.store(false, std::memory_order_relaxed);
  }
}

const char* dispatch_scope_enter(const char* what) {
  Ctx* c = ctx();
  ++c->dispatch_depth;
  const char* prev = c->dispatch_what;
  c->dispatch_what = what;
  return prev;
}

void dispatch_scope_exit(const char* prev) {
  Ctx* c = ctx();
  if (c->dispatch_depth > 0) {
    --c->dispatch_depth;
  }
  c->dispatch_what = c->dispatch_depth == 0 ? nullptr : prev;
}

bool in_dispatch_scope() { return ctx()->dispatch_depth > 0; }

void bounded_wait_enter() { ++ctx()->bounded_depth; }

void bounded_wait_exit() {
  Ctx* c = ctx();
  if (c->bounded_depth > 0) {
    --c->bounded_depth;
  }
}

void on_blocking_point(const char* what) {
  Ctx* c = ctx();
  if (c->dispatch_depth <= 0 || c->bounded_depth > 0) {
    return;
  }
  Graph& g = graph();
  std::lock_guard<std::mutex> lk(g.mu);
  ++g.violations;
  avars().violations << 1;
  if (g.blocking_reports.size() < kMaxReports) {
    std::string r = std::string("blocking call (") + what +
                    ") inside dispatch scope ";
    r += c->dispatch_what != nullptr ? c->dispatch_what : "?";
    g.blocking_reports.push_back(std::move(r));
  }
}

uint64_t lock_cycles_found() {
  Graph& g = graph();
  std::lock_guard<std::mutex> lk(g.mu);
  return g.cycles;
}

uint64_t blocking_violations() {
  Graph& g = graph();
  std::lock_guard<std::mutex> lk(g.mu);
  return g.violations;
}

std::string report() {
  ensure_registered();
  Graph& g = graph();
  std::lock_guard<std::mutex> lk(g.mu);
  std::string out = "analysis ";
  out += enabled() ? "ON" : "OFF (set /flags/trpc_analysis?setvalue=true)";
  out += "\nlock graph: " + std::to_string(g.edges.size()) + " nodes";
  if (g.saturated) {
    out += " (SATURATED: node cap hit, edge recording stopped — "
           "inversion counts are a lower bound)";
  }
  out += "\n";
  out += "lock-order inversions: " + std::to_string(g.cycles) + "\n";
  out += "blocking-in-dispatch violations: " +
         std::to_string(g.violations) + "\n";
  for (const std::string& r : g.cycle_reports) {
    out += "\n" + r + "\n";
  }
  for (const std::string& r : g.blocking_reports) {
    out += "\n" + r + "\n";
  }
  return out;
}

void reset_for_test() {
  Graph& g = graph();
  std::lock_guard<std::mutex> lk(g.mu);
  g.edges.clear();
  g.site_of.clear();
  g.reported.clear();
  g.cycle_reports.clear();
  g.blocking_reports.clear();
  g.cycles = 0;
  g.violations = 0;
  g.saturated = false;
  g_graph_used.store(false, std::memory_order_relaxed);
}

}  // namespace analysis
}  // namespace trpc
