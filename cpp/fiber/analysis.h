// Runtime invariant checkers (ISSUE 7) — fiber-aware lock-order
// recording (lockdep-lite) and a blocking-call-on-dispatch-context
// detector, both behind the default-off reloadable `trpc_analysis` flag.
//
// Why in-process instead of leaning on TSan alone: TSan sees memory
// orderings, not POLICIES.  A lock-order inversion that has not yet
// deadlocked and a handler that parks a messenger dispatch fiber are
// both invisible to it, yet both are the exact failure classes of an
// M:N fiber runtime (the no-pinned-read-fiber invariant behind the
// messenger's inline windows and the QoS drainer role).  These checkers
// run in ANY build — including production, flipped on via
// /flags/trpc_analysis?setvalue=true — and report through vars
// (analysis_lock_cycles / analysis_blocking_violations) and the
// /analysis builtin.  With the flag off every hook is one relaxed
// atomic load + branch; the perf-smoke floors gate that.
#pragma once

#include <atomic>
#include <cstdint>
#include <string>

namespace trpc {
namespace analysis {

// Backing switch for the reloadable trpc_analysis flag (kept in a plain
// atomic so the hot-path gate below inlines to one relaxed load; the
// flag's on_update hook writes it).  Call ensure_registered() once per
// surface that can flip the flag before first use (builtin /flags does).
extern std::atomic<bool> g_enabled;
// Sticky: set on the first recorded acquisition and never cleared, so
// cold paths (lock destructors) can skip the graph mutex entirely in
// processes that never armed the mode — while a process that toggled
// the flag off STILL purges destroyed locks from the populated graph
// (gating purely on enabled() resurrects address-reuse phantom cycles).
extern std::atomic<bool> g_graph_used;
void ensure_registered();

inline bool enabled() {
  return g_enabled.load(std::memory_order_relaxed);
}

inline bool graph_used() {
  return g_graph_used.load(std::memory_order_relaxed);
}

// ---- fiber-aware lock-order recorder (lockdep-lite) --------------------
// Instrumented by FiberMutex (fiber/sync.h).  Held-lock stacks live in
// fiber-local storage (a parked fiber migrating workers keeps its
// stack); plain pthreads fall back to thread-local.  Each acquisition
// adds held→new edges to a global acquisition graph; an edge that
// closes a cycle is a lock-order inversion, reported once per edge with
// the symbolized acquisition sites.  `site` is the caller's return
// address (the acquisition site named in reports).
void on_lock_acquired(void* lock, void* site);
void on_lock_released(void* lock);
// Called from the lock's destructor: drops the instance's node and every
// edge touching it.  Without this, address reuse (a destroyed stack/heap
// mutex's address recycled by an unrelated one) would stitch phantom
// cycles between locks that never coexisted, and dead nodes would pin
// the graph's node cap forever.
void on_lock_destroyed(void* lock);

// ---- blocking-call-on-dispatch-context detector ------------------------
// The messenger's inline dispatch windows and the QoS drainer role mark
// themselves as dispatch scopes; any would-block point reached inside
// one (Event::wait about to park, ScopedPthreadWait pinning the worker)
// is a violation of the no-pinned-read-fiber invariant.
// enter returns the PREVIOUS scope label; pass it back to exit so a
// nested scope (messenger inline window → QoS drainer role) restores
// the outer label instead of leaving violations misattributed.
const char* dispatch_scope_enter(const char* what);
void dispatch_scope_exit(const char* prev);
bool in_dispatch_scope();
void on_blocking_point(const char* what);

// RAII for runtime call sites; no-ops (and no FLS touch) when disabled.
class ScopedDispatch {
 public:
  explicit ScopedDispatch(const char* what) : armed_(enabled()) {
    if (armed_) {
      prev_ = dispatch_scope_enter(what);
    }
  }
  ~ScopedDispatch() {
    if (armed_) {
      dispatch_scope_exit(prev_);
    }
  }

 private:
  bool armed_;
  const char* prev_ = nullptr;
};

// Marks a BOUNDED wait — a park whose duration is capped by framework
// lock-hold times (FiberMutex's contended slow path), not by arbitrary
// user code or external events.  The blocking detector exempts these:
// contended-lock microsleeps inside an inline dispatch window are
// normal (and showed up 249 times in a 3s echo run when first armed);
// reporting them would bury the real unbounded parks the
// no-pinned-read-fiber invariant is about.  Fiber-aware (the flag lives
// in the same FLS context), so a lock waiter migrating workers keeps it.
void bounded_wait_enter();
void bounded_wait_exit();
class ScopedBoundedWait {
 public:
  ScopedBoundedWait() : armed_(enabled()) {
    if (armed_) {
      bounded_wait_enter();
    }
  }
  ~ScopedBoundedWait() {
    if (armed_) {
      bounded_wait_exit();
    }
  }

 private:
  bool armed_;
};

// ---- reporting ---------------------------------------------------------
uint64_t lock_cycles_found();
uint64_t blocking_violations();
// Human-readable state dump for the /analysis builtin: enabled bit,
// graph size, recorded cycles and blocking violations (newest last).
std::string report();
// Test support: drop the graph, rings and counters (vars keep their
// lifetime totals; the report ring is cleared).
void reset_for_test();

}  // namespace analysis
}  // namespace trpc
