// Userspace context switching — the fcontext core of the fiber runtime.
//
// Parity: bthread's boost.context-derived assembly
// (/root/reference/src/bthread/context.h:80-90).  Re-designed minimal for
// x86_64 SysV: a suspended context IS its stack pointer; jump saves the six
// callee-saved registers + mxcsr/x87cw on the current stack and switches.
#pragma once

#include <cstddef>
#include <cstdint>

extern "C" {

// Saves the current continuation (sp stored into *save_sp), switches to
// target_sp, and makes `arg` the return value observed by the resumed
// context (or the entry argument of a fresh context).
void* trpc_jump_context(void** save_sp, void* target_sp, void* arg);

}  // extern "C"

namespace trpc {

// Builds a fresh suspended context on [stack_base, stack_base+size).
// When first jumped to, calls entry(arg) where arg is the jump's 3rd
// argument.  entry must never return (switch away instead).
void* make_context(void* stack_base, size_t size, void (*entry)(void*));

}  // namespace trpc
