// C++20 coroutine adapter over the fiber runtime.
//
// Parity: the reference's experimental coroutine bridge
// (/root/reference/src/brpc/coroutine.h + usercode_in_coroutine):
// user code written as co_await chains rides the same scheduler as
// callback code.  Condensed form: CoTask<T> (eager coroutine whose
// completion is a fiber-parkable event), co_run (run a callable on a
// fresh fiber, resume the coroutine when it returns), and co_call
// (issue an async Channel RPC, resume on its done closure).  A resumed
// coroutine continues on the fiber that completed the awaited work —
// the same continuation-stealing the reference's bridge does.
#pragma once

#include <atomic>
#include <coroutine>
#include <exception>
#include <functional>
#include <memory>
#include <optional>
#include <string>
#include <utility>

#include "fiber/fiber.h"
#include "fiber/sync.h"
#include "net/channel.h"
#include "net/controller.h"

namespace trpc {

// Eagerly-started coroutine handle.  join() parks the calling fiber (or
// pthread) until the body completes; co_await composes tasks (single
// awaiter).  Exceptions thrown by the body rethrow from join() /
// await_resume().
template <typename T>
class CoTask {
  // Completion state lives on the HEAP, shared by the frame's promise,
  // the task object, and (via a stack-local copy) the completing fiber:
  // the final signal may release a join()er whose ~CoTask destroys the
  // coroutine frame instantly, and CountdownEvent::signal touches its
  // Event after the count hits zero — so the signaled object must
  // outlive the frame, which a promise member cannot.
  struct State {
    std::optional<T> value;
    std::exception_ptr error;
    // The completion handshake: nullptr = running & unawaited, the done
    // sentinel = body finished, anything else = the awaiting parent's
    // handle.  A single CAS on each side closes the suspend-vs-complete
    // race (no lost wakeup, no double resume).
    std::atomic<void*> waiter{nullptr};
    CountdownEvent done{1};
  };

  static void* done_sentinel() {
    static char sentinel;
    return &sentinel;
  }

 public:
  struct promise_type {
    std::shared_ptr<State> state = std::make_shared<State>();

    CoTask get_return_object() {
      return CoTask(
          std::coroutine_handle<promise_type>::from_promise(*this),
          state);
    }
    std::suspend_never initial_suspend() noexcept { return {}; }
    struct FinalAwaiter {
      bool await_ready() noexcept { return false; }
      std::coroutine_handle<> await_suspend(
          std::coroutine_handle<promise_type> h) noexcept {
        // Stack-local ref: everything after this line must survive the
        // frame (a released join()er may destroy it concurrently).
        std::shared_ptr<State> st = h.promise().state;
        void* prev = st->waiter.exchange(done_sentinel(),
                                         std::memory_order_acq_rel);
        std::coroutine_handle<> next =
            prev != nullptr ? std::coroutine_handle<>::from_address(prev)
                            : std::noop_coroutine();
        st->done.signal();  // touches only the heap State
        return next;
      }
      void await_resume() noexcept {}
    };
    FinalAwaiter final_suspend() noexcept { return {}; }
    void return_value(T v) { state->value = std::move(v); }
    void unhandled_exception() { state->error = std::current_exception(); }
  };

  CoTask(std::coroutine_handle<promise_type> h, std::shared_ptr<State> st)
      : h_(h), st_(std::move(st)) {}
  CoTask(CoTask&& o) noexcept
      : h_(std::exchange(o.h_, nullptr)), st_(std::move(o.st_)) {}
  CoTask(const CoTask&) = delete;
  ~CoTask() {
    if (h_) {
      st_->done.wait(-1);  // the frame dies with the task object
      h_.destroy();
    }
  }

  // Parks until the coroutine body has finished; returns its value (or
  // rethrows what the body threw).
  T join() {
    st_->done.wait(-1);
    return take();
  }

  // Composition: co_await task.
  bool await_ready() {
    return st_->waiter.load(std::memory_order_acquire) ==
           done_sentinel();
  }
  bool await_suspend(std::coroutine_handle<> parent) {
    void* expected = nullptr;
    if (st_->waiter.compare_exchange_strong(
            expected, parent.address(), std::memory_order_acq_rel)) {
      return true;  // FinalAwaiter will resume the parent
    }
    return false;  // completed in the window: resume immediately
  }
  T await_resume() { return take(); }

 private:
  T take() {
    if (st_->error) {
      std::rethrow_exception(st_->error);
    }
    return std::move(*st_->value);
  }

  std::coroutine_handle<promise_type> h_;
  std::shared_ptr<State> st_;
};

// Awaitable running `fn` on a fresh fiber; the coroutine resumes (on
// that fiber) with fn's return value.
template <typename Fn>
auto co_run(Fn fn) {
  using R = decltype(fn());
  struct Awaiter {
    Fn fn;
    std::optional<R> result;
    std::coroutine_handle<> h;

    bool await_ready() { return false; }
    bool await_suspend(std::coroutine_handle<> handle) {
      h = handle;
      if (fiber_start(
              nullptr,
              [](void* arg) {
                auto* self = static_cast<Awaiter*>(arg);
                self->result = self->fn();
                self->h.resume();  // continuation runs on this fiber
              },
              this, 0) != 0) {
        // Spawn failure (fiber exhaustion): run inline and continue
        // without suspending — hanging the coroutine forever is the one
        // unacceptable outcome.
        result = fn();
        return false;
      }
      return true;
    }
    R await_resume() { return std::move(*result); }
  };
  return Awaiter{std::move(fn)};
}

// Awaitable for one async RPC: issues CallMethod with a done closure
// that resumes the coroutine (on the response fiber).  The caller owns
// cntl/response, same lifetimes as the callback API.
inline auto co_call(Channel* ch, const std::string& method,
                    const IOBuf& request, IOBuf* response,
                    Controller* cntl) {
  struct Awaiter {
    Channel* ch;
    const std::string& method;
    const IOBuf& request;
    IOBuf* response;
    Controller* cntl;

    bool await_ready() { return false; }
    void await_suspend(std::coroutine_handle<> h) {
      ch->CallMethod(method, request, response, cntl,
                     [h]() mutable { h.resume(); });
    }
    void await_resume() {}
  };
  return Awaiter{ch, method, request, response, cntl};
}

}  // namespace trpc
