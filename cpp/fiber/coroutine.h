// C++20 coroutine adapter over the fiber runtime.
//
// Parity: the reference's experimental coroutine bridge
// (/root/reference/src/brpc/coroutine.h + usercode_in_coroutine):
// user code written as co_await chains rides the same scheduler as
// callback code.  Condensed form: CoTask<T> (eager coroutine whose
// completion is a fiber-parkable event), co_run (run a callable on a
// fresh fiber, resume the coroutine when it returns), and co_call
// (issue an async Channel RPC, resume on its done closure).  A resumed
// coroutine continues on the fiber that completed the awaited work —
// the same continuation-stealing the reference's bridge does.
#pragma once

#include <coroutine>
#include <functional>
#include <optional>
#include <utility>

#include "fiber/fiber.h"
#include "fiber/sync.h"
#include "net/channel.h"
#include "net/controller.h"

namespace trpc {

// Eagerly-started coroutine handle.  join() parks the calling fiber (or
// pthread) until the body completes; co_await composes tasks (single
// awaiter).  Exceptions thrown by the body rethrow from join() /
// await_resume().
template <typename T>
class CoTask {
  // `waiter` is the completion handshake: nullptr = running & unawaited,
  // kDoneSentinel = body finished, anything else = the awaiting parent's
  // handle.  A single CAS on each side closes the suspend-vs-complete
  // race (the lost-wakeup and the double-resume are both impossible).
  static void* done_sentinel() {
    static char sentinel;
    return &sentinel;
  }

 public:
  struct promise_type {
    std::optional<T> value;
    std::exception_ptr error;
    std::atomic<void*> waiter{nullptr};
    CountdownEvent done{1};  // for join(); signaled LAST

    CoTask get_return_object() {
      return CoTask(
          std::coroutine_handle<promise_type>::from_promise(*this));
    }
    std::suspend_never initial_suspend() noexcept { return {}; }
    struct FinalAwaiter {
      bool await_ready() noexcept { return false; }
      std::coroutine_handle<> await_suspend(
          std::coroutine_handle<promise_type> h) noexcept {
        promise_type& p = h.promise();
        // Claim completion; learn whether a parent already attached.
        void* prev = p.waiter.exchange(done_sentinel(),
                                       std::memory_order_acq_rel);
        std::coroutine_handle<> next =
            prev != nullptr ? std::coroutine_handle<>::from_address(prev)
                            : std::noop_coroutine();
        // done.signal() is the LAST touch of the promise: it may release
        // a join()er whose ~CoTask destroys this frame immediately.
        p.done.signal();
        return next;
      }
      void await_resume() noexcept {}
    };
    FinalAwaiter final_suspend() noexcept { return {}; }
    void return_value(T v) { value = std::move(v); }
    void unhandled_exception() { error = std::current_exception(); }
  };

  explicit CoTask(std::coroutine_handle<promise_type> h) : h_(h) {}
  CoTask(CoTask&& o) noexcept : h_(std::exchange(o.h_, nullptr)) {}
  CoTask(const CoTask&) = delete;
  ~CoTask() {
    if (h_) {
      h_.promise().done.wait(-1);  // the frame dies with the task object
      h_.destroy();
    }
  }

  // Parks until the coroutine body has finished; returns its value (or
  // rethrows what the body threw).
  T join() {
    h_.promise().done.wait(-1);
    return take();
  }

  // Composition: co_await task.
  bool await_ready() {
    return h_.promise().waiter.load(std::memory_order_acquire) ==
           done_sentinel();
  }
  bool await_suspend(std::coroutine_handle<> parent) {
    void* expected = nullptr;
    if (h_.promise().waiter.compare_exchange_strong(
            expected, parent.address(), std::memory_order_acq_rel)) {
      return true;  // FinalAwaiter will resume the parent
    }
    return false;  // completed in the window: resume immediately
  }
  T await_resume() { return take(); }

 private:
  T take() {
    promise_type& p = h_.promise();
    if (p.error) {
      std::rethrow_exception(p.error);
    }
    return std::move(*p.value);
  }

  std::coroutine_handle<promise_type> h_;
};

// Awaitable running `fn` on a fresh fiber; the coroutine resumes (on
// that fiber) with fn's return value.
template <typename Fn>
auto co_run(Fn fn) {
  using R = decltype(fn());
  struct Awaiter {
    Fn fn;
    std::optional<R> result;
    std::coroutine_handle<> h;

    bool await_ready() { return false; }
    void await_suspend(std::coroutine_handle<> handle) {
      h = handle;
      fiber_start(
          nullptr,
          [](void* arg) {
            auto* self = static_cast<Awaiter*>(arg);
            self->result = self->fn();
            self->h.resume();  // continuation runs on this fiber
          },
          this, 0);
    }
    R await_resume() { return std::move(*result); }
  };
  return Awaiter{std::move(fn)};
}

// Awaitable for one async RPC: issues CallMethod with a done closure
// that resumes the coroutine (on the response fiber).  The caller owns
// cntl/response, same lifetimes as the callback API.
inline auto co_call(Channel* ch, const std::string& method,
                    const IOBuf& request, IOBuf* response,
                    Controller* cntl) {
  struct Awaiter {
    Channel* ch;
    const std::string& method;
    const IOBuf& request;
    IOBuf* response;
    Controller* cntl;

    bool await_ready() { return false; }
    void await_suspend(std::coroutine_handle<> h) {
      ch->CallMethod(method, request, response, cntl,
                     [h]() mutable { h.resume(); });
    }
    void await_resume() {}
  };
  return Awaiter{ch, method, request, response, cntl};
}

}  // namespace trpc
