#include "fiber/event.h"

#include <linux/futex.h>
#include <sys/syscall.h>
#include <unistd.h>

#include <cerrno>

#include "base/logging.h"
#include "base/time.h"
#include "fiber/analysis.h"
#include "fiber/scheduler.h"
#include "fiber/timer.h"

namespace trpc {

namespace {

int futex_wait_private(std::atomic<int>* addr, int expected,
                       const timespec* timeout) {
  return syscall(SYS_futex, reinterpret_cast<int*>(addr), FUTEX_WAIT_PRIVATE,
                 expected, timeout, nullptr, 0);
}

int futex_wake_private(std::atomic<int>* addr, int n) {
  return syscall(SYS_futex, reinterpret_cast<int*>(addr), FUTEX_WAKE_PRIVATE,
                 n, nullptr, nullptr, 0);
}

}  // namespace

// Waiter node.  Fiber waiters are heap-allocated and ref-counted because a
// timeout timer can outlive the wait; pthread waiters live on the caller's
// stack (unlinked under the event lock before return).
struct EventWaiter {
  EventWaiter* next = nullptr;
  EventWaiter* prev = nullptr;
  Event* ev = nullptr;
  FiberMeta* fiber = nullptr;          // null → pthread waiter
  std::atomic<int> pword{0};           // pthread futex word (1 = woken)
  std::atomic<int> refs{1};
  uint64_t timer_id = 0;
  int64_t deadline_us = -1;            // >=0 → publish schedules a timer
  uint32_t expected = 0;
  bool linked = false;
  bool timedout = false;
  bool no_link = false;  // value changed before publish; wait returns 0

  void unref() {
    if (refs.fetch_sub(1, std::memory_order_acq_rel) == 1) {
      delete this;
    }
  }
};

void Event::lock() {
  while (lock_.test_and_set(std::memory_order_acquire)) {
#if defined(__x86_64__)
    __builtin_ia32_pause();
#elif defined(__aarch64__)
    __asm__ volatile("yield");
#endif
  }
}

void Event::unlock() { lock_.clear(std::memory_order_release); }

Event::~Event() {
  // Waiters must be gone; waking here would race with destruction anyway.
  lock();
  CHECK(head_ == nullptr) << "Event destroyed with waiters";
  unlock();
}

void event_timeout_cb(void* p) {
  EventWaiter* w = static_cast<EventWaiter*>(p);
  Event* ev = w->ev;
  FiberMeta* to_wake = nullptr;
  ev->lock();
  if (w->linked) {
    // Unlink and wake with the timeout flag.
    if (w->prev != nullptr) {
      w->prev->next = w->next;
    } else {
      ev->head_ = w->next;
    }
    if (w->next != nullptr) {
      w->next->prev = w->prev;
    } else {
      ev->tail_ = w->prev;
    }
    w->linked = false;
    w->timedout = true;
    to_wake = w->fiber;
  }
  ev->unlock();
  if (to_wake != nullptr) {
    Scheduler::instance()->ready_to_run(to_wake);
  }
  w->unref();
}

// Runs on the scheduler context after the waiting fiber switched away.
// a1 = Event*, a2 = EventWaiter*.
void Event::publish_post(void* a1, void* a2) {
  Event* ev = static_cast<Event*>(a1);
  EventWaiter* w = static_cast<EventWaiter*>(a2);
  bool requeue = false;
  ev->lock();
  if (ev->value.load(std::memory_order_relaxed) != w->expected) {
    // Raced with a change: don't block after all.
    w->no_link = true;
    requeue = true;
  } else if (w->fiber->interrupted.load(std::memory_order_acquire)) {
    // A pending interrupt that arrived before we could link would be lost
    // (the interrupter's wake found no node): don't park at all — the
    // wait converts the flag to EINTR.  Decided UNDER the lock; touching
    // the node after unlock would race a concurrent waker freeing it.
    w->no_link = true;
    requeue = true;
  } else {
    w->linked = true;
    w->prev = ev->tail_;
    w->next = nullptr;
    if (ev->tail_ != nullptr) {
      ev->tail_->next = w;
    } else {
      ev->head_ = w;
    }
    ev->tail_ = w;
    if (w->deadline_us >= 0) {
      w->refs.fetch_add(1, std::memory_order_relaxed);
      w->timer_id = TimerThread::instance()->schedule(w->deadline_us,
                                                      event_timeout_cb, w);
    }
  }
  ev->unlock();
  if (requeue) {
    Scheduler::instance()->ready_to_run(w->fiber);
  }
}

thread_local bool tls_force_pthread_wait = false;

ScopedPthreadWait::ScopedPthreadWait() : prev_(tls_force_pthread_wait) {
  // No analysis report here: entering pthread-wait mode only pins the
  // worker if a wait actually blocks, and Event::wait reports at that
  // would-block point — a ctor report would double-count it (and fire
  // even on paths that never block).
  tls_force_pthread_wait = true;
}

ScopedPthreadWait::~ScopedPthreadWait() { tls_force_pthread_wait = prev_; }

bool in_pthread_wait_mode() { return tls_force_pthread_wait; }

int Event::wait(uint32_t expected, int64_t deadline_us) {
  if (value.load(std::memory_order_acquire) != expected) {
    return EWOULDBLOCK;
  }
  // Invariant checker (ISSUE 7): about to actually block — a park inside
  // a dispatch scope (messenger inline window, QoS drainer role) pins
  // connection/lane dispatch behind arbitrary wait time.  Report-only.
  if (analysis::enabled() && analysis::in_dispatch_scope()) {
    analysis::on_blocking_point("Event::wait");
  }
  Worker* w = tls_worker;
  if (w != nullptr && w->current() != nullptr && !tls_force_pthread_wait) {
    // -- fiber path --
    EventWaiter* node = new EventWaiter();
    node->ev = this;
    node->fiber = w->current();
    node->expected = expected;
    node->deadline_us = deadline_us;
    node->fiber->park_lock();
    node->fiber->parked_on.store(this, std::memory_order_release);
    node->fiber->park_unlock();
    w->suspend_current(&Event::publish_post, this, node);
    // Resumed: either woken, timed out, interrupted, or never linked.
    // Clearing parked_on under the park lock guarantees no interrupter is
    // still inside wake_all on this Event when we return (and possibly
    // destroy it — fiber_sleep parks on a stack Event).
    node->fiber->park_lock();
    node->fiber->parked_on.store(nullptr, std::memory_order_release);
    node->fiber->park_unlock();
    int rc = 0;
    uint64_t timer_to_cancel = 0;
    lock();
    if (node->timedout) {
      rc = ETIMEDOUT;
    } else if (!node->no_link && node->timer_id != 0) {
      timer_to_cancel = node->timer_id;
    }
    unlock();
    if (timer_to_cancel != 0 &&
        TimerThread::instance()->unschedule(timer_to_cancel)) {
      node->unref();  // timer will never run
    }
    FiberMeta* self = node->fiber;  // pool memory, outlives the node
    node->unref();
    if (self->interrupted.exchange(false, std::memory_order_acq_rel)) {
      rc = EINTR;  // fiber_interrupt consumed by this wait
    }
    return rc;
  }
  // -- pthread path --
  EventWaiter node;
  node.ev = this;
  node.expected = expected;
  lock();
  if (value.load(std::memory_order_relaxed) != expected) {
    unlock();
    return EWOULDBLOCK;
  }
  node.linked = true;
  node.prev = tail_;
  if (tail_ != nullptr) {
    tail_->next = &node;
  } else {
    head_ = &node;
  }
  tail_ = &node;
  unlock();

  int rc = 0;
  while (node.pword.load(std::memory_order_acquire) == 0) {
    timespec ts;
    timespec* tsp = nullptr;
    if (deadline_us >= 0) {
      const int64_t now = monotonic_time_us();
      int64_t left = deadline_us - now;
      if (left <= 0) {
        rc = ETIMEDOUT;
        break;
      }
      ts.tv_sec = left / 1000000;
      ts.tv_nsec = (left % 1000000) * 1000;
      tsp = &ts;
    }
    futex_wait_private(&node.pword, 0, tsp);
  }
  if (rc == ETIMEDOUT) {
    lock();
    const bool was_linked = node.linked;
    if (was_linked) {
      if (node.prev != nullptr) {
        node.prev->next = node.next;
      } else {
        head_ = node.next;
      }
      if (node.next != nullptr) {
        node.next->prev = node.prev;
      } else {
        tail_ = node.prev;
      }
      node.linked = false;
    }
    unlock();
    if (!was_linked) {
      // Woken concurrently with the timeout: the waker will still store to
      // our stack node; wait for it so the access finishes before return.
      rc = 0;
      while (node.pword.load(std::memory_order_acquire) == 0) {
        futex_wait_private(&node.pword, 0, nullptr);
      }
    }
  }
  return rc;
}

int Event::wake(int n) {
  FiberMeta* fibers[16];
  int woken = 0;
  while (woken < n) {
    int batch_fibers = 0;
    EventWaiter* pthread_nodes[16];
    int batch_pthreads = 0;
    lock();
    while (woken < n && head_ != nullptr && batch_fibers < 16 &&
           batch_pthreads < 16) {
      EventWaiter* w = head_;
      head_ = w->next;
      if (head_ != nullptr) {
        head_->prev = nullptr;
      } else {
        tail_ = nullptr;
      }
      w->linked = false;
      if (w->fiber != nullptr) {
        fibers[batch_fibers++] = w->fiber;
      } else {
        pthread_nodes[batch_pthreads++] = w;
      }
      ++woken;
    }
    const bool more = head_ != nullptr;
    unlock();
    for (int i = 0; i < batch_fibers; ++i) {
      Scheduler::instance()->ready_to_run(fibers[i]);
    }
    for (int i = 0; i < batch_pthreads; ++i) {
      pthread_nodes[i]->pword.store(1, std::memory_order_release);
      futex_wake_private(&pthread_nodes[i]->pword, 1);
    }
    if (!more || woken >= n) {
      break;
    }
  }
  return woken;
}

void fiber_sleep_until_us(int64_t deadline_us) {
  Worker* w = tls_worker;
  if (w == nullptr || w->current() == nullptr) {
    const int64_t left = deadline_us - monotonic_time_us();
    if (left > 0) {
      usleep(static_cast<useconds_t>(left));
    }
    return;
  }
  Event ev;  // nobody wakes it; the deadline does
  ev.wait(0, deadline_us);
}

void fiber_sleep_us(int64_t us) {
  fiber_sleep_until_us(monotonic_time_us() + us);
}

}  // namespace trpc
