// Event — futex semantics on a 32-bit word for fibers AND pthreads.
//
// Parity: bthread's butex (/root/reference/src/bthread/butex.h:41-84), THE
// blocking primitive everything above reduces to.  wait() blocks only while
// value == expected (checked again under the internal lock after the context
// switch — the publish-after-switch pattern); wake() moves fiber waiters
// back to a run queue and kicks pthread waiters' kernel futex.  This is the
// seam where "park on DMA completion" plugs in: whatever thread observes a
// completion just calls wake().
#pragma once

#include <atomic>
#include <cstdint>

namespace trpc {

struct FiberMeta;
struct EventWaiter;

class Event {
 public:
  std::atomic<uint32_t> value{0};

  // Blocks while value == expected.  Returns 0 when woken, EWOULDBLOCK if
  // value != expected on entry, ETIMEDOUT when deadline_us (monotonic,
  // -1 = none) passes.  Callable from fibers and plain pthreads.
  int wait(uint32_t expected, int64_t deadline_us = -1);
  // Wakes up to n waiters; returns the number woken.
  int wake(int n);
  int wake_all() { return wake(1 << 30); }

  ~Event();

 private:
  friend struct EventWaiter;
  friend void event_timeout_cb(void* p);
  void lock();
  void unlock();
  static void publish_post(void* a1, void* a2);

  std::atomic_flag lock_ = ATOMIC_FLAG_INIT;
  EventWaiter* head_ = nullptr;  // doubly-linked FIFO
  EventWaiter* tail_ = nullptr;
};

// Sleep usable from fibers (parks on a private Event) and pthreads.
void fiber_sleep_until_us(int64_t deadline_us_monotonic);

// While set on the calling thread, Event::wait blocks the PTHREAD even when
// called from a fiber (no context switch, no migration).  Embedded-language
// callbacks (ctypes) need this: CPython's GIL state is thread-bound, so a
// parked fiber resuming on another worker would corrupt it.  Costs a worker
// thread while blocked — the usercode_in_pthread trade-off
// (/root/reference/src/brpc/details/usercode_backup_pool.h).
class ScopedPthreadWait {
 public:
  ScopedPthreadWait();
  ~ScopedPthreadWait();

 private:
  bool prev_;
};

// True while the calling thread is inside a ScopedPthreadWait region.
bool in_pthread_wait_mode();

}  // namespace trpc
