// ExecutionQueue — MPSC serialized executor.
//
// Parity: bthread ExecutionQueue
// (/root/reference/src/bthread/execution_queue.h:163-196): lock-free
// multi-producer push, one consumer fiber draining batches in order; used by
// streaming RPC and LB feedback.  Re-designed: Treiber push + reverse drain
// (the reference threads an intrusive doubly list through nodes).
#pragma once

#include <atomic>

#include "fiber/fiber.h"

namespace trpc {

template <typename T>
class ExecutionQueue {
 public:
  // handler(meta, items, n): consume a FIFO batch.  Return nonzero to stop.
  using Handler = int (*)(void* meta, T* items, size_t n);

  // drop_fn (optional) disposes items discarded by a stop-drain (e.g. heap
  // payloads the handler would have freed).
  using DropFn = void (*)(T&);

  void start(Handler handler, void* meta, DropFn drop_fn = nullptr) {
    handler_ = handler;
    meta_ = meta;
    drop_fn_ = drop_fn;
  }

  // Reuse after a stop(): drains leftovers and accepts work again.  Only
  // legal when no consumer is live (idle()).
  void restart(Handler handler, void* meta, DropFn drop_fn = nullptr) {
    drain(head_.exchange(nullptr, std::memory_order_acquire));
    handler_ = handler;
    meta_ = meta;
    drop_fn_ = drop_fn;
    running_.store(false, std::memory_order_relaxed);
    stopped_.store(false, std::memory_order_release);
  }

  // Callable from any thread/fiber.  Returns 0, or -1 after stop().
  int execute(const T& item) {
    if (stopped_.load(std::memory_order_acquire)) {
      return -1;
    }
    Node* n = new Node{item, nullptr};
    Node* old = head_.load(std::memory_order_relaxed);
    do {
      n->next = old;
    } while (!head_.compare_exchange_weak(old, n, std::memory_order_release,
                                          std::memory_order_relaxed));
    if (old == nullptr) {
      // Queue was empty: become (or spawn) the consumer.
      schedule_consumer();
    }
    return 0;
  }

  void stop() { stopped_.store(true, std::memory_order_release); }

  ~ExecutionQueue() {
    drain(head_.exchange(nullptr, std::memory_order_acquire));
  }

  bool idle() const {
    return head_.load(std::memory_order_acquire) == nullptr &&
           !running_.load(std::memory_order_acquire);
  }

 private:
  struct Node {
    T value;
    Node* next;
  };

  void schedule_consumer() {
    bool expect = false;
    if (!running_.compare_exchange_strong(expect, true,
                                          std::memory_order_acq_rel)) {
      return;  // a consumer is already live; it will re-check before idling
    }
    fiber_start(nullptr, &ExecutionQueue::consume_thunk, this, 0);
  }

  static void consume_thunk(void* self) {
    static_cast<ExecutionQueue*>(self)->consume();
  }

  void consume() {
    while (true) {
      Node* chain = head_.exchange(nullptr, std::memory_order_acquire);
      if (chain == nullptr) {
        running_.store(false, std::memory_order_release);
        // Producers that pushed after our exchange saw old==non-null only if
        // they raced before it; re-check to close the window.
        if (head_.load(std::memory_order_acquire) != nullptr) {
          bool expect = false;
          if (running_.compare_exchange_strong(expect, true,
                                               std::memory_order_acq_rel)) {
            continue;
          }
        }
        return;
      }
      // Reverse the LIFO chain into FIFO order.
      Node* fifo = nullptr;
      size_t count = 0;
      while (chain != nullptr) {
        Node* next = chain->next;
        chain->next = fifo;
        fifo = chain;
        chain = next;
        ++count;
      }
      // Copy into a flat batch for the handler.
      T* batch = new T[count];
      size_t i = 0;
      while (fifo != nullptr) {
        batch[i++] = fifo->value;
        Node* done = fifo;
        fifo = fifo->next;
        delete done;
      }
      const int rc = handler_(meta_, batch, count);
      delete[] batch;
      if (rc != 0) {
        // Handler asked to stop: refuse new work, then drain (and free)
        // anything pushed concurrently so nodes can't leak.
        stopped_.store(true, std::memory_order_release);
        drain(head_.exchange(nullptr, std::memory_order_acquire));
        running_.store(false, std::memory_order_release);
        return;
      }
    }
  }

  void drain(Node* chain) {
    while (chain != nullptr) {
      Node* next = chain->next;
      if (drop_fn_ != nullptr) {
        drop_fn_(chain->value);
      }
      delete chain;
      chain = next;
    }
  }

  Handler handler_ = nullptr;
  DropFn drop_fn_ = nullptr;
  void* meta_ = nullptr;
  std::atomic<Node*> head_{nullptr};
  std::atomic<bool> running_{false};
  std::atomic<bool> stopped_{false};
};

}  // namespace trpc
