// fiber_fd_wait — park a fiber until an arbitrary fd is ready.
//
// Parity: bthread_fd_wait/timedwait (/root/reference/src/bthread/fd.cpp):
// fibers wait on fds they do not own through the event machinery instead
// of blocking worker pthreads.  Redesigned: a dedicated poller pthread
// runs its own epoll of ONESHOT registrations keyed by fd; each fd keeps
// a waiter LIST (concurrent waits on one fd — reader and writer — are
// armed with the union of their masks and woken selectively), and each
// wait parks on a per-call Event the poller wakes.  (Sockets owned by the
// runtime keep using the main dispatcher; this path serves user fds.)
#include <errno.h>
#include <pthread.h>
#include <sys/epoll.h>
#include <unistd.h>

#include <atomic>
#include <map>
#include <mutex>
#include <vector>

#include "base/logging.h"
#include "fiber/event.h"
#include "fiber/fiber.h"

namespace trpc {

namespace {

struct FdWait {
  Event ev;               // value 0 = pending; 1 = ready
  int want = 0;           // EPOLLIN / EPOLLOUT / ...
  std::atomic<int> revents{0};
};

class FdPoller {
 public:
  static FdPoller* instance() {
    static FdPoller* p = new FdPoller();  // leaked singleton
    return p;
  }

  int wait(int fd, int events, int64_t deadline_us) {
    FdWait w;
    w.want = events;
    {
      std::lock_guard<std::mutex> g(mu_);
      fds_[fd].push_back(&w);
      if (rearm_locked(fd) != 0) {
        const int saved = errno;
        unregister_locked(fd, &w);
        errno = saved;
        return -1;
      }
    }
    const int rc = w.ev.wait(0, deadline_us);
    {
      // Removing ourselves under the lock guarantees the poller is not
      // mid-wake on our stack-resident Event after we return.
      std::lock_guard<std::mutex> g(mu_);
      unregister_locked(fd, &w);
    }
    if (rc == ETIMEDOUT || rc == EINTR) {
      errno = rc;
      return -1;
    }
    return w.revents.load(std::memory_order_acquire);
  }

 private:
  FdPoller() {
    epfd_ = epoll_create1(EPOLL_CLOEXEC);
    CHECK(epfd_ >= 0);
    pthread_t tid;
    pthread_create(
        &tid, nullptr,
        [](void* self) -> void* {
          static_cast<FdPoller*>(self)->run();
          return nullptr;
        },
        this);
    pthread_detach(tid);
  }

  // (Re)arms fd with the UNION of all waiters' masks, ONESHOT.  Call with
  // mu_ held.  No waiters → deregisters.
  int rearm_locked(int fd) {
    auto it = fds_.find(fd);
    if (it == fds_.end() || it->second.empty()) {
      epoll_ctl(epfd_, EPOLL_CTL_DEL, fd, nullptr);
      fds_.erase(fd);
      return 0;
    }
    uint32_t mask = EPOLLONESHOT;
    for (const FdWait* w : it->second) {
      mask |= static_cast<uint32_t>(w->want);
    }
    epoll_event ee;
    ee.events = mask;
    ee.data.u64 = static_cast<uint64_t>(fd);
    if (epoll_ctl(epfd_, EPOLL_CTL_MOD, fd, &ee) == 0) {
      return 0;
    }
    if (errno == ENOENT && epoll_ctl(epfd_, EPOLL_CTL_ADD, fd, &ee) == 0) {
      return 0;
    }
    return -1;
  }

  void unregister_locked(int fd, FdWait* w) {
    auto it = fds_.find(fd);
    if (it == fds_.end()) {
      return;
    }
    auto& v = it->second;
    for (auto vit = v.begin(); vit != v.end(); ++vit) {
      if (*vit == w) {
        v.erase(vit);
        break;
      }
    }
    rearm_locked(fd);  // drops or narrows the registration
  }

  void run() {
    epoll_event events[16];
    while (true) {
      const int n = epoll_wait(epfd_, events, 16, -1);
      for (int i = 0; i < n; ++i) {
        const int fd = static_cast<int>(events[i].data.u64);
        const uint32_t got = events[i].events;
        std::lock_guard<std::mutex> g(mu_);
        auto it = fds_.find(fd);
        if (it == fds_.end()) {
          continue;  // all waiters abandoned (timeout beat readiness)
        }
        auto& v = it->second;
        for (auto vit = v.begin(); vit != v.end();) {
          FdWait* w = *vit;
          // Errors/hangups wake everyone; otherwise only matching masks.
          if ((got & (EPOLLERR | EPOLLHUP)) != 0 ||
              (got & static_cast<uint32_t>(w->want)) != 0) {
            w->revents.store(static_cast<int>(got),
                             std::memory_order_release);
            w->ev.value.store(1, std::memory_order_release);
            w->ev.wake_all();
            vit = v.erase(vit);
          } else {
            ++vit;
          }
        }
        rearm_locked(fd);  // remaining waiters (e.g. writer) re-arm
      }
    }
  }

  int epfd_ = -1;
  std::mutex mu_;
  std::map<int, std::vector<FdWait*>> fds_;
};

}  // namespace

int fiber_fd_wait(int fd, int events, int64_t deadline_us) {
  return FdPoller::instance()->wait(fd, events, deadline_us);
}

}  // namespace trpc
