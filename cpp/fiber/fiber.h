// Fiber runtime public API — pthread-like M:N userspace threads.
//
// Parity: the bthread C API (/root/reference/src/bthread/bthread.h —
// bthread_start_urgent/background, join, yield, usleep) over a
// TaskControl/TaskGroup-style work-stealing scheduler
// (/root/reference/src/bthread/task_group.h).  Re-designed: a fiber switches
// through its worker's scheduler context (two-hop switch) instead of
// fiber→fiber chaining, and deferred "publish after switch" actions replace
// the reference's set_remained machinery.
#pragma once

#include <cstdint>
#include <string>

namespace trpc {

// version<<32 | pool slot; 0 is invalid (parity: bthread_t,
// task_group_inl.h:28-38).
using fiber_t = uint64_t;

constexpr int kFiberUrgent = 1;  // run ASAP (caller's queue front)

// -- worker tags (parity: bthread_tag, task_control.h:94-99) --------------
// Workers are partitioned into tagged groups; spawn and steal stay INSIDE
// a group, so saturating one tag's workers cannot starve another's (the
// reference's per-server bthread_tag isolation, server.h:280).  Tag 0 is
// the default group.  A fiber spawned without an explicit tag inherits the
// spawning worker's tag (so a tagged server's whole downstream — handler,
// KeepWrite, timeout fibers — stays in its group).
constexpr int kMaxFiberTags = 4;
// OR into fiber_start's flags to pin the fiber to `tag`'s worker group.
constexpr int fiber_tag_flags(int tag) { return (tag + 1) << 8; }
// Provisions `workers` pthreads for `tag` (idempotent; tag 0 comes from
// fiber_init).  Non-zero tags auto-provision a default-sized group on
// first use.  Returns 0, or EINVAL for an out-of-range tag.
int fiber_start_tag_workers(int tag, int workers);
// Tag of the calling fiber's worker (0 off-worker).
int fiber_current_tag();
int fiber_worker_count_tag(int tag);

// Start the scheduler with n worker pthreads (idempotent; auto-started with
// a default on first fiber_start).
void fiber_init(int workers);
int fiber_worker_count();

int fiber_start(fiber_t* out, void (*fn)(void*), void* arg, int flags = 0);
// Bulk spawn: starts fn(args[i]) for i in [0, n) and publishes them with
// ONE ParkingLot signal per 64-fiber stride (one futex syscall wakes up
// to 64 workers, where 64 fiber_start calls would signal — and
// potentially syscall — 64 times).  Queue-push order follows args order,
// but EXECUTION
// order is unspecified (a spawning worker pops its own run queue LIFO and
// thieves steal FIFO) — callers needing strict FIFO must publish from a
// non-worker thread into a single-worker tag group, or order themselves.
// Tag/urgent flags as fiber_start (the whole batch shares them; urgent
// claims the one-deep priority slot for the FIRST fiber only).  Returns
// the number of fibers actually started (< n only on pool exhaustion).
size_t fiber_start_batch(void (*fn)(void*), void* const* args, size_t n,
                         int flags = 0);
// Cumulative bulk-wake telemetry: batches published, fibers across them,
// and the largest single batch (stat/ exposes these as /vars series).
void fiber_bulk_wake_stats(uint64_t* batches, uint64_t* fibers,
                           uint64_t* max_batch);
// Waits until the fiber finishes.  Returns 0 (also for already-gone ids).
int fiber_join(fiber_t f);
// Parks the calling fiber until `fd` has any of `events` (EPOLLIN /
// EPOLLOUT / ...) or deadline_us passes (parity: bthread_fd_wait,
// bthread/fd.cpp).  Returns the ready events, or -1 with errno
// ETIMEDOUT / EINTR / epoll errors.
int fiber_fd_wait(int fd, int events, int64_t deadline_us = -1);
// Diagnostic dump of all live fibers: id, state (parked/runnable) and
// the symbolized entry function (parity: the TaskTracer-backed /bthreads
// service, task_tracer.cpp:40-43).  With `stacks`, each PARKED fiber's
// suspension point is unwound by walking its saved rbp chain (the
// context layout in context.S puts rbp at sp+48, the return address at
// sp+56; the build keeps frame pointers).  Best-effort: a fiber resuming
// mid-walk yields stale frames, never a fault — every pointer is
// bounds-checked against the fiber's own mapped stack.
std::string fiber_dump_all(size_t max_rows = 200, bool stacks = false);
// Interrupts a parked fiber (parity: TaskGroup::interrupt, task_group.h:208
// / bthread_stop): its current (or next) blocking Event::wait returns
// EINTR.  Cooperative — the fiber decides how to unwind.  Returns 0, or
// ESRCH for a dead/stale id.
int fiber_interrupt(fiber_t f);
// True if the id refers to a live fiber.
bool fiber_exists(fiber_t f);
void fiber_yield();
void fiber_sleep_us(int64_t us);
// Id of the calling fiber (0 when not on a fiber).
fiber_t fiber_self();
bool in_fiber();

// -- fiber-local storage (parity: bthread_key_*, src/bthread/key.cpp) ----
struct fls_key_t {
  uint32_t index = 0;
  uint32_t version = 0;
};
int fls_key_create(fls_key_t* key, void (*dtor)(void*));
int fls_key_delete(fls_key_t key);
int fls_set(fls_key_t key, void* value);
void* fls_get(fls_key_t key);

}  // namespace trpc
