#include "fiber/fid.h"

#include <cerrno>

#include "base/resource_pool.h"
#include "fiber/scheduler.h"
#include "fiber/sync.h"

namespace trpc {

namespace {

struct IdMeta {
  std::atomic<uint32_t> version{0};  // even = dead slot, odd = live
  FiberMutex mu;
  Event join_ev;  // value = live version; bumped at destroy
  void* data = nullptr;
  int (*on_error)(fid_t, void*, int) = nullptr;
  uint32_t slot = 0;
};

using IdPool = ResourcePool<IdMeta>;

IdMeta* meta_of(fid_t id) {
  const uint32_t ver = static_cast<uint32_t>(id >> 32);
  if ((ver & 1) == 0) {
    return nullptr;
  }
  IdMeta* m = IdPool::instance()->at(static_cast<uint32_t>(id));
  if (m == nullptr || m->version.load(std::memory_order_acquire) != ver) {
    return nullptr;
  }
  return m;
}

}  // namespace

int fid_create(fid_t* id, void* data, int (*on_error)(fid_t, void*, int)) {
  IdMeta* m = nullptr;
  const uint32_t slot = IdPool::instance()->acquire(&m);
  if (m == nullptr) {
    return ENOMEM;
  }
  m->slot = slot;
  m->data = data;
  m->on_error = on_error;
  const uint32_t ver = m->version.load(std::memory_order_relaxed) + 1;  // odd
  m->join_ev.value.store(ver, std::memory_order_relaxed);
  m->version.store(ver, std::memory_order_release);
  *id = (static_cast<uint64_t>(ver) << 32) | slot;
  return 0;
}

int fid_lock(fid_t id, void** data) {
  IdMeta* m = meta_of(id);
  if (m == nullptr) {
    return EINVAL;
  }
  m->mu.lock();
  // Re-validate: the id may have been destroyed while we queued on the lock.
  if (m->version.load(std::memory_order_acquire) !=
      static_cast<uint32_t>(id >> 32)) {
    m->mu.unlock();
    return EINVAL;
  }
  if (data != nullptr) {
    *data = m->data;
  }
  return 0;
}

int fid_unlock(fid_t id) {
  IdMeta* m = meta_of(id);
  if (m == nullptr) {
    return EINVAL;
  }
  m->mu.unlock();
  return 0;
}

int fid_unlock_and_destroy(fid_t id) {
  const uint32_t ver = static_cast<uint32_t>(id >> 32);
  IdMeta* m = meta_of(id);
  if (m == nullptr) {
    return EINVAL;
  }
  // Kill the version first (holders of the lock queue will re-validate),
  // then release the lock, wake joiners, recycle.
  m->version.store(ver + 1, std::memory_order_release);
  m->mu.unlock();
  m->join_ev.value.store(ver + 1, std::memory_order_release);
  m->join_ev.wake_all();
  IdPool::instance()->release(m->slot);
  return 0;
}

int fid_error(fid_t id, int error_code) {
  void* data = nullptr;
  const int rc = fid_lock(id, &data);
  if (rc != 0) {
    return rc;
  }
  IdMeta* m = meta_of(id);
  if (m != nullptr && m->on_error != nullptr) {
    return m->on_error(id, data, error_code);  // must unlock/destroy
  }
  return fid_unlock_and_destroy(id);
}

int fid_join(fid_t id) {
  const uint32_t ver = static_cast<uint32_t>(id >> 32);
  if ((ver & 1) == 0) {
    return 0;
  }
  IdMeta* m = IdPool::instance()->at(static_cast<uint32_t>(id));
  if (m == nullptr) {
    return 0;
  }
  while (m->join_ev.value.load(std::memory_order_acquire) == ver) {
    m->join_ev.wait(ver, -1);
  }
  return 0;
}

bool fid_exists(fid_t id) { return meta_of(id) != nullptr; }

std::string fid_dump_all(size_t max_rows) {
  return dump_pool_table<IdMeta>(
      "live correlation ids (id  locked)\n", max_rows,
      [](uint32_t slot, IdMeta* m, std::string* line) {
        const uint32_t ver = m->version.load(std::memory_order_acquire);
        if ((ver & 1) == 0) {
          return false;
        }
        if (line != nullptr) {
          char buf[64];
          snprintf(buf, sizeof(buf), "%016llx  %s\n",
                   static_cast<unsigned long long>(
                       (static_cast<uint64_t>(ver) << 32) | slot),
                   m->mu.locked() ? "yes" : "no");
          *line = buf;
        }
        return true;
      });
}

}  // namespace trpc
