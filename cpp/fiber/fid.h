// fid — versioned correlation id with built-in locking and join.
//
// Parity: bthread_id (/root/reference/src/bthread/id.h:46-78), the machinery
// that lets racing RPC responses / timeouts / retries serialize on one id
// and makes stale responses harmless (versioned handle + exclusive lock +
// destroy-join).  Re-designed condensed: a FiberMutex guards the payload, a
// join Event signals destruction, and validity is a version match against
// the pooled meta (the reference additionally queues pending errors).
#pragma once

#include <cstdint>
#include <string>

namespace trpc {

using fid_t = uint64_t;  // version<<32 | pool slot; 0 invalid

// on_error(id, data, error_code) is invoked WITH the id locked; it must end
// by calling fid_unlock or fid_unlock_and_destroy.  Null on_error → error()
// destroys the id.
int fid_create(fid_t* id, void* data,
               int (*on_error)(fid_t, void*, int));
// Locks the id for exclusive use.  Returns 0 (data out), EINVAL if gone.
int fid_lock(fid_t id, void** data);
int fid_unlock(fid_t id);
int fid_unlock_and_destroy(fid_t id);
// Locks and runs on_error.  EINVAL if gone.
int fid_error(fid_t id, int error_code);
// Blocks until the id is destroyed (0 even if already gone).
int fid_join(fid_t id);
bool fid_exists(fid_t id);

// Text table of live correlation ids (/ids builtin; reference:
// builtin/ids_service.cpp).  Capped at max_rows rows; always appends the
// full live count.
std::string fid_dump_all(size_t max_rows);

}  // namespace trpc
