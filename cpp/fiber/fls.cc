// Fiber-local storage (parity: bthread_key_create/[gs]etspecific,
// /root/reference/src/bthread/key.cpp — versioned keys so deleted keys
// can't read stale values; destructors run at fiber exit).
#include <mutex>
#include <vector>

#include "fiber/scheduler.h"

namespace trpc {

namespace {

struct KeyInfo {
  uint32_t version = 0;  // even = free, odd = live (like fiber versions)
  void (*dtor)(void*) = nullptr;
};

// Deliberately leaked: fiber exit paths may run during static destruction.
std::mutex& keys_mu() {
  static std::mutex* mu = new std::mutex();
  return *mu;
}
std::vector<KeyInfo>& keys() {
  static auto* v = new std::vector<KeyInfo>();
  return *v;
}
std::vector<uint32_t>& free_keys() {
  static auto* v = new std::vector<uint32_t>();
  return *v;
}

}  // namespace

int fls_key_create(fls_key_t* key, void (*dtor)(void*)) {
  std::lock_guard<std::mutex> g(keys_mu());
  uint32_t index;
  if (!free_keys().empty()) {
    index = free_keys().back();
    free_keys().pop_back();
  } else {
    index = static_cast<uint32_t>(keys().size());
    keys().emplace_back();
  }
  keys()[index].version += 1;  // → odd (live)
  keys()[index].dtor = dtor;
  key->index = index;
  key->version = keys()[index].version;
  return 0;
}

int fls_key_delete(fls_key_t key) {
  std::lock_guard<std::mutex> g(keys_mu());
  if (key.index >= keys().size() || keys()[key.index].version != key.version) {
    return -1;
  }
  keys()[key.index].version += 1;  // → even (free)
  keys()[key.index].dtor = nullptr;
  free_keys().push_back(key.index);
  return 0;
}

int fls_set(fls_key_t key, void* value) {
  Worker* w = tls_worker;
  if (w == nullptr || w->current() == nullptr) {
    return -1;
  }
  FiberMeta* m = w->current();
  if (m->fls.size() <= key.index) {
    m->fls.resize(key.index + 1);
  }
  m->fls[key.index].value = value;
  m->fls[key.index].version = key.version;
  return 0;
}

void* fls_get(fls_key_t key) {
  Worker* w = tls_worker;
  if (w == nullptr || w->current() == nullptr) {
    return nullptr;
  }
  FiberMeta* m = w->current();
  if (m->fls.size() <= key.index ||
      m->fls[key.index].version != key.version) {
    return nullptr;
  }
  return m->fls[key.index].value;
}

void run_fls_destructors(FiberMeta* m) {
  for (uint32_t i = 0; i < m->fls.size(); ++i) {
    void* value = m->fls[i].value;
    if (value == nullptr) {
      continue;
    }
    void (*dtor)(void*) = nullptr;
    {
      std::lock_guard<std::mutex> g(keys_mu());
      if (i < keys().size() && keys()[i].version == m->fls[i].version) {
        dtor = keys()[i].dtor;
      }
    }
    m->fls[i].value = nullptr;
    if (dtor != nullptr) {
      dtor(value);
    }
  }
  m->fls.clear();
}

}  // namespace trpc
