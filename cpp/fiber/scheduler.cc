#include "fiber/scheduler.h"

#include <linux/futex.h>
#include <pthread.h>
#include <sys/syscall.h>
#include <unistd.h>

#include <cstring>

#include "base/futex_mutex.h"
#include "base/logging.h"
#include "base/symbolize.h"
#include "base/rand.h"
#include "base/resource_pool.h"
#include "fiber/context.h"
#include <dlfcn.h>

#include "fiber/event.h"
#include "stat/timeline.h"

// ASan fiber-switch annotations (parity: the reference's ASan-aware stack
// switching, task_group.h:311 asan_task_runner + stack poisoning).  No-ops
// unless built with -fsanitize=address.
#if defined(__has_feature)
#if __has_feature(address_sanitizer)
#define TRPC_HAS_ASAN_FEATURE 1
#endif
#endif
#if defined(__SANITIZE_ADDRESS__) || defined(TRPC_HAS_ASAN_FEATURE)
extern "C" {
void __sanitizer_start_switch_fiber(void** fake_stack_save,
                                    const void* bottom, size_t size);
void __sanitizer_finish_switch_fiber(void* fake_stack_save,
                                     const void** bottom_old,
                                     size_t* size_old);
}
#define TRPC_ASAN_FIBERS 1
#else
#define TRPC_ASAN_FIBERS 0
static inline void __sanitizer_start_switch_fiber(void**, const void*,
                                                  size_t) {}
static inline void __sanitizer_finish_switch_fiber(void*, const void**,
                                                   size_t*) {}
#endif

// TSan fiber-switch annotations: without them TSan sees one pthread's
// shadow stack teleporting between fiber stacks and reports phantom
// races.  No-ops unless built with -fsanitize=thread.
// Declarations + the acquire/release edge macros live in the shared shim
// (base/tsan.h); everything no-ops outside -fsanitize=thread.
#include "base/tsan.h"
#define TRPC_TSAN_FIBERS TRPC_TSAN

namespace trpc {

thread_local Worker* tls_worker = nullptr;

namespace {

using FiberPool = ResourcePool<FiberMeta>;

// Flight-recorder hook for transitions about a SPECIFIC fiber: ready/
// wake/steal fire on the waker's/thief's thread, so the event stamps
// the TARGET fiber's ambient trace (FiberMeta fields), not the
// emitter's.  Callers gate on timeline::enabled() so the flag-off cost
// stays at one relaxed load per transition.
inline void timeline_fiber_event(uint32_t type, FiberMeta* m,
                                 uint64_t b = 0) {
  // Relaxed: diagnostic snapshot of the target's context (see the
  // ambient_trace comment in scheduler.h).
  timeline::record_ctx(type, m->id(), b,
                       m->ambient_trace.load(std::memory_order_relaxed),
                       m->ambient_span.load(std::memory_order_relaxed));
}

void requeue_post(void* a1, void*) {
  Scheduler::instance()->ready_to_run(static_cast<FiberMeta*>(a1));
}

void finish_fiber_post(void* p, void*) {
  FiberMeta* m = static_cast<FiberMeta*>(p);
  const uint32_t ver = m->version.load(std::memory_order_relaxed);
  if (TRPC_TSAN_FIBERS && m->tsan_fiber != nullptr) {
    __tsan_destroy_fiber(m->tsan_fiber);
    m->tsan_fiber = nullptr;
  }
  release_stack(m->stack);
  m->stack = StackMem{};
  m->sp = nullptr;
  // Even version = idle slot; the bumped done word releases joiners.  The
  // meta is pool-recycled, never freed, so late joiners touching the event
  // see the new value and return (type-stable memory, like TaskMeta).
  m->version.store(ver + 1, std::memory_order_release);
  m->done_event.value.store(ver + 1, std::memory_order_release);
  m->done_event.wake_all();
  FiberPool::instance()->release(m->slot);
}

void fiber_entry(void* p) {
  FiberMeta* m = static_cast<FiberMeta*>(p);
  // Complete the ASan handshake for the first entry onto this stack.
  __sanitizer_finish_switch_fiber(nullptr, nullptr, nullptr);
  m->fn.load(std::memory_order_relaxed)(m->arg);
  run_fls_destructors(m);
  Worker* w = tls_worker;  // worker we ended on (may differ from start)
  w->suspend_current(finish_fiber_post, m, nullptr, /*dying=*/true);
  CHECK(false) << "resumed a finished fiber";
}

}  // namespace

FiberMeta* fiber_meta_of(fiber_t f) {
  const uint32_t slot = static_cast<uint32_t>(f);
  const uint32_t ver = static_cast<uint32_t>(f >> 32);
  if ((ver & 1) == 0) {
    return nullptr;
  }
  FiberMeta* m = FiberPool::instance()->at(slot);
  if (m == nullptr || m->version.load(std::memory_order_acquire) != ver) {
    return nullptr;
  }
  return m;
}

void ParkingLot::signal(int n) {
  // Edge to a waker-to-parked-worker handoff TSan cannot model: the
  // release below pairs with wait()'s acquire only when the waiter
  // re-reads seq_, but a worker woken by the FUTEX_WAKE syscall itself
  // never touches seq_ again — annotate the same edge explicitly so
  // everything published before signal() is visible after wait().
  TRPC_TSAN_RELEASE(&seq_);
  seq_.fetch_add(1, std::memory_order_release);
  // seq_ is already bumped, so a worker past its stamp() re-check that
  // has not yet reached FUTEX_WAIT will see the changed word and return
  // without sleeping — skipping the wake syscall when nobody has
  // registered as parked is therefore lost-wakeup-free.
  if (waiters_.load(std::memory_order_acquire) > 0) {
    futex_word_op(&seq_, FUTEX_WAKE_PRIVATE, n, nullptr);
  }
}

void ParkingLot::wait(int stamp) {
  waiters_.fetch_add(1, std::memory_order_acq_rel);
  futex_word_op(&seq_, FUTEX_WAIT_PRIVATE, stamp, nullptr);
  waiters_.fetch_sub(1, std::memory_order_acq_rel);
  // Close the signal() edge (see above): the kernel ordered the wake
  // after the waker's seq_ bump, but no acquire-read of seq_ follows.
  TRPC_TSAN_ACQUIRE(&seq_);
}

Scheduler* Scheduler::instance() {
  // Deliberately leaked: worker pthreads outlive static destruction.
  static Scheduler* s = new Scheduler();
  return s;
}

void Scheduler::start(int workers) { start_tag(0, workers); }

void Scheduler::start_tag(int tag, int workers) {
  if (tag < 0 || tag >= kMaxTags) {
    return;
  }
  TagGroup& g = tags_[tag];
  std::call_once(g.once, [this, &g, tag, workers] {
    int n = workers;
    if (n <= 0) {
      const long ncpu = sysconf(_SC_NPROCESSORS_ONLN);
      n = std::max(4L, std::min(8L, ncpu));
    }
    n = std::min(n, kMaxWorkers);
    for (int i = 0; i < n; ++i) {
      g.workers[i] = new Worker(this, i, tag);
      pthread_t tid;
      pthread_create(
          &tid, nullptr,
          [](void* w) -> void* {
            static_cast<Worker*>(w)->main_loop();
            return nullptr;
          },
          g.workers[i]);
      pthread_detach(tid);
    }
    g.nworkers.store(n, std::memory_order_release);
  });
}

void Scheduler::ready_to_run(FiberMeta* m, bool urgent) {
  if (timeline::enabled()) {
    // Relaxed: written only by the worker that last ran m; a stale read
    // can only misname ready-vs-wake on a racing transition.
    timeline_fiber_event(m->last_worker.load(std::memory_order_relaxed) < 0
                             ? timeline::kFiberReady
                             : timeline::kFiberWake,
                         m);
  }
  TagGroup& g = tags_[m->tag];
  Worker* w = tls_worker;
  // A thread about to block pthread-style must not trap work in its own
  // queues — it won't return to its scheduler loop until woken.  A worker
  // of ANOTHER tag must not take the fiber either: spawn stays in-group.
  if (w != nullptr && (w->tag() != m->tag || in_pthread_wait_mode())) {
    w = nullptr;
  }
  if (w != nullptr) {
    if (urgent) {
      // Claim the worker's one-deep priority slot; it runs before the queue.
      FiberMeta* expect = nullptr;
      if (w->urgent_.compare_exchange_strong(expect, m,
                                             std::memory_order_acq_rel)) {
        g.lot.signal(2);
        return;
      }
    }
    if (!w->runq().push(m)) {
      push_remote(m);
    }
  } else {
    push_remote(m);
  }
  g.lot.signal(urgent ? 2 : 1);
}

void Scheduler::push_remote(FiberMeta* m) {
  TagGroup& g = tags_[m->tag];
  std::lock_guard<std::mutex> lk(g.remote_mu);
  g.remote_q.push_back(m);
}

void Scheduler::ready_to_run_batch(FiberMeta* const* ms, size_t n,
                                   bool urgent) {
  if (n == 0) {
    return;
  }
  if (n == 1) {
    ready_to_run(ms[0], urgent);
    return;
  }
  if (timeline::enabled()) {
    timeline::record(timeline::kBulkWake, n, 0);
    for (size_t i = 0; i < n; ++i) {
      // Relaxed: same ready-vs-wake naming tolerance as ready_to_run.
      timeline_fiber_event(
          ms[i]->last_worker.load(std::memory_order_relaxed) < 0
              ? timeline::kFiberReady
              : timeline::kFiberWake,
          ms[i]);
    }
  }
  TagGroup& g = tags_[ms[0]->tag];
  Worker* w = tls_worker;
  if (w != nullptr && (w->tag() != ms[0]->tag || in_pthread_wait_mode())) {
    w = nullptr;
  }
  size_t first = 0;
  if (urgent && w != nullptr) {
    // Urgent batches claim the one-deep priority slot for their FIRST
    // fiber (the slot is one-deep by design); the rest queue normally
    // but still ride the elevated signal below.
    FiberMeta* expect = nullptr;
    if (w->urgent_.compare_exchange_strong(expect, ms[0],
                                           std::memory_order_acq_rel)) {
      first = 1;
    }
  }
  if (w != nullptr) {
    // Push to the caller's own queue in order; thieves + the signal below
    // fan the batch out.  Overflow spills to the remote queue under ONE
    // lock (a nearly-full runq is exactly the loaded case where per-node
    // locking would hurt).
    size_t i = first;
    while (i < n && w->runq().push(ms[i])) {
      ++i;
    }
    if (i < n) {
      std::lock_guard<std::mutex> lk(g.remote_mu);
      for (; i < n; ++i) {
        g.remote_q.push_back(ms[i]);
      }
    }
  } else {
    std::lock_guard<std::mutex> lk(g.remote_mu);
    for (size_t i = first; i < n; ++i) {
      g.remote_q.push_back(ms[i]);
    }
  }
  bulk_wake_batches.fetch_add(1, std::memory_order_relaxed);
  bulk_wake_fibers.fetch_add(n, std::memory_order_relaxed);
  uint64_t cur = bulk_wake_max.load(std::memory_order_relaxed);
  while (n > cur && !bulk_wake_max.compare_exchange_weak(
                        cur, n, std::memory_order_relaxed)) {
  }
  // ONE signal for the whole batch: a single FUTEX_WAKE releases up to n
  // parked workers, where per-spawn publication would re-enter the futex
  // path n times.  Urgent batches wake one extra worker, mirroring
  // ready_to_run's signal(2) bias.
  g.lot.signal(static_cast<int>(n) + (urgent ? 1 : 0));
}

bool Scheduler::pop_remote(FiberMeta** out, int tag) {
  TagGroup& g = tags_[tag];
  std::lock_guard<std::mutex> lk(g.remote_mu);
  if (g.remote_q.empty()) {
    return false;
  }
  *out = g.remote_q.front();
  g.remote_q.pop_front();
  return true;
}

bool Scheduler::steal(FiberMeta** out, Worker* thief) {
  // Steal range = the thief's own tag group (task_control.h:94 parity:
  // per-tag groups do not poach each other's work).
  TagGroup& g = tags_[thief->tag()];
  const int n = g.nworkers.load(std::memory_order_acquire);
  if (n <= 1) {
    return false;
  }
  const uint64_t start = fast_rand_less_than(n);
  for (int i = 0; i < n; ++i) {
    Worker* victim = g.workers[(start + i) % n];
    if (victim == nullptr || victim == thief) {
      continue;
    }
    if (victim->runq().steal(out)) {
      if (timeline::enabled()) {
        timeline_fiber_event(timeline::kFiberSteal, *out,
                             static_cast<uint64_t>(victim->index()));
      }
      return true;
    }
    // The victim may be pthread-blocked with a fiber parked in its urgent
    // slot; claim it so it can't starve.
    FiberMeta* urgent =
        victim->urgent_.exchange(nullptr, std::memory_order_acq_rel);
    if (urgent != nullptr) {
      *out = urgent;
      if (timeline::enabled()) {
        timeline_fiber_event(timeline::kFiberSteal, urgent,
                             static_cast<uint64_t>(victim->index()));
      }
      return true;
    }
  }
  return false;
}

Worker::Worker(Scheduler* sched, int index, int tag)
    : sched_(sched), index_(index), tag_(tag) {}

FiberMeta* Worker::pick_next() {
  FiberMeta* m = urgent_.exchange(nullptr, std::memory_order_acq_rel);
  if (m != nullptr) {
    return m;
  }
  if (runq_.pop(&m)) {
    return m;
  }
  if (sched_->pop_remote(&m, tag_)) {
    return m;
  }
  if (sched_->steal(&m, this)) {
    return m;
  }
  return nullptr;
}

void Worker::run_fiber(FiberMeta* m) {
  current_ = m;
  // Relaxed last_worker: only the worker about to run m writes it, and
  // the scheduler queue handoff orders successive runners.
  const int32_t prev_w = m->last_worker.load(std::memory_order_relaxed);
  if (timeline::enabled()) {
    if (prev_w >= 0 && prev_w != index_) {
      timeline_fiber_event(timeline::kFiberMigrate, m,
                           static_cast<uint64_t>(index_));
    }
    timeline_fiber_event(timeline::kFiberRun, m,
                         static_cast<uint64_t>(index_));
  }
  m->last_worker.store(index_, std::memory_order_relaxed);
  __sanitizer_start_switch_fiber(&asan_fake_stack_, m->stack.base,
                                 m->stack.size);
  if (TRPC_TSAN_FIBERS) {
    if (m->tsan_fiber == nullptr) {
      m->tsan_fiber = __tsan_create_fiber(0);
    }
    __tsan_switch_to_fiber(m->tsan_fiber, 0);
  }
  trpc_jump_context(&sched_sp_, m->sp, m);
  __sanitizer_finish_switch_fiber(asan_fake_stack_, nullptr, nullptr);
  current_ = nullptr;
  if (post_fn_ != nullptr) {
    PostSwitchFn fn = post_fn_;
    post_fn_ = nullptr;
    fn(post_a1_, post_a2_);
  }
}

void Worker::suspend_current(PostSwitchFn post_fn, void* a1, void* a2,
                             bool dying) {
  FiberMeta* m = current_;
  if (timeline::enabled()) {
    // Still on the fiber's logical context: park/done events carry its
    // own ambient trace, so a span's gap decomposes into parked time.
    timeline_fiber_event(dying ? timeline::kFiberDone
                               : timeline::kFiberPark,
                         m);
  }
  post_fn_ = post_fn;
  post_a1_ = a1;
  post_a2_ = a2;
  // A dying fiber passes nullptr fake-stack storage so ASan retires its
  // fake frames instead of preserving them for a resume.
  __sanitizer_start_switch_fiber(dying ? nullptr : &m->asan_fake_stack,
                                 pthread_stack_base_, pthread_stack_size_);
  if (TRPC_TSAN_FIBERS) {
    __tsan_switch_to_fiber(tsan_sched_fiber_, 0);
  }
  trpc_jump_context(&m->sp, sched_sp_, nullptr);
  // Resumed (possibly on another worker's scheduler context).
  __sanitizer_finish_switch_fiber(m->asan_fake_stack, nullptr, nullptr);
}

void Worker::main_loop() {
  tls_worker = this;
  if (TRPC_TSAN_FIBERS) {
    tsan_sched_fiber_ = __tsan_get_current_fiber();
  }
#if TRPC_ASAN_FIBERS
  {
    pthread_attr_t attr;
    pthread_getattr_np(pthread_self(), &attr);
    pthread_attr_getstack(&attr, &pthread_stack_base_, &pthread_stack_size_);
    pthread_attr_destroy(&attr);
  }
#endif
  ParkingLot& lot = sched_->group(tag_).lot;
  while (true) {
    FiberMeta* m = pick_next();
    if (m != nullptr) {
      run_fiber(m);
      continue;
    }
    const int stamp = lot.stamp();
    m = pick_next();  // re-check after stamp: closes the missed-signal window
    if (m != nullptr) {
      run_fiber(m);
      continue;
    }
    lot.wait(stamp);
  }
}

// ---- public API ---------------------------------------------------------

void fiber_init(int workers) { Scheduler::instance()->start(workers); }

int fiber_worker_count() { return Scheduler::instance()->worker_count(); }

int fiber_start_tag_workers(int tag, int workers) {
  if (tag < 0 || tag >= kMaxFiberTags) {
    return EINVAL;
  }
  Scheduler::instance()->start_tag(tag, workers);
  return 0;
}

int fiber_current_tag() {
  Worker* w = tls_worker;
  return w != nullptr ? w->tag() : 0;
}

int fiber_worker_count_tag(int tag) {
  if (tag < 0 || tag >= kMaxFiberTags) {
    return 0;
  }
  return Scheduler::instance()->worker_count(tag);
}

namespace {

// Shared by fiber_start / fiber_start_batch: resolve the worker tag from
// `flags` (explicit flag wins, else inherit the spawning worker's tag) and
// provision its group.  Returns the tag, or -1 for an out-of-range flag.
int resolve_spawn_tag(Scheduler* sched, int flags) {
  int tag = (flags >> 8) & 0xff;
  if (tag == 0) {
    tag = fiber_current_tag();
  } else {
    tag -= 1;
    if (tag >= kMaxFiberTags) {
      return -1;
    }
  }
  if (tag != 0 && sched->worker_count(tag) == 0) {
    sched->start_tag(tag, 0);  // auto-provision a default-sized group
  }
  return tag;
}

// Acquire + initialize one runnable meta (not yet published).
FiberMeta* make_fiber_meta(void (*fn)(void*), void* arg, int tag) {
  FiberMeta* m = nullptr;
  const uint32_t slot = FiberPool::instance()->acquire(&m);
  if (m == nullptr) {
    return nullptr;
  }
  m->slot = slot;
  m->tag = static_cast<uint8_t>(tag);
  m->fn.store(fn, std::memory_order_relaxed);
  m->arg = arg;
  m->interrupted.store(false, std::memory_order_relaxed);
  m->parked_on.store(nullptr, std::memory_order_relaxed);
  // Relaxed: pre-publication init (the slot is not yet visible), same as
  // the surrounding stores; a recycled meta must not leak the previous
  // fiber's trace context or worker history.
  m->ambient_trace.store(0, std::memory_order_relaxed);
  m->ambient_span.store(0, std::memory_order_relaxed);
  m->ambient_deadline.store(0, std::memory_order_relaxed);
  m->ambient_cancel.store(nullptr, std::memory_order_relaxed);
  m->last_worker.store(-1, std::memory_order_relaxed);
  const uint32_t ver = m->version.load(std::memory_order_relaxed) + 1;  // odd
  m->done_event.value.store(ver, std::memory_order_relaxed);
  m->version.store(ver, std::memory_order_relaxed);
  m->stack = allocate_stack(kDefaultStackSize);
  m->sp = make_context(m->stack.base, m->stack.size, fiber_entry);
  return m;
}

}  // namespace

int fiber_start(fiber_t* out, void (*fn)(void*), void* arg, int flags) {
  Scheduler* sched = Scheduler::instance();
  if (!sched->started()) {
    sched->start(0);
  }
  const int tag = resolve_spawn_tag(sched, flags);
  if (tag < 0) {
    return -1;
  }
  FiberMeta* m = make_fiber_meta(fn, arg, tag);
  if (m == nullptr) {
    return -1;
  }
  if (timeline::enabled()) {
    timeline::record(timeline::kFiberCreate, m->id(), 0);
  }
  if (out != nullptr) {
    *out = m->id();
  }
  sched->ready_to_run(m, (flags & kFiberUrgent) != 0);
  return 0;
}

size_t fiber_start_batch(void (*fn)(void*), void* const* args, size_t n,
                         int flags) {
  if (n == 0) {
    return 0;
  }
  Scheduler* sched = Scheduler::instance();
  if (!sched->started()) {
    sched->start(0);
  }
  const int tag = resolve_spawn_tag(sched, flags);
  if (tag < 0) {
    return 0;
  }
  constexpr size_t kStride = 64;
  FiberMeta* ms[kStride];
  size_t started = 0;
  while (started < n) {
    const size_t want = std::min(n - started, kStride);
    size_t got = 0;
    while (got < want) {
      FiberMeta* m = make_fiber_meta(fn, args[started + got], tag);
      if (m == nullptr) {
        break;  // pool exhausted: publish what we have
      }
      if (timeline::enabled()) {
        timeline::record(timeline::kFiberCreate, m->id(), 0);
      }
      ms[got++] = m;
    }
    sched->ready_to_run_batch(ms, got, (flags & kFiberUrgent) != 0);
    started += got;
    if (got < want) {
      break;
    }
  }
  return started;
}

void fiber_bulk_wake_stats(uint64_t* batches, uint64_t* fibers,
                           uint64_t* max_batch) {
  Scheduler* s = Scheduler::instance();
  *batches = s->bulk_wake_batches.load(std::memory_order_relaxed);
  *fibers = s->bulk_wake_fibers.load(std::memory_order_relaxed);
  *max_batch = s->bulk_wake_max.load(std::memory_order_relaxed);
}

namespace {

// Unwinds a PARKED fiber from its saved context (context.S layout:
// sp+48 saved rbp, sp+56 return address), walking the frame-pointer
// chain.  Best-effort under concurrency: the fiber may resume mid-walk,
// so every dereference is bounds-checked against its own stack — reads
// can go stale but cannot fault (the stack stays mapped while the meta
// is live).
std::string walk_parked_stack(FiberMeta* m, int max_frames) {
  uint8_t* sp = static_cast<uint8_t*>(m->sp);
  uint8_t* lo = static_cast<uint8_t*>(m->stack.base);
  uint8_t* hi = lo + m->stack.size;
  if (lo == nullptr || sp < lo || sp + 64 > hi) {
    return "";
  }
  std::string out;
  void* pc = *reinterpret_cast<void**>(sp + 56);
  uint8_t* rbp = *reinterpret_cast<uint8_t**>(sp + 48);
  for (int i = 0; i < max_frames && pc != nullptr; ++i) {
    out += "    #" + std::to_string(i) + " " + symbolize_addr(pc) + "\n";
    if (rbp < sp || rbp + 16 > hi ||
        (reinterpret_cast<uintptr_t>(rbp) & 7) != 0) {
      break;  // frame chain left the stack (or was never valid)
    }
    pc = *reinterpret_cast<void**>(rbp + 8);
    uint8_t* next = *reinterpret_cast<uint8_t**>(rbp);
    if (next <= rbp) {
      break;  // chains must grow upward; anything else is garbage
    }
    rbp = next;
  }
  return out;
}

}  // namespace

std::string fiber_dump_all(size_t max_rows, bool stacks) {
  return dump_pool_table<FiberMeta>(
      "live fibers (id  state  entry)\n", max_rows,
      [stacks](uint32_t slot, FiberMeta* m, std::string* line) {
        const uint32_t ver = m->version.load(std::memory_order_acquire);
        if ((ver & 1) == 0) {
          return false;  // even = idle slot
        }
        if (line == nullptr) {
          return true;  // counted, rows already capped
        }
        const Event* parked = m->parked_on.load(std::memory_order_acquire);
        char buf[256];
        const char* sym = "?";
        Dl_info info;
        void* fn = reinterpret_cast<void*>(
            m->fn.load(std::memory_order_relaxed));
        if (fn != nullptr && dladdr(fn, &info) != 0 &&
            info.dli_sname != nullptr) {
          sym = info.dli_sname;
        }
        snprintf(buf, sizeof(buf), "%016llx  %-8s %s\n",
                 static_cast<unsigned long long>(
                     (static_cast<uint64_t>(ver) << 32) | slot),
                 parked != nullptr ? "parked" : "runnable", sym);
        *line = buf;
        if (stacks && parked != nullptr) {
          *line += walk_parked_stack(m, 16);
        }
        return true;
      });
}

int fiber_interrupt(fiber_t f) {
  FiberMeta* m = fiber_meta_of(f);
  if (m == nullptr) {
    return ESRCH;
  }
  // Everything under the park lock: (a) the version re-check closes the
  // recycled-slot race (a delayed interrupted.store must not EINTR an
  // unrelated new fiber), and (b) the waiter cannot clear parked_on and
  // destroy the Event while we are inside wake_all (stack Events —
  // fiber_sleep — die right after the wait returns).  Spurious wakes of
  // co-waiters are part of the Event contract (callers re-check).
  m->park_lock();
  if (m->version.load(std::memory_order_acquire) !=
      static_cast<uint32_t>(f >> 32)) {
    m->park_unlock();
    return ESRCH;
  }
  m->interrupted.store(true, std::memory_order_release);
  Event* ev = m->parked_on.load(std::memory_order_acquire);
  if (ev != nullptr) {
    ev->wake_all();
  }
  m->park_unlock();
  return 0;
}

int fiber_join(fiber_t f) {
  const uint32_t ver = static_cast<uint32_t>(f >> 32);
  FiberMeta* m = fiber_meta_of(f);
  if (m == nullptr) {
    return 0;  // already gone (or never existed)
  }
  // The done event's value holds the live version until exit bumps it; the
  // meta is type-stable, so waiting on a recycled slot just returns.
  while (m->done_event.value.load(std::memory_order_acquire) == ver) {
    m->done_event.wait(ver, -1);
  }
  return 0;
}

bool fiber_exists(fiber_t f) { return fiber_meta_of(f) != nullptr; }

fiber_t fiber_self() {
  Worker* w = tls_worker;
  return (w != nullptr && w->current() != nullptr) ? w->current()->id() : 0;
}

bool in_fiber() { return tls_worker != nullptr && tls_worker->current() != nullptr; }

void fiber_yield() {
  Worker* w = tls_worker;
  if (w == nullptr || w->current() == nullptr) {
    sched_yield();
    return;
  }
  w->suspend_current(requeue_post, w->current(), nullptr);
}

}  // namespace trpc
