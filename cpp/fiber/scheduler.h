// Scheduler internals shared by event/timer/sync (not user-facing).
//
// Parity map: Scheduler ≈ bthread TaskControl (task_control.h:46), Worker ≈
// TaskGroup (task_group.h), FiberMeta ≈ TaskMeta, ParkingLot ≈ parking_lot.h.
#pragma once

#include <atomic>
#include <sched.h>
#include <cstdint>
#include <deque>
#include <mutex>
#include <vector>

#include "fiber/event.h"
#include "fiber/fiber.h"
#include "fiber/stack.h"
#include "fiber/wsqueue.h"

namespace trpc {

class Worker;

// Deferred action run by the scheduler AFTER the fiber's context has been
// switched away — the publish-after-switch pattern that closes the
// "woken before fully suspended" race (parity: TaskGroup::set_remained,
// task_group.h:124).
using PostSwitchFn = void (*)(void* arg1, void* arg2);

struct FiberMeta {
  // Atomic: /fibers dumps read it concurrently with slot recycling.
  std::atomic<void (*)(void*)> fn{nullptr};
  void* arg = nullptr;
  void* sp = nullptr;  // suspended continuation
  StackMem stack;
  void* asan_fake_stack = nullptr;  // ASan fiber handshake state
  void* tsan_fiber = nullptr;       // TSan fiber identity (tsan builds)
  // Even = idle slot; odd = live fiber.  The version half of fiber_t.
  std::atomic<uint32_t> version{0};
  // Interruption (parity: TaskGroup::interrupt / bthread_stop): the Event
  // this fiber is currently parked on (null while runnable), and a
  // pending-interrupt flag consumed by the next Event::wait return.
  // park_mu serializes interrupters against park/unpark: an interrupter
  // may only touch the Event while holding it, and the waiter clears
  // parked_on under it BEFORE the Event can be destroyed — so wake_all
  // from fiber_interrupt can never run on a dead Event.
  std::atomic<class Event*> parked_on{nullptr};
  std::atomic<bool> interrupted{false};
  std::atomic_flag park_mu = ATOMIC_FLAG_INIT;
  // Ambient trace context (net/span.cc reads/writes these when the
  // fiber installs a span; the timeline recorder stamps them into every
  // event).  Value storage directly on the meta instead of FLS slots:
  // scheduler-side emitters (ready/wake on the WAKER's thread) must be
  // able to read the TARGET fiber's context, which fls_get — keyed off
  // the calling thread — cannot do.  Atomics because those cross-thread
  // reads race the owning fiber's stores; relaxed everywhere (same-fiber
  // accesses are program-ordered across migration by the scheduler's
  // queue handoff, cross-thread reads are diagnostic-only).
  std::atomic<uint64_t> ambient_trace{0};
  std::atomic<uint64_t> ambient_span{0};
  // Ambient deadline plane (net/deadline.h): the absolute monotonic
  // deadline (µs; 0 = none) and cancel scope of the request this fiber
  // is serving.  Same storage rationale as ambient_trace; unlike the
  // trace pair these are only ever read by the OWNING fiber, but they
  // live here (not FLS) so the values follow the fiber across worker
  // migration.  Relaxed: same-fiber accesses are program-ordered across
  // migration by the scheduler's queue handoff.
  std::atomic<int64_t> ambient_deadline{0};
  std::atomic<void*> ambient_cancel{nullptr};
  // Last worker index this fiber ran on (-1 = never ran).  Written only
  // by the running worker; ready_to_run on a waker thread reads it to
  // tell first-ready from wake — atomic for that cross-thread read.
  std::atomic<int32_t> last_worker{-1};

  void park_lock() {
    while (park_mu.test_and_set(std::memory_order_acquire)) {
      sched_yield();
    }
  }
  void park_unlock() { park_mu.clear(std::memory_order_release); }
  // Join event: value holds the live version while running; bumped at exit.
  Event done_event;
  struct FlsSlot {
    void* value = nullptr;
    uint32_t version = 0;
  };
  std::vector<FlsSlot> fls;
  uint32_t slot = 0;  // own index in the pool
  uint8_t tag = 0;    // worker-group pin (task_control.h:94 tag parity)

  fiber_t id() const {
    return (static_cast<uint64_t>(version.load(std::memory_order_relaxed))
            << 32) |
           slot;
  }
};

FiberMeta* fiber_meta_of(fiber_t f);  // nullptr if stale/invalid
void run_fls_destructors(FiberMeta* m);

class ParkingLot {
 public:
  // Returns a stamp to pass to wait().
  int stamp() const { return seq_.load(std::memory_order_acquire); }
  void signal(int n);
  void wait(int stamp);

 private:
  std::atomic<int> seq_{0};
  // FUTEX_WAKE costs a syscall even with nobody parked — at 100k+ qps
  // most ready_to_run calls hit busy workers.  signal() ALWAYS bumps
  // seq_ (so a waiter between stamp and FUTEX_WAIT sees the change and
  // returns) and only syscalls when someone is actually parked.
  std::atomic<int> waiters_{0};
};

class Scheduler {
 public:
  static constexpr int kMaxTags = 4;  // kMaxFiberTags (fiber.h)

  static Scheduler* instance();
  void start(int workers);                  // tag 0
  void start_tag(int tag, int workers);     // idempotent per tag
  bool started() const {
    return tags_[0].nworkers.load(std::memory_order_acquire) > 0;
  }
  int worker_count(int tag = 0) const {
    return tags_[tag].nworkers.load(std::memory_order_acquire);
  }

  // Make a runnable fiber visible to some worker OF ITS TAG (any thread).
  void ready_to_run(FiberMeta* m, bool urgent = false);
  // Publish n runnables of ONE tag with a single ParkingLot signal (the
  // bulk-wake path behind fiber_start_batch).  Queue-push order follows
  // ms[]; execution order is unspecified (see fiber_start_batch).
  void ready_to_run_batch(FiberMeta* const* ms, size_t n, bool urgent);
  bool steal(FiberMeta** out, Worker* thief);
  bool pop_remote(FiberMeta** out, int tag);
  void push_remote(FiberMeta* m);

  // Bulk-wake telemetry (read by fiber_bulk_wake_stats).
  std::atomic<uint64_t> bulk_wake_batches{0};
  std::atomic<uint64_t> bulk_wake_fibers{0};
  std::atomic<uint64_t> bulk_wake_max{0};

  // Per-tag worker group: spawn/steal/park confined inside (the
  // reference's per-tag TaskControl groups, task_control.h:94-99).
  struct TagGroup {
    Worker* workers[64] = {};
    std::atomic<int> nworkers{0};
    std::mutex remote_mu;
    std::deque<FiberMeta*> remote_q;
    ParkingLot lot;
    std::once_flag once;
  };
  TagGroup& group(int tag) { return tags_[tag]; }

 private:
  Scheduler() = default;
  static constexpr int kMaxWorkers = 64;
  TagGroup tags_[kMaxTags];
};

class Worker {
 public:
  Worker(Scheduler* sched, int index, int tag);
  void main_loop();  // pthread entry

  // Called from a running fiber: switch back to the scheduler context.
  // post_fn(arg1, arg2) runs on the scheduler context after the switch.
  // dying = the fiber never resumes (lets ASan retire its fake frames).
  void suspend_current(PostSwitchFn post_fn, void* a1, void* a2,
                       bool dying = false);

  FiberMeta* current() const { return current_; }
  WorkStealingQueue<FiberMeta*>& runq() { return runq_; }
  int index() const { return index_; }
  int tag() const { return tag_; }

 private:
  friend class Scheduler;
  FiberMeta* pick_next();
  void run_fiber(FiberMeta* m);

  Scheduler* sched_;
  int index_;
  int tag_;
  // One-deep priority slot checked before the run queue (kFiberUrgent).
  std::atomic<FiberMeta*> urgent_{nullptr};
  WorkStealingQueue<FiberMeta*> runq_;
  FiberMeta* current_ = nullptr;
  void* sched_sp_ = nullptr;  // scheduler continuation while a fiber runs
  void* asan_fake_stack_ = nullptr;
  void* tsan_sched_fiber_ = nullptr;  // this worker's scheduler context
  void* pthread_stack_base_ = nullptr;  // this worker pthread's stack
  size_t pthread_stack_size_ = 0;
  PostSwitchFn post_fn_ = nullptr;
  void* post_a1_ = nullptr;
  void* post_a2_ = nullptr;
};

extern thread_local Worker* tls_worker;

}  // namespace trpc
