#include "fiber/stack.h"

#include <sys/mman.h>
#include <unistd.h>

#include <vector>

#include "base/logging.h"
#include "fiber/context.h"

namespace trpc {

namespace {

struct TlsStackCache {
  std::vector<StackMem> stacks;
  ~TlsStackCache() {
    for (StackMem& s : stacks) {
      munmap(s.base, s.size);
    }
  }
};

thread_local TlsStackCache g_stack_cache;
constexpr size_t kMaxCachedStacks = 32;

}  // namespace

StackMem allocate_stack(size_t size) {
  if (!g_stack_cache.stacks.empty()) {
    StackMem s = g_stack_cache.stacks.back();
    g_stack_cache.stacks.pop_back();
    if (s.size == size) {
      return s;
    }
    munmap(s.base, s.size);
  }
  const size_t page = static_cast<size_t>(sysconf(_SC_PAGESIZE));
  void* mem = mmap(nullptr, size, PROT_READ | PROT_WRITE,
                   MAP_PRIVATE | MAP_ANONYMOUS | MAP_STACK, -1, 0);
  CHECK(mem != MAP_FAILED) << "stack mmap failed";
  // Guard page at the low end catches overflow.
  CHECK(mprotect(mem, page, PROT_NONE) == 0);
  return StackMem{mem, size};
}

void release_stack(StackMem s) {
  if (g_stack_cache.stacks.size() < kMaxCachedStacks) {
    g_stack_cache.stacks.push_back(s);
    return;
  }
  munmap(s.base, s.size);
}

extern "C" void trpc_context_trampoline();

void* make_context(void* stack_base, size_t size, void (*entry)(void*)) {
  uintptr_t top = (reinterpret_cast<uintptr_t>(stack_base) + size) & ~15ull;
  // Layout (context.S): 64 bytes — fpu word, 6 regs, ret addr.
  uint64_t* frame = reinterpret_cast<uint64_t*>(top - 64);
  uint32_t mxcsr = 0;
  uint16_t fcw = 0;
  __asm__ volatile("stmxcsr %0; fnstcw %1" : "=m"(mxcsr), "=m"(fcw));
  frame[0] = static_cast<uint64_t>(mxcsr) | (static_cast<uint64_t>(fcw) << 32);
  frame[1] = 0;                                     // r15
  frame[2] = 0;                                     // r14
  frame[3] = 0;                                     // r13
  frame[4] = 0;                                     // r12
  frame[5] = reinterpret_cast<uint64_t>(entry);     // rbx → trampoline target
  frame[6] = 0;                                     // rbp
  frame[7] = reinterpret_cast<uint64_t>(&trpc_context_trampoline);
  return frame;
}

}  // namespace trpc
