#include "fiber/stack.h"

#include <sys/mman.h>
#include <unistd.h>

#include <mutex>
#include <vector>

#include "base/logging.h"
#include "base/tls_cache.h"
#include "fiber/context.h"

// ASan's fiber support (__sanitizer_start_switch_fiber in scheduler.cc)
// tags fiber stacks in shadow memory; munmap does NOT clear shadow, so a
// later unrelated mmap reusing the range would inherit stale stack poison
// and trip false positives.  Unpoison before every stack unmap.
#if defined(__SANITIZE_ADDRESS__)
#define TRPC_HAS_ASAN 1
#elif defined(__has_feature)
#if __has_feature(address_sanitizer)
#define TRPC_HAS_ASAN 1
#endif
#endif
#ifdef TRPC_HAS_ASAN
extern "C" void __asan_unpoison_memory_region(void const volatile*, size_t);
#define TRPC_UNPOISON_STACK(p, n) __asan_unpoison_memory_region(p, n)
#else
#define TRPC_UNPOISON_STACK(p, n) \
  do {                            \
  } while (0)
#endif

namespace trpc {

namespace {

struct StackCacheTag {};

void drain_stack(StackMem& s) {
  TRPC_UNPOISON_STACK(s.base, s.size);
  munmap(s.base, s.size);
}

std::vector<StackMem>* tls_stack_cache() {
  return TlsFreeCache<StackMem, StackCacheTag>::get(&drain_stack);
}

constexpr size_t kMaxCachedStacks = 32;

// Second-level shared cache (bthread StackFactory get/return_stack global
// pool parity, stack_inl.h).  The TLS caches alone defeat themselves under
// this runtime's thread asymmetry: dispatcher/poller pthreads SPAWN fibers
// (read fibers, timers) but never finish one, so their TLS cache is
// forever empty and every spawn paid mmap+mprotect+first-touch faults —
// ~25% of the 1KB-echo profile (r5).  Producers overflow here in batches;
// consumers refill in batches; one lock hit amortizes over kBatch spawns.
struct GlobalStackCache {
  std::mutex mu;
  std::vector<StackMem> stacks;
};

GlobalStackCache& global_stack_cache() {
  static auto* g = new GlobalStackCache();  // leaked: released after statics
  return *g;
}

constexpr size_t kMaxGlobalStacks = 512;
constexpr size_t kBatch = 8;

}  // namespace

StackMem allocate_stack(size_t size) {
  std::vector<StackMem>* cache = tls_stack_cache();
  if (cache != nullptr) {
    if (cache->empty()) {
      // Refill a batch from the shared cache (only same-size stacks live
      // there, so no per-entry size screening needed beyond the check
      // below).
      GlobalStackCache& g = global_stack_cache();
      std::lock_guard<std::mutex> lk(g.mu);
      while (!g.stacks.empty() && cache->size() < kBatch) {
        cache->push_back(g.stacks.back());
        g.stacks.pop_back();
      }
    }
    if (!cache->empty()) {
      StackMem s = cache->back();
      cache->pop_back();
      if (s.size == size) {
        return s;
      }
      TRPC_UNPOISON_STACK(s.base, s.size);
      munmap(s.base, s.size);
    }
  }
  const size_t page = static_cast<size_t>(sysconf(_SC_PAGESIZE));
  void* mem = mmap(nullptr, size, PROT_READ | PROT_WRITE,
                   MAP_PRIVATE | MAP_ANONYMOUS | MAP_STACK, -1, 0);
  CHECK(mem != MAP_FAILED) << "stack mmap failed";
  // Guard page at the low end catches overflow.
  CHECK(mprotect(mem, page, PROT_NONE) == 0);
  return StackMem{mem, size};
}

void release_stack(StackMem s) {
  if (s.size != kDefaultStackSize) {
    // Odd sizes never enter the caches; keeps the shared pool uniform.
    TRPC_UNPOISON_STACK(s.base, s.size);
    munmap(s.base, s.size);
    return;
  }
  std::vector<StackMem>* cache = tls_stack_cache();
  if (cache != nullptr) {
    if (cache->size() >= kMaxCachedStacks) {
      // Spill a batch to the shared cache so spawn-only threads can eat.
      GlobalStackCache& g = global_stack_cache();
      std::lock_guard<std::mutex> lk(g.mu);
      while (g.stacks.size() < kMaxGlobalStacks &&
             cache->size() > kMaxCachedStacks - kBatch) {
        g.stacks.push_back(cache->back());
        cache->pop_back();
      }
    }
    if (cache->size() < kMaxCachedStacks) {
      cache->push_back(s);
      return;
    }
  }
  TRPC_UNPOISON_STACK(s.base, s.size);
  munmap(s.base, s.size);
}

extern "C" void trpc_context_trampoline();

void* make_context(void* stack_base, size_t size, void (*entry)(void*)) {
  uintptr_t top = (reinterpret_cast<uintptr_t>(stack_base) + size) & ~15ull;
#if defined(__x86_64__)
  // Layout (context.S): 64 bytes — fpu word, 6 regs, ret addr.
  uint64_t* frame = reinterpret_cast<uint64_t*>(top - 64);
  uint32_t mxcsr = 0;
  uint16_t fcw = 0;
  __asm__ volatile("stmxcsr %0; fnstcw %1" : "=m"(mxcsr), "=m"(fcw));
  frame[0] = static_cast<uint64_t>(mxcsr) | (static_cast<uint64_t>(fcw) << 32);
  frame[1] = 0;                                     // r15
  frame[2] = 0;                                     // r14
  frame[3] = 0;                                     // r13
  frame[4] = 0;                                     // r12
  frame[5] = reinterpret_cast<uint64_t>(entry);     // rbx → trampoline target
  frame[6] = 0;                                     // rbp
  frame[7] = reinterpret_cast<uint64_t>(&trpc_context_trampoline);
  return frame;
#elif defined(__aarch64__)
  // Layout (context.S): 160 bytes — d8..d15, x19..x28, x29, x30.
  uint64_t* frame = reinterpret_cast<uint64_t*>(top - 160);
  for (int i = 0; i < 20; ++i) {
    frame[i] = 0;  // d8..d15 (8), x19..x28 (10 slots start at [8])
  }
  frame[8] = reinterpret_cast<uint64_t>(entry);  // x19 → trampoline target
  frame[18] = 0;                                 // x29 (fp)
  frame[19] = reinterpret_cast<uint64_t>(&trpc_context_trampoline);  // x30
  return frame;
#else
#error "unsupported architecture: add a make_context block"
#endif
}

}  // namespace trpc
