#include "fiber/stack.h"

#include <sys/mman.h>
#include <unistd.h>

#include <vector>

#include "base/logging.h"
#include "fiber/context.h"

// ASan's fiber support (__sanitizer_start_switch_fiber in scheduler.cc)
// tags fiber stacks in shadow memory; munmap does NOT clear shadow, so a
// later unrelated mmap reusing the range would inherit stale stack poison
// and trip false positives.  Unpoison before every stack unmap.
#if defined(__SANITIZE_ADDRESS__)
#define TRPC_HAS_ASAN 1
#elif defined(__has_feature)
#if __has_feature(address_sanitizer)
#define TRPC_HAS_ASAN 1
#endif
#endif
#ifdef TRPC_HAS_ASAN
extern "C" void __asan_unpoison_memory_region(void const volatile*, size_t);
#define TRPC_UNPOISON_STACK(p, n) __asan_unpoison_memory_region(p, n)
#else
#define TRPC_UNPOISON_STACK(p, n) \
  do {                            \
  } while (0)
#endif

namespace trpc {

namespace {

// Heap-owned TLS cache behind trivially-destructible thread_locals (same
// static-destruction hazard as the resource-pool caches).
struct TlsStackCache {
  std::vector<StackMem> stacks;
};

struct TlsStackGuard {
  TlsStackCache** slot = nullptr;
  bool* dead = nullptr;
  ~TlsStackGuard() {
    if (slot != nullptr && *slot != nullptr) {
      for (StackMem& s : (*slot)->stacks) {
        TRPC_UNPOISON_STACK(s.base, s.size);
        munmap(s.base, s.size);
      }
      delete *slot;
      *slot = nullptr;
    }
    if (dead != nullptr) {
      *dead = true;
    }
  }
};

TlsStackCache* tls_stack_cache() {
  static thread_local TlsStackCache* cache = nullptr;  // trivial dtor
  static thread_local bool cache_dead = false;
  static thread_local TlsStackGuard guard;
  if (cache_dead) {
    return nullptr;
  }
  if (cache == nullptr) {
    cache = new TlsStackCache();
    guard.slot = &cache;
    guard.dead = &cache_dead;
  }
  return cache;
}

constexpr size_t kMaxCachedStacks = 32;

}  // namespace

StackMem allocate_stack(size_t size) {
  TlsStackCache* cache = tls_stack_cache();
  if (cache != nullptr && !cache->stacks.empty()) {
    StackMem s = cache->stacks.back();
    cache->stacks.pop_back();
    if (s.size == size) {
      return s;
    }
    TRPC_UNPOISON_STACK(s.base, s.size);
    munmap(s.base, s.size);
  }
  const size_t page = static_cast<size_t>(sysconf(_SC_PAGESIZE));
  void* mem = mmap(nullptr, size, PROT_READ | PROT_WRITE,
                   MAP_PRIVATE | MAP_ANONYMOUS | MAP_STACK, -1, 0);
  CHECK(mem != MAP_FAILED) << "stack mmap failed";
  // Guard page at the low end catches overflow.
  CHECK(mprotect(mem, page, PROT_NONE) == 0);
  return StackMem{mem, size};
}

void release_stack(StackMem s) {
  TlsStackCache* cache = tls_stack_cache();
  if (cache != nullptr && cache->stacks.size() < kMaxCachedStacks) {
    cache->stacks.push_back(s);
    return;
  }
  TRPC_UNPOISON_STACK(s.base, s.size);
  munmap(s.base, s.size);
}

extern "C" void trpc_context_trampoline();

void* make_context(void* stack_base, size_t size, void (*entry)(void*)) {
  uintptr_t top = (reinterpret_cast<uintptr_t>(stack_base) + size) & ~15ull;
#if defined(__x86_64__)
  // Layout (context.S): 64 bytes — fpu word, 6 regs, ret addr.
  uint64_t* frame = reinterpret_cast<uint64_t*>(top - 64);
  uint32_t mxcsr = 0;
  uint16_t fcw = 0;
  __asm__ volatile("stmxcsr %0; fnstcw %1" : "=m"(mxcsr), "=m"(fcw));
  frame[0] = static_cast<uint64_t>(mxcsr) | (static_cast<uint64_t>(fcw) << 32);
  frame[1] = 0;                                     // r15
  frame[2] = 0;                                     // r14
  frame[3] = 0;                                     // r13
  frame[4] = 0;                                     // r12
  frame[5] = reinterpret_cast<uint64_t>(entry);     // rbx → trampoline target
  frame[6] = 0;                                     // rbp
  frame[7] = reinterpret_cast<uint64_t>(&trpc_context_trampoline);
  return frame;
#elif defined(__aarch64__)
  // Layout (context.S): 160 bytes — d8..d15, x19..x28, x29, x30.
  uint64_t* frame = reinterpret_cast<uint64_t*>(top - 160);
  for (int i = 0; i < 20; ++i) {
    frame[i] = 0;  // d8..d15 (8), x19..x28 (10 slots start at [8])
  }
  frame[8] = reinterpret_cast<uint64_t>(entry);  // x19 → trampoline target
  frame[18] = 0;                                 // x29 (fp)
  frame[19] = reinterpret_cast<uint64_t>(&trpc_context_trampoline);  // x30
  return frame;
#else
#error "unsupported architecture: add a make_context block"
#endif
}

}  // namespace trpc
