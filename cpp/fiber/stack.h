// Fiber stacks: mmap'd with a low guard page, cached per thread.
// Parity: bthread stacks (/root/reference/src/bthread/stack.h:56-73).
#pragma once

#include <cstddef>

namespace trpc {

struct StackMem {
  void* base = nullptr;
  size_t size = 0;
};

// 1MB like the reference's NORMAL stacks (stack.h:56): pages commit lazily,
// and embedded-language callbacks (Python handlers via capi) need headroom.
constexpr size_t kDefaultStackSize = 1024 * 1024;

StackMem allocate_stack(size_t size);
void release_stack(StackMem s);

}  // namespace trpc
