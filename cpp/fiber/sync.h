// Fiber-aware synchronization built on Event (parity: bthread mutex /
// condition / countdown_event, /root/reference/src/bthread/mutex.cpp,
// countdown_event.cpp — blocking parks the fiber, never the worker pthread).
#pragma once

#include <cerrno>

#include "base/time.h"
#include "fiber/analysis.h"
#include "fiber/event.h"
#include "stat/profiler.h"

namespace trpc {

// Futex-style mutex: 0 unlocked, 1 locked, 2 locked with waiters.
class FiberMutex {
 public:
  ~FiberMutex() {
    // Keep the analysis graph honest across address reuse (analysis.h).
    // Gated on graph_used, NOT enabled(): a process that toggled the
    // flag off still holds graph nodes that must purge, while one that
    // never armed the mode pays a relaxed load + untaken branch.
    if (analysis::graph_used()) {
      analysis::on_lock_destroyed(this);
    }
  }

  void lock() {
    uint32_t c = 0;
    if (ev_.value.compare_exchange_strong(c, 1, std::memory_order_acquire,
                                          std::memory_order_relaxed)) {
      // Lock-order recording (ISSUE 7): one relaxed load + untaken
      // branch on the uncontended path when trpc_analysis is off.
      // tracked_ latches the decision for THIS acquisition so a flag
      // flip while held can't strand a stale held-stack entry (only the
      // holder touches tracked_, ordered by the mutex itself).
      if (analysis::enabled()) {
        tracked_ = true;
        analysis::on_lock_acquired(this, __builtin_return_address(0));
      }
      return;
    }
    // Contended slow path: sampled by the contention profiler (parity:
    // bthread/mutex.cpp's lock-wait sampling feeding /contention).
    const int64_t t0 = monotonic_time_us();
    {
      // Bounded framework wait: the blocking detector must not count a
      // contended-lock microsleep as a dispatch-scope park (analysis.h).
      analysis::ScopedBoundedWait bounded;
      do {
        if (c == 2 ||
            ev_.value.compare_exchange_strong(c, 2,
                                              std::memory_order_acquire,
                                              std::memory_order_relaxed)) {
          ev_.wait(2, -1);
        }
        c = 0;
      } while (!ev_.value.compare_exchange_strong(
          c, 2, std::memory_order_acquire, std::memory_order_relaxed));
    }
    contention_record(__builtin_return_address(0),
                      monotonic_time_us() - t0);
    if (analysis::enabled()) {
      tracked_ = true;
      analysis::on_lock_acquired(this, __builtin_return_address(0));
    }
  }

  bool try_lock() {
    uint32_t c = 0;
    if (ev_.value.compare_exchange_strong(c, 1, std::memory_order_acquire,
                                          std::memory_order_relaxed)) {
      if (analysis::enabled()) {
        tracked_ = true;
        analysis::on_lock_acquired(this, __builtin_return_address(0));
      }
      return true;
    }
    return false;
  }

  void unlock() {
    // Keyed on the acquisition-time latch, not the live flag: release
    // bookkeeping must run even if trpc_analysis was flipped off while
    // this lock was held, or the FLS held-stack entry leaks and seeds
    // phantom edges after a re-enable.
    if (tracked_) {
      tracked_ = false;
      analysis::on_lock_released(this);
    }
    if (ev_.value.exchange(0, std::memory_order_release) == 2) {
      ev_.wake(1);
    }
  }

  // Diagnostic snapshot (/ids dump); racy by nature, never for control.
  bool locked() const {
    return ev_.value.load(std::memory_order_relaxed) != 0;
  }

 private:
  Event ev_;
  // Whether the CURRENT hold was recorded with the analysis plane; holder-
  // owned (written under the lock), so no atomicity needed.
  bool tracked_ = false;
};

// Countdown latch (parity: bthread::CountdownEvent).
class CountdownEvent {
 public:
  explicit CountdownEvent(int count) : count_(count) { ev_.value.store(0); }

  void signal(int n = 1) {
    if (count_.fetch_sub(n, std::memory_order_acq_rel) <= n) {
      ev_.value.store(1, std::memory_order_release);
      ev_.wake_all();
    }
  }

  // Returns 0, or ETIMEDOUT.
  int wait(int64_t deadline_us = -1) {
    while (count_.load(std::memory_order_acquire) > 0) {
      const int rc = ev_.wait(0, deadline_us);
      if (rc == ETIMEDOUT) {
        return rc;
      }
    }
    return 0;
  }

 private:
  std::atomic<int> count_;
  Event ev_;
};

template <typename Mutex>
class LockGuard {
 public:
  explicit LockGuard(Mutex& mu) : mu_(mu) { mu_.lock(); }
  ~LockGuard() { mu_.unlock(); }

 private:
  Mutex& mu_;
};

}  // namespace trpc
