// Fiber-aware synchronization built on Event (parity: bthread mutex /
// condition / countdown_event, /root/reference/src/bthread/mutex.cpp,
// countdown_event.cpp — blocking parks the fiber, never the worker pthread).
#pragma once

#include <cerrno>

#include "base/time.h"
#include "fiber/event.h"
#include "stat/profiler.h"

namespace trpc {

// Futex-style mutex: 0 unlocked, 1 locked, 2 locked with waiters.
class FiberMutex {
 public:
  void lock() {
    uint32_t c = 0;
    if (ev_.value.compare_exchange_strong(c, 1, std::memory_order_acquire,
                                          std::memory_order_relaxed)) {
      return;
    }
    // Contended slow path: sampled by the contention profiler (parity:
    // bthread/mutex.cpp's lock-wait sampling feeding /contention).
    const int64_t t0 = monotonic_time_us();
    do {
      if (c == 2 ||
          ev_.value.compare_exchange_strong(c, 2, std::memory_order_acquire,
                                            std::memory_order_relaxed)) {
        ev_.wait(2, -1);
      }
      c = 0;
    } while (!ev_.value.compare_exchange_strong(c, 2,
                                                std::memory_order_acquire,
                                                std::memory_order_relaxed));
    contention_record(__builtin_return_address(0),
                      monotonic_time_us() - t0);
  }

  bool try_lock() {
    uint32_t c = 0;
    return ev_.value.compare_exchange_strong(c, 1, std::memory_order_acquire,
                                             std::memory_order_relaxed);
  }

  void unlock() {
    if (ev_.value.exchange(0, std::memory_order_release) == 2) {
      ev_.wake(1);
    }
  }

  // Diagnostic snapshot (/ids dump); racy by nature, never for control.
  bool locked() const {
    return ev_.value.load(std::memory_order_relaxed) != 0;
  }

 private:
  Event ev_;
};

// Countdown latch (parity: bthread::CountdownEvent).
class CountdownEvent {
 public:
  explicit CountdownEvent(int count) : count_(count) { ev_.value.store(0); }

  void signal(int n = 1) {
    if (count_.fetch_sub(n, std::memory_order_acq_rel) <= n) {
      ev_.value.store(1, std::memory_order_release);
      ev_.wake_all();
    }
  }

  // Returns 0, or ETIMEDOUT.
  int wait(int64_t deadline_us = -1) {
    while (count_.load(std::memory_order_acquire) > 0) {
      const int rc = ev_.wait(0, deadline_us);
      if (rc == ETIMEDOUT) {
        return rc;
      }
    }
    return 0;
  }

 private:
  std::atomic<int> count_;
  Event ev_;
};

template <typename Mutex>
class LockGuard {
 public:
  explicit LockGuard(Mutex& mu) : mu_(mu) { mu_.lock(); }
  ~LockGuard() { mu_.unlock(); }

 private:
  Mutex& mu_;
};

}  // namespace trpc
