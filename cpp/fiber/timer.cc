#include "fiber/timer.h"

#include <linux/futex.h>
#include <pthread.h>

#include <atomic>
#include <queue>
#include <unordered_set>
#include <vector>

#include "base/futex_mutex.h"
#include "base/time.h"

namespace trpc {

struct TimerEntry {
  int64_t deadline_us;
  uint64_t id;
  TimerThread::Fn fn;
  void* arg;
  bool operator>(const TimerEntry& o) const {
    return deadline_us > o.deadline_us;
  }
};

namespace {

// Sharded: every RPC schedules a timeout at call start and unschedules it
// at completion — two lock acquisitions per call on what used to be ONE
// global mutex, contending with the timer loop itself.  With lazy
// cancellation the single heap also held ~qps × timeout_s dead entries
// (400k at 80k qps / 5s timeouts), so each push paid log2 of that under
// the lock.  Shards split both the contention and the heap depth; ids
// carry their shard in the low bits so unschedule is lock-local too.
constexpr int kTimerShardBits = 2;
constexpr int kTimerShards = 1 << kTimerShardBits;
constexpr uint64_t kShardMask = kTimerShards - 1;

struct Shard {
  // base/futex_mutex.h, NOT std::mutex: schedule() runs on fibers while
  // run() is a plain pthread — see the header for the gcc-10 libtsan
  // interceptor story this sidesteps (ISSUE 7).
  FutexMutex mu;
  // Sleep word for the shard loop: bumped (release) by schedule() when a
  // new earliest deadline lands, so a loop that read its stamp under the
  // lock can never sleep past it (the futex compare closes the window).
  std::atomic<uint32_t> wake_seq{0};
  std::priority_queue<TimerEntry, std::vector<TimerEntry>,
                      std::greater<TimerEntry>>
      heap;
  std::unordered_set<uint64_t> pending;
  uint64_t next_seq = 1;
};

}  // namespace

struct TimerThread::Impl {
  Shard shards[kTimerShards];
};

TimerThread* TimerThread::instance() {
  // Deliberately leaked: the timer pthreads outlive static destruction.
  static TimerThread* t = new TimerThread();
  return t;
}

TimerThread::TimerThread() : impl_(new Impl) {
  for (int i = 0; i < kTimerShards; ++i) {
    pthread_t tid;
    struct Arg {
      TimerThread* self;
      int shard;
    };
    pthread_create(
        &tid, nullptr,
        [](void* p) -> void* {
          Arg* a = static_cast<Arg*>(p);
          TimerThread* self = a->self;
          const int shard = a->shard;
          delete a;
          self->run(shard);
          return nullptr;
        },
        new Arg{this, i});
    pthread_detach(tid);
  }
}

uint64_t TimerThread::schedule(int64_t deadline_us, Fn fn, void* arg) {
  // Spread load across shards; the TLS counter keeps one thread's
  // schedule/unschedule pairs mostly shard-local without any sharing.
  static thread_local uint32_t rr = 0;
  Shard& s = impl_->shards[++rr & kShardMask];
  s.mu.lock();
  const uint64_t id =
      (s.next_seq++ << kTimerShardBits) | (&s - impl_->shards);
  s.heap.push(TimerEntry{deadline_us, id, fn, arg});
  s.pending.insert(id);
  // Wake the loop if the new timer is the earliest.
  const bool earliest = s.heap.top().id == id;
  if (earliest) {
    s.wake_seq.fetch_add(1, std::memory_order_release);
  }
  s.mu.unlock();
  if (earliest) {
    futex_word_op(&s.wake_seq, FUTEX_WAKE_PRIVATE, 1, nullptr);
  }
  return id;
}

bool TimerThread::unschedule(uint64_t id) {
  Shard& s = impl_->shards[id & kShardMask];
  s.mu.lock();
  const bool erased = s.pending.erase(id) > 0;  // heap entry skipped lazily
  s.mu.unlock();
  return erased;
}

void TimerThread::run(int shard) {
  Shard& s = impl_->shards[shard];
  while (true) {
    s.mu.lock();
    int64_t next_deadline = -1;
    while (!s.heap.empty()) {
      TimerEntry top = s.heap.top();
      if (s.pending.count(top.id) == 0) {  // cancelled
        s.heap.pop();
        continue;
      }
      const int64_t now = monotonic_time_us();
      if (top.deadline_us > now) {
        next_deadline = top.deadline_us;
        break;
      }
      s.heap.pop();
      s.pending.erase(top.id);
      s.mu.unlock();
      top.fn(top.arg);
      s.mu.lock();
    }
    // Stamp read UNDER the lock: a schedule() that lands an earlier
    // deadline can only run after our unlock, and its bump makes the
    // futex compare below fail — no sleep can outlive a new earliest.
    const uint32_t stamp = s.wake_seq.load(std::memory_order_acquire);
    s.mu.unlock();
    timespec ts;
    timespec* tsp = nullptr;
    if (next_deadline >= 0) {
      const int64_t left = next_deadline - monotonic_time_us();
      if (left <= 0) {
        continue;
      }
      ts.tv_sec = left / 1000000;
      ts.tv_nsec = (left % 1000000) * 1000;
      tsp = &ts;
    }
    futex_word_op(&s.wake_seq, FUTEX_WAIT_PRIVATE, stamp, tsp);
  }
}

}  // namespace trpc
