#include "fiber/timer.h"

#include <pthread.h>

#include <atomic>
#include <condition_variable>
#include <mutex>
#include <queue>
#include <unordered_set>
#include <vector>

#include "base/time.h"

namespace trpc {

struct TimerEntry {
  int64_t deadline_us;
  uint64_t id;
  TimerThread::Fn fn;
  void* arg;
  bool operator>(const TimerEntry& o) const {
    return deadline_us > o.deadline_us;
  }
};

namespace {

// Sharded: every RPC schedules a timeout at call start and unschedules it
// at completion — two lock acquisitions per call on what used to be ONE
// global mutex, contending with the timer loop itself.  With lazy
// cancellation the single heap also held ~qps × timeout_s dead entries
// (400k at 80k qps / 5s timeouts), so each push paid log2 of that under
// the lock.  Shards split both the contention and the heap depth; ids
// carry their shard in the low bits so unschedule is lock-local too.
constexpr int kTimerShardBits = 2;
constexpr int kTimerShards = 1 << kTimerShardBits;
constexpr uint64_t kShardMask = kTimerShards - 1;

struct Shard {
  std::mutex mu;
  std::condition_variable cv;
  std::priority_queue<TimerEntry, std::vector<TimerEntry>,
                      std::greater<TimerEntry>>
      heap;
  std::unordered_set<uint64_t> pending;
  uint64_t next_seq = 1;
};

}  // namespace

struct TimerThread::Impl {
  Shard shards[kTimerShards];
};

TimerThread* TimerThread::instance() {
  // Deliberately leaked: the timer pthreads outlive static destruction.
  static TimerThread* t = new TimerThread();
  return t;
}

TimerThread::TimerThread() : impl_(new Impl) {
  for (int i = 0; i < kTimerShards; ++i) {
    pthread_t tid;
    struct Arg {
      TimerThread* self;
      int shard;
    };
    pthread_create(
        &tid, nullptr,
        [](void* p) -> void* {
          Arg* a = static_cast<Arg*>(p);
          TimerThread* self = a->self;
          const int shard = a->shard;
          delete a;
          self->run(shard);
          return nullptr;
        },
        new Arg{this, i});
    pthread_detach(tid);
  }
}

uint64_t TimerThread::schedule(int64_t deadline_us, Fn fn, void* arg) {
  // Spread load across shards; the TLS counter keeps one thread's
  // schedule/unschedule pairs mostly shard-local without any sharing.
  static thread_local uint32_t rr = 0;
  Shard& s = impl_->shards[++rr & kShardMask];
  std::unique_lock<std::mutex> g(s.mu);
  const uint64_t id =
      (s.next_seq++ << kTimerShardBits) | (&s - impl_->shards);
  s.heap.push(TimerEntry{deadline_us, id, fn, arg});
  s.pending.insert(id);
  // Wake the loop if the new timer is the earliest.
  if (s.heap.top().id == id) {
    s.cv.notify_one();
  }
  return id;
}

bool TimerThread::unschedule(uint64_t id) {
  Shard& s = impl_->shards[id & kShardMask];
  std::lock_guard<std::mutex> g(s.mu);
  return s.pending.erase(id) > 0;  // heap entry skipped lazily
}

void TimerThread::run(int shard) {
  Shard& s = impl_->shards[shard];
  std::unique_lock<std::mutex> g(s.mu);
  while (true) {
    while (!s.heap.empty()) {
      TimerEntry top = s.heap.top();
      if (s.pending.count(top.id) == 0) {  // cancelled
        s.heap.pop();
        continue;
      }
      const int64_t now = monotonic_time_us();
      if (top.deadline_us > now) {
        break;
      }
      s.heap.pop();
      s.pending.erase(top.id);
      g.unlock();
      top.fn(top.arg);
      g.lock();
    }
    if (s.heap.empty()) {
      s.cv.wait(g);
    } else {
      s.cv.wait_for(g, std::chrono::microseconds(s.heap.top().deadline_us -
                                                 monotonic_time_us()));
    }
  }
}

}  // namespace trpc
