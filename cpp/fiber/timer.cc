#include "fiber/timer.h"

#include <pthread.h>

#include <condition_variable>
#include <mutex>
#include <queue>
#include <unordered_set>
#include <vector>

#include "base/time.h"

namespace trpc {

struct TimerEntry {
  int64_t deadline_us;
  uint64_t id;
  TimerThread::Fn fn;
  void* arg;
  bool operator>(const TimerEntry& o) const {
    return deadline_us > o.deadline_us;
  }
};

struct TimerThread::Impl {
  std::mutex mu;
  std::condition_variable cv;
  std::priority_queue<TimerEntry, std::vector<TimerEntry>,
                      std::greater<TimerEntry>>
      heap;
  std::unordered_set<uint64_t> pending;
  uint64_t next_id = 1;
};

TimerThread* TimerThread::instance() {
  // Deliberately leaked: the timer pthread outlives static destruction.
  static TimerThread* t = new TimerThread();
  return t;
}

TimerThread::TimerThread() : impl_(new Impl) {
  pthread_t tid;
  pthread_create(
      &tid, nullptr,
      [](void* self) -> void* {
        static_cast<TimerThread*>(self)->run();
        return nullptr;
      },
      this);
  pthread_detach(tid);
}

uint64_t TimerThread::schedule(int64_t deadline_us, Fn fn, void* arg) {
  std::unique_lock<std::mutex> g(impl_->mu);
  const uint64_t id = impl_->next_id++;
  impl_->heap.push(TimerEntry{deadline_us, id, fn, arg});
  impl_->pending.insert(id);
  // Wake the loop if the new timer is the earliest.
  if (impl_->heap.top().id == id) {
    impl_->cv.notify_one();
  }
  return id;
}

bool TimerThread::unschedule(uint64_t id) {
  std::lock_guard<std::mutex> g(impl_->mu);
  return impl_->pending.erase(id) > 0;  // heap entry skipped lazily
}

void TimerThread::run() {
  std::unique_lock<std::mutex> g(impl_->mu);
  while (true) {
    while (!impl_->heap.empty()) {
      TimerEntry top = impl_->heap.top();
      if (impl_->pending.count(top.id) == 0) {  // cancelled
        impl_->heap.pop();
        continue;
      }
      const int64_t now = monotonic_time_us();
      if (top.deadline_us > now) {
        break;
      }
      impl_->heap.pop();
      impl_->pending.erase(top.id);
      g.unlock();
      top.fn(top.arg);
      g.lock();
    }
    if (impl_->heap.empty()) {
      impl_->cv.wait(g);
    } else {
      impl_->cv.wait_for(g, std::chrono::microseconds(
                                impl_->heap.top().deadline_us -
                                monotonic_time_us()));
    }
  }
}

}  // namespace trpc
