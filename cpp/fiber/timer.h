// TimerThread — one global timing pthread.
//
// Parity: bthread TimerThread (/root/reference/src/bthread/timer_thread.h:53)
// which backs RPC deadlines and sleeps.  Re-designed: mutex+condvar min-heap
// with a pending-id set for O(1) lazy cancellation (the reference hashes
// timers into buckets).
#pragma once

#include <cstdint>

namespace trpc {

class TimerThread {
 public:
  using Fn = void (*)(void*);

  static TimerThread* instance();

  // Runs fn(arg) at monotonic deadline_us (in the timer thread; keep it
  // cheap — typically just an Event::wake).  Returns a cancellation id.
  uint64_t schedule(int64_t deadline_us, Fn fn, void* arg);
  // True if the timer was removed before firing (fn will NOT run).
  bool unschedule(uint64_t id);

 private:
  TimerThread();
  void run(int shard);
  struct Impl;
  Impl* impl_;
};

}  // namespace trpc
