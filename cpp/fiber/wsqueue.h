// Chase-Lev work-stealing deque (single owner push/pop at the bottom,
// concurrent thieves steal at the top).
// Parity: bthread WorkStealingQueue
// (/root/reference/src/bthread/work_stealing_queue.h:32).
#pragma once

#include <atomic>
#include <cstddef>

namespace trpc {

template <typename T>
class WorkStealingQueue {
 public:
  explicit WorkStealingQueue(size_t cap = 8192)
      : cap_(cap), mask_(cap - 1), buf_(new std::atomic<T>[cap]) {
    static_assert(sizeof(T) <= sizeof(void*), "T must be pointer-sized");
  }
  ~WorkStealingQueue() { delete[] buf_; }

  // Owner only.  Returns false when full.
  bool push(T item) {
    const size_t b = bottom_.load(std::memory_order_relaxed);
    const size_t t = top_.load(std::memory_order_acquire);
    if (b - t >= cap_) {
      return false;
    }
    buf_[b & mask_].store(item, std::memory_order_relaxed);
    bottom_.store(b + 1, std::memory_order_release);
    return true;
  }

  // Owner only.
  bool pop(T* out) {
    size_t b = bottom_.load(std::memory_order_relaxed);
    const size_t t0 = top_.load(std::memory_order_relaxed);
    if (t0 >= b) {
      return false;
    }
    b -= 1;
    bottom_.store(b, std::memory_order_relaxed);
    std::atomic_thread_fence(std::memory_order_seq_cst);
    size_t t = top_.load(std::memory_order_relaxed);
    if (t > b) {  // emptied by thieves
      bottom_.store(b + 1, std::memory_order_relaxed);
      return false;
    }
    T item = buf_[b & mask_].load(std::memory_order_relaxed);
    if (t == b) {  // last element: race with thieves via CAS on top
      if (!top_.compare_exchange_strong(t, t + 1, std::memory_order_seq_cst,
                                        std::memory_order_relaxed)) {
        bottom_.store(b + 1, std::memory_order_relaxed);
        return false;
      }
      bottom_.store(b + 1, std::memory_order_relaxed);
    }
    *out = item;
    return true;
  }

  // Any thread.
  bool steal(T* out) {
    size_t t = top_.load(std::memory_order_acquire);
    std::atomic_thread_fence(std::memory_order_seq_cst);
    const size_t b = bottom_.load(std::memory_order_acquire);
    if (t >= b) {
      return false;
    }
    T item = buf_[t & mask_].load(std::memory_order_relaxed);
    if (!top_.compare_exchange_strong(t, t + 1, std::memory_order_seq_cst,
                                      std::memory_order_relaxed)) {
      return false;  // lost the race; caller retries elsewhere
    }
    *out = item;
    return true;
  }

  size_t approx_size() const {
    const size_t b = bottom_.load(std::memory_order_relaxed);
    const size_t t = top_.load(std::memory_order_relaxed);
    return b > t ? b - t : 0;
  }

 private:
  const size_t cap_;
  const size_t mask_;
  std::atomic<T>* buf_;
  alignas(64) std::atomic<size_t> top_{1};
  alignas(64) std::atomic<size_t> bottom_{1};
};

}  // namespace trpc
