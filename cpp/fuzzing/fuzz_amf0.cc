// libFuzzer target: the AMF0 value reader (RTMP command payloads).
#include <string>

#include "net/rtmp.h"

#include "fuzzing/fuzz_driver.h"

using namespace trpc;

extern "C" int LLVMFuzzerTestOneInput(const uint8_t* data, size_t size) {
  const std::string input(reinterpret_cast<const char*>(data), size);
  Amf0Value v;
  size_t pos = 0;
  const int rc = amf0_read(input, &pos, &v, 0);
  if (rc < -1 || rc > 1 || (rc == 1 && pos > input.size())) {
    __builtin_trap();
  }
  return 0;
}
