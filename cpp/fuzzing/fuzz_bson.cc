// libFuzzer target: the BSON document reader (mongo OP_MSG bodies).
#include <string>

#include "net/mongo.h"

#include "fuzzing/fuzz_driver.h"

using namespace trpc;

extern "C" int LLVMFuzzerTestOneInput(const uint8_t* data, size_t size) {
  const std::string input(reinterpret_cast<const char*>(data), size);
  BsonDoc doc;
  size_t pos = 0;
  const int rc = bson_read_doc(input, &pos, &doc, 0);
  if (rc < -1 || rc > 1 || (rc == 1 && pos > input.size())) {
    __builtin_trap();
  }
  return 0;
}
