// Shared driver for the fuzz targets (reference: test/fuzzing/*.cpp +
// oss-fuzz.sh).  Each target defines the libFuzzer ABI:
//
//   extern "C" int LLVMFuzzerTestOneInput(const uint8_t* data, size_t n);
//
// Built with a real fuzzer engine (clang -fsanitize=fuzzer
// -DTRPC_LIBFUZZER), the engine drives it.  On this image (gcc, no
// libFuzzer) the fallback main() below replays every file in the seed
// corpus directory (argv[1]) verbatim, then runs a deterministic
// structure-aware mutation loop over the seeds — the same harness the
// ASan/TSan CI configs execute, so corpus regressions gate every build.
#pragma once

#include <dirent.h>

#include <cstdint>
#include <cstdio>
#include <cstring>
#include <fstream>
#include <string>
#include <vector>

extern "C" int LLVMFuzzerTestOneInput(const uint8_t* data, size_t size);

#ifndef TRPC_LIBFUZZER

namespace trpc_fuzz {

inline uint64_t& rng_state() {
  static uint64_t s = 0x9e3779b97f4a7c15ull;  // fixed seed: repeatable
  return s;
}

inline uint64_t rng() {
  uint64_t& s = rng_state();
  s ^= s << 13;
  s ^= s >> 7;
  s ^= s << 17;
  return s;
}

inline std::string mutate(const std::string& base) {
  std::string m = base;
  switch (rng() % 6) {
    case 0:  // bit flips
      for (int i = 0; i < 1 + static_cast<int>(rng() % 8); ++i) {
        if (!m.empty()) {
          m[rng() % m.size()] ^= static_cast<char>(1 << (rng() % 8));
        }
      }
      break;
    case 1:  // truncate
      m.resize(rng() % (m.size() + 1));
      break;
    case 2: {  // splice halves
      const size_t cut = m.empty() ? 0 : rng() % m.size();
      m = m.substr(cut) + m.substr(0, cut);
      break;
    }
    case 3:  // stomp a 4-byte window with a hostile length
      if (m.size() >= 4) {
        const uint32_t evil =
            (rng() % 2) ? 0xffffffffu : static_cast<uint32_t>(rng());
        memcpy(m.data() + rng() % (m.size() - 3), &evil, 4);
      }
      break;
    case 4:  // duplicate a slice
      if (!m.empty()) {
        const size_t at = rng() % m.size();
        const size_t n = 1 + rng() % std::min<size_t>(64, m.size() - at);
        m.insert(at, m.substr(at, n));
      }
      break;
    default:  // random garbage byte run
      for (int i = 0; i < 4; ++i) {
        m.push_back(static_cast<char>(rng()));
      }
      break;
  }
  return m;
}

inline int drive(int argc, char** argv, int mutations_per_seed = 20000) {
  if (argc < 2) {
    fprintf(stderr, "usage: %s <corpus_dir> [mutations_per_seed]\n",
            argv[0]);
    return 2;
  }
  if (argc > 2) {
    mutations_per_seed = atoi(argv[2]);
  }
  std::vector<std::string> seeds;
  DIR* d = opendir(argv[1]);
  if (d == nullptr) {
    fprintf(stderr, "cannot open corpus dir %s\n", argv[1]);
    return 2;
  }
  while (dirent* e = readdir(d)) {
    if (e->d_name[0] == '.') {
      continue;
    }
    std::ifstream f(std::string(argv[1]) + "/" + e->d_name,
                    std::ios::binary);
    seeds.emplace_back(std::istreambuf_iterator<char>(f),
                       std::istreambuf_iterator<char>());
  }
  closedir(d);
  if (seeds.empty()) {
    fprintf(stderr, "empty corpus dir %s\n", argv[1]);
    return 2;
  }
  // 1. Replay every seed verbatim (regression corpus).
  for (const std::string& s : seeds) {
    LLVMFuzzerTestOneInput(reinterpret_cast<const uint8_t*>(s.data()),
                           s.size());
  }
  // 2. Deterministic mutation sweep.
  for (int i = 0; i < mutations_per_seed; ++i) {
    const std::string input = mutate(seeds[rng() % seeds.size()]);
    LLVMFuzzerTestOneInput(reinterpret_cast<const uint8_t*>(input.data()),
                           input.size());
  }
  printf("%zu seeds + %d mutations: ok\n", seeds.size(),
         mutations_per_seed);
  return 0;
}

}  // namespace trpc_fuzz

int main(int argc, char** argv) { return trpc_fuzz::drive(argc, argv); }

#endif  // !TRPC_LIBFUZZER
