// libFuzzer target: HPACK header-block decoding incl. huffman
// (reference fuzz_hpack).
#include "net/hpack.h"

#include "fuzzing/fuzz_driver.h"

using namespace trpc;

extern "C" int LLVMFuzzerTestOneInput(const uint8_t* data, size_t size) {
  {
    HpackDecoder dec;
    HeaderList out;
    (void)dec.decode(data, size, &out);  // must terminate, never overread
  }
  {
    std::string plain;
    (void)hpack_huffman_decode(data, size, &plain);
  }
  if (size >= 1) {
    const uint8_t* p = data;
    uint64_t v = 0;
    (void)hpack_decode_int(&p, data + size, 5, &v);
    if (p > data + size) {
      __builtin_trap();  // decoder ran past the buffer
    }
  }
  return 0;
}
