// libFuzzer target: the HTTP/1.x request parser (reference fuzz_http).
#include "base/iobuf.h"
#include "net/http_message.h"

#include "fuzzing/fuzz_driver.h"

using namespace trpc;

extern "C" int LLVMFuzzerTestOneInput(const uint8_t* data, size_t size) {
  IOBuf buf;
  buf.append(data, size);
  HttpRequest req;
  IOBuf body;
  const size_t before = buf.size();
  const ParseError rc = http_parse_request(&buf, &req, &body);
  if (rc == ParseError::kNotEnoughData && buf.size() != before) {
    __builtin_trap();
  }
  if (buf.size() > before) {
    __builtin_trap();
  }
  return 0;
}
