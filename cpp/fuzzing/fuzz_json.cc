// libFuzzer target: the strict JSON parser (reference fuzz_json).
#include <string>

#include "base/json.h"

#include "fuzzing/fuzz_driver.h"

using namespace trpc;

extern "C" int LLVMFuzzerTestOneInput(const uint8_t* data, size_t size) {
  const std::string input(reinterpret_cast<const char*>(data), size);
  Json j;
  if (Json::parse(input, &j)) {
    // Parse success implies dump terminates and re-parses.
    Json j2;
    if (!Json::parse(j.dump(), &j2)) {
      __builtin_trap();
    }
  }
  return 0;
}
