// libFuzzer target: mcpack_v2 value parser (base/mcpack.h).
#include "base/mcpack.h"

#include "fuzzing/fuzz_driver.h"

using namespace trpc;

extern "C" int LLVMFuzzerTestOneInput(const uint8_t* data, size_t size) {
  McpackValue v;
  size_t consumed = 0;
  if (McpackValue::parse(reinterpret_cast<const char*>(data), size, &v,
                         &consumed)) {
    if (consumed > size) {
      __builtin_trap();  // parser claimed bytes past the buffer
    }
    // Parse success implies serializability (the tree is well-formed).
    (void)v.serialize();
  }
  return 0;
}
