// libFuzzer target: the memcache binary frame parser.
#include <string>

#include "net/memcache.h"

#include "fuzzing/fuzz_driver.h"

using namespace trpc;

extern "C" int LLVMFuzzerTestOneInput(const uint8_t* data, size_t size) {
  const std::string input(reinterpret_cast<const char*>(data), size);
  McFrame frame;
  size_t pos = 0;
  const int rc = mc_parse_frame(input, &pos, &frame);
  if (rc < -1 || rc > 1 || (rc == 1 && pos > input.size())) {
    __builtin_trap();
  }
  return 0;
}
