// libFuzzer target: nshead frame cutting (magic/body_len validation).
#include "base/iobuf.h"
#include "net/nshead.h"

#include "fuzzing/fuzz_driver.h"

using namespace trpc;

extern "C" int LLVMFuzzerTestOneInput(const uint8_t* data, size_t size) {
  IOBuf buf;
  buf.append(data, size);
  NsheadHead head;
  IOBuf body;
  const size_t before = buf.size();
  const int rc = nshead_cut_frame(&buf, &head, &body);
  if (rc < -1 || rc > 1) {
    __builtin_trap();
  }
  if (rc == 0 && buf.size() != before) {
    __builtin_trap();  // not-enough-data must not consume
  }
  return 0;
}
