// libFuzzer target: protobuf wire-format parser + schemaless JSON walk
// (reference fuzz_json, fuzz_uncompress analogues).
#include <string>

#include "base/pbwire.h"

#include "fuzzing/fuzz_driver.h"

using namespace trpc;

extern "C" int LLVMFuzzerTestOneInput(const uint8_t* data, size_t size) {
  const std::string input(reinterpret_cast<const char*>(data), size);
  PbMessage m;
  if (m.parse(input)) {
    // Parse success implies a semantic fixpoint under re-serialization.
    const std::string round = m.serialize();
    PbMessage m2;
    if (!m2.parse(round) || m2.fields().size() != m.fields().size() ||
        m2.serialize() != round) {
      __builtin_trap();
    }
    (void)pb_to_json_schemaless(m);  // must terminate on any parse
  }
  return 0;
}
