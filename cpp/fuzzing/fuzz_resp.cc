// libFuzzer target: RESP command + reply parsers (reference fuzz_redis).
#include <string>
#include <vector>

#include "net/redis.h"

#include "fuzzing/fuzz_driver.h"

using namespace trpc;

extern "C" int LLVMFuzzerTestOneInput(const uint8_t* data, size_t size) {
  const std::string input(reinterpret_cast<const char*>(data), size);
  {
    std::vector<std::string> args;
    size_t pos = 0;
    const int rc = resp_parse_command(input, &pos, &args);
    if (rc < -1 || rc > 1 || (rc == 1 && pos > input.size()) ||
        (rc != 1 && pos != 0)) {
      __builtin_trap();
    }
  }
  {
    RedisReply reply;
    size_t pos = 0;
    const int rc = resp_parse_reply(input, &pos, &reply);
    if (rc < -1 || rc > 1 || (rc == 1 && pos > input.size())) {
      __builtin_trap();
    }
  }
  return 0;
}
