// libFuzzer target: framed-thrift payload parser (reference
// fuzz_butil/thrift analogue).
#include <string>

#include "net/thrift.h"

#include "fuzzing/fuzz_driver.h"

using namespace trpc;

extern "C" int LLVMFuzzerTestOneInput(const uint8_t* data, size_t size) {
  const std::string input(reinterpret_cast<const char*>(data), size);
  ThriftMessage m;
  (void)thrift_parse_payload(input, &m);  // terminate, never crash/overread
  return 0;
}
