// libFuzzer target: the tstd frame parser (reference fuzz_baidu_std).
#include "base/iobuf.h"
#include "net/protocol.h"

#include "fuzzing/fuzz_driver.h"

using namespace trpc;

extern "C" int LLVMFuzzerTestOneInput(const uint8_t* data, size_t size) {
  IOBuf buf;
  buf.append(data, size);
  InputMessage msg;
  const size_t before = buf.size();
  const ParseError rc = tstd_protocol().parse(&buf, &msg, nullptr);
  // Invariants: never consume on NotEnoughData; never grow the buffer.
  if (rc == ParseError::kNotEnoughData && buf.size() != before) {
    __builtin_trap();
  }
  if (buf.size() > before) {
    __builtin_trap();
  }
  return 0;
}
