// Seed-corpus generator: emits one file per valid message shape into
// cpp/fuzzing/corpus/<target>/ using the REAL packers, so checked-in
// seeds track the wire formats.  Re-run after a format change:
//   ./build/gen_corpus cpp/fuzzing/corpus
#include <sys/stat.h>

#include <cstdio>
#include <fstream>
#include <string>

#include "base/iobuf.h"
#include "base/json.h"
#include "base/mcpack.h"
#include "base/pbwire.h"
#include "net/hpack.h"
#include "net/mongo.h"
#include "net/nshead.h"
#include "net/protocol.h"
#include "net/rtmp.h"
#include "net/thrift.h"

using namespace trpc;

namespace {

std::string g_root;

void put(const std::string& target, const std::string& name,
         const std::string& bytes) {
  const std::string dir = g_root + "/" + target;
  mkdir(dir.c_str(), 0755);
  std::ofstream f(dir + "/" + name, std::ios::binary);
  f.write(bytes.data(), static_cast<std::streamsize>(bytes.size()));
}

}  // namespace

int main(int argc, char** argv) {
  g_root = argc > 1 ? argv[1] : "cpp/fuzzing/corpus";

  // -- tstd: request / response / auth / stream-frame shapes ------------
  for (int variant = 0; variant < 4; ++variant) {
    RpcMeta meta;
    meta.type = variant == 0   ? RpcMeta::kRequest
                : variant == 1 ? RpcMeta::kResponse
                : variant == 2 ? RpcMeta::kAuth
                               : RpcMeta::kStreamFrame;
    meta.correlation_id = 0x1234567890 + variant;
    meta.method = "Echo.Echo";
    if (variant == 1) {
      meta.error_code = 42;
      meta.error_text = "deliberate";
    }
    if (variant == 3) {
      meta.stream_id = 7;
      meta.ack_bytes = 1 << 20;
    }
    meta.attachment_size = variant == 0 ? 16 : 0;
    IOBuf frame, payload;
    payload.append(std::string(48 + variant * 100, 'x'));
    tstd_pack(&frame, meta, payload);
    put("tstd", "frame" + std::to_string(variant), frame.to_string());
  }

  // -- http --------------------------------------------------------------
  put("http", "get", "GET /vars HTTP/1.1\r\nHost: a\r\n\r\n");
  put("http", "post",
      "POST /Echo.Echo HTTP/1.1\r\nHost: a\r\nContent-Length: 5\r\n\r\n"
      "hello");
  put("http", "chunked",
      "POST /x HTTP/1.1\r\nTransfer-Encoding: chunked\r\n\r\n"
      "4\r\nWiki\r\n0\r\nX-T: v\r\n\r\n");
  put("http", "query",
      "GET /flags/a?setvalue=%31+2&k HTTP/1.0\r\nConnection: "
      "keep-alive\r\n\r\n");
  put("http", "head", "HEAD /health#frag HTTP/1.1\r\nA: b\r\nC: d\r\n\r\n");

  // -- hpack: a real header block from our encoder -----------------------
  {
    HpackEncoder enc;
    HeaderList hl;
    hl.emplace_back(":method", "POST");
    hl.emplace_back(":path", "/pkg.Svc/Method");
    hl.emplace_back(":authority", "host.example:443");
    hl.emplace_back("content-type", "application/grpc");
    hl.emplace_back("x-custom", std::string(100, 'v'));
    std::string block;
    enc.encode(hl, &block);
    put("hpack", "grpc_headers", block);
    std::string block2;
    enc.encode(hl, &block2);  // second block: indexed-field forms
    put("hpack", "indexed_repeat", block2);
  }

  // -- resp --------------------------------------------------------------
  put("resp", "command", "*3\r\n$3\r\nSET\r\n$1\r\nk\r\n$5\r\nvalue\r\n");
  put("resp", "replies",
      "+OK\r\n-ERR boom\r\n:12345\r\n$6\r\nfoobar\r\n*2\r\n:1\r\n:2\r\n");
  put("resp", "nested", "*2\r\n*2\r\n:1\r\n$1\r\na\r\n*1\r\n+x\r\n");
  put("resp", "inline", "PING\r\n");

  // -- pbwire ------------------------------------------------------------
  {
    PbMessage m;
    m.add_bytes(1, "EchoService");
    m.add_varint(2, 3);
    m.add_sint(3, -99);
    PbMessage inner;
    inner.add_bytes(1, std::string(200, 'n'));
    m.add_message(4, inner);
    m.add_fixed64(5, 0x1122334455667788ULL);
    m.add_fixed32(6, 0xabcdef01u);
    put("pbwire", "meta", m.serialize());
  }

  // -- thrift ------------------------------------------------------------
  {
    ThriftMessage m;
    m.mtype = TMessageType::kCall;
    m.method = "Echo";
    m.seq_id = 9;
    m.body = ThriftValue::Struct();
    m.body.add_field(1, ThriftValue::Str(std::string(64, 'p')));
    ThriftValue lst = ThriftValue::List(TType::kI32);
    lst.elems = {ThriftValue::I32(1), ThriftValue::I32(2)};
    m.body.add_field(2, lst);
    ThriftValue mp = ThriftValue::Map(TType::kString, TType::kI64);
    mp.kvs.emplace_back(ThriftValue::Str("k"), ThriftValue::I64(7));
    m.body.add_field(3, mp);
    std::string wire;
    thrift_pack_message(m, &wire);
    put("thrift", "call", wire.substr(4));  // frame payload
  }

  // -- mcpack ------------------------------------------------------------
  {
    McpackValue obj = McpackValue::Object();
    obj.add_field("i32", McpackValue::I32(-123456));
    obj.add_field("u64", McpackValue::U64(uint64_t{1} << 63));
    obj.add_field("s", McpackValue::Str("hello mcpack"));
    obj.add_field("bin",
                  McpackValue::Binary(std::string("\x00\x01\x02", 3)));
    McpackValue arr = McpackValue::Array();
    arr.add_item(McpackValue::Str("a"));
    arr.add_item(McpackValue::I32(2));
    obj.add_field("arr", std::move(arr));
    McpackValue iso = McpackValue::IsoArray(McpackType::kInt32);
    for (int i = 0; i < 5; ++i) {
      iso.add_item(McpackValue::I32(i * 100));
    }
    obj.add_field("iso", std::move(iso));
    obj.add_field("big", McpackValue::Str(std::string(1000, 'x')));
    put("mcpack", "object", obj.serialize());
    put("mcpack", "scalar", McpackValue::I32(7).serialize());
  }

  // -- json --------------------------------------------------------------
  put("json", "object",
      "{\"a\":1,\"b\":[true,null,2.5],\"c\":{\"d\":\"e\\u00e9\"}}");
  put("json", "escapes", "[\"line\\n\\ttab\\\"q\\\\\",-0.5e-3,1e9]");

  // -- bson (via the real writer) ---------------------------------------
  {
    BsonDoc doc;
    doc.emplace_back("str", BsonValue::Str("hello"));
    doc.emplace_back("num", BsonValue::Double(2.5));
    BsonDoc inner;
    inner.emplace_back("k", BsonValue::Str("v"));
    doc.emplace_back("sub", BsonValue::Document(inner));
    std::string wire;
    bson_write_doc(doc, &wire);
    put("bson", "doc", wire);
  }

  // -- amf0 (via the real writer) ---------------------------------------
  {
    std::string wire;
    amf0_write(Amf0Value::Str("connect"), &wire);
    amf0_write(Amf0Value::Number(1), &wire);
    amf0_write(Amf0Value::Object({{"app", Amf0Value::Str("live")},
                                  {"flashVer", Amf0Value::Str("F")}}),
               &wire);
    put("amf0", "connect", wire);
  }

  // -- memcache binary ---------------------------------------------------
  {
    // GET request: magic 0x80, opcode 0x00, key "k".
    std::string get;
    get.push_back(static_cast<char>(0x80));
    get.push_back(0x00);
    get.append("\x00\x01", 2);           // key len 1
    get.push_back(0x00);                   // extras len
    get.push_back(0x00);                   // data type
    get.append("\x00\x00", 2);           // vbucket
    get.append("\x00\x00\x00\x01", 4); // total body 1
    get.append("\x00\x00\x00\x07", 4); // opaque
    get.append(8, '\x00');                // cas
    get.push_back('k');
    put("memcache", "get", get);
  }

  // -- nshead ------------------------------------------------------------
  {
    NsheadHead head;
    head.body_len = 11;
    IOBuf body;
    body.append("hello-nshd!");
    IOBuf frame;
    nshead_pack(head, body, &frame);
    put("nshead", "frame", frame.to_string());
  }

  printf("corpus written under %s\n", g_root.c_str());
  return 0;
}
