// Authentication seam — per-connection credential verify.
//
// Parity: the reference's Authenticator (/root/reference/src/brpc/
// authenticator.h: GenerateCredential on the client's first message,
// VerifyCredential server-side; the "auth fight" in
// input_messenger.cpp:271-289 makes exactly one first message verify per
// connection).  Condensed: the client sends one kAuth-typed frame as the
// FIRST write on a new connection (FIFO write queue = guaranteed
// ordering); the server verifies it once, marks the socket, and rejects
// any request arriving on an unverified socket when an authenticator is
// installed.
#pragma once

#include <string>

#include "base/endpoint.h"

namespace trpc {

class Authenticator {
 public:
  virtual ~Authenticator() = default;
  // Client: fills the credential carried by the connection's first frame.
  // Nonzero fails the connect.
  virtual int generate_credential(std::string* auth_str) const = 0;
  // Server: verifies a peer's credential.  Nonzero rejects (and fails)
  // the connection.
  virtual int verify_credential(const std::string& auth_str,
                                const EndPoint& peer) const = 0;
};

}  // namespace trpc
