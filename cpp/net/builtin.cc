// Builtin introspection services (parity: src/brpc/builtin/ — registered at
// server start, server.cpp:501-604: /status /vars /connections /flags
// /index /version /health /list /protobufs /threads /memory /metrics ...).
#include <stdio.h>
#include <string.h>
#include <unistd.h>

#include <algorithm>
#include <atomic>
#include <cstdlib>
#include <filesystem>
#include <system_error>
#include <vector>
#include <string>

#include "base/flags.h"
#include "base/json.h"
#include "base/logging.h"
#include "base/proc.h"
#include "base/time.h"
#include "fiber/analysis.h"
#include "fiber/fiber.h"
#include "fiber/fid.h"
#include "net/fault.h"
#include "net/http_protocol.h"
#include "net/naming.h"
#include "net/server.h"
#include "net/socket.h"
#include "net/span.h"
#include "stat/capture.h"
#include "stat/heap_profiler.h"
#include "stat/slo.h"
#include "stat/profiler.h"
#include "stat/timeline.h"
#include "stat/tuner.h"
#include "stat/variable.h"

namespace trpc {

std::atomic<int64_t> g_socket_count{0};

namespace {

// Defined at load time so /flags can list and flip it before any /dir
// request arrives (function-local statics in the registry make this
// initialization-order-safe).
Flag* dir_service_flag = Flag::define_bool(
    "enable_dir_service", false,
    "serve the /dir filesystem browser (reference: -enable_dir_service)");

std::string flags_text() {
  std::string out;
  for (Flag* f : Flag::all()) {
    out += f->name() + " = " + f->value_string();
    if (f->value_string() != f->default_value()) {
      out += " (default: " + f->default_value() + ")";
    }
    if (!f->reloadable()) {
      out += " [immutable]";
    }
    out += "  # " + f->description() + "\n";
  }
  return out;
}

}  // namespace

bool builtin_http_dispatch(Server* srv, const HttpRequest& req,
                           const IOBuf& payload, int* status,
                           std::string* body, std::string* content_type) {
  const std::string& path = req.path;
  *status = 200;
  if (path == "/health") {
    *body = "OK\n";
    return true;
  }
  if (path == "/version") {
    *body = "tpu-rpc/0.2.0\n";
    return true;
  }
  if (path == "/vars" || path == "/vars/") {
    const std::string* fmt = req.query("format");
    if (fmt != nullptr && *fmt == "json") {
      Json j = Json::object();
      for (auto& [name, value] : Variable::dump_exposed()) {
        double num = 0;
        if (parse_plain_number(value.c_str(), &num)) {
          j.set(name, Json::number(num));
        } else {
          j.set(name, Json::str(value));
        }
      }
      *body = j.dump();
      *content_type = "application/json";
      return true;
    }
    std::string out;
    for (auto& [name, value] : Variable::dump_exposed()) {
      out += name + " : " + value + "\n";
    }
    *body = std::move(out);
    return true;
  }
  if (path.rfind("/vars/", 0) == 0) {  // single variable
    const std::string want = path.substr(6);
    for (auto& [name, value] : Variable::dump_exposed()) {
      if (name == want) {
        *body = name + " : " + value + "\n";
        return true;
      }
    }
    *status = 404;
    *body = "no such var: " + want + "\n";
    return true;
  }
  if (path == "/status") {
    const std::string* fmt = req.query("format");
    if (fmt != nullptr && *fmt == "json") {
      Json j = Json::object();
      j.set("port", Json::number(srv->port()));
      j.set("uptime_s",
            Json::number((monotonic_time_us() - srv->start_time_us()) /
                         1000000.0));
      j.set("requests_served",
            Json::number(static_cast<double>(srv->requests_served.load())));
      j.set("in_flight", Json::number(srv->in_flight.load()));
      Json methods = Json::array();
      srv->for_each_method([&methods](const std::string& name) {
        methods.push_back(Json::str(name));
      });
      j.set("methods", std::move(methods));
      *body = j.dump();
      *content_type = "application/json";
      return true;
    }
    const int64_t up_us = monotonic_time_us() - srv->start_time_us();
    std::string out = "server port " + std::to_string(srv->port()) +
                      "\nuptime_s " + std::to_string(up_us / 1000000) +
                      "\nrequests_served " +
                      std::to_string(srv->requests_served.load()) +
                      "\nin_flight " + std::to_string(srv->in_flight.load()) +
                      "\nmethods:\n";
    srv->for_each_method(
        [&out](const std::string& name) { out += "  " + name + "\n"; });
    *body = std::move(out);
    return true;
  }
  if (path == "/brpc_metrics" || path == "/metrics") {
    *body = Variable::dump_prometheus();
    return true;
  }
  if (path == "/connections") {
    *body = "live_sockets " +
            std::to_string(g_socket_count.load(std::memory_order_relaxed)) +
            "\n";
    return true;
  }
  // ---- round-2 additions -------------------------------------------------
  if (path == "/flags" || path == "/flags/") {
    // ?format=json serves the introspection dump the tuner and tools
    // consume: {name, type, value, default, reloadable, min?, max?} —
    // bounds from the declared validators (base/flags.h set_int_range),
    // same body as trpc_flags_dump / observe.py flags().
    const std::string* fmt = req.query("format");
    if (fmt != nullptr && *fmt == "json") {
      *body = Flag::dump_json();
      *content_type = "application/json";
      return true;
    }
    *body = flags_text();
    return true;
  }
  if (path.rfind("/flags/", 0) == 0) {
    const std::string name = path.substr(7);
    Flag* f = Flag::find(name);
    if (f == nullptr) {
      *status = 404;
      *body = "no such flag: " + name + "\n";
      return true;
    }
    // ?setvalue=v mutates (reference: /flags/<name>?setvalue=... with a
    // registered validator making the flip safe).
    const std::string* setv = req.query("setvalue");
    if (setv != nullptr) {
      const int rc = f->set_from_string(*setv);
      if (rc == 0) {
        *body = name + " = " + f->value_string() + "\n";
      } else {
        *status = rc == -3 ? 403 : 400;
        *body = (rc == -3 ? std::string("flag is immutable: ")
                          : std::string("bad value for ")) +
                name + "\n";
      }
      return true;
    }
    *body = name + " = " + f->value_string() + "  # " + f->description() +
            "\n";
    return true;
  }
  if (path == "/faults") {
    // Live fault-injection control (net/fault.h).  ?set=<spec> installs
    // the process-wide TRANSPORT schedule (via the fault_schedule flag,
    // so /flags stays in sync); ?server=<spec> installs THIS server's
    // dispatch/accept schedule; ?reset=1 restarts both deterministic
    // sequences (counters + logs; schedules kept).  GET renders state +
    // the injected-fault log.
    fault_register_flag();
    // Validate BOTH specs before applying EITHER: a 400 must mean
    // "nothing changed", never "half the request armed".
    const std::string* setv = req.query("set");
    const std::string* srvv = req.query("server");
    if (setv != nullptr && !FaultActor::global().parse_ok(*setv)) {
      *status = 400;
      *body = "bad fault schedule: " + *setv + "\n";
      return true;
    }
    if (srvv != nullptr && !srv->faults().parse_ok(*srvv)) {
      *status = 400;
      *body = "bad server fault schedule: " + *srvv + "\n";
      return true;
    }
    if (setv != nullptr && Flag::set("fault_schedule", *setv) != 0) {
      *status = 400;
      *body = "bad fault schedule: " + *setv + "\n";
      return true;
    }
    if (srvv != nullptr && srv->SetFaults(*srvv) != 0) {
      *status = 400;
      *body = "bad server fault schedule: " + *srvv + "\n";
      return true;
    }
    const std::string* rst = req.query("reset");
    if (rst != nullptr && *rst != "0") {
      FaultActor::global().reset_counters();
      srv->faults().reset_counters();
    }
    FaultActor& g = FaultActor::global();
    *body = "transport_schedule " + (g.active() ? g.spec() : "(off)") +
            "\ntransport_decisions " + std::to_string(g.decisions()) +
            "\ntransport_injected " + std::to_string(g.injected()) +
            "\nserver_schedule " +
            (srv->faults().active() ? srv->faults().spec() : "(off)") +
            "\nserver_decisions " +
            std::to_string(srv->faults().decisions()) +
            "\nserver_injected " +
            std::to_string(srv->faults().injected()) + "\nlog:\n" +
            g.log_text() + srv->faults().log_text();
    return true;
  }
  if (path == "/rpcz") {
    uint64_t want_trace = 0;
    const std::string* tq = req.query("trace_id");
    if (tq != nullptr) {
      want_trace = strtoull(tq->c_str(), nullptr, 16);
    }
    const std::string* fmt = req.query("format");
    if (fmt != nullptr && *fmt == "json") {
      // Structured spans for tools/trace_stitch.py (and anything else
      // programmatic).  Served even while collection is off: the ring
      // may hold spans from an earlier enabled window, and a stitcher
      // fanning out to N nodes needs a parseable body from each.
      // Capped well below the max ring size: recent_spans deep-copies
      // under the same mutex submit_span takes on every RPC completion,
      // so an unbounded dump would stall live traffic from the very
      // tool meant to debug it.
      size_t limit = 200;
      const std::string* lq = req.query("limit");
      if (lq != nullptr) {
        const long v = atol(lq->c_str());
        if (v > 0 && v <= (1 << 16)) {
          limit = static_cast<size_t>(v);
        }
      }
      *body = rpcz_dump_json(limit, want_trace);
      *content_type = "application/json";
      return true;
    }
    if (!rpcz_enabled()) {
      *body =
          "rpcz is off; enable with /flags/rpcz_enabled?setvalue=true\n";
      return true;
    }
    char line[512];
    std::string out =
        "trace_id         span_id          parent           side   latency_us"
        " err  method (annotations)\n";
    for (const Span& s : recent_spans(200, want_trace)) {
      snprintf(line, sizeof(line),
               "%016llx %016llx %016llx %-6s %10lld %4d  %s",
               static_cast<unsigned long long>(s.trace_id),
               static_cast<unsigned long long>(s.span_id),
               static_cast<unsigned long long>(s.parent_span_id),
               s.server_side ? "server" : "client",
               static_cast<long long>(s.end_us - s.start_us), s.error_code,
               s.method.c_str());
      out += line;
      for (const auto& [ts, text] : s.annotations) {
        snprintf(line, sizeof(line), " [+%lldus %s]",
                 static_cast<long long>(ts - s.start_us), text.c_str());
        out += line;
      }
      out += "\n";
    }
    *body = std::move(out);
    return true;
  }
  if (path == "/hotspots") {
    // CPU profile: SIGPROF sampling for ?seconds=N (default 2, cap 30),
    // rendered as a flat symbolized profile (hotspots_service parity).
    int seconds = 2;
    const std::string* sq = req.query("seconds");
    if (sq != nullptr) {
      seconds = atoi(sq->c_str());
    }
    if (seconds < 1) {
      seconds = 1;
    }
    if (seconds > 30) {
      seconds = 30;
    }
    *body = profile_cpu_for(seconds);
    return true;
  }
  if (path == "/contention") {
    *body = contention_dump();
    return true;
  }
  if (path == "/timeline") {
    // Flight recorder (stat/timeline.h): per-thread rings of fiber/
    // messenger/socket/stripe/QoS events recorded while the reloadable
    // trpc_timeline flag is on.  Served even while recording is off —
    // the rings may hold events from an earlier enabled window, and
    // tools/trace_stitch.py --timeline needs a parseable body from
    // every node it fans out to.  ?limit=N caps events per thread
    // (default 4096, max 65536); ?format=binary streams the packed
    // form observe.py's reader parses.
    size_t limit = 4096;
    const std::string* lq = req.query("limit");
    if (lq != nullptr) {
      const long v = atol(lq->c_str());
      if (v > 0) {
        // Clamp (don't silently fall back to the default): a caller
        // asking for more than the cap gets the cap — same behavior as
        // trpc_timeline_dump.
        limit = std::min(static_cast<size_t>(v),
                         static_cast<size_t>(1 << 16));
      }
    }
    const std::string* fmt = req.query("format");
    if (fmt != nullptr && *fmt == "binary") {
      *body = timeline::dump_binary(limit);
      *content_type = "application/octet-stream";
    } else {
      *body = timeline::dump_json(limit);
      *content_type = "application/json";
    }
    return true;
  }
  if (path == "/capture") {
    // Traffic capture (stat/capture.h): arrival-process summary +
    // per-tenant baseline over the records held while the reloadable
    // trpc_capture flag was on (flip it via
    // /flags/trpc_capture?setvalue=true).  Served even while capture is
    // off — the reservoir may hold an earlier enabled window.
    // ?records=N embeds the newest N records (max 65536);
    // ?dump=<path> writes the binary capture file server-side and
    // answers {"dumped": N}; ?reset=1 clears the window.
    const std::string* dq = req.query("dump");
    if (dq != nullptr && !dq->empty()) {
      const int64_t n = capture::dump_file(*dq);
      if (n < 0) {
        *status = 500;
        *body = "cannot write " + *dq + "\n";
        return true;
      }
      *body = "{\"dumped\": " + std::to_string(n) + "}";
      *content_type = "application/json";
      return true;
    }
    const std::string* rq = req.query("reset");
    if (rq != nullptr && *rq == "1") {
      capture::reset();
      *body = "{\"reset\": true}";
      *content_type = "application/json";
      return true;
    }
    size_t records = 0;
    const std::string* nq = req.query("records");
    if (nq != nullptr) {
      const long v = atol(nq->c_str());
      if (v > 0) {
        records = std::min(static_cast<size_t>(v),
                           static_cast<size_t>(1 << 16));
      }
    }
    *body = capture::dump_json(records);
    *content_type = "application/json";
    return true;
  }
  if (path == "/tuner") {
    // Self-tuning controller (stat/tuner.h): status, live rule table,
    // sampled inputs and the structured decision journal, recorded
    // while the reloadable trpc_tuner flag is on (flip it via
    // /flags/trpc_tuner?setvalue=true).  Served even while tuning is
    // off — the journal may hold decisions from an earlier enabled
    // window.  ?limit=N caps journal entries (default 128, max 512).
    size_t limit = 128;
    const std::string* lq = req.query("limit");
    if (lq != nullptr) {
      const long v = atol(lq->c_str());
      if (v > 0) {
        limit = std::min(static_cast<size_t>(v),
                         static_cast<size_t>(512));
      }
    }
    *body = tuner::dump_json(limit);
    *content_type = "application/json";
    return true;
  }
  if (path == "/slo") {
    // Per-tenant SLO attainment + burn rates (stat/slo.h), recorded
    // while the reloadable trpc_slo flag is on.  Served even with no
    // engine installed — the shape stays machine-readable either way.
    auto slo = srv != nullptr ? srv->slo_engine() : nullptr;
    if (slo != nullptr) {
      *body = slo->dump_json();
    } else {
      *body = std::string("{\"enabled\":") +
              (slo::enabled() ? "true" : "false") +
              ",\"tenants\":[]}";
    }
    *content_type = "application/json";
    return true;
  }
  if (path == "/fleet") {
    // Fleet-wide merged view over the LOCAL naming registry: per-tenant
    // rate/p50/p99/error-rate/budget-remaining/burn-rate from merged
    // digests (octave-wise sample pooling — never averaged node p99s).
    // ?service=<name> selects the service (default "fleet").
    const std::string* sq = req.query("service");
    *body = fleet_dump_json(sq != nullptr ? *sq : "fleet");
    *content_type = "application/json";
    return true;
  }
  if (path == "/analysis") {
    // Runtime invariant checkers (fiber/analysis.h): lock-order
    // inversions + blocking-in-dispatch violations recorded while the
    // reloadable trpc_analysis flag is on (flip it via
    // /flags/trpc_analysis?setvalue=true).
    *body = analysis::report();
    return true;
  }
  if (path == "/pprof/profile") {
    // gperftools-protocol CPU profile: external pprof tooling attaches
    // with `pprof http://host:port/pprof/profile` (pprof_service.h:26).
    int seconds = 10;
    const std::string* sq = req.query("seconds");
    if (sq != nullptr) {
      seconds = atoi(sq->c_str());
    }
    seconds = std::min(std::max(seconds, 1), 60);
    *body = profile_cpu_pprof(seconds);
    if (body->empty()) {
      *status = 503;
      *body = "another profile is already running\n";
      return true;
    }
    *content_type = "application/octet-stream";
    return true;
  }
  if (path == "/pprof/symbol") {
    // GET: capability probe.  POST: "0xA+0xB" → "0xA\tname" lines.
    if (req.verb == "POST") {
      *body = pprof_symbolize_post(payload.to_string());
    } else {
      *body = "num_symbols: 1\n";
    }
    return true;
  }
  if (path == "/pprof/cmdline") {
    FILE* f = fopen("/proc/self/cmdline", "r");
    if (f != nullptr) {
      char buf[4096];
      const size_t n = fread(buf, 1, sizeof(buf), f);
      fclose(f);
      for (size_t i = 0; i < n; ++i) {
        body->push_back(buf[i] == '\0' ? '\n' : buf[i]);
      }
    }
    return true;
  }
  if (path == "/pprof/heap") {
    // First call enables the sampler (no tcmalloc in the image — the
    // runtime's own new/delete sampler, heap_profiler.h); later calls
    // dump the live profile accumulated since.
    if (!heap_profiler_running()) {
      heap_profiler_start();
      *body =
          "heap sampling enabled; re-query after the workload to get the "
          "live profile\n";
      return true;
    }
    *body = heap_profiler_dump();
    return true;
  }
  if (path == "/fibers" || path == "/bthreads") {
    // ?stacks=1 additionally unwinds each parked fiber's suspension
    // point (TaskTracer parity: where a stuck fiber IS, not just its
    // entry symbol).
    const std::string* sv = req.query("stacks");
    *body = fiber_dump_all(200, sv != nullptr && *sv != "0");
    return true;
  }
  if (path == "/threads") {
    *body = "fiber_workers " + std::to_string(fiber_worker_count()) +
            "\nos_threads " + std::to_string(proc_status_kb("Threads:")) +
            "\n";
    // Per-tag worker groups (bthread_tag parity), provisioned tags only.
    for (int t = 1; t < kMaxFiberTags; ++t) {
      const int n = fiber_worker_count_tag(t);
      if (n > 0) {
        *body += "fiber_workers_tag" + std::to_string(t) + " " +
                 std::to_string(n) + "\n";
      }
    }
    return true;
  }
  if (path == "/memory") {
    *body = "vm_rss_kb " + std::to_string(proc_status_kb("VmRSS:")) +
            "\nvm_size_kb " + std::to_string(proc_status_kb("VmSize:")) +
            "\nvm_hwm_kb " + std::to_string(proc_status_kb("VmHWM:")) + "\n";
    return true;
  }
  if (path == "/list" || path == "/protobufs") {
    // Method inventory (the pb-less analogue of /protobufs).
    std::string out;
    srv->for_each_method(
        [&out](const std::string& name) { out += name + "\n"; });
    *body = std::move(out);
    return true;
  }
  if (path == "/sockets") {
    *body = Socket::DumpAll(500);
    // ?hot=1 appends per-socket hot-path state (queued-write flag,
    // writer role, pending events) — the wedge-forensics view.
    const std::string* hot = req.query("hot");
    if (hot != nullptr && *hot == "1") {
      *body += "\n" + Socket::DumpHotState();
    }
    return true;
  }
  if (path == "/ids") {
    *body = fid_dump_all(500);
    return true;
  }
  if (path == "/vlog") {
    // The reference's /vlog lists VLOG sites; the analogue here is the
    // runtime log threshold, flippable like /flags (?setlevel=0..4).
    static const char* kNames[] = {"debug", "info", "warning", "error",
                                   "fatal"};
    const std::string* lv = req.query("setlevel");
    if (lv != nullptr) {
      char* end = nullptr;
      const long v = strtol(lv->c_str(), &end, 10);
      if (end == lv->c_str() || *end != '\0' || v < 0 || v > 4) {
        *status = 400;
        *body = "setlevel must be 0(debug)..4(fatal)\n";
        return true;
      }
      log_min_level().store(static_cast<int>(v),
                            std::memory_order_relaxed);
    }
    const int cur = log_min_level().load(std::memory_order_relaxed);
    *body = "min_log_level " + std::to_string(cur) + " (" +
            kNames[cur < 0 || cur > 4 ? 1 : cur] + ")\n";
    return true;
  }
  if (path == "/dir" || path.rfind("/dir/", 0) == 0) {
    // Filesystem browser.  Opt-in like the reference (DirService only
    // registers behind -enable_dir_service, server.cpp:119, default
    // false) because it serves ANY path; flip live via
    // /flags/enable_dir_service?setvalue=true.
    if (!dir_service_flag->bool_value()) {
      *status = 403;
      *body =
          "disabled; enable with /flags/enable_dir_service?setvalue=true\n";
      return true;
    }
    std::string target =
        path.size() > 4 ? path.substr(4) : std::string("/");
    std::error_code ec;
    if (std::filesystem::is_directory(target, ec)) {
      std::string out;
      std::vector<std::string> rows;
      for (const auto& entry :
           std::filesystem::directory_iterator(target, ec)) {
        std::string row = entry.path().filename().string();
        if (entry.is_directory(ec)) {
          row += "/";
        } else {
          std::error_code size_ec;
          const auto sz = entry.file_size(size_ec);
          // Dangling symlinks / proc pseudo-files have no stat-able
          // size; print "?" instead of uintmax_t(-1).
          row += size_ec ? "  ?" : "  " + std::to_string(sz);
        }
        rows.push_back(std::move(row));
      }
      std::sort(rows.begin(), rows.end());
      for (const auto& r : rows) {
        out += r + "\n";
      }
      *body = std::move(out);
    } else if (std::filesystem::is_regular_file(target, ec)) {
      FILE* f = fopen(target.c_str(), "rb");
      if (f == nullptr) {
        *status = 403;
        *body = "cannot open " + target + "\n";
        return true;
      }
      char buf[8192];
      size_t n;
      constexpr size_t kMaxFile = 4u << 20;
      while ((n = fread(buf, 1, sizeof(buf), f)) > 0) {
        body->append(buf, n);
        if (body->size() > kMaxFile) {
          body->resize(kMaxFile);
          body->append("\n... (truncated at 4MB)\n");
          break;
        }
      }
      fclose(f);
      *content_type = "application/octet-stream";
    } else {
      *status = 404;
      *body = "no such path: " + target + "\n";
    }
    return true;
  }
  if (path == "/index" || path == "/") {
    *body =
        "/health\n/version\n/status\n/vars\n/vars/<name>\n/brpc_metrics\n"
        "/connections\n/flags[?format=json]\n/flags/<name>[?setvalue=v]\n"
        "/threads\n"
        "/memory\n/list\n/protobufs\n/index\n"
        "/rpcz[?trace_id=hex&format=json&limit=N]\n"
        "/timeline[?format=binary&limit=N]\n"
        "/capture[?records=N&dump=path&reset=1]\n"
        "/tuner[?limit=N]\n"
        "/slo\n/fleet[?service=name]\n"
        "/faults[?set=spec&server=spec&reset=1]\n"
        "/hotspots[?seconds=N]\n/contention\n/analysis\n/fibers\n"
        "/sockets\n/ids\n"
        "/vlog[?setlevel=N]\n/dir/<path>\n"
        "/pprof/profile[?seconds=N]\n/pprof/symbol\n/pprof/cmdline\n"
        "/pprof/heap\n";
    return true;
  }
  (void)content_type;
  return false;
}

}  // namespace trpc
