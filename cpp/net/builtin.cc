// Builtin introspection services (parity: src/brpc/builtin/ — /vars,
// /status, /health, /version, /connections registered at server start,
// server.cpp:501-604).
#include <unistd.h>

#include <atomic>
#include <string>

#include "base/time.h"
#include "net/http_protocol.h"
#include "net/server.h"
#include "stat/variable.h"

namespace trpc {

std::atomic<int64_t> g_socket_count{0};

bool builtin_http_dispatch(Server* srv, const std::string& path,
                           std::string* body, std::string* content_type) {
  if (path == "/health") {
    *body = "OK\n";
    return true;
  }
  if (path == "/version") {
    *body = "tpu-rpc/0.1.0\n";
    return true;
  }
  if (path == "/vars" || path == "/vars/") {
    std::string out;
    for (auto& [name, value] : Variable::dump_exposed()) {
      out += name + " : " + value + "\n";
    }
    *body = std::move(out);
    return true;
  }
  if (path == "/status") {
    const int64_t up_us = monotonic_time_us() - srv->start_time_us();
    std::string out = "server 127.0.0.1:" + std::to_string(srv->port()) +
                      "\nuptime_s " + std::to_string(up_us / 1000000) +
                      "\nrequests_served " +
                      std::to_string(srv->requests_served.load()) +
                      "\nmethods:\n";
    srv->for_each_method(
        [&out](const std::string& name) { out += "  " + name + "\n"; });
    *body = std::move(out);
    return true;
  }
  if (path == "/brpc_metrics" || path == "/metrics") {
    *body = Variable::dump_prometheus();
    return true;
  }
  if (path == "/connections") {
    *body = "live_sockets " +
            std::to_string(g_socket_count.load(std::memory_order_relaxed)) +
            "\n";
    return true;
  }
  return false;
}

}  // namespace trpc
