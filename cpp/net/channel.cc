#include "net/channel.h"

#include <functional>

#include <errno.h>

#include "base/compress.h"
#include "base/logging.h"
#include "base/time.h"
#include "fiber/fiber.h"
#include "fiber/timer.h"
#include "net/h2_client.h"
#include "net/messenger.h"
#include "net/deadline.h"
#include "net/progressive.h"
#include "net/protocol.h"
#include "net/ici_transport.h"
#include "net/shm_transport.h"
#include "net/socket_map.h"
#include "net/span.h"
#include "net/stream.h"
#include "net/rma.h"
#include "net/stripe.h"
#include "net/tls.h"

namespace trpc {

// Completes a call that is currently LOCKED via its fid: records latency,
// cancels the timeout timer, destroys the id (waking sync joiners) and runs
// the async done.  Mirrors Controller::OnVersionedRPCReturned ordering
// (controller.cpp:611): state is finalized before anyone can observe it.
// Shared with the h2 client response path (h2_client.cc).
void complete_locked_call(fid_t cid, Controller* cntl) {
  cntl->set_latency_us(monotonic_time_us() - cntl->call().start_us);
  // Progressive reads get exactly one terminal callback, success or not,
  // before the caller can observe completion.
  if (cntl->call().preader != nullptr) {
    ProgressiveReader* r = cntl->call().preader;
    cntl->call().preader = nullptr;
    r->on_done(cntl->error_code(), cntl->error_text());
  }
  // h2 calls completing WITHOUT a response (timeout / local failure) must
  // drop their client-side stream state, or dead streams accumulate on
  // the multiplexed connection for its whole lifetime.
  if (cntl->call().h2_stream != 0) {
    if (cntl->Failed()) {
      h2_client_cancel(cntl->call().socket_id, cntl->call().h2_stream);
    }
    cntl->call().h2_stream = 0;
  }
  // Same for tstd stream offers: a call that failed before any
  // acceptance arrived leaves its streams unestablished, and a parked
  // StreamWrite would otherwise re-arm its establishment wait forever.
  if (cntl->Failed() && cntl->call().offered_stream != 0) {
    StreamClose(cntl->call().offered_stream);
    cntl->call().offered_stream = 0;
    for (uint64_t sid : cntl->call().extra_offered) {
      StreamClose(sid);
    }
    cntl->call().extra_offered.clear();
  }
  // Connection-type epilogue: pooled connections go back to the shared
  // pool (socket.h:611-627 parity), short ones close now.
  const SocketId conn = cntl->call().socket_id;
  if (conn != 0) {
    const auto ct = static_cast<ConnectionType>(cntl->call().conn_type);
    if (ct == ConnectionType::kPooled) {
      SocketRef s(Socket::Address(conn));
      if (s) {
        if (cntl->Failed()) {
          // A failed/timed-out call may still have its response in
          // flight: pooling the connection would queue the next caller
          // behind stale bytes (the reference drops pooled sockets on
          // error for the same reason).
          s->SetFailed(ESHUTDOWN);
        } else {
          SocketMap::instance()->give_back(
              s->remote(),
              static_cast<const Authenticator*>(cntl->call().conn_auth),
              conn);
        }
      }
    } else if (ct == ConnectionType::kShort) {
      SocketRef s(Socket::Address(conn));
      if (s) {
        s->SetFailed(ESHUTDOWN);
      }
    }
  }
  auto* span = static_cast<Span*>(cntl->call().span);
  if (span != nullptr) {
    cntl->call().span = nullptr;
    if (cntl->call().response != nullptr) {
      span->response_bytes = cntl->call().response->size();
    }
    submit_span(span, cntl->error_code());
  }
  // Landing registration must die BEFORE the fid can recycle: a late
  // stripe chunk for this cid must never memcpy into a buffer the caller
  // has already reclaimed (stripe_unregister_landing drains in-flight
  // landers).  Cheap no-op for the unregistered (non-batch) hot path.
  if (cntl->call().land_registered) {
    stripe_unregister_landing(cid);
    cntl->call().land_registered = false;
  }
  cntl->call().land_buf = nullptr;
  cntl->call().land_cap = 0;
  const uint64_t timer = cntl->call().timeout_timer;
  const bool inline_safe = cntl->done_inline_safe();
  Closure done = std::move(cntl->call().done);
  fid_unlock_and_destroy(cid);
  if (timer != 0) {
    TimerThread::instance()->unschedule(timer);
  }
  if (done) {
    // A non-empty done is the USER's async completion (sync callers join
    // the fid instead).  When this completion is running inline on a
    // connection's dispatch fiber (batched-dispatch fast path), arbitrary
    // user code must not park it — everything behind it on the connection
    // would stall — so the closure gets its own fiber there.  Dones the
    // framework marked inline-safe (batch-pipeline completions: bounded,
    // park-free) skip the spawn and run here directly.
    if (messenger_in_inline_dispatch() && !inline_safe) {
      auto* heap_done = new Closure(std::move(done));
      if (fiber_start(
              nullptr,
              [](void* p) {
                auto* d = static_cast<Closure*>(p);
                (*d)();
                delete d;
              },
              heap_done) != 0) {
        (*heap_done)();  // pool exhausted: inline beats dropping
        delete heap_done;
      }
    } else {
      done();
    }
  }
}

namespace {

int on_call_error(fid_t cid, void* data, int code) {
  Controller* cntl = static_cast<Controller*>(data);
  cntl->SetFailed(code,
                  code == ETIMEDOUT   ? "rpc timeout"
                  : code == ECANCELED ? "rpc canceled by caller"
                  : code == kEDeadlineExpired
                      ? "end-to-end deadline expired"
                      : "rpc failed");
  complete_locked_call(cid, cntl);
  return 0;
}

void timeout_fiber(void* arg) {
  fid_error(reinterpret_cast<fid_t>(arg), ETIMEDOUT);
}

// Runs on the TimerThread: must stay cheap (timer.h contract).  The actual
// completion — fid locking and the user's done() — moves to a fiber.
void timeout_cb(void* arg) {
  fiber_start(nullptr, timeout_fiber, arg, 0);
}

// Deadline-bound variant (net/deadline.h): when the AMBIENT end-to-end
// budget is strictly tighter than the call's own timeout, its expiry is
// budget exhaustion, not a per-hop timeout — surfaced as the typed
// kEDeadlineExpired so retry layers stop the chain instead of re-burning
// a budget that is equally dead everywhere.
void deadline_fiber(void* arg) {
  fid_error(reinterpret_cast<fid_t>(arg), kEDeadlineExpired);
}

void deadline_cb(void* arg) {
  fiber_start(nullptr, deadline_fiber, arg, 0);
}

}  // namespace

// Response path installed into the tstd protocol (messenger dispatch).
void tstd_process_response(InputMessage&& msg) {
  const fid_t cid = msg.meta.correlation_id;
  void* data = nullptr;
  if (fid_lock(cid, &data) != 0) {
    return;  // stale response (timed out / retried away): harmless
  }
  Controller* cntl = static_cast<Controller*>(data);
  if (cntl->call().offered_stream != 0) {
    const auto& offered = cntl->call().extra_offered;
    const auto& accepted = msg.meta.extra_streams;
    if (msg.meta.stream_id != 0) {
      // Server accepted: bind ids + adopt its advertised window.
      stream_on_accept_response(cntl->call().offered_stream,
                                msg.meta.stream_id,
                                cntl->call().socket_id,
                                msg.meta.ack_bytes);
      // Batch acceptances align by index with our extra offers.
      for (size_t i = 0; i < offered.size() && i < accepted.size(); ++i) {
        stream_on_accept_response(offered[i], accepted[i].first,
                                  cntl->call().socket_id,
                                  accepted[i].second);
      }
      // Extras the server did not accept are dead.
      for (size_t i = accepted.size(); i < offered.size(); ++i) {
        StreamClose(offered[i]);
      }
    } else {
      // The handler never accepted (plain response / older peer): a
      // hanging unestablished stream would park writers forever —
      // close the primary and EVERY extra, whatever a (buggy/hostile)
      // peer put in the extra_streams tail of a no-acceptance response.
      StreamClose(cntl->call().offered_stream);
      for (uint64_t sid : offered) {
        StreamClose(sid);
      }
    }
    cntl->call().offered_stream = 0;
    cntl->call().extra_offered.clear();
  }
  if (msg.meta.error_code != 0) {
    cntl->SetFailed(msg.meta.error_code, msg.meta.error_text);
  } else {
    IOBuf payload = std::move(msg.payload);
    if (msg.meta.attachment_size > 0 &&
        msg.meta.attachment_size <= payload.size()) {
      IOBuf body;
      payload.cutn(&body, payload.size() - msg.meta.attachment_size);
      cntl->response_attachment() = std::move(payload);
      payload = std::move(body);
    }
    if (msg.meta.compress_type != 0) {
      const Compressor* c = find_compressor(
          static_cast<CompressType>(msg.meta.compress_type));
      IOBuf plain;
      if (c == nullptr ||
          !c->decompress(payload, &plain, 1ull << 30)) {
        cntl->SetFailed(EBADMSG, "response decompression failed");
        complete_locked_call(cid, cntl);
        return;
      }
      payload = std::move(plain);
    }
    if (cntl->call().response != nullptr) {
      *cntl->call().response = std::move(payload);
    }
  }
  complete_locked_call(cid, cntl);
}

Channel::~Channel() {
  SocketRef s(Socket::Address(sock_));
  if (s) {
    s->SetFailed(ESHUTDOWN);
  }
}

int Channel::Init(const std::string& addr, const Options* opts) {
  fiber_init(0);
  tstd_protocol();
  if (opts != nullptr) {
    opts_ = *opts;
  }
  if (opts_.protocol == "tstd") {
    proto_ = 0;
  } else if (opts_.protocol == "h2") {
    proto_ = 1;
  } else if (opts_.protocol == "grpc") {
    proto_ = 2;
  } else {
    return -1;  // unknown protocol must not silently mean tstd
  }
  ConnectionType ct;
  if (!parse_connection_type(opts_.connection_type, &ct)) {
    return -1;  // typo'd type must not silently mean "single"
  }
  if ((opts_.use_shm || opts_.use_ici) && ct != ConnectionType::kSingle) {
    return -1;  // shm/ici rings are inherently single-connection
  }
  if (opts_.use_shm && opts_.use_ici) {
    return -1;
  }
  if (opts_.use_tls &&
      (ct != ConnectionType::kSingle || opts_.use_shm || opts_.use_ici ||
       !tls_available())) {
    return -1;  // TLS rides the single TCP connection
  }
  if (!opts_.use_tls &&
      (!opts_.tls_cert.empty() || !opts_.tls_ca.empty())) {
    return -1;  // cert/CA options without use_tls must not silently no-op
  }
  if (proto_ != 0) {
    if (ct != ConnectionType::kSingle || opts_.use_shm || opts_.use_ici) {
      return -1;  // h2 multiplexes one connection by design
    }
    h2_client_protocol_index();  // register before any response arrives
  }
  conn_type_ = static_cast<uint8_t>(ct);
  sni_host_ = addr.rfind("unix:", 0) == 0 ? ""
                                          : addr.substr(0, addr.rfind(':'));
  return hostname2endpoint(addr.c_str(), &ep_);
}

std::string Channel::transport_name() {
  SocketRef s(Socket::Address(sock_));
  return s ? s->transport()->name() : "";
}

std::string Channel::alpn() {
  SocketRef s(Socket::Address(sock_));
  return s ? tls_alpn_selected(s.get()) : "";
}

// First write on a fresh connection: the credential frame (FIFO write
// queue guarantees it precedes every request).
static int send_credential(SocketId sid, const Authenticator* auth) {
  if (auth == nullptr) {
    return 0;
  }
  std::string cred;
  if (auth->generate_credential(&cred) != 0) {
    return -1;
  }
  RpcMeta meta;
  meta.type = RpcMeta::kAuth;
  IOBuf payload;
  payload.append(cred);
  IOBuf frame;
  tstd_pack(&frame, meta, payload);
  SocketRef s(Socket::Address(sid));
  return s && s->Write(std::move(frame)) == 0 ? 0 : -1;
}

// Ring-transport bootstrap (rdma_handshake-over-TCP parity, shared by the
// shm and ICI paths): ship the freshly-minted segment name over a
// throwaway TCP channel — which carries the channel's authenticator, so
// auth-gated servers accept the handshake — then install the fd-less ring
// socket via `attach` and send the credential frame over the rings (the
// ring connection is a fresh connection to an auth-checking server).
// Returns 0 with *sock live on success.
static int ring_bootstrap(const EndPoint& ep, const Channel::Options& copts,
                          const char* method, const std::string& seg_name,
                          const std::function<int(SocketId*)>& attach,
                          SocketId* sock) {
  Channel tcp;
  Channel::Options topts;
  topts.timeout_ms = copts.timeout_ms;
  topts.auth = copts.auth;
  if (tcp.Init(endpoint2str(ep), &topts) != 0) {
    return -1;
  }
  Controller cntl;
  cntl.set_timeout_ms(copts.timeout_ms);
  IOBuf req, resp;
  req.append(seg_name);
  tcp.CallMethod(method, req, &resp, &cntl);
  if (cntl.Failed() || !resp.equals("ok", 2) || attach(sock) != 0) {
    return -1;
  }
  if (send_credential(*sock, copts.auth) != 0) {
    SocketRef dead(Socket::Address(*sock));
    if (dead) {
      dead->SetFailed(EACCES);
    }
    return -1;
  }
  return 0;
}

int Channel::ensure_socket(SocketId* out) {
  LockGuard<FiberMutex> g(sock_mu_);
  Socket* s = Socket::Address(sock_);
  if (s != nullptr) {
    if (!s->Failed()) {
      *out = sock_;
      s->Dereference();
      return 0;
    }
    s->Dereference();
  }
  if (opts_.use_ici) {
    std::string name;
    auto conn = ici_conn_create(&name);
    if (conn != nullptr &&
        ring_bootstrap(ep_, opts_, kIciConnectMethod, name,
                       [&conn](SocketId* sid) {
                         return ici_socket_create(
                             conn, &messenger_on_readable, nullptr, sid);
                       },
                       &sock_) == 0) {
      *out = sock_;
      return 0;
    }
    LOG(Warning) << "ici handshake with " << endpoint2str(ep_)
                 << " failed; falling back to tcp";
  }
  if (opts_.use_shm) {
    std::string name;
    auto conn = shm_conn_create(&name);
    if (conn != nullptr &&
        ring_bootstrap(ep_, opts_, kShmConnectMethod, name,
                       [&conn](SocketId* sid) {
                         return shm_socket_create(
                             conn, &messenger_on_readable, nullptr, sid);
                       },
                       &sock_) == 0) {
      *out = sock_;
      return 0;
    }
    LOG(Warning) << "shm handshake with " << endpoint2str(ep_)
                 << " failed; falling back to tcp";
  }
  Socket::Options sopts;
  sopts.fd = -1;  // lazy connect in the write fiber
  sopts.remote = ep_;
  sopts.on_readable = &messenger_on_readable;
  if (opts_.use_tls) {
    std::string err;
    void* ctx = opts_.tls_cert.empty() && opts_.tls_ca.empty()
                    ? tls_client_ctx(&err)
                    : tls_client_ctx_mtls(opts_.tls_cert, opts_.tls_key,
                                          opts_.tls_ca, &err);
    if (ctx == nullptr) {
      LOG(Warning) << "tls client init failed: " << err;
      return -1;
    }
    sopts.transport = tls_transport();
    // h2/grpc channels advertise ALPN h2 (gRPC servers commonly require
    // it); tstd is not an IANA protocol, so it offers no ALPN.  SNI
    // carries the Init hostname (IP literals filtered by the factory).
    sopts.transport_ctx_holder =
        tls_conn_client(ctx, proto_ != 0 ? "\x02h2" : "", sni_host_);
  }
  if (Socket::Create(sopts, &sock_) != 0) {
    return -1;
  }
  if (proto_ != 0) {
    // h2/grpc: pin + install connection state while still single-threaded
    // (sock_mu_ held); the credential rides the "authorization" header per
    // request (h2_client_issue), not a tstd kAuth frame.
    h2_client_bind(sock_);
    *out = sock_;
    return 0;
  }
  if (send_credential(sock_, opts_.auth) != 0) {
    SocketRef dead(Socket::Address(sock_));
    if (dead) {
      dead->SetFailed(EACCES);
    }
    return -1;
  }
  *out = sock_;
  return 0;
}

void Channel::CallMethod(const std::string& method, const IOBuf& request,
                         IOBuf* response, Controller* cntl, Closure done) {
  cntl->set_method(method);
  cntl->call().response = response;
  cntl->call().done = std::move(done);
  cntl->call().start_us = monotonic_time_us();
  // Controller reuse: a previous call's connection ownership must not
  // leak into this call's early-failure paths.
  cntl->call().socket_id = 0;
  cntl->call().conn_type = 0;
  cntl->call().conn_auth = nullptr;
  cntl->call().h2_stream = 0;
  const bool sync = !cntl->call().done;
  // rpcz: client span; a handler fiber's ambient server span becomes the
  // parent (channel.cpp:506-527 parity).
  Span* span = nullptr;
  if (rpcz_enabled()) {
    span = start_span(/*server_side=*/false, method);
    span->request_bytes = request.size();
    cntl->call().span = span;
  }

  fid_t cid = 0;
  if (fid_create(&cid, cntl, on_call_error) != 0) {
    cntl->SetFailed(ENOMEM, "out of call ids");
    if (span != nullptr) {
      cntl->call().span = nullptr;  // never reaches complete_locked_call
      submit_span(span, ENOMEM);
    }
    if (!sync && cntl->call().done) {
      cntl->call().done();
    }
    return;
  }
  cntl->call().cid = cid;
  // Hold the call lock through setup so a racing response or an eager
  // timeout cannot complete (and free) the call mid-construction —
  // responses/timeouts queue on the fid until we unlock (channel.cpp:481
  // parity).
  CHECK(fid_lock(cid, nullptr) == 0);

  // Deadline plane (net/deadline.h): the effective budget is
  // min(caller/channel timeout, the ambient deadline of the request this
  // fiber is serving) — a proxied call therefore re-stamps
  // budget-minus-elapsed at every hop.  The serving request's cancel
  // scope learns this call's id so a cascading cancel reaches it.
  int64_t deadline_abs = 0;
  bool ambient_bound = false;  // the ambient budget is the tight constraint
  const int64_t eff_timeout_ms = cntl->timeout_ms_or(opts_.timeout_ms);
  if (deadline_wire_enabled()) {
    if (eff_timeout_ms > 0) {
      deadline_abs = cntl->call().start_us + eff_timeout_ms * 1000;
    }
    const int64_t amb = ambient_deadline();
    if (amb != 0 && (deadline_abs == 0 || amb < deadline_abs)) {
      deadline_abs = amb;
      ambient_bound = true;
    }
  }
  CancelScope* parent_scope = ambient_cancel();
  if (parent_scope != nullptr) {
    parent_scope->add_call(cid);
  }
  if (deadline_abs != 0 && monotonic_time_us() >= deadline_abs) {
    // Budget already exhausted: fail fast without touching the wire —
    // dispatching a request nobody can wait for is exactly the wasted
    // work the plane exists to shed.
    deadline_vars().client_expired_total << 1;
    fid_unlock(cid);
    fid_error(cid, kEDeadlineExpired);
    if (sync) {
      fid_join(cid);
    }
    return;
  }

  SocketId sid = 0;
  const auto ct = static_cast<ConnectionType>(conn_type_);
  if (proto_ != 0 &&
      (cntl->call().offered_stream != 0 ||
       cntl->request_compress_type() != 0)) {
    // Streaming offers and tstd-negotiated compression have no h2
    // carrier; failing loudly beats silently dropping the option.
    fid_unlock(cid);
    fid_error(cid, EINVAL);
    if (sync) {
      fid_join(cid);
    }
    return;
  }
  if (cntl->call().offered_stream != 0 && ct != ConnectionType::kSingle) {
    // A stream outlives the call and pins its connection; pooled/short
    // connections are per-call by definition.
    fid_unlock(cid);
    fid_error(cid, EINVAL);
    if (sync) {
      fid_join(cid);
    }
    return;
  }
  int sock_rc;
  switch (ct) {
    case ConnectionType::kPooled: {
      bool fresh = false;
      sock_rc =
          SocketMap::instance()->take_pooled(ep_, opts_.auth, &sid, &fresh);
      if (sock_rc == 0 && fresh) {
        sock_rc = send_credential(sid, opts_.auth);
      }
      break;
    }
    case ConnectionType::kShort:
      sock_rc = SocketMap::instance()->create_short(ep_, &sid);
      if (sock_rc == 0) {
        sock_rc = send_credential(sid, opts_.auth);
      }
      break;
    case ConnectionType::kSingle:
    default:
      sock_rc = ensure_socket(&sid);
      break;
  }
  if (sock_rc != 0) {
    if (sid != 0) {
      // The socket exists but the credential could not be sent: close it
      // rather than leaking a connected fd per failed call.
      SocketRef dead(Socket::Address(sid));
      if (dead) {
        dead->SetFailed(EACCES);
      }
    }
    fid_unlock(cid);
    fid_error(cid, ECONNREFUSED);
    if (sync) {
      fid_join(cid);
    }
    return;
  }
  cntl->call().socket_id = sid;
  cntl->call().conn_type = static_cast<uint8_t>(ct);
  cntl->call().conn_auth = opts_.auth;

  // Local timer at the TIGHTER of the caller's timeout and the ambient
  // deadline: an explicit-0 timeout still dies when the end-to-end
  // budget does.
  int64_t timer_at =
      eff_timeout_ms > 0 ? cntl->call().start_us + eff_timeout_ms * 1000 : 0;
  if (deadline_abs != 0 && (timer_at == 0 || deadline_abs < timer_at)) {
    timer_at = deadline_abs;
  }
  if (timer_at > 0) {
    cntl->call().timeout_timer = TimerThread::instance()->schedule(
        timer_at, ambient_bound ? deadline_cb : timeout_cb,
        reinterpret_cast<void*>(cid));
  }

  if (proto_ != 0) {  // h2 / grpc path: PackH2Request-equivalent
    std::string auth_hdr;
    if (opts_.auth != nullptr &&
        opts_.auth->generate_credential(&auth_hdr) != 0) {
      fid_unlock(cid);
      fid_error(cid, EACCES);
      if (sync) {
        fid_join(cid);
      }
      return;
    }
    IOBuf body = request;  // zero-copy share
    if (!cntl->request_attachment().empty()) {
      body.append(cntl->request_attachment());  // h2 has no split concept
    }
    if (span != nullptr) {
      span_annotate(span, "request packed");
    }
    uint32_t stream_id = 0;
    const bool ok = h2_client_issue(sid, cid, method, body, proto_ == 2,
                                    endpoint2str(ep_), auth_hdr,
                                    &stream_id,
                                    cntl->call().preader) == 0;
    cntl->call().h2_stream = stream_id;
    fid_unlock(cid);
    if (!ok) {
      fid_error(cid, ECONNRESET);
    }
    if (sync) {
      fid_join(cid);
    }
    return;
  }

  RpcMeta meta;
  meta.type = RpcMeta::kRequest;
  meta.correlation_id = cid;
  meta.method = method;
  // QoS tag (net/qos.h): the caller's explicit tag wins, else the
  // channel default; untagged stays absent from the wire entirely.
  if (cntl->qos_set()) {
    meta.qos_priority = cntl->qos_priority();
    meta.qos_tenant = cntl->qos_tenant();
  } else {
    meta.qos_priority = opts_.qos_priority;
    meta.qos_tenant = opts_.qos_tenant;
  }
  meta.stream_id = cntl->call().offered_stream;  // stream offer piggyback
  if (meta.stream_id != 0) {
    meta.ack_bytes = stream_recv_window(meta.stream_id);  // advertise window
    for (uint64_t sid : cntl->call().extra_offered) {  // batch offers
      meta.extra_streams.emplace_back(sid, stream_recv_window(sid));
    }
  }
  if (span != nullptr) {
    meta.trace_id = span->trace_id;   // server links as our child
    meta.span_id = span->span_id;
    span_annotate(span, "request packed");
  }
  if (deadline_abs != 0) {
    // Wire stamp (tail-group 7): the REMAINING budget at send — never 0
    // here (0 means unset); a budget that just hit zero stamps 1µs and
    // sheds at the server instead.
    const int64_t rem = deadline_abs - monotonic_time_us();
    meta.deadline_us = static_cast<uint64_t>(rem > 0 ? rem : 1);
    deadline_vars().stamped_total << 1;
  }
  IOBuf body = request;  // zero-copy share
  if (cntl->request_compress_type() != 0) {
    const Compressor* c = find_compressor(
        static_cast<CompressType>(cntl->request_compress_type()));
    IOBuf squeezed;
    if (c == nullptr || !c->compress(body, &squeezed)) {
      fid_unlock(cid);
      fid_error(cid, EINVAL);
      if (sync) {
        fid_join(cid);
      }
      return;
    }
    body = std::move(squeezed);
    meta.compress_type = cntl->request_compress_type();
  }
  if (!cntl->request_attachment().empty()) {
    meta.attachment_size =
        static_cast<uint32_t>(cntl->request_attachment().size());
    body.append(cntl->request_attachment());
  }
  if (cntl->checksum_enabled()) {
    meta.has_checksum = true;  // striped sends CRC per chunk (stripe.cc)
  }
  // Striped response landing (batch plane): register the caller's buffer
  // under the cid BEFORE the request can reach the server, so even a
  // chunk that beats the head frame lands in place.  Only worth it when
  // the buffer could hold a striped (above-threshold) response.
  if (cntl->call().land_buf != nullptr &&
      stripe_eligible(cntl->call().land_cap)) {
    stripe_register_landing(cid, cntl->call().land_buf,
                            cntl->call().land_cap);
    cntl->call().land_registered = true;
    // One-sided advertisement (net/rma.h): when the landing buffer is
    // itself an exportable rma region and this connection has an rma
    // session, the request's meta names it — the server then PUTS the
    // response straight into the caller's buffer.
    rma_advertise_response(sid, cid, &meta);
  }

  bool write_ok;
  // Long-transfer loops poll the token between chunks: a cancelled
  // caller (or an expired budget) stops writing within one chunk.
  const DeadlineToken dtok{parent_scope, deadline_abs};
  const int rma_rc = rma_try_send(sid, &meta, &body, 0, 0, 0, dtok);
  if (rma_rc == 0) {
    // Body written one-sided into the peer's window; the control frame
    // is queued.  Nothing rides the stripe layer.
    write_ok = true;
  } else if (rma_rc < 0) {
    write_ok = false;
  } else if (stripe_should(sid, meta.stream_id, body.size())) {
    // Multi-rail large-message path (net/stripe.h): cut the body into
    // chunk frames issued concurrently.  Pooled channels spread chunks
    // over extra pooled connections to the same endpoint (each rail has
    // its own kernel pipe + read fiber on the far side); single/shm
    // channels stripe over the one connection, which still pipelines the
    // receiver's landing memcpys against the wire.
    std::vector<SocketId> rails{sid};
    std::vector<SocketId> extra;
    if (ct == ConnectionType::kPooled) {
      const int want = stripe_rails();
      for (int i = 1; i < want; ++i) {
        SocketId rid = 0;
        bool fresh = false;
        if (SocketMap::instance()->take_pooled(ep_, opts_.auth, &rid,
                                               &fresh) != 0) {
          break;
        }
        if (fresh && send_credential(rid, opts_.auth) != 0) {
          SocketRef dead(Socket::Address(rid));
          if (dead) {
            dead->SetFailed(EACCES);
          }
          break;
        }
        extra.push_back(rid);
        rails.push_back(rid);
      }
    }
    write_ok = stripe_send(sid, rails, std::move(meta), std::move(body),
                           stripe_make_id(), dtok) == 0;
    // Rails go straight back to the pool: their chunk frames are queued
    // FIFO on each socket, so a later borrower's frames follow ours.
    for (SocketId rid : extra) {
      SocketMap::instance()->give_back(ep_, opts_.auth, rid);
    }
  } else {
    write_ok =
        stripe_frame_send(sid, std::move(meta), std::move(body)) == 0;
  }
  fid_unlock(cid);
  if (!write_ok) {
    // A send the DEADLINE TOKEN aborted mid-transfer is not a transport
    // fault: surface the cancel/budget code so retry layers stop the
    // chain and no healthy node gets quarantined for the caller's clock.
    if (dtok.aborted()) {
      fid_error(cid, parent_scope != nullptr && parent_scope->cancelled()
                         ? ECANCELED
                         : kEDeadlineExpired);
    } else {
      fid_error(cid, ECONNRESET);
    }
  }
  if (sync) {
    fid_join(cid);
  }
}

}  // namespace trpc
