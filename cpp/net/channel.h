// Channel — the client stub.
//
// Parity: brpc::Channel (/root/reference/src/brpc/channel.cpp:446-630
// CallMethod: correlation-id lock, timeout timer, IssueRPC write, sync
// Join) condensed to the single-server pooled-connection case; combo
// channels and LB compose above this (SURVEY.md §2.4).
#pragma once

#include <mutex>
#include <string>

#include "base/endpoint.h"
#include "net/auth.h"
#include "fiber/sync.h"
#include "net/controller.h"
#include "net/socket.h"

namespace trpc {

// Finalizes a call whose fid is currently LOCKED by the caller: records
// latency, runs the connection-type epilogue, cancels the timeout timer,
// destroys the id (waking sync joiners) and runs the async done.  Shared
// by the tstd and h2 client response paths.
void complete_locked_call(fid_t cid, Controller* cntl);

class Channel {
 public:
  struct Options {
    int64_t timeout_ms = 1000;
    int max_retry = 0;  // retries on connection failure (not timeouts)
    // Connection type matrix (socket_map.h: "single" multiplexes one
    // shared connection; "pooled" gives each call an exclusive one from
    // a shared per-endpoint pool; "short" is one per call).
    std::string connection_type = "single";
    // Client credential for servers requiring auth (auth.h; not owned).
    const Authenticator* auth = nullptr;
    // Wire protocol this channel speaks: "tstd" (default framed RPC),
    // "h2" (HTTP/2, response body = payload), or "grpc" (h2 + gRPC
    // path/framing/trailers).  h2/grpc connections are multiplexed and
    // require connection_type "single".
    std::string protocol = "tstd";
    // Same-host shared-memory transport (net/shm_transport.h): the channel
    // handshakes a ring segment over TCP, then calls flow through shm.
    // Falls back to TCP transparently if the handshake fails.
    bool use_shm = false;
    // ICI DMA-ring transport (net/ici_transport.h): posted-block credit
    // windows over registered DeviceArena slabs, rdma_endpoint parity.
    // Handshakes over TCP like use_shm; single-connection only.
    bool use_ici = false;
    // TLS to the server (net/tls.h).  Requires connection_type "single"
    // (the TLS session rides the one multiplexed connection) and excludes
    // use_shm/use_ici.  No peer verification by default, like the
    // reference's default ChannelSSLOptions.
    bool use_tls = false;
    // mTLS client half (ChannelSSLOptions::client_cert parity): present
    // this certificate during the handshake (may be empty with tls_ca
    // set: verification-only).  With tls_ca, the server's CHAIN is
    // verified against it — and when the Init address is a hostname, the
    // certificate must match that name too (IP literals: chain-only).
    // All PEM paths; Init fails if set without use_tls.
    std::string tls_cert;
    std::string tls_key;
    std::string tls_ca;
    // Default QoS tag stamped on every request whose controller didn't
    // set its own (net/qos.h: tenant bills per-tenant admission, priority
    // picks the dispatch lane; 0 = highest).  Tenant names cap at 64
    // bytes (wire limit).
    std::string qos_tenant;
    uint8_t qos_priority = 0;
  };

  ~Channel();  // fails the pooled socket so its resources (fd / shm
               // segment) are reclaimed on clean shutdown

  // addr: "ip:port" or "host:port".  Returns 0 on success.
  int Init(const std::string& addr, const Options* opts = nullptr);

  // done == nullptr → synchronous (parks the calling fiber / blocks the
  // calling pthread on the call's fid).  On return/completion, cntl holds
  // the status and *response the payload.
  void CallMethod(const std::string& method, const IOBuf& request,
                  IOBuf* response, Controller* cntl, Closure done = nullptr);

  // Retargets the channel's default QoS tag (Options::qos_tenant/
  // qos_priority) for subsequent calls.  Set before issuing traffic —
  // not synchronized against concurrent CallMethods.
  void set_default_qos(const std::string& tenant, uint8_t priority) {
    opts_.qos_tenant = tenant.size() > 64 ? tenant.substr(0, 64) : tenant;
    opts_.qos_priority = priority;
  }

  const EndPoint& endpoint() const { return ep_; }
  // Connection type parsed in Init (socket_map.h ConnectionType raw
  // value; 0 = single).  The batch pipeline keys its issue strategy on
  // this: single-connection channels issue from ONE fiber (FIFO wire
  // order), pooled/short fan out one issue fiber per call so inline
  // request writes overlap across their sockets.
  uint8_t conn_type_raw() const { return conn_type_; }
  // Name of the live connection's transport ("tcp", "shm_ring",
  // "ici_ring", "tls"), or "" if no socket has been established yet.
  std::string transport_name();
  // Negotiated ALPN protocol of the live TLS connection ("h2" for
  // h2/grpc-over-TLS channels), or "" (no socket / plaintext / no ALPN).
  std::string alpn();

 private:
  int ensure_socket(SocketId* out);

  EndPoint ep_;
  Options opts_;
  std::string sni_host_;  // host part of the Init address (TLS SNI)
  uint8_t proto_ = 0;  // 0 = tstd, 1 = h2, 2 = grpc (parsed in Init)
  // FiberMutex, NOT std::mutex: ensure_socket can block (shm handshake is a
  // sync RPC) and contenders must park their fibers, never wedge worker
  // pthreads — with a std::mutex, N concurrent first-calls deadlock the
  // scheduler.
  FiberMutex sock_mu_;
  SocketId sock_ = 0;
  uint8_t conn_type_ = 0;  // ConnectionType, parsed once in Init
};

}  // namespace trpc
